#!/usr/bin/env python
"""Scaling-efficiency sweep — the reference paper's table shape.

Theano-MPI's headline results are "time per 5120 images" tables per worker
count × exchange strategy (SURVEY.md §6).  This reproduces that table shape:
for each worker count (powers of two up to the visible chips) and strategy,
train a few steady-state iterations and report time-per-5120, images/sec,
and scaling efficiency vs 1 worker.

On real multi-chip TPU hardware this is the BASELINE.json scaling-efficiency
measurement; on the CPU-simulated mesh (TMPI_FORCE_CPU=1) the numbers only
demonstrate the harness, not hardware scaling.

Usage:
  python scripts/scaling_sweep.py [--model cifar10] [--strategies allreduce ring]
       [--iters 20] [--batch-size 128]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("TMPI_FORCE_CPU"):
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")

from theanompi_tpu.models.registry import MODELS  # noqa: E402


def measure(modelfile, modelclass, extra, n_workers, strategy, batch_size,
            iters, warmup, tp=1, pp=1, sp=1):
    import importlib

    import jax
    import jax.numpy as jnp

    from theanompi_tpu.parallel import steps
    from theanompi_tpu.parallel.exchanger import BSP_Exchanger
    from theanompi_tpu.parallel.mesh import worker_mesh

    mesh = worker_mesh(n_workers, tp=tp, pp=pp, sp=sp)
    config = {"mesh": mesh, "size": n_workers, "verbose": False,
              "exch_strategy": strategy, "batch_size": batch_size,
              "tp": tp, "pp": pp, "sp": sp, **extra}
    model = getattr(importlib.import_module(modelfile), modelclass)(config)
    model.compile_iter_fns(BSP_Exchanger(config))
    batch = model.data.next_train_batch(0)
    dev = steps.put_batch(mesh, batch, model.batch_spec())
    n_images = int(batch["y"].shape[0])
    lr, rng = jnp.float32(model.current_lr), jax.random.key(0)
    st = model.step_state
    for i in range(warmup):
        st, c, e = model.train_fn(st, dev, lr, rng, jnp.int32(i))
    jax.block_until_ready(st["params"])
    t0 = time.time()
    for i in range(iters):
        st, c, e = model.train_fn(st, dev, lr, rng, jnp.int32(warmup + i))
    jax.block_until_ready(st["params"])
    dt = time.time() - t0
    ips = n_images * iters / dt
    n_chips = n_workers * tp * pp * sp      # a worker is a GROUP of chips
    return {"workers": n_workers, "strategy": strategy,
            "images_per_sec": round(ips, 1),
            "images_per_sec_per_chip": round(ips / n_chips, 1),
            "time_per_5120": round(5120.0 / ips, 3)}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="cifar10", choices=sorted(MODELS))
    p.add_argument("--strategies", nargs="*",
                   default=["allreduce", "ring", "nccl16"],
                   choices=["allreduce", "ar", "nccl32", "nccl16", "bf16",
                            "ring", "ring16", "asa32", "asa16", "copper",
                            "copper16", "onebit", "compressed", "topk"])
    p.add_argument("--batch-size", type=int, default=128,
                   help="per-worker batch (reference style)")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--json", action="store_true", help="JSONL output")
    p.add_argument("--measure-comm", action="store_true",
                   help="add a comm-share column per strategy (differences "
                        "each fused step against the 'none' strategy)")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel degree per worker group "
                        "(transformer family; sweeps dp GROUPS)")
    p.add_argument("--pp", type=int, default=1, help="pipeline degree")
    p.add_argument("--sp", type=int, default=1, help="sequence degree")
    args = p.parse_args(argv)

    import jax
    group = args.tp * args.pp * args.sp
    n_dev = len(jax.devices()) // group
    if n_dev == 0:
        p.error(f"group size tp*pp*sp = {group} exceeds the "
                f"{len(jax.devices())} visible devices — nothing to sweep")
    counts, c = [], 1
    while c <= n_dev:
        counts.append(c)
        c *= 2
    modelfile, modelclass, extra = MODELS[args.model]

    # measure_comm (the reference's t_train/t_comm decomposition, SURVEY §6):
    # the fused BSP step hides the collective inside one XLA program, so
    # comm share is recovered by differencing against the 'none' strategy
    # (same elementwise work, no collective) at each worker count.
    base_step = {}
    if args.measure_comm:
        for n in counts:
            if n == 1:
                base_step[n] = None     # no comm at 1 worker by definition
                continue
            r0 = measure(modelfile, modelclass, extra, n, "none",
                         args.batch_size, args.iters, args.warmup,
                         tp=args.tp, pp=args.pp, sp=args.sp)
            base_step[n] = r0["time_per_5120"]

    base_ips = {}
    rows = []
    for strategy in args.strategies:
        for n in counts:
            r = measure(modelfile, modelclass, extra, n, strategy,
                        args.batch_size, args.iters, args.warmup,
                        tp=args.tp, pp=args.pp, sp=args.sp)
            key = strategy
            if n == 1:
                base_ips[key] = r["images_per_sec"]
            eff = r["images_per_sec"] / (base_ips[key] * n) \
                if base_ips.get(key) else float("nan")
            r["scaling_efficiency"] = round(eff, 3)
            comm_txt = ""
            if args.measure_comm:
                if base_step.get(n):
                    share = max(0.0, 1.0 - base_step[n] / r["time_per_5120"])
                    r["comm_share"] = round(share, 3)
                    comm_txt = f" | comm {share:5.1%}"
                else:
                    r["comm_share"] = 0.0
                    comm_txt = " | comm   n/a"
            rows.append(r)
            if args.json:
                print(json.dumps(r), flush=True)
            else:
                print(f"{args.model} {strategy:>10} x{n}: "
                      f"{r['images_per_sec']:>9.1f} img/s "
                      f"({r['images_per_sec_per_chip']:>8.1f}/chip) | "
                      f"{r['time_per_5120']:>7.3f} s/5120 | "
                      f"eff {eff:5.1%}{comm_txt}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
