#!/usr/bin/env bash
# The ROADMAP tier-1 verify gate, wrapped verbatim so the builder and the
# reviewer run the SAME command (one place to keep the pytest flags, the
# timeout, and the DOTS_PASSED accounting in sync).
#   scripts/tier1.sh
# Exits with pytest's return code; prints DOTS_PASSED=<n> as the last line.
#
# Preceded by the schema drift guard (scripts/check_schema_drift.py):
# recorder.SECTIONS, the print_train_info record keys, and the telemetry
# phase-event names must all derive from telemetry.PHASES — a bucket added
# to one but not the others fails the gate here, before pytest runs.
cd "$(dirname "$0")/.."
python scripts/check_schema_drift.py || { echo "tier1: schema drift guard FAILED" >&2; exit 9; }
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
