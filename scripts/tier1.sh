#!/usr/bin/env bash
# The ROADMAP tier-1 verify gate, wrapped verbatim so the builder and the
# reviewer run the SAME command (one place to keep the pytest flags, the
# timeout, and the DOTS_PASSED accounting in sync).
#   scripts/tier1.sh
# Exits with pytest's return code; prints DOTS_PASSED=<n> as the last line.
#
# Preceded by the tpulint suite (scripts/lint.py --check-baseline): the
# whole-program invariant checkers of docs/design.md §12 — trace purity
# and rng/donation discipline closed over the repo-wide call graph
# (analysis/engine.py), SPMD collective discipline (axis names,
# rank-divergent branches, start/done pairing), PartitionSpec/shard_map
# schema checks, exchange_body symmetry, the jax_compat shim boundary,
# the telemetry hot-path enabled-guard contract, the recorder/
# telemetry schema sync, the host-concurrency pass (thread-role
# inference; shared-state races, lock-order cycles, signal safety,
# daemon discipline — design.md §16), and the distributed-protocol
# conformance pass (design.md §21: client/server wire op-table diffs,
# DedupWindow claim dominance on every mutating handler path, §15
# retry-verdict/close-taxonomy checks, membership state-machine
# exhaustiveness incl. reactor hooks and the versioned wire-header
# field vocabulary), and the compile-surface pass (design.md §26:
# cache-key completeness — config knobs that shape a traced program
# reachable from the AOT surfaces must reach a guarded
# compile_cache.key_extra stamp, cross-checked against a live stamping
# probe — plus retrace hazards like fresh-lambda jit identity,
# jit-in-loop, non-static shape params and .lower() on an installed
# Compiled, and bf16-wire dtype-flow discipline incl. the per-module
# NONBITEXACT round-trip registry).  Any finding not covered by
# tpulint_baseline.json — or a stale baseline entry — fails the gate
# here, without importing jax, before pytest.  An unchanged tree is a
# .tpulint_cache/ hit: the gate costs well under a second.
cd "$(dirname "$0")/.."
python scripts/lint.py --check-baseline || { echo "tier1: tpulint gate FAILED (run scripts/lint.py for details)" >&2; exit 9; }
# The simfleet determinism gate (docs/design.md §18): same seed must
# produce a byte-identical event log, a different seed must not, and a
# 512-worker invariant suite (kills, wedges, stragglers, net windows
# through the REAL membership/reactor/dedup logic on a virtual clock)
# must pass inside a CPU-seconds budget.  No subprocesses, no sockets,
# no jax execution — it runs before pytest so a broken survivability
# refactor fails in seconds.
python scripts/simfleet_run.py --gate --budget 120 || { echo "tier1: simfleet gate FAILED (run scripts/simfleet_run.py --gate for details)" >&2; exit 8; }
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
