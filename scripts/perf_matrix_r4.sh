#!/usr/bin/env bash
# Round-4 perf matrix on the live TPU chip — the complete set the round-3
# verdict asked for (#1b): every BASELINE.json staged config at its
# reference batch, the spc and bf16-BN A/B levers, b64/b128 headroom rows,
# compressed-wire rows, the transformer family, and the staged rules
# (EASGD on VGG-16, GoSGD on ResNet-50).  Writes one JSON line per config;
# rows already measured in the out-file are skipped, so the script is
# re-runnable after a tunnel wedge (scripts/tpu_watch_r4.sh drives that).
# VGG-16 rows run LAST: both round-3 wedges struck around VGG-16 activity.
#   ./scripts/perf_matrix_r4.sh [out_file]
set -u -o pipefail
OUT="${1:-perf_matrix_r4.jsonl}"
cd "$(dirname "$0")/.."
. scripts/_bench_row.sh

# Row order is greedy-by-value-per-minute-of-tunnel-uptime: the first
# round-4 window lasted ~10 min (one row + one wedge mid-spc4-compile), so
# each pass front-loads the highest-value UNMEASURED configs with the
# quickest compiles, and pushes the wedge-correlated big compiles (spc
# scans — today's trigger — and the transformer family) to the back.
# Measured rows are skipped, so later passes reach the back of the list.

# -- staged configs at reference batch sizes (the comparison that counts) --
run alexnet-b128             BENCH_MODEL=alexnet
run resnet50-b32             BENCH_MODEL=resnet50
run googlenet-b32            BENCH_MODEL=googlenet
run vgg16-b32                BENCH_MODEL=vgg16
run cifar10-b128             BENCH_MODEL=cifar10

# -- bf16-BN lever A/B (round-3 trace: BN stat reductions = 16% of ResNet
#    busy time; the verdict wants the lever MEASURED, not just shipped) --
run resnet50-b32-bnbf16      BENCH_MODEL=resnet50 BENCH_BN_DTYPE=bfloat16

# -- batch-size headroom (MFU pushes; verdict #2 wants b128 rows) --
run resnet50-b64             BENCH_MODEL=resnet50 BENCH_BATCH=64
run resnet50-b128            BENCH_MODEL=resnet50 BENCH_BATCH=128
run resnet50-b128-bnbf16     BENCH_MODEL=resnet50 BENCH_BATCH=128 BENCH_BN_DTYPE=bfloat16
run googlenet-b128           BENCH_MODEL=googlenet BENCH_BATCH=128
run vgg16-b64                BENCH_MODEL=vgg16 BENCH_BATCH=64

# -- staged rules + compressed wire on their staged models (BASELINE #3-#5) --
run vgg16-b32-easgd          BENCH_MODEL=vgg16 BENCH_RULE=easgd
run resnet50-b32-gosgd       BENCH_MODEL=resnet50 BENCH_RULE=gosgd
run vgg16-b32-topk           BENCH_MODEL=vgg16 BENCH_STRATEGY=topk
run vgg16-b32-onebit         BENCH_MODEL=vgg16 BENCH_STRATEGY=onebit
run vgg16-b32-powersgd4      BENCH_MODEL=vgg16 BENCH_STRATEGY=powersgd4

# -- real-data path (verdict #3): .hkl shards -> native loader -> device --
run alexnet-b128-realdata    BENCH_MODEL=alexnet BENCH_REAL_DATA=1
# u8-wire A/B: ship uint8 crops, cast+mean-subtract on device (4x smaller
# host->device transfers; the tunnel-attached chip should feel this most)
run alexnet-b128-realdata-u8w BENCH_MODEL=alexnet BENCH_REAL_DATA=1 BENCH_WIRE_U8=1

# -- transformer family (beyond-parity; value = sequences/sec/chip) --
run transformer_lm-b16       BENCH_MODEL=transformer_lm BENCH_BATCH=16 BENCH_CFG="$LM_CFG"
run transformer_lm-b16-flash BENCH_MODEL=transformer_lm BENCH_BATCH=16 BENCH_CFG="${LM_CFG%\}},\"attn_impl\":\"flash\"}"
run moe_lm-b16               BENCH_MODEL=moe_lm         BENCH_BATCH=16 BENCH_CFG="$LM_CFG"

# -- spc (multi-step dispatch) rows LAST: the scan-of-k-steps compile is
#    the biggest program per model and the round-4 wedge #1 trigger --
run alexnet-b128-spc4        BENCH_MODEL=alexnet  BENCH_SPC=4
run alexnet-b128-spc8        BENCH_MODEL=alexnet  BENCH_SPC=8 BENCH_SYNTH_BATCHES=8
run googlenet-b32-spc8       BENCH_MODEL=googlenet BENCH_SPC=8 BENCH_SYNTH_BATCHES=8
run resnet50-b32-spc8        BENCH_MODEL=resnet50 BENCH_SPC=8 BENCH_SYNTH_BATCHES=8
run resnet50-b32-spc8-bnbf16 BENCH_MODEL=resnet50 BENCH_SPC=8 BENCH_SYNTH_BATCHES=8 BENCH_BN_DTYPE=bfloat16
run resnet50-b128-spc4       BENCH_MODEL=resnet50 BENCH_BATCH=128 BENCH_SPC=4
run googlenet-b128-spc4      BENCH_MODEL=googlenet BENCH_BATCH=128 BENCH_SPC=4
run vgg16-b32-spc4           BENCH_MODEL=vgg16 BENCH_SPC=4

python scripts/merge_matrix.py "$OUT"
cat "$OUT"
