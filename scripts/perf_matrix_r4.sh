#!/usr/bin/env bash
# Round-4 perf matrix on the live TPU chip — the complete set the round-3
# verdict asked for (#1b): every BASELINE.json staged config at its
# reference batch, the spc and bf16-BN A/B levers, b64/b128 headroom rows,
# compressed-wire rows, the transformer family, and the staged rules
# (EASGD on VGG-16, GoSGD on ResNet-50).  Writes one JSON line per config;
# rows already measured in the out-file are skipped, so the script is
# re-runnable after a tunnel wedge (scripts/tpu_watch_r4.sh drives that).
# VGG-16 rows run LAST: both round-3 wedges struck around VGG-16 activity.
#   ./scripts/perf_matrix_r4.sh [out_file]
set -u -o pipefail
OUT="${1:-perf_matrix_r4.jsonl}"
cd "$(dirname "$0")/.."
. scripts/_bench_row.sh

# -- staged configs at reference batch sizes (the comparison that counts) --
run alexnet-b128             BENCH_MODEL=alexnet
run alexnet-b128-spc4        BENCH_MODEL=alexnet  BENCH_SPC=4
run alexnet-b128-spc8        BENCH_MODEL=alexnet  BENCH_SPC=8 BENCH_SYNTH_BATCHES=8
run googlenet-b32            BENCH_MODEL=googlenet
run googlenet-b32-spc8       BENCH_MODEL=googlenet BENCH_SPC=8 BENCH_SYNTH_BATCHES=8
run resnet50-b32             BENCH_MODEL=resnet50
run resnet50-b32-spc8        BENCH_MODEL=resnet50 BENCH_SPC=8 BENCH_SYNTH_BATCHES=8
run cifar10-b128             BENCH_MODEL=cifar10

# -- bf16-BN lever A/B (round-3 trace: BN stat reductions = 16% of ResNet
#    busy time; the verdict wants the lever MEASURED, not just shipped) --
run resnet50-b32-bnbf16      BENCH_MODEL=resnet50 BENCH_BN_DTYPE=bfloat16
run resnet50-b32-spc8-bnbf16 BENCH_MODEL=resnet50 BENCH_SPC=8 BENCH_SYNTH_BATCHES=8 BENCH_BN_DTYPE=bfloat16

# -- batch-size headroom (MFU pushes; verdict #2 wants b128 rows) --
run resnet50-b64             BENCH_MODEL=resnet50 BENCH_BATCH=64
run resnet50-b128            BENCH_MODEL=resnet50 BENCH_BATCH=128
run resnet50-b128-bnbf16     BENCH_MODEL=resnet50 BENCH_BATCH=128 BENCH_BN_DTYPE=bfloat16
run resnet50-b128-spc4       BENCH_MODEL=resnet50 BENCH_BATCH=128 BENCH_SPC=4
run googlenet-b128           BENCH_MODEL=googlenet BENCH_BATCH=128
run googlenet-b128-spc4      BENCH_MODEL=googlenet BENCH_BATCH=128 BENCH_SPC=4

# -- staged rules on their staged models (BASELINE.json #3/#4) --
run resnet50-b32-gosgd       BENCH_MODEL=resnet50 BENCH_RULE=gosgd

# -- real-data path (verdict #3): .hkl shards -> native loader -> device --
run alexnet-b128-realdata    BENCH_MODEL=alexnet BENCH_REAL_DATA=1
# u8-wire A/B: ship uint8 crops, cast+mean-subtract on device (4x smaller
# host->device transfers; the tunnel-attached chip should feel this most)
run alexnet-b128-realdata-u8w BENCH_MODEL=alexnet BENCH_REAL_DATA=1 BENCH_WIRE_U8=1

# -- transformer family (beyond-parity; value = sequences/sec/chip) --
run transformer_lm-b16       BENCH_MODEL=transformer_lm BENCH_BATCH=16 BENCH_CFG="$LM_CFG"
run transformer_lm-b16-flash BENCH_MODEL=transformer_lm BENCH_BATCH=16 BENCH_CFG="${LM_CFG%\}},\"attn_impl\":\"flash\"}"
run moe_lm-b16               BENCH_MODEL=moe_lm         BENCH_BATCH=16 BENCH_CFG="$LM_CFG"

# -- vgg16 last: prime wedge suspect (staged configs #3 and #5) --
run vgg16-b32                BENCH_MODEL=vgg16
run vgg16-b32-spc4           BENCH_MODEL=vgg16 BENCH_SPC=4
run vgg16-b32-easgd          BENCH_MODEL=vgg16 BENCH_RULE=easgd
run vgg16-b32-topk           BENCH_MODEL=vgg16 BENCH_STRATEGY=topk
run vgg16-b32-onebit         BENCH_MODEL=vgg16 BENCH_STRATEGY=onebit
run vgg16-b64                BENCH_MODEL=vgg16 BENCH_BATCH=64

python scripts/merge_matrix.py "$OUT"
cat "$OUT"
