#!/usr/bin/env python
"""Time-to-accuracy comparison across the four rules — the shape of the
reference paper's HEADLINE claim (arXiv:1605.08325 experiments; SURVEY.md
§6: EASGD reaches the target val error in less wall-clock than BSP at
higher worker counts).

Each rule trains the CIFAR-10 smoke model end to end through the 3-call
session API on the same mesh and records wall-clock seconds and epochs to
a stated val accuracy.  Writes one JSON line per rule to
``rules_time_to_acc.json`` and prints a table.

On the CPU sim the ABSOLUTE times mean nothing (and the sim shares one
host, so the async rules' wall-clock advantage is understated); the
recorded artifact is the rule-semantics comparison: every rule reaches
the target, and the per-epoch accuracy traces document HOW (BSP's large
effective batch converges in the fewest epochs; the weakly-coupled rules
trade per-step coupling for more epochs).  On real chips the same script
gives the reference-style wall-clock table.

    python scripts/rules_time_to_acc.py [target_acc]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU sim is the default on this box (the rule comparison wants 8 visible
# devices and the single tunnel chip can't offer them); TMPI_FORCE_TPU=1
# opts out so the documented real-chip path is actually reachable
# (round-4 ADVICE: the previous `or True` made the env guard dead code)
if not os.environ.get("TMPI_FORCE_TPU"):
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")

import theanompi_tpu as tmpi  # noqa: E402

RULES = [
    # calibrated budgets from tests/test_convergence.py (+ASGD, same family
    # of weakly-coupled rules as GoSGD).  ASGD's center absorbs the SUM of
    # all workers' accumulated deltas (downpour semantics, ≙ the reference)
    # — at 8 workers the stable lr scales down by the worker count, the
    # standard downpour practice (lr 0.02 diverges, recorded 2026-07-31).
    ("BSP", 6, {}),
    ("EASGD", 16, {"sync_freq": 2, "alpha": 0.1}),
    ("ASGD", 20, {"sync_freq": 2, "learning_rate": 0.0025}),
    ("GOSGD", 12, {"exch_prob": 0.25}),
]


def main() -> int:
    target = float(sys.argv[1]) if len(sys.argv) > 1 else 0.90
    out_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "rules_time_to_acc.json")
    rows = []
    for name, epochs, extra in RULES:
        rule = getattr(tmpi, name)()
        kw = dict(devices=8, modelfile="theanompi_tpu.models.cifar10",
                  modelclass="Cifar10_model", epochs=epochs,
                  synthetic_train=2048, synthetic_val=256, batch_size=16,
                  printFreq=1000, compute_dtype="float32",
                  learning_rate=0.02, scale_lr=False, verbose=False)
        kw.update(extra)            # per-rule overrides win (ASGD's lr)
        rule.init(**kw)
        t0 = time.time()
        rec = rule.wait()
        wall = time.time() - t0
        accs = [round(1.0 - r["val_error"], 4) for r in rec.epoch_records]
        hit = next((i + 1 for i, a in enumerate(accs) if a >= target), None)
        # seconds to target ~ proportional share of the run (epochs are
        # equal-length); exact per-epoch stamps would need recorder surgery
        t_hit = round(wall * hit / len(accs), 1) if hit else None
        row = {"rule": name, "target_acc": target, "epochs_budget": epochs,
               "epochs_to_target": hit, "secs_to_target_approx": t_hit,
               "wall_secs_total": round(wall, 1), "best_acc": max(accs),
               "acc_by_epoch": accs,
               "platform": "cpu-sim-8dev (semantics comparison; absolute "
                           "times not meaningful)"}
        rows.append(row)
        print(f"{name:6s}  to {target:.0%}: "
              f"{hit if hit else '—'} epochs  (~{t_hit}s)   "
              f"best {max(accs):.1%}", flush=True)
    with open(out_path, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    print(f"wrote {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
