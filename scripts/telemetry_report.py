#!/usr/bin/env python
"""Merge per-rank telemetry streams into one run report.

Reads a record/telemetry directory produced by a run with ``record_dir``
set (see ``theanompi_tpu/utils/telemetry.py`` and docs/design.md §11):

* ``telemetry_rank{r}.jsonl``        — the per-rank event streams
* ``telemetry_summary_rank{r}.json`` — counters/gauges/histograms at close
* ``flight_rank{r}.jsonl`` / ``crash_*/flight_rank{r}.jsonl`` — crash dumps

and emits the cross-worker run report the bucket sums can't answer:

* **phase breakdown** — per recorder section (train/comm/load/...), event
  count, total seconds, mean and p50/p95/p99 tail percentiles;
* **per-rank throughput timeline** — images/sec over wall time from the
  periodic ``train_record`` events;
* **straggler ranking** — wall time is cut into windows (``--window``,
  default 10 s); each window's slowest rank (highest mean ``phase.train``
  dt) is charged one straggle; ranks sorted by windows-straggled and mean
  step time;
* **health flags** — prefetch queue starvation (starved dequeues / min
  queue depth) and HBM headroom (peak bytes vs limit from ``gauges``
  events), plus any flight recordings found (a crash/stall happened) and
  per-rank sentry ``anomaly`` counts (NaN loss / loss spike / throughput
  regression — ``utils/sentry``);
* **``--trace out.json``** — the merged per-rank streams converted to
  Chrome trace-event JSON: one process (track group) per rank holding
  the phase spans (a ``phase`` event's span is ``[ts − dt, ts]``),
  counter tracks for HBM bytes-in-use, prefetch queue depth, heartbeat
  progress, and images/sec, and instant markers for anomaly/crash/stall/
  fatal-signal events plus the elastic-membership transitions
  (``worker_join``/``worker_leave``/``worker_demote``) and chaos-harness
  ``fault_injected`` audits — open directly in Perfetto (ui.perfetto.dev)
  or ``chrome://tracing`` for the cross-rank straggler/churn timeline.

* **distributed traces** (round 16, docs/design.md §17) — ``span``
  events from the causal-tracing layer (``utils/tracing.py``) are joined
  ACROSS rank files by span id: each exchange round's client span, its
  ``wire.<op>`` children, and the center's ``center.<op>`` handler spans
  become one per-round trace with a critical path (compute | stage |
  wire | queue | apply), a join rate, and dedup-twin accounting; the
  per-worker straggler ROOT-CAUSE table (which component dominated) is
  what ``membership.check_stragglers`` cites in its demote events, and
  the Perfetto export draws flow arrows from each client wire span to
  the server span it caused;
* **``--since TS`` / ``--last SEC``** — time-window the load (cheap
  ``ts``-prefix line skip, no full parse) so long elastic/chaos runs can
  be reported incrementally.

Usage:
    python scripts/telemetry_report.py <record_dir> [--window SEC]
                                       [--since TS | --last SEC]
                                       [--json out.json] [--trace out.json]

Stdlib only — runnable on a machine with no jax installed.
"""

import argparse
import glob
import json
import os
import sys
from collections import defaultdict


# Event kinds this report (and the --trace converter) consumes — the
# tpulint schema-drift checker asserts the emitters' vocabulary (telemetry
# phase events, sentry anomalies, devprof device profiles, membership
# transitions, chaos fault injections) stays inside it, so an emitter
# can't add a kind the report silently drops.
TRACKED_EVENTS = ("phase", "train_record", "val_record", "gauges",
                  "device_profile", "anomaly", "crash", "stall",
                  "fatal_signal", "worker_join", "worker_leave",
                  "worker_demote", "fault_injected",
                  "center_down", "center_restored", "wire",
                  "span", "statusz", "alert", "numerics")

# gauges-event keys drawn as Perfetto counter tracks (plus
# images_per_sec from train_record events); heartbeat.iter is the
# membership lease's liveness signal (parallel/membership.py);
# wire.outage_s is the wire client's healed-outage duration
# (parallel/wire.py); the numerics.* keys ride `numerics` events
# (utils/numerics, docs/design.md §25) — grad-norm, update-ratio,
# beacon-divergence and ‖w−c‖ counter tracks per rank
TRACE_COUNTER_KEYS = ("hbm_bytes_in_use", "prefetch.queue_depth",
                      "heartbeat.iter", "wire.outage_s",
                      "numerics.grad_norm", "numerics.update_ratio",
                      "numerics.divergence", "numerics.dist_center")

INSTANT_EVENTS = ("anomaly", "crash", "stall", "fatal_signal",
                  "worker_join", "worker_leave", "worker_demote",
                  "fault_injected", "center_down", "center_restored",
                  "wire", "statusz", "alert")

# The critical-path component vocabulary (mirrors utils/tracing.py
# COMPONENTS — schema-drift-probed): every second of a traced exchange
# round is charged to exactly one of these.
TRACE_COMPONENTS = ("compute", "stage", "wire", "queue", "apply")


def percentile(values, q):
    # same nearest-rank formula as telemetry.Histogram.percentile — kept
    # local so this script stays stdlib-only (importing the package would
    # drag jax in via theanompi_tpu/__init__)
    if not values:
        return None
    s = sorted(values)
    idx = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[idx]


def _line_ts(line):
    """The ``ts`` of one JSONL line WITHOUT a full json parse — telemetry
    serializes ``ts`` first (dict insertion order), so a prefix scan is
    enough.  None when the line doesn't open with the ts key (then the
    caller falls back to a real parse)."""
    if not line.startswith('{"ts":'):
        return None
    end = line.find(",", 6)
    if end < 0:
        end = line.find("}", 6)
    if end < 0:
        return None
    try:
        return float(line[6:end].strip())
    except ValueError:
        return None


def load_events(record_dir, since=None, until=None):
    """All events from every per-rank stream, sorted by timestamp.

    ``since``/``until`` (epoch seconds) window the load for long
    elastic/chaos runs: out-of-window lines are skipped on a cheap
    ``ts``-prefix scan, never fully json-parsed — incremental reporting
    without paying for the whole stream."""
    events = []
    for path in sorted(glob.glob(
            os.path.join(record_dir, "telemetry_rank*.jsonl"))):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                if since is not None or until is not None:
                    ts = _line_ts(line)
                    if ts is not None and (
                            (since is not None and ts < since) or
                            (until is not None and ts > until)):
                        continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue          # a crash can truncate the last line
                if not (isinstance(ev, dict) and "ev" in ev):
                    continue
                if since is not None and ev.get("ts", 0) < since:
                    continue          # fallback for ts-not-first lines
                if until is not None and ev.get("ts", 0) > until:
                    continue
                events.append(ev)
    events.sort(key=lambda e: e.get("ts", 0))
    return events


def stream_extent(record_dir):
    """``(first_ts, last_ts)`` across the per-rank streams, read from each
    file's head and tail only (no full parse) — what ``--last N`` anchors
    its window against.  ``(None, None)`` when nothing is parseable."""
    lo = hi = None
    for path in sorted(glob.glob(
            os.path.join(record_dir, "telemetry_rank*.jsonl"))):
        try:
            with open(path, "rb") as f:
                head = f.readline().decode("utf-8", "replace").strip()
                ts = _line_ts(head)
                if ts is not None:
                    lo = ts if lo is None else min(lo, ts)
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - 65536))
                tail = f.read().decode("utf-8", "replace").splitlines()
            for line in reversed(tail):
                ts = _line_ts(line.strip())
                if ts is not None:
                    hi = ts if hi is None else max(hi, ts)
                    break
        except OSError:
            continue
    return lo, hi


def load_summaries(record_dir):
    out = {}
    for path in sorted(glob.glob(
            os.path.join(record_dir, "telemetry_summary_rank*.json"))):
        try:
            with open(path) as f:
                s = json.load(f)
            out[int(s.get("rank", 0))] = s
        except (ValueError, OSError):
            continue
    return out


def find_flight_dumps(record_dir):
    return sorted(
        glob.glob(os.path.join(record_dir, "flight_rank*.jsonl")) +
        glob.glob(os.path.join(record_dir, "crash_*", "flight_rank*.jsonl")))


def phase_breakdown(events):
    """Per-section dt distribution from the ``phase`` events."""
    dts = defaultdict(list)
    for ev in events:
        if ev["ev"] == "phase":
            dts[ev.get("sec", "?")].append(float(ev.get("dt", 0.0)))
    out = {}
    for sec, vals in sorted(dts.items()):
        out[sec] = {"count": len(vals), "total": round(sum(vals), 4),
                    "mean": round(sum(vals) / len(vals), 6),
                    "p50": percentile(vals, 50), "p95": percentile(vals, 95),
                    "p99": percentile(vals, 99),
                    # exact extreme over the (windowed) stream — the one
                    # sample a reservoir can drop and an SLO cares about
                    "max": round(max(vals), 6)}
    return out


def throughput_timeline(events):
    """Per-rank [(t_rel, images_per_sec), ...] from train_record events."""
    t0 = events[0]["ts"] if events else 0.0
    tl = defaultdict(list)
    for ev in events:
        if ev["ev"] == "train_record" and "images_per_sec" in ev:
            tl[int(ev.get("rank", 0))].append(
                (round(ev["ts"] - t0, 1), round(ev["images_per_sec"], 1)))
    return dict(tl)


def straggler_ranking(events, window_s):
    """Charge each wall-clock window to its slowest rank (highest mean
    ``phase.train`` dt).  Single-rank runs trivially 'win' every window —
    the mean/p95 columns are the useful part there."""
    train = [(ev["ts"], int(ev.get("rank", 0)), float(ev.get("dt", 0.0)))
             for ev in events
             if ev["ev"] == "phase" and ev.get("sec") == "train"]
    if not train:
        return []
    t0 = train[0][0]
    per_window = defaultdict(lambda: defaultdict(list))
    per_rank = defaultdict(list)
    for ts, rank, dt in train:
        per_window[int((ts - t0) / window_s)][rank].append(dt)
        per_rank[rank].append(dt)
    straggles = defaultdict(int)
    for w, by_rank in per_window.items():
        if len(by_rank) < 1:
            continue
        slowest = max(by_rank,
                      key=lambda r: sum(by_rank[r]) / len(by_rank[r]))
        straggles[slowest] += 1
    ranking = []
    for rank in sorted(per_rank):
        vals = per_rank[rank]
        ranking.append({
            "rank": rank, "windows_straggled": straggles.get(rank, 0),
            "dispatches": len(vals),
            "mean_train_secs": round(sum(vals) / len(vals), 6),
            "p95_train_secs": percentile(vals, 95)})
    ranking.sort(key=lambda r: (-r["windows_straggled"],
                                -(r["mean_train_secs"] or 0)))
    return ranking


def assemble_traces(events):
    """Join client and server ``span`` events across rank streams into
    per-round distributed traces (docs/design.md §17).

    A round is a client span named ``round`` (async islands) or
    ``exchange`` (sync SPMD dispatch); its ``wire.<op>`` child spans were
    emitted by the wire client, and the server's ``center.<op>`` spans
    join by parent span id — a chaos-duplicated or retried request may
    produce several server spans for one client span, of which exactly
    one is APPLIED (the ``dedup``-tagged twins are counted but never
    charged to the critical path).

    Per-round critical path: every second of the round is charged to one
    component — ``queue``/``apply`` from the server's reply-header time
    split, ``wire`` is each op's remaining transit time (dt − q − a,
    retries included: that IS wire time), ``stage`` from the round's own
    ``stage_s`` field when the worker measured one, and ``compute`` is
    the residual (local steps, data wait, elastic update math).  The
    components therefore sum to the observed round time by construction
    — the 5% acceptance tolerance covers clock skew between the two
    processes' q/a stamps, not bookkeeping slack."""
    rounds = []
    wires = defaultdict(list)
    servers = defaultdict(list)
    for ev in events:
        if ev.get("ev") != "span":
            continue
        if ev.get("side") == "server":
            servers[ev.get("parent")].append(ev)
        elif str(ev.get("name", "")).startswith("wire."):
            wires[ev.get("trace")].append(ev)
        elif ev.get("name") in ("round", "exchange"):
            rounds.append(ev)
    out = []
    for r in rounds:
        tid = r.get("trace")
        total = float(r.get("dt", 0.0))
        wire_s = queue_s = apply_s = 0.0
        wire_ops = joined = unjoined = dedup_twins = 0
        for w in wires.get(tid, ()):
            q = float(w.get("q") or 0.0)
            a = float(w.get("a") or 0.0)
            dt = float(w.get("dt", 0.0))
            queue_s += q
            apply_s += a
            wire_s += max(0.0, dt - q - a)
            wire_ops += 1
            srvs = servers.get(w.get("span"), ())
            if any(not s.get("dedup") for s in srvs):
                joined += 1
            else:
                unjoined += 1
            dedup_twins += sum(1 for s in srvs if s.get("dedup"))
        stage = float(r.get("stage_s") or 0.0)
        compute = max(0.0, total - wire_s - queue_s - apply_s - stage)
        components = {"compute": round(compute, 6),
                      "stage": round(stage, 6),
                      "wire": round(wire_s, 6),
                      "queue": round(queue_s, 6),
                      "apply": round(apply_s, 6)}
        dominant = max(components, key=components.get)
        out.append({"trace": tid, "rank": int(r.get("rank", 0)),
                    "island": r.get("island"), "name": r.get("name"),
                    "t0": r.get("t0", r.get("ts")), "dt": round(total, 6),
                    "components": components, "dominant": dominant,
                    "wire_ops": wire_ops, "joined": joined,
                    "unjoined": unjoined, "dedup_twins": dedup_twins,
                    "outcome": r.get("outcome")})
    out.sort(key=lambda t: t.get("t0") or 0.0)
    return out


def straggler_root_cause(events, window_s, traces=None):
    """Per-worker root-cause table from the assembled traces: WHICH
    critical-path component dominated each worker's rounds, per
    ``window_s`` wall-clock window — the demote-event citation
    ``membership.MembershipController.check_stragglers`` attaches, so a
    straggler demotion names its cause (slow compute vs a slow wire vs a
    queued-up center), not just its symptom."""
    traces = assemble_traces(events) if traces is None else traces
    if not traces:
        return {}
    t_origin = min(t.get("t0") or 0.0 for t in traces)
    win = defaultdict(lambda: defaultdict(lambda: defaultdict(float)))
    totals = defaultdict(lambda: defaultdict(float))
    counts = defaultdict(int)
    dt_sum = defaultdict(float)
    for t in traces:
        rank = t["rank"]
        w = int(((t.get("t0") or 0.0) - t_origin) / window_s)
        for comp, secs in t["components"].items():
            win[rank][w][comp] += secs
            totals[rank][comp] += secs
        counts[rank] += 1
        dt_sum[rank] += t["dt"]
    out = {}
    for rank in sorted(counts):
        dom_windows = defaultdict(int)
        for comps in win[rank].values():
            dom_windows[max(comps, key=comps.get)] += 1
        tot = totals[rank]
        overall = max(tot, key=tot.get)
        denom = sum(tot.values()) or 1.0
        out[rank] = {
            "rounds": counts[rank], "windows": len(win[rank]),
            "dominant": overall,
            "dominant_share": round(tot[overall] / denom, 4),
            "windows_dominated_by": dict(sorted(dom_windows.items())),
            "mean_round_s": round(dt_sum[rank] / counts[rank], 6),
            "components_total_s": {k: round(v, 4)
                                   for k, v in sorted(tot.items())}}
    return out


def trace_summary(events, window_s=10.0):
    """The run-level trace digest: round/join/dedup counts, critical-path
    totals, and the per-worker root-cause table.  Empty dict when the
    streams carry no spans (tracing off)."""
    traces = assemble_traces(events)
    if not traces:
        return {}
    joined = sum(t["joined"] for t in traces)
    unjoined = sum(t["unjoined"] for t in traces)
    comp = {k: round(sum(t["components"][k] for t in traces), 4)
            for k in TRACE_COMPONENTS}
    denom = joined + unjoined
    return {
        "rounds": len(traces),
        "wire_ops": sum(t["wire_ops"] for t in traces),
        "joined": joined, "unjoined": unjoined,
        "join_rate": round(joined / denom, 4) if denom else None,
        "dedup_twins": sum(t["dedup_twins"] for t in traces),
        "components_total_s": comp,
        "dominant": max(comp, key=comp.get),
        "root_cause": straggler_root_cause(events, window_s,
                                           traces=traces)}


def health_flags(events, summaries):
    """Queue-starvation and HBM-headroom verdicts, per rank where known."""
    flags = {}
    # prefetch starvation: counters + queue-depth histogram from summaries
    starve = {}
    for rank, s in summaries.items():
        c = s.get("counters", {})
        deq = c.get("prefetch.dequeues", 0)
        if deq:
            h = s.get("hist", {}).get("prefetch.queue_depth", {})
            share = c.get("prefetch.starved_dequeues", 0) / deq
            starve[rank] = {
                "dequeues": int(deq), "starved_share": round(share, 4),
                "min_queue_depth": h.get("min"),
                "p50_queue_depth": h.get("p50"),
                "starving": share > 0.05}
    if starve:
        flags["prefetch"] = starve
    # HBM headroom: the LAST gauges event per rank
    hbm = {}
    for ev in events:
        if ev["ev"] == "gauges" and "hbm_peak_bytes" in ev:
            rank = int(ev.get("rank", 0))
            peak, limit = ev["hbm_peak_bytes"], ev.get("hbm_bytes_limit")
            hbm[rank] = {"peak_bytes": int(peak),
                         "limit_bytes": int(limit) if limit else None,
                         "peak_share": round(peak / limit, 4) if limit
                         else None,
                         "near_oom": bool(limit) and peak / limit > 0.9}
    if hbm:
        flags["hbm"] = hbm
    # sentry anomalies: per-rank counts by kind — a run that tripped the
    # sentry must never read as healthy in the merged report
    anomalies = {}
    for ev in events:
        if ev["ev"] == "anomaly":
            rank = int(ev.get("rank", 0))
            kind = str(ev.get("kind", "?"))
            anomalies.setdefault(rank, {})
            anomalies[rank][kind] = anomalies[rank].get(kind, 0) + 1
    if anomalies:
        flags["anomalies"] = anomalies
    return flags


def numerics_health(events):
    """Per-rank numerics-plane digest (utils/numerics, §25): the LAST
    report's stats plus worst-case values over the window — the beacon
    divergence and nonfinite count must surface even if the run recovered
    afterwards.  Empty dict when the plane was off."""
    out = {}
    for ev in events:
        if ev.get("ev") != "numerics":
            continue
        rank = int(ev.get("rank", 0))
        row = out.setdefault(rank, {"reports": 0, "max_divergence": 0.0,
                                    "nonfinite_total": 0.0,
                                    "max_grad_norm": 0.0,
                                    "min_update_ratio": None,
                                    "max_dist_center": 0.0, "last": {}})
        row["reports"] += 1
        div = ev.get("divergence")
        if isinstance(div, (int, float)) and div == div:
            row["max_divergence"] = max(row["max_divergence"], div)
        nf = ev.get("nonfinite")
        if isinstance(nf, (int, float)):
            row["nonfinite_total"] += nf
        gn = ev.get("grad_norm")
        if isinstance(gn, (int, float)) and gn == gn:
            row["max_grad_norm"] = max(row["max_grad_norm"], gn)
        ur = ev.get("update_ratio")
        if isinstance(ur, (int, float)):
            row["min_update_ratio"] = ur if row["min_update_ratio"] \
                is None else min(row["min_update_ratio"], ur)
        dc = ev.get("dist_center")
        if isinstance(dc, (int, float)) and dc == dc:
            row["max_dist_center"] = max(row["max_dist_center"], dc)
        row["last"] = {k: ev.get(k)
                       for k in ("iter", "grad_norm", "grad_max_abs",
                                 "nonfinite", "param_norm", "update_norm",
                                 "update_ratio", "divergence",
                                 "dist_center", "ef_norm", "beacon")}
    return out


def wire_health(events, summaries):
    """Per-rank wire-layer health (parallel/wire.py): rtt percentiles,
    retry/timeout/corrupt/dedup counters from the summaries, healed
    outages from the ``wire`` events — the network half of the churn
    story the membership transitions tell."""
    out = {}
    ranks = set(summaries) | {int(e.get("rank", 0)) for e in events
                              if e.get("ev") == "wire"}
    for rank in sorted(ranks):
        s = summaries.get(rank, {})
        row = {k: v for k, v in s.get("counters", {}).items()
               if k.startswith("wire.")}
        h = s.get("hist", {}).get("wire.rtt")
        if h:
            row["rtt_count"] = h.get("count")
            row["rtt_p50"] = h.get("p50")
            row["rtt_p99"] = h.get("p99")
            # the EXACT streaming extreme (telemetry.Histogram tracks it
            # outside the reservoir) — the worst RTT an SLO cares about,
            # which percentile-of-reservoir can drop
            row["rtt_max"] = h.get("max")
        # the v2 reply-header time split: RTT decomposable into center
        # queueing vs apply even with tracing disabled (§17 satellite)
        for key, label in (("wire.server_queue", "server_queue"),
                           ("wire.server_apply", "server_apply")):
            hh = s.get("hist", {}).get(key)
            if hh:
                row[label + "_p50"] = hh.get("p50")
                row[label + "_p99"] = hh.get("p99")
        outages = [e for e in events
                   if e.get("ev") == "wire" and e.get("kind") == "outage"
                   and int(e.get("rank", 0)) == rank]
        if outages:
            row["outages"] = len(outages)
            row["outage_total_s"] = round(
                sum(float(e.get("secs", 0.0)) for e in outages), 3)
        if row:
            out[rank] = row
    return out


def build_trace(events):
    """Merged per-rank events → Chrome trace-event JSON (Perfetto/
    chrome://tracing).  Layout: one process per rank (pid = rank) with a
    ``phases`` thread of span events, counter tracks for HBM/queue-depth
    (``gauges`` events) and images/sec (``train_record`` events), and
    instant markers for anomaly/crash/stall/fatal-signal.  Spans are
    emitted in ts order with non-negative durations — a ``phase`` event
    is stamped at bracket END, so its span is ``[ts − dt, ts]``, clamped
    at the capture origin."""
    ranks = sorted({int(e.get("rank", 0)) for e in events})
    t0 = min((e["ts"] for e in events if "ts" in e), default=0.0)

    def us(ts):
        return max(0.0, round((ts - t0) * 1e6, 1))

    # §17 causal spans: rounds on tid 1, wire/server handler spans on
    # tid 2 — pre-scanned so the thread metadata and the cross-track flow
    # arrows (client wire span → the server span it caused) can be built
    span_evs = [e for e in events if e.get("ev") == "span" and "ts" in e]
    span_tids = {(int(e.get("rank", 0)),
                  1 if e.get("name") in ("round", "exchange") else 2)
                 for e in span_evs}

    meta, body = [], []
    for r in ranks:
        meta.append({"ph": "M", "pid": r, "name": "process_name",
                     "args": {"name": "center" if r < 0 else f"rank {r}"}})
        meta.append({"ph": "M", "pid": r, "name": "process_sort_index",
                     "args": {"sort_index": r}})
        meta.append({"ph": "M", "pid": r, "tid": 0, "name": "thread_name",
                     "args": {"name": "phases"}})
    for r, tid in sorted(span_tids):
        meta.append({"ph": "M", "pid": r, "tid": tid, "name": "thread_name",
                     "args": {"name": "rounds" if tid == 1 else "spans"}})
    for ev in events:
        kind = ev.get("ev")
        if kind not in TRACKED_EVENTS or "ts" not in ev:
            continue
        rank = int(ev.get("rank", 0))
        if kind == "phase":
            dur = max(0.0, float(ev.get("dt", 0.0))) * 1e6
            end = us(ev["ts"])
            start = max(0.0, end - dur)
            body.append({"ph": "X", "pid": rank, "tid": 0,
                         "ts": round(start, 1),
                         "dur": round(end - start, 1),
                         "name": str(ev.get("sec", "?")), "cat": "phase"})
        elif kind == "gauges":
            for key in TRACE_COUNTER_KEYS:
                if key in ev:
                    body.append({"ph": "C", "pid": rank, "tid": 0,
                                 "ts": us(ev["ts"]), "name": key,
                                 "args": {"value": ev[key]}})
        elif kind == "numerics":
            # numerics events carry short field names; the counter-track
            # vocabulary uses the gauge-qualified "numerics.<field>"
            for key in TRACE_COUNTER_KEYS:
                if not key.startswith("numerics."):
                    continue
                field = key.split(".", 1)[1]
                val = ev.get(field)
                if isinstance(val, (int, float)) and val == val:
                    body.append({"ph": "C", "pid": rank, "tid": 0,
                                 "ts": us(ev["ts"]), "name": key,
                                 "args": {"value": val}})
        elif kind == "train_record":
            if "images_per_sec" in ev:
                body.append({"ph": "C", "pid": rank, "tid": 0,
                             "ts": us(ev["ts"]), "name": "images_per_sec",
                             "args": {"value": round(
                                 ev["images_per_sec"], 1)}})
        elif kind == "val_record":
            if "val_cost" in ev and ev["val_cost"] == ev["val_cost"]:
                body.append({"ph": "C", "pid": rank, "tid": 0,
                             "ts": us(ev["ts"]), "name": "val_cost",
                             "args": {"value": round(ev["val_cost"], 5)}})
        elif kind == "device_profile":
            if ev.get("overlap_ratio") is not None:
                body.append({"ph": "C", "pid": rank, "tid": 0,
                             "ts": us(ev["ts"]),
                             "name": "device.overlap_ratio",
                             "args": {"value": ev["overlap_ratio"]}})
        elif kind == "span":
            name = str(ev.get("name", "?"))
            tid = 1 if name in ("round", "exchange") else 2
            dur = max(0.0, float(ev.get("dt", 0.0)) * 1e6)
            start = us(float(ev["t0"])) if ev.get("t0") is not None \
                else max(0.0, us(ev["ts"]) - dur)
            label = name
            if ev.get("dedup"):
                label += ":dedup"
            elif ev.get("ok") is False:
                label += ":failed"
            elif ev.get("outcome") and ev["outcome"] != "exchanged":
                label += f":{ev['outcome']}"
            body.append({"ph": "X", "pid": rank, "tid": tid,
                         "ts": round(start, 1), "dur": round(dur, 1),
                         "name": label, "cat": "span",
                         "args": {k: ev.get(k)
                                  for k in ("trace", "span", "parent",
                                            "q", "a", "retries", "island")
                                  if ev.get(k) is not None}})
        elif kind == "alert":
            # fleet-health SLO alerts (utils/fleetmon): the marker names
            # the firing RULE and value, so the Perfetto timeline reads
            # "alert:step_time_degraded=0.41 (w3)" at the instant the
            # rule engine fired — next to the fault/membership markers
            # that explain it
            who = "fleet" if ev.get("worker") is None \
                else f"w{ev['worker']}"
            val = ev.get("value")
            label = f"alert:{ev.get('rule', '?')}"
            if val is not None:
                label += f"={val:g}" if isinstance(val, (int, float)) \
                    else f"={val}"
            body.append({"ph": "i", "pid": rank, "tid": 0,
                         "ts": us(ev["ts"]), "s": "p",
                         "name": f"{label} ({who})", "cat": "alert"})
        elif kind in INSTANT_EVENTS:
            parts = []
            if "worker" in ev:          # membership/chaos events name the
                parts.append(f"w{ev['worker']}")   # affected worker
            d = ev.get("kind") or ev.get("reason") or ev.get("role") or \
                ev.get("label") or ev.get("error", "")[:40] or \
                ev.get("signum", "")
            if d:
                parts.append(str(d))
            detail = ":".join(parts)
            body.append({"ph": "i", "pid": rank, "tid": 0,
                         "ts": us(ev["ts"]), "s": "p",
                         "name": f"{kind}:{detail}" if detail else kind,
                         "cat": "alert"})
    # flow arrows: each server span binds back to the client wire span
    # that caused it (join by parent span id) — the visual cross-rank
    # link between a worker's exchange and the center handler it hit.
    # The flow id is the SERVER span id, so a dedup twin gets its own
    # arrow out of the same client span.
    def _mid(ev):
        dur = max(0.0, float(ev.get("dt", 0.0)) * 1e6)
        start = us(float(ev["t0"])) if ev.get("t0") is not None \
            else max(0.0, us(ev["ts"]) - dur)
        return round(start + dur / 2.0, 1)

    wire_client = {e.get("span"): e for e in span_evs
                   if e.get("side") != "server"
                   and str(e.get("name", "")).startswith("wire.")}
    for s_ev in span_evs:
        if s_ev.get("side") != "server":
            continue
        c_ev = wire_client.get(s_ev.get("parent"))
        if c_ev is None:
            continue              # client span lost (crash mid-round)
        fid = str(s_ev.get("span"))
        body.append({"ph": "s", "id": fid, "cat": "wire", "name": "rpc",
                     "pid": int(c_ev.get("rank", 0)), "tid": 2,
                     "ts": _mid(c_ev)})
        body.append({"ph": "f", "bp": "e", "id": fid, "cat": "wire",
                     "name": "rpc", "pid": int(s_ev.get("rank", 0)),
                     "tid": 2, "ts": _mid(s_ev)})
    body.sort(key=lambda e: e["ts"])
    return {"displayTimeUnit": "ms", "traceEvents": meta + body}


def build_report(record_dir, window_s=10.0, events=None):
    if events is None:
        events = load_events(record_dir)
    summaries = load_summaries(record_dir)
    dumps = find_flight_dumps(record_dir)
    runs = sorted({ev.get("run") for ev in events if ev.get("run")})
    ranks = sorted({int(ev.get("rank", 0)) for ev in events})
    crashes = [ev for ev in events if ev["ev"] in ("crash", "stall",
                                                   "fatal_signal",
                                                   "anomaly")]
    # last device-attribution result per rank (worker trace_dir captures,
    # utils/devprof) — the comm/compute overlap evidence
    device = {}
    for ev in events:
        if ev["ev"] == "device_profile":
            device[int(ev.get("rank", 0))] = {
                k: ev.get(k) for k in ("compute_secs", "comm_secs",
                                       "exposed_comm_secs", "overlap_ratio",
                                       "lanes", "train_dispatches")}
    # membership transitions + injected faults (elastic runtime,
    # parallel/membership.py + utils/chaos.py) — the run's churn story
    membership = [
        {"ts": ev["ts"], "ev": ev["ev"], "worker": ev.get("worker"),
         "reason": ev.get("reason"), "kind": ev.get("kind"),
         "rejoin": ev.get("rejoin")}
        for ev in events
        if ev["ev"] in ("worker_join", "worker_leave", "worker_demote",
                        "fault_injected", "center_down",
                        "center_restored")]
    # fleet-health SLO alerts (utils/fleetmon): what the rule engine
    # fired during the window, cited next to the wire health it explains
    alerts = [{"ts": ev["ts"], "rule": ev.get("rule"),
               "series": ev.get("series"), "scope": ev.get("scope"),
               "worker": ev.get("worker"), "value": ev.get("value"),
               "threshold": ev.get("threshold"),
               "action": ev.get("action")}
              for ev in events if ev["ev"] == "alert"]
    return {
        "record_dir": os.path.abspath(record_dir),
        "runs": runs, "ranks": ranks, "events": len(events),
        "device_profiles": device,
        "phases": phase_breakdown(events),
        "throughput_timeline": throughput_timeline(events),
        "straggler_ranking": straggler_ranking(events, window_s),
        "flags": health_flags(events, summaries),
        "counters": {r: s.get("counters", {}) for r, s in summaries.items()},
        "wire": wire_health(events, summaries),
        "numerics": numerics_health(events),
        "alerts": alerts,
        "traces": trace_summary(events, window_s),
        "membership_events": membership,
        "crash_events": crashes,
        "flight_dumps": dumps,
    }


def print_report(rep):
    print(f"telemetry report — {rep['record_dir']}")
    print(f"  runs: {', '.join(rep['runs']) or '(none)'}   "
          f"ranks: {rep['ranks']}   events: {rep['events']}")
    if rep["phases"]:
        print("\nphase breakdown (seconds per dispatch):")
        print(f"  {'phase':<9}{'count':>7}{'total':>10}{'mean':>10}"
              f"{'p50':>10}{'p95':>10}{'p99':>10}")
        for sec, p in rep["phases"].items():
            print(f"  {sec:<9}{p['count']:>7}{p['total']:>10.3f}"
                  f"{p['mean']:>10.5f}{p['p50']:>10.5f}{p['p95']:>10.5f}"
                  f"{p['p99']:>10.5f}")
    if rep["straggler_ranking"]:
        print("\nstraggler ranking (slowest rank per "
              "window, slowest first):")
        for r in rep["straggler_ranking"]:
            print(f"  rank {r['rank']}: straggled {r['windows_straggled']} "
                  f"window(s), mean train {r['mean_train_secs'] * 1e3:.2f} ms"
                  f", p95 {r['p95_train_secs'] * 1e3:.2f} ms "
                  f"over {r['dispatches']} dispatches")
    for rank, tl in sorted(rep["throughput_timeline"].items()):
        pts = " ".join(f"{t}s:{ips}" for t, ips in tl[-8:])
        print(f"\nrank {rank} throughput timeline (img/s, last 8): {pts}")
    pf = rep["flags"].get("prefetch")
    if pf:
        print("\nprefetch queue:")
        for rank, f in sorted(pf.items()):
            verdict = "STARVING" if f["starving"] else "healthy"
            print(f"  rank {rank}: {verdict} — starved share "
                  f"{f['starved_share']:.1%} of {f['dequeues']} dequeues, "
                  f"min depth {f['min_queue_depth']}, "
                  f"p50 depth {f['p50_queue_depth']}")
    hb = rep["flags"].get("hbm")
    if hb:
        print("\nHBM headroom:")
        for rank, f in sorted(hb.items()):
            share = (f"{f['peak_share']:.1%} of limit"
                     if f["peak_share"] is not None else "limit unknown")
            verdict = " — NEAR OOM" if f["near_oom"] else ""
            print(f"  rank {rank}: peak {f['peak_bytes'] / 2**30:.2f} GiB "
                  f"({share}){verdict}")
    if rep.get("device_profiles"):
        print("\ndevice-time attribution (last trace capture per rank):")
        for rank, d in sorted(rep["device_profiles"].items()):
            overlap = (f"{d['overlap_ratio']:.1%} overlap"
                       if d.get("overlap_ratio") is not None
                       else "no collectives in window")
            print(f"  rank {rank}: compute {d.get('compute_secs', 0):.3f}s "
                  f"comm {d.get('comm_secs', 0):.3f}s exposed "
                  f"{d.get('exposed_comm_secs', 0):.3f}s ({overlap})")
    nm = rep.get("numerics")
    if nm:
        print("\nnumerics health (per-rank, last report + window worst):")
        for rank, n in sorted(nm.items()):
            last = n.get("last", {})
            verdict = ""
            if n["max_divergence"] > 0:
                verdict = " — DIVERGED"
            elif n["nonfinite_total"] > 0:
                verdict = " — OVERFLOWED"
            gn = last.get("grad_norm")
            ur = last.get("update_ratio")
            dc = last.get("dist_center")
            ef = last.get("ef_norm")
            parts = [f"iter {last.get('iter')}"]
            if isinstance(gn, (int, float)):
                parts.append(f"grad_norm {gn:.4g}")
            if isinstance(ur, (int, float)):
                parts.append(f"update_ratio {ur:.3g}")
            if isinstance(dc, (int, float)) and dc:
                parts.append(f"dist_center {dc:.4g}")
            if isinstance(ef, (int, float)) and ef:
                parts.append(f"ef_norm {ef:.4g}")
            beacon = last.get("beacon")
            parts.append(
                f"divergence {n['max_divergence']:.4g} (max)"
                if beacon else "no beacon")
            parts.append(f"nonfinite {int(n['nonfinite_total'])}")
            print(f"  rank {rank}: " + ", ".join(parts)
                  + f" over {n['reports']} report(s){verdict}")
    an = rep["flags"].get("anomalies")
    if an:
        print("\nsentry anomalies:")
        for rank, kinds in sorted(an.items()):
            pretty = ", ".join(f"{k}×{n}" for k, n in sorted(kinds.items()))
            print(f"  rank {rank}: {pretty}")
    if rep.get("wire"):
        print("\nwire health (center RPC layer):")
        for rank, w in sorted(rep["wire"].items()):
            rtt = (f"rtt p50 {w['rtt_p50'] * 1e3:.1f}ms "
                   f"p99 {w['rtt_p99'] * 1e3:.1f}ms "
                   f"max {w['rtt_max'] * 1e3:.1f}ms "
                   f"over {w['rtt_count']} ops"
                   if w.get("rtt_p50") is not None else "no rtt samples")
            if w.get("server_queue_p50") is not None:
                # the v2 reply-header split: how much of that RTT was the
                # center queueing/applying rather than the wire itself
                rtt += (f" [center queue p50 "
                        f"{w['server_queue_p50'] * 1e3:.2f}ms, apply p50 "
                        f"{w.get('server_apply_p50', 0) * 1e3:.2f}ms]")
            churn = ", ".join(
                f"{k.split('.', 1)[1]}×{int(v)}" for k, v in sorted(
                    w.items()) if k.startswith("wire.") and v)
            outage = (f", outages {w['outages']} "
                      f"({w['outage_total_s']}s total)"
                      if w.get("outages") else "")
            print(f"  rank {rank}: {rtt}"
                  + (f" — {churn}" if churn else "") + outage)
        wire_alerts = [a for a in rep.get("alerts", ())
                       if str(a.get("series", "")).startswith("wire")]
        if wire_alerts:
            # the SLO verdicts behind those numbers: which wire rules
            # fired in this window, on whom
            cite = ", ".join(
                f"{a['rule']}"
                + ("[fleet]" if a.get("worker") is None
                   else f"[w{a['worker']}]")
                for a in wire_alerts[-6:])
            print(f"  alerts fired: {cite}")
    alerts = rep.get("alerts")
    if alerts:
        print(f"\nfleet-health alerts ({len(alerts)} fired):")
        for a in alerts[-10:]:
            who = "fleet" if a.get("worker") is None \
                else f"worker {a['worker']}"
            act = f" -> {a['action']}" if a.get("action") else ""
            print(f"  {a['rule']} on {who}: {a['series']}={a['value']} "
                  f"(threshold {a['threshold']}){act}")
    tr = rep.get("traces")
    if tr:
        jr = (f"{tr['join_rate']:.1%} joined" if tr.get("join_rate")
              is not None else "no wire ops")
        print(f"\ndistributed traces ({tr['rounds']} exchange rounds, "
              f"{tr['wire_ops']} wire ops, {jr}, "
              f"{tr['dedup_twins']} dedup twin(s)):")
        comp = tr["components_total_s"]
        print("  critical path totals: " + "  ".join(
            f"{k} {comp[k]:.3f}s" for k in comp))
        if tr.get("root_cause"):
            print("  straggler root cause (dominant component per worker):")
            for rank, rc in sorted(tr["root_cause"].items(),
                                   key=lambda kv: str(kv[0])):
                wins = ", ".join(f"{k}×{v}" for k, v in
                                 rc["windows_dominated_by"].items())
                print(f"    rank {rank}: {rc['dominant'].upper()} "
                      f"({rc['dominant_share']:.0%} of round time; "
                      f"windows: {wins}; mean round "
                      f"{rc['mean_round_s'] * 1e3:.1f} ms over "
                      f"{rc['rounds']} rounds)")
    if rep.get("membership_events"):
        print("\nmembership transitions / injected faults:")
        for ev in rep["membership_events"][-12:]:
            detail = ev.get("reason") or ev.get("kind") or ""
            who = "center" if ev["ev"].startswith("center_") \
                else f"worker {ev.get('worker')}"
            print(f"  {ev['ev']} {who}"
                  + (f" ({detail})" if detail else "")
                  + (" [rejoin]" if ev.get("rejoin") else ""))
    if rep["crash_events"]:
        print("\ncrash/stall/anomaly events:")
        for ev in rep["crash_events"][-5:]:
            detail = ev.get("error") or ev.get("label") or \
                ev.get("kind") or ev.get("signum", "")
            print(f"  rank {ev.get('rank', 0)} {ev['ev']}: {detail}")
    if rep["flight_dumps"]:
        print("\nflight recordings (crash/stall trails):")
        for p in rep["flight_dumps"]:
            print(f"  {p}")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("record_dir")
    ap.add_argument("--window", type=float, default=10.0,
                    help="straggler window seconds (default 10)")
    ap.add_argument("--since", type=float, default=None, metavar="TS",
                    help="only events at/after this unix timestamp — "
                         "incremental reports over long runs without "
                         "parsing the whole stream")
    ap.add_argument("--last", type=float, default=None, metavar="SEC",
                    help="only the trailing SEC seconds of the stream "
                         "(anchored at the newest event)")
    ap.add_argument("--json", default=None, metavar="OUT",
                    help="also write the machine-readable report here "
                         "('-' for stdout)")
    ap.add_argument("--trace", default=None, metavar="OUT",
                    help="also write Chrome trace-event JSON (one track "
                         "per rank: phase spans, HBM/queue-depth/img-s "
                         "counter tracks, anomaly markers) — open in "
                         "Perfetto (ui.perfetto.dev) or chrome://tracing")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.record_dir):
        print(f"no such directory: {args.record_dir}", file=sys.stderr)
        return 2
    since = args.since
    if args.last is not None:
        _, hi = stream_extent(args.record_dir)
        if hi is not None:
            last_since = hi - args.last
            since = last_since if since is None else max(since, last_since)
    events = load_events(args.record_dir,        # parsed ONCE, shared by
                         since=since)            # report and --trace
    rep = build_report(args.record_dir, args.window,
                       events=events)
    if since is not None:
        rep["since"] = round(since, 3)
    if not rep["events"]:
        win = " in the requested window" if since is not None else ""
        print(f"no telemetry_rank*.jsonl events under "
              f"{args.record_dir}{win} — run with record_dir set "
              "(telemetry streams there)", file=sys.stderr)
        return 1
    print_report(rep)
    if args.json == "-":
        print(json.dumps(rep))
    elif args.json:
        with open(args.json, "w") as f:
            json.dump(rep, f, indent=1, sort_keys=True)
        print(f"\nwrote {args.json}")
    if args.trace:
        trace = build_trace(events)
        with open(args.trace, "w") as f:
            json.dump(trace, f)
        spans = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
        print(f"\nwrote {args.trace} ({spans} spans across "
              f"{len(rep['ranks'])} rank track(s)) — open in Perfetto")
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:
        os._exit(0)          # downstream `head`/pager closed the pipe
