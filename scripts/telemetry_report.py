#!/usr/bin/env python
"""Merge per-rank telemetry streams into one run report.

Reads a record/telemetry directory produced by a run with ``record_dir``
set (see ``theanompi_tpu/utils/telemetry.py`` and docs/design.md §11):

* ``telemetry_rank{r}.jsonl``        — the per-rank event streams
* ``telemetry_summary_rank{r}.json`` — counters/gauges/histograms at close
* ``flight_rank{r}.jsonl`` / ``crash_*/flight_rank{r}.jsonl`` — crash dumps

and emits the cross-worker run report the bucket sums can't answer:

* **phase breakdown** — per recorder section (train/comm/load/...), event
  count, total seconds, mean and p50/p95/p99 tail percentiles;
* **per-rank throughput timeline** — images/sec over wall time from the
  periodic ``train_record`` events;
* **straggler ranking** — wall time is cut into windows (``--window``,
  default 10 s); each window's slowest rank (highest mean ``phase.train``
  dt) is charged one straggle; ranks sorted by windows-straggled and mean
  step time;
* **health flags** — prefetch queue starvation (starved dequeues / min
  queue depth) and HBM headroom (peak bytes vs limit from ``gauges``
  events), plus any flight recordings found (a crash/stall happened).

Usage:
    python scripts/telemetry_report.py <record_dir> [--window SEC]
                                       [--json out.json]

Stdlib only — runnable on a machine with no jax installed.
"""

import argparse
import glob
import json
import os
import sys
from collections import defaultdict


def percentile(values, q):
    # same nearest-rank formula as telemetry.Histogram.percentile — kept
    # local so this script stays stdlib-only (importing the package would
    # drag jax in via theanompi_tpu/__init__)
    if not values:
        return None
    s = sorted(values)
    idx = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[idx]


def load_events(record_dir):
    """All events from every per-rank stream, sorted by timestamp."""
    events = []
    for path in sorted(glob.glob(
            os.path.join(record_dir, "telemetry_rank*.jsonl"))):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue          # a crash can truncate the last line
                if isinstance(ev, dict) and "ev" in ev:
                    events.append(ev)
    events.sort(key=lambda e: e.get("ts", 0))
    return events


def load_summaries(record_dir):
    out = {}
    for path in sorted(glob.glob(
            os.path.join(record_dir, "telemetry_summary_rank*.json"))):
        try:
            with open(path) as f:
                s = json.load(f)
            out[int(s.get("rank", 0))] = s
        except (ValueError, OSError):
            continue
    return out


def find_flight_dumps(record_dir):
    return sorted(
        glob.glob(os.path.join(record_dir, "flight_rank*.jsonl")) +
        glob.glob(os.path.join(record_dir, "crash_*", "flight_rank*.jsonl")))


def phase_breakdown(events):
    """Per-section dt distribution from the ``phase`` events."""
    dts = defaultdict(list)
    for ev in events:
        if ev["ev"] == "phase":
            dts[ev.get("sec", "?")].append(float(ev.get("dt", 0.0)))
    out = {}
    for sec, vals in sorted(dts.items()):
        out[sec] = {"count": len(vals), "total": round(sum(vals), 4),
                    "mean": round(sum(vals) / len(vals), 6),
                    "p50": percentile(vals, 50), "p95": percentile(vals, 95),
                    "p99": percentile(vals, 99)}
    return out


def throughput_timeline(events):
    """Per-rank [(t_rel, images_per_sec), ...] from train_record events."""
    t0 = events[0]["ts"] if events else 0.0
    tl = defaultdict(list)
    for ev in events:
        if ev["ev"] == "train_record" and "images_per_sec" in ev:
            tl[int(ev.get("rank", 0))].append(
                (round(ev["ts"] - t0, 1), round(ev["images_per_sec"], 1)))
    return dict(tl)


def straggler_ranking(events, window_s):
    """Charge each wall-clock window to its slowest rank (highest mean
    ``phase.train`` dt).  Single-rank runs trivially 'win' every window —
    the mean/p95 columns are the useful part there."""
    train = [(ev["ts"], int(ev.get("rank", 0)), float(ev.get("dt", 0.0)))
             for ev in events
             if ev["ev"] == "phase" and ev.get("sec") == "train"]
    if not train:
        return []
    t0 = train[0][0]
    per_window = defaultdict(lambda: defaultdict(list))
    per_rank = defaultdict(list)
    for ts, rank, dt in train:
        per_window[int((ts - t0) / window_s)][rank].append(dt)
        per_rank[rank].append(dt)
    straggles = defaultdict(int)
    for w, by_rank in per_window.items():
        if len(by_rank) < 1:
            continue
        slowest = max(by_rank,
                      key=lambda r: sum(by_rank[r]) / len(by_rank[r]))
        straggles[slowest] += 1
    ranking = []
    for rank in sorted(per_rank):
        vals = per_rank[rank]
        ranking.append({
            "rank": rank, "windows_straggled": straggles.get(rank, 0),
            "dispatches": len(vals),
            "mean_train_secs": round(sum(vals) / len(vals), 6),
            "p95_train_secs": percentile(vals, 95)})
    ranking.sort(key=lambda r: (-r["windows_straggled"],
                                -(r["mean_train_secs"] or 0)))
    return ranking


def health_flags(events, summaries):
    """Queue-starvation and HBM-headroom verdicts, per rank where known."""
    flags = {}
    # prefetch starvation: counters + queue-depth histogram from summaries
    starve = {}
    for rank, s in summaries.items():
        c = s.get("counters", {})
        deq = c.get("prefetch.dequeues", 0)
        if deq:
            h = s.get("hist", {}).get("prefetch.queue_depth", {})
            share = c.get("prefetch.starved_dequeues", 0) / deq
            starve[rank] = {
                "dequeues": int(deq), "starved_share": round(share, 4),
                "min_queue_depth": h.get("min"),
                "p50_queue_depth": h.get("p50"),
                "starving": share > 0.05}
    if starve:
        flags["prefetch"] = starve
    # HBM headroom: the LAST gauges event per rank
    hbm = {}
    for ev in events:
        if ev["ev"] == "gauges" and "hbm_peak_bytes" in ev:
            rank = int(ev.get("rank", 0))
            peak, limit = ev["hbm_peak_bytes"], ev.get("hbm_bytes_limit")
            hbm[rank] = {"peak_bytes": int(peak),
                         "limit_bytes": int(limit) if limit else None,
                         "peak_share": round(peak / limit, 4) if limit
                         else None,
                         "near_oom": bool(limit) and peak / limit > 0.9}
    if hbm:
        flags["hbm"] = hbm
    return flags


def build_report(record_dir, window_s=10.0):
    events = load_events(record_dir)
    summaries = load_summaries(record_dir)
    dumps = find_flight_dumps(record_dir)
    runs = sorted({ev.get("run") for ev in events if ev.get("run")})
    ranks = sorted({int(ev.get("rank", 0)) for ev in events})
    crashes = [ev for ev in events if ev["ev"] in ("crash", "stall",
                                                   "fatal_signal")]
    return {
        "record_dir": os.path.abspath(record_dir),
        "runs": runs, "ranks": ranks, "events": len(events),
        "phases": phase_breakdown(events),
        "throughput_timeline": throughput_timeline(events),
        "straggler_ranking": straggler_ranking(events, window_s),
        "flags": health_flags(events, summaries),
        "counters": {r: s.get("counters", {}) for r, s in summaries.items()},
        "crash_events": crashes,
        "flight_dumps": dumps,
    }


def print_report(rep):
    print(f"telemetry report — {rep['record_dir']}")
    print(f"  runs: {', '.join(rep['runs']) or '(none)'}   "
          f"ranks: {rep['ranks']}   events: {rep['events']}")
    if rep["phases"]:
        print("\nphase breakdown (seconds per dispatch):")
        print(f"  {'phase':<9}{'count':>7}{'total':>10}{'mean':>10}"
              f"{'p50':>10}{'p95':>10}{'p99':>10}")
        for sec, p in rep["phases"].items():
            print(f"  {sec:<9}{p['count']:>7}{p['total']:>10.3f}"
                  f"{p['mean']:>10.5f}{p['p50']:>10.5f}{p['p95']:>10.5f}"
                  f"{p['p99']:>10.5f}")
    if rep["straggler_ranking"]:
        print("\nstraggler ranking (slowest rank per "
              "window, slowest first):")
        for r in rep["straggler_ranking"]:
            print(f"  rank {r['rank']}: straggled {r['windows_straggled']} "
                  f"window(s), mean train {r['mean_train_secs'] * 1e3:.2f} ms"
                  f", p95 {r['p95_train_secs'] * 1e3:.2f} ms "
                  f"over {r['dispatches']} dispatches")
    for rank, tl in sorted(rep["throughput_timeline"].items()):
        pts = " ".join(f"{t}s:{ips}" for t, ips in tl[-8:])
        print(f"\nrank {rank} throughput timeline (img/s, last 8): {pts}")
    pf = rep["flags"].get("prefetch")
    if pf:
        print("\nprefetch queue:")
        for rank, f in sorted(pf.items()):
            verdict = "STARVING" if f["starving"] else "healthy"
            print(f"  rank {rank}: {verdict} — starved share "
                  f"{f['starved_share']:.1%} of {f['dequeues']} dequeues, "
                  f"min depth {f['min_queue_depth']}, "
                  f"p50 depth {f['p50_queue_depth']}")
    hb = rep["flags"].get("hbm")
    if hb:
        print("\nHBM headroom:")
        for rank, f in sorted(hb.items()):
            share = (f"{f['peak_share']:.1%} of limit"
                     if f["peak_share"] is not None else "limit unknown")
            verdict = " — NEAR OOM" if f["near_oom"] else ""
            print(f"  rank {rank}: peak {f['peak_bytes'] / 2**30:.2f} GiB "
                  f"({share}){verdict}")
    if rep["crash_events"]:
        print("\ncrash/stall events:")
        for ev in rep["crash_events"][-5:]:
            detail = ev.get("error") or ev.get("label") or \
                ev.get("signum", "")
            print(f"  rank {ev.get('rank', 0)} {ev['ev']}: {detail}")
    if rep["flight_dumps"]:
        print("\nflight recordings (crash/stall trails):")
        for p in rep["flight_dumps"]:
            print(f"  {p}")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("record_dir")
    ap.add_argument("--window", type=float, default=10.0,
                    help="straggler window seconds (default 10)")
    ap.add_argument("--json", default=None, metavar="OUT",
                    help="also write the machine-readable report here "
                         "('-' for stdout)")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.record_dir):
        print(f"no such directory: {args.record_dir}", file=sys.stderr)
        return 2
    rep = build_report(args.record_dir, args.window)
    if not rep["events"]:
        print(f"no telemetry_rank*.jsonl events under {args.record_dir} — "
              "run with record_dir set (telemetry streams there)",
              file=sys.stderr)
        return 1
    print_report(rep)
    if args.json == "-":
        print(json.dumps(rep))
    elif args.json:
        with open(args.json, "w") as f:
            json.dump(rep, f, indent=1, sort_keys=True)
        print(f"\nwrote {args.json}")
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:
        os._exit(0)          # downstream `head`/pager closed the pipe
