#!/usr/bin/env python
"""Predicted multi-chip scaling efficiency from measured 1-chip rows.

This environment exposes ONE real TPU chip (round-4 verdict: "do not ask
for real multi-chip runs; do ask for the comm-share-derived efficiency
prediction").  This script produces that prediction: for each staged
BASELINE.json config with a measured TPU t_train in the canonical perf
matrix, it models the per-step wire bytes of the config's exchange
strategy analytically (formulas below, from the strategy implementations
in ``theanompi_tpu/parallel/strategies.py`` / ``exchanger.py``), divides
by the TPU v5e ICI link bandwidth, and reports predicted scaling
efficiency at 8 and 32 chips under two bounds:

- ``eff_no_overlap``  = t_step / (t_step + t_comm)   (comm fully exposed)
- ``eff_full_overlap`` = t_step / max(t_step, t_comm) (comm fully hidden)

The truth lands between the bounds; XLA overlaps collectives with
independent compute inside the jitted step, so well-fused configs sit
near the full-overlap bound.  The reference's own headline table
(SURVEY.md §6: time-per-5120-images vs worker count) is the shape this
mirrors.

**Bucketed-pipeline model (round 9, ISSUE 13).**  The one-shot bounds
above say nothing about WHERE between them a config lands; the bucketed
wire (``parallel/buckets.py``) makes that predictable.  With the
payload split into ``n = ceil(payload_bytes / bucket_bytes)`` buckets:

    t_comm   = n·LAT + wire_bytes / BW          (latency + bandwidth)
    fill     = LAT + (wire_bytes / n) / BW      (first bucket: nothing
                                                 can hide before its
                                                 producers finish)
    credit   = min(t_comm − fill, TAIL·t_step)  (overlap credit, capped
                                                 by the backprop tail —
                                                 there is no compute
                                                 left to hide behind
                                                 once backprop drains)
    exposed  = t_comm − credit
    eff      = t_step / (t_step + exposed)

``LAT`` (:data:`COLL_LATENCY_S`) is the per-collective setup cost that
makes n → ∞ a loss, not a win; ``TAIL`` (:data:`BACKPROP_TAIL_FRAC`)
approximates the backward share of the step a reduction can overlap
(grads become final back-to-front through roughly the second half).
A monolithic wire is the n = 1 case: fill = t_comm, credit = 0 — the
``eff_no_overlap`` bound, recovered exactly.  Each row reports the
monolithic and the 4 MiB-bucketed prediction side by side, and
``pred_exposed_comm_secs`` is emitted per row so the measured
``exposed_comm_secs`` BENCH_TRACE column of the r9 matrix rows
(bucketed + monolithic controls, scripts/rows.py) can be compared
prediction-vs-trace per config.

Wire-bytes-per-step models (P = param count, b = wire bytes/elem,
N = chips; ring collectives over a 1D ICI ring, per-chip bytes):
- allreduce/ring (BSP fused grads):  2 * (N-1)/N * P * b
- bf16 wire (nccl16/asa16):          same with b=2
- EASGD (sync_freq=f):               2 * (N-1)/N * P * b / f
- ASGD  (sync_freq=f, default 1):    same formula
- GoSGD (exch_prob=p):               p * P * b   (expected send per step)
- topk (ratio=r):                    (N-1) * r * P * 8   (allgather of
                                     (idx,val) pairs from every worker)
- onebit:                            2 * (N-1)/N * P/8  (packed signs)
- powersgd rank r:                   2 * (N-1)/N * (r * sum(rows+cols)
                                     + dense) * 4, where rows/cols follow
                                     PowerSGD's OWN factorization of each
                                     leaf — [prod(shape[:-1]), shape[-1]]
                                     — summed over the leaves its
                                     _compressible gate accepts, and
                                     ``dense`` counts the elements of the
                                     leaves it sends as a plain psum
                                     (round-5 ADVICE: the old
                                     shape[0]+size//shape[0] estimate
                                     overstated vgg16 wire bytes ~60×)

ICI bandwidth: TPU v5e has 4 ICI links/chip at ~45 GB/s per direction
(public "How to Scale Your Model" figures); a bidirectional ring uses
two directions -> BW = 90 GB/s effective, with a 2x sensitivity band
reported (45/180) since the achieved fraction depends on topology and
XLA's collective scheduling.

Usage: python scripts/predict_scaling.py [matrix.jsonl ...]
Writes one JSON object to stdout (the watcher redirects it to
scaling_prediction_r5.json) and a human table to stderr.
"""

import glob
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ICI_GBPS = 90e9          # bidirectional 1D-ring effective, v5e (see above)
SENS = (45e9, 180e9)     # sensitivity band
CHIP_COUNTS = (8, 32)

# bucketed-pipeline model constants (docstring above): per-collective
# setup latency (dispatch + ICI rendezvous — order of the ~µs published
# for small TPU collectives; the 2x band on BW dwarfs its uncertainty),
# the planner default bucket size, and the backprop-tail share of the
# step available as overlap credit
COLL_LATENCY_S = 5e-6
DEFAULT_BUCKET_BYTES = 4 << 20
BACKPROP_TAIL_FRAC = 0.5


def bucketed_exchange(wire_b: float, payload_b: float, t_step: float,
                      bucket_bytes: int) -> dict:
    """Exposed-comm prediction for one exchange under the bucketed
    -pipeline model.  ``wire_b`` is what actually crosses ICI (compressed
    strategies ship less), ``payload_b`` is what the planner slices —
    strategy-dependent, see :func:`bucket_payload_bytes`;
    ``bucket_bytes <= 0`` or a payload smaller than one bucket is the
    monolithic n = 1 case."""
    n = 1 if bucket_bytes <= 0 else max(1, -(-int(payload_b) // int(bucket_bytes)))
    t_comm = n * COLL_LATENCY_S + wire_b / ICI_GBPS
    fill = COLL_LATENCY_S + (wire_b / n) / ICI_GBPS
    credit = min(max(0.0, t_comm - fill), BACKPROP_TAIL_FRAC * t_step)
    exposed = t_comm - credit
    return {"n_buckets": n,
            "t_comm_s": round(t_comm, 6),
            "pred_exposed_comm_secs": round(exposed, 6),
            "pred_overlap_ratio": (round(1.0 - exposed / t_comm, 4)
                                   if t_comm > 0 else None),
            "eff": round(t_step / (t_step + exposed), 4)}

def pipeline_bubble(pp: int, v: int, m: int, t_chunk: float = 1.0,
                    t_hop: float = 0.0) -> dict:
    """Pipeline-schedule bubble model (round 10, ISSUE 16).

    The fill/drain GPipe scan idles ``pp−1`` warm-up/drain ticks of an
    ``m + pp − 1``-tick schedule; interleaving ``v`` virtual stages per
    device (``parallel/pipeline.py`` schedule table) keeps each device's
    useful work at ``v·m`` chunk-ticks but each tick is a ``1/v``-depth
    chunk, so the same ``pp−1`` idle ticks sit in a ``v·m + pp − 1``-tick
    schedule — the bubble shrinks by ~``v``.  With per-tick costs:

        busy  = v·m·t_chunk                (useful compute per device)
        span  = (v·m + pp − 1)·(t_chunk + t_hop)
        bubble_fraction = 1 − busy/span

    ``t_hop`` is the per-tick activation-hop cost the schedule pays
    ``v·m + pp − 1`` times instead of ``m + pp − 1`` — the price of
    interleaving, zero when the async hop fully overlaps chunk compute
    (jax_compat.ppermute_start/done under the fused scan).  At
    ``t_hop = 0`` this reduces to the classic ``(pp−1)/(v·m + pp−1)``,
    which is exactly what the measured ``pipeline_bubble_ticks`` column
    (devprof.pipeline_schedule_report) reports when the capture's hop
    count verifies the tick structure."""
    ticks = v * m + pp - 1
    busy = v * m * t_chunk
    span = ticks * (t_chunk + t_hop)
    return {"pp": pp, "v": v, "m": m, "ticks": ticks,
            "warmup_ticks": pp - 1,
            "bubble_fraction": round(1.0 - busy / span, 4)}


# staged r10 pipeline rows (scripts/rows.py) -> (matrix label, pp, v, M);
# t_chunk/t_hop default to the uniform-tick model — the measured join
# below reports both the tick-count and wall-time measured bubbles next
# to the prediction
PIPELINE_CONFIGS = [
    ("transformer_lm-b16-pp4-trace",    4, 1, 8),
    ("transformer_lm-b16-pp4-v2-trace", 4, 2, 8),
    ("transformer_lm-b16-pp4-v4-trace", 4, 4, 8),
]


def update_state_bytes_per_chip(replicated_bytes: float, n: int) -> float:
    """Update-plane-sharding memory model (round 11, ISSUE 17): the
    leaf-wise wrapper (``parallel/update_sharding.py``) chunks every
    planned leaf to ``ceil(L/N)`` elements per chip, so per-chip
    update-state bytes are ``replicated/N`` to first order.  The measured
    ``update_state_bytes_per_chip`` (devprof.USHARD_ROW_COLUMNS) sits
    slightly ABOVE this: ceil rounding pads each ragged leaf by at most
    ``N−1`` elements, and sub-threshold leaves (< ``ushard_min_bytes`` or
    < N elements) stay fully replicated per chip."""
    return replicated_bytes / n


# staged r11 update-sharding rows (scripts/rows.py): sharded row joined
# against its replicated control (which carries the same report columns
# via BENCH_USHARD_REPORT=1) -> (ushard label, control label, N)
USHARD_CONFIGS = [
    ("transformer_lm-b8-n2-ushard", "transformer_lm-b8-n2", 2),
    ("transformer_lm-b8-n4-ushard", "transformer_lm-b8-n4", 4),
]


# staged r12 fused-compression rows (scripts/rows.py): fuse row (Pallas
# kernel pipeline) joined against its forced-oracle control ->
# (fuse label, control label, strategy)
COMPRESS_CONFIGS = [
    ("transformer_lm-b8-onebit-n2-fuse",
     "transformer_lm-b8-onebit-n2", "onebit"),
    ("transformer_lm-b8-topk-n2-fuse",
     "transformer_lm-b8-topk-n2", "topk"),
    ("transformer_lm-b8-powersgd2-n2-fuse",
     "transformer_lm-b8-powersgd2-n2", "powersgd2"),
]


# staged configs (BASELINE.json) -> (matrix row, strategy model, params key)
CONFIGS = [
    ("alexnet-b128",      "allreduce", 4, "alexnet", 128),
    ("googlenet-b32",     "allreduce", 4, "googlenet", 32),
    ("vgg16-b32",         "allreduce", 4, "vgg16", 32),
    ("resnet50-b32",      "allreduce", 4, "resnet50", 32),
    ("cifar10-b128",      "allreduce", 4, "cifar10", 128),
    ("vgg16-b32-easgd",   "easgd",     4, "vgg16", 32),
    ("resnet50-b32-gosgd", "gosgd",    4, "resnet50", 32),
    ("vgg16-b32-topk",    "topk",      4, "vgg16", 32),
    ("vgg16-b32-onebit",  "onebit",    4, "vgg16", 32),
    ("vgg16-b32-powersgd4", "powersgd4", 4, "vgg16", 32),
]

_COUNT_SRC = r"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")   # never touch the axon backend
import importlib
import numpy as np
from theanompi_tpu.models.registry import MODELS
from theanompi_tpu.parallel.strategies import PowerSGD
ps = PowerSGD(4)     # the staged powersgd4 config's rank gates the
                     # compressible set; lower ranks compress a superset
out = {}
for name in sys.argv[1:]:
    modelfile, modelclass, extra = MODELS[name]
    cfg = {"size": 1, "rank": 0, "verbose": False, **extra}
    m = getattr(importlib.import_module(modelfile), modelclass)(cfg)
    leaves = jax.tree.leaves(m.params)
    P = sum(int(l.size) for l in leaves)
    # PowerSGD's factorization of leaf M is [prod(shape[:-1]), shape[-1]]
    # (conv kernels fold every leading dim into rows); it ships
    # r*(rows+cols) per COMPRESSIBLE leaf and a plain dense psum for the
    # rest — mirror exactly that split here
    rc = sum(int(np.prod(np.shape(l)[:-1])) + int(np.shape(l)[-1])
             for l in leaves if ps._compressible(np.shape(l)))
    dense = sum(int(l.size)
                for l in leaves if not ps._compressible(np.shape(l)))
    out[name] = {"params": P, "rows_plus_cols": rc, "powersgd_dense": dense}
print(json.dumps(out))
"""


def _param_counts(models: list) -> dict:
    """Instantiate each model on the CPU backend in a SUBPROCESS (the
    parent may live next to a wedged axon tunnel; the child forces the
    CPU platform programmatically before any backend touch) and cache
    the counts beside the repo."""
    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "..", "model_param_counts.json")
    have = {}
    if os.path.exists(cache):
        with open(cache) as f:
            have = json.load(f)
    # powersgd_dense marks the corrected-schema entries (round-5 ADVICE);
    # entries cached under the old rows_plus_cols formula recount
    missing = [m for m in models
               if m not in have or "powersgd_dense" not in have[m]]
    if missing:
        r = subprocess.run([sys.executable, "-c", _COUNT_SRC] + missing,
                           capture_output=True, text=True, timeout=1200)
        if r.returncode != 0:
            sys.stderr.write(r.stderr[-2000:])
            raise RuntimeError("param-count subprocess failed")
        have.update(json.loads(r.stdout.strip().splitlines()[-1]))
        with open(cache, "w") as f:
            json.dump(have, f, indent=1, sort_keys=True)
    return have


def wire_bytes(strategy: str, P: int, rows_plus_cols: int, n: int,
               powersgd_dense: int = 0) -> float:
    ring = 2.0 * (n - 1) / n
    if strategy == "allreduce":
        return ring * P * 4
    if strategy == "easgd":
        return ring * P * 4 / 4            # sync_freq default 4
    if strategy == "asgd":
        return ring * P * 4
    if strategy == "gosgd":
        return 0.25 * P * 4                # exch_prob default
    if strategy == "topk":
        return (n - 1) * 0.01 * P * 8      # ratio default, (idx,val)
    if strategy == "onebit":
        return ring * P / 8
    if strategy.startswith("powersgd"):
        r = int(strategy[len("powersgd"):] or 2)
        # low-rank factors for the compressible leaves + full fp32
        # allreduce for the leaves PowerSGD leaves dense
        return ring * (r * rows_plus_cols + powersgd_dense) * 4
    raise ValueError(strategy)


def bucket_payload_bytes(strategy: str, P: int, powersgd_dense: int) -> float:
    """What the bucket planner actually SLICES per strategy — the bucket
    count (and so the latency term) follows this, not the raw fp32
    gradient: the psum-family rules and onebit bucket the fp32 payload
    (onebit slices the error-fed fp32 vector before packing), topk
    buckets its packed (bf16 val + i16 offset = 4·k_c bytes) chunk rows
    (TopK.CHUNK=8192, ratio 1% — strategies.TopK._rows_per_bucket), and
    powersgd buckets only the dense remainder its low-rank factors skip."""
    if strategy == "topk":
        chunk, k_c = 8192, max(1, round(8192 * 0.01))
        return 4.0 * k_c * (P / chunk)
    if strategy.startswith("powersgd"):
        return powersgd_dense * 4.0
    return P * 4.0


def newest_matrix(paths: list) -> dict:
    """config -> result dict, newest round wins, degraded rows excluded —
    reusing the SAME convention implementations as the rest of the
    pipeline (merge_matrix._is_degraded, bench._matrix_round) so the
    prediction can't anchor to rows the merge hygiene considers voided."""
    from bench import _matrix_round
    from scripts.merge_matrix import _is_degraded
    rows: dict = {}
    for path in sorted(paths, key=_matrix_round):
        for line in open(path):
            try:
                row = json.loads(line)
            except ValueError:
                continue
            res = row.get("result")
            if not isinstance(res, dict) or _is_degraded(row):
                continue
            rows[row.get("config", "")] = res
    return rows


def main() -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = sys.argv[1:] or sorted(
        glob.glob(os.path.join(repo, "perf_matrix_*.jsonl")))
    measured = newest_matrix(paths)
    counts = _param_counts(sorted({c[3] for c in CONFIGS}))

    out = {"ici_bw_bytes_per_s": ICI_GBPS, "sensitivity_band": SENS,
           "method": "analytic wire-bytes / ICI-bw anchored to measured "
                     "1-chip t_step; see scripts/predict_scaling.py "
                     "docstring for formulas and bounds", "rows": []}
    hdr = (f"{'config':24} {'ips/chip':>9} {'t_step ms':>9} "
           + "".join(f"{'eff@' + str(n) + ' (no/full ovl)':>22}"
                     for n in CHIP_COUNTS))
    print(hdr, file=sys.stderr)
    for cfg, strat, b, model, batch in CONFIGS:
        res = measured.get(cfg)
        row = {"config": cfg, "strategy": strat, "model": model}
        if not res or "spc" in str(res.get("metric", "")):
            row["measured"] = None
            out["rows"].append(row)
            print(f"{cfg:24} {'--':>9}  (no healthy spc=1 TPU row yet)",
                  file=sys.stderr)
            continue
        ips = float(res["value"])
        t_step = batch / ips
        P = counts[model]["params"]
        rc = counts[model]["rows_plus_cols"]
        dense = counts[model].get("powersgd_dense", 0)
        row.update(measured_ips_per_chip=ips, t_step_s=round(t_step, 6),
                   params=P)
        # measured overlap evidence (BENCH_TRACE columns) when the r9
        # matrix rows exist — the prediction-vs-trace comparison per row
        for m_res, key in ((measured.get(cfg + "-trace") or res,
                            "measured_monolithic"),
                           (measured.get(cfg + "-bucket4m-trace"),
                            "measured_bucket4m")):
            if m_res and m_res.get("exposed_comm_secs") is not None:
                row[key] = {
                    "exposed_comm_secs": m_res["exposed_comm_secs"],
                    "overlap_ratio": m_res.get("overlap_ratio"),
                    "n_buckets": m_res.get("n_buckets")}
        cells = ""
        for n in CHIP_COUNTS:
            wb = wire_bytes(strat, P, rc, n, dense)
            t_comm = wb / ICI_GBPS
            no_ovl = t_step / (t_step + t_comm)
            full_ovl = t_step / max(t_step, t_comm)
            row[f"pred_{n}chip"] = {
                "t_comm_s": round(t_comm, 6),
                "eff_no_overlap": round(no_ovl, 4),
                "eff_full_overlap": round(full_ovl, 4),
                "eff_band_low": round(t_step / (t_step + wb / SENS[0]), 4),
                "eff_band_high": round(t_step / (t_step + wb / SENS[1]), 4),
                # the bucketed-pipeline refinement (docstring): where
                # between the bounds the schedule actually lands — the
                # planner slices this strategy's OWN bucketable payload
                # (bucket_payload_bytes), the wire ships its (possibly
                # compressed) bytes
                "monolithic": bucketed_exchange(
                    wb, bucket_payload_bytes(strat, P, dense), t_step, 0),
                "bucket4m": bucketed_exchange(
                    wb, bucket_payload_bytes(strat, P, dense), t_step,
                    DEFAULT_BUCKET_BYTES)}
            cells += f"{no_ovl:>11.3f}/{full_ovl:<10.3f}"
        out["rows"].append(row)
        print(f"{cfg:24} {ips:>9.0f} {t_step * 1e3:>9.2f} {cells}",
              file=sys.stderr)
    # pipeline-schedule rows (round 10): predicted bubble vs the measured
    # devprof columns of the r10 matrix rows — same predicted-vs-measured
    # join the r9 bucket rows get above
    out["pipeline_rows"] = []
    print(f"\n{'pipeline row':34} {'pred bubble':>11} {'meas ticks':>10} "
          f"{'meas time':>9} {'verified':>8}", file=sys.stderr)
    for label, pp, v, m in PIPELINE_CONFIGS:
        pred = pipeline_bubble(pp, v, m)
        prow = {"config": label, "predicted": pred, "measured": None}
        res = measured.get(label)
        if res and res.get("pipeline_bubble_ticks") is not None:
            prow["measured"] = {
                k: res.get(k)
                for k in ("pipeline_bubble_ticks", "pipeline_bubble_time",
                          "pipeline_schedule_verified", "bubble_fraction")}
            mt = res["pipeline_bubble_ticks"]
            pb = pred["bubble_fraction"]
            prow["rel_err_ticks"] = (round(abs(mt - pb) / pb, 4)
                                     if pb else None)
            print(f"{label:34} {pb:>11.4f} {mt:>10.4f} "
                  f"{res.get('pipeline_bubble_time') or float('nan'):>9.4f} "
                  f"{str(res.get('pipeline_schedule_verified')):>8}",
                  file=sys.stderr)
        else:
            print(f"{label:34} {pred['bubble_fraction']:>11.4f} "
                  f"{'--':>10}  (no measured r10 row yet)", file=sys.stderr)
        out["pipeline_rows"].append(prow)
    # update-plane-sharding rows (round 11): predicted per-chip update
    # -state bytes (replicated/N, model above) vs the measured devprof
    # columns of the r11 matrix rows — the control row prices the
    # replicated baseline, the ushard row the sharded layout
    out["update_state_rows"] = []
    print(f"\n{'update-sharding row':30} {'pred B/chip':>11} "
          f"{'meas B/chip':>11} {'shrink':>7} {'rel err':>8}",
          file=sys.stderr)
    for label, control, n in USHARD_CONFIGS:
        res, ctl = measured.get(label), measured.get(control)
        urow = {"config": label, "control": control, "n_workers": n,
                "measured": None}
        repl = (res or {}).get("update_state_bytes_replicated") \
            or (ctl or {}).get("update_state_bytes_replicated")
        if repl:
            urow["predicted_bytes_per_chip"] = int(
                update_state_bytes_per_chip(repl, n))
            urow["predicted_shrink"] = float(n)
        if res and res.get("update_state_bytes_per_chip") is not None:
            meas = res["update_state_bytes_per_chip"]
            urow["measured"] = {
                k: res.get(k)
                for k in ("update_state_bytes_per_chip",
                          "update_state_bytes_replicated",
                          "update_state_shrink")}
            if ctl and ctl.get("update_state_bytes_per_chip") is not None:
                urow["control_bytes_per_chip"] = \
                    ctl["update_state_bytes_per_chip"]
            if repl:
                pred = urow["predicted_bytes_per_chip"]
                urow["rel_err"] = (round(abs(meas - pred) / pred, 4)
                                   if pred else None)
                print(f"{label:30} {pred:>11} {meas:>11} "
                      f"{res.get('update_state_shrink') or 0:>7.2f} "
                      f"{urow['rel_err']:>8.4f}", file=sys.stderr)
        else:
            print(f"{label:30} "
                  f"{urow.get('predicted_bytes_per_chip', '--'):>11} "
                  f"{'--':>11}  (no measured r11 row yet)", file=sys.stderr)
        out["update_state_rows"].append(urow)
    # fused-compression rows (round 12): the analytic HBM-traffic model
    # (devprof.compress_traffic_model — the same model whose columns the
    # r12 rows carry, evaluated here at a nominal size: the legacy/fused
    # ratio is a ratio of linear-in-n terms, so it is size-invariant for
    # onebit/topk and shape-ratio-driven for powersgd) joined against the
    # measured fuse/control step-time pair.  The modeled shrink bounds the
    # kernel win; a measured speedup below it means the exchange was not
    # HBM-bound at this problem size, not that the kernels lost.
    # Imported lazily AND fail-soft: the r5 watcher rehearsal runs this
    # script from a bare scratch tree where the package is absent — the
    # compress join is additive reporting, never a reason to crash the
    # prediction chain.
    try:
        from theanompi_tpu.utils.devprof import compress_traffic_model
    except ImportError:
        compress_traffic_model = None
        print("\n(compress rows skipped: theanompi_tpu not importable)",
              file=sys.stderr)
    out["compress_rows"] = []
    if compress_traffic_model is not None:
        print(f"\n{'compress row':34} {'pred shrink':>11} {'pred dec':>8} "
              f"{'row shrink':>10} {'fuse/ctl':>9}", file=sys.stderr)
    for label, control, strat in COMPRESS_CONFIGS:
        if compress_traffic_model is None:
            break
        pred = compress_traffic_model(
            strat.rstrip("0123456789"), 1 << 22, 2,
            leaf_shapes=[(512, 256)] if strat.startswith("powersgd")
            else None)
        crow = {"config": label, "control": control, "strategy": strat,
                "predicted": {k: pred[k] for k in
                              ("compress_hbm_shrink",
                               "compress_decode_shrink")} if pred else None,
                "measured": None}
        res, ctl = measured.get(label), measured.get(control)
        rep = next((r for r in (res, ctl)
                    if r and r.get("compress_hbm_shrink") is not None), None)
        if rep:
            crow["measured"] = {
                k: rep.get(k)
                for k in ("compress_hbm_bytes_legacy",
                          "compress_hbm_bytes_fused", "compress_hbm_shrink",
                          "compress_decode_shrink")}
        if res and ctl and res.get("value") and ctl.get("value"):
            crow["step_speedup"] = round(res["value"] / ctl["value"], 3)
        if crow["measured"] is not None:
            ps = (pred or {}).get("compress_hbm_shrink") or 0
            print(f"{label:34} {ps:>11.3f} "
                  f"{(pred or {}).get('compress_decode_shrink') or 0:>8.3f} "
                  f"{crow['measured']['compress_hbm_shrink'] or 0:>10.3f} "
                  f"{crow.get('step_speedup') or float('nan'):>9.3f}",
                  file=sys.stderr)
        else:
            print(f"{label:34} "
                  f"{(pred or {}).get('compress_hbm_shrink', '--'):>11} "
                  f"{'--':>8}  (no measured r12 pair yet)", file=sys.stderr)
        out["compress_rows"].append(crow)
    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
