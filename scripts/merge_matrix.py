#!/usr/bin/env python
"""Dedup/merge perf-matrix JSONL artifacts (round-3 verdict, weak #7).

A matrix pass interrupted by a tunnel wedge leaves null rows that a later
re-run supersedes; nothing previously merged those recovered rows back, so
the half-empty table risked becoming "the number".  This tool rewrites one
canonical file: for each config keep the LAST non-null result (or a single
null if none succeeded), preserving first-seen config order.

    python scripts/merge_matrix.py out.jsonl [more.jsonl ...]

With several inputs, later files win ties and the FIRST file is rewritten.
"""

import json
import sys


def merge(paths: list[str]) -> None:
    order: list[str] = []
    best: dict[str, dict] = {}
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                    cfg = row["config"]
                except (ValueError, KeyError, TypeError):
                    # a pass killed mid-append leaves a truncated line; drop
                    # it rather than disabling the canonical merge forever
                    print(f"merge_matrix: dropping malformed line in {path}:"
                          f" {line[:80]}", file=sys.stderr)
                    continue
                if cfg not in best:
                    order.append(cfg)
                    best[cfg] = row
                elif row.get("result") is not None or \
                        best[cfg].get("result") is None:
                    best[cfg] = row
    with open(paths[0], "w") as f:
        for cfg in order:
            f.write(json.dumps(best[cfg]) + "\n")


if __name__ == "__main__":
    if len(sys.argv) < 2:
        sys.exit(__doc__)
    merge(sys.argv[1:])
