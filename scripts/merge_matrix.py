#!/usr/bin/env python
"""Dedup/merge perf-matrix JSONL artifacts (round-3 verdict, weak #7).

A matrix pass interrupted by a tunnel wedge leaves null rows that a later
re-run supersedes; nothing previously merged those recovered rows back, so
the half-empty table risked becoming "the number".  This tool rewrites one
canonical file: for each config keep the LAST non-null result (or a single
null if none succeeded), preserving first-seen config order.

    python scripts/merge_matrix.py out.jsonl [more.jsonl ...]

With several inputs, later files win ties and the FIRST file is rewritten.

Degraded-window hygiene (round-4 verdict #8): a row whose result or note
carries a "degraded" marker (the manual voiding convention — see
perf_matrix_r4.jsonl's alexnet-b128 row and BASELINE.md's round-4 hardware
section) never beats a healthy non-null row for the same config, so the
stale number can't be quoted from the canonical artifact by accident.  A
VOIDING TOMBSTONE (result null + degraded note, optionally with
``"voided_value": N``) outranks untagged rows carrying the voided value —
merging an old backup that still holds the original untagged reading
cannot resurrect it — while a genuine healthy re-measure (a different
reading) supersedes the tombstone.

Stale hygiene (round 7): bench.py's wedge fallback tags re-emitted
last-good numbers ``stale: true`` — they rank below any fresh measurement
(but above tombstones/degraded rows), so a wedged round's fallback can
never shadow a later genuine re-measure.

Column tolerance (round 12): rows grow columns over rounds
(``compile_secs``/``cache`` in r8, tails in r9, the trace-derived
``overlap_ratio``/``exposed_comm_secs``/``device_*`` columns with
BENCH_TRACE).  The merge compares rows ONLY on the contract fields it
names (``config``, ``result.value``, ``ts``, the degraded/stale
markers); every access is ``get``-based and every numeric comparison is
fenced, so a column absent from (or unparseable in) one side is
UNKNOWN — it can neither KeyError the merge nor demote a row.
"""

import json
import sys


def _as_float(v):
    """Numeric view of one row field, or None when absent/unparseable —
    the unknown-compares-as-unknown rule."""
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def _is_degraded(row: dict) -> bool:
    """A row voided (or tagged) for coming from a degraded tunnel window.
    Convention: the word 'degraded' in the row's note or in the result's
    metric string.  Shared with bench.py's _last_good and
    predict_scaling.py — keep the convention in THIS one place.
    Defensive against foreign rows whose result isn't a dict."""
    res = row.get("result")
    blob = str(row.get("note", "")) + str(
        res.get("metric", "") if isinstance(res, dict) else "")
    return "degraded" in blob.lower()


def _is_stale(row: dict) -> bool:
    """A STALE-last-good row: bench.py's wedge fallback re-emitting an
    older healthy reading (``stale: true`` in the result, set by _fail;
    the metric-string marker covers hand-merged pre-tag artifacts).  An
    honest number, but never fresher than a real measurement."""
    res = row.get("result")
    if not isinstance(res, dict):
        return False
    return bool(res.get("stale")) or \
        "stale last-good" in str(res.get("metric", "")).lower()


def _rank(row: dict, voided: dict, cfg: str) -> int:
    """fresh healthy non-null (4) > stale last-good non-null (3) >
    voiding tombstone (2) > degraded non-null (1) > plain null (0).
    The tombstone outranks degraded readings so a merged-in old backup
    still holding the original untagged value can't resurrect it; a
    non-null row whose value matches the config's tombstoned reading is
    classified degraded even when untagged — UNLESS the row carries a
    ``ts`` newer than the tombstone's (a genuine healthy re-measure can
    coincide with the voided reading; round-5 ADVICE), and the demotion
    is always logged so it is never silent."""
    res = row.get("result")
    if res is None:
        return 2 if _is_degraded(row) else 0
    if not isinstance(res, dict):
        return 0          # foreign/hand-edited row — never canonical
    if _is_degraded(row):
        return 1
    tomb = voided.get(cfg)
    val = _as_float(res.get("value"))
    tomb_val = _as_float(tomb["value"]) if tomb is not None else None
    if val is not None and tomb_val is not None and \
            abs(val - tomb_val) < 1e-6:
        ts = _as_float(row.get("ts"))
        tomb_ts = _as_float(tomb.get("ts"))
        if ts is not None and tomb_ts is not None and ts > tomb_ts:
            # re-measured after the voiding — trust it; but a STALE
            # fallback is ts-stamped at re-EMISSION time, so it passes
            # this check while still carrying the voided old reading —
            # it must stay below fresh measurements
            return 3 if _is_stale(row) else 4
        print(f"merge_matrix: {cfg} non-null value {val} matches the "
              f"tombstoned voided_value — demoting to degraded (a genuine "
              f"re-measure should carry a 'ts' newer than the tombstone's)",
              file=sys.stderr)
        return 1
    return 3 if _is_stale(row) else 4


def merge(paths: list[str]) -> None:
    order: list[str] = []
    best: dict[str, dict] = {}
    # config -> {"value": tombstoned reading, "ts": tombstone timestamp}
    voided: dict[str, dict] = {}
    for path in paths:              # first sweep: collect tombstones
        with open(path) as f:
            for line in f:
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if isinstance(row, dict) and _is_degraded(row) and \
                        row.get("voided_value") is not None:
                    cfg = row.get("config", "")
                    new = {"value": row["voided_value"],
                           "ts": row.get("ts")}
                    old = voided.get(cfg)
                    # the NEWEST tombstone governs (a stamped one beats an
                    # unstamped one): last-file-wins here would let an old
                    # backup's earlier tombstone re-open the ts window and
                    # resurrect the very reading the newer tombstone voids
                    new_ts = _as_float(new["ts"])
                    old_ts = _as_float(old.get("ts")) if old else None
                    if old is None or old_ts is None or \
                            (new_ts is not None and new_ts >= old_ts):
                        voided[cfg] = new
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                    cfg = row["config"]
                except (ValueError, KeyError, TypeError):
                    # a pass killed mid-append leaves a truncated line; drop
                    # it rather than disabling the canonical merge forever
                    print(f"merge_matrix: dropping malformed line in {path}:"
                          f" {line[:80]}", file=sys.stderr)
                    continue
                if cfg not in best:
                    order.append(cfg)
                    best[cfg] = row
                    continue
                # within a rank class the LAST row wins (newest re-measure)
                if _rank(row, voided, cfg) >= _rank(best[cfg], voided, cfg):
                    best[cfg] = row
    # a degraded or stale survivor (no fresh sibling anywhere) is flagged
    # so nothing downstream quotes it silently
    for cfg, row in best.items():
        r = _rank(row, voided, cfg) if row.get("result") is not None else None
        if r == 1:
            print(f"merge_matrix: {cfg} only has a DEGRADED-window "
                  "reading — do not quote; re-measure in a healthy "
                  "window", file=sys.stderr)
        elif r == 3:
            print(f"merge_matrix: {cfg} only has a STALE last-good "
                  "reading — re-measure when the tunnel answers",
                  file=sys.stderr)
    with open(paths[0], "w") as f:
        for cfg in order:
            f.write(json.dumps(best[cfg]) + "\n")


if __name__ == "__main__":
    if len(sys.argv) < 2:
        sys.exit(__doc__)
    merge(sys.argv[1:])
