#!/usr/bin/env bash
# Round-11 perf matrix — the update-plane-sharding round (ISSUE 17
# tentpole): TransformerLM on pure data meshes at N∈{2,4}, replicated
# control vs leaf-wise sharded update plane (BENCH_USHARD,
# parallel/update_sharding.py).  Every row carries the update-plane
# memory report (devprof.USHARD_ROW_COLUMNS: update_state_bytes_per_chip
# / _replicated / update_state_shrink — controls via
# BENCH_USHARD_REPORT=1, shrink ~1.0) so the headline per-chip ~N×
# shrink is read row-vs-row at fixed model/batch/N:
#   jq -r 'select(.result) | [.config, .result.update_state_bytes_per_chip,
#          .result.update_state_shrink, .result.value] | @tsv'
# and scripts/predict_scaling.py --json joins the measured column against
# its replicated/N model per row (out["update_state_rows"]).
#
# Same discipline as perf_matrix_r10.sh (the PR 3 prewarm machinery):
#   1. prewarm: every staged r11 row's program — the ushard rows' AOT
#      keys carry the `ushard` stamp (utils/compile_cache.key_extra) —
#      compiles into the executable store BEFORE the window.
#   2. canary: the replicated n2 control must report `cache: hit`, or
#      the pass aborts loudly instead of burning the window compiling.
#   3. the scans: rows from scripts/rows.py --round r11 (the manifest
#      prewarm consumed); rows already measured in the out-file skip.
#   ./scripts/perf_matrix_r11.sh [out_file]
set -u -o pipefail
OUT="${1:-perf_matrix_r11.jsonl}"
cd "$(dirname "$0")/.."
. scripts/_bench_row.sh

CACHE="${BENCH_COMPILE_CACHE:-/tmp/jax_bench_cache}"
LM_CFG='{"d_model":256,"n_head":8,"n_layer":4,"seq_len":128,"vocab":8192,"synthetic_train":64,"n_workers":2}'

# 1. prewarm (idempotent: cached rows skip in ~ms); live backend venue
# first, topology venue fallback when the tunnel can't answer
echo "== prewarm -> $CACHE" >&2
timeout -s KILL 3000 python -u scripts/prewarm_cache.py --rows r11 \
    --cache "$CACHE" --platform tpu >&2 \
  || timeout -s KILL 3000 python -u scripts/prewarm_cache.py --rows r11 \
    --cache "$CACHE" --platform topology:v5e:2x2x1 >&2 \
  || echo "== prewarm failed (rows will compile on the clock)" >&2

# 2. canary: the replicated n2 control program must hit the executable
# cache — a miss means the key composition (n_workers mesh shaping, the
# conditional `ushard` stamp in key_extra) drifted from what prewarm
# stored
echo "== canary: transformer_lm-b8-n2 must report cache: hit" >&2
canary=$(env BENCH_SKIP_PROBE="${BENCH_SKIP_PROBE:-1}" \
             BENCH_MODEL=transformer_lm BENCH_BATCH=8 \
             BENCH_CFG="$LM_CFG" \
             BENCH_USHARD_REPORT=1 \
             BENCH_ITERS=5 \
             BENCH_COMPILE_CACHE="$CACHE" python bench.py 2>>"${OUT%.jsonl}.err" | tail -1)
echo "$canary" | python -c '
import json, sys
row = json.loads(sys.stdin.read())
cache = row.get("cache")
assert cache == "hit", (
    f"canary row is cache: {cache!r}, not \"hit\" — the update-sharding "
    f"program key does not match what prewarm stored (row: {row}); "
    f"aborting before the staged rows burn the window on compiles")
print("== canary hit (compile %ss)" % (row.get("compile_secs"),),
      file=sys.stderr)
' || exit 1
echo "{\"config\": \"transformer_lm-b8-n2-canary\", \"result\": $canary}" >> "$OUT"

# 3. the staged rows (replicated control + ushard, at N=2 and N=4)
while read -r line; do
  eval "run $line"
done < <(python scripts/rows.py --round r11 --sh)

python scripts/merge_matrix.py "$OUT"
cat "$OUT"

# 4. closing gate: fresh rows within BENCH_REGRESS_PCT (default 10%) of
# each label's best fresh committed reading — the window self-judges
python scripts/bench_regress.py "$OUT" \
    --threshold "${BENCH_REGRESS_PCT:-10}" \
    --json "${OUT%.jsonl}_regress.json" \
  || { echo "== bench_regress: throughput regression gate FAILED" >&2; exit 7; }
