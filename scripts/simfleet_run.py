#!/usr/bin/env python
"""Rehearse a production-width fault schedule in virtual time.

The two-command recipe (README, docs/design.md §18):

    # 1,000 workers, 10,000 local steps each, seeded kills + wedges +
    # stragglers + net windows (drop/dup/partition/...), invariants
    # checked, byte-identical event log per seed — seconds of CPU
    python scripts/simfleet_run.py --workers 1000 --steps 10000 \\
        --seed 7 --n-faults 20 --net-n-faults 8 --stragglers 20 \\
        --realized-out /tmp/sim/sim_realized.jsonl

    # replay the realized schedule through the LIVE harness (real
    # processes, real ChaosMonkey/ChaosProxy) at small scale
    python scripts/chaos_run.py --workers 4 --steps 40 \\
        --faults-from /tmp/sim/sim_realized.jsonl --record-dir /tmp/live

Modes:

* default — one simulated run: summary, invariant verdicts, log hash.
  rc 0 only if every invariant holds.
* ``--gate`` — the tier-1 determinism gate: same seed twice must hash
  byte-identical (and differ for seed+1), then a 512-worker invariant
  suite must pass inside ``--budget`` CPU-seconds.
* ``--fidelity DIR`` — the cross-check: simulate a 4-worker schedule,
  export its realized faults, replay through the live elastic runtime,
  and require the same membership-event sequence (needs jax; minutes).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from theanompi_tpu.simfleet import FleetSim, check_invariants  # noqa: E402
from theanompi_tpu.utils import chaos  # noqa: E402


def build_fleet(args, seed=None, workers=None) -> FleetSim:
    sched = chaos.parse_schedule(args.faults) if args.faults else None
    net = chaos.parse_schedule(args.net_faults) if args.net_faults else None
    return FleetSim(
        n_workers=workers if workers is not None else args.workers,
        steps=args.steps, sync_freq=args.sync_freq,
        seed=seed if seed is not None else args.seed,
        n_shards=args.shards, schedule=sched, net_schedule=net,
        n_faults=args.n_faults, net_n_faults=args.net_n_faults,
        n_stragglers=args.stragglers,
        fault_t_min=args.t_min, fault_t_max=args.t_max,
        fleetmon=args.fleetmon)


def report(fleet, cpu_s) -> bool:
    s = fleet.summary
    print(f"simfleet: {s['n_workers']} workers, seed {s['seed']} — "
          f"{s['virtual_secs']}s virtual in {cpu_s:.1f}s CPU "
          f"({s['events']} events)")
    print(f"  finished={s['finished']} failed={s['failed']} "
          f"deaths={s['deaths']} transitions={s['transitions']} "
          f"mesh_regens={s['mesh_regens']}")
    print(f"  center: applies/shard={s['center']['applied_per_shard']} "
          f"dedup_hits={sum(s['center']['dedup_hits_per_shard'])} "
          f"restarts={s['center']['restarts']}")
    print(f"  frames faulted: {s['frames_faulted'] or 'none'}")
    if s.get("fleetmon"):
        fm = s["fleetmon"]
        by = ", ".join(f"{k}×{v}" for k, v in fm["by_rule"].items()) \
            or "none"
        print(f"  fleetmon: {fm['alerts']} alert(s) over "
              f"{fm['evaluations']} evaluation(s) — {by}")
    ok_all = True
    for name, ok, detail in check_invariants(fleet):
        ok_all &= ok
        print(f"  [{'PASS' if ok else 'FAIL'}] {name}: {detail}")
    print(f"  event-log sha256: {fleet.log.sha256()}")
    return ok_all


def run_gate(args) -> int:
    """Tier-1: determinism + a 512-worker invariant suite on a budget."""
    t0 = time.process_time()
    pair = []
    for _ in range(2):
        f = FleetSim(n_workers=128, steps=1200, sync_freq=8,
                     seed=args.seed, n_faults=5, net_n_faults=4,
                     n_stragglers=4)
        f.run()
        pair.append(f.log.sha256())
    if pair[0] != pair[1]:
        print(f"GATE FAIL: same seed, different event logs "
              f"({pair[0][:16]} != {pair[1][:16]})")
        return 1
    f3 = FleetSim(n_workers=128, steps=1200, sync_freq=8,
                  seed=args.seed + 1, n_faults=5, net_n_faults=4,
                  n_stragglers=4)
    f3.run()
    if f3.log.sha256() == pair[0]:
        print("GATE FAIL: different seeds produced identical logs "
              "(the schedule is not actually seeded)")
        return 1
    print(f"determinism: same seed ⇒ identical log ({pair[0][:16]}…), "
          f"seed+1 differs")
    fleet = FleetSim(n_workers=512, steps=2000, sync_freq=16,
                     seed=args.seed, n_faults=10, net_n_faults=6,
                     n_stragglers=10, fault_t_min=8.0, fault_t_max=60.0)
    fleet.run()
    ok = report(fleet, time.process_time() - t0)
    cpu = time.process_time() - t0
    if cpu > args.budget:
        print(f"GATE FAIL: {cpu:.1f}s CPU exceeds the "
              f"{args.budget:.0f}s budget")
        return 1
    print(f"simfleet gate: {'PASS' if ok else 'FAIL'} "
          f"({cpu:.1f}s CPU of {args.budget:.0f}s budget)")
    return 0 if ok else 1


def run_fidelity(args) -> int:
    from theanompi_tpu.simfleet.fidelity import crosscheck
    out = crosscheck(args.fidelity, n_workers=4,
                     schedule=args.faults or "kill@6:1",
                     steps=args.steps if args.steps <= 200 else 40,
                     seed=args.seed)
    print(f"sim membership sequences:  {out['sim']}")
    print(f"live membership sequences: {out['live']}")
    print(f"live rc={out['live_rc']}  realized={out['realized_path']}")
    print(f"fidelity cross-check: {'PASS' if out['ok'] else 'FAIL'}")
    return 0 if out["ok"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workers", type=int, default=1000)
    ap.add_argument("--steps", type=int, default=10000,
                    help="local steps per worker (rounds)")
    ap.add_argument("--sync-freq", type=int, default=25,
                    help="local steps per exchange round")
    # seed 2's seeded draws cover every fault kind at the default counts
    # (kills, wedges, delays, and all five net window kinds incl.
    # partitions) — the acceptance run exercises the whole taxonomy
    ap.add_argument("--seed", type=int, default=2)
    ap.add_argument("--shards", type=int, default=2,
                    help="center shards (ROADMAP 4b load-balance probe)")
    ap.add_argument("--faults", default=None,
                    help="explicit process schedule "
                         "(chaos grammar: kill@20:3,stop@30:5:20,...)")
    ap.add_argument("--net-faults", default=None,
                    help="explicit wire schedule "
                         "(net_dup@8:-1:6,net_partition@45:-1:3,...)")
    ap.add_argument("--n-faults", type=int, default=20,
                    help="seeded process faults when --faults absent")
    ap.add_argument("--net-n-faults", type=int, default=8,
                    help="seeded net windows when --net-faults absent")
    ap.add_argument("--stragglers", type=int, default=20,
                    help="persistent stragglers (4x step time)")
    ap.add_argument("--fleetmon", action="store_true",
                    help="rehearse the §20 health plane: the REAL "
                         "FleetCollector + SLO rule engine on the "
                         "virtual clock; alerts join the event log")
    ap.add_argument("--t-min", type=float, default=10.0)
    ap.add_argument("--t-max", type=float, default=150.0)
    ap.add_argument("--log-out", default=None,
                    help="write the canonical event log (jsonl)")
    ap.add_argument("--realized-out", default=None,
                    help="write the realized fault schedule (replayable "
                         "via chaos_run.py --faults-from)")
    ap.add_argument("--gate", action="store_true",
                    help="tier-1 determinism + 512-worker invariant gate")
    ap.add_argument("--budget", type=float, default=120.0,
                    help="--gate CPU-seconds budget")
    ap.add_argument("--fidelity", default=None, metavar="DIR",
                    help="run the live fidelity cross-check into DIR")
    args = ap.parse_args(argv)

    if args.gate:
        return run_gate(args)
    if args.fidelity:
        return run_fidelity(args)

    t0 = time.process_time()
    fleet = build_fleet(args)
    fleet.run()
    ok = report(fleet, time.process_time() - t0)
    if args.log_out:
        fleet.log.write(args.log_out)
        print(f"event log -> {args.log_out}")
    if args.realized_out:
        from theanompi_tpu.simfleet.fidelity import export_realized
        os.makedirs(os.path.dirname(args.realized_out) or ".",
                    exist_ok=True)
        export_realized(fleet.realized, args.realized_out)
        print(f"realized schedule -> {args.realized_out}")
        if args.workers <= 8:
            print(f"replay live:  python scripts/chaos_run.py "
                  f"--workers {args.workers} --steps 40 "
                  f"--faults-from {args.realized_out} "
                  f"--record-dir <dir>")
        else:
            # a live replay only makes sense at live width — faults
            # targeting workers a 4-process run doesn't have would drop
            print("to replay live, export from a sim at the live width "
                  f"(--workers 4), or run the automated cross-check: "
                  f"python scripts/simfleet_run.py --fidelity <dir>")
    return 0 if ok else 3


if __name__ == "__main__":
    raise SystemExit(main())
