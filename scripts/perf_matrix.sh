#!/usr/bin/env bash
# Round-3 perf A/B matrix on the live TPU chip.  Writes one JSON line per
# config to perf_matrix.jsonl — the data behind BASELINE.md's MFU table.
#   ./scripts/perf_matrix.sh [out_file]
set -u -o pipefail
OUT="${1:-perf_matrix.jsonl}"
cd "$(dirname "$0")/.."
: > "$OUT"
. scripts/_bench_row.sh

# staged configs at reference batch sizes (the comparison that counts)
run alexnet-b128            BENCH_MODEL=alexnet
run alexnet-b128-spc4       BENCH_MODEL=alexnet  BENCH_SPC=4
run googlenet-b32           BENCH_MODEL=googlenet
run googlenet-b32-spc8      BENCH_MODEL=googlenet BENCH_SPC=8 BENCH_SYNTH_BATCHES=8
run vgg16-b32               BENCH_MODEL=vgg16
run vgg16-b32-spc4          BENCH_MODEL=vgg16    BENCH_SPC=4
run resnet50-b32            BENCH_MODEL=resnet50
run resnet50-b32-spc8       BENCH_MODEL=resnet50 BENCH_SPC=8 BENCH_SYNTH_BATCHES=8
run resnet50-b32-spc8-bnbf16 BENCH_MODEL=resnet50 BENCH_SPC=8 BENCH_SYNTH_BATCHES=8 BENCH_BN_DTYPE=bfloat16
run resnet50-b32-bnbf16     BENCH_MODEL=resnet50 BENCH_BN_DTYPE=bfloat16
run cifar10-b128            BENCH_MODEL=cifar10

# batch-size headroom (MFU context, not the headline)
run resnet50-b64            BENCH_MODEL=resnet50 BENCH_BATCH=64
run resnet50-b128           BENCH_MODEL=resnet50 BENCH_BATCH=128
run googlenet-b128          BENCH_MODEL=googlenet BENCH_BATCH=128

# compressed-wire staged config #5 at VGG-16 scale (chunked top-k + onebit)
run vgg16-b32-topk          BENCH_MODEL=vgg16 BENCH_STRATEGY=topk
run vgg16-b32-onebit        BENCH_MODEL=vgg16 BENCH_STRATEGY=onebit

# transformer family (beyond-parity; value = sequences/sec/chip)
run transformer_lm-b16      BENCH_MODEL=transformer_lm BENCH_BATCH=16 BENCH_CFG="$LM_CFG"
run moe_lm-b16              BENCH_MODEL=moe_lm         BENCH_BATCH=16 BENCH_CFG="$LM_CFG"

cat "$OUT"
