#!/usr/bin/env bash
# tpulint pre-commit entry point: lint exactly the STAGED content of the
# staged .py files.
#
#   ln -s ../../scripts/precommit_lint.sh .git/hooks/pre-commit
#
# The staged BLOBS are checked out of the index into a temp tree and
# linted there (`--root`), so the verdict matches what the commit will
# contain even when the worktree has further unstaged edits — while the
# repo's baseline and .tpulint_cache/ are passed through, so a
# re-commit of unchanged staged content is a cache hit.  File SELECTION
# belongs to `--diff CACHED` (round 19): lint.py asks git for the
# staged-vs-HEAD .py delta itself and applies the ONE lint-scope filter
# (core.DEFAULT_PATHS), so the hook and CI share one changed-file code
# path and scope definition — this script checks out every staged .py
# blob and deliberately does NOT re-implement the filter (a second copy
# could drift and silently drop files from the verdict).  Exit codes
# follow scripts/lint.py: 0 clean, 1 findings, 2 usage.
#
# TPULINT_SARIF=<path>: additionally write a SARIF 2.1.0 log of the NEW
# findings to <path> (CI PR-diff annotation).  The extra invocation
# shares the repo's result cache, so it costs one warm-cache hit.
set -u
cd "$(dirname "$0")/.."
repo="$PWD"

staged=()
while IFS= read -r f; do
    staged+=("$f")
done < <(git diff --cached --name-only --diff-filter=d -- '*.py')

if [ ${#staged[@]} -eq 0 ]; then
    echo "precommit-lint: no staged python files"
    exit 0
fi

tmp="$(mktemp -d "${TMPDIR:-/tmp}/tpulint-precommit.XXXXXX")"
trap 'rm -rf "$tmp"' EXIT
git checkout-index --prefix="$tmp/" -- "${staged[@]}" || exit 2

python scripts/lint.py --root "$tmp" \
    --baseline "$repo/tpulint_baseline.json" \
    --cache-dir "$repo/.tpulint_cache" \
    --diff CACHED
rc=$?

if [ -n "${TPULINT_SARIF:-}" ]; then
    python scripts/lint.py --root "$tmp" \
        --baseline "$repo/tpulint_baseline.json" \
        --cache-dir "$repo/.tpulint_cache" \
        --diff CACHED --format sarif > "$TPULINT_SARIF" \
        || echo "precommit-lint: SARIF emit failed (verdict above stands)" >&2
fi

exit "$rc"
