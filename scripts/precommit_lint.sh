#!/usr/bin/env bash
# tpulint pre-commit entry point: lint exactly the STAGED content of the
# staged .py files.
#
#   ln -s ../../scripts/precommit_lint.sh .git/hooks/pre-commit
#
# Staged paths are filtered to the repo lint scope (theanompi_tpu/,
# scripts/, tests/, bench.py — the same roots the tier-1 gate walks); a
# commit touching nothing in scope lints nothing and exits 0.  The
# staged BLOBS are checked out of the index into a temp tree and linted
# there (`--root`), so the verdict matches what the commit will contain
# even when the worktree has further unstaged edits — while the repo's
# baseline and .tpulint_cache/ are passed through, so a re-commit of
# unchanged staged content is a cache hit.  Exit codes follow
# scripts/lint.py: 0 clean, 1 findings, 2 usage.
set -u
cd "$(dirname "$0")/.."
repo="$PWD"

staged=()
while IFS= read -r f; do
    case "$f" in
        theanompi_tpu/*.py|scripts/*.py|tests/*.py|bench.py)
            staged+=("$f")
            ;;
    esac
done < <(git diff --cached --name-only --diff-filter=ACMR -- '*.py')

if [ ${#staged[@]} -eq 0 ]; then
    echo "precommit-lint: no staged python files in lint scope"
    exit 0
fi

tmp="$(mktemp -d "${TMPDIR:-/tmp}/tpulint-precommit.XXXXXX")"
trap 'rm -rf "$tmp"' EXIT
git checkout-index --prefix="$tmp/" -- "${staged[@]}" || exit 2

python scripts/lint.py --root "$tmp" \
    --baseline "$repo/tpulint_baseline.json" \
    --cache-dir "$repo/.tpulint_cache" \
    "${staged[@]}"
