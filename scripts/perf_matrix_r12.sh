#!/usr/bin/env bash
# Round-12 perf matrix — the fused-compression round (ISSUE 18 tentpole):
# TransformerLM on a 2-worker data mesh, one `fuse` row (Pallas
# single-pass compression kernels, BENCH_FUSE=1) against a control row
# (jnp oracle path, BENCH_FUSE=0 → THEANOMPI_TPU_NO_PALLAS=1) per
# compression strategy (onebit / topk / powersgd2).  Wire bits are
# identical in both modes (ops/compress.py oracle pairing, docs/design.md
# §24); the step-time delta is the kernels' HBM-traffic win.  Every
# compression row also carries the modeled traffic columns
# (devprof.COMPRESS_ROW_COLUMNS: compress_hbm_bytes_legacy / _fused /
# compress_hbm_shrink / compress_decode_shrink):
#   jq -r 'select(.result) | [.config, .result.compress_hbm_shrink,
#          .result.compress_decode_shrink, .result.value] | @tsv'
# and scripts/predict_scaling.py --json joins the measured fuse/control
# pairs against the model (out["compress_rows"]).
#
# Same discipline as perf_matrix_r11.sh (the PR 3 prewarm machinery):
#   1. prewarm: every staged r12 row's program — the control rows' AOT
#      keys carry the `no_pallas` stamp (utils/compile_cache.key_extra) —
#      compiles into the executable store BEFORE the window.
#   2. canary: the onebit control row must report `cache: hit`, or the
#      pass aborts loudly instead of burning the window compiling.
#   3. the scans: rows from scripts/rows.py --round r12 (the manifest
#      prewarm consumed); rows already measured in the out-file skip.
#   ./scripts/perf_matrix_r12.sh [out_file]
set -u -o pipefail
OUT="${1:-perf_matrix_r12.jsonl}"
cd "$(dirname "$0")/.."
. scripts/_bench_row.sh

CACHE="${BENCH_COMPILE_CACHE:-/tmp/jax_bench_cache}"
LM_CFG='{"d_model":256,"n_head":8,"n_layer":4,"seq_len":128,"vocab":8192,"synthetic_train":64,"n_workers":2}'

# 1. prewarm (idempotent: cached rows skip in ~ms); live backend venue
# first, topology venue fallback when the tunnel can't answer
echo "== prewarm -> $CACHE" >&2
timeout -s KILL 3000 python -u scripts/prewarm_cache.py --rows r12 \
    --cache "$CACHE" --platform tpu >&2 \
  || timeout -s KILL 3000 python -u scripts/prewarm_cache.py --rows r12 \
    --cache "$CACHE" --platform topology:v5e:2x2x1 >&2 \
  || echo "== prewarm failed (rows will compile on the clock)" >&2

# 2. canary: the onebit CONTROL program must hit the executable cache —
# a miss means the key composition (the conditional `no_pallas` stamp in
# key_extra, applied through bench_row_config's shared BENCH_FUSE=0
# handling) drifted from what prewarm stored
echo "== canary: transformer_lm-b8-onebit-n2 must report cache: hit" >&2
canary=$(env BENCH_SKIP_PROBE="${BENCH_SKIP_PROBE:-1}" \
             BENCH_MODEL=transformer_lm BENCH_BATCH=8 \
             BENCH_STRATEGY=onebit BENCH_FUSE=0 \
             BENCH_CFG="$LM_CFG" \
             BENCH_ITERS=5 \
             BENCH_COMPILE_CACHE="$CACHE" python bench.py 2>>"${OUT%.jsonl}.err" | tail -1)
echo "$canary" | python -c '
import json, sys
row = json.loads(sys.stdin.read())
cache = row.get("cache")
assert cache == "hit", (
    f"canary row is cache: {cache!r}, not \"hit\" — the forced-oracle "
    f"program key does not match what prewarm stored (row: {row}); "
    f"aborting before the staged rows burn the window on compiles")
print("== canary hit (compile %ss)" % (row.get("compile_secs"),),
      file=sys.stderr)
' || exit 1
echo "{\"config\": \"transformer_lm-b8-onebit-n2-canary\", \"result\": $canary}" >> "$OUT"

# 3. the staged rows (fuse + control per compression strategy)
while read -r line; do
  eval "run $line"
done < <(python scripts/rows.py --round r12 --sh)

python scripts/merge_matrix.py "$OUT"
cat "$OUT"

# 4. closing gate: fresh rows within BENCH_REGRESS_PCT (default 10%) of
# each label's best fresh committed reading — the window self-judges
python scripts/bench_regress.py "$OUT" \
    --threshold "${BENCH_REGRESS_PCT:-10}" \
    --json "${OUT%.jsonl}_regress.json" \
  || { echo "== bench_regress: throughput regression gate FAILED" >&2; exit 7; }
