#!/usr/bin/env python
"""The matrix-row manifest: ONE definition of every staged bench config.

Before round 8, each ``perf_matrix_r*.sh`` embedded its row definitions as
inline env assignments and ``forensics/prewarm_cache.py`` carried its own
parallel CONFIGS list — two hand-synced copies of (model, batch, rule, spc,
flags).  A drift between them silently forfeits the executable-cache hit
the prewarm exists to guarantee (the program key is content-addressed: a
shape that merely LOOKS the same misses).  This module is the single
source both sides consume:

* ``scripts/perf_matrix_r8.sh`` (and later rounds) iterate
  ``python scripts/rows.py --round 8 --sh`` — one ``label ENV=V ...`` line
  per row, fed straight to ``_bench_row.sh``'s ``run``;
* ``scripts/prewarm_cache.py`` builds each row's program through
  ``bench.bench_row_config(row.env)`` — the SAME env→config assembly the
  bench inner uses — and compiles it into the executable cache.

Row labels follow the ``_cfg_matches`` conventions in bench.py
(model[-bN][-rule][-strategy][-spcK][-realdata][-winload][-...]) so
``last_good`` fallbacks and resume-skip logic keep working unchanged.
"""

from __future__ import annotations

import argparse
import shlex
import sys
from typing import Dict, List, NamedTuple, Tuple


class Row(NamedTuple):
    label: str
    env: Dict[str, str]          # BENCH_* settings that shape the row
    rounds: Tuple[str, ...]      # matrix rounds / groups this row belongs to


def _r(label: str, rounds: str, **env) -> Row:
    return Row(label, {k: str(v) for k, v in env.items()},
               tuple(rounds.split()))


# "heavy" = the wedge-correlated long compiles (26–270 s each measured in
# round 5, forensics/prewarm_cache.py docstring) — the prewarm default: what
# a short hardware window cannot afford to compile on the clock.
ROWS: List[Row] = [
    # -- round-8 canary + acceptance rows (executable-cache proof) --------
    _r("cifar10-b128-spc4", "r8 heavy", BENCH_MODEL="cifar10", BENCH_SPC=4),
    _r("alexnet-b128-spc4", "r8 heavy", BENCH_MODEL="alexnet", BENCH_SPC=4),
    _r("alexnet-b128", "r8 heavy", BENCH_MODEL="alexnet"),
    _r("vgg16-b32", "r8 heavy", BENCH_MODEL="vgg16"),
    _r("resnet50-b32", "r8 heavy", BENCH_MODEL="resnet50"),
    _r("googlenet-b32", "r8 heavy", BENCH_MODEL="googlenet"),
    _r("cifar10-b128", "r8 heavy", BENCH_MODEL="cifar10"),
    # -- batch-headroom + dtype-lever rows (round-5 staging) -------------
    _r("alexnet-b256-spc4", "heavy", BENCH_MODEL="alexnet", BENCH_BATCH=256,
       BENCH_SPC=4),
    _r("alexnet-b256", "heavy", BENCH_MODEL="alexnet", BENCH_BATCH=256),
    _r("resnet50-b32-bnbf16", "heavy", BENCH_MODEL="resnet50",
       BENCH_BN_DTYPE="bfloat16"),
    _r("resnet50-b64", "heavy", BENCH_MODEL="resnet50", BENCH_BATCH=64),
    _r("resnet50-b128", "heavy", BENCH_MODEL="resnet50", BENCH_BATCH=128),
    _r("resnet50-b128-bnbf16", "heavy", BENCH_MODEL="resnet50",
       BENCH_BATCH=128, BENCH_BN_DTYPE="bfloat16"),
    _r("resnet50-b128-spc4", "heavy", BENCH_MODEL="resnet50",
       BENCH_BATCH=128, BENCH_SPC=4),
    _r("googlenet-b128", "heavy", BENCH_MODEL="googlenet", BENCH_BATCH=128),
    _r("googlenet-b128-spc4", "heavy", BENCH_MODEL="googlenet",
       BENCH_BATCH=128, BENCH_SPC=4),
    _r("vgg16-b64", "heavy", BENCH_MODEL="vgg16", BENCH_BATCH=64),
    _r("vgg16-b32-spc4", "heavy", BENCH_MODEL="vgg16", BENCH_SPC=4),
    # -- spc8 scan bodies: the biggest programs per model (round 5/6) ----
    _r("alexnet-b128-spc8", "heavy", BENCH_MODEL="alexnet", BENCH_SPC=8,
       BENCH_SYNTH_BATCHES=8),
    _r("googlenet-b32-spc8", "heavy", BENCH_MODEL="googlenet", BENCH_SPC=8,
       BENCH_SYNTH_BATCHES=8),
    _r("resnet50-b32-spc8", "heavy", BENCH_MODEL="resnet50", BENCH_SPC=8,
       BENCH_SYNTH_BATCHES=8),
    _r("resnet50-b32-spc8-bnbf16", "heavy", BENCH_MODEL="resnet50",
       BENCH_SPC=8, BENCH_SYNTH_BATCHES=8, BENCH_BN_DTYPE="bfloat16"),
    # -- round-6 fused-cadence rows --------------------------------------
    _r("alexnet-b128-easgd-spc8", "r8 heavy", BENCH_MODEL="alexnet",
       BENCH_RULE="easgd", BENCH_SPC=8, BENCH_SYNTH_BATCHES=8),
    _r("vgg16-b32-easgd-spc8", "r8 heavy", BENCH_MODEL="vgg16",
       BENCH_RULE="easgd", BENCH_SPC=8, BENCH_SYNTH_BATCHES=8),
    _r("alexnet-b128-gosgd-spc8", "heavy", BENCH_MODEL="alexnet",
       BENCH_RULE="gosgd", BENCH_SPC=8, BENCH_SYNTH_BATCHES=8),
    # -- round-7 window-staging rows (same programs as their plain-spc
    #    siblings — the executable cache dedups them by content) ---------
    _r("cifar10-b128-spc4-winload", "r7", BENCH_MODEL="cifar10",
       BENCH_SPC=4, BENCH_WINLOAD=1),
    _r("alexnet-b128-spc4-winload", "r7 r8", BENCH_MODEL="alexnet",
       BENCH_SPC=4, BENCH_WINLOAD=1),
    _r("vgg16-b32-easgd-spc8-winload", "r7 r8", BENCH_MODEL="vgg16",
       BENCH_RULE="easgd", BENCH_SPC=8, BENCH_WINLOAD=1,
       BENCH_SYNTH_BATCHES=8),
    _r("alexnet-b128-realdata-spc4-winload", "r7 r8", BENCH_MODEL="alexnet",
       BENCH_SPC=4, BENCH_REAL_DATA=1, BENCH_WINLOAD=1),
    # -- round-9 bucketed-overlap rows (ISSUE 13): every row captures a
    #    BENCH_TRACE window so overlap_ratio / exposed_comm_secs land in
    #    the row JSON, bucketed and monolithic-control alike — the
    #    acceptance comparison is read straight off the BENCH_TRACE
    #    columns at fixed model/rule/spc ----------------------------------
    _r("alexnet-b128-trace", "r9 heavy", BENCH_MODEL="alexnet",
       BENCH_TRACE=1),                           # monolithic BSP control
    _r("alexnet-b128-bucket4m-trace", "r9 heavy", BENCH_MODEL="alexnet",
       BENCH_BUCKET_BYTES=4194304, BENCH_TRACE=1),
    _r("vgg16-b32-onebit-trace", "r9 heavy", BENCH_MODEL="vgg16",
       BENCH_STRATEGY="onebit", BENCH_TRACE=1),  # compressed-wire control
    _r("vgg16-b32-onebit-bucket4m-trace", "r9 heavy", BENCH_MODEL="vgg16",
       BENCH_STRATEGY="onebit", BENCH_BUCKET_BYTES=4194304, BENCH_TRACE=1),
    _r("alexnet-b128-easgd-spc8-trace", "r9 heavy", BENCH_MODEL="alexnet",
       BENCH_RULE="easgd", BENCH_SPC=8, BENCH_SYNTH_BATCHES=8,
       BENCH_TRACE=1),                           # monolithic psum control
    _r("alexnet-b128-easgd-spc8-bucket4m-trace", "r9 heavy",
       BENCH_MODEL="alexnet", BENCH_RULE="easgd", BENCH_SPC=8,
       BENCH_SYNTH_BATCHES=8, BENCH_BUCKET_BYTES=4194304, BENCH_TRACE=1),
    # -- round-10 interleaved-pipeline rows (ISSUE 16): TransformerLM at
    #    depth on a pp=4 'pipe' mesh — fill/drain control vs v∈{2,4}
    #    interleaved virtual stages (pp_interleave), each row tracing so
    #    devprof's bubble_fraction lands in the row JSON next to
    #    predict_scaling's modeled bubble.  n_layer=16 divides pp·v for
    #    every staged v; M=8 microbatches (pp | M, the interleaved
    #    grouping requirement) ------------------------------------------
    _r("transformer_lm-b16-pp4-trace", "r10 heavy",
       BENCH_MODEL="transformer_lm", BENCH_BATCH=16, BENCH_TRACE=1,
       BENCH_CFG='{"d_model":512,"n_head":8,"n_layer":16,"seq_len":512,'
                 '"vocab":32768,"synthetic_train":512,"pp":4,'
                 '"pp_microbatches":8}'),       # fill/drain control
    _r("transformer_lm-b16-pp4-v2-trace", "r10 heavy",
       BENCH_MODEL="transformer_lm", BENCH_BATCH=16, BENCH_TRACE=1,
       BENCH_CFG='{"d_model":512,"n_head":8,"n_layer":16,"seq_len":512,'
                 '"vocab":32768,"synthetic_train":512,"pp":4,'
                 '"pp_microbatches":8,"pp_interleave":2}'),
    _r("transformer_lm-b16-pp4-v4-trace", "r10 heavy",
       BENCH_MODEL="transformer_lm", BENCH_BATCH=16, BENCH_TRACE=1,
       BENCH_CFG='{"d_model":512,"n_head":8,"n_layer":16,"seq_len":512,'
                 '"vocab":32768,"synthetic_train":512,"pp":4,'
                 '"pp_microbatches":8,"pp_interleave":4}'),
    # -- round-11 update-plane-sharding rows (ISSUE 17): TransformerLM on
    #    pure data meshes at N∈{2,4} — replicated control vs leaf-wise
    #    sharded update plane (BENCH_USHARD).  Every row carries the
    #    devprof.USHARD_ROW_COLUMNS memory report (controls via
    #    BENCH_USHARD_REPORT=1, shrink ~1.0) so the headline per-chip
    #    ~N× shrink is read row-vs-row at fixed model/batch/N, and
    #    scripts/predict_scaling.py --json joins the measured
    #    update_state_bytes_per_chip against its analytic model ---------
    _r("transformer_lm-b8-n2", "r11",
       BENCH_MODEL="transformer_lm", BENCH_BATCH=8, BENCH_USHARD_REPORT=1,
       BENCH_CFG='{"d_model":256,"n_head":8,"n_layer":4,"seq_len":128,'
                 '"vocab":8192,"synthetic_train":64,"n_workers":2}'),
    _r("transformer_lm-b8-n2-ushard", "r11",
       BENCH_MODEL="transformer_lm", BENCH_BATCH=8, BENCH_USHARD=1,
       BENCH_CFG='{"d_model":256,"n_head":8,"n_layer":4,"seq_len":128,'
                 '"vocab":8192,"synthetic_train":64,"n_workers":2}'),
    _r("transformer_lm-b8-n4", "r11",
       BENCH_MODEL="transformer_lm", BENCH_BATCH=8, BENCH_USHARD_REPORT=1,
       BENCH_CFG='{"d_model":256,"n_head":8,"n_layer":4,"seq_len":128,'
                 '"vocab":8192,"synthetic_train":64,"n_workers":4}'),
    _r("transformer_lm-b8-n4-ushard", "r11",
       BENCH_MODEL="transformer_lm", BENCH_BATCH=8, BENCH_USHARD=1,
       BENCH_CFG='{"d_model":256,"n_head":8,"n_layer":4,"seq_len":128,'
                 '"vocab":8192,"synthetic_train":64,"n_workers":4}'),
    # -- r12: fused compression kernels (ops/compress.py, ops/factor_pack.py,
    # docs/design.md §24).  Per compression strategy, a `fuse` row (Pallas
    # kernel pipeline, BENCH_FUSE=1) against a control row (jnp oracle path,
    # BENCH_FUSE=0 → THEANOMPI_TPU_NO_PALLAS=1) — identical wire bits, the
    # step-time delta is the kernels' HBM-traffic win.  On the CPU sim both
    # run the oracles (the rows pin wiring + the compress_traffic_report
    # columns); the A/B lands when the hardware window reopens.
    # scripts/predict_scaling.py joins these against the modeled shrink.
    _r("transformer_lm-b8-onebit-n2", "r12",
       BENCH_MODEL="transformer_lm", BENCH_BATCH=8, BENCH_STRATEGY="onebit",
       BENCH_FUSE=0,
       BENCH_CFG='{"d_model":256,"n_head":8,"n_layer":4,"seq_len":128,'
                 '"vocab":8192,"synthetic_train":64,"n_workers":2}'),
    _r("transformer_lm-b8-onebit-n2-fuse", "r12",
       BENCH_MODEL="transformer_lm", BENCH_BATCH=8, BENCH_STRATEGY="onebit",
       BENCH_FUSE=1,
       BENCH_CFG='{"d_model":256,"n_head":8,"n_layer":4,"seq_len":128,'
                 '"vocab":8192,"synthetic_train":64,"n_workers":2}'),
    _r("transformer_lm-b8-topk-n2", "r12",
       BENCH_MODEL="transformer_lm", BENCH_BATCH=8, BENCH_STRATEGY="topk",
       BENCH_FUSE=0,
       BENCH_CFG='{"d_model":256,"n_head":8,"n_layer":4,"seq_len":128,'
                 '"vocab":8192,"synthetic_train":64,"n_workers":2}'),
    _r("transformer_lm-b8-topk-n2-fuse", "r12",
       BENCH_MODEL="transformer_lm", BENCH_BATCH=8, BENCH_STRATEGY="topk",
       BENCH_FUSE=1,
       BENCH_CFG='{"d_model":256,"n_head":8,"n_layer":4,"seq_len":128,'
                 '"vocab":8192,"synthetic_train":64,"n_workers":2}'),
    _r("transformer_lm-b8-powersgd2-n2", "r12",
       BENCH_MODEL="transformer_lm", BENCH_BATCH=8,
       BENCH_STRATEGY="powersgd2", BENCH_FUSE=0,
       BENCH_CFG='{"d_model":256,"n_head":8,"n_layer":4,"seq_len":128,'
                 '"vocab":8192,"synthetic_train":64,"n_workers":2}'),
    _r("transformer_lm-b8-powersgd2-n2-fuse", "r12",
       BENCH_MODEL="transformer_lm", BENCH_BATCH=8,
       BENCH_STRATEGY="powersgd2", BENCH_FUSE=1,
       BENCH_CFG='{"d_model":256,"n_head":8,"n_layer":4,"seq_len":128,'
                 '"vocab":8192,"synthetic_train":64,"n_workers":2}'),
]


def rows(selector: str = "all") -> List[Row]:
    """Rows for a selector: ``all``, a group/round tag (``r8``, ``heavy``),
    or a comma-separated list of exact labels."""
    if selector == "all":
        return list(ROWS)
    by_label = {r.label: r for r in ROWS}
    if "," in selector or selector in by_label:
        out = []
        for lab in selector.split(","):
            if lab not in by_label:
                raise SystemExit(f"rows.py: unknown row label {lab!r}")
            out.append(by_label[lab])
        return out
    picked = [r for r in ROWS if selector in r.rounds]
    if not picked:
        raise SystemExit(f"rows.py: selector {selector!r} matches nothing "
                         f"(groups: {sorted(set(sum((list(r.rounds) for r in ROWS), [])))})")
    return picked


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--round", default="all", metavar="SEL",
                   help="group tag (r7/r8/heavy), 'all', or label[,label...]")
    p.add_argument("--sh", action="store_true",
                   help="emit one shell line per row: label ENV=V ... "
                        "(for `run` in scripts/_bench_row.sh)")
    p.add_argument("--labels", action="store_true",
                   help="emit labels only")
    args = p.parse_args(argv)
    for r in rows(args.round):
        if args.labels:
            print(r.label)
        elif args.sh:
            print(" ".join([shlex.quote(r.label)] +
                           [f"{k}={shlex.quote(v)}"
                            for k, v in sorted(r.env.items())]))
        else:
            print(f"{r.label:40s} {r.env}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
