#!/usr/bin/env python
"""Capture a TPU profiler trace of one model's training step and print the
top HLO ops by self time.

The reference's perf story was wall-clock section buckets (SURVEY.md §2.10);
on TPU the per-op breakdown comes from XLA's profiler.  This script is the
bottleneck-analysis harness behind BASELINE.md's MFU table.

Usage: python scripts/profile_model.py [model] [batch] [iters]
Env: PROFILE_DIR (default /tmp/tpu_profile)
"""

import glob
import gzip
import json
import os
import sys


def main():
    model_name = sys.argv[1] if len(sys.argv) > 1 else "resnet50"
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 0
    iters = int(sys.argv[3]) if len(sys.argv) > 3 else 10
    trace_dir = os.environ.get("PROFILE_DIR", f"/tmp/tpu_profile_{model_name}")

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import jax
    import jax.numpy as jnp
    import importlib
    from bench import MODELS
    from theanompi_tpu.parallel.exchanger import get_exchanger
    from theanompi_tpu.parallel.mesh import WORKER_AXIS, worker_mesh
    from theanompi_tpu.parallel import steps

    jax.config.update("jax_default_prng_impl", "rbg")
    mesh = worker_mesh()
    modelfile, modelclass, extra = MODELS[model_name]
    config = {"mesh": mesh, "size": mesh.shape[WORKER_AXIS], "rank": 0,
              "verbose": False, **extra}
    if batch:
        config["batch_size"] = batch
    model = getattr(importlib.import_module(modelfile), modelclass)(config)
    exchanger = get_exchanger("bsp", config)
    model.compile_iter_fns(exchanger)
    dev_batch = steps.put_batch(mesh, model.data.next_train_batch(0))
    lr = jnp.float32(model.current_lr)
    rng = jax.random.key(0)

    def step(i):
        model.step_state, cost, err = model.train_fn(
            model.step_state, dev_batch, lr, rng, jnp.int32(i))

    for i in range(5):
        step(i)
    jax.block_until_ready(model.step_state["params"])

    jax.profiler.start_trace(trace_dir)
    for i in range(iters):
        step(5 + i)
    jax.block_until_ready(model.step_state["params"])
    jax.profiler.stop_trace()

    xplanes = glob.glob(os.path.join(trace_dir, "plugins/profile/*/*.xplane.pb"))
    if not xplanes:
        print("no xplane capture found", file=sys.stderr)
        return 1
    xplane = max(xplanes, key=os.path.getmtime)

    from tensorboard_plugin_profile.convert import raw_to_tool_data as rtd
    data, _ = rtd.xspace_to_tool_data([xplane], "framework_op_stats", {})
    if isinstance(data, bytes):
        data = data.decode()
    table = json.loads(data)
    # framework_op_stats: [ {…gviz table…} ] — rows of per-op totals
    rows = []
    for t in table:
        cols = [c["label"] for c in t.get("cols", [])]
        if "Total self-time (us)" not in cols and "total_self_time" not in str(cols).lower():
            continue
        for r in t.get("rows", []):
            vals = [c.get("v") for c in r["c"]]
            rows.append(dict(zip(cols, vals)))
    if not rows:
        # fallback: dump whatever structure came back
        print(json.dumps(table)[:4000])
        return 0
    key = [c for c in rows[0] if "self-time" in c.lower() and "total" in c.lower()][0]
    rows.sort(key=lambda r: -(r.get(key) or 0))
    total = sum(r.get(key) or 0 for r in rows)
    print(f"== {model_name} batch {model.batch_size}: top ops by self time "
          f"({iters} steps, total {total/1e3:.1f} ms) ==")
    namecol = [c for c in rows[0] if c.lower() in ("operation", "op name", "type")]
    for r in rows[:25]:
        name = " | ".join(str(r.get(c)) for c in rows[0] if isinstance(r.get(c), str))
        print(f"{(r.get(key) or 0)/1e3:9.2f} ms  {100*(r.get(key) or 0)/max(total,1):5.1f}%  {name[:110]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
