#!/usr/bin/env python
"""Capture a profiler trace of one model's training step and print the
device-time attribution: top op classes, compute vs collective time,
EXPOSED collective time, and the comm/compute overlap ratio.

The reference's perf story was wall-clock section buckets (SURVEY.md
§2.10); the per-op breakdown comes from XLA's profiler.  The capture,
glob walk, and trace parse live in ``theanompi_tpu/utils/devprof.py``
(the shared, tested trace reader — this script used to do the walk
inline); this harness just builds the model, drives a traced window, and
formats the result.

Usage:
    python scripts/profile_model.py [model] [batch] [iters]
        [--rule bsp] [--spc K] [--json OUT]

``--json`` writes the machine-readable profile (the full devprof dict +
run metadata) so BASELINE.md's MFU/bottleneck table regenerates
mechanically instead of by scraping console output.

Env: PROFILE_DIR (trace capture dir, default /tmp/tpu_profile_<model>).
"""

import argparse
import json
import os
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("model", nargs="?", default="resnet50")
    ap.add_argument("batch", nargs="?", type=int, default=0)
    ap.add_argument("iters", nargs="?", type=int, default=10)
    ap.add_argument("--rule", default="bsp",
                    choices=["bsp", "easgd", "asgd", "gosgd"])
    ap.add_argument("--spc", type=int, default=1,
                    help="steps_per_call of the traced dispatch")
    ap.add_argument("--json", default=None, metavar="OUT",
                    help="write the machine-readable profile here "
                         "('-' for stdout)")
    ap.add_argument("--cfg", default=None, metavar="JSON",
                    help="JSON config overrides (bench.py BENCH_CFG "
                         "conventions — transformer dims, pp/tp/sp, "
                         "pp_interleave...); tp/pp/sp shape the mesh")
    ap.add_argument("--schedule", action="store_true",
                    help="print the per-lane schedule occupancy report "
                         "(devprof.schedule_occupancy) from the capture; "
                         "with pp>1 in --cfg, also the hop-event pipeline "
                         "schedule measurement")
    args = ap.parse_args(argv)
    model_name = args.model
    trace_dir = os.environ.get("PROFILE_DIR",
                               f"/tmp/tpu_profile_{model_name}")

    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    import jax
    import jax.numpy as jnp
    import importlib
    from theanompi_tpu.models.registry import MODELS
    from theanompi_tpu.parallel.exchanger import get_exchanger
    from theanompi_tpu.parallel.mesh import WORKER_AXIS, worker_mesh
    from theanompi_tpu.parallel import steps
    from theanompi_tpu.utils import devprof

    jax.config.update("jax_default_prng_impl", "rbg")
    overrides = json.loads(args.cfg) if args.cfg else {}
    mesh = worker_mesh(tp=int(overrides.get("tp", 1)),
                       pp=int(overrides.get("pp", 1)),
                       sp=int(overrides.get("sp", 1)))
    modelfile, modelclass, extra = MODELS[model_name]
    config = {"mesh": mesh, "size": mesh.shape[WORKER_AXIS], "rank": 0,
              "verbose": False, **extra, **overrides}
    if args.batch:
        config["batch_size"] = args.batch
    if args.spc > 1:
        config["steps_per_call"] = args.spc
    model = getattr(importlib.import_module(modelfile), modelclass)(config)
    exchanger = get_exchanger(args.rule, config)
    model.compile_iter_fns(exchanger)
    spc = int(config.get("steps_per_call", 1))
    if spc > 1:
        batches = [model.data.next_train_batch(j) for j in range(spc)]
        dev_batch = steps.put_batch_stack(mesh, batches, model.batch_spec())
    else:
        dev_batch = steps.put_batch(mesh, model.data.next_train_batch(0),
                                    model.batch_spec())
    lr = jnp.float32(model.current_lr)
    rng = jax.random.key(0)

    def step(i):
        # 1-based count strided by spc, exactly the worker/bench
        # convention: the fused in-scan exchange cadence fires at its true
        # rate (a 0-based count would run steps down to count0 < 0 and
        # fire a step-0 exchange no real run issues)
        with jax.profiler.TraceAnnotation(devprof.TRAIN_DISPATCH_SPAN):
            model.step_state, cost, err = model.train_fn(
                model.step_state, dev_batch, lr, rng,
                jnp.int32((i + 1) * spc))

    for i in range(5):
        step(i)
    jax.block_until_ready(model.step_state["params"])

    with devprof.capture(trace_dir) as cap:
        for i in range(args.iters):
            step(5 + i)
        jax.block_until_ready(model.step_state["params"])
    prof = cap.profile
    if prof is None:
        print(f"no trace capture found under {trace_dir}", file=sys.stderr)
        return 1

    print(f"== {model_name} batch {model.batch_size} {args.rule.upper()}"
          f"{f' spc={spc}' if spc > 1 else ''}: {args.iters} traced "
          f"dispatch(es) on {jax.devices()[0].platform} ==")
    print(devprof.format_profile(prof, top=25))
    if args.schedule:
        # per-lane tick-level occupancy (compute / hop / other-comm /
        # idle strips) — a schedule regression is diagnosable per lane,
        # not just a worse scalar
        events = devprof.load_dir_events(trace_dir)
        print()
        print(devprof.format_schedule(devprof.schedule_occupancy(events)))
        pp = int(config.get("pp", 1) or 1)
        if pp > 1:
            rep = devprof.pipeline_schedule_report(
                events, pp=pp,
                v=int(config.get("pp_interleave", 1) or 1),
                m=int(config.get("pp_microbatches", 1) or 1))
            print(f"pipeline schedule: ticks/pass={rep['ticks_per_pass']} "
                  f"measured_ticks={rep['measured_ticks']} "
                  f"verified={rep['schedule_verified']} "
                  f"bubble_ticks={rep['bubble_fraction_ticks']} "
                  f"bubble_time={rep['bubble_fraction']}")
    if args.json:
        doc = {"model": model_name, "batch_size": int(model.batch_size),
               "rule": args.rule, "spc": spc, "iters": args.iters,
               "platform": jax.devices()[0].platform,
               "device_kind": getattr(jax.devices()[0], "device_kind", "?"),
               "trace_dir": trace_dir, **prof}
        if args.json == "-":
            print(json.dumps(doc))
        else:
            with open(args.json, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
