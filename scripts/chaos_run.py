#!/usr/bin/env python
"""Chaos-mode training run: inject faults, verify the elastic reactions.

The two-command recipe (README):

    # elastic EASGD: 3 workers, SIGKILL worker 2 at t=20s — the run
    # absorbs the death (leave → backoff respawn → rejoin-from-center)
    python scripts/chaos_run.py --rule easgd --workers 3 --steps 120 \\
        --faults kill@20:2 --record-dir /tmp/chaos

    # then read the churn story (membership markers in the report/trace)
    python scripts/telemetry_report.py /tmp/chaos --trace /tmp/chaos.json

Modes (the rule reaction matrix, docs/design.md §14):

* ``--rule easgd|asgd`` — elastic membership: island workers around a
  center server under ``parallel/membership.py``'s supervisor.  Faults
  hit worker subprocesses; the run completes WITHOUT a world restart.
* ``--rule bsp`` — supervised world restart: ``launcher --supervise``
  under chaos; a SIGKILLed worker resumes from the last committed window
  cursor via the crash-atomic checkpoint.

Faults come from ``--faults`` (explicit ``kind@sec:worker[:dur]`` list)
or ``--seed``/``--n-faults`` (reproducible random draws over the non-zero
workers).  After the run the merged telemetry stream is audited: every
applied kill fault must have a matching ``worker_leave`` AND a
``worker_join`` rejoin (elastic mode); ``--verify-loss`` additionally
evaluates the final center on the model's validation set and gates on
``--loss-threshold`` — convergence-to-accuracy under injected faults,
the chaos acceptance gate.
"""

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def eval_center_loss(modelfile, modelclass, config, center_npz):
    """Validation cost of the persisted final center params — loads the
    model in-process, replaces its replicas with the center, runs the val
    loop.  The convergence half of the chaos gate."""
    import importlib

    import jax
    import numpy as np

    from theanompi_tpu.parallel import steps
    from theanompi_tpu.parallel.exchanger import Exchanger
    from theanompi_tpu.parallel.mesh import WORKER_AXIS
    from theanompi_tpu.utils.recorder import Recorder

    cfg = dict(config)
    cfg.setdefault("verbose", False)
    cls = getattr(importlib.import_module(modelfile), modelclass)
    model = cls(cfg)
    model.compile_iter_fns(Exchanger(cfg))
    with np.load(center_npz) as z:
        leaves = [z[f"leaf{i}"] for i in range(len(z.files))]
    params = jax.tree.unflatten(jax.tree.structure(model.params), leaves)
    params = jax.tree.map(lambda x, like: np.asarray(x, like.dtype),
                          params, jax.tree.map(np.asarray, model.params))
    n = model.mesh.shape[WORKER_AXIS]
    sp = model._state_specs
    model.step_state["params"] = steps.replicate_tree(
        params, n, model.mesh, None if sp is None else sp["params"])
    rec = Recorder({"verbose": False})
    model.begin_val()
    for _ in range(model.data.n_batch_val):
        model.val_iter(0, rec)
    model.end_val()
    return rec.print_val_info(0)["val_cost"]


def audit_membership(record_dir, kill_targets):
    """Match telemetry membership transitions against the injected kills:
    every killed worker needs a crash/wedge ``worker_leave`` and a respawn
    ``worker_join``.  Returns (ok, transitions)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import telemetry_report as tr
    events = tr.load_events(record_dir)
    trans = [e for e in events
             if e["ev"] in ("worker_join", "worker_leave", "worker_demote",
                            "fault_injected")]
    ok = True
    for w in sorted(set(kill_targets)):
        leaves = [e for e in trans if e["ev"] == "worker_leave"
                  and e.get("worker") == w
                  and e.get("reason") in ("crashed", "wedged",
                                          "lease_expired")]
        joins = [e for e in trans if e["ev"] == "worker_join"
                 and e.get("worker") == w and e.get("rejoin")]
        if not leaves:
            print(f"AUDIT FAIL: no crash worker_leave for killed worker {w}")
            ok = False
        if not joins:
            print(f"AUDIT FAIL: no rejoin worker_join for killed worker {w}")
            ok = False
    return ok, trans


def run_bsp_chaos(args, kv):
    """``launcher --supervise`` under chaos: SIGKILL the worker subprocess
    mid-epoch, assert the supervisor resumes it to completion."""
    from theanompi_tpu.utils import chaos

    cmd = [sys.executable, "-m", "theanompi_tpu.launcher",
           "--supervise", str(args.max_restarts), "--rule", "bsp",
           "--modelfile", args.modelfile, "--modelclass", args.modelclass,
           "--backoff", "0.2"] + kv
    sup = subprocess.Popen(cmd)
    schedule = chaos.parse_schedule(args.faults) if args.faults else \
        chaos.seeded_schedule(args.seed, [0], n_faults=args.n_faults,
                              t_min=args.t_min, t_max=args.t_max)

    def pid_of(_target):
        return chaos.find_child_pid(sup.pid, "theanompi_tpu.worker",
                                    timeout_s=0.2)

    monkey = chaos.ChaosMonkey(schedule, pid_of=pid_of)
    monkey.start()
    rc = sup.wait()
    monkey.stop()
    applied = [f for f in monkey.applied if f.error is None]
    print(f"bsp chaos: rc={rc}, faults applied: {applied}")
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rule", default="easgd",
                    choices=["easgd", "asgd", "bsp"])
    ap.add_argument("--modelfile", default="tests.conftest")
    ap.add_argument("--modelclass", default="TinyModel")
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--steps", type=int, default=120,
                    help="local steps per elastic worker before clean exit")
    ap.add_argument("--faults", default=None,
                    help="explicit schedule: kind@sec:worker[:dur],...")
    ap.add_argument("--seed", type=int, default=7,
                    help="seeded random faults when --faults is not given")
    ap.add_argument("--n-faults", type=int, default=1)
    ap.add_argument("--t-min", type=float, default=10.0)
    ap.add_argument("--t-max", type=float, default=30.0)
    ap.add_argument("--record-dir", required=True)
    ap.add_argument("--host-devices", type=int, default=1,
                    help="simulated chips per worker (CPU venue)")
    ap.add_argument("--sync-freq", type=int, default=2)
    ap.add_argument("--max-restarts", type=int, default=4)
    ap.add_argument("--lease-timeout", type=float, default=20.0)
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--verify-loss", action="store_true",
                    help="evaluate the final center on the val set")
    ap.add_argument("--loss-threshold", type=float, default=None,
                    help="chaos gate: final center val cost must be below")
    ap.add_argument("config", nargs="*", help="key=value model config")
    args = ap.parse_args(argv)

    os.makedirs(args.record_dir, exist_ok=True)
    if args.rule == "bsp":
        return run_bsp_chaos(args, args.config)

    from theanompi_tpu.parallel.membership import parse_kv, run_elastic
    from theanompi_tpu.utils import chaos

    schedule = chaos.parse_schedule(args.faults) if args.faults else \
        chaos.seeded_schedule(args.seed,
                              list(range(1, args.workers + 1)),
                              n_faults=args.n_faults, t_min=args.t_min,
                              t_max=args.t_max)
    print(f"chaos schedule: {schedule}")
    config = parse_kv(args.config)
    config.setdefault("sync_freq", args.sync_freq)
    t0 = time.time()
    rc = run_elastic(
        args.rule, args.modelfile, args.modelclass, config, args.workers,
        record_dir=args.record_dir, steps=args.steps,
        host_devices=args.host_devices, chaos_schedule=schedule,
        timeout_s=args.timeout,
        supervisor_kw={"max_restarts": args.max_restarts,
                       "lease_timeout": args.lease_timeout})
    print(f"elastic run rc={rc} in {time.time() - t0:.1f}s")
    if rc != 0:
        return rc

    kills = [f.target for f in schedule
             if f.kind == "kill" and f.applied and f.error is None]
    if not kills:
        print("warning: no kill fault landed on a live worker — nothing "
              "to audit (workers finished before the schedule fired?)")
    ok, trans = audit_membership(args.record_dir, kills)
    for e in trans:
        print(f"  {e['ev']} worker={e.get('worker')} "
              f"reason={e.get('reason') or e.get('kind')}")
    if not ok:
        return 4
    if args.verify_loss or args.loss_threshold is not None:
        center = os.path.join(args.record_dir, "center_final.npz")
        loss = eval_center_loss(args.modelfile, args.modelclass,
                                config, center)
        print(f"final center val cost: {loss:.4f}")
        with open(os.path.join(args.record_dir, "chaos_gate.json"),
                  "w") as f:
            json.dump({"val_cost": loss, "kills": kills,
                       "threshold": args.loss_threshold}, f)
        if args.loss_threshold is not None and \
                not loss < args.loss_threshold:
            print(f"CHAOS GATE FAIL: {loss:.4f} >= {args.loss_threshold}")
            return 5
    print("chaos gate: PASS")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
