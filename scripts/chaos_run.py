#!/usr/bin/env python
"""Chaos-mode training run: inject faults, verify the elastic reactions.

The two-command recipe (README):

    # elastic EASGD: 3 workers, SIGKILL worker 2 at t=20s — the run
    # absorbs the death (leave → backoff respawn → rejoin-from-center)
    python scripts/chaos_run.py --rule easgd --workers 3 --steps 120 \\
        --faults kill@20:2 --record-dir /tmp/chaos

    # then read the churn story (membership markers in the report/trace)
    python scripts/telemetry_report.py /tmp/chaos --trace /tmp/chaos.json

Modes (the rule reaction matrix, docs/design.md §14):

* ``--rule easgd|asgd`` — elastic membership: island workers around a
  center server under ``parallel/membership.py``'s supervisor.  Faults
  hit worker subprocesses; the run completes WITHOUT a world restart.
* ``--rule bsp`` — supervised world restart: ``launcher --supervise``
  under chaos; a SIGKILLed worker resumes from the last committed window
  cursor via the crash-atomic checkpoint.

Faults come from ``--faults`` (explicit ``kind@sec:worker[:dur]`` list)
or ``--seed``/``--n-faults`` (reproducible random draws over the non-zero
workers).  After the run the merged telemetry stream is audited: every
applied kill fault must have a matching ``worker_leave`` AND a
``worker_join`` rejoin (elastic mode); ``--verify-loss`` additionally
evaluates the final center on the model's validation set and gates on
``--loss-threshold`` — convergence-to-accuracy under injected faults,
the chaos acceptance gate.
"""

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def eval_center_loss(modelfile, modelclass, config, center_npz):
    """Validation cost of the persisted final center params — loads the
    model in-process, replaces its replicas with the center, runs the val
    loop.  The convergence half of the chaos gate."""
    import importlib

    import jax
    import numpy as np

    from theanompi_tpu.parallel import steps
    from theanompi_tpu.parallel.exchanger import Exchanger
    from theanompi_tpu.parallel.mesh import WORKER_AXIS
    from theanompi_tpu.utils.recorder import Recorder

    cfg = dict(config)
    cfg.setdefault("verbose", False)
    cls = getattr(importlib.import_module(modelfile), modelclass)
    model = cls(cfg)
    model.compile_iter_fns(Exchanger(cfg))
    with np.load(center_npz) as z:
        leaves = [z[f"leaf{i}"] for i in range(len(z.files))]
    params = jax.tree.unflatten(jax.tree.structure(model.params), leaves)
    params = jax.tree.map(lambda x, like: np.asarray(x, like.dtype),
                          params, jax.tree.map(np.asarray, model.params))
    n = model.mesh.shape[WORKER_AXIS]
    sp = model._state_specs
    model.step_state["params"] = steps.replicate_tree(
        params, n, model.mesh, None if sp is None else sp["params"])
    rec = Recorder({"verbose": False})
    model.begin_val()
    for _ in range(model.data.n_batch_val):
        model.val_iter(0, rec)
    model.end_val()
    return rec.print_val_info(0)["val_cost"]


def audit_membership(record_dir, kill_targets):
    """Match telemetry membership transitions against the injected kills:
    every killed worker needs a crash/wedge ``worker_leave`` and a respawn
    ``worker_join``.  Returns (ok, transitions)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import telemetry_report as tr
    events = tr.load_events(record_dir)
    trans = [e for e in events
             if e["ev"] in ("worker_join", "worker_leave", "worker_demote",
                            "center_down", "center_restored",
                            "fault_injected")]
    ok = True
    for w in sorted(set(kill_targets)):
        leaves = [e for e in trans if e["ev"] == "worker_leave"
                  and e.get("worker") == w
                  and e.get("reason") in ("crashed", "wedged",
                                          "lease_expired")]
        joins = [e for e in trans if e["ev"] == "worker_join"
                 and e.get("worker") == w and e.get("rejoin")]
        if not leaves:
            print(f"AUDIT FAIL: no crash worker_leave for killed worker {w}")
            ok = False
        if not joins:
            print(f"AUDIT FAIL: no rejoin worker_join for killed worker {w}")
            ok = False
    return ok, trans


def audit_center(record_dir, n_center_kills, require_dedup):
    """The round-14 half of the gate: every center SIGKILL must have its
    ``center_down`` → ``center_restored`` pair (and the run must END
    restored, not down); when duplicate frames were injected, the center's
    dedup window must have actually deduplicated (counter > 0) and its
    applied-once bookkeeping must balance.  Returns (ok, stats)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import telemetry_report as tr
    events = tr.load_events(record_dir)
    downs = [e for e in events if e["ev"] == "center_down"]
    restores = [e for e in events if e["ev"] == "center_restored"]
    ok = True
    if n_center_kills:
        if len(downs) < n_center_kills:
            print(f"AUDIT FAIL: {n_center_kills} center kills but only "
                  f"{len(downs)} center_down events")
            ok = False
        if len(restores) < n_center_kills:
            print(f"AUDIT FAIL: {n_center_kills} center kills but only "
                  f"{len(restores)} center_restored events")
            ok = False
        if downs and (not restores
                      or restores[-1]["ts"] < downs[-1]["ts"]):
            print("AUDIT FAIL: the run ended center_down (no "
                  "center_restored after the last outage)")
            ok = False
    stats = None
    stats_path = os.path.join(record_dir, "center_stats.json")
    if os.path.exists(stats_path):
        with open(stats_path) as f:
            stats = json.load(f)
    if stats is not None:
        applied = sum(int(v) for v in stats.get("by_island", {}).values())
        if applied != int(stats.get("n_updates", -1)):
            print(f"AUDIT FAIL: applied-once bookkeeping off — n_updates="
                  f"{stats.get('n_updates')} != Σ by_island = {applied}")
            ok = False
    if require_dedup:
        hits = (stats or {}).get("dedup_hits", 0)
        faulted = (stats or {}).get("net_frames_faulted")
        if not stats:
            print("AUDIT FAIL: duplicate frames injected but no "
                  "center_stats.json to prove deduplication")
            ok = False
        elif faulted is not None and faulted.get("net_dup", 0) == 0:
            # the window opened but no frame crossed it (workers still
            # booting, schedule mistimed) — nothing to dedup, not a bug
            print("warning: net_dup window(s) opened but no frame passed "
                  "through them — dedup gate vacuous this run")
        elif hits <= 0:
            print("AUDIT FAIL: duplicate frames injected but the center's "
                  "dedup window recorded 0 hits — duplicates were "
                  "re-applied or never arrived")
            ok = False
        else:
            print(f"dedup audit: {hits} duplicate(s) deduplicated, "
                  f"applied-once bookkeeping balanced")
    return ok, stats


def run_bsp_chaos(args, kv):
    """``launcher --supervise`` under chaos: SIGKILL the worker subprocess
    mid-epoch, assert the supervisor resumes it to completion."""
    from theanompi_tpu.utils import chaos

    cmd = [sys.executable, "-m", "theanompi_tpu.launcher",
           "--supervise", str(args.max_restarts), "--rule", "bsp",
           "--modelfile", args.modelfile, "--modelclass", args.modelclass,
           "--backoff", "0.2"] + kv
    sup = subprocess.Popen(cmd)
    schedule = chaos.parse_schedule(args.faults) if args.faults else \
        chaos.seeded_schedule(args.seed, [0], n_faults=args.n_faults,
                              t_min=args.t_min, t_max=args.t_max)

    def pid_of(_target):
        return chaos.find_child_pid(sup.pid, "theanompi_tpu.worker",
                                    timeout_s=0.2)

    monkey = chaos.ChaosMonkey(schedule, pid_of=pid_of)
    monkey.start()
    rc = sup.wait()
    monkey.stop()
    applied = [f for f in monkey.applied if f.error is None]
    print(f"bsp chaos: rc={rc}, faults applied: {applied}")
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rule", default="easgd",
                    choices=["easgd", "asgd", "bsp"])
    ap.add_argument("--modelfile", default="tests.conftest")
    ap.add_argument("--modelclass", default="TinyModel")
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--steps", type=int, default=120,
                    help="local steps per elastic worker before clean exit")
    ap.add_argument("--faults", default=None,
                    help="explicit schedule: kind@sec:worker[:dur],... "
                         "(worker 0 = the center — implies --center-proc)")
    ap.add_argument("--faults-from", default=None,
                    help="replay a REALIZED schedule file "
                         "(chaos_realized.jsonl from a previous live run, "
                         "or sim_realized.jsonl from scripts/"
                         "simfleet_run.py) — process faults go to the "
                         "monkey, net_* windows to the proxy")
    ap.add_argument("--seed", type=int, default=7,
                    help="seeded random faults when --faults is not given")
    ap.add_argument("--n-faults", type=int, default=1)
    ap.add_argument("--t-min", type=float, default=10.0)
    ap.add_argument("--t-max", type=float, default=30.0)
    ap.add_argument("--center-proc", action="store_true",
                    help="run the center as its own supervised process "
                         "(snapshots + respawn; auto-on when a fault "
                         "targets worker 0)")
    ap.add_argument("--net-faults", default=None,
                    help="wire-level schedule through the ChaosProxy: "
                         "net_dup@5:-1:6,net_partition@12:-1:3,... "
                         "(target -1 = every client)")
    ap.add_argument("--net-seed", type=int, default=None,
                    help="seeded net-fault windows when --net-faults is "
                         "not given")
    ap.add_argument("--net-n-faults", type=int, default=3)
    ap.add_argument("--net-duration", type=float, default=3.0)
    ap.add_argument("--fleetmon", action="store_true",
                    help="run the fleet-health collector (utils/fleetmon)"
                         " for this run and close with the alert-audit: "
                         "every landed fault whose symptom a rule covers "
                         "must raise its alert within one evaluation "
                         "window")
    ap.add_argument("--record-dir", required=True)
    ap.add_argument("--host-devices", type=int, default=1,
                    help="simulated chips per worker (CPU venue)")
    ap.add_argument("--sync-freq", type=int, default=2)
    ap.add_argument("--max-restarts", type=int, default=4)
    ap.add_argument("--lease-timeout", type=float, default=20.0)
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--verify-loss", action="store_true",
                    help="evaluate the final center on the val set")
    ap.add_argument("--loss-threshold", type=float, default=None,
                    help="chaos gate: final center val cost must be below")
    ap.add_argument("config", nargs="*", help="key=value model config")
    args = ap.parse_args(argv)

    os.makedirs(args.record_dir, exist_ok=True)
    if args.rule == "bsp":
        return run_bsp_chaos(args, args.config)

    from theanompi_tpu.parallel.membership import parse_kv, run_elastic
    from theanompi_tpu.utils import chaos

    replayed = chaos.schedule_from_realized(args.faults_from) \
        if args.faults_from else None
    if replayed is not None:
        # a realized file from a WIDER fleet (a 1,000-worker rehearsal)
        # targets workers this replay doesn't have — dropping them
        # silently would report a fault-free run as a faithful replay
        wide = [f for f in replayed
                if f.target > args.workers]
        if wide:
            print(f"warning: dropping {len(wide)}/{len(replayed)} "
                  f"realized fault(s) targeting workers beyond "
                  f"--workers {args.workers} (e.g. {wide[0]!r}) — "
                  f"export the replay schedule from a sim run at the "
                  f"live width (simfleet_run.py --workers "
                  f"{args.workers}), or use --fidelity")
            replayed = [f for f in replayed if f.target <= args.workers]
        if not replayed:
            print("error: nothing left to replay from "
                  f"{args.faults_from}")
            return 2
        # one realized file carries both planes; split by kind
        schedule = [f for f in replayed
                    if f.kind not in chaos.NET_FAULT_KINDS]
    elif args.faults:
        schedule = chaos.parse_schedule(args.faults)
    else:
        schedule = chaos.seeded_schedule(
            args.seed, list(range(1, args.workers + 1)),
            n_faults=args.n_faults, t_min=args.t_min, t_max=args.t_max)
    net_schedule = None
    if replayed is not None:
        net_schedule = [f for f in replayed
                        if f.kind in chaos.NET_FAULT_KINDS] or None
    elif args.net_faults:
        net_schedule = chaos.parse_schedule(args.net_faults)
    elif args.net_seed is not None:
        net_schedule = chaos.seeded_schedule(
            args.net_seed, [-1], n_faults=args.net_n_faults,
            t_min=args.t_min, t_max=args.t_max,
            kinds=chaos.NET_FAULT_KINDS, duration=args.net_duration)
    center_proc = args.center_proc or \
        any(f.target == 0 for f in schedule)
    print(f"chaos schedule: {schedule}"
          + (f"\nnet schedule:   {net_schedule}" if net_schedule else "")
          + (f"\ncenter: supervised subprocess (snapshots + respawn)"
             if center_proc else ""))
    config = parse_kv(args.config)
    config.setdefault("sync_freq", args.sync_freq)
    if args.fleetmon:
        config["fleetmon"] = True
        # the wedge rule must out-wait healthy silence but fire inside a
        # stop fault — half the lease timeout mirrors the live default
        config.setdefault("fleetmon_heartbeat_s", args.lease_timeout / 2.0)
    t0 = time.time()
    rc = run_elastic(
        args.rule, args.modelfile, args.modelclass, config, args.workers,
        record_dir=args.record_dir, steps=args.steps,
        host_devices=args.host_devices, chaos_schedule=schedule,
        net_chaos_schedule=net_schedule, center_proc=center_proc,
        timeout_s=args.timeout,
        supervisor_kw={"max_restarts": args.max_restarts,
                       "lease_timeout": args.lease_timeout})
    print(f"elastic run rc={rc} in {time.time() - t0:.1f}s")
    if rc != 0:
        return rc

    landed = [f for f in schedule
              if f.kind == "kill" and f.applied and f.error is None]
    kills = [f.target for f in landed if f.target != 0]
    center_kills = [f for f in landed if f.target == 0]
    if not landed:
        print("warning: no kill fault landed on a live worker — nothing "
              "to audit (workers finished before the schedule fired?)")
    ok, trans = audit_membership(args.record_dir, kills)
    for e in trans:
        print(f"  {e['ev']} worker={e.get('worker')} "
              f"reason={e.get('reason') or e.get('kind')}")
    dup_injected = bool(net_schedule) and \
        any(f.kind == "net_dup" and f.applied for f in net_schedule)
    center_ok, _stats = audit_center(args.record_dir, len(center_kills),
                                     require_dedup=dup_injected)
    ok = ok and center_ok
    if args.fleetmon:
        # the §20 alert-audit: match every landed fault whose symptom a
        # rule covers to its alert, from the realized log + the alert
        # events the collector streamed into this run's telemetry
        from theanompi_tpu.utils import fleetmon
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import telemetry_report as tr
        events = tr.load_events(args.record_dir)
        alert_events = [e for e in events
                        if e["ev"] == fleetmon.ALERT_EVENT]
        realized = []
        realized_path = os.path.join(args.record_dir,
                                     "chaos_realized.jsonl")
        if os.path.exists(realized_path):
            with open(realized_path) as f:
                for line in f:
                    try:
                        realized.append(json.loads(line))
                    except ValueError:
                        continue
        rules = fleetmon.default_rules(
            heartbeat_s=float(config["fleetmon_heartbeat_s"]))
        alert_ok, lines = fleetmon.audit_alerts(
            alert_events, realized, rules,
            eval_window_s=float(config.get("fleetmon_eval_s", 2.0)))
        for line in lines:
            print(line)
        if not alert_ok:
            print("ALERT AUDIT FAIL: a covered fault raised no alert "
                  "within its window")
            ok = False
        else:
            print(f"alert audit: PASS ({len(alert_events)} alert(s) "
                  f"fired)")
    if not ok:
        return 4
    if args.verify_loss or args.loss_threshold is not None:
        center = os.path.join(args.record_dir, "center_final.npz")
        loss = eval_center_loss(args.modelfile, args.modelclass,
                                config, center)
        print(f"final center val cost: {loss:.4f}")
        with open(os.path.join(args.record_dir, "chaos_gate.json"),
                  "w") as f:
            json.dump({"val_cost": loss, "kills": kills,
                       "threshold": args.loss_threshold}, f)
        if args.loss_threshold is not None and \
                not loss < args.loss_threshold:
            print(f"CHAOS GATE FAIL: {loss:.4f} >= {args.loss_threshold}")
            return 5
    print("chaos gate: PASS")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
