# Shared helper for the perf-matrix scripts (source, don't execute):
# run <label> [ENV=V ...] — one bench.py row appended to $OUT as JSON,
# stderr kept in ${OUT%.jsonl}.err.  LM_CFG is the transformer-family
# benchmark shape.
run() {
  local label="$1"; shift
  echo "== $label" >&2
  local line
  line=$(env "$@" BENCH_MFU=1 BENCH_ITERS=20 timeout 1200 python bench.py 2>>"${OUT%.jsonl}.err" | tail -1) || line=""
  if [ -n "$line" ]; then
    echo "{\"config\": \"$label\", \"result\": $line}" >> "$OUT"
  else
    echo "{\"config\": \"$label\", \"result\": null}" >> "$OUT"
  fi
}

LM_CFG='{"d_model":512,"n_head":8,"n_layer":8,"seq_len":512,"vocab":32768,"synthetic_train":512}'
