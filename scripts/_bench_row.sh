# Shared helper for the perf-matrix scripts (source, don't execute):
# run <label> [ENV=V ...] — one bench.py row appended to $OUT as JSON,
# stderr kept in ${OUT%.jsonl}.err.  LM_CFG is the transformer-family
# benchmark shape.
#
# Rows are RESUMABLE (round-3 verdict, weak #7): a config that already has a
# non-null result in $OUT is skipped, so re-running a matrix script after a
# tunnel wedge measures only the missing rows.  bench.py carries the per-row
# timeout itself (BENCH_TIMEOUT, wedge-proof wrapper) and emits structured
# JSON on failure; a failed row is recorded as null so the next pass retries
# it.  Dedup superseded nulls with scripts/merge_matrix.py.
WEDGED=0
run() {
  local label="$1"; shift
  if [ "$WEDGED" = 1 ]; then
    echo "== $label (tunnel wedged earlier this pass — skip)" >&2
    return 0
  fi
  if [ -s "$OUT" ] && grep -qF "\"config\": \"$label\", \"result\": {\"metric\"" "$OUT" 2>/dev/null; then
    echo "== $label (already measured — skip)" >&2
    return 0
  fi
  echo "== $label" >&2
  local line
  # rows skip the per-row backend probe — the matrix driver (watcher)
  # probes once per pass; the wrapper still hard-kills a wedged row at
  # BENCH_TIMEOUT and classifies the wedge with a post-check probe
  line=$(env BENCH_SKIP_PROBE="${BENCH_SKIP_PROBE:-1}" "$@" BENCH_MFU=1 BENCH_ITERS=20 python bench.py 2>>"${OUT%.jsonl}.err" | tail -1) || true
  case "$line" in
    '{"metric"'*) echo "{\"config\": \"$label\", \"result\": $line}" >> "$OUT" ;;
    *) echo "== $label failed: ${line:-no output}" >&2
       echo "{\"config\": \"$label\", \"result\": null}" >> "$OUT"
       # a wedge mid-matrix would burn two probe timeouts per remaining row;
       # once one row reports the wedge signature, stop the pass (the
       # watcher re-runs the script when the tunnel answers again)
       case "$line" in
         *wedged*|*"probe hung"*) WEDGED=1 ;;
       esac ;;
  esac
}

LM_CFG='{"d_model":512,"n_head":8,"n_layer":8,"seq_len":512,"vocab":32768,"synthetic_train":512}'
