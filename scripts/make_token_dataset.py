#!/usr/bin/env python
"""Text → flat token files for the LM family (models/data/tokens.py).

Byte-level tokenization (vocab 256, zero dependencies — the fallback
GPT-2-style byte alphabet): reads one or more text files, concatenates,
splits train/val, and writes ``train.bin`` / ``val.bin`` as raw uint16
arrays — the nanoGPT format ``TokenFileData`` memory-maps.

Usage:
  python scripts/make_token_dataset.py corpus.txt [more.txt ...] \
      --out data/mycorpus [--val-frac 0.05]

Then train with:
  rule.init(..., modelfile='theanompi_tpu.models.transformer_lm',
            modelclass='TransformerLM', data_dir='data/mycorpus', vocab=256)

For BPE corpora, tokenize externally and drop the id arrays in the same
``train.bin``/``val.bin`` shape (uint16 for vocab ≤ 65536) — set
``token_dtype``/``vocab`` accordingly.
"""

import argparse
import os
import sys

import numpy as np


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("inputs", nargs="+", help="text files (utf-8/binary)")
    p.add_argument("--out", required=True, help="output directory")
    p.add_argument("--val-frac", type=float, default=0.05)
    args = p.parse_args(argv)
    if not (0.0 <= args.val_frac < 1.0):
        p.error(f"--val-frac must be in [0, 1); got {args.val_frac} "
                f"(>= 1 would leave an empty train split)")

    chunks = []
    for path in args.inputs:
        with open(path, "rb") as f:
            chunks.append(np.frombuffer(f.read(), dtype=np.uint8))
    toks = np.concatenate(chunks).astype(np.uint16)
    if len(toks) < 2:
        print(f"corpus too small ({len(toks)} bytes)", file=sys.stderr)
        return 2
    n_val = int(len(toks) * args.val_frac)
    os.makedirs(args.out, exist_ok=True)
    toks[:len(toks) - n_val].tofile(os.path.join(args.out, "train.bin"))
    toks[len(toks) - n_val:].tofile(os.path.join(args.out, "val.bin"))
    print(f"{len(toks) - n_val} train + {n_val} val byte-tokens "
          f"(vocab 256) -> {args.out}/train.bin, val.bin")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
