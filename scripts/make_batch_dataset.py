#!/usr/bin/env python
"""Pre-process an image-folder dataset into the framework's batch-file layout.

The reference inherited its data prep from ``uoguelph-mlrg/theano_alexnet``:
ImageNet resized offline to 256×256 and packed into hickle ``.hkl`` files of
one uint8 batch each, plus a mean image (SURVEY.md §2.8).  This script
produces the same on-disk contract from a ``class/img.jpg`` folder tree (or
synthesizes one for pipeline testing), streaming one batch at a time — RAM
stays O(batch) no matter the dataset size (ImageNet-1k is ~250 GB decoded).

    out_dir/
      train_hkl/0000.hkl ...     (or .npy without h5py)  [B, 256, 256, 3] u8
      val_hkl/0000.hkl ...
      train_labels.npy  val_labels.npy  img_mean.npy

Usage:
  python scripts/make_batch_dataset.py --src /data/imagenet_raw --out /data/imagenet
  python scripts/make_batch_dataset.py --synthetic 16 --out /tmp/fake_imagenet
"""

import argparse
import os

import numpy as np

RAW = 256


def _iter_images(src):
    """Yield (path, class_index) over a class-per-directory tree."""
    classes = sorted(d for d in os.listdir(src)
                     if os.path.isdir(os.path.join(src, d)))
    idx = {c: i for i, c in enumerate(classes)}
    for c in classes:
        d = os.path.join(src, c)
        for name in sorted(os.listdir(d)):
            if name.lower().split(".")[-1] in ("jpg", "jpeg", "png", "bmp"):
                yield os.path.join(d, name), idx[c]


def _load_resized(path):
    from PIL import Image
    with Image.open(path) as im:
        im = im.convert("RGB")
        # reference prep: scale shorter side to 256, center crop 256×256
        w, h = im.size
        s = RAW / min(w, h)
        im = im.resize((max(RAW, round(w * s)), max(RAW, round(h * s))))
        w, h = im.size
        ox, oy = (w - RAW) // 2, (h - RAW) // 2
        im = im.crop((ox, oy, ox + RAW, oy + RAW))
        return np.asarray(im, np.uint8)


def _save_batch(path_base, batch):
    try:
        import h5py
        with h5py.File(path_base + ".hkl", "w") as f:
            f.create_dataset("data", data=batch)
        return path_base + ".hkl"
    except ImportError:
        np.save(path_base + ".npy", batch)
        return path_base + ".npy"


def write_split(loader, items, out_sub, batch_size, mean_acc=None):
    """Stream full batches of ``items`` through ``loader`` into batch files.
    Returns the kept labels (partial trailing batch dropped, as the
    reference's fixed-size batch files require)."""
    os.makedirs(out_sub, exist_ok=True)
    kept_labels = []
    for b in range(len(items) // batch_size):
        chunk = items[b * batch_size:(b + 1) * batch_size]
        batch = np.stack([loader(it) for it in chunk])
        if mean_acc is not None:
            mean_acc += batch.astype(np.float64).sum(axis=0)
        _save_batch(os.path.join(out_sub, f"{b:04d}"), batch)
        kept_labels.extend(y for _, y in chunk)
    return np.asarray(kept_labels, np.int64)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--src", help="class-per-directory image tree")
    p.add_argument("--out", required=True)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--val-frac", type=float, default=0.05)
    p.add_argument("--synthetic", type=int, default=0,
                   help="instead of --src: write N synthetic train batches")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    os.makedirs(args.out, exist_ok=True)
    bs = args.batch_size

    if args.synthetic:
        n_train, n_val = args.synthetic * bs, max(bs, args.synthetic * bs // 8)
        r = np.random.RandomState(args.seed)
        labels = r.randint(0, 1000, n_train + n_val)
        # items are (row_seed, label); loader synthesizes deterministically
        items = [((args.seed, i), int(labels[i]))
                 for i in range(n_train + n_val)]

        def loader(item):
            (seed, i), _ = item
            return np.random.RandomState([seed, i]).randint(
                0, 256, (RAW, RAW, 3), dtype=np.uint8)
    else:
        if not args.src:
            p.error("--src or --synthetic required")
        items = list(_iter_images(args.src))     # (path, label) — paths only
        np.random.RandomState(args.seed).shuffle(items)
        n_val = max(bs, int(len(items) * args.val_frac) // bs * bs)
        n_train = len(items) - n_val
        if n_train < bs:
            p.error(f"{len(items)} images is too few for batch size {bs} "
                    f"(needs at least one train and one val batch: "
                    f">= {2 * bs} images)")
        print(f"streaming {len(items)} images from {args.src} ...")

        def loader(item):
            return _load_resized(item[0])

    mean_acc = np.zeros((RAW, RAW, 3), np.float64)
    tr_labels = write_split(loader, items[:n_train],
                            os.path.join(args.out, "train_hkl"), bs, mean_acc)
    va_labels = write_split(loader, items[n_train:],
                            os.path.join(args.out, "val_hkl"), bs)
    np.save(os.path.join(args.out, "train_labels.npy"), tr_labels)
    np.save(os.path.join(args.out, "val_labels.npy"), va_labels)
    np.save(os.path.join(args.out, "img_mean.npy"),
            (mean_acc / max(len(tr_labels), 1)).astype(np.float32))
    print(f"wrote {args.out}: {len(tr_labels)} train / {len(va_labels)} val "
          f"images in {bs}-image batch files")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
