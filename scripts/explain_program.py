#!/usr/bin/env python
"""Print and diff the AOT executable cache's per-program cost manifests.

A cache HIT deserializes in milliseconds and tells you nothing about
what you're about to run; since PR 7 the cache manifest
(``utils/compile_cache``) records a cost/memory summary per entry at
write time — flops, bytes accessed, argument/output/temp bytes and the
HBM-peak estimate — so the question "what does this cached program cost"
is answerable without recompiling anything.

    python scripts/explain_program.py <cache_dir>              # table
    python scripts/explain_program.py <cache_dir> --json       # raw dict
    python scripts/explain_program.py <cache_dir> --diff A B   # two entries

``A``/``B`` resolve by key prefix first, then by label substring (the
NEWEST matching entry wins — labels repeat across spc/batch variants).
The diff prints per-field deltas: where did the flops/HBM go between two
variants of the same program (e.g. ``train:AlexNet:spc1`` vs ``spc4``,
or a donated entry vs its donation-free twin) — and, for entries that
recorded their ``key_extra`` dict (PR 20+), a structured stamp diff
naming WHICH knob produced the key split, using the cache-key checker's
stamp vocabulary (``analysis/checkers/compile_surface.STAMP_MEANING``).

Stdlib only — reads ``manifest.json``, never unpickles entry bodies.
"""

import argparse
import json
import os
import sys
import time

COST_FIELDS = ("flops", "bytes_accessed", "transcendentals",
               "argument_bytes", "output_bytes", "temp_bytes",
               "alias_bytes", "generated_code_bytes", "peak_hbm_bytes_est")


def load_manifest(cache_dir):
    path = os.path.join(cache_dir, "manifest.json")
    try:
        with open(path) as f:
            m = json.load(f)
    except OSError:
        print(f"no manifest at {path} — not a compile-cache dir (or "
              "nothing cached yet)", file=sys.stderr)
        return None
    except ValueError as e:
        print(f"unparseable manifest {path}: {e}", file=sys.stderr)
        return None
    return m if isinstance(m, dict) else {}


def resolve(manifest, token):
    """One entry by key prefix, else by label substring (newest wins)."""
    hits = [(k, v) for k, v in manifest.items() if k.startswith(token)]
    if not hits:
        hits = [(k, v) for k, v in manifest.items()
                if token in str(v.get("label", ""))]
    if not hits:
        return None, None
    return max(hits, key=lambda kv: kv[1].get("created", 0))


def _fmt_count(v):
    if v is None:
        return "-"
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(v) >= div:
            return f"{v / div:.2f}{unit}"
    return f"{v:.0f}"


def _fmt_bytes(v):
    if v is None:
        return "-"
    for unit, div in (("GiB", 2**30), ("MiB", 2**20), ("KiB", 2**10)):
        if abs(v) >= div:
            return f"{v / div:.2f}{unit}"
    return f"{v}B"


def _age(created):
    if not created:
        return "-"
    secs = max(0.0, time.time() - float(created))
    for unit, div in (("d", 86400), ("h", 3600), ("m", 60)):
        if secs >= div:
            return f"{secs / div:.1f}{unit}"
    return f"{secs:.0f}s"


def print_table(manifest):
    rows = sorted(manifest.items(),
                  key=lambda kv: kv[1].get("created", 0), reverse=True)
    print(f"{'key':<14}{'label':<34}{'plat':<6}{'compile':>8}{'blob':>10}"
          f"{'flops':>9}{'rd/wr':>10}{'hbm est':>10}{'hits':>6}{'age':>7}")
    for key, e in rows:
        cost = e.get("cost", {})
        print(f"{key[:12]:<14}"
              f"{str(e.get('label', '?'))[:32]:<34}"
              f"{str(e.get('platform', '?')):<6}"
              f"{(str(e.get('compile_secs')) + 's'):>8}"
              f"{_fmt_bytes(e.get('bytes')):>10}"
              f"{_fmt_count(cost.get('flops')):>9}"
              f"{_fmt_bytes(cost.get('bytes_accessed')):>10}"
              f"{_fmt_bytes(cost.get('peak_hbm_bytes_est')):>10}"
              f"{e.get('hits', 0):>6}"
              f"{_age(e.get('created')):>7}")
    no_cost = sum(1 for _, e in rows if not e.get("cost"))
    if no_cost:
        print(f"({no_cost} entr{'y' if no_cost == 1 else 'ies'} predate the "
              "cost manifest — re-prewarm to populate)", file=sys.stderr)


def _stamp_meanings():
    """The cache-key checker's stamp vocabulary, imported through the
    scripts/lint.py synthetic-package bootstrap so jax never loads; an
    unimportable checker degrades to bare stamp names, never a crash."""
    try:
        if "theanompi_tpu" not in sys.modules:
            import importlib.machinery
            import types
            root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            sys.path.insert(0, root)
            pkg = types.ModuleType("theanompi_tpu")
            pkg.__path__ = [os.path.join(root, "theanompi_tpu")]
            pkg.__spec__ = importlib.machinery.ModuleSpec(
                "theanompi_tpu", loader=None, is_package=True)
            pkg.__spec__.submodule_search_locations = pkg.__path__
            sys.modules["theanompi_tpu"] = pkg
        from theanompi_tpu.analysis.checkers.compile_surface import \
            STAMP_MEANING
        return dict(STAMP_MEANING)
    except Exception:
        return {}


def print_extra_diff(a, b):
    """The structured ``key_extra`` stamp diff — which knob split the
    key.  Entries written before PR 20 carry no ``extra``; say so
    instead of pretending the stamps match."""
    ea, eb = a.get("extra"), b.get("extra")
    print("key_extra:")
    if ea is None or eb is None:
        which = "both" if ea is None and eb is None else \
            "A" if ea is None else "B"
        print(f"  ({which} predate the extras manifest — re-prewarm to "
              "record the stamp dicts)")
        return
    meanings = _stamp_meanings()
    differing = sorted(k for k in set(ea) | set(eb)
                       if ea.get(k) != eb.get(k))
    if not differing:
        print("  identical — the key split came from the traced program "
              "itself (HLO hash, avals, shardings, donation), not a "
              "config knob")
        return
    for k in differing:
        va = ea.get(k, "<unstamped>")
        vb = eb.get(k, "<unstamped>")
        meaning = meanings.get(k)
        tail = f"  ({meaning})" if meaning else ""
        print(f"  {k:<14}{str(va):>14} -> {str(vb):<14}{tail}")


def print_diff(manifest, a_tok, b_tok):
    ak, a = resolve(manifest, a_tok)
    bk, b = resolve(manifest, b_tok)
    missing = [t for t, k in ((a_tok, ak), (b_tok, bk)) if k is None]
    if missing:
        print(f"cannot resolve {missing} against the manifest (key prefix "
              "or label substring)", file=sys.stderr)
        return 2
    print(f"A: {ak[:12]} {a.get('label')} ({a.get('platform')}, "
          f"compiled {_age(a.get('created'))} ago)")
    print(f"B: {bk[:12]} {b.get('label')} ({b.get('platform')}, "
          f"compiled {_age(b.get('created'))} ago)")
    ca, cb = a.get("cost", {}), b.get("cost", {})
    def _fmt_secs(v):
        return "-" if v is None else f"{v:.2f}s"

    rows = [("compile_secs", a.get("compile_secs"), b.get("compile_secs")),
            ("blob_bytes", a.get("bytes"), b.get("bytes"))]
    rows += [(f, ca.get(f), cb.get(f)) for f in COST_FIELDS
             if f in ca or f in cb]
    print(f"  {'field':<24}{'A':>14}{'B':>14}{'B/A':>8}")
    for field, va, vb in rows:
        fmt = _fmt_secs if "secs" in field else \
            _fmt_bytes if "bytes" in field else _fmt_count
        ratio = (f"{vb / va:.3f}x"
                 if isinstance(va, (int, float)) and va
                 and isinstance(vb, (int, float)) else "-")
        print(f"  {field:<24}{fmt(va):>14}{fmt(vb):>14}{ratio:>8}")
    print_extra_diff(a, b)
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("cache_dir")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw manifest dict to stdout")
    ap.add_argument("--diff", nargs=2, metavar=("A", "B"), default=None,
                    help="diff two entries (key prefix or label substring)")
    args = ap.parse_args(argv)
    manifest = load_manifest(args.cache_dir)
    if manifest is None:
        return 2
    if not manifest:
        print("manifest is empty — nothing cached yet", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(manifest, indent=1, sort_keys=True))
        return 0
    if args.diff:
        return print_diff(manifest, *args.diff)
    print_table(manifest)
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:
        os._exit(0)
