#!/usr/bin/env bash
# Round-6 perf matrix — the fused-cadence rows (ISSUE 1 tentpole): the
# async rules' exchange now fuses into the steps_per_call scan
# (lax.cond on the in-scan count), so EASGD/ASGD/GoSGD and BSP params
# mode can ride the multi-step dispatch that round-3 profiling showed
# recovers ~26% host-dispatch overhead.  This script stages the A/B that
# quantifies it on the real chip: each staged rule config (BASELINE.json
# pairs VGG-16 with EASGD and ResNet-50 with GoSGD) at spc=1 (already in
# r5) vs spc=4/spc=8 with the cadence in-scan.  Rows already measured in
# the out-file are skipped, so the script is re-runnable after a tunnel
# wedge (same convention as perf_matrix_r5.sh).
#   ./scripts/perf_matrix_r6.sh [out_file]
set -u -o pipefail
OUT="${1:-perf_matrix_r6.jsonl}"
cd "$(dirname "$0")/.."
. scripts/_bench_row.sh

# cheap canary first: proves the fused-cadence compile path works on the
# chip at all before the big VGG/ResNet scans are attempted
run cifar10-b128-easgd-spc4   BENCH_MODEL=cifar10  BENCH_RULE=easgd BENCH_SPC=4

# -- the acceptance rows: staged async rules with the cadence in-scan --
run vgg16-b32-easgd-spc8      BENCH_MODEL=vgg16    BENCH_RULE=easgd BENCH_SPC=8 BENCH_SYNTH_BATCHES=8
run resnet50-b32-gosgd-spc8   BENCH_MODEL=resnet50 BENCH_RULE=gosgd BENCH_SPC=8 BENCH_SYNTH_BATCHES=8

# -- spc scaling shape for the same configs (is spc=4 already enough?) --
run vgg16-b32-easgd-spc4      BENCH_MODEL=vgg16    BENCH_RULE=easgd BENCH_SPC=4
run resnet50-b32-gosgd-spc4   BENCH_MODEL=resnet50 BENCH_RULE=gosgd BENCH_SPC=4

# -- the remaining fused rules, on the flagship model --
run alexnet-b128-asgd-spc8    BENCH_MODEL=alexnet  BENCH_RULE=asgd  BENCH_SPC=8 BENCH_SYNTH_BATCHES=8
run alexnet-b128-gosgd-spc8   BENCH_MODEL=alexnet  BENCH_RULE=gosgd BENCH_SPC=8 BENCH_SYNTH_BATCHES=8

python scripts/merge_matrix.py "$OUT"
cat "$OUT"
