#!/usr/bin/env bash
# Detached round-3 watcher: probe the wedged axon TPU tunnel every 10 min;
# if it answers, run the remaining perf-matrix rows ONCE and exit.
#   nohup ./scripts/tpu_watch_and_rest.sh >/tmp/tpu_watch.log 2>&1 &
cd "$(dirname "$0")/.."
for i in $(seq 1 60); do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "$(date -u) tunnel answered — running perf_matrix_rest" >&2
    ./scripts/perf_matrix_rest.sh perf_matrix_r3.jsonl 2>>perf_matrix_r3.log
    exit 0
  fi
  sleep 600
done
echo "$(date -u) gave up after 60 probes" >&2
