#!/usr/bin/env bash
# Detached round-3 watcher: probe the wedged axon TPU tunnel every 10 min;
# if a REAL TPU answers, run the remaining perf-matrix rows ONCE and exit.
#   nohup ./scripts/tpu_watch_and_rest.sh >/tmp/tpu_watch.log 2>&1 &
set -u -o pipefail
cd "$(dirname "$0")/.." || exit 1

LOCK=/tmp/tpu_watch_and_rest.pid
if [ -f "$LOCK" ] && kill -0 "$(cat "$LOCK")" 2>/dev/null; then
  echo "another watcher (pid $(cat "$LOCK")) is already running" >&2
  exit 1
fi
echo $$ > "$LOCK"
trap 'rm -f "$LOCK"' EXIT

for i in $(seq 1 60); do
  # platform must be CHECKED in-process: a wedged tunnel can fall back to
  # the CPU backend with only a warning, and CPU-speed rows would corrupt
  # the MFU table perf_matrix_r3.jsonl feeds
  if timeout 90 python -c \
      "import jax; assert jax.devices()[0].platform == 'tpu'" \
      >/dev/null 2>&1; then
    echo "$(date -u) TPU answered — running perf_matrix_rest" >&2
    ./scripts/perf_matrix_rest.sh perf_matrix_r3.jsonl 2>>perf_matrix_r3.log
    exit $?
  fi
  sleep 600
done
echo "$(date -u) gave up after 60 probes" >&2
exit 2
