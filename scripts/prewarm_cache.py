#!/usr/bin/env python
"""Pre-build the matrix rows' executables into the AOT cache — off-line.

Promoted from ``forensics/prewarm_cache.py`` (round 5), which proved the
heavy row programs COMPILE for v5e on a 1-vCPU host without the tunnel
(26–270 s each) but left an open question: the XLA persistent cache's read
path never hit in that venue, so whether the runtime would reuse the
entries was unknowable until a healthy window.  This promotion closes the
question by serializing the compiled executables OURSELVES through
``theanompi_tpu/utils/compile_cache.py`` — the same content-addressed
store ``model_base.compile_iter_fns`` and ``bench.py`` read — under a key
we control.  Drift-proofing: rows come from ``scripts/rows.py`` (the same
manifest the matrix scripts iterate) and each row's config is assembled by
``bench.bench_row_config`` (the same env→config path the bench inner
runs), so the prewarmed program is byte-identical to the one the hardware
window will request.

Two venues:

* ``--platform cpu`` / ``tpu`` (live backend): builds the model and runs
  ``compile_iter_fns`` with the cache configured — train, val, AND the
  standalone exchange collective all land in the store.  This is also the
  CPU proof path the tests drive.
* ``--platform topology:v5e:2x2x1`` (off-line AOT, the wedged-tunnel
  venue): lowers the train program against a topology mesh with abstract
  state avals (no device placement — topology devices are not
  addressable) and compiles/serializes it.  Already-cached rows are
  skipped from the entry itself (the store IS the done-marker; the old
  ``/tmp/prewarm_done.txt`` sidecar is obsolete).

Run under a killable timeout when the tunnel may be wedged (repo probe
convention):

    timeout -s KILL 3000 python -u scripts/prewarm_cache.py --rows heavy \
        --platform topology:v5e:2x2x1

A per-row failure prints and skips to the next; a mismatched row only
wastes its cache entry.
"""

from __future__ import annotations

import argparse
import faulthandler
import os
import sys
import time

os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
faulthandler.enable()
faulthandler.dump_traceback_later(600, repeat=True, file=sys.stderr)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--rows", default="heavy", metavar="SEL",
                   help="row selector for scripts/rows.py: group tag "
                        "(heavy/r7/r8), 'all', or label[,label...] "
                        "(default: heavy — the wedge-correlated compiles)")
    p.add_argument("--cache", default=None, metavar="DIR",
                   help="executable cache dir (default: "
                        "$BENCH_COMPILE_CACHE or /tmp/jax_bench_cache — "
                        "bench.py's default, so its rows hit)")
    p.add_argument("--platform", default="cpu",
                   help="'cpu'/'tpu' (live backend via compile_iter_fns) "
                        "or 'topology:<name>' e.g. topology:v5e:2x2x1 "
                        "(off-line AOT against a device topology)")
    p.add_argument("--spc1-flops", action="store_true", default=True,
                   help="also prewarm the spc=1 sibling of every spc>1 row "
                        "(bench.py's MFU flop-count program) [default]")
    p.add_argument("--no-spc1-flops", dest="spc1_flops",
                   action="store_false")
    return p.parse_args(argv)


def _configure_jax(prng: str, force_cpu: bool):
    import jax
    if force_cpu:
        # host-side work (param init, synthetic batches) must run on the
        # CPU backend — an axon default would hang on a wedged tunnel, and
        # the JAX_PLATFORMS env var is hijacked by the plugin (bench.py)
        jax.config.update("jax_platforms", "cpu")
    from theanompi_tpu.base import canonical_prng_impl
    impl = canonical_prng_impl(prng)
    if impl:
        jax.config.update("jax_default_prng_impl", impl)
    return jax


def _row_environ(row) -> dict:
    """The env the bench inner will ACTUALLY see for this row: ambient
    BENCH_* exports overlaid by the row's own settings — the semantics of
    ``_bench_row.sh``'s ``env K=V ... python bench.py``.  Keying from
    ``row.env`` alone would let any exported BENCH_* (a forgotten
    BENCH_BATCH, a BENCH_BN_DTYPE from an earlier experiment) silently
    re-key every measured program and forfeit every prewarm hit."""
    env = {k: v for k, v in os.environ.items() if k.startswith("BENCH_")}
    env.update(row.env)
    return env


def prewarm_live(row, cache_dir: str, spc1_flops: bool) -> str:
    """Live-backend prewarm: compile_iter_fns with the cache configured —
    exactly what the worker/bench will run, so the hit is tautological."""
    import importlib
    from bench import bench_model_config, bench_row_config, bench_row_mesh
    from theanompi_tpu.models.registry import MODELS
    from theanompi_tpu.parallel.exchanger import get_exchanger
    from theanompi_tpu.utils import compile_cache as cc

    model_name, rule, row_cfg, flags = bench_row_config(_row_environ(row))
    if flags["real_data"]:
        return f"{row.label}: SKIP (realdata rows need the on-disk " \
               f"dataset; the program equals its synthetic sibling)"
    modelfile, modelclass, extra = MODELS[model_name]
    mesh = bench_row_mesh(row_cfg)
    config = bench_model_config(mesh, extra, row_cfg,
                                compile_cache=cache_dir)
    model = getattr(importlib.import_module(modelfile), modelclass)(config)
    exchanger = get_exchanger(rule, config)
    t0 = time.time()
    model.compile_iter_fns(exchanger)
    parts = {k: v.get("cache") for k, v in model.compile_info.items()
             if isinstance(v, dict) and "cache" in v}
    spc = int(model.steps_per_call)
    if spc1_flops and spc > 1:
        # bench.py's spc>1 rows AOT-compile the spc=1 program purely for
        # its flop count — prewarm it through the ONE shared composition
        # (model_base.aot_train_program, the same call bench makes)
        _, info1 = model.aot_train_program(cc.get(cache_dir), spc=1,
                                           exchanger=exchanger)
        parts["spc1_flops"] = info1["cache"]
    return f"{row.label}: {parts} in {time.time() - t0:.1f}s"


def prewarm_topology(row, cache_dir: str, topo_name: str,
                     spc1_flops: bool) -> str:
    """Off-line AOT prewarm: lower against a topology mesh with abstract
    state avals and serialize the compiled executable.  No device
    placement anywhere (topology devices are not addressable)."""
    import importlib
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh
    from bench import bench_model_config, bench_row_config
    from theanompi_tpu.models.registry import MODELS
    from theanompi_tpu.parallel.exchanger import get_exchanger
    from theanompi_tpu.parallel.mesh import WORKER_AXIS
    from theanompi_tpu.utils import compile_cache as cc

    model_name, rule, row_cfg, flags = bench_row_config(_row_environ(row))
    if flags["real_data"]:
        return f"{row.label}: SKIP (realdata — program equals the " \
               f"synthetic sibling)"
    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=topo_name)
    topo_mesh = Mesh(np.array(topo.devices[:1]), (WORKER_AXIS,))
    modelfile, modelclass, extra = MODELS[model_name]
    config = bench_model_config(topo_mesh, extra, row_cfg)
    model = getattr(importlib.import_module(modelfile), modelclass)(config)
    exchanger = get_exchanger(rule, config)
    exchanger.prepare(topo_mesh, model)
    cache = cc.get(cache_dir)
    out = {}
    for spc in sorted({int(model.steps_per_call)} |
                      ({1} if spc1_flops else set())):
        # load=False: nothing to load an executable INTO in this venue —
        # a present entry is the done-marker and is left untouched
        _, info = model.aot_train_program(cache, spc=spc,
                                          exchanger=exchanger, load=False)
        out[f"spc{spc}"] = f"{info['cache']} ({info['compile_secs']:.1f}s)"
    return f"{row.label}: {out}"


def main(argv=None) -> int:
    args = parse_args(argv)
    cache_dir = args.cache or os.environ.get("BENCH_COMPILE_CACHE",
                                             "/tmp/jax_bench_cache")
    topo = None
    if args.platform.startswith("topology:"):
        topo = args.platform.split(":", 1)[1]
    jax = _configure_jax(
        prng=os.environ.get("BENCH_PRNG", "rbg"),
        force_cpu=(topo is not None or args.platform == "cpu"))
    if topo is None and args.platform == "tpu" \
            and jax.devices()[0].platform != "tpu":
        # the plugin can fail fast into a silent CPU fallback — exiting 0
        # here would cache useless cpu-keyed entries AND suppress
        # perf_matrix_r8.sh's `||` topology-venue retry (bench.py refuses
        # the same fallback for the same reason)
        print(f"prewarm: requested platform tpu but backend is "
              f"{jax.devices()[0].platform!r} — refusing (the `||` "
              f"topology venue is the off-line fallback)", flush=True)
        return 1
    from scripts.rows import rows
    picked = rows(args.rows)
    print(f"prewarm: {len(picked)} row(s) -> {cache_dir} "
          f"(platform={args.platform})", flush=True)
    failed = 0
    for row in picked:
        try:
            if topo is not None:
                msg = prewarm_topology(row, cache_dir, topo,
                                       args.spc1_flops)
            else:
                msg = prewarm_live(row, cache_dir, args.spc1_flops)
            print(msg, flush=True)
        except Exception as e:
            failed += 1
            print(f"{row.label}: FAILED {type(e).__name__}: "
                  f"{str(e)[:300]}", flush=True)
    n = len([f for f in os.listdir(cache_dir)
             if f.endswith(".jexec")]) if os.path.isdir(cache_dir) else 0
    print(f"cache now holds {n} executable(s) in {cache_dir}", flush=True)
    # ANY failed row is a nonzero exit: perf_matrix_r8.sh chains venues
    # with `||`, and a partially-failed live prewarm must still trigger
    # the topology-venue retry (cached rows skip there in ~ms)
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
