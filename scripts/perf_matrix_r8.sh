#!/usr/bin/env bash
# Round-8 perf matrix — the executable-cache round (ISSUE 3 tentpole):
# compile once off-line, hit instantly in the hardware window.
#
# Order of operations is the whole point:
#   1. prewarm: scripts/prewarm_cache.py compiles every staged row's
#      program into the AOT executable store (content-addressed,
#      utils/compile_cache.py) — safe to run BEFORE the window, with the
#      tunnel wedged, on this 1-vCPU host (topology venue).
#   2. canary: one cheap row must report `cache: hit` — if it doesn't,
#      the key composition drifted and every big row would pay its full
#      compile on the clock, so the pass ABORTS loudly instead of
#      silently burning the window (the round-5 failure mode).
#   3. the scans: every row JSON now carries compile_secs + cache, the
#      evidence the round-5 verdict asked the next window to produce.
# Rows come from scripts/rows.py (the same manifest prewarm consumed —
# shapes can never drift between prewarm and measurement).
# Rows already measured in the out-file are skipped (re-runnable after a
# wedge, same convention as perf_matrix_r6/r7.sh).
#   ./scripts/perf_matrix_r8.sh [out_file]
set -u -o pipefail
OUT="${1:-perf_matrix_r8.jsonl}"
cd "$(dirname "$0")/.."
. scripts/_bench_row.sh

CACHE="${BENCH_COMPILE_CACHE:-/tmp/jax_bench_cache}"

# 1. prewarm (idempotent: cached rows skip in ~ms).  On the TPU host the
# live backend venue is the strongest guarantee; fall back to the v5e
# topology venue when the tunnel can't answer.
echo "== prewarm -> $CACHE" >&2
timeout -s KILL 3000 python -u scripts/prewarm_cache.py --rows r8 \
    --cache "$CACHE" --platform tpu >&2 \
  || timeout -s KILL 3000 python -u scripts/prewarm_cache.py --rows r8 \
    --cache "$CACHE" --platform topology:v5e:2x2x1 >&2 \
  || echo "== prewarm failed (rows will compile on the clock)" >&2

# 2. canary: the cheapest staged row must be a cache hit before the big
# scans are attempted.  || exit — a miss here means every heavy row
# would recompile on the clock; stop and investigate instead.
echo "== canary: cifar10-b128-spc4 must report cache: hit" >&2
canary=$(env BENCH_SKIP_PROBE="${BENCH_SKIP_PROBE:-1}" \
             BENCH_MODEL=cifar10 BENCH_SPC=4 BENCH_ITERS=5 \
             BENCH_COMPILE_CACHE="$CACHE" python bench.py 2>>"${OUT%.jsonl}.err" | tail -1)
echo "$canary" | python -c '
import json, sys
row = json.loads(sys.stdin.read())
cache = row.get("cache")
assert cache == "hit", (
    f"canary row is cache: {cache!r}, not \"hit\" — the prewarm key does "
    f"not match what compile_iter_fns requests (row: {row}); aborting "
    f"before the heavy rows burn the window on compiles")
print("== canary hit (compile %ss)" % row.get("compile_secs"), file=sys.stderr)
' || exit 1
# recorded under its own label: the canary is a degraded measurement
# (5 iters, no MFU) — the REAL cifar10-b128-spc4 row must still run in
# step 3, and _bench_row.sh's resume-skip matches on the config label
echo "{\"config\": \"cifar10-b128-spc4-canary\", \"result\": $canary}" >> "$OUT"

# 3. the staged rows, straight from the shared manifest
while read -r line; do
  eval "run $line"
done < <(python scripts/rows.py --round r8 --sh)

python scripts/merge_matrix.py "$OUT"
cat "$OUT"
