#!/usr/bin/env python
"""fleetz — one table for every live process in a run directory.

Each long-lived process of an elastic run (worker CLIs, the center
server, the supervisor) serves a tiny ``statusz`` socket
(``theanompi_tpu/utils/tracing.py``, docs/design.md §17) and registers
it under ``<record_dir>/statusz/``.  This script dials every registered
endpoint and prints the fleet's live state — role, pid, uptime, current
iteration, current span, spans emitted, last event — marking
unreachable endpoints DOWN (a crashed process leaves its discovery file
behind; a cleanly-exited one removes it).

Usage:
    python scripts/fleetz.py <record_dir> [--json] [--events N]
                             [--watch] [--interval S] [--iterations N]

``--events N`` additionally tails the last N flight-ring events of every
reachable process (the cross-process "what is everyone doing right now"
that used to need N terminals).

``--watch`` re-probes and re-prints every ``--interval`` seconds — the
live control-room view.  When the run serves a fleet-health collector
(``utils/fleetmon``, registered in the same roster under role
``fleetmon``), each frame also shows its recent alerts and fleet rank
count.  ``--iterations N`` bounds the loop (N=1 is the single-shot test
mode; 0 = forever); the exit code reflects the LAST frame's roster (any
DOWN row → 2, same as the one-shot contract).

Runs jax-free: the package parent is bootstrapped synthetically (the
``scripts/lint.py`` pattern) so ``utils/tracing.py`` loads without
executing the jax-importing package ``__init__``.
"""

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the ONE synthetic-package bootstrap lives in scripts/lint.py — reuse
# it so a change to the jax-free loading scheme cannot drift between
# the two jax-free CLIs
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from lint import _bootstrap_package  # noqa: E402

_bootstrap_package()
from theanompi_tpu.utils import tracing  # noqa: E402


def probe(doc, timeout_s=2.0):
    """One roster entry → its live health reply (or a DOWN row)."""
    addr = f"{doc.get('host', '127.0.0.1')}:{doc.get('port')}"
    try:
        rep = tracing.statusz_query(addr, "health", timeout_s=timeout_s)
    except Exception as e:
        return {"ok": False, "role": doc.get("role"), "id": doc.get("id"),
                "pid": doc.get("pid"), "addr": addr, "down": True,
                "error": repr(e)[:80]}
    rep.setdefault("role", doc.get("role"))
    rep.setdefault("id", doc.get("id"))
    rep["addr"] = addr
    return rep


def fleet_table(record_dir, timeout_s=2.0):
    return [probe(doc, timeout_s)
            for doc in tracing.read_statusz_docs(record_dir)]


def print_table(rows):
    cols = ("role", "id", "pid", "state", "uptime", "iter", "spans",
            "current span", "last event")
    table = []
    for r in rows:
        cur = r.get("current_span") or {}
        last = r.get("last_event") or {}
        table.append((
            str(r.get("role", "?")), str(r.get("id", "?")),
            str(r.get("pid", "?")),
            "DOWN" if r.get("down") else "up",
            f"{r.get('uptime_s', 0):.0f}s" if not r.get("down") else "-",
            str(r.get("iter", r.get("steps", "-"))),
            str(r.get("spans", "-")),
            cur.get("name", "-") if cur else "-",
            last.get("ev", "-") if last else "-"))
    widths = [max(len(c), *(len(row[i]) for row in table)) if table
              else len(c) for i, c in enumerate(cols)]
    print("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    for row in table:
        print("  ".join(v.ljust(w) for v, w in zip(row, widths)))


def print_alerts(rows, timeout_s=2.0, n=5):
    """When a fleet-health collector is in the roster, show its recent
    alerts + fleet size — the control-room summary line."""
    for r in rows:
        if r.get("role") != "fleetmon" or r.get("down"):
            continue
        try:
            rep = tracing.statusz_query(r["addr"], "alerts", n=n,
                                        timeout_s=timeout_s)
        except Exception:
            continue
        alerts = rep.get("alerts", [])
        print(f"\nfleetmon: {len(r.get('ranks', []))} rank(s) streaming, "
              f"{r.get('alerts', 0)} alert(s) total, "
              f"{r.get('evaluations', 0)} evaluation(s)")
        for a in alerts[-n:]:
            who = "fleet" if a.get("rank") is None else f"w{a['rank']}"
            print(f"  ALERT {a.get('rule')} [{who}] "
                  f"{a.get('series')}={a.get('value')} "
                  f"(threshold {a.get('threshold')}) ts={a.get('ts')}")


def one_frame(args):
    """One probe → print pass; returns the exit code for this frame."""
    docs = tracing.read_statusz_docs(args.record_dir)
    if not docs:
        print(f"no statusz endpoints registered under "
              f"{tracing.statusz_dir(args.record_dir)} — is a run with "
              f"record_dir set (and statusz not disabled) live?",
              file=sys.stderr)
        return 1
    rows = [probe(doc, args.timeout) for doc in docs]
    if args.json:
        print(json.dumps({"fleet": rows}, default=str))
    else:
        print_table(rows)
        print_alerts(rows, args.timeout)
    if args.events:
        for r in rows:
            if r.get("down"):
                continue
            try:
                rep = tracing.statusz_query(r["addr"], "events",
                                            n=args.events,
                                            timeout_s=args.timeout)
            except Exception:
                continue
            print(f"\n{r.get('role')} {r.get('id')} — last "
                  f"{args.events} events:")
            for ev in rep.get("events", []):
                detail = {k: v for k, v in ev.items()
                          if k not in ("ts", "run", "rank", "ev")}
                print(f"  ts={ev.get('ts')} {ev.get('ev')} {detail}")
    # any DOWN row is worth a nonzero exit: a dead process left its
    # discovery file behind (clean exits deregister)
    return 0 if all(not r.get("down") for r in rows) else 2


def main(argv=None):
    import time
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("record_dir")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (one JSON doc)")
    ap.add_argument("--events", type=int, default=0, metavar="N",
                    help="also tail each live process's last N "
                         "flight-ring events")
    ap.add_argument("--watch", action="store_true",
                    help="re-probe every --interval seconds (live view)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="--watch refresh period (default 2s)")
    ap.add_argument("--iterations", type=int, default=0, metavar="N",
                    help="--watch frame budget (0 = forever; 1 = the "
                         "single-shot test mode)")
    ap.add_argument("--timeout", type=float, default=2.0)
    args = ap.parse_args(argv)
    if not args.watch:
        return one_frame(args)
    frame = 0
    rc = 1
    while True:
        frame += 1
        print(f"--- fleetz watch frame {frame} "
              f"({time.strftime('%H:%M:%S')}) ---")
        rc = one_frame(args)
        if args.iterations and frame >= args.iterations:
            return rc
        time.sleep(args.interval)


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:
        os._exit(0)
