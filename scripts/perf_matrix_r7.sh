#!/usr/bin/env bash
# Round-7 perf matrix — window-granular input staging (ISSUE 2 tentpole):
# with para_load on and steps_per_call>1 the PrefetchLoader producer
# assembles + stages whole spc windows OFF the consumer thread
# (k draws → host stack → steps.stage_window), so train_iter dequeues a
# mesh-resident window and dispatches immediately.  These rows stage the
# A/B for the next hardware window: each winload config against its
# consumer-assembled sibling (alexnet-b128-spc4 is the r3 flagship
# record row; vgg16-b32-easgd-spc8 is the r6 fused-cadence row).
# load_wait_share in the result is the overlap evidence: ~0 = the
# producer kept up with the chip.
# Rows already measured in the out-file are skipped, so the script is
# re-runnable after a tunnel wedge (same convention as perf_matrix_r6.sh).
#   ./scripts/perf_matrix_r7.sh [out_file]
set -u -o pipefail
OUT="${1:-perf_matrix_r7.jsonl}"
cd "$(dirname "$0")/.."
. scripts/_bench_row.sh

# cheap canary: proves the window producer + staged-window dispatch path
# compiles and streams on the chip before the big scans are attempted
run cifar10-b128-spc4-winload   BENCH_MODEL=cifar10  BENCH_SPC=4 BENCH_WINLOAD=1

# -- the acceptance rows: flagship + fused-cadence configs, window-staged --
run alexnet-b128-spc4-winload   BENCH_MODEL=alexnet  BENCH_SPC=4 BENCH_WINLOAD=1
run vgg16-b32-easgd-spc8-winload BENCH_MODEL=vgg16   BENCH_RULE=easgd BENCH_SPC=8 BENCH_WINLOAD=1

# -- the full pipeline: DISK -> native augment -> window stack -> staged
#    window, all off-thread while the chip trains (streams fresh data
#    every step; compare against r4's alexnet-b128-realdata spc=1 row) --
run alexnet-b128-realdata-spc4-winload BENCH_MODEL=alexnet BENCH_SPC=4 BENCH_REAL_DATA=1 BENCH_WINLOAD=1

python scripts/merge_matrix.py "$OUT"
cat "$OUT"
