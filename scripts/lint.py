#!/usr/bin/env python
"""tpulint launcher — THE analysis entry point ``scripts/tier1.sh`` runs.

    python scripts/lint.py                   # repo-wide, human output
    python scripts/lint.py --check-baseline  # tier-1 gate mode
    python scripts/lint.py --diff HEAD       # only files changed vs a ref
    python scripts/lint.py --update-baseline # regenerate the baseline
    python scripts/lint.py --list-checks

The suite lives in ``theanompi_tpu/analysis/`` — but importing
``theanompi_tpu`` executes its package ``__init__`` which drags jax in
(seconds of import, a backend in a lint process).  This launcher
registers a SYNTHETIC ``theanompi_tpu`` parent package whose
``__path__`` points at the source tree without executing
``__init__.py``: submodule imports (``theanompi_tpu.analysis``, the
schema-drift checker's ``theanompi_tpu.utils.recorder`` live probe)
resolve normally, and jax never loads — a cold whole-program run stays
around ten seconds on this container and an unchanged tree is a
``.tpulint_cache/`` hit in well under one.

``TPULINT_ASSERT_NO_JAX=1`` makes the process fail if jax sneaks into
``sys.modules`` anyway (used by tests/test_lint.py to pin the
contract).
"""

import importlib.machinery
import os
import sys
import types

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bootstrap_package() -> None:
    if "theanompi_tpu" in sys.modules:      # a real import beat us to it
        return
    sys.path.insert(0, ROOT)
    pkg_dir = os.path.join(ROOT, "theanompi_tpu")
    pkg = types.ModuleType("theanompi_tpu")
    pkg.__path__ = [pkg_dir]
    pkg.__spec__ = importlib.machinery.ModuleSpec(
        "theanompi_tpu", loader=None, is_package=True)
    pkg.__spec__.submodule_search_locations = [pkg_dir]
    sys.modules["theanompi_tpu"] = pkg


def main(argv=None) -> int:
    _bootstrap_package()
    from theanompi_tpu.analysis import cli
    rc = cli.main(sys.argv[1:] if argv is None else argv)
    if os.environ.get("TPULINT_ASSERT_NO_JAX") and "jax" in sys.modules:
        print("tpulint: jax was imported during the lint run — the "
              "no-backend contract is broken", file=sys.stderr)
        return 3
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
