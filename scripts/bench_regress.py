#!/usr/bin/env python
"""Self-judging throughput gate: fresh rows vs the committed trajectory.

Every hardware window so far was judged by a human reading BENCH_r*.json
next to the new rows.  This gate makes the comparison mechanical so the
next window can close on itself (``scripts/perf_matrix_r9.sh`` runs it
last): for each row label (the ``config`` field), the baseline is the
BEST *fresh* measurement in the committed trajectory — rows tagged
``stale: true`` (the PR 2 wedge-fallback flag), carrying a ``STALE
last-good`` metric, a ``degraded`` marker, or a top-level ``error`` are
EXCLUDED (a wedged round's re-emitted number must not become the bar,
in either direction) — and a fresh row more than ``--threshold`` percent
below its label's baseline fails the gate.

Usage:
    python scripts/bench_regress.py fresh.jsonl [more...]
        [--baseline GLOB ...] [--threshold PCT] [--json OUT]

Inputs may be perf-matrix JSONL (``{"config": ..., "result": {...}}``
lines) or BENCH_r*.json single-row files; the baseline defaults to the
committed ``BENCH_r*.json`` trajectory plus every committed
``perf_matrix_r*.jsonl``.  Exit codes: 0 = no regression, 2 = nothing
comparable (no fresh rows, or no baseline overlaps them — a warning,
not a verdict), 3 = regression past the threshold.

Stdlib only — runnable on the TPU host with no jax env active.
"""

import argparse
import glob
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_METRIC_LABEL_RE = re.compile(r"\(([a-z0-9_]+) batch (\d+)", re.I)


def _row_from_result(result, label=None, error=None):
    """One normalized row dict from a result payload (perf-matrix
    ``result`` or BENCH ``parsed``), or None when there is no value."""
    if not isinstance(result, dict):
        return None
    value = result.get("value")
    try:
        value = float(value)
    except (TypeError, ValueError):
        return None
    metric = str(result.get("metric", ""))
    if label is None:
        label = result.get("config") or \
            (result.get("last_good") or {}).get("config")
    if label is None:
        m = _METRIC_LABEL_RE.search(metric)
        if m:
            label = f"{m.group(1)}-b{m.group(2)}"
    blob = (metric + str(result.get("note", ""))).lower()
    stale = bool(result.get("stale")) or "stale last-good" in blob \
        or bool(error) or bool(result.get("error"))
    degraded = "degraded" in blob
    return {"label": str(label or "default"), "value": value,
            "stale": stale, "degraded": degraded,
            "unit": result.get("unit")}


def load_rows(path):
    """All normalized rows from one artifact, either format."""
    rows = []
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        print(f"bench_regress: cannot read {path}: {e}", file=sys.stderr)
        return rows
    if path.endswith(".jsonl"):
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            row = _row_from_result(doc.get("result"),
                                   label=doc.get("config"))
            if row:
                rows.append(row)
        return rows
    try:
        doc = json.loads(text)
    except ValueError:
        return rows
    parsed = doc.get("parsed", doc) if isinstance(doc, dict) else doc
    items = parsed if isinstance(parsed, list) else [parsed]
    for item in items:
        if not isinstance(item, dict):
            continue
        row = _row_from_result(item, error=item.get("error"))
        if row:
            rows.append(row)
    return rows


def build_baseline(paths):
    """(label -> (best fresh value, source path), stale-only labels).
    Stale/degraded rows are excluded per the module docstring; a label
    the trajectory carries ONLY in stale rows is returned separately so
    the caller can tell "never measured" apart from "every committed
    measurement was a wedge re-emission" — the latter must not fail the
    gate (there is no trustworthy bar), but it deserves a loud warning."""
    best = {}
    seen_stale = set()
    for path in paths:
        for row in load_rows(path):
            if row["stale"] or row["degraded"]:
                seen_stale.add(row["label"])
                continue
            cur = best.get(row["label"])
            if cur is None or row["value"] > cur[0]:
                best[row["label"]] = (row["value"], path)
    return best, seen_stale - set(best)


def judge(fresh_rows, baseline, threshold_pct):
    """Per-label verdicts: ``regression`` / ``ok`` / ``improved`` /
    ``new`` (no baseline) / ``stale-skipped``."""
    verdicts = []
    for row in fresh_rows:
        if row["stale"] or row["degraded"]:
            verdicts.append({**row, "verdict": "stale-skipped"})
            continue
        base = baseline.get(row["label"])
        if base is None:
            verdicts.append({**row, "verdict": "new"})
            continue
        base_v, src = base
        delta_pct = 100.0 * (row["value"] - base_v) / base_v if base_v \
            else 0.0
        verdict = "ok"
        if delta_pct < -threshold_pct:
            verdict = "regression"
        elif delta_pct > threshold_pct:
            verdict = "improved"
        verdicts.append({**row, "verdict": verdict,
                         "baseline": base_v, "baseline_src": src,
                         "delta_pct": round(delta_pct, 2)})
    return verdicts


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("fresh", nargs="+",
                    help="fresh row artifact(s): perf-matrix .jsonl or "
                         "BENCH-style .json")
    ap.add_argument("--baseline", action="append", default=None,
                    metavar="GLOB",
                    help="baseline artifact glob(s); default: the "
                         "committed BENCH_r*.json + perf_matrix_r*.jsonl")
    ap.add_argument("--threshold", type=float, default=10.0, metavar="PCT",
                    help="regression tolerance in percent (default 10)")
    ap.add_argument("--json", default=None, metavar="OUT",
                    help="write the machine-readable verdicts here")
    args = ap.parse_args(argv)

    globs = args.baseline or [os.path.join(ROOT, "BENCH_r*.json"),
                              os.path.join(ROOT, "perf_matrix_r*.jsonl")]
    base_paths = sorted(p for g in globs for p in glob.glob(g))
    # the fresh file under judgment must not also serve as its own bar
    fresh_abs = {os.path.abspath(p) for p in args.fresh}
    base_paths = [p for p in base_paths
                  if os.path.abspath(p) not in fresh_abs]
    baseline, stale_only = build_baseline(base_paths)

    fresh_rows = [r for p in args.fresh for r in load_rows(p)]
    verdicts = judge(fresh_rows, baseline, args.threshold)
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"threshold_pct": args.threshold,
                       "baseline_files": base_paths,
                       "verdicts": verdicts}, f, indent=1, sort_keys=True)

    regressions = [v for v in verdicts if v["verdict"] == "regression"]
    judged = [v for v in verdicts if v["verdict"] not in ("stale-skipped",)]
    for v in verdicts:
        if v["verdict"] in ("stale-skipped", "new"):
            print(f"  {v['label']:<28} {v['value']:>12.2f}  "
                  f"[{v['verdict']}]")
        else:
            print(f"  {v['label']:<28} {v['value']:>12.2f}  vs best "
                  f"{v['baseline']:.2f} ({v['delta_pct']:+.1f}%) "
                  f"[{v['verdict']}]")
    if not fresh_rows:
        print("bench_regress: no comparable fresh rows — nothing judged",
              file=sys.stderr)
        return 2
    if not judged:
        # every fresh row was a stale/degraded re-emission: the run under
        # judgment carried no trustworthy measurement.  That is a
        # baseline-hygiene problem, not a perf verdict — exit 0 so a
        # wedged hardware window doesn't fail CI on its own echo.
        print("bench_regress: STALE-BASELINE WARNING — every fresh row "
              "is stale/degraded; nothing trustworthy to judge. "
              "Re-run the bench window before trusting the trajectory.",
              file=sys.stderr)
        return 0
    if not any("baseline" in v for v in judged):
        stale_hit = sorted({v["label"] for v in judged
                            if v["label"] in stale_only})
        if stale_hit:
            # the labels DO exist in the committed trajectory, but only
            # in rows the stale filter excluded — the baseline for them
            # is all wedge re-emissions.  Loud, but not a failure.
            print("bench_regress: STALE-BASELINE WARNING — baseline for "
                  f"label(s) {stale_hit} exists only in stale/degraded "
                  "committed rows; no trustworthy bar to judge against. "
                  "Commit a fresh measurement to re-arm the gate.",
                  file=sys.stderr)
            return 0
        print("bench_regress: no fresh label overlaps the baseline "
              "trajectory — nothing judged", file=sys.stderr)
        return 2
    if regressions:
        print(f"BENCH REGRESSION GATE FAIL: {len(regressions)} label(s) "
              f"more than {args.threshold:g}% below their best fresh "
              f"baseline", file=sys.stderr)
        return 3
    print(f"bench_regress: PASS ({len(judged)} row(s) within "
          f"{args.threshold:g}% of the trajectory)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
