#!/usr/bin/env python
"""GoSGD mixing-rate experiment: 'perm' vs 'shift' vs 'iid' peer assignment.

Pure gossip (no training): workers start from diverse random params and
exchange every step; we track the cross-worker variance of the replicas.
The decay rate is the mixing rate of the gossip matrix sequence — the
evidence behind the peer-assignment design choice (VERDICT round-1 Missing
#6: the shared-shift variant shipped without it).

Run on the simulated mesh:  TMPI_FORCE_CPU=1 python scripts/gosgd_mixing.py

Measured result (8 workers, d=1024, 60 exchanges, 5 seeds, p=0.25 — the
reference's default send probability): the two modes mix at statistically
indistinguishable rates (variance decay/exchange 0.869 'perm' vs 0.865
'shift'; half-variance at 5 vs 6 exchanges).  Round 4 adds 'iid' — the
reference's exact collision-permitting routing — which mixes slightly
SLOWER (0.879/exchange at p=1, 3 seeds: collisions concentrate mass on one
receiver while leaving others empty-handed), further supporting 'perm' as
the default.  At p=1 'shift' actually mixes
FASTER (cyclic shifts have no short cycles; random derangements contain
2-cycles that keep re-averaging the same pair).  'perm' is therefore the
default on fidelity grounds, not speed: per-sender peer draws decorrelate
(matching the reference's independent draws; one shared shift makes every
sender's peer a deterministic function of one random number) and an
exchange costs P wire bytes instead of the shift mode's P·log₂N.

Round 5 adds ``--k-sweep`` (verdict weak #6: "a long run cycles 16
routings rather than fresh draws"): mixing measured across family sizes
K ∈ {4, 16, 64, 256} for both pre-drawn modes (8 workers, d=1024, 30
exchanges, 3 seeds, p=0.25, per-seed family seeds).  Result
(``gosgd_k_sweep.json``): decay/exchange is FLAT in K — perm 0.873/
0.836/0.819/0.830, iid 0.857/0.834/0.862/0.869, half-variance at 5
exchanges in every cell, differences within seed noise.  Cycling a K=16
family does not slow mixing; runs that still want fresh families can set
``gosgd_seed`` (new config knob) or raise ``gosgd_n_perms``.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("TMPI_FORCE_CPU"):
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")


class _Stub:
    """Minimal model surface for Exchanger.prepare/extra_state_template."""

    def __init__(self, params):
        self.params = params

    def param_specs(self):      # pure-DP stub (no tensor/pipeline sharding)
        return None


def run_mode(mode: str, n: int, d: int, iters: int, seed: int,
             prob: float = 1.0, n_perms: int = 16):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from theanompi_tpu.parallel import steps
    from theanompi_tpu.parallel.exchanger import GOSGD_Exchanger
    from theanompi_tpu.parallel.mesh import worker_mesh

    mesh = worker_mesh(n)
    r = np.random.RandomState(seed)
    boxed_params = {"w": r.randn(n, d).astype(np.float32)}
    exch = GOSGD_Exchanger({"exch_prob": prob, "gosgd_peers": mode,
                            "gosgd_n_perms": n_perms,
                            # different seeds ALSO get different routing
                            # families, so the seed average isn't pinned
                            # to one K-sized draw
                            "gosgd_seed": seed * 7919})
    stub = _Stub({"w": boxed_params["w"][0]})
    exch.model = stub
    exch.prepare(mesh, stub)
    state = {
        "params": steps.place_boxed(boxed_params, mesh),
        "opt_state": steps.place_boxed({"w": np.zeros((n, d), np.float32)},
                                       mesh),
        "bn_state": steps.place_boxed({"z": np.zeros((n, 1), np.float32)},
                                      mesh),
        "extra": steps.place_boxed({"alpha": np.ones((n,), np.float32)},
                                   mesh),
    }
    key = jax.random.key(seed + 1)
    curve = []
    for i in range(iters):
        w = np.asarray(jax.device_get(state["params"]["w"]))
        curve.append(float(w.var(axis=0).mean()))
        key, sub = jax.random.split(key)
        state = exch._exchange_fn(state, sub, jnp.int32(i))
    w = np.asarray(jax.device_get(state["params"]["w"]))
    curve.append(float(w.var(axis=0).mean()))
    return curve


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--workers", type=int, default=8)
    p.add_argument("--dim", type=int, default=4096)
    p.add_argument("--iters", type=int, default=40)
    p.add_argument("--seeds", type=int, default=5)
    p.add_argument("--prob", type=float, default=0.25,
                   help="per-worker send probability (reference default 0.25)")
    p.add_argument("--k-sweep", action="store_true",
                   help="sweep the pre-drawn routing-family size K "
                        "(gosgd_n_perms) instead of comparing modes — the "
                        "round-4 verdict's (weak #6) sensitivity check "
                        "that cycling a small static family does not slow "
                        "mixing")
    args = p.parse_args(argv)

    import numpy as np

    def stats(mode, n_perms):
        curves = np.array([run_mode(mode, args.workers, args.dim,
                                    args.iters, s, args.prob, n_perms)
                           for s in range(args.seeds)])
        mean = curves.mean(axis=0)
        norm = mean / mean[0]
        # geometric decay rate over the first 20 exchanges
        horizon = min(20, args.iters)
        rate = (norm[horizon]) ** (1.0 / horizon)
        half = int(np.argmax(norm < 0.5)) if (norm < 0.5).any() else -1
        return {"decay_per_exchange": round(float(rate), 4),
                "exchanges_to_half_variance": half,
                f"variance_ratio_at_{horizon}":
                    round(float(norm[horizon]), 5)}

    out = {}
    if args.k_sweep:
        for mode in ("perm", "iid"):        # the two pre-drawn-family modes
            for k in (4, 16, 64, 256):
                out[f"{mode}-K{k}"] = s = stats(mode, k)
                print(f"{mode:>6} K={k:<4}: decay/exchange "
                      f"{s['decay_per_exchange']:.4f}, half-variance at "
                      f"{s['exchanges_to_half_variance']}", flush=True)
    else:
        for mode in ("perm", "shift", "iid"):
            out[mode] = s = stats(mode, 16)
            print(f"{mode:>6}: decay/exchange "
                  f"{s['decay_per_exchange']:.4f}, half-variance at "
                  f"{s['exchanges_to_half_variance']}", flush=True)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
