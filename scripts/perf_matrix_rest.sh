#!/usr/bin/env bash
# The rows perf_matrix.sh did not get to before the round-3 tunnel wedge.
# VGG-16 rows run LAST: the wedge started mid-vgg16-b32, so if it wedges
# again everything else is already measured.
#   ./scripts/perf_matrix_rest.sh [out_file]
set -u -o pipefail
OUT="${1:-perf_matrix_r3.jsonl}"
cd "$(dirname "$0")/.."
. scripts/_bench_row.sh

run resnet50-b32            BENCH_MODEL=resnet50
run resnet50-b32-spc8       BENCH_MODEL=resnet50 BENCH_SPC=8 BENCH_SYNTH_BATCHES=8
run resnet50-b32-spc8-bnbf16 BENCH_MODEL=resnet50 BENCH_SPC=8 BENCH_SYNTH_BATCHES=8 BENCH_BN_DTYPE=bfloat16
run resnet50-b32-bnbf16     BENCH_MODEL=resnet50 BENCH_BN_DTYPE=bfloat16
run cifar10-b128            BENCH_MODEL=cifar10
run resnet50-b64            BENCH_MODEL=resnet50 BENCH_BATCH=64
run resnet50-b128           BENCH_MODEL=resnet50 BENCH_BATCH=128
run googlenet-b128          BENCH_MODEL=googlenet BENCH_BATCH=128
run googlenet-b32-spc8      BENCH_MODEL=googlenet BENCH_SPC=8 BENCH_SYNTH_BATCHES=8
run alexnet-b128-spc8       BENCH_MODEL=alexnet BENCH_SPC=8 BENCH_SYNTH_BATCHES=8

run transformer_lm-b16      BENCH_MODEL=transformer_lm BENCH_BATCH=16 BENCH_CFG="$LM_CFG"
run transformer_lm-b16-flash BENCH_MODEL=transformer_lm BENCH_BATCH=16 BENCH_CFG="${LM_CFG%\}},\"attn_impl\":\"flash\"}"
run moe_lm-b16              BENCH_MODEL=moe_lm         BENCH_BATCH=16 BENCH_CFG="$LM_CFG"

# vgg16 last — prime wedge suspect
run vgg16-b32               BENCH_MODEL=vgg16
run vgg16-b32-spc4          BENCH_MODEL=vgg16    BENCH_SPC=4
run vgg16-b32-topk          BENCH_MODEL=vgg16 BENCH_STRATEGY=topk
run vgg16-b32-onebit        BENCH_MODEL=vgg16 BENCH_STRATEGY=onebit

cat "$OUT"
