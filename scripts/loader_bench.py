#!/usr/bin/env python
"""Host-side input-pipeline throughput — no accelerator required.

The reference's flagship was its parallel loader feeding real ``.hkl``
batches at AlexNet rates (SURVEY.md §2.8/§7: at 14k img/s that is ~1.1 GB/s
of augmented float32).  This measures exactly that capability in isolation:
disk → ``.hkl`` read → fused native crop/mirror/mean/cast →
(optionally) the PrefetchLoader producer — images/sec and GB/s out of the
host pipeline, the ceiling it can feed a chip at.

    python scripts/loader_bench.py [--batches 32] [--batch-size 128]
                                   [--u8-wire] [--prefetch]

Writes one JSON line; nothing here touches a TPU, so it runs (and proves
the SURVEY §7 "input pipeline at AlexNet speeds" hard part) even while the
tunnel is down.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# nothing here needs an accelerator — and a wedged TPU tunnel would hang the
# first backend touch on import, so pin the CPU backend up front
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batches", type=int, default=32)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--epochs", type=int, default=3,
                   help="timed passes over the shard set")
    p.add_argument("--u8-wire", action="store_true",
                   help="measure the aug_wire_u8 path (crop+mirror only)")
    p.add_argument("--prefetch", action="store_true",
                   help="pull through the PrefetchLoader producer thread")
    p.add_argument("--workers", type=int, default=1,
                   help="PrefetchLoader materializer pool size (implies "
                        "--prefetch when > 1)")
    p.add_argument("--windows", type=int, default=0, metavar="K",
                   help="window mode A/B (steps_per_call=K dispatch "
                        "inputs): staged-window DEQUEUE latency through "
                        "the PrefetchLoader window producer vs serial "
                        "consumer-side assembly (K draws + stack + put)")
    p.add_argument("--compute-ms", type=float, default=0.0,
                   help="simulated per-window compute between dequeues "
                        "(0 = use the measured serial assembly time, so "
                        "the producer gets the same overlap budget a real "
                        "training dispatch would give it)")
    p.add_argument("--data-dir", default=None)
    args = p.parse_args(argv)

    # shared generator (half-generated-dir wipe included) — bench.py's
    # import is wedge-safe: its module level touches no jax backend
    from bench import _ensure_bench_dataset
    d = _ensure_bench_dataset(args.batches, args.batch_size, args.data_dir)

    from theanompi_tpu.models.data.imagenet import ImageNet_data

    cfg = {"size": 1, "data_dir": d}
    if args.u8_wire:
        cfg["aug_wire_u8"] = True
    if args.windows > 1:
        return _bench_windows(args, cfg)
    data = ImageNet_data(cfg, batch_size=args.batch_size)
    if args.prefetch or args.workers > 1:
        from theanompi_tpu.models.data.prefetch import PrefetchLoader
        data = PrefetchLoader(data, n_workers=args.workers)

    # warm the page cache + any lazy native-library build; epoch 0 then
    # CONTINUES from batch 1 (no re-shuffle — that would restart the
    # producer and regenerate the warmup batch inside the timed window)
    data.shuffle_data(0)
    b = data.next_train_batch(0)
    bytes_per_img = b["x"][0].nbytes
    n_imgs = 0
    t0 = time.time()
    for ep in range(args.epochs):
        if ep > 0:
            data.shuffle_data(ep)
        for i in range(1 if ep == 0 else 0, data.n_batch_train):
            batch = data.next_train_batch(i)
            n_imgs += batch["x"].shape[0]
    dt = time.time() - t0
    ips = n_imgs / dt
    out = {
        "metric": "host_loader_images_per_sec"
                  + (" (u8-wire)" if args.u8_wire else " (fused f32)")
                  + (f" via PrefetchLoader x{args.workers}"
                     if (args.prefetch or args.workers > 1) else ""),
        "value": round(ips, 1),
        "unit": "images/sec",
        "gb_per_sec_out": round(ips * bytes_per_img / 1e9, 3),
        "images": n_imgs,
        "seconds": round(dt, 2),
        "note": "host pipeline only (disk->.hkl->augment); the rate it can "
                "feed a chip at — AlexNet v5e needs ~14k img/s "
                "(BASELINE.md)",
    }
    print(json.dumps(out))
    return 0


def _bench_windows(args, cfg) -> int:
    """--windows K: the ISSUE-2 A/B in isolation — what does the CONSUMER
    thread pay per ``steps_per_call`` dispatch input?  Serial path: k draws
    + host stack + device_put on the consumer (the pre-window train_iter).
    Window path: the PrefetchLoader producer assembles+stages whole
    windows in the background and the consumer only DEQUEUES — with a
    compute-sized gap between dequeues (as training provides), the
    dequeue latency is the stall the chip actually sees."""
    import jax as _jax

    from theanompi_tpu.models.data.imagenet import ImageNet_data
    from theanompi_tpu.models.data.prefetch import PrefetchLoader
    from theanompi_tpu.parallel import steps
    from theanompi_tpu.parallel.mesh import worker_mesh

    k = args.windows
    mesh = worker_mesh(1)

    def block(w):
        _jax.block_until_ready(_jax.tree_util.tree_leaves(w)[0])

    serial = ImageNet_data(cfg, batch_size=args.batch_size)
    n_windows = serial.n_batch_train // k
    assert n_windows >= 2, (f"--windows {k} needs >= {2 * k} batches "
                            f"(have {serial.n_batch_train})")
    serial.shuffle_data(0)
    # warm: page cache, native-library build, first device_put
    block(steps.put_batch_stack(
        mesh, [serial.next_train_batch(j) for j in range(k)], None))
    t_serial = []
    for ep in range(args.epochs):
        serial.shuffle_data(ep + 1)
        for wi in range(n_windows):
            t1 = time.time()
            batches = [serial.next_train_batch(wi * k + j) for j in range(k)]
            block(steps.put_batch_stack(mesh, batches, None))
            t_serial.append(time.time() - t1)
    serial_ms = 1e3 * sum(t_serial) / len(t_serial)

    compute_s = (args.compute_ms / 1e3) if args.compute_ms > 0 \
        else serial_ms / 1e3
    data = PrefetchLoader(ImageNet_data(cfg, batch_size=args.batch_size),
                          n_workers=args.workers)
    data.set_window(k, lambda w: steps.stage_window(mesh, w, None))
    t_deq = []
    for ep in range(args.epochs):
        data.shuffle_data(ep + 1)
        for wi in range(n_windows):
            t1 = time.time()
            w = data.next_train_window((wi + 1) * k)
            block(w)
            dt = time.time() - t1
            if wi > 0:          # window 0 pays the producer spin-up
                t_deq.append(dt)
            time.sleep(compute_s)     # the producer's overlap budget
    deq_ms = 1e3 * sum(t_deq) / len(t_deq)

    out = {
        "metric": f"staged_window_dequeue_vs_serial_assembly (k={k}, "
                  f"batch {args.batch_size}"
                  + (", u8-wire" if args.u8_wire else "")
                  + f", pool x{args.workers})",
        "value": round(deq_ms, 3),
        "unit": "ms/window dequeue",
        "serial_assembly_ms": round(serial_ms, 3),
        "window_dequeue_ms": round(deq_ms, 3),
        "consumer_stall_saved_ms": round(serial_ms - deq_ms, 3),
        "compute_ms_between_dequeues": round(compute_s * 1e3, 3),
        "windows": len(t_deq),
        "note": "serial = k draws + stack + put ON the consumer thread "
                "(pre-window train_iter); dequeue = what window-mode "
                "train_iter pays (producer staged off-thread)",
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
