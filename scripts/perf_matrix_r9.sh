#!/usr/bin/env bash
# Round-9 perf matrix — the bucketed-overlap round (ISSUE 13 tentpole):
# read overlap_ratio up / exposed_comm_secs down straight off the
# BENCH_TRACE columns, bucketed rows vs their monolithic controls.
#
# Same discipline as perf_matrix_r8.sh (the PR 3 prewarm machinery):
#   1. prewarm: every staged r9 row's program — bucketed schedules
#      included, their AOT key carries bucket_bytes — compiles into the
#      executable store BEFORE the window (utils/compile_cache.py).
#   2. canary: one cheap row must report `cache: hit`, or the pass
#      aborts loudly instead of burning the hardware window compiling.
#   3. the scans: each row JSON carries bucket_bytes / n_buckets
#      (devprof.BUCKET_ROW_COLUMNS) next to overlap_ratio /
#      exposed_comm_secs (devprof.TRACE_ROW_COLUMNS), so the acceptance
#      comparison is one jq away:
#        jq -r 'select(.result) | [.config, .result.n_buckets,
#               .result.overlap_ratio, .result.exposed_comm_secs] | @tsv'
# Rows come from scripts/rows.py --round r9 (the same manifest prewarm
# consumed); rows already measured in the out-file are skipped.
#   ./scripts/perf_matrix_r9.sh [out_file]
set -u -o pipefail
OUT="${1:-perf_matrix_r9.jsonl}"
cd "$(dirname "$0")/.."
. scripts/_bench_row.sh

CACHE="${BENCH_COMPILE_CACHE:-/tmp/jax_bench_cache}"

# 1. prewarm (idempotent: cached rows skip in ~ms); live backend venue
# first, topology venue fallback when the tunnel can't answer
echo "== prewarm -> $CACHE" >&2
timeout -s KILL 3000 python -u scripts/prewarm_cache.py --rows r9 \
    --cache "$CACHE" --platform tpu >&2 \
  || timeout -s KILL 3000 python -u scripts/prewarm_cache.py --rows r9 \
    --cache "$CACHE" --platform topology:v5e:2x2x1 >&2 \
  || echo "== prewarm failed (rows will compile on the clock)" >&2

# 2. canary: the cheapest r9 program must hit the executable cache — a
# miss means the bucketed key composition drifted from prewarm's
echo "== canary: alexnet-b128-bucket4m must report cache: hit" >&2
canary=$(env BENCH_SKIP_PROBE="${BENCH_SKIP_PROBE:-1}" \
             BENCH_MODEL=alexnet BENCH_BUCKET_BYTES=4194304 \
             BENCH_ITERS=5 \
             BENCH_COMPILE_CACHE="$CACHE" python bench.py 2>>"${OUT%.jsonl}.err" | tail -1)
echo "$canary" | python -c '
import json, sys
row = json.loads(sys.stdin.read())
cache = row.get("cache")
assert cache == "hit", (
    f"canary row is cache: {cache!r}, not \"hit\" — the bucketed "
    f"program key does not match what prewarm stored (row: {row}); "
    f"aborting before the heavy rows burn the window on compiles")
print("== canary hit (compile %ss, n_buckets=%s)"
      % (row.get("compile_secs"), row.get("n_buckets")), file=sys.stderr)
' || exit 1
echo "{\"config\": \"alexnet-b128-bucket4m-canary\", \"result\": $canary}" >> "$OUT"

# 3. the staged rows (bucketed + monolithic controls, every one tracing)
while read -r line; do
  eval "run $line"
done < <(python scripts/rows.py --round r9 --sh)

python scripts/merge_matrix.py "$OUT"
cat "$OUT"

# 4. closing gate: the fresh rows must sit within BENCH_REGRESS_PCT
# (default 10%) of each label's best fresh committed reading — stale/
# degraded trajectory rows are excluded from the bar, so a wedged
# round's fallback can neither hide nor fake a regression.  The window
# self-judges instead of waiting for a human diff.
python scripts/bench_regress.py "$OUT" \
    --threshold "${BENCH_REGRESS_PCT:-10}" \
    --json "${OUT%.jsonl}_regress.json" \
  || { echo "== bench_regress: throughput regression gate FAILED" >&2; exit 7; }
