#!/usr/bin/env bash
# Round-10 perf matrix — the interleaved-pipeline round (ISSUE 16
# tentpole): TransformerLM at depth on a pp=4 'pipe' mesh, fill/drain
# control vs v∈{2,4} interleaved virtual stages (pp_interleave).  Every
# row captures a BENCH_TRACE window so the schedule measurement lands in
# the row JSON (devprof.PIPELINE_ROW_COLUMNS): pipeline_bubble_ticks
# (exact when pipeline_schedule_verified), pipeline_bubble_time (what
# the bubble costs in wall time), next to the generic trace columns.
# The acceptance comparison is one jq away:
#   jq -r 'select(.result) | [.config, .result.pipeline_bubble_ticks,
#          .result.pipeline_bubble_time,
#          .result.pipeline_schedule_verified] | @tsv'
# and scripts/predict_scaling.py --json joins the measured column against
# its (pp, v, M, t_chunk, t_hop) bubble model per row.
#
# Same discipline as perf_matrix_r9.sh (the PR 3 prewarm machinery):
#   1. prewarm: every staged r10 row's program — the interleaved rows'
#      AOT keys carry pp_interleave (utils/compile_cache.key_extra) —
#      compiles into the executable store BEFORE the window.
#   2. canary: the fill/drain control must report `cache: hit`, or the
#      pass aborts loudly instead of burning the window compiling.
#   3. the scans: rows from scripts/rows.py --round r10 (the manifest
#      prewarm consumed); rows already measured in the out-file skip.
#   ./scripts/perf_matrix_r10.sh [out_file]
set -u -o pipefail
OUT="${1:-perf_matrix_r10.jsonl}"
cd "$(dirname "$0")/.."
. scripts/_bench_row.sh

CACHE="${BENCH_COMPILE_CACHE:-/tmp/jax_bench_cache}"
PIPE_CFG='{"d_model":512,"n_head":8,"n_layer":16,"seq_len":512,"vocab":32768,"synthetic_train":512,"pp":4,"pp_microbatches":8}'

# 1. prewarm (idempotent: cached rows skip in ~ms); live backend venue
# first, topology venue fallback when the tunnel can't answer
echo "== prewarm -> $CACHE" >&2
timeout -s KILL 3000 python -u scripts/prewarm_cache.py --rows r10 \
    --cache "$CACHE" --platform tpu >&2 \
  || timeout -s KILL 3000 python -u scripts/prewarm_cache.py --rows r10 \
    --cache "$CACHE" --platform topology:v5e:2x2x1 >&2 \
  || echo "== prewarm failed (rows will compile on the clock)" >&2

# 2. canary: the fill/drain control program must hit the executable
# cache — a miss means the pipeline key composition (pp/pp_microbatches/
# pp_interleave in key_extra) drifted from what prewarm stored
echo "== canary: transformer_lm-b16-pp4 must report cache: hit" >&2
canary=$(env BENCH_SKIP_PROBE="${BENCH_SKIP_PROBE:-1}" \
             BENCH_MODEL=transformer_lm BENCH_BATCH=16 \
             BENCH_CFG="$PIPE_CFG" \
             BENCH_ITERS=5 \
             BENCH_COMPILE_CACHE="$CACHE" python bench.py 2>>"${OUT%.jsonl}.err" | tail -1)
echo "$canary" | python -c '
import json, sys
row = json.loads(sys.stdin.read())
cache = row.get("cache")
assert cache == "hit", (
    f"canary row is cache: {cache!r}, not \"hit\" — the pipelined "
    f"program key does not match what prewarm stored (row: {row}); "
    f"aborting before the heavy rows burn the window on compiles")
print("== canary hit (compile %ss)" % (row.get("compile_secs"),),
      file=sys.stderr)
' || exit 1
echo "{\"config\": \"transformer_lm-b16-pp4-canary\", \"result\": $canary}" >> "$OUT"

# 3. the staged rows (fill/drain control + v=2 + v=4, every one tracing)
while read -r line; do
  eval "run $line"
done < <(python scripts/rows.py --round r10 --sh)

python scripts/merge_matrix.py "$OUT"
cat "$OUT"

# 4. closing gate: fresh rows within BENCH_REGRESS_PCT (default 10%) of
# each label's best fresh committed reading — the window self-judges
python scripts/bench_regress.py "$OUT" \
    --threshold "${BENCH_REGRESS_PCT:-10}" \
    --json "${OUT%.jsonl}_regress.json" \
  || { echo "== bench_regress: throughput regression gate FAILED" >&2; exit 7; }
