#!/usr/bin/env bash
# Round-4 watcher: probe the axon TPU tunnel every 2 min; whenever a REAL
# TPU answers, run the round-4 perf matrix (resumable — measured rows are
# skipped), merge, and exit once every config has a number.  Survives
# repeat wedges: a mid-matrix wedge leaves null rows that the next recovery
# pass retries (round-3 verdict weak #2/#7: auto-resume + canonical merge).
#   nohup ./scripts/tpu_watch_r4.sh >/tmp/tpu_watch_r4.log 2>&1 &
set -u -o pipefail
cd "$(dirname "$0")/.." || exit 1
OUT="${1:-perf_matrix_r4.jsonl}"
N_CONFIGS=$(grep -c '^run ' scripts/perf_matrix_r4.sh)

LOCK=/tmp/tpu_watch_r4.pid
if [ -f "$LOCK" ] && kill -0 "$(cat "$LOCK")" 2>/dev/null; then
  echo "another watcher (pid $(cat "$LOCK")) is already running" >&2
  exit 1
fi
echo $$ > "$LOCK"
trap 'rm -f "$LOCK"' EXIT

done_rows() {
  [ -s "$OUT" ] || { echo 0; return; }
  python scripts/merge_matrix.py "$OUT" 2>/dev/null || true
  grep -cF '"result": {"metric"' "$OUT" || true
}

# Probe every 2 min: the round-4 wedge history shows tunnel-alive windows
# as short as ~10 min, so a 10-min probe cadence could eat a whole window.
# 420 probes x ~2.5 min worst-case spacing covers the full ~12 h round.
for i in $(seq 1 420); do
  # platform must be CHECKED in-process: a wedged tunnel can fall back to
  # the CPU backend with only a warning, and CPU-speed rows would corrupt
  # the MFU table this matrix feeds
  if timeout 90 python -c \
      "import jax; assert jax.devices()[0].platform == 'tpu'" \
      >/dev/null 2>&1; then
    echo "$(date -u) TPU answered — running perf_matrix_r4 (pass $i)" >&2
    ./scripts/perf_matrix_r4.sh "$OUT" 2>>perf_matrix_r4.log || true
    n=$(done_rows)
    echo "$(date -u) pass done: $n/$N_CONFIGS rows measured" >&2
    if [ "$n" -ge "$N_CONFIGS" ]; then
      echo "$(date -u) matrix complete" >&2
      exit 0
    fi
  fi
  sleep 120
done
echo "$(date -u) gave up after 420 probes; $(done_rows)/$N_CONFIGS rows" >&2
exit 2
