#!/usr/bin/env bash
# Round-5 perf matrix on the live TPU chip — round-4 verdict #1: "numbers
# ARE the round".  Same complete config set as round 4 (every BASELINE.json
# staged config at its reference batch, the bf16-BN and batch-size levers,
# compressed-wire rows, the transformer family, the staged rules, the
# real-data pipeline rows, spc multi-step dispatch), written to a FRESH
# round-5 artifact so every number in it is from this round's windows.
# Rows already measured in the out-file are skipped, so the script is
# re-runnable after a tunnel wedge (scripts/tpu_watch_r5.sh drives that).
#
# New vs r4: two local-compile A/B rows (PALLAS_AXON_REMOTE_COMPILE=0 —
# client-side AOT compile via the local libtpu instead of terminal-side
# compile).  WEDGE.md's forensics point at terminal-side activity from big
# compiles as the wedge trigger; the -lc rows test the avoidance recipe.
# The cheap cifar10 canary runs early (validates the local-compile path
# works at all in this image); the big-compile A/B runs last.
#   ./scripts/perf_matrix_r5.sh [out_file]
set -u -o pipefail
OUT="${1:-perf_matrix_r5.jsonl}"
cd "$(dirname "$0")/.."
. scripts/_bench_row.sh

# Row order is greedy-by-value-per-minute-of-tunnel-uptime (windows have
# been as short as ~10 min): the round-4 degraded alexnet-b128 reading was
# voided (verdict #8), so it re-measures FIRST; then the never-measured
# staged configs; wedge-correlated big compiles (spc scans, VGG-16) last.

# -- staged configs at reference batch sizes (the comparison that counts) --
run alexnet-b128             BENCH_MODEL=alexnet
run resnet50-b32             BENCH_MODEL=resnet50
run googlenet-b32            BENCH_MODEL=googlenet
run cifar10-b128             BENCH_MODEL=cifar10
# local-compile canary: tiny program, proves PALLAS_AXON_REMOTE_COMPILE=0
# initializes + compiles + runs in this image before we lean on it below
run cifar10-b128-lc          BENCH_MODEL=cifar10 PALLAS_AXON_REMOTE_COMPILE=0
run vgg16-b32                BENCH_MODEL=vgg16

# -- bf16-BN lever A/B (BASELINE.md round-4 committed predictions) --
run resnet50-b32-bnbf16      BENCH_MODEL=resnet50 BENCH_BN_DTYPE=bfloat16

# -- batch-size headroom (MFU pushes; verdict #3 round-4 wants verdicts) --
run resnet50-b64             BENCH_MODEL=resnet50 BENCH_BATCH=64
run resnet50-b128            BENCH_MODEL=resnet50 BENCH_BATCH=128
run resnet50-b128-bnbf16     BENCH_MODEL=resnet50 BENCH_BATCH=128 BENCH_BN_DTYPE=bfloat16
run googlenet-b128           BENCH_MODEL=googlenet BENCH_BATCH=128
run vgg16-b64                BENCH_MODEL=vgg16 BENCH_BATCH=64

# -- staged rules + compressed wire on their staged models (BASELINE #3-#5) --
run vgg16-b32-easgd          BENCH_MODEL=vgg16 BENCH_RULE=easgd
run resnet50-b32-gosgd       BENCH_MODEL=resnet50 BENCH_RULE=gosgd
run vgg16-b32-topk           BENCH_MODEL=vgg16 BENCH_STRATEGY=topk
run vgg16-b32-onebit         BENCH_MODEL=vgg16 BENCH_STRATEGY=onebit
run vgg16-b32-powersgd4      BENCH_MODEL=vgg16 BENCH_STRATEGY=powersgd4

# -- real-data path (verdict #4): .hkl shards -> native loader -> device --
run alexnet-b128-realdata    BENCH_MODEL=alexnet BENCH_REAL_DATA=1
run alexnet-b128-realdata-u8w BENCH_MODEL=alexnet BENCH_REAL_DATA=1 BENCH_WIRE_U8=1

# -- transformer family (beyond-parity; value = sequences/sec/chip) --
run transformer_lm-b16       BENCH_MODEL=transformer_lm BENCH_BATCH=16 BENCH_CFG="$LM_CFG"
run transformer_lm-b16-flash BENCH_MODEL=transformer_lm BENCH_BATCH=16 BENCH_CFG="${LM_CFG%\}},\"attn_impl\":\"flash\"}"
run moe_lm-b16               BENCH_MODEL=moe_lm         BENCH_BATCH=16 BENCH_CFG="$LM_CFG"

# -- spc (multi-step dispatch) rows LAST: the scan-of-k-steps compile is
#    the biggest program per model and the round-4 wedge #1 trigger.
#    alexnet-b128-spc4 first: it is the flagship record config (r3:
#    14,162 img/s) and the driver's round-end bench default --
run alexnet-b128-spc4        BENCH_MODEL=alexnet  BENCH_SPC=4
run alexnet-b128-spc8        BENCH_MODEL=alexnet  BENCH_SPC=8 BENCH_SYNTH_BATCHES=8
run googlenet-b32-spc8       BENCH_MODEL=googlenet BENCH_SPC=8 BENCH_SYNTH_BATCHES=8
run resnet50-b32-spc8        BENCH_MODEL=resnet50 BENCH_SPC=8 BENCH_SYNTH_BATCHES=8
run resnet50-b32-spc8-bnbf16 BENCH_MODEL=resnet50 BENCH_SPC=8 BENCH_SYNTH_BATCHES=8 BENCH_BN_DTYPE=bfloat16
run resnet50-b128-spc4       BENCH_MODEL=resnet50 BENCH_BATCH=128 BENCH_SPC=4
run googlenet-b128-spc4      BENCH_MODEL=googlenet BENCH_BATCH=128 BENCH_SPC=4
run vgg16-b32-spc4           BENCH_MODEL=vgg16 BENCH_SPC=4
# flagship record-setter headroom: double the batch on the spc4 record
# config (r3 trace: after spc fixed host dispatch, HBM/MXU utilization is
# the next lever — bigger batch amortizes both)
run alexnet-b256-spc4        BENCH_MODEL=alexnet BENCH_BATCH=256 BENCH_SPC=4

# -- wedge-avoidance A/B (WEDGE.md): re-run the two biggest wedge triggers
#    with client-side compile; identical math, different compile venue --
run vgg16-b32-lc             BENCH_MODEL=vgg16 PALLAS_AXON_REMOTE_COMPILE=0
run alexnet-b128-spc4-lc     BENCH_MODEL=alexnet BENCH_SPC=4 PALLAS_AXON_REMOTE_COMPILE=0

python scripts/merge_matrix.py "$OUT"
cat "$OUT"
