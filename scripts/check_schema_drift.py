#!/usr/bin/env python
"""Schema drift guard — run by scripts/tier1.sh before the pytest gate.

Three consumers must agree on the phase/section vocabulary, with
``telemetry.PHASES`` as the ONE source of truth:

1. ``recorder.SECTIONS`` — the wall-clock buckets the worker loop brackets;
2. the ``print_train_info`` record keys — the ``t_<section>`` fields every
   inforec JSONL line (and plot_records panel) reads;
3. the telemetry phase-event names — the ``phase`` events' ``sec`` field
   and ``phase.<section>`` histograms that ``telemetry_report.py`` merges.

A new bucket added to one place but not the others silently drops that
phase from records, plots, or reports; this guard fails the tier-1 gate
instead.  Checks run against LIVE objects (a Recorder driven through one
print, a Telemetry instance fed one bracket per phase), not just the
declarations, so a hand-rolled record dict drifting from the list is
caught too.

Exit 0 = in sync; nonzero = drift (details on stderr).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from theanompi_tpu.utils import recorder, telemetry

    errors = []

    # 1. recorder.SECTIONS must BE the canonical list (same object or equal)
    if tuple(recorder.SECTIONS) != tuple(telemetry.PHASES):
        errors.append(
            f"recorder.SECTIONS {recorder.SECTIONS!r} != telemetry.PHASES "
            f"{telemetry.PHASES!r}")

    # 2. the record keys a live print_train_info actually emits
    r = recorder.Recorder({"verbose": False, "printFreq": 1})
    r.start()
    r.end("train")
    r.train_error(1, 1.0, 0.5, 8)
    if not r.print_train_info(1):
        errors.append("print_train_info(1) did not fire at printFreq=1")
    else:
        got = {k for k in r._all_records[-1] if k.startswith("t_")}
        want = {"t_" + s for s in telemetry.PHASES if s != "val"}
        if got != want:
            errors.append(
                f"print_train_info record keys {sorted(got)} != "
                f"t_<PHASES except val> {sorted(want)}")
    if tuple(recorder.RECORD_KEYS) != tuple(
            "t_" + s for s in telemetry.PHASES if s != "val"):
        errors.append(f"recorder.RECORD_KEYS {recorder.RECORD_KEYS!r} "
                      "drifted from telemetry.PHASES")

    # 3. the phase-event names a live registry emits for each section
    tm = telemetry.Telemetry(rank=0, run_id="drift-check")
    for s in telemetry.PHASES:
        tm.phase(s, 0.0)
    evs = [e for e in tm.tail(len(telemetry.PHASES) + 1)
           if e["ev"] == "phase"]
    got_secs = {e.get("sec") for e in evs}
    if got_secs != set(telemetry.PHASES):
        errors.append(f"telemetry phase-event names {sorted(got_secs)} != "
                      f"PHASES {sorted(telemetry.PHASES)}")
    got_hists = {k for k in tm.hists if k.startswith("phase.")}
    if got_hists != {"phase." + s for s in telemetry.PHASES}:
        errors.append(f"telemetry phase histograms {sorted(got_hists)} "
                      "drifted from PHASES")

    if errors:
        for e in errors:
            print(f"SCHEMA DRIFT: {e}", file=sys.stderr)
        return 1
    print(f"schema in sync: {len(telemetry.PHASES)} phases "
          f"({', '.join(telemetry.PHASES)})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
