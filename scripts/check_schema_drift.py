#!/usr/bin/env python
"""DEPRECATED shim — the schema drift guard is now a tpulint checker.

The live-object checks (recorder.SECTIONS / print_train_info record
keys / telemetry phase-event names all deriving from telemetry.PHASES)
moved to ``theanompi_tpu/analysis/checkers/schema_drift.py`` so
``scripts/tier1.sh`` has exactly ONE analysis entry point
(``scripts/lint.py``).  This script execs that CLI restricted to the
schema-drift checker, preserving the old exit-code contract (0 = in
sync, nonzero = drift) for anything still invoking it directly.
"""

import os
import sys

if __name__ == "__main__":
    print("check_schema_drift.py is deprecated: running "
          "`scripts/lint.py --only schema-drift` (the tpulint suite is "
          "the one analysis entry point)", file=sys.stderr)
    lint = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint.py")
    os.execv(sys.executable, [sys.executable, lint, "--only",
                              "schema-drift"])
