#!/usr/bin/env python
"""DEPRECATED shim — the schema drift guard is now a tpulint checker.

The live-object probes live in
``theanompi_tpu/analysis/checkers/schema_drift.py`` and have grown far
past the original recorder/telemetry phase sync: device gauges, sentry
anomaly schema, bench trace columns, membership/center event
vocabularies, wire counters and version loudness, span/statusz fields,
fleet-health rules, thread-role coverage, and (round 19) the §21
protocol cross-check of the extracted center op table against a live
``RemoteCenter``'s runtime surface.  ``scripts/tier1.sh`` has exactly
ONE analysis entry point (``scripts/lint.py``); this script execs that
CLI restricted to the schema-drift checker, preserving the old
exit-code contract (0 = in sync, nonzero = drift) for anything still
invoking it directly — ``os.execv`` replaces the process, so the CLI's
exit code IS this script's exit code, whatever checkers land later.
"""

import os
import sys

if __name__ == "__main__":
    print("check_schema_drift.py is deprecated: running "
          "`scripts/lint.py --only schema-drift` (the tpulint suite is "
          "the one analysis entry point)", file=sys.stderr)
    lint = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint.py")
    os.execv(sys.executable, [sys.executable, lint, "--only",
                              "schema-drift"])
