#!/usr/bin/env bash
# Round-5 watcher: probe the axon TPU tunnel every 2 min; whenever a REAL
# TPU answers, run the round-5 perf matrix (resumable — measured rows are
# skipped), then the post-matrix analysis stages, and exit once everything
# has a number.  Survives repeat wedges: a mid-matrix wedge leaves null
# rows that the next recovery pass retries.
#
# New vs the round-4 watcher (WEDGE.md forensics):
#  - every probe outcome is appended to forensics/probe_timeline.log with
#    a timestamp + the listener set, so wedge/recovery transitions are on
#    the record and can be correlated with what was running;
#  - on the first recovery of a window, a network snapshot is taken while
#    the matrix runs (the healthy-state connection signature WEDGE.md
#    lacks: which port the plugin actually dials);
#  - after the matrix completes, runs the fresh flagship bench row
#    (BENCH_r05_fresh.json) so the round's official number can never be a
#    stale last-good if any healthy window occurred.
#   nohup ./scripts/tpu_watch_r5.sh >/tmp/tpu_watch_r5.log 2>&1 &
set -u -o pipefail
cd "$(dirname "$0")/.." || exit 1
OUT="${1:-perf_matrix_r5.jsonl}"
N_CONFIGS=$(grep -c '^run ' scripts/perf_matrix_r5.sh)
mkdir -p forensics

LOCK=/tmp/tpu_watch_r5.pid
if [ -f "$LOCK" ] && kill -0 "$(cat "$LOCK")" 2>/dev/null; then
  echo "another watcher (pid $(cat "$LOCK")) is already running" >&2
  exit 1
fi
echo $$ > "$LOCK"
trap 'rm -f "$LOCK"' EXIT

done_rows() {
  [ -s "$OUT" ] || { echo 0; return; }
  python scripts/merge_matrix.py "$OUT" 2>/dev/null || true
  grep -cF '"result": {"metric"' "$OUT" || true
}

probe_log() {  # probe_log <ok|wedged> <pass#>
  echo "$(date -u +%FT%TZ) probe=$1 pass=$2 listeners=[$(ss -tln 2>/dev/null \
    | awk 'NR>1{print $4}' | paste -sd, -)]" >> forensics/probe_timeline.log
}

net_snapshot() {  # background: sample connections during the first rows
  t=0
  for d in 5 15 40 120; do   # cumulative offsets t+5/20/60/180s
    sleep "$d"; t=$((t + d))
    { echo "== $(date -u +%FT%TZ) (t+${t}s into recovery pass)";
      ss -tnp 2>/dev/null; } >> forensics/healthy_net_signature.txt
  done
}

# Probe every 2 min: wedge history shows tunnel-alive windows as short as
# ~10 min, so a sparser cadence could eat a whole window.  420 probes x
# ~2.5 min worst-case spacing covers a full ~12 h round.
first_recovery=1
for i in $(seq 1 420); do
  # platform must be CHECKED in-process: a wedged tunnel can fall back to
  # the CPU backend with only a warning, and CPU-speed rows would corrupt
  # the MFU table this matrix feeds
  if timeout 90 python -c \
      "import jax; assert jax.devices()[0].platform == 'tpu'" \
      >/dev/null 2>&1; then
    probe_log ok "$i"
    echo "$(date -u) TPU answered — running perf_matrix_r5 (pass $i)" >&2
    if [ "$first_recovery" = 1 ]; then
      first_recovery=0
      net_snapshot &
    fi
    ./scripts/perf_matrix_r5.sh "$OUT" 2>>perf_matrix_r5.log || true
    n=$(done_rows)
    echo "$(date -u) pass done: $n/$N_CONFIGS rows measured" >&2
    # fresh flagship record EVERY pass until one healthy reading lands
    # (NOT gated on matrix completion: one permanently-failing row must
    # not leave the round's official number a stale last-good when
    # healthy windows occurred).  Compile is cached, so a repeat pass
    # pays ~1 bench row.
    if ! grep -qs '"value"' BENCH_r05_fresh.json || \
         grep -qs 'STALE' BENCH_r05_fresh.json; then
      python bench.py > BENCH_r05_fresh.json.tmp 2>>perf_matrix_r5.log \
        && mv BENCH_r05_fresh.json.tmp BENCH_r05_fresh.json || true
    fi
    # scaling prediction re-derives from whatever rows exist so far
    python scripts/predict_scaling.py > scaling_prediction_r5.json \
      2>>perf_matrix_r5.log || true
    if [ "$n" -ge "$N_CONFIGS" ]; then
      echo "$(date -u) matrix complete — all stages done" >&2
      exit 0
    fi
  else
    probe_log wedged "$i"
  fi
  sleep 120
done
echo "$(date -u) gave up after 420 probes; $(done_rows)/$N_CONFIGS rows" >&2
exit 2
