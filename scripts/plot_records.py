#!/usr/bin/env python
"""Plot training records — the reference's offline matplotlib plotting.

Theano-MPI dumped per-rank ``inforec`` record files for offline plotting of
cost/error/throughput curves (SURVEY.md §2.10, §5 'Metrics/observability');
this reads this framework's ``inforec_rank*.jsonl`` (or ``.npy``) dumps from
a record dir and writes PNG curves.

Usage: python scripts/plot_records.py <record_dir> [out.png]
"""

import json
import os
import sys


def load_records(record_dir):
    recs = []
    for name in sorted(os.listdir(record_dir)):
        if name.startswith("inforec_rank") and name.endswith(".jsonl"):
            with open(os.path.join(record_dir, name)) as f:
                recs.extend(json.loads(line) for line in f if line.strip())
    if not recs:
        import numpy as np
        for name in sorted(os.listdir(record_dir)):
            if name.startswith("inforec_rank") and name.endswith(".npy"):
                recs.extend(np.load(os.path.join(record_dir, name),
                                    allow_pickle=True).tolist())
    return recs


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print(__doc__)
        return 1
    record_dir = argv[0]
    out = argv[1] if len(argv) > 1 else os.path.join(record_dir, "curves.png")

    recs = load_records(record_dir)
    train = [r for r in recs if "cost" in r]
    val = [r for r in recs if "val_cost" in r]
    if not train and not val:
        print(f"no records found in {record_dir}")
        return 1

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, axes = plt.subplots(1, 3, figsize=(15, 4))
    if train:
        it = [r["iter"] for r in train]
        axes[0].plot(it, [r["cost"] for r in train], label="train cost")
        axes[1].plot(it, [r["error"] for r in train], label="train err")
        axes[2].plot(it, [r.get("images_per_sec", 0) for r in train],
                     label="img/s")
    if val:
        it = [r["iter"] for r in val]
        axes[0].plot(it, [r["val_cost"] for r in val], "o-", label="val cost")
        axes[1].plot(it, [r["val_error"] for r in val], "o-",
                     label="val top-1 err")
        axes[1].plot(it, [r["val_error_top5"] for r in val], "s--",
                     label="val top-5 err")
    for ax, title in zip(axes, ("cost", "error", "throughput")):
        ax.set_xlabel("iteration")
        ax.set_title(title)
        ax.legend()
        ax.grid(True, alpha=0.3)
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    print(f"wrote {out} ({len(train)} train / {len(val)} val records)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
