#!/usr/bin/env python
"""Plot training records — the reference's offline matplotlib plotting.

Theano-MPI dumped per-rank ``inforec`` record files for offline plotting of
cost/error/throughput curves (SURVEY.md §2.10, §5 'Metrics/observability');
this reads this framework's ``inforec_rank*.jsonl`` (or ``.npy``) dumps from
a record dir — or one record file directly — and writes PNG curves:
cost, error, throughput, and the per-section time breakdown (every bucket
in ``recorder.SECTIONS``, including the round-7/8 ``stage`` and ``compile``
additions — the list is imported, so new buckets plot automatically).

Usage: python scripts/plot_records.py <record_dir_or_file> [out.png]
"""

import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _phases():
    """The canonical section list from utils/telemetry.py, loaded by FILE
    path: the module itself is stdlib-only, but importing it through the
    package would drag jax in via theanompi_tpu/__init__ — this script
    must keep running on jax-less plotting machines (numpy + matplotlib
    only, as before)."""
    path = os.path.join(_REPO, "theanompi_tpu", "utils", "telemetry.py")
    spec = importlib.util.spec_from_file_location("_tmpi_telemetry", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.PHASES


PHASES = _phases()


def _load_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _load_npy(path):
    import numpy as np
    return np.load(path, allow_pickle=True).tolist()


def load_records(path):
    """Records from a directory of per-rank dumps, or from one ``.jsonl`` /
    ``.npy`` file directly.  JSONL wins in a directory (it carries the
    epoch/validation records too); ``.npy`` is the fallback."""
    if os.path.isfile(path):
        return _load_npy(path) if path.endswith(".npy") \
            else _load_jsonl(path)
    recs = []
    for name in sorted(os.listdir(path)):
        if name.startswith("inforec_rank") and name.endswith(".jsonl"):
            recs.extend(_load_jsonl(os.path.join(path, name)))
    if not recs:
        for name in sorted(os.listdir(path)):
            if name.startswith("inforec_rank") and name.endswith(".npy"):
                recs.extend(_load_npy(os.path.join(path, name)))
    return recs


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print(__doc__)
        return 1
    src = argv[0]
    out_dir = src if os.path.isdir(src) else os.path.dirname(src) or "."
    out = argv[1] if len(argv) > 1 else os.path.join(out_dir, "curves.png")

    recs = load_records(src)
    train = [r for r in recs if "cost" in r]
    val = [r for r in recs if "val_cost" in r]
    if not train and not val:
        print(f"no records found in {src}")
        return 1

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, axes = plt.subplots(1, 4, figsize=(20, 4))
    if train:
        it = [r["iter"] for r in train]
        axes[0].plot(it, [r["cost"] for r in train], label="train cost")
        axes[1].plot(it, [r["error"] for r in train], label="train err")
        axes[2].plot(it, [r.get("images_per_sec", 0) for r in train],
                     label="img/s")
        # per-section time breakdown: every recorder bucket with signal
        # (the canonical section list — stage/compile included — comes
        # from telemetry.PHASES, the one source of truth)
        for s in PHASES:
            key = "t_" + s
            ys = [r.get(key, 0.0) for r in train]
            if any(y > 0 for y in ys):
                axes[3].plot(it, ys, label=key)
    if val:
        it = [r["iter"] for r in val]
        axes[0].plot(it, [r["val_cost"] for r in val], "o-", label="val cost")
        axes[1].plot(it, [r["val_error"] for r in val], "o-",
                     label="val top-1 err")
        axes[1].plot(it, [r["val_error_top5"] for r in val], "s--",
                     label="val top-5 err")
    for ax, title in zip(axes, ("cost", "error", "throughput",
                                "time breakdown (s per print window)")):
        ax.set_xlabel("iteration")
        ax.set_title(title)
        if ax.get_legend_handles_labels()[0]:
            ax.legend()
        ax.grid(True, alpha=0.3)
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    print(f"wrote {out} ({len(train)} train / {len(val)} val records)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
