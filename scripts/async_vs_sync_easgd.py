#!/usr/bin/env python
"""Async-island EASGD vs synchronous-cadence EASGD throughput (round-3
verdict weak #5: nothing checked the async mode is even throughput-neutral;
the reference's paper claim was EASGD beating BSP in time-to-accuracy).

Same model, same devices: N sync workers in one lockstep program vs
N/islands-chip islands exchanging with the host center at their own pace.
Reports aggregate samples/sec for each and the ratio.

    TMPI_FORCE_CPU=1 python scripts/async_vs_sync_easgd.py
    (on hardware: needs >= 2 chips for 2 islands; CPU-sim numbers are for
     RELATIVE comparison only — absolute img/s on the sim mean nothing)
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("TMPI_FORCE_CPU"):
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")


def measure_sync(n, batch, steps, sync_freq, model_cfg):
    import jax

    from theanompi_tpu.models.cifar10 import Cifar10_model
    from theanompi_tpu.parallel.exchanger import get_exchanger
    from theanompi_tpu.parallel.mesh import worker_mesh

    mesh = worker_mesh(n)
    cfg = {"mesh": mesh, "size": n, "rank": 0, "verbose": False,
           "batch_size": batch, "sync_freq": sync_freq, **model_cfg}
    m = Cifar10_model(cfg)
    exch = get_exchanger("easgd", cfg)
    m.compile_iter_fns(exch)
    m.data.shuffle_data(0)
    for i in range(3):                      # warmup + compile
        m.train_iter(i, None)
        exch.exchange(None, i)
    jax.block_until_ready(m.step_state["params"])
    t0 = time.time()
    for i in range(steps):
        m.train_iter(3 + i, None)
        exch.exchange(None, 3 + i)
        # keep the dispatch queue shallow: a deep async queue of 8-partition
        # programs can starve a CPU-backend collective rendezvous past its
        # 40s termination timeout (observed); the async islands block at
        # every exchange anyway, so this keeps the comparison symmetric
        jax.block_until_ready(m.step_state["params"])
    dt = time.time() - t0
    return steps * batch * n / dt           # global samples/sec


def measure_async(n, islands, batch, seconds, sync_freq, model_cfg):
    from theanompi_tpu.models.cifar10 import Cifar10_model
    from theanompi_tpu.parallel.async_easgd import AsyncEASGDTrainer

    def factory(cfg):
        return Cifar10_model(cfg)

    tr = AsyncEASGDTrainer(factory, {
        "async_islands": islands, "sync_freq": sync_freq, "n_workers": n,
        "batch_size": batch, "verbose": False, **model_cfg})
    # islands compile inside the measured window unless warmed: start, wait
    # for every island's first exchanges (compile included), THEN time.
    tr.start()
    deadline = time.time() + 600
    while (min((r.exchanges_done for r in tr.islands), default=0) < 1
           and time.time() < deadline):
        time.sleep(0.05)
    base = [r.steps_done for r in tr.islands]
    t0 = time.time()
    time.sleep(seconds)
    steps = sum(r.steps_done - b for r, b in zip(tr.islands, base))
    dt = time.time() - t0
    tr.stop_and_join(timeout=120)
    per_island_chips = n // islands
    return steps * batch * per_island_chips / dt   # aggregate samples/sec


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--islands", type=int, default=2)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--seconds", type=float, default=15.0)
    p.add_argument("--sync-freq", type=int, default=4)
    p.add_argument("--out", default="async_vs_sync_easgd.json")
    args = p.parse_args(argv)

    import jax
    model_cfg = {"synthetic_train": 64 * args.devices,
                 "synthetic_val": 32, "compute_dtype": "float32"}
    platform = jax.devices()[0].platform
    sync_sps = measure_sync(args.devices, args.batch, args.steps,
                            args.sync_freq, model_cfg)
    async_sps = measure_async(args.devices, args.islands, args.batch,
                              args.seconds, args.sync_freq, model_cfg)
    out = {"platform": platform, "devices": args.devices,
           "islands": args.islands, "batch_per_chip": args.batch,
           "sync_easgd_samples_per_sec": round(sync_sps, 1),
           "async_islands_samples_per_sec": round(async_sps, 1),
           "async_over_sync": round(async_sps / sync_sps, 3),
           "note": ("aggregate samples/sec, same devices; CPU-sim numbers "
                    "are relative-only" if platform == "cpu" else
                    "aggregate samples/sec, same devices")}
    print(json.dumps(out))
    with open(args.out, "w") as f:
        f.write(json.dumps(out) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
