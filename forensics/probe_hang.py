"""Wedge-forensics probe: touch the axon backend with faulthandler armed.

Dumps all-thread Python stacks to stderr after 45s and 90s if still
alive, so a wedged jax.devices() leaves its own trace. Run under
`timeout -s KILL 120` from the watcher/forensics harness."""
import faulthandler, sys, os, time
faulthandler.enable()
faulthandler.dump_traceback_later(45, repeat=True, file=sys.stderr)
print("probe pid", os.getpid(), flush=True)
t0 = time.time()
import jax
print("jax imported at", round(time.time()-t0, 1), flush=True)
ds = jax.devices()
print("devices:", ds, "at", round(time.time()-t0, 1), flush=True)
