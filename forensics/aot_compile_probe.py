#!/usr/bin/env python
"""Wedge-independent proof that the LOCAL compile venue works (WEDGE.md §4).

The `-lc` matrix rows flip `PALLAS_AXON_REMOTE_COMPILE=0`, moving XLA
compilation from the remote terminal (the suspected wedge trigger) to the
baked local libtpu.  A wedged tunnel blocks the *client* (claim leg), but
the compile engine itself needs no tunnel: this probe builds a TPU v5e
topology description (`jax.experimental.topologies`, local libtpu,
TPU_SKIP_MDS_QUERY=1), lowers a representative training step (conv
forward+backward + cross-worker pmean inside shard_map over a 4-chip
mesh), compiles it for v5e ON THIS CPU HOST, and serializes the
executable.

Measured 2026-07-31 (this box, 1 vCPU, tunnel wedged the whole time):
    mesh: {'workers': 4} -> lowered 0.1 s
    COMPILED conv train step for v5e on this host: 9.1 s
    serialized executable: 1,624,747 bytes

So the -lc rows' compile path is proven reachable and fast enough; only
executable load/execution needs the (healthy) tunnel client.

Run under a killable timeout like every jax-touching probe on this host
(the wedged tunnel hangs any accidental backend touch forever):

    timeout -s KILL 420 python forensics/aot_compile_probe.py

faulthandler is armed below as well, so a hang leaves its own stack.
"""

import faulthandler
import os
import sys
import time

os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
faulthandler.enable()
faulthandler.dump_traceback_later(120, repeat=True, file=sys.stderr)

import numpy as np                                  # noqa: E402
import jax                                          # noqa: E402
import jax.numpy as jnp                             # noqa: E402
from jax.experimental import topologies             # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P   # noqa: E402
from theanompi_tpu.jax_compat import shard_map as _shard_map  # noqa: E402


def main() -> int:
    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x2x1")
    mesh = Mesh(np.array(topo.devices).reshape(4), ("workers",))
    print("mesh:", dict(mesh.shape), flush=True)

    def conv_loss(w, x, y):
        h = jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return jnp.mean((h - y) ** 2)

    def train_step(w, x, y):
        def body(w, x, y):
            loss, g = jax.value_and_grad(conv_loss)(w, x, y)
            g = jax.lax.pmean(g, "workers")
            return w - 0.01 * g, loss[None]
        w2, loss = _shard_map(body, mesh=mesh,
                                 in_specs=(P(), P("workers"), P("workers")),
                                 out_specs=(P(), P("workers")))(w, x, y)
        return w2, loss.mean()

    w = jax.ShapeDtypeStruct((3, 3, 64, 64), jnp.bfloat16)
    x = jax.ShapeDtypeStruct((32, 56, 56, 64), jnp.bfloat16)
    y = jax.ShapeDtypeStruct((32, 56, 56, 64), jnp.bfloat16)
    t0 = time.time()
    lowered = jax.jit(train_step).lower(w, x, y)
    print("lowered", round(time.time() - t0, 1), "s", flush=True)
    t0 = time.time()
    compiled = lowered.compile()
    print("COMPILED conv train step for v5e on this host:",
          round(time.time() - t0, 1), "s", flush=True)
    from jax.experimental.serialize_executable import serialize
    payload, _, _ = serialize(compiled)
    print("serialized executable:", len(payload), "bytes", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
