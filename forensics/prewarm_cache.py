#!/usr/bin/env python
"""PROMOTED to ``scripts/prewarm_cache.py`` (round 8) — this shim forwards.

The round-5 forensic experiment this file held (compile the staged matrix
rows for v5e off-line, hope the opaque XLA persistent-cache key matches in
the hardware window) is superseded: the promoted script serializes the
compiled executables through ``theanompi_tpu/utils/compile_cache.py`` —
the same content-addressed store ``compile_iter_fns`` and ``bench.py``
read — under a key the repo controls, and its row list comes from
``scripts/rows.py`` (shared with the matrix scripts) instead of a
hand-synced CONFIGS copy.  The round-5 measurements (all seven staged
programs compiled on the 1-vCPU host, 26–270 s each, tunnel wedged
throughout) are recorded in WEDGE.md and in the promoted script's
docstring.

Historical invocation still works and now prewarms the REAL store:

    timeout -s KILL 3000 python -u forensics/prewarm_cache.py
"""

import os
import runpy
import sys

if __name__ == "__main__":
    sys.argv = [sys.argv[0], "--rows", "heavy",
                "--platform", "topology:v5e:2x2x1"] + sys.argv[1:]
    runpy.run_path(os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "prewarm_cache.py"),
        run_name="__main__")
