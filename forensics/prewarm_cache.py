#!/usr/bin/env python
"""Pre-compile the heavy matrix-row programs for TPU v5e WITHOUT the tunnel.

Builds each staged model's real train-step program over a topology-AOT
v5e mesh (local libtpu; see forensics/aot_compile_probe.py for the
engine proof) and compiles it with the bench's persistent compile cache
dir configured.  IF the runtime's cache key for the same program matches
(same platform 'tpu', same serialized HLO, same jax version — the open
variable is the terminal's libtpu/platform_version string), the first
healthy tunnel window skips straight past the wedge-correlated compiles
to the measurements.  If the keys don't match, the extra cache entries
are simply ignored — the experiment cannot make anything worse.

Measured 2026-07-31 (tunnel wedged throughout): all seven staged
programs compiled for v5e on this 1-vCPU host — alexnet-b128[-spc4]
~47 s each, alexnet-b256-spc4 57-67 s, vgg16-b32 119 s, resnet50-b32
186 s, googlenet-b32 247-270 s, cifar10-b128 26 s — and cache entries
were written (/tmp/jax_bench_cache 3 -> 18 files).  Caveat, observed:
a topology-AOT RE-run recompiles at full cost with the entry count
stable, i.e. this venue's own cache READ path does not hit; whether the
axon runtime's compile reads these entries is unresolved until a
healthy window (runtime->runtime caching is the r4-proven path).  Risk
either way: none.

Run under a killable timeout (repo probe convention — a stray backend
touch on the wedged tunnel hangs forever; faulthandler armed):

    timeout -s KILL 3000 python -u forensics/prewarm_cache.py

Writes one status line per config; a per-config failure skips to the
next (shapes/dtypes must mirror bench.py's call exactly for a key to be
useful, but a mismatch only wastes the entry).
"""

import faulthandler
import os
import sys
import time

os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
faulthandler.enable()
faulthandler.dump_traceback_later(600, repeat=True, file=sys.stderr)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np                                   # noqa: E402
import jax                                           # noqa: E402

# host-side array work (param init, synthetic batches) must run on the
# CPU backend — the axon default would hang on the wedged tunnel
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("BENCH_COMPILE_CACHE",
                                 "/tmp/jax_bench_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_default_prng_impl", "rbg")   # bench default

import jax.numpy as jnp                              # noqa: E402
from jax.experimental import topologies              # noqa: E402
from jax.sharding import Mesh                        # noqa: E402

from theanompi_tpu.models.registry import MODELS     # noqa: E402
from theanompi_tpu.parallel import steps             # noqa: E402
from theanompi_tpu.parallel.exchanger import get_exchanger  # noqa: E402
from theanompi_tpu.parallel.mesh import WORKER_AXIS  # noqa: E402

# (label, model, batch override, steps_per_call, extra config) — the
# wedge-correlated heavy compiles first (they are what a short window
# cannot afford).  Mirrors scripts/perf_matrix_r5.sh: spc8 rows carry
# synthetic_batches=8 (BENCH_SYNTH_BATCHES=8 there), the bnbf16 rows the
# bn_norm_dtype lever; the spc=1 b256 entry exists for bench.py's
# spc>1 MFU flop-count compile (a cache hit only if the spc=1 program
# for the same batch is already compiled).
CONFIGS = [
    ("alexnet-b128-spc4", "alexnet", None, 4, {}),
    ("alexnet-b128", "alexnet", None, 1, {}),
    ("vgg16-b32", "vgg16", None, 1, {}),
    ("resnet50-b32", "resnet50", None, 1, {}),
    ("googlenet-b32", "googlenet", None, 1, {}),
    ("alexnet-b256-spc4", "alexnet", 256, 4, {}),
    ("alexnet-b256", "alexnet", 256, 1, {}),
    ("cifar10-b128", "cifar10", None, 1, {}),
    # spc8 scan bodies — the biggest programs per model
    ("alexnet-b128-spc8", "alexnet", None, 8, {"synthetic_batches": 8}),
    ("googlenet-b32-spc8", "googlenet", None, 8, {"synthetic_batches": 8}),
    ("resnet50-b32-spc8", "resnet50", None, 8, {"synthetic_batches": 8}),
    ("resnet50-b32-spc8-bnbf16", "resnet50", None, 8,
     {"synthetic_batches": 8, "bn_norm_dtype": "bfloat16"}),
    # bf16-BN lever + batch-headroom rows
    ("resnet50-b32-bnbf16", "resnet50", None, 1,
     {"bn_norm_dtype": "bfloat16"}),
    ("resnet50-b64", "resnet50", 64, 1, {}),
    ("resnet50-b128", "resnet50", 128, 1, {}),
    ("resnet50-b128-bnbf16", "resnet50", 128, 1,
     {"bn_norm_dtype": "bfloat16"}),
    ("resnet50-b128-spc4", "resnet50", 128, 4, {}),
    ("googlenet-b128", "googlenet", 128, 1, {}),
    ("googlenet-b128-spc4", "googlenet", 128, 4, {}),
    ("vgg16-b64", "vgg16", 64, 1, {}),
    ("vgg16-b32-spc4", "vgg16", None, 4, {}),
]


def sds(tree):
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
        if not hasattr(x, "aval") else
        jax.ShapeDtypeStruct(x.shape, x.dtype), tree)


def prewarm(label, model_name, batch, spc, topo_mesh, cfg_extra) -> str:
    import importlib
    modelfile, modelclass, extra = MODELS[model_name]
    config = {"mesh": topo_mesh, "size": 1, "rank": 0, "verbose": False,
              **extra, **cfg_extra}
    if batch:
        config["batch_size"] = batch
    if spc > 1:
        config["steps_per_call"] = spc
    model = getattr(importlib.import_module(modelfile), modelclass)(config)
    exchanger = get_exchanger("bsp", config)
    exchanger.prepare(topo_mesh, model)

    # mirror compile_iter_fns' state WITHOUT device placement (topology
    # devices are not addressable): abstract avals shaped like the boxed
    # [n_workers=1, ...] state
    unboxed = {"params": model.params,
               "opt_state": model.opt.init(model.params),
               "bn_state": model.bn_state,
               "extra": exchanger.extra_state_template()}
    state_sds = {k: jax.tree.map(
        lambda x: jax.ShapeDtypeStruct((1,) + tuple(np.shape(x)),
                                       np.asarray(x).dtype), v)
        for k, v in unboxed.items()}

    if spc > 1:
        batches = [model.data.next_train_batch(j) for j in range(spc)]
        host = {k: np.stack([np.asarray(b[k]) for b in batches])
                for k in batches[0]}
    else:
        host = {k: np.asarray(v)
                for k, v in model.data.next_train_batch(0).items()}
    batch_sds = sds(host)

    train_fn = steps.build_train_step(topo_mesh, model, exchanger,
                                      n_steps=spc)
    rng_aval = jax.ShapeDtypeStruct((), jax.random.key(0).dtype)
    t0 = time.time()
    lowered = train_fn.lower(state_sds, batch_sds,
                             jax.ShapeDtypeStruct((), jnp.float32),
                             rng_aval, jax.ShapeDtypeStruct((), jnp.int32))
    t_l = time.time() - t0
    t0 = time.time()
    lowered.compile()
    return (f"{label}: lowered {t_l:.1f}s, compiled for v5e in "
            f"{time.time() - t0:.1f}s")


def main() -> int:
    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x2x1")
    topo_mesh = Mesh(np.array(topo.devices[:1]), (WORKER_AXIS,))
    # the topology-AOT venue re-pays full compiles on re-run (its cache
    # read path does not hit — see docstring), so completed labels are
    # tracked in a sidecar and skipped; delete the file to force redo
    done_file = "/tmp/prewarm_done.txt"
    done = set(open(done_file).read().split()) \
        if os.path.exists(done_file) else set()
    for label, model_name, batch, spc, cfg_extra in CONFIGS:
        if label in done:
            print(f"{label}: already prewarmed — skip", flush=True)
            continue
        try:
            print(prewarm(label, model_name, batch, spc, topo_mesh,
                          cfg_extra), flush=True)
            with open(done_file, "a") as f:
                f.write(label + "\n")
        except Exception as e:
            print(f"{label}: FAILED {type(e).__name__}: {str(e)[:300]}",
                  flush=True)
    cache = jax.config.jax_compilation_cache_dir
    n = len(os.listdir(cache)) if os.path.isdir(cache) else 0
    print("cache entries now:", n, "in", cache, flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
