#!/usr/bin/env python
"""FSDP / ZeRO-3 on the TransformerLM: params themselves sharded over the
workers (beyond parity — the reference kept a full replica per GPU).

Each worker persists one ceil(P/N) flat parameter chunk plus the optimizer
and EMA state for that chunk — persistent model memory ÷N per chip.  The
step all-gathers the full tree transiently; the gradient reduce-scatter is
the gather's AD transpose.  Trajectories are bit-equal to plain BSP
(tests/test_fsdp.py), so this is a pure memory lever: flip ``fsdp=True``
off to compare.

Checkpoints are worker-count portable: train on N chips, resume on M —
the chunks re-partition on load.
"""

from _common import setup, n_devices

setup()

from theanompi_tpu import BSP  # noqa: E402

if __name__ == "__main__":
    rule = BSP()
    rule.init(
        devices=n_devices(),
        modelfile="theanompi_tpu.models.transformer_lm",
        modelclass="TransformerLM",
        fsdp=True,
        ema_decay=0.999,         # the shadow tracks the chunk, sharded too
        # sized to run in minutes on the CPU sim too; scale up on real chips
        d_model=128, n_head=4, n_layer=2, seq_len=64, vocab=512,
        batch_size=8,
        synthetic_train=512, synthetic_val=128,
        epochs=1, printFreq=8,
        optimizer="adam", learning_rate=3e-4, lr_schedule="cosine",
        grad_clip=1.0,
        scale_lr=False,
    )
    rec = rule.wait()
    print("final val:", rec.epoch_records[-1])
