#!/usr/bin/env python
"""Switch-style mixture-of-experts LM with expert parallelism.

Every other block's MLP is a top-1 MoE (``parallel/moe.py``); with ``tp=k``
the experts are SHARDED over the 'model' axis (each chip in a group hosts
``moe_experts/k`` experts) while attention stays tensor-parallel on the
same axis.  The Switch load-balance loss rides into the objective with
coefficient ``moe_aux``.
"""

from _common import setup

setup()

from theanompi_tpu import BSP  # noqa: E402

if __name__ == "__main__":
    rule = BSP()
    rule.init(
        devices=4,
        tp=2,                  # = expert-parallel degree
        modelfile="theanompi_tpu.models.transformer_lm",
        modelclass="MoETransformerLM",
        batch_size=16,
        seq_len=128,
        vocab=256,
        d_model=256,
        n_layer=4,
        n_head=8,
        moe_experts=8,
        moe_every=2,
        capacity_factor=1.25,
        moe_aux=0.01,
        epochs=5,
        printFreq=20,
    )
    rule.wait()
