#!/usr/bin/env python
"""WGAN on CIFAR-10 — the reference's late-added GAN family.

The G/D pair trains as ONE compiled SPMD step (stop-gradient decoupled
objectives); the critic's n_critic cadence and weight clipping ride the
postprocess_update hook.  All four exchange rules work on GANs — this uses
BSP so every chip's critic sees the full gradient signal.
"""

from _common import setup, n_devices

setup()

from theanompi_tpu import BSP  # noqa: E402

if __name__ == "__main__":
    rule = BSP()
    rule.init(
        devices=n_devices(),
        modelfile="theanompi_tpu.models.wgan",
        modelclass="WGAN",
        epochs=25,
        n_critic=5,
        clip=0.01,
        printFreq=20,
    )
    rec = rule.wait()
    print("done; G loss column is 'error' in the records")
