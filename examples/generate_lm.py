#!/usr/bin/env python
"""Train a byte-level LM on your own text, then sample from it.

The full text → tokens → train → generate loop in one session script:

  python scripts/make_token_dataset.py mytext.txt --out data/corpus
  TMPI_FORCE_CPU=1 XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/generate_lm.py data/corpus "Once upon a time"

Generation runs the jit-compiled KV-cache sampler on the canonical params
(EMA shadow if ``ema_decay`` is set).  Without arguments it falls back to
the synthetic increment stream and prints the continued number sequence.
"""

import sys

import numpy as np

from _common import setup, n_devices

setup()

from theanompi_tpu import BSP  # noqa: E402

if __name__ == "__main__":
    data_dir = sys.argv[1] if len(sys.argv) > 1 else None
    prompt_text = sys.argv[2] if len(sys.argv) > 2 else None

    kw = dict(data_dir=data_dir, vocab=256) if data_dir else \
        dict(vocab=32, noise=0.0)
    rule = BSP()
    rule.init(
        devices=n_devices(),
        modelfile="theanompi_tpu.models.transformer_lm",
        modelclass="TransformerLM",
        batch_size=16, seq_len=128, d_model=256, n_layer=4, n_head=8,
        learning_rate=3e-3, grad_clip=1.0, lr_schedule="cosine",
        ema_decay=0.999, epochs=5, printFreq=20, **kw)
    rule.wait()

    if data_dir:
        max_new = 64
        raw = np.frombuffer((prompt_text or "The ").encode(),
                            dtype=np.uint8).astype(np.int32)
        # the position table caps prompt+continuation at seq_len — keep the
        # prompt's TAIL rather than dying after training completed
        raw = raw[-(128 - max_new):]
        prompt = raw[None]
        out = rule.model.generate(prompt, max_new_tokens=max_new,
                                  temperature=0.8, seed=0)
        print("PROMPT:", prompt_text)
        print("SAMPLE:", bytes(out[0].astype(np.uint8)).decode(
            errors="replace"))
    else:
        prompt = np.array([[5, 6, 7, 8]], np.int32)
        out = rule.model.generate(prompt, max_new_tokens=12)
        print("prompt", prompt[0].tolist(), "->", out[0].tolist())
