#!/usr/bin/env python
"""BSP on the CIFAR-10 smoke-test CNN — the reference README's quick-start.

Reference session-script shape (SURVEY.md §2.6):

    from theanompi import BSP
    rule = BSP()
    rule.init(devices=['cuda0', 'cuda1'])
    rule.wait()
"""

from _common import setup, n_devices

setup()

from theanompi_tpu import BSP  # noqa: E402

if __name__ == "__main__":
    rule = BSP()
    rule.init(
        devices=n_devices(),
        modelfile="theanompi_tpu.models.cifar10",
        modelclass="Cifar10_model",
        # drop the two lines below to train on real CIFAR-10 via
        # config['data_dir'] — synthetic keeps the example self-contained
        synthetic_train=2048,
        synthetic_val=512,
        epochs=3,
        printFreq=10,
        # the reference's linear-LR-scaling contract multiplies the model's
        # base lr by the worker count; at 8 workers that needs a cooler base
        # (the reference tuned per-run — no warmup schedule existed in 2016)
        learning_rate=0.01,
        scale_lr=False,
    )
    rec = rule.wait()
    print("final val:", rec.epoch_records[-1])
