#!/usr/bin/env python
"""VGG-16 with elastic averaging (EASGD) — BASELINE.json staged config #3.

Every ``sync_freq`` iterations each worker does the elastic pairwise update
with the center parameters (worker ← worker − α(worker − center);
center ← center + α·mean(worker − center)); between syncs workers train
independently on their shards, which is EASGD's exploration benefit.
Validation scores the CENTER parameters, as the reference's server did.
"""

import os

from _common import setup, n_devices

setup()

from theanompi_tpu import EASGD  # noqa: E402

if __name__ == "__main__":
    rule = EASGD()
    rule.init(
        devices=n_devices(),
        modelfile="theanompi_tpu.models.vggnet_16",
        modelclass="VGGNet_16",
        data_dir=os.environ.get("IMAGENET_DIR"),
        sync_freq=8,
        alpha=0.5,
        para_load=True,
        epochs=70,
        printFreq=20,
    )
    rec = rule.wait()
    print("final val:", rec.epoch_records[-1])
