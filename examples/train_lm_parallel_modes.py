#!/usr/bin/env python
"""One TransformerLM, every parallelism axis: dp / tp / pp / sp / tp×pp.

The reference (Theano-MPI) is pure data parallelism; this session shows the
same 3-call rule API driving the model-parallel meshes (`parallel/tp.py`,
`parallel/pipeline.py`, `parallel/sp.py`):

* ``tp=k``   — Megatron-style tensor parallelism over a 'model' axis
               (head-sharded attention, column/row-parallel MLP,
               vocab-parallel embedding + loss)
* ``pp=k``   — GPipe pipeline over a 'pipe' axis (stacked block params,
               microbatch streaming via ppermute)
* ``sp=k``   — sequence parallelism over a 'seq' axis (ring attention;
               batch leaves placed [workers, seq])
* ``tp`` + ``pp`` together — a 3-D dp×pipe×model mesh
* ``tp`` + ``sp`` together — a 3-D dp×seq×model mesh (head-sharded ring
               attention: long context AND wide model at once)

Pick a mode with MODE=dp|tp|pp|sp|tp_pp|tp_sp (default tp).  ``devices``
counts DATA-PARALLEL groups: devices=2 with tp=2, pp=2 uses 8 chips.
"""

import os

from _common import setup

setup()

MODES = {
    "dp":    dict(devices=8),
    "tp":    dict(devices=4, tp=2),
    "pp":    dict(devices=2, pp=4, pp_microbatches=8),
    "sp":    dict(devices=2, sp=4),
    "tp_pp": dict(devices=2, tp=2, pp=2, pp_microbatches=8),
    "tp_sp": dict(devices=2, tp=2, sp=2),
}

from theanompi_tpu import BSP  # noqa: E402

if __name__ == "__main__":
    mode = os.environ.get("MODE", "tp")
    if mode not in MODES:
        import sys
        sys.exit(f"MODE must be one of {sorted(MODES)}; got {mode!r}")
    rule = BSP()
    rule.init(
        modelfile="theanompi_tpu.models.transformer_lm",
        modelclass="TransformerLM",
        batch_size=16,
        seq_len=128,
        vocab=256,
        d_model=256,
        n_layer=4,
        n_head=8,
        epochs=5,
        printFreq=20,
        **MODES[mode],
    )
    rule.wait()
