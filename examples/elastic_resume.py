#!/usr/bin/env python
"""Elastic resume: train on N workers, stop, resume on M — a capability
the reference could not offer (`mpirun -np N` was fixed for a job's life;
its checkpoints were per-rank).

Checkpoints are worker-count portable for the BSP / ZeRO-1 / FSDP
layouts: BSP grads-mode state dedups to one replica; ZeRO-1 optimizer
chunks and FSDP parameter chunks re-partition on load (the chunk layout
is recorded in the checkpoint meta).  Per-worker exchange-strategy state
(onebit/topk/powersgd error-feedback buffers, async diverged replicas)
has NO refit path — resuming such a run on a different worker count
raises a targeted error from ``load()`` (round-4 ADVICE #3).  This
script trains 1 epoch on 8 workers with FSDP + adam, checkpoints,
rebuilds on 4 workers, resumes, and shows the val accuracy carrying
over.
"""

import os
import shutil
import tempfile

from _common import setup

setup()

from theanompi_tpu import BSP  # noqa: E402


def run(devices, epochs, ckpt_dir, resume):
    rule = BSP()
    rule.init(devices=devices,
              modelfile="theanompi_tpu.models.cifar10",
              modelclass="Cifar10_model",
              fsdp=True, optimizer="adam", learning_rate=1e-3,
              synthetic_train=2048, synthetic_val=512, batch_size=16,
              epochs=epochs, printFreq=32,
              ckpt_dir=ckpt_dir, resume=resume,
              compute_dtype="float32", scale_lr=False)
    rec = rule.wait()
    print(f"[{devices} workers] last val:", rec.epoch_records[-1])
    return rec


if __name__ == "__main__":
    d = os.environ.get("CKPT_DIR") or tempfile.mkdtemp(prefix="elastic_")
    try:
        print("== phase 1: 8 workers, FSDP chunks = 1/8 of the params each")
        run(8, epochs=1, ckpt_dir=d, resume=False)
        print("== phase 2: resume the SAME training on 4 workers "
              "(chunks re-partition on load)")
        run(4, epochs=2, ckpt_dir=d, resume=True)
    finally:
        if not os.environ.get("CKPT_DIR"):
            shutil.rmtree(d, ignore_errors=True)
