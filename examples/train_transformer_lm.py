#!/usr/bin/env python
"""Causal transformer LM under BSP — the beyond-parity sequence model.

Runs on the synthetic next-token stream with zero data setup; pass
``data_dir=/path/to/corpus`` holding nanoGPT-style ``train.bin``/``val.bin``
flat token files (``token_dtype`` defaults to uint16) to train on a real,
memory-mapped corpus (``models/data/tokens.py``).  The sequence-SHARDED
long-context path is ``ops/ring_attention.py`` on a 2-D data×seq mesh;
this session trains data-parallel like any zoo model.
"""

from _common import setup, n_devices

setup()

from theanompi_tpu import BSP  # noqa: E402

if __name__ == "__main__":
    rule = BSP()
    rule.init(
        devices=n_devices(),
        modelfile="theanompi_tpu.models.transformer_lm",
        modelclass="TransformerLM",
        batch_size=16,
        seq_len=128,
        vocab=256,
        d_model=256,
        n_layer=4,
        n_head=8,
        epochs=10,
        printFreq=20,
        async_ckpt=True,
        ckpt_dir="./ckpt_lm",
    )
    rule.wait()
