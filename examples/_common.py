"""Shared bring-up for the example session scripts."""

import os
import sys

# runnable from anywhere without installing the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def setup():
    """Force the simulated CPU mesh when TMPI_FORCE_CPU=1 (for machines
    without TPU chips) — must run before the first jax backend touch."""
    if os.environ.get("TMPI_FORCE_CPU"):
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        import jax
        jax.config.update("jax_platforms", "cpu")


def n_devices(default=None):
    import jax
    return default or len(jax.devices())
