"""Shared bring-up for the example session scripts."""

import os
import subprocess
import sys
import time

# runnable from anywhere without installing the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# per-user path (round-4 ADVICE): a fixed shared /tmp name would let
# another user pre-create it (poisoning the cached verdict for the TTL)
# and collide two users' probe writes
_PROBE_CACHE = f"/tmp/tmpi_backend_probe.{os.getuid()}"
_PROBE_TTL_S = 600


def _backend_answers(timeout_s: float = 60.0) -> bool:
    """True when the accelerator backend initializes — probed in a KILLABLE
    subprocess, because a wedged TPU tunnel hangs every in-process
    ``jax.devices()`` call indefinitely (this environment's failure mode;
    see bench.py's wrapper).  The verdict is cached briefly so a sweep of
    example runs pays one probe, not one per script."""
    try:
        st = os.stat(_PROBE_CACHE)
        # trust only our OWN cache file: /tmp is world-writable, so a
        # pre-created file by another uid could poison the verdict (and
        # our overwrite of it would fail silently below)
        if st.st_uid == os.getuid() and \
                time.time() - st.st_mtime < _PROBE_TTL_S:
            return open(_PROBE_CACHE).read().strip() == "ok"
    except OSError:
        pass
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, timeout=timeout_s)
        ok = r.returncode == 0
    except subprocess.TimeoutExpired:
        ok = False
    try:
        # write via a private temp file + rename: open(path, "w") on a
        # predictable /tmp name would follow a pre-planted symlink and
        # truncate whatever it points at; os.replace swaps the NAME
        # (replacing any symlink) without ever writing through it
        import tempfile
        fd, tmp = tempfile.mkstemp(prefix=_PROBE_CACHE + ".")
        with os.fdopen(fd, "w") as f:
            f.write("ok" if ok else "dead")
        os.replace(tmp, _PROBE_CACHE)
    except OSError:
        pass
    return ok


def _force_cpu_mesh() -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")


def setup():
    """Pick the backend BEFORE the first jax touch: honor TMPI_FORCE_CPU=1
    (simulated 8-device CPU mesh), otherwise probe the accelerator in a
    killable subprocess and fall back to the CPU mesh with a warning when
    it hangs or fails — an example script should never hang silently on a
    wedged tunnel."""
    if os.environ.get("TMPI_FORCE_CPU"):
        _force_cpu_mesh()
        return
    if not _backend_answers():
        print("[examples] accelerator backend did not answer (wedged "
              "tunnel?) — falling back to the simulated 8-device CPU mesh",
              file=sys.stderr)
        _force_cpu_mesh()


def n_devices(default=None):
    import jax
    return default or len(jax.devices())
