#!/usr/bin/env python
"""AlexNet with GENUINELY asynchronous EASGD — worker islands.

``easgd_mode='async'`` partitions the visible chips into independent
islands, each running its own compiled SPMD program from its own host
thread; the elastic center lives host-side (the TPU-native analogue of the
reference's EASGD *server process*, ``easgd_server.py``).  A straggler
island never blocks the others — the property the in-mesh synchronous
cadence cannot express.

``wait()`` returns the AsyncEASGDTrainer (island/center progress stats)
rather than a per-iteration Recorder: the islands run headless.
"""

import os

from _common import setup, n_devices

setup()

from theanompi_tpu import EASGD  # noqa: E402

if __name__ == "__main__":
    rule = EASGD()
    rule.init(
        devices=n_devices(),
        modelfile="theanompi_tpu.models.alex_net",
        modelclass="AlexNet",
        data_dir=os.environ.get("IMAGENET_DIR"),
        easgd_mode="async",
        async_islands=2,        # islands of n_devices/2 chips each
        sync_freq=8,            # local steps between island<->center syncs
        alpha=0.5,
        run_seconds=float(os.environ.get("RUN_SECONDS", 300)),
        batch_size=128,
    )
    trainer = rule.wait()
    print(trainer.stats())
    trainer.save("./inc")
