#!/usr/bin/env python
"""Asynchronous EASGD/ASGD across PROCESSES — one shared center over TCP.

The reference ran a dedicated MPI server rank holding the EASGD center;
worker NODES exchanged with it at their own pace.  This session reproduces
that topology without MPI (``parallel/center_server.py``): the first
process serves the center, every other process joins it by address — each
an independent JAX runtime (its own chips, its own compiled programs),
coupled only by the socket.

One-machine demo on the simulated mesh (two terminals, or `&`):

  # terminal 1 — serve the center AND train one island
  TMPI_FORCE_CPU=1 ROLE=server CENTER_PORT=47555 \\
      python examples/train_async_multiprocess.py

  # terminal 2 — a second process joins the same center (ISLAND_BASE must
  # clear the server's islands: it runs ids 0..ISLANDS-1, so base = 2)
  TMPI_FORCE_CPU=1 ROLE=worker CENTER_ADDR=127.0.0.1:47555 ISLAND_BASE=2 \\
      python examples/train_async_multiprocess.py

On a real pod, run ROLE=server on one host and ROLE=worker (with
CENTER_ADDR=<server-host>:<port>) on the rest.  RULE=asgd selects the
downpour exchange (accumulate ``sync_freq`` steps, ship the delta, reset
to the returned center) instead of the elastic one.
"""

import os

from _common import setup, n_devices

setup()

from theanompi_tpu import ASGD, EASGD  # noqa: E402

if __name__ == "__main__":
    role = os.environ.get("ROLE", "server")
    rule_name = os.environ.get("RULE", "easgd").lower()
    rule = (ASGD if rule_name == "asgd" else EASGD)()
    kw = dict(
        devices=n_devices(),
        modelfile="theanompi_tpu.models.cifar10",
        modelclass="Cifar10_model",
        async_islands=int(os.environ.get("ISLANDS", 2)),
        island_base=int(os.environ.get("ISLAND_BASE", 0)),
        sync_freq=4,
        run_seconds=float(os.environ.get("RUN_SECONDS", 30)),
        batch_size=32,
        synthetic_train=4096,
    )
    kw["easgd_mode" if rule_name == "easgd" else "asgd_mode"] = "async"
    if role == "server":
        kw.update(center_serve=True,
                  center_port=int(os.environ.get("CENTER_PORT", 0)),
                  # keep serving after this process's islands finish so
                  # late workers can still drain their exchanges
                  center_keep_serving=bool(os.environ.get("KEEP_SERVING")))
    else:
        kw.update(center_addr=os.environ["CENTER_ADDR"])
    rule.init(**kw)
    trainer = rule.wait()
    if role == "server" and hasattr(trainer, "center_address"):
        print("center served at", trainer.center_address, flush=True)
    print(trainer.stats())
    trainer.save("./inc")
    if role == "server" and os.environ.get("KEEP_SERVING"):
        # outlive this process's own islands so late workers (first compile
        # can take tens of seconds) finish their exchanges
        import time
        extra = float(os.environ.get("SERVE_EXTRA", 90))
        print(f"serving the center {extra:.0f}s more for late workers",
              flush=True)
        time.sleep(extra)
        print("final:", trainer.center.updates_by_island)
