#!/usr/bin/env python
"""Wire-strategy sweep: one model, every exchange strategy, side by side.

The TPU counterpart of the reference paper's strategy comparison tables
(``Exch_allreduce`` vs ``asa32`` vs ``asa16`` vs NCCL — SURVEY.md §2.3/§6):
trains a few iterations of CIFAR-10 BSP under each strategy and prints
images/sec and the final cost so both the perf and the numerics are visible.
"""

import sys
import time

from _common import setup, n_devices

setup()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from theanompi_tpu.models.cifar10 import Cifar10_model  # noqa: E402
from theanompi_tpu.parallel import steps  # noqa: E402
from theanompi_tpu.parallel.exchanger import BSP_Exchanger  # noqa: E402
from theanompi_tpu.parallel.mesh import worker_mesh  # noqa: E402

STRATEGIES = ["allreduce", "nccl16", "ring", "asa16", "onebit", "topk",
              "powersgd2"]
ITERS, WARMUP = 20, 5

if __name__ == "__main__":
    if len(sys.argv) > 1:       # e.g. python strategy_sweep.py ring onebit
        STRATEGIES = sys.argv[1:]
    mesh = worker_mesh(n_devices())
    n = mesh.shape["workers"]
    for name in STRATEGIES:
        config = {"mesh": mesh, "size": n, "verbose": False,
                  "synthetic_train": 4096, "exch_strategy": name}
        model = Cifar10_model(config)
        model.compile_iter_fns(BSP_Exchanger(config))
        batch = model.data.next_train_batch(0)
        dev = steps.put_batch(mesh, batch)
        lr, rng = jnp.float32(model.current_lr), jax.random.key(0)
        st = model.step_state
        for i in range(WARMUP):
            st, cost, err = model.train_fn(st, dev, lr, rng, jnp.int32(i))
        jax.block_until_ready(st["params"])
        t0 = time.time()
        for i in range(ITERS):
            st, cost, err = model.train_fn(st, dev, lr, rng,
                                           jnp.int32(WARMUP + i))
        jax.block_until_ready(st["params"])
        ips = batch["y"].shape[0] * ITERS / (time.time() - t0)
        print(f"{name:>10}: {ips:10.0f} img/s   cost {float(jnp.mean(cost)):.4f}")
