#!/usr/bin/env python
"""ResNet-50 with gossip SGD (GoSGD) — BASELINE.json staged config #4.

Per iteration each worker draws Bernoulli(exch_prob); senders ship
(α/2·params, α/2) to a random peer over a shared ``lax.ppermute`` ring-shift
and receivers merge by weighted average.  No barrier, no server — the
mixing-weight invariant Σα = n_workers is conserved exactly.
"""

import os

from _common import setup, n_devices

setup()

from theanompi_tpu import GOSGD  # noqa: E402

if __name__ == "__main__":
    rule = GOSGD()
    rule.init(
        devices=n_devices(),
        modelfile="theanompi_tpu.models.resnet50",
        modelclass="ResNet50",
        data_dir=os.environ.get("IMAGENET_DIR"),
        exch_prob=0.25,
        para_load=True,
        epochs=90,
        printFreq=20,
    )
    rec = rule.wait()
    print("final val:", rec.epoch_records[-1])
