#!/usr/bin/env python
"""AlexNet ImageNet BSP — the paper's main benchmark configuration.

Expects ``$IMAGENET_DIR`` (or edit data_dir below) with the reference's
on-disk layout: ``train_hkl/`` and ``val_hkl/`` of 128-image uint8 batch
files, ``train_labels.npy`` / ``val_labels.npy``, ``img_mean.npy``
(SURVEY.md §2.8).  Without it, synthetic batches keep the script runnable
for throughput measurement.
"""

import os

from _common import setup, n_devices

setup()

from theanompi_tpu import BSP  # noqa: E402

if __name__ == "__main__":
    rule = BSP()
    rule.init(
        devices=n_devices(),
        modelfile="theanompi_tpu.models.alex_net",
        modelclass="AlexNet",
        data_dir=os.environ.get("IMAGENET_DIR"),
        para_load=True,              # background prefetch (≙ reference flag)
        aug_per_image=True,          # upgrade over the per-batch ref augment
        exch_strategy="allreduce",   # try: ring, asa16, onebit, topk
        ckpt_dir="./snapshots/alexnet",
        record_dir="./inc/alexnet",
        prng_impl="rbg",
        epochs=70,
        printFreq=40,
    )
    rec = rule.wait()
    print("final val:", rec.epoch_records[-1])
