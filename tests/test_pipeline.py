"""Pipeline parallelism (parallel/pipeline.py): GPipe microbatch pipelining
over a 'pipe' mesh axis must compute the SAME model as the dense layout —
same init (stacked from the same per-layer keys), same losses and updates up
to fp32 summation-order noise.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from theanompi_tpu.models.transformer_lm import TransformerLM
from theanompi_tpu.parallel.exchanger import BSP_Exchanger
from theanompi_tpu.parallel.mesh import PIPE_AXIS, WORKER_AXIS, worker_mesh
from theanompi_tpu.jax_compat import shard_map
from theanompi_tpu.parallel.pipeline import (microbatch, pipeline_apply,
                                             unmicrobatch)

LM_CFG = dict(verbose=False, batch_size=8, seq_len=16, vocab=32,
              synthetic_train=64, synthetic_val=32,
              d_model=32, n_head=4, n_layer=4, compute_dtype=jnp.float32)


def _make(dp, pp, **kw):
    mesh = worker_mesh(dp, pp=pp)
    cfg = {**LM_CFG, "mesh": mesh, "size": dp, "rank": 0, "pp": pp, **kw}
    return TransformerLM(cfg)


def _train_steps(model, n_steps):
    exch = BSP_Exchanger(model.config)
    model.compile_iter_fns(exch)
    model.data.shuffle_data(0)
    costs = []
    for i in range(n_steps):
        model.train_iter(i, None)
        costs.append(float(model.current_info["cost"]))
    return costs


def test_pipeline_apply_matches_sequential():
    """The raw pipeline primitive on a pure 'pipe' mesh vs a sequential scan
    of the same stacked layers — forward AND gradient."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    pp, L, m, mb, d = 4, 4, 8, 2, 16
    mesh = Mesh(np.asarray(jax.devices()[:pp]), (PIPE_AXIS,))
    r = np.random.RandomState(0)
    stack = jnp.asarray(0.3 * r.randn(L, d, d).astype(np.float32))
    x = jnp.asarray(r.randn(m * mb, d).astype(np.float32))

    def layer(w, h):
        return jnp.tanh(h @ w)

    def stage_fn(st, h):
        def body(hh, w):
            return layer(w, hh), None
        hh, _ = lax.scan(body, h, st)
        return hh

    def pipe_loss(stack, x):
        y = pipeline_apply(stage_fn, stack, microbatch(x, m))
        return jnp.sum(unmicrobatch(y) ** 2)

    def seq_loss(stack, x):
        return jnp.sum(stage_fn(stack, x) ** 2)

    def f(stack, x):
        cost, g = jax.value_and_grad(pipe_loss)(stack, x)
        return cost, g

    sm = jax.jit(shard_map(f, mesh=mesh,
                               in_specs=(P(PIPE_AXIS), P()),
                               out_specs=(P(), P(PIPE_AXIS))))
    cost, grad = sm(jax.device_put(stack, NamedSharding(mesh, P(PIPE_AXIS))),
                    jax.device_put(x, NamedSharding(mesh, P())))
    cost_ref, grad_ref = jax.value_and_grad(seq_loss)(stack, x)
    assert float(cost) == pytest.approx(float(cost_ref), rel=1e-5)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(grad_ref),
                               rtol=1e-4, atol=1e-6)


def test_pp_init_identical_to_dense(mesh8):
    dense = _make(dp=2, pp=1)
    pp = _make(dp=2, pp=4)
    stacked = pp.params["blocks"]
    for i, blk in enumerate(dense.blocks):
        jax.tree.map(lambda s, d: np.testing.assert_array_equal(
            np.asarray(s[i]), np.asarray(d)),
            stacked, dense.params[blk.name])


def test_pp_bsp_training_matches_dense(mesh8):
    dense = _make(dp=2, pp=1)
    pp = _make(dp=2, pp=4)
    c_dense = _train_steps(dense, 6)
    c_pp = _train_steps(pp, 6)
    np.testing.assert_allclose(c_pp, c_dense, rtol=2e-4, atol=2e-5)


def test_pp_mesh_and_sharding(mesh8):
    pp = _make(dp=2, pp=4)
    assert dict(pp.mesh.shape) == {WORKER_AXIS: 2, PIPE_AXIS: 4}
    pp.compile_iter_fns(BSP_Exchanger(pp.config))
    w = pp.step_state["params"]["blocks"]["fc1"]["w"]
    assert w.sharding.spec == (WORKER_AXIS, PIPE_AXIS), w.sharding.spec
    # one device holds [1 worker, 1 layer, d, 4d]
    assert w.addressable_shards[0].data.shape == (1, 1, 32, 128)


def test_pp_val_and_checkpoint(tmp_path, mesh8):
    from theanompi_tpu.parallel import steps
    pp = _make(dp=2, pp=4)
    _train_steps(pp, 3)
    pp.begin_val()
    pp.val_iter(0, None)
    pp.end_val()
    pp.save(str(tmp_path), epoch=0, count=3)
    before = jax.device_get(steps.tree_to_host(pp.step_state["params"]))
    pp2 = _make(dp=2, pp=4)
    pp2.compile_iter_fns(BSP_Exchanger(pp2.config))
    assert pp2.load(str(tmp_path)) == 0
    after = jax.device_get(steps.tree_to_host(pp2.step_state["params"]))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), before, after)


def test_pp_microbatch_divisibility_asserts(mesh8):
    with pytest.raises(AssertionError, match="divisible"):
        microbatch(jnp.zeros((10, 4)), 4)


# -- interleaved virtual stages (round 10, ISSUE 16) ------------------------

def test_pipeline_apply_interleaved_matches_v1():
    """The raw primitive at v=2 computes the same function as v=1: same
    forward cost, same gradients (modulo the stage-permuted parameter
    layout interleaving requires — rows map through stage_permutation)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from theanompi_tpu.parallel.pipeline import stage_permutation
    pp, L, m, mb, d, v = 4, 8, 8, 2, 16, 2
    mesh = Mesh(np.asarray(jax.devices()[:pp]), (PIPE_AXIS,))
    r = np.random.RandomState(0)
    stack = jnp.asarray(0.3 * r.randn(L, d, d).astype(np.float32))
    x = jnp.asarray(r.randn(m * mb, d).astype(np.float32))
    perm = stage_permutation(L, pp, v)

    def layer(w, h):
        return jnp.tanh(h @ w)

    def stage_fn(st, h):
        def body(hh, w):
            return layer(w, hh), None
        hh, _ = lax.scan(body, h, st)
        return hh

    def run(interleave):
        def pipe_loss(stack, x):
            y = pipeline_apply(stage_fn, stack, microbatch(x, m),
                               interleave=interleave)
            return jnp.sum(unmicrobatch(y) ** 2)

        def f(stack, x):
            return jax.value_and_grad(pipe_loss)(stack, x)

        sm = jax.jit(shard_map(f, mesh=mesh,
                               in_specs=(P(PIPE_AXIS), P()),
                               out_specs=(P(), P(PIPE_AXIS))))
        st = stack if interleave == 1 else stack[np.asarray(perm)]
        return sm(jax.device_put(st, NamedSharding(mesh, P(PIPE_AXIS))),
                  jax.device_put(x, NamedSharding(mesh, P())))

    cost1, grad1 = run(1)
    cost2, grad2 = run(v)
    assert float(cost2) == pytest.approx(float(cost1), rel=1e-6)
    # grad2 is w.r.t. the permuted stack; un-permute back to depth order
    np.testing.assert_allclose(
        np.asarray(grad2)[np.argsort(perm)], np.asarray(grad1),
        rtol=1e-5, atol=1e-7)


def test_pp_interleaved_init_identical_to_dense(mesh8):
    """Interleaved init stacks the same per-layer params, just in stage
    order — _gathered_dense_params round-trips them to depth order."""
    dense = _make(dp=2, pp=1, n_layer=8)
    ppm = _make(dp=2, pp=4, n_layer=8, pp_interleave=2)
    gathered = ppm._gathered_dense_params()
    for i, blk in enumerate(dense.blocks):
        jax.tree.map(lambda g, d: np.testing.assert_array_equal(
            np.asarray(g), np.asarray(d)),
            gathered[blk.name], dense.params[blk.name])


def test_pp_interleaved_training_matches_v1_exact(mesh8):
    """v=2 walks each chunk's microbatches in the same order as v=1, so
    even the fp summation order matches — training costs are IDENTICAL,
    not merely close."""
    c1 = _train_steps(_make(dp=2, pp=4, n_layer=8), 5)
    c2 = _train_steps(_make(dp=2, pp=4, n_layer=8, pp_interleave=2), 5)
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))


def test_pp_interleaved_v4_matches_v1_exact(mesh8):
    c1 = _train_steps(_make(dp=2, pp=4, n_layer=16), 4)
    c4 = _train_steps(_make(dp=2, pp=4, n_layer=16, pp_interleave=4), 4)
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c4))


def test_pp_interleaved_training_matches_dense(mesh8):
    """Same tolerance the v=1 pin uses (fp noise only)."""
    c_dense = _train_steps(_make(dp=2, pp=1, n_layer=8), 5)
    c_v2 = _train_steps(_make(dp=2, pp=4, n_layer=8, pp_interleave=2), 5)
    np.testing.assert_allclose(c_v2, c_dense, rtol=2e-4, atol=2e-5)


def test_pp_interleaved_spc_fused_exact(mesh8):
    """The fused multi-step dispatch (steps_per_call) composes with the
    interleaved schedule: same costs as v=1 under the same cadence."""
    c1 = _train_steps(_make(dp=2, pp=4, n_layer=8, steps_per_call=2), 4)
    c2 = _train_steps(_make(dp=2, pp=4, n_layer=8, steps_per_call=2,
                            pp_interleave=2), 4)
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))


def test_pp_interleaved_moe_aux_exact(mesh8):
    """with_aux masking stays exact over real ticks under interleaving:
    the MoE load-balance aux (psummed over the schedule) matches v=1
    bit-for-bit."""
    from theanompi_tpu.models.transformer_lm import MoETransformerLM

    def make(v):
        mesh = worker_mesh(2, pp=4)
        cfg = {**LM_CFG, "mesh": mesh, "size": 2, "rank": 0, "pp": 4,
               "n_layer": 8, "moe_experts": 4, "moe_every": 1,
               "pp_interleave": v}
        return MoETransformerLM(cfg)

    c1 = _train_steps(make(1), 4)
    c2 = _train_steps(make(2), 4)
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))


def test_pp_interleave_validation_errors(mesh8):
    with pytest.raises(ValueError, match="pp_interleave"):
        _make(dp=2, pp=4, n_layer=8, pp_interleave=3)   # 8 % (4*3) != 0
    with pytest.raises(ValueError, match="pp_microbatches"):
        _make(dp=2, pp=4, n_layer=8, pp_interleave=2, pp_microbatches=6)
    with pytest.raises(ValueError, match="pp"):
        mesh = worker_mesh(2, pp=1)
        TransformerLM({**LM_CFG, "mesh": mesh, "size": 2, "rank": 0,
                       "pp": 1, "pp_interleave": 2})

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
