"""Ring attention vs the single-device oracle: exact (to accumulation
order) on an 8-way sequence-sharded mesh, fwd and grads, causal and not."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from theanompi_tpu.ops.ring_attention import (attention_reference,
                                              ring_attention,
                                              ring_attention_sharded)
from theanompi_tpu.parallel.mesh import worker_mesh
from theanompi_tpu.jax_compat import shard_map

B, H, T, D = 2, 3, 64, 16        # T shards 8 ways × 8 tokens


def _qkv(seed=0):
    r = np.random.RandomState(seed)
    return tuple(jnp.asarray(r.randn(B, H, T, D).astype(np.float32))
                 for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full_attention(mesh8, causal):
    q, k, v = _qkv(1)
    out = ring_attention_sharded(q, k, v, mesh8, axis="workers",
                                 causal=causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_grads_match(mesh8, causal):
    """The whole point is TRAINING long sequences: gradients through the
    ring (scan + ppermute) must match full attention's."""
    q, k, v = _qkv(2)
    spec = P(None, None, "workers", None)

    def ring_loss(q, k, v):
        fn = shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis="workers",
                                           causal=causal),
            mesh=mesh8, in_specs=(spec, spec, spec), out_specs=spec)
        return jnp.sum(fn(q, k, v) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

    sh = NamedSharding(mesh8, spec)
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(qs, ks, vs)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_ring_attention_bf16_inputs(mesh8):
    """bf16 activations (the TPU training dtype) with fp32 accumulation."""
    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(3))
    out = ring_attention_sharded(q, k, v, mesh8, axis="workers", causal=True)
    assert out.dtype == jnp.bfloat16
    ref = attention_reference(q.astype(jnp.float32), k.astype(jnp.float32),
                              v.astype(jnp.float32), causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=5e-2, atol=5e-2)


def test_2d_mesh_data_x_sequence_training_step():
    """Composition proof: a 2-D mesh (2 data-parallel workers × 4 sequence
    shards) trains a toy attention model — ring attention over the 'seq'
    axis inside the step, gradient psum over BOTH axes — and the loss
    decreases.  This is the long-context story on top of the same shard_map
    machinery the four exchangers use."""
    from jax import lax

    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("workers", "seq"))
    b, h, t, d, nclass = 4, 2, 32, 8, 2

    r = np.random.RandomState(0)
    x = jnp.asarray(r.randn(b, h, t, d).astype(np.float32))
    y = jnp.asarray((r.rand(b) > 0.5).astype(np.int32))
    params = {
        "wq": jnp.asarray(0.3 * r.randn(d, d).astype(np.float32)),
        "wk": jnp.asarray(0.3 * r.randn(d, d).astype(np.float32)),
        "wv": jnp.asarray(0.3 * r.randn(d, d).astype(np.float32)),
        "head": jnp.asarray(0.3 * r.randn(h * d, nclass).astype(np.float32)),
    }

    x_spec = P("workers", None, "seq", None)
    y_spec = P("workers")

    def loss_fn(params, x, y):
        q = jnp.einsum("bhtd,de->bhte", x, params["wq"])
        k = jnp.einsum("bhtd,de->bhte", x, params["wk"])
        v = jnp.einsum("bhtd,de->bhte", x, params["wv"])
        o = ring_attention(q, k, v, axis="seq", causal=True)
        # mean over the (sharded) sequence: local sum / global T
        pooled = lax.psum(o.sum(axis=2), "seq") / t        # [b_loc, h, d]
        logits = pooled.reshape(pooled.shape[0], -1) @ params["head"]
        logz = jax.nn.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, y[:, None], axis=-1)[:, 0]
        return jnp.mean(logz - ll)

    def step(params, x, y, lr):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        grads = jax.tree.map(
            lambda g: lax.pmean(lax.pmean(g, "workers"), "seq"), grads)
        new = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return new, lax.pmean(lax.pmean(loss, "workers"), "seq")

    sm = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=({k: P() for k in params}, x_spec, y_spec, P()),
        out_specs=({k: P() for k in params}, P())))

    xs = jax.device_put(x, NamedSharding(mesh, x_spec))
    ys = jax.device_put(y, NamedSharding(mesh, y_spec))
    losses = []
    for i in range(12):
        params, loss = sm(params, xs, ys, jnp.float32(0.5))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_ring_attention_jit_compiles_multichip():
    """Under jit on a fresh 8-way sequence mesh (the dryrun-style check)."""
    mesh = worker_mesh(8, axis_name="seq")
    q, k, v = _qkv(4)
    spec = P(None, None, "seq", None)
    fn = jax.jit(shard_map(
        lambda a, b, c: ring_attention(a, b, c, axis="seq", causal=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))
    sh = NamedSharding(mesh, spec)
    out = fn(*(jax.device_put(x, sh) for x in (q, k, v)))
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
