"""Persistent AOT executable cache (``utils/compile_cache``) contracts.

The subsystem's claims, machine-checked on the CPU mesh:

* **round-trip across processes** — a fresh process compiling the same
  model/rule/spc config via ``compile_iter_fns`` reports ``cache: hit``,
  its compile wall time is measurably below the cold path, and its
  training outputs are bit-identical to the fresh compile's (the ISSUE-3
  acceptance evidence);
* **key sensitivity** — spc/rule/mesh/prng/donation each produce a new
  key (a stale executable can never serve a different program);
* **the fallback ladder** — a corrupted blob or a version-drifted entry
  falls back to a fresh compile with ``deserialize_fallbacks``
  incremented, never an error;
* **checkpoint resume hits** — the recompile after ``load()`` (the
  wedge-recovery restart path) deserializes instead of recompiling.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tests.conftest import TinyModel
from theanompi_tpu.parallel.exchanger import get_exchanger
from theanompi_tpu.utils import compile_cache as cc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(REPO, "tests", "_compile_cache_child.py")


def _run_child(cache_dir, out_path, rule="bsp", spc=2):
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    r = subprocess.run(
        [sys.executable, CHILD, str(cache_dir), str(out_path), rule,
         str(spc)],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert r.returncode == 0, f"child failed:\n{r.stdout}\n{r.stderr}"
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_fresh_process_roundtrip_bit_identical(tmp_path):
    """Cold process: miss + fresh compile + serialize.  Warm process: hit,
    faster compile path, BIT-IDENTICAL costs and parameters — the
    deserialized executable IS the program, not an approximation of it."""
    cache = tmp_path / "cache"
    cold = _run_child(cache, tmp_path / "cold.npz")
    warm = _run_child(cache, tmp_path / "warm.npz")
    assert cold["train_cache"] == "miss"
    assert warm["train_cache"] == "hit"
    # the startup-latency claim: the warm build of the train/val/exchange
    # programs must beat the cold one outright, and the cache-timed path
    # (deserialize vs XLA compile — tracing/lowering excluded, both runs
    # pay it) by 2×+ (margin absorbs CI noise; the real ratio is ~10×)
    assert warm["compile_wall"] < cold["compile_wall"], (warm, cold)
    assert warm["compile_secs"] < 0.5 * cold["compile_secs"], (warm, cold)
    a, b = np.load(tmp_path / "cold.npz"), np.load(tmp_path / "warm.npz")
    np.testing.assert_array_equal(a["costs"], b["costs"])
    np.testing.assert_array_equal(a["params"], b["params"])
    # entries + manifest landed
    assert any(f.endswith(".jexec") for f in os.listdir(cache))
    manifest = json.load(open(cache / "manifest.json"))
    assert any(int(v.get("hits", 0)) > 0 for v in manifest.values())


def _train_key(config, rule="bsp", extra_env=None):
    """Key of the train program a given config would request."""
    model = TinyModel(dict(config, verbose=False))
    exch = get_exchanger(rule, model.config)
    model.compile_iter_fns(exch)
    info = model.compile_info["train"]
    assert info["cache"] in ("miss", "hit"), info
    return info["key"]


def test_key_sensitivity(tmp_path):
    """spc / rule / mesh / prng / donation each flip the key."""
    cache = str(tmp_path / "kc")
    base = {"compile_cache": cache, "steps_per_call": 1}
    k_base = _train_key(base)
    assert k_base == _train_key(base), "same config must reproduce its key"
    k_spc = _train_key(dict(base, steps_per_call=2))
    k_rule = _train_key(base, rule="easgd")
    k_mesh = _train_key(dict(base, n_workers=4))
    keys = {"base": k_base, "spc": k_spc, "rule": k_rule, "mesh": k_mesh}
    try:
        jax.config.update("jax_default_prng_impl", "rbg")
        keys["prng"] = _train_key(base)
    finally:
        jax.config.update("jax_default_prng_impl", "threefry2x32")
    vals = list(keys.values())
    assert len(set(vals)) == len(vals), f"key collision: {keys}"

    # donation signature: same function, donated vs not → different keys
    def f(x):
        return x * 2.0

    x = jnp.ones((8,))
    lo_plain = jax.jit(f).lower(x)
    lo_don = jax.jit(f, donate_argnums=(0,)).lower(x)
    assert cc.program_key(lo_plain) != cc.program_key(lo_don)


def _tiny_entry(cache_dir):
    """One small cached program; returns (cache, lowered, key)."""
    cache = cc.CompileCache(str(cache_dir))
    lowered = jax.jit(lambda x: x + 1.0).lower(jnp.ones((16,)))
    compiled, info = cache.get_or_compile(lowered, label="tiny")
    assert info["cache"] == "miss" and info["serialized"], info
    return cache, lowered, info["key"]


def test_corrupted_blob_falls_back(tmp_path):
    cache, lowered, key = _tiny_entry(tmp_path)
    path = os.path.join(cache.cache_dir, key + ".jexec")
    raw = open(path, "rb").read()
    with open(path, "wb") as fh:          # keep the header, garble the body
        fh.write(raw.split(b"\n", 1)[0] + b"\n" + b"\x00garbage\x01" * 64)
    fresh = cc.CompileCache(str(tmp_path))
    compiled, info = fresh.get_or_compile(lowered, label="tiny")
    assert info["cache"] == "deserialize_fallback", info
    assert fresh.counters["deserialize_fallbacks"] == 1
    np.testing.assert_array_equal(
        np.asarray(compiled(jnp.ones((16,)))), np.full((16,), 2.0))
    # the entry was rewritten: next read is a clean hit again
    again = cc.CompileCache(str(tmp_path))
    _, info2 = again.get_or_compile(lowered, label="tiny")
    assert info2["cache"] == "hit", info2


def test_version_mismatch_falls_back(tmp_path):
    cache, lowered, key = _tiny_entry(tmp_path)
    path = os.path.join(cache.cache_dir, key + ".jexec")
    head, body = open(path, "rb").read().split(b"\n", 1)
    header = json.loads(head.decode())
    header["jax"] = "0.0.0-somebody-elses-runtime"
    with open(path, "wb") as fh:
        fh.write(json.dumps(header).encode() + b"\n" + body)
    fresh = cc.CompileCache(str(tmp_path))
    compiled, info = fresh.get_or_compile(lowered, label="tiny")
    assert info["cache"] == "deserialize_fallback", info
    assert "0.0.0" in info["fallback_reason"]
    assert fresh.counters["deserialize_fallbacks"] == 1
    assert compiled is not None


def test_prewarm_header_check_recompiles(tmp_path):
    """``load=False`` trusts an entry only after its header parses: a
    truncated or version-drifted entry is re-prewarmed off-line instead of
    being discovered as a deserialize-fallback in the hardware window."""
    cache, lowered, key = _tiny_entry(tmp_path)
    path = os.path.join(cache.cache_dir, key + ".jexec")
    with open(path, "wb") as fh:
        fh.write(b"truncated junk, no header")
    fresh = cc.CompileCache(str(tmp_path))
    _, info = fresh.get_or_compile(lowered, label="tiny", load=False)
    assert info["cache"] == "deserialize_fallback", info
    assert fresh.counters["deserialize_fallbacks"] == 1
    assert info["serialized"]                  # entry rewritten in place
    _, info2 = cc.CompileCache(str(tmp_path)).get_or_compile(
        lowered, label="tiny", load=False)
    assert info2["cache"] == "hit", info2      # second prewarm: clean hit


def test_checkpoint_resume_hits_cache(tmp_path):
    """The wedge-recovery restart: a second worker-style build of the SAME
    config (then restoring the checkpoint) deserializes every program —
    train, val, and the standalone exchange collective."""
    cache = str(tmp_path / "cache")
    ckpt = str(tmp_path / "ckpt")
    cfg = {"verbose": False, "compile_cache": cache}
    m1 = TinyModel(dict(cfg))
    ex1 = get_exchanger("easgd", m1.config)
    m1.compile_iter_fns(ex1)
    assert m1.compile_info["train"]["cache"] == "miss"
    m1.data.shuffle_data(0)
    m1.train_iter(1)
    ex1.exchange(None, 1)
    m1.save(ckpt, epoch=0, count=1)

    m2 = TinyModel(dict(cfg))
    ex2 = get_exchanger("easgd", m2.config)
    m2.compile_iter_fns(ex2)
    for fn in ("train", "val", "exchange"):
        assert m2.compile_info[fn]["cache"] == "hit", m2.compile_info
    assert m2.load(ckpt) == 0
    m2.data.shuffle_data(0)
    m2.train_iter(2)                       # deserialized program trains on
    ex2.exchange(None, 2)
    assert np.isfinite(float(m2.current_info["cost"]))


def test_uncreatable_cache_dir_disables(tmp_path):
    """An uncreatable dir (read-only mount, a file in the way) degrades to
    the inert instance instead of crashing the run — the module contract:
    every cache-side error is non-fatal."""
    blocker = tmp_path / "file"
    blocker.write_text("not a directory")
    c = cc.CompileCache(str(blocker / "cache"))
    assert not c.enabled
    m = TinyModel({"verbose": False,
                   "compile_cache": str(blocker / "cache")})
    m.compile_iter_fns(get_exchanger("bsp", m.config))   # must not raise
    assert m.compile_info["train"]["cache"] == "off"


def test_cache_off_is_lazy_jit():
    """No cache configured → pre-cache behavior: compile_info says 'off'
    and train_fn is still the lazy jit wrapper, not an AOT Compiled."""
    m = TinyModel({"verbose": False})
    m.compile_iter_fns(get_exchanger("bsp", m.config))
    assert m.compile_info["train"]["cache"] == "off"
    assert not isinstance(m.train_fn, jax.stages.Compiled)
    assert not m.compile_cache.enabled


def test_recorder_compile_bucket():
    from theanompi_tpu.utils.recorder import Recorder
    rec = Recorder({"verbose": False, "printFreq": 1})
    rec.start()
    rec.end("compile")
    rec.start()
    rec.end("train")
    rec.train_error(1, 0.5, 0.1, 8)
    rec.print_train_info(1)
    r = rec._all_records[-1]
    assert r["t_compile"] >= 0 and "t_train" in r
    # bucket resets after the print, like every section
    assert rec.t_sec["compile"] == 0.0
    ep = rec.print_val_info(1)
    assert "t_compile" in ep        # cumulative, for resume-goes-to-~0


def test_rows_manifest_consistency():
    """Every manifest row's env round-trips through bench_row_config and
    its label matches bench's _cfg_matches conventions — the drift guard
    between prewarm shapes and measured shapes."""
    sys.path.insert(0, REPO)
    from scripts.rows import ROWS, rows
    import bench
    assert rows("r8") and rows("heavy")
    labels = [r.label for r in ROWS]
    assert len(set(labels)) == len(labels), "duplicate row labels"
    for row in ROWS:
        # bench_row_config force-exports THEANOMPI_TPU_NO_PALLAS for
        # oracle-control rows — keep that out of the test process
        saved_np = os.environ.get("THEANOMPI_TPU_NO_PALLAS")
        try:
            model_name, rule, config, flags = \
                bench.bench_row_config(row.env)
        finally:
            if saved_np is None:
                os.environ.pop("THEANOMPI_TPU_NO_PALLAS", None)
            else:
                os.environ["THEANOMPI_TPU_NO_PALLAS"] = saved_np
        assert row.label.startswith(model_name), row
        # bench.py's fallback matcher must recognize the row's own label
        # under the row's own env (the contract last_good relies on)
        old = {k: os.environ.get(k) for k in row.env}
        os.environ.update(row.env)
        try:
            assert bench._cfg_matches(row.label), row
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        if "BENCH_SPC" in row.env and int(row.env["BENCH_SPC"]) > 1:
            assert config["steps_per_call"] == int(row.env["BENCH_SPC"])


@pytest.mark.slow
def test_prewarm_then_registry_model_hits(tmp_path):
    """scripts/prewarm_cache.py (live CPU venue) then a worker-style
    compile of the same manifest row: the executable store must hit —
    the whole prewarm-then-measure window workflow, minus the TPU."""
    cache = str(tmp_path / "cache")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    r = subprocess.run(
        [sys.executable, "-u", os.path.join(REPO, "scripts",
                                            "prewarm_cache.py"),
         "--rows", "cifar10-b128", "--cache", cache, "--platform", "cpu",
         "--no-spc1-flops"],
        capture_output=True, text=True, timeout=560, env=env, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "cifar10-b128:" in r.stdout and "FAILED" not in r.stdout

    child = (
        "import os\n"
        "os.environ['XLA_FLAGS'] = ''\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "jax.config.update('jax_default_prng_impl', 'rbg')\n"
        "import importlib, json, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from bench import bench_row_config\n"
        "from scripts.rows import rows\n"
        "from theanompi_tpu.models.registry import MODELS\n"
        "from theanompi_tpu.parallel.exchanger import get_exchanger\n"
        "from theanompi_tpu.parallel.mesh import worker_mesh, WORKER_AXIS\n"
        "row = rows('cifar10-b128')[0]\n"
        "name, rule, cfg, flags = bench_row_config(row.env)\n"
        "mf, mc, extra = MODELS[name]\n"
        "mesh = worker_mesh(None)\n"
        "config = {'mesh': mesh, 'size': mesh.shape[WORKER_AXIS],\n"
        "          'rank': 0, 'verbose': False, **extra, **cfg,\n"
        f"          'compile_cache': {cache!r}}}\n"
        "m = getattr(importlib.import_module(mf), mc)(config)\n"
        "m.compile_iter_fns(get_exchanger(rule, config))\n"
        "print(json.dumps(m.compile_info['train']))\n")
    r2 = subprocess.run([sys.executable, "-c", child], capture_output=True,
                        text=True, timeout=560, env=env, cwd=REPO)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    info = json.loads(r2.stdout.strip().splitlines()[-1])
    assert info["cache"] == "hit", info
