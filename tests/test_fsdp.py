"""FSDP / ZeRO-3 (parallel/fsdp.py): parameters sharded over the workers
axis as flat chunks, gathered transiently per step, gradients reduce-
scattered by the all_gather's AD transpose — bit-equal to plain BSP."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tests.conftest import TinyModel
from theanompi_tpu.models.transformer_lm import TransformerLM
from theanompi_tpu.parallel import steps
from theanompi_tpu.parallel.exchanger import BSP_Exchanger, get_exchanger
from theanompi_tpu.parallel.mesh import WORKER_AXIS, worker_mesh


def _train(model, exch, n_steps):
    model.compile_iter_fns(exch)
    model.data.shuffle_data(0)
    costs = []
    for i in range(n_steps):
        model.train_iter(i, None)
        costs.append(float(model.current_info["cost"]))
    return costs


def _make_tiny(fsdp, mesh, **kw):
    cfg = {"mesh": mesh, "size": 4, "rank": 0, "verbose": False,
           "fsdp": fsdp, **kw}
    return TinyModel(cfg), cfg


def _host_params(model):
    if model._fsdp is not None:
        return model.canonical_host_params()
    return steps.unbox(jax.device_get(model.step_state["params"]))


@pytest.mark.parametrize("optimizer", ["momentum", "adam"])
def test_fsdp_bit_equal_to_bsp(mesh4, optimizer):
    """Same data, same seed: the gather/transpose-scatter step must trace
    plain BSP's trajectory EXACTLY (psum and psum_scatter reduce in the
    same order on the simulated mesh; elementwise update on chunks)."""
    base, _ = _make_tiny(False, mesh4, optimizer=optimizer)
    shard, _ = _make_tiny(True, mesh4, optimizer=optimizer)
    c0 = _train(base, BSP_Exchanger(base.config), 6)
    c1 = _train(shard, BSP_Exchanger(shard.config), 6)
    np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)),
        _host_params(base), _host_params(shard))


def test_fsdp_state_is_the_partition(mesh4):
    """Persistent memory: params AND optimizer state live as one
    ceil(P/N) chunk per worker — the boxed [n, chunk] layout IS the
    partition, and chunks genuinely differ across workers."""
    model, _ = _make_tiny(True, mesh4, optimizer="adam")
    model.compile_iter_fns(BSP_Exchanger(model.config))
    chunk = -(-model.n_params // 4)
    p = model.step_state["params"]
    assert p.shape == (4, chunk)
    assert p.sharding.spec == (WORKER_AXIS,)
    m = model.step_state["opt_state"]["m"]
    assert m.shape == (4, chunk)
    pp = np.asarray(jax.device_get(p))
    assert not np.array_equal(pp[0], pp[1])
    # the gathered full tree still matches the init params before training
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=0, atol=0),
        model.canonical_host_params(), jax.device_get(model.params))


def test_fsdp_composes_with_n_subb(mesh4):
    """Microbatch accumulation re-gathers per microbatch inside the scan
    and accumulates the CHUNK-sized gradient (scatter-then-sum — the
    accumulator is 1/N the size of BSP's full-tree sum-then-reduce).  The
    reduction order therefore differs by one level of fp32 associativity:
    trajectories track to float tolerance, not bit-exactly (the n_subb=1
    case IS bit-exact — test_fsdp_bit_equal_to_bsp)."""
    base, _ = _make_tiny(False, mesh4, n_subb=2, batch_size=16)
    shard, _ = _make_tiny(True, mesh4, n_subb=2, batch_size=16)
    c0 = _train(base, BSP_Exchanger(base.config), 4)
    c1 = _train(shard, BSP_Exchanger(shard.config), 4)
    np.testing.assert_allclose(np.asarray(c0), np.asarray(c1),
                               rtol=1e-6, atol=1e-7)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-7),
        _host_params(base), _host_params(shard))


def test_fsdp_composes_with_steps_per_call(mesh4):
    """k full FSDP steps per dispatch (the scan carries the chunk state)
    must land bit-equal to k single-step dispatches."""
    one, _ = _make_tiny(True, mesh4)
    spc, _ = _make_tiny(True, mesh4, steps_per_call=2)
    _train(one, BSP_Exchanger(one.config), 4)
    m = spc
    m.compile_iter_fns(BSP_Exchanger(m.config))
    m.data.shuffle_data(0)
    for last in (1, 3):
        m.train_iter(last, None)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), _host_params(one), _host_params(m))


def test_fsdp_ema_matches_dense_ema(mesh4):
    """The EMA shadow tracks the CHUNK under fsdp; the assembled shadow
    must equal the dense EMA shadow, and validation reads it."""
    base, _ = _make_tiny(False, mesh4, ema_decay=0.9)
    shard, _ = _make_tiny(True, mesh4, ema_decay=0.9)
    _train(base, BSP_Exchanger(base.config), 5)
    _train(shard, BSP_Exchanger(shard.config), 5)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)),
        base._ema_host_params(), shard._ema_host_params())
    # begin_val assembles the shadow on device — same tree
    shard.begin_val()
    boxed = jax.device_get(shard._val_params_boxed)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)[0]),
        shard._ema_host_params(), boxed)
    shard.end_val()


def test_fsdp_grad_clip_close_to_bsp(mesh4):
    """Global-norm clipping: the chunked norm (one vector psum) equals the
    leaf-wise norm up to fp32 summation order — trajectories track to
    float tolerance with a clip LOW enough to actually engage."""
    base, _ = _make_tiny(False, mesh4, grad_clip=0.05)
    shard, _ = _make_tiny(True, mesh4, grad_clip=0.05)
    c0 = _train(base, BSP_Exchanger(base.config), 5)
    c1 = _train(shard, BSP_Exchanger(shard.config), 5)
    np.testing.assert_allclose(np.asarray(c0), np.asarray(c1),
                               rtol=1e-5, atol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
        _host_params(base), _host_params(shard))


def test_fsdp_val_matches_bsp(mesh4):
    """Validation gathers the full tree on device; metrics must equal the
    dense model's on the same replicas."""
    base, _ = _make_tiny(False, mesh4)
    shard, _ = _make_tiny(True, mesh4)
    _train(base, BSP_Exchanger(base.config), 4)
    _train(shard, BSP_Exchanger(shard.config), 4)
    for m in (base, shard):
        m.begin_val()
    b0 = base.data.next_val_batch(0)
    dev = steps.put_batch(base.mesh, b0, None)
    r0 = [np.asarray(x) for x in base.val_fn(
        base._val_params_boxed, base._val_bn_boxed, dev)]
    r1 = [np.asarray(x) for x in shard.val_fn(
        shard._val_params_boxed, shard._val_bn_boxed, dev)]
    for a, b in zip(r0, r1):
        np.testing.assert_array_equal(a, b)


def test_fsdp_checkpoint_exact_resume(tmp_path, mesh4):
    """Save mid-run, rebuild from disk, continue: bit-equal to the
    uninterrupted run.  Chunks are genuinely per-worker state — the
    checkpoint stores params AND opt_state boxed (no dedup)."""
    solo, _ = _make_tiny(True, mesh4)
    c_solo = _train(solo, BSP_Exchanger(solo.config), 6)

    a, _ = _make_tiny(True, mesh4)
    _train(a, BSP_Exchanger(a.config), 3)
    a.save(str(tmp_path), epoch=0, count=3)
    import json
    import os
    with open(os.path.join(str(tmp_path), "ckpt_epoch0.json")) as f:
        meta = json.load(f)
    assert set(meta["boxed_parts"]) >= {"params", "opt_state"}, meta
    # the .npy snapshot holds the FULL canonical tree, not chunks
    snap = os.path.join(str(tmp_path), "params_epoch0")
    full_shapes = sorted(np.shape(l) for l in jax.tree.leaves(a.params))
    snap_shapes = sorted(np.load(os.path.join(snap, f)).shape
                         for f in os.listdir(snap))
    assert snap_shapes == full_shapes

    b, _ = _make_tiny(True, mesh4)
    b.compile_iter_fns(BSP_Exchanger(b.config))
    assert b.load(str(tmp_path)) == 0    # also restores the data cursor —
    costs = []                           # no shuffle_data() after load
    for i in range(3, 6):
        b.train_iter(i, None)
        costs.append(float(b.current_info["cost"]))
    np.testing.assert_array_equal(np.asarray(c_solo[3:]), np.asarray(costs))
    jax.tree.map(lambda x, y: np.testing.assert_array_equal(
        np.asarray(x), np.asarray(y)), _host_params(solo), _host_params(b))


def test_fsdp_checkpoint_is_worker_count_portable(tmp_path, mesh4, mesh8):
    """Elastic resume for chunked state: chunking is a pure partition of
    the padded flat vector, so a 4-worker fsdp checkpoint re-slices onto
    8 workers (and back) — assembled params and optimizer flat identical,
    and training continues."""
    d = str(tmp_path / "ckpt")
    m4, _ = _make_tiny(True, mesh4, optimizer="adam")
    _train(m4, BSP_Exchanger(m4.config), 3)
    m4.save(d, epoch=0, count=3)
    ref = m4.canonical_host_params()
    ref_m = np.asarray(jax.device_get(
        m4.step_state["opt_state"]["m"])).reshape(-1)[:m4.n_params]

    cfg8 = {"mesh": mesh8, "size": 8, "rank": 0, "verbose": False,
            "fsdp": True, "optimizer": "adam"}
    m8 = TinyModel(cfg8)
    m8.compile_iter_fns(BSP_Exchanger(cfg8))
    assert m8.load(d) == 0
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), ref, m8.canonical_host_params())
    got_m = np.asarray(jax.device_get(
        m8.step_state["opt_state"]["m"])).reshape(-1)[:m8.n_params]
    np.testing.assert_array_equal(ref_m, got_m)
    t8 = np.asarray(jax.device_get(m8.step_state["opt_state"]["t"]))
    assert t8.shape == (8,) and (t8 == t8[0]).all() and t8[0] == 3
    m8.train_iter(3, None)               # and it keeps training

    # different model config must fail LOUDLY, not silently re-slice
    cfg_bad = dict(cfg8, n_train=256)
    bad = TinyModel({**cfg_bad, "batch_size": 8})
    bad.params = jax.tree.map(
        lambda x: np.zeros(np.shape(x)[:-1] + (np.shape(x)[-1] + 1,),
                           np.float32), bad.params)
    from theanompi_tpu.parallel.fsdp import FsdpLayout
    bad._fsdp = FsdpLayout(bad.params, 8)
    bad.compile_iter_fns(BSP_Exchanger(bad.config))
    with pytest.raises(AssertionError, match="different model config"):
        bad.load(d)


def test_rechunk_roundtrips_across_worker_counts():
    """Pure-layout property: re-partitioning a flat vector through ANY
    sequence of worker counts is the identity on the data (pad is sliced
    off and re-derived each hop) — for both fsdp's flat layout and zero's
    rank-major model-sharded layout."""
    from theanompi_tpu.parallel import zero as zero_lib
    from theanompi_tpu.parallel.fsdp import FsdpLayout
    rng = np.random.RandomState(0)
    params = {"a": rng.randn(13, 7).astype(np.float32),
              "b": rng.randn(29).astype(np.float32)}
    flat = np.concatenate([params["a"].reshape(-1), params["b"]])
    for ns in ([4, 8, 3, 4], [1, 5, 1]):
        lay = {n: FsdpLayout(params, n) for n in ns}
        boxed = lay[ns[0]].chunk_host(params)
        for n in ns[1:]:
            boxed = lay[n].rechunk(boxed)
        np.testing.assert_array_equal(
            boxed.reshape(-1)[:flat.size], flat)
    # zero's rank-major layout: shards=3 model ranks, each local_total=40
    local_total, shards = 40, 3
    per_rank = rng.randn(shards, local_total).astype(np.float32)

    def to_boxed(n):
        c = zero_lib.chunk_size(local_total, n)
        padded = np.pad(per_rank, ((0, 0), (0, c * n - local_total)))
        return np.transpose(padded.reshape(shards, n, c),
                            (1, 0, 2)).reshape(n, shards * c)

    boxed = to_boxed(5)
    for n in (2, 7, 5):
        boxed = zero_lib.rechunk_boxed(boxed, n, shards, local_total)
    np.testing.assert_array_equal(boxed, to_boxed(5))


def test_fsdp_rejects_incompatible_configs(mesh4, mesh8):
    """fsdp is BSP-grads + exact allreduce only; zero_opt is subsumed;
    model-parallel layouts shard params their own way."""
    m, cfg = _make_tiny(True, mesh4, sync_freq=2)
    with pytest.raises(AssertionError, match="allreduce"):
        m.compile_iter_fns(get_exchanger("gosgd", cfg))
    for bad in ({"exch_strategy": "topk"}, {"exch_mode": "params"},
                {"exch_strategy": "none"}):
        m, cfg = _make_tiny(True, mesh4, **bad)
        with pytest.raises(AssertionError, match="allreduce"):
            m.compile_iter_fns(BSP_Exchanger(cfg))
    with pytest.raises(AssertionError, match="subsumes"):
        _make_tiny(True, mesh4, zero_opt=True)
    mesh = worker_mesh(2, tp=2)
    with pytest.raises(AssertionError, match="tensor/pipeline|data-parallel"):
        TransformerLM({"mesh": mesh, "size": 2, "rank": 0, "tp": 2,
                       "verbose": False, "fsdp": True, "batch_size": 8,
                       "seq_len": 16, "vocab": 32, "d_model": 32,
                       "n_head": 4, "n_layer": 2,
                       "compute_dtype": jnp.float32})


def test_fsdp_transformer_trains(mesh8):
    """The LM family rides fsdp unchanged (pure-DP layout): loss falls and
    the persistent state is chunked."""
    mesh = worker_mesh(8)
    cfg = {"mesh": mesh, "size": 8, "rank": 0, "verbose": False,
           "fsdp": True, "batch_size": 8, "seq_len": 16, "vocab": 32,
           "d_model": 32, "n_head": 4, "n_layer": 2,
           "synthetic_train": 128, "compute_dtype": jnp.float32}
    model = TransformerLM(cfg)
    costs = _train(model, BSP_Exchanger(cfg), 6)
    assert np.isfinite(costs).all()
    assert np.mean(costs[-3:]) < np.mean(costs[:3])
    chunk = -(-model.n_params // 8)
    assert model.step_state["params"].shape == (8, chunk)
    # generation reads the canonical (assembled) params — works on chunks
    out = np.asarray(model.generate(np.array([[1, 2, 3]]),
                                    max_new_tokens=4))
    assert out.shape == (1, 4) and (out >= 0).all() and (out < 32).all()


def test_per_worker_strategy_state_rejects_worker_count_change(
        tmp_path, mesh4, mesh8):
    """Round-4 ADVICE #3: exchange-strategy error-feedback state (onebit/
    topk/powersgd) is boxed per-worker with NO refit path — resuming on a
    different worker count must fail with the targeted message naming the
    limitation, not a raw leaf-shape mismatch."""
    d = str(tmp_path / "ckpt")
    m4, cfg4 = _make_tiny(False, mesh4, exch_strategy="topk")
    _train(m4, get_exchanger("bsp", cfg4), 3)
    m4.save(d, epoch=0, count=3)

    cfg8 = {"mesh": mesh8, "size": 8, "rank": 0, "verbose": False,
            "exch_strategy": "topk"}
    m8 = TinyModel(cfg8)
    m8.compile_iter_fns(get_exchanger("bsp", cfg8))
    with pytest.raises(ValueError, match="no.*worker-count refit"):
        m8.load(d)

    # same worker count stays fully resumable (the supported path)
    m4b, cfg4b = _make_tiny(False, mesh4, exch_strategy="topk")
    m4b.compile_iter_fns(get_exchanger("bsp", cfg4b))
    assert m4b.load(d) == 0

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
