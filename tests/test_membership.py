"""Elastic membership: leases, controller transitions, rule-aware
reactions (demote/readmit at the center and in the mesh), backoff and
crash-loop plumbing (parallel/membership.py, docs/design.md §14)."""

import json
import os
import time

import numpy as np
import pytest

import jax

from tests.conftest import TinyModel
from theanompi_tpu.parallel import membership as mb
from theanompi_tpu.parallel.async_easgd import ElasticCenter
from theanompi_tpu.parallel.center_server import CenterServer, RemoteCenter
from theanompi_tpu.parallel.exchanger import (ASGD_Exchanger,
                                              EASGD_Exchanger,
                                              GOSGD_Exchanger)
from theanompi_tpu.utils import telemetry


def _tm():
    return telemetry.Telemetry(rank=0, run_id="membership-test")


def _events(tm, *kinds):
    return [e for e in tm.tail(64) if e["ev"] in kinds]


# -- leases ------------------------------------------------------------------

def test_lease_beat_roundtrip_and_heartbeat_gauges(tmp_path):
    tm = _tm()
    lease = mb.WorkerLease(str(tmp_path), 3, telemetry_=tm)
    lease.beat(7)
    docs = mb.read_leases(str(tmp_path))
    assert docs[3]["step"] == 7 and docs[3]["status"] == "live"
    assert docs[3]["pid"] == os.getpid()
    # no torn temp files left behind (atomic replace)
    assert not [f for f in os.listdir(tmp_path) if ".tmp" in f]
    # the beat streamed the declared heartbeat gauges
    assert tm.gauges["heartbeat.iter"] == 7
    gs = _events(tm, "gauges")
    assert gs and gs[-1]["heartbeat.iter"] == 7
    lease.release()
    assert mb.read_leases(str(tmp_path))[3]["status"] == "left"


def test_controller_join_expire_rejoin_cycle(tmp_path):
    tm = _tm()
    ctl = mb.MembershipController(lease_dir=str(tmp_path),
                                  lease_timeout=0.2, telemetry_=tm)
    lease = mb.WorkerLease(str(tmp_path), 1, telemetry_=tm,
                           min_interval_s=0.0)
    lease.beat(1)
    trans = ctl.poll()
    assert [t[0] for t in trans] == ["worker_join"]
    assert ctl.active_ranks() == [1]
    time.sleep(0.3)                      # lease expires: wedged or dead
    trans = ctl.poll()
    assert [t[0] for t in trans] == ["worker_leave"]
    assert trans[0][2]["reason"] == "lease_expired"
    assert ctl.active_ranks() == []
    lease.beat(9)                        # the worker comes back
    trans = ctl.poll()
    assert [t[0] for t in trans] == ["worker_join"]
    assert trans[0][2]["rejoin"] is True
    # every transition is one telemetry event tagged with the worker id
    evs = _events(tm, *mb.MEMBERSHIP_EVENTS)
    assert [e["ev"] for e in evs] == ["worker_join", "worker_leave",
                                     "worker_join"]
    assert all(e["worker"] == 1 for e in evs)


def test_stale_lease_cannot_resurrect_a_dead_worker(tmp_path):
    """A killed process's last beat can still be inside the lease window —
    the supervisor's death observation must win until a NEWER beat."""
    ctl = mb.MembershipController(lease_dir=str(tmp_path),
                                  lease_timeout=30.0, telemetry_=_tm())
    lease = mb.WorkerLease(str(tmp_path), 1, min_interval_s=0.0)
    lease.beat(5)
    ctl.poll()
    assert ctl.active_ranks() == [1]
    ctl.leave(1, reason="crashed", rc=-9)   # supervisor saw the SIGKILL
    assert ctl.poll() == []                  # fresh-but-stale lease ignored
    assert ctl.active_ranks() == []
    lease.beat(6)                            # respawn actually beat
    trans = ctl.poll()
    assert [t[0] for t in trans] == ["worker_join"]
    assert trans[0][2]["rejoin"] is True


def test_clean_finish_is_not_a_death(tmp_path):
    ctl = mb.MembershipController(lease_dir=str(tmp_path),
                                  lease_timeout=30.0, telemetry_=_tm())
    lease = mb.WorkerLease(str(tmp_path), 4)
    lease.beat(10)
    ctl.poll()
    lease.release()
    trans = ctl.poll()
    assert [t[0] for t in trans] == ["worker_leave"]
    assert trans[0][2]["reason"] == "finished"
    assert ctl.status()["left"] == [4]


# -- straggler demotion ------------------------------------------------------

def test_straggler_demotion_and_min_active_floor():
    tm = _tm()
    ctl = mb.MembershipController(telemetry_=tm, straggle_windows=3,
                                  min_active=1)
    for w in (0, 1, 2):
        ctl.join(w)
    ranking = [{"rank": 2, "windows_straggled": 5, "mean_train_secs": 0.9},
               {"rank": 1, "windows_straggled": 1, "mean_train_secs": 0.1},
               {"rank": 0, "windows_straggled": 0, "mean_train_secs": 0.1}]
    assert ctl.check_stragglers(ranking) == [2]
    assert ctl.status()["demoted"] == [2]
    evs = _events(tm, "worker_demote")
    assert evs[-1]["worker"] == 2 and evs[-1]["reason"] == "straggler"
    # re-running does not double-demote
    assert ctl.check_stragglers(ranking) == []
    # readmission is a worker_join with rejoin
    ctl.readmit(2)
    assert ctl.active_ranks() == [0, 1, 2]
    join = _events(tm, "worker_join")[-1]
    assert join["worker"] == 2 and join["reason"] == "readmit"
    # the ranking is CUMULATIVE: the evidence that demoted worker 2 must
    # NOT re-demote it after readmission — only NEW straggles can
    assert ctl.check_stragglers(ranking) == []
    assert ctl.active_ranks() == [0, 1, 2]
    worse = [dict(ranking[0], windows_straggled=8)] + ranking[1:]
    assert ctl.check_stragglers(worse) == [2]     # 3 fresh windows → out
    # the floor: never demote the last active workers
    ctl2 = mb.MembershipController(straggle_windows=1, min_active=2)
    ctl2.join(0)
    ctl2.join(1)
    assert ctl2.check_stragglers(
        [{"rank": 1, "windows_straggled": 9},
         {"rank": 0, "windows_straggled": 0}]) == []


def test_straggler_ranking_sourced_from_telemetry_streams(tmp_path):
    """The controller consumes telemetry_report's windowed ranking over
    real per-rank stream files — rank 2's fat phase.train dts must get it
    demoted."""
    t0 = time.time()
    for rank in range(3):
        with open(tmp_path / f"telemetry_rank{rank}.jsonl", "w") as f:
            for i in range(30):
                dt = 0.5 if rank == 2 else 0.01
                f.write(json.dumps(
                    {"ts": t0 + i, "run": "r", "rank": rank, "ev": "phase",
                     "sec": "train", "dt": dt}) + "\n")
    ctl = mb.MembershipController(telemetry_=_tm(),
                                  record_dir=str(tmp_path),
                                  straggle_windows=2, straggle_window_s=5.0)
    for w in range(3):
        ctl.join(w)
    assert ctl.check_stragglers() == [2]
    assert ctl.status()["demoted"] == [2]


# -- backoff / breaker / flight tail ----------------------------------------

def test_backoff_bounded_exponential_with_jitter():
    b = mb.Backoff(base=1.0, factor=2.0, cap=8.0, jitter=0.25, seed=3)
    for attempt, nominal in [(0, 1.0), (1, 2.0), (2, 4.0), (3, 8.0),
                             (9, 8.0)]:
        for _ in range(16):
            d = b.delay(attempt)
            assert 0.75 * nominal <= d <= 1.25 * nominal, (attempt, d)


def test_backoff_jitter_seedable_and_rng_injectable():
    """Round-17 satellite: respawn timing is reproducible — same seed ⇒
    same delay stream, and an INJECTED shared rng lets a whole rehearsal
    (simfleet, the chaos tests) own one seeded stream.  Default behavior
    (no seed, no rng) stays an independent unseeded draw."""
    import random
    a = mb.Backoff(base=1.0, cap=8.0, seed=42)
    b = mb.Backoff(base=1.0, cap=8.0, seed=42)
    assert [a.delay(i) for i in range(8)] == \
        [b.delay(i) for i in range(8)]
    # injected rng: Backoff consumes exactly one draw per delay from the
    # SHARED stream, so two consumers interleave deterministically
    rng1, rng2 = random.Random(7), random.Random(7)
    c = mb.Backoff(base=1.0, cap=8.0, rng=rng1)
    expect = [1.0 * (1.0 - 0.25 + 0.5 * rng2.random())]
    expect.append(2.0 * (1.0 - 0.25 + 0.5 * rng2.random()))
    assert [c.delay(0), c.delay(1)] == expect
    with pytest.raises(AssertionError, match="not both"):
        mb.Backoff(seed=1, rng=random.Random(1))
    # defaults still draw independently (overwhelmingly unequal streams)
    d1 = [mb.Backoff().delay(3) for _ in range(4)]
    d2 = [mb.Backoff().delay(3) for _ in range(4)]
    assert d1 != d2


def test_crash_loop_breaker_window_semantics():
    br = mb.CrashLoopBreaker(limit=3, window_s=10.0)
    assert br.record_failure(now=0.0) is False
    assert br.record_failure(now=1.0) is False
    assert br.record_failure(now=2.0) is True         # 3 within 10s
    # spread failures never trip
    br2 = mb.CrashLoopBreaker(limit=3, window_s=10.0)
    assert br2.record_failure(now=0.0) is False
    assert br2.record_failure(now=20.0) is False
    assert br2.record_failure(now=40.0) is False


def test_flight_tail_lines_reads_newest_dump(tmp_path):
    tm = telemetry.Telemetry(rank=0, run_id="ft", stream_dir=str(tmp_path))
    tm.event("phase", sec="train", dt=0.1)
    tm.event("crash", error="boom")
    tm.dump_flight(reason="test")
    tm.close()
    lines = mb.flight_tail_lines(str(tmp_path), n=8)
    assert lines and "flight tail" in lines[0]
    assert any("crash" in ln and "boom" in ln for ln in lines)
    assert mb.flight_tail_lines(str(tmp_path / "nope")) == []


# -- center reactions (EASGD/ASGD shrink without stopping) -------------------

def _center_with_probe():
    center = ElasticCenter(alpha=0.5)
    p0 = {"w": np.ones((2, 2), np.float32)}
    center.ensure_init(p0)
    return center, p0


def test_elastic_center_demote_drops_pushes_readmit_restores():
    center, p0 = _center_with_probe()
    d = {"w": np.full((2, 2), 2.0, np.float32)}
    center.push_delta(d, island=1)
    assert center.n_updates == 1
    center.demote_island(1)
    snap = center.pull()
    center.push_delta(d, island=1)           # dropped
    np.testing.assert_array_equal(center.pull()["w"], snap["w"])
    assert center.n_updates == 1
    assert center.dropped_by_island == {1: 1}
    # pulls still serve the demoted island (it keeps training locally)
    assert center.pull() is not None
    # ASGD push_pull: pull half still answers, push half dropped
    fresh = center.push_pull(d, island=1)
    np.testing.assert_array_equal(fresh["w"], snap["w"])
    assert center.dropped_by_island == {1: 2}
    center.readmit_island(1)
    center.push_delta(d, island=1)
    assert center.n_updates == 2
    assert not np.array_equal(center.pull()["w"], snap["w"])


def test_center_reactor_drives_demote_and_readmit():
    center, _ = _center_with_probe()
    reactor = mb.CenterReactor(center)
    ctl = mb.MembershipController(telemetry_=_tm(), reactors=[reactor])
    ctl.join(1)
    ctl.join(2)
    ctl.demote(2)
    assert center.demoted == {2}
    ctl.readmit(2)
    assert center.demoted == set()
    ctl.leave(1, reason="crashed")           # zombie pushes must not land
    assert center.demoted == {1}
    ctl.join(1, reason="respawn")            # rejoin readmits
    assert center.demoted == set()


def test_center_down_restored_event_pair():
    """The round-14 outage pair: controller-emitted, worker-less, audited
    by chaos_run's center gate and rendered as instant markers."""
    tm = _tm()
    ctl = mb.MembershipController(telemetry_=tm)
    ctl.center_down(reason="crashed", rc=-9)
    ctl.center_restored(attempt=1)
    evs = _events(tm, *mb.CENTER_EVENTS)
    assert [e["ev"] for e in evs] == ["center_down", "center_restored"]
    assert evs[0]["reason"] == "crashed" and evs[0]["rc"] == -9
    assert [t[0] for t in ctl.transitions] == list(mb.CENTER_EVENTS)


def test_center_reactor_defers_through_outage_and_flushes():
    """A demote/readmit against a DOWN center must not raise into the
    supervision loop — the intent is remembered and lands on flush once
    the center answers again."""
    class FlakyCenter:
        def __init__(self):
            self.up = False
            self.demoted = set()

        def demote_island(self, island):
            if not self.up:
                raise ConnectionError("center down")
            self.demoted.add(island)

        def readmit_island(self, island):
            if not self.up:
                raise ConnectionError("center down")
            self.demoted.discard(island)

    center = FlakyCenter()
    center.up = True
    reactor = mb.CenterReactor(center)
    ctl = mb.MembershipController(telemetry_=_tm(), reactors=[reactor])
    ctl.join(1)
    ctl.join(2)
    center.up = False                       # the outage begins
    ctl.leave(1, reason="crashed")          # center down: deferred
    assert center.demoted == set()
    assert reactor._pending == {1: "demote"}
    center.up = True
    reactor.flush_pending()
    assert center.demoted == {1}
    assert reactor._pending == {}
    # latest intent wins while deferred
    center.up = False
    ctl.leave(2, reason="crashed")
    ctl.join(2, reason="respawn")
    assert reactor._pending == {2: "readmit"}
    center.up = True
    reactor.flush_pending()
    assert center.demoted == {1}            # 2 readmitted, 1 still out


def test_remote_center_demote_over_the_wire():
    srv = CenterServer(alpha=0.5)
    host, port = srv.start()
    try:
        remote = RemoteCenter(f"{host}:{port}", alpha=0.5)
        p0 = {"w": np.ones(3, np.float32)}
        remote.ensure_init(p0)
        remote.demote_island(5)
        remote.push_delta({"w": np.ones(3, np.float32)}, island=5)
        st = remote.stats()
        assert st["demoted"] == [5]
        assert st["dropped_by_island"] == {"5": 1} or \
            st["dropped_by_island"] == {5: 1}
        assert st["n_updates"] == 0
        remote.readmit_island(5)
        remote.push_delta({"w": np.ones(3, np.float32)}, island=5)
        assert remote.n_updates == 1
    finally:
        srv.stop()


# -- in-mesh reactions (SPMD demote-then-recover) ---------------------------

def _setup(exchanger_cls, n=8, **cfg):
    from theanompi_tpu.parallel.mesh import worker_mesh
    mesh = worker_mesh(n)
    config = {"mesh": mesh, "size": n, "rank": 0, "verbose": False,
              "batch_size": 8, "sync_each_iter": True, **cfg}
    model = TinyModel(config)
    exch = exchanger_cls(config)
    model.compile_iter_fns(exch)
    model.data.shuffle_data(0)
    return model, exch


def _boxed_leaves(state):
    return jax.tree_util.tree_leaves(jax.device_get(state["params"]))


def test_easgd_demote_then_recover_in_mesh():
    """Demoted rank: bit-frozen replica, zero contribution to the center
    mean; readmitted rank: pulled back toward the center — a healthy
    worker is readmitted and participates again."""
    model, exch = _setup(EASGD_Exchanger, sync_freq=1, alpha=0.5)
    for i in range(2):
        model.train_iter(i + 1, None)
    exch.set_active_ranks([r for r in range(8) if r != 2])
    before = _boxed_leaves(model.step_state)
    c_before = jax.device_get(exch.canonical_params(model.step_state))
    exch.exchange(None, 1)
    after = _boxed_leaves(model.step_state)
    for b, a in zip(before, after):
        np.testing.assert_array_equal(b[2], a[2])     # frozen replica
        assert not np.array_equal(b[0], a[0])          # active rank moved
    # center moved by the mean over the 7 ACTIVE ranks only (exact
    # algebra pinned on leaf 0)
    c_after = jax.device_get(exch.canonical_params(model.step_state))
    l0_b, c0_b = before[0], jax.tree_util.tree_leaves(c_before)[0]
    mask = np.ones((8,) + (1,) * (l0_b.ndim - 1), np.float32)
    mask[2] = 0.0
    mean_delta = ((l0_b - c0_b[None]) * mask).sum(axis=0) / 7.0
    np.testing.assert_allclose(
        jax.tree_util.tree_leaves(c_after)[0], c0_b + 0.5 * mean_delta,
        rtol=1e-5)
    # readmit: rank 2 participates again
    exch.set_active_ranks(None)
    before = _boxed_leaves(model.step_state)
    exch.exchange(None, 2)
    after = _boxed_leaves(model.step_state)
    assert any(not np.array_equal(b[2], a[2])
               for b, a in zip(before, after))


def test_gosgd_demote_freezes_alpha_and_params_then_recovers():
    model, exch = _setup(GOSGD_Exchanger, exch_prob=1.0)
    for i in range(2):
        model.train_iter(i + 1, None)
    exch.set_active_ranks([0, 1, 3, 4, 5, 6, 7])
    before = _boxed_leaves(model.step_state)
    a_before = jax.device_get(model.step_state["extra"]["alpha"])
    for i in range(4):
        exch.exchange(None, i + 1)
    after = _boxed_leaves(model.step_state)
    a_after = jax.device_get(model.step_state["extra"]["alpha"])
    for b, a in zip(before, after):
        np.testing.assert_array_equal(b[2], a[2])
    assert a_after[2] == a_before[2]                   # α frozen
    np.testing.assert_allclose(a_after.sum(), a_before.sum(), rtol=1e-5)
    # readmit: regenerated topology includes rank 2 again; with p=1 every
    # rank sends each exchange, so within a few draws rank 2 both moves
    # and its α changes
    exch.set_active_ranks(None)
    before = _boxed_leaves(model.step_state)
    a_b = jax.device_get(model.step_state["extra"]["alpha"])
    for i in range(4):
        exch.exchange(None, 10 + i)
    after = _boxed_leaves(model.step_state)
    a_a = jax.device_get(model.step_state["extra"]["alpha"])
    assert any(not np.array_equal(b[2], a[2])
               for b, a in zip(before, after)) or a_a[2] != a_b[2]


def test_asgd_demoted_rank_keeps_local_replica():
    model, exch = _setup(ASGD_Exchanger, sync_freq=1)
    for i in range(2):
        model.train_iter(i + 1, None)
    exch.set_active_ranks([r for r in range(8) if r != 3])
    before = _boxed_leaves(model.step_state)
    exch.exchange(None, 1)
    after = _boxed_leaves(model.step_state)
    for b, a in zip(before, after):
        np.testing.assert_array_equal(b[3], a[3])      # not reset to center
        # active ranks DID reset to the (common) new center
        np.testing.assert_array_equal(a[0], a[1])
    exch.set_active_ranks(None)
    exch.exchange(None, 2)
    after2 = _boxed_leaves(model.step_state)
    for a in after2:
        np.testing.assert_array_equal(a[3], a[0])      # readmitted: resets


def test_bsp_refuses_membership_change():
    from theanompi_tpu.parallel.exchanger import BSP_Exchanger
    model, exch = _setup(BSP_Exchanger)
    assert not exch.supports_elastic()
    with pytest.raises(NotImplementedError, match="supervise"):
        exch.set_active_ranks([0, 1])


def test_set_active_ranks_validation():
    model, exch = _setup(EASGD_Exchanger, sync_freq=1)
    with pytest.raises(AssertionError):
        exch.set_active_ranks([])
    with pytest.raises(AssertionError):
        exch.set_active_ranks([0, 99])
    # full set normalizes to None (no mask algebra traced)
    exch.set_active_ranks(list(range(8)))
    assert exch._active_ranks is None


def test_mesh_reactor_applies_active_set():
    calls = []

    class StubExch:
        size = 4
        fused = False

        def set_active_ranks(self, active):
            calls.append(tuple(active))

    reactor = mb.MeshReactor(StubExch())
    ctl = mb.MembershipController(telemetry_=_tm(), reactors=[reactor])
    for w in range(4):
        ctl.join(w)
    calls.clear()
    ctl.demote(3)
    assert calls[-1] == (0, 1, 2)
    ctl.readmit(3)
    assert calls[-1] == (0, 1, 2, 3)
