"""Stall watchdog + supervised restart (failure detection/recovery,
SURVEY.md §5 aux subsystems)."""

import os
import time

from theanompi_tpu.utils.watchdog import StallWatchdog


def test_supervisor_restarts_crashed_worker_and_resumes(tmp_path):
    """launcher --supervise: an injected mid-training crash is recovered by
    restarting the worker subprocess with resume=true from the latest
    per-epoch checkpoint; the overall run exits 0."""
    from theanompi_tpu import launcher

    marker = str(tmp_path / "crashed")
    ckpt = str(tmp_path / "ckpt")
    # n_train=256 / (8 workers × batch 8) = 4 iters/epoch: counts 1-4 are
    # epoch 0 (checkpoint saved at its end), so crash_at=5 fires in epoch 1
    # AFTER a checkpoint exists — the restart must take the resume path
    rc = launcher.main([
        "--supervise", "2", "--rule", "bsp",
        "--modelfile", "tests.conftest", "--modelclass", "CrashOnceModel",
        "platform=cpu", "epochs=2", "batch_size=8", "n_train=256",
        "n_workers=8", "verbose=false", "scale_lr=false",
        f"ckpt_dir={ckpt}", f"crash_marker={marker}", "crash_at=5",
    ])
    assert rc == 0
    assert os.path.exists(marker)          # the crash really happened
    # epoch 0's checkpoint predates the crash; epoch 1's must come from the
    # RESUMED run (the crashed run died at its first iteration)
    assert os.path.exists(os.path.join(ckpt, "ckpt_epoch0.npz"))
    with open(os.path.join(ckpt, "LATEST")) as f:
        assert int(f.read()) == 1


def test_supervisor_recovers_the_transformer(tmp_path):
    """Crash recovery is model-agnostic: the LM crashes mid-epoch-1 and the
    supervisor restarts it with resume=true from epoch 0's checkpoint."""
    from theanompi_tpu import launcher

    marker = str(tmp_path / "crashed")
    ckpt = str(tmp_path / "ckpt")
    # synthetic_train=128 / (8 workers × batch 4) = 4 iters/epoch; crash_at=5
    # fires in epoch 1, after epoch 0's checkpoint exists
    rc = launcher.main([
        "--supervise", "2", "--rule", "bsp",
        "--modelfile", "tests.conftest", "--modelclass", "CrashOnceLM",
        "platform=cpu", "epochs=2", "batch_size=4", "synthetic_train=128",
        "synthetic_val=64", "seq_len=16", "vocab=32", "d_model=32",
        "n_head=4", "n_layer=1", "compute_dtype=float32",
        "n_workers=8", "verbose=false", "scale_lr=false",
        f"ckpt_dir={ckpt}", f"crash_marker={marker}", "crash_at=5",
    ])
    assert rc == 0
    assert os.path.exists(marker)
    with open(os.path.join(ckpt, "LATEST")) as f:
        assert int(f.read()) == 1


def test_supervisor_recovers_from_hang_via_stall_action_exit(tmp_path):
    """The full hang-recovery loop: a worker that STALLS (not crashes) is
    killed by its own watchdog (stall_action=exit → rc 42) and the
    supervisor restarts it from the checkpoint; the retry completes."""
    from theanompi_tpu import launcher

    marker = str(tmp_path / "hung")
    ckpt = str(tmp_path / "ckpt")
    t0 = time.time()
    rc = launcher.main([
        "--supervise", "1", "--rule", "bsp",
        "--modelfile", "tests.conftest", "--modelclass", "HangOnceModel",
        "platform=cpu", "epochs=2", "batch_size=8", "n_train=256",
        "n_workers=8", "verbose=false", "scale_lr=false",
        "stall_timeout=1.5", "stall_action=exit",
        f"ckpt_dir={ckpt}", f"hang_marker={marker}", "hang_at=5",
    ])
    elapsed = time.time() - t0
    assert rc == 0
    assert os.path.exists(marker)          # the hang really happened
    # the hang sleeps 300s — finishing far sooner proves the watchdog KILLED
    # the first worker rather than the sleep merely elapsing
    assert elapsed < 180, f"{elapsed:.0f}s: watchdog kill didn't happen"
    with open(os.path.join(ckpt, "LATEST")) as f:
        assert int(f.read()) == 1


def test_watchdog_fires_once_per_stall_and_rearms():
    events = []
    wd = StallWatchdog(timeout_s=0.15, poll_s=0.03,
                       on_stall=lambda el, label: events.append((el, label)))
    with wd:
        wd.beat("iter 1")
        time.sleep(0.4)              # stall → exactly one firing
        assert len(events) == 1
        assert events[0][0] >= 0.15 and events[0][1] == "iter 1"
        wd.beat("iter 2")            # recovery re-arms
        time.sleep(0.4)
        assert len(events) == 2
        assert events[1][1] == "iter 2"
    assert wd.stall_count == 2


def test_watchdog_silent_while_beating():
    events = []
    wd = StallWatchdog(timeout_s=0.2, poll_s=0.03,
                       on_stall=lambda el, label: events.append(el))
    with wd:
        for i in range(8):
            wd.beat(f"iter {i}")
            time.sleep(0.05)
    assert events == []


def test_watchdog_disabled_at_zero_timeout():
    wd = StallWatchdog(timeout_s=0)
    wd.start()
    assert wd._thread is None
    wd.stop()


def test_watchdog_in_worker_loop_detects_slow_iteration(capsys):
    """Through the session API: a deliberately slow data loader trips the
    watchdog mid-epoch; training still completes."""
    import theanompi_tpu as tmpi

    events = []
    orig = StallWatchdog._default_handler
    StallWatchdog._default_handler = \
        lambda self, el, label: events.append((el, label))
    try:
        import tests.conftest as cf

        class SlowData(cf.SyntheticData):
            def next_train_batch(self, count):
                time.sleep(0.3)
                return super().next_train_batch(count)

        class SlowModel(cf.TinyModel):
            def build_model(self):
                super().build_model()
                self.data = SlowData(self.config, self.batch_size,
                                     n_train=64)

        cf.SlowModel = SlowModel     # importable by dotted path
        rule = tmpi.BSP()
        rule.init(devices=4, modelfile="tests.conftest",
                  modelclass="SlowModel", epochs=1, batch_size=8,
                  verbose=False, scale_lr=False, stall_timeout=0.1)
        rule.wait()
    finally:
        StallWatchdog._default_handler = orig
    assert events, "watchdog never fired despite 0.3s iterations"
    assert any("iter" in label or "no heartbeat" in label
               for _, label in events)


def test_watchdog_rearm_protocol_fires_once_per_episode():
    """The single-writer re-arm protocol (tpulint shared-state-race fix):
    the monitor fires ONCE per stall episode, and a heartbeat — the only
    writer of the beat sequence — re-arms it for the next one."""
    stalls = []
    wd = StallWatchdog(timeout_s=0.15, poll_s=0.03, first_timeout_s=0.15,
                       on_stall=lambda el, lab: stalls.append(lab))
    with wd:
        wd.beat("ep1")
        time.sleep(0.5)            # one episode, several poll ticks
        assert wd.stall_count == 1, stalls
        wd.beat("ep2")             # re-arm
        time.sleep(0.5)
        assert wd.stall_count == 2, stalls
    assert stalls == ["ep1", "ep2"]
