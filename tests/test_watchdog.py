"""Stall watchdog (failure detection, SURVEY.md §5 aux subsystems)."""

import time

from theanompi_tpu.utils.watchdog import StallWatchdog


def test_watchdog_fires_once_per_stall_and_rearms():
    events = []
    wd = StallWatchdog(timeout_s=0.15, poll_s=0.03,
                       on_stall=lambda el, label: events.append((el, label)))
    with wd:
        wd.beat("iter 1")
        time.sleep(0.4)              # stall → exactly one firing
        assert len(events) == 1
        assert events[0][0] >= 0.15 and events[0][1] == "iter 1"
        wd.beat("iter 2")            # recovery re-arms
        time.sleep(0.4)
        assert len(events) == 2
        assert events[1][1] == "iter 2"
    assert wd.stall_count == 2


def test_watchdog_silent_while_beating():
    events = []
    wd = StallWatchdog(timeout_s=0.2, poll_s=0.03,
                       on_stall=lambda el, label: events.append(el))
    with wd:
        for i in range(8):
            wd.beat(f"iter {i}")
            time.sleep(0.05)
    assert events == []


def test_watchdog_disabled_at_zero_timeout():
    wd = StallWatchdog(timeout_s=0)
    wd.start()
    assert wd._thread is None
    wd.stop()


def test_watchdog_in_worker_loop_detects_slow_iteration(capsys):
    """Through the session API: a deliberately slow data loader trips the
    watchdog mid-epoch; training still completes."""
    import theanompi_tpu as tmpi

    events = []
    orig = StallWatchdog._default_handler
    StallWatchdog._default_handler = \
        lambda self, el, label: events.append((el, label))
    try:
        import tests.conftest as cf

        class SlowData(cf.SyntheticData):
            def next_train_batch(self, count):
                time.sleep(0.3)
                return super().next_train_batch(count)

        class SlowModel(cf.TinyModel):
            def build_model(self):
                super().build_model()
                self.data = SlowData(self.config, self.batch_size,
                                     n_train=64)

        cf.SlowModel = SlowModel     # importable by dotted path
        rule = tmpi.BSP()
        rule.init(devices=4, modelfile="tests.conftest",
                  modelclass="SlowModel", epochs=1, batch_size=8,
                  verbose=False, scale_lr=False, stall_timeout=0.1)
        rule.wait()
    finally:
        StallWatchdog._default_handler = orig
    assert events, "watchdog never fired despite 0.3s iterations"
    assert any("iter" in label or "no heartbeat" in label
               for _, label in events)
