"""Cross-process asynchrony (round-3 verdict missing #3): the elastic/
downpour center served over a socket, islands in DIFFERENT processes
exchanging with it at their own pace — the reference's server-rank
topology (SURVEY.md §3.2) without MPI."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax

from tests.conftest import TinyModel
from theanompi_tpu.parallel.async_easgd import AsyncEASGDTrainer, ElasticCenter
from theanompi_tpu.parallel.center_server import CenterServer, RemoteCenter


def _factory(cfg):
    cfg = dict(cfg)
    cfg["verbose"] = False
    cfg.setdefault("batch_size", 8)
    return TinyModel(cfg)


def test_remote_center_protocol_matches_local_algebra():
    """RemoteCenter over a live socket must produce the same center as the
    in-memory ElasticCenter given the same op sequence."""
    srv = CenterServer(alpha=0.5)
    host, port = srv.start()
    try:
        remote = RemoteCenter(f"{host}:{port}", alpha=0.5)
        local = ElasticCenter(alpha=0.5)
        p0 = {"a": np.ones((3, 2), np.float32), "b": np.zeros(4, np.float32)}
        remote.ensure_init(p0)
        local.ensure_init(p0)
        d1 = {"a": np.full((3, 2), 0.5, np.float32),
              "b": np.arange(4, dtype=np.float32)}
        remote.push_delta(d1, island=0)
        local.push_delta(d1, island=0)
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                     remote.pull(), local.pull())
        # downpour round-trip: absorbed in full and returned atomically
        r2 = remote.push_pull(d1, island=1)
        l2 = local.push_pull(d1, island=1)
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b), r2, l2)
        assert remote.n_updates == local.n_updates == 2
        assert remote.updates_by_island == {0: 1, 1: 1}
    finally:
        srv.stop()


def test_async_asgd_islands_in_process():
    """Downpour islands: push_pull absorbs the island delta and resets the
    island to the fresh center — both islands drift toward consensus."""
    tr = AsyncEASGDTrainer(_factory, {
        "async_islands": 2, "sync_freq": 2, "seed": 3}, rule="asgd")
    tr.start()
    deadline = time.time() + 120
    while (min(r.exchanges_done for r in tr.islands) < 2
           and time.time() < deadline):
        time.sleep(0.05)
    tr.stop_and_join(timeout=60)
    assert all(r.error is None for r in tr.islands)
    assert tr.center.n_updates >= 4
    # after an exchange the island equals the then-fresh center; training
    # continues, so just pin that all replicas stay finite and the center
    # moved off its init
    c = tr.center_params
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree_util.tree_leaves(c))


def test_asgd_rule_async_mode():
    """The 3-call session API selects the downpour-island path by config."""
    import theanompi_tpu as tmpi
    rule = tmpi.ASGD()
    rule.init(devices=4, modelfile="tests.conftest", modelclass="TinyModel",
              asgd_mode="async", async_islands=2, sync_freq=2,
              run_seconds=4.0, batch_size=8, verbose=False)
    tr = rule.wait()
    assert tr.center.n_updates >= 1
    assert all(r.error is None for r in tr.islands)


@pytest.mark.parametrize("rule", ["easgd", "asgd"])
def test_two_process_async_center(rule):
    """TWO independent JAX processes (no jax.distributed) join one center
    over TCP; the throttled process lags while the other progresses — the
    reference's defining asynchrony, across real process boundaries."""
    srv = CenterServer(alpha=0.5)
    host, port = srv.start()
    helper = os.path.join(os.path.dirname(__file__), "async_center_proc.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    try:
        procs = [
            subprocess.Popen(
                [sys.executable, helper, str(i), f"{host}:{port}", rule,
                 "8.0" if i == 1 else "0.0",    # proc 1 = straggler
                 # proc 0 runs GOAL-based (until 2 exchanges) so CI-box
                 # contention can't flake the budget; the straggler keeps a
                 # fixed short window
                 "6.0" if i == 1 else "-1"],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                env=env)
            for i in range(2)]
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=600)
            assert p.returncode == 0, f"proc failed:\n{err[-3000:]}"
            line = [ln for ln in out.splitlines() if ln.startswith("ST ")][0]
            outs.append(json.loads(line[3:]))
    finally:
        srv.stop()
    fast = next(o for o in outs if o["proc"] == 0)["islands"][0]
    slow = next(o for o in outs if o["proc"] == 1)["islands"][0]
    # the fast process kept stepping/exchanging while the straggler slept
    assert fast["steps"] >= 4 and fast["exchanges"] >= 2, (fast, slow)
    assert slow["steps"] <= 2, (fast, slow)
    assert fast["steps"] > slow["steps"]
    # the shared center heard from the fast process (island_base 0); its
    # bookkeeping is consistent across processes
    by_island = srv.center.updates_by_island
    assert by_island.get(0, 0) >= 2, by_island
    assert srv.center.n_updates == sum(by_island.values())


def test_center_serve_mixed_topology_any_join_order():
    """A trainer's LOCAL islands (pytree interface) and a REMOTE client
    (leaf-list wire) must share one canonical store — this exact topology
    crashed before the flat-leaf center refactor."""
    tr = AsyncEASGDTrainer(_factory, {
        "async_islands": 1, "sync_freq": 2, "seed": 3,
        "center_serve": True, "center_keep_serving": True})
    tr.start()
    deadline = time.time() + 120
    while tr.islands[0].exchanges_done < 1 and time.time() < deadline:
        time.sleep(0.05)
    tr.stop_and_join(timeout=60)           # islands quiesce; server stays up
    try:
        snap = tr.center.pull()
        remote = RemoteCenter(tr.center_address, alpha=0.5)
        remote.ensure_init(snap)           # no-op on the live store
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                     snap, remote.pull())
        delta = jax.tree.map(lambda x: np.ones_like(x), snap)
        remote.push_delta(delta, island=7)  # alpha=0.5 on the server side
        after = tr.center.pull()
        jax.tree.map(lambda s, a: np.testing.assert_allclose(
            a, s + 0.5, rtol=1e-6), snap, after)
        assert tr.center.updates_by_island.get(7) == 1
    finally:
        tr._server.stop()

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
