"""End-to-end data-prep → train → plot chain, and deterministic replay.

The prep script (scripts/make_batch_dataset.py) must produce the on-disk
contract the ImageNet loader consumes; a session over it must run and dump
records that the plot script can render.  Replay determinism (same seeds →
bit-identical runs) is the rebuild's answer to the reference's missing race
detection (SURVEY.md §5).
"""

import os
import subprocess
import sys

import jax
import numpy as np

import theanompi_tpu as tmpi
from theanompi_tpu.parallel import steps

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_prep_train_plot_chain(tmp_path):
    data_dir = str(tmp_path / "data")
    rec_dir = str(tmp_path / "rec")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/make_batch_dataset.py"),
         "--synthetic", "4", "--out", data_dir, "--batch-size", "8"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert os.path.isdir(os.path.join(data_dir, "train_hkl"))

    rule = tmpi.BSP()
    rule.init(devices=2, modelfile="theanompi_tpu.models.alex_net",
              modelclass="AlexNet", data_dir=data_dir, batch_size=8,
              crop_size=227, epochs=1, printFreq=1, compute_dtype="float32",
              scale_lr=False, learning_rate=0.001, verbose=False,
              record_dir=rec_dir)
    rec = rule.wait()
    assert rec._all_records and np.isfinite(rec._all_records[-1]["cost"])

    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/plot_records.py"),
         rec_dir],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert os.path.exists(os.path.join(rec_dir, "curves.png"))


def test_scaling_sweep_comm_share(tmp_path):
    """--measure-comm must surface a comm_share column per strategy row by
    differencing the fused step against the 'none' strategy (the reference's
    t_train/t_comm table decomposition, SURVEY.md §6)."""
    import json
    env = dict(os.environ, TMPI_FORCE_CPU="1")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/scaling_sweep.py"),
         "--model", "cifar10", "--strategies", "allreduce",
         "--iters", "2", "--warmup", "1", "--batch-size", "8",
         "--json", "--measure-comm"],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    rows = [json.loads(l) for l in r.stdout.splitlines()
            if l.startswith("{")]
    assert rows, r.stdout
    assert all("comm_share" in row for row in rows)
    assert any(row["workers"] > 1 for row in rows)


def test_deterministic_replay():
    """Two runs with identical seeds/config must be bit-identical — the
    deterministic-replay guarantee the reference could not make."""
    def run():
        rule = tmpi.GOSGD()   # the rule with the most RNG in play
        rule.init(devices=4, modelfile="theanompi_tpu.models.cifar10",
                  modelclass="Cifar10_model", epochs=1, synthetic_train=128,
                  synthetic_val=64, batch_size=8, compute_dtype="float32",
                  verbose=False, scale_lr=False, exch_prob=0.7, seed=11)
        rule.wait()
        return jax.device_get(rule.model.step_state["params"])

    a, b = run(), run()
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(la, lb)

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
