"""LM text generation (TransformerLM.generate): a jit-compiled decode scan.

The synthetic stream's rule is x[t+1] = x[t]+1 (mod V) with 5% noise — a
briefly-trained model must continue prompts with the +1 rule, which makes
generation quality machine-checkable without real text data.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from theanompi_tpu.models.transformer_lm import (MoETransformerLM,
                                                 TransformerLM)
from theanompi_tpu.parallel.exchanger import BSP_Exchanger
from theanompi_tpu.parallel.mesh import worker_mesh

CFG = dict(verbose=False, batch_size=16, seq_len=32, vocab=16,
           synthetic_train=512, synthetic_val=64, noise=0.0,
           d_model=64, n_head=4, n_layer=2, compute_dtype=jnp.float32,
           learning_rate=3e-3)


def _train(model, n_steps):
    model.compile_iter_fns(BSP_Exchanger(model.config))
    model.data.shuffle_data(0)
    for i in range(n_steps):
        model.train_iter(i, None)
    return model


def test_generate_learns_the_increment_rule(mesh8):
    mesh = worker_mesh(4)
    model = _train(TransformerLM({**CFG, "mesh": mesh, "size": 4,
                                  "rank": 0}), 60)
    prompt = np.array([[3, 4, 5, 6], [11, 12, 13, 14]], np.int32)
    out = model.generate(prompt, max_new_tokens=8)
    assert out.shape == (2, 8)
    want = np.stack([np.arange(7, 15) % 16, np.arange(15, 23) % 16])
    acc = float(np.mean(out == want))
    assert acc >= 0.8, (out, want, acc)


def test_generate_greedy_is_deterministic_sampling_varies(mesh8):
    mesh = worker_mesh(2)
    model = _train(TransformerLM({**CFG, "mesh": mesh, "size": 2,
                                  "rank": 0}), 10)
    p = np.array([[1, 2, 3]], np.int32)
    a = model.generate(p, max_new_tokens=6)
    b = model.generate(p, max_new_tokens=6)
    np.testing.assert_array_equal(a, b)          # greedy: deterministic
    s1 = model.generate(p, max_new_tokens=6, temperature=2.0, seed=1)
    s2 = model.generate(p, max_new_tokens=6, temperature=2.0, seed=2)
    assert s1.shape == (1, 6)
    assert not np.array_equal(s1, s2)            # different seeds differ
    np.testing.assert_array_equal(
        s1, model.generate(p, max_new_tokens=6, temperature=2.0, seed=1))


def test_kv_cache_matches_full_forward_decode(mesh8):
    """The KV-cache sampler must emit the same tokens as the full-forward
    sampler (same trained params, greedy) — and the raw decode-step logits
    path is pinned by the attention layers' own math being identical."""
    mesh = worker_mesh(4)
    model = _train(TransformerLM({**CFG, "mesh": mesh, "size": 4,
                                  "rank": 0}), 40)
    prompt = np.array([[2, 3, 4], [9, 10, 11]], np.int32)
    kv = model.generate(prompt, max_new_tokens=10, kv_cache=True)
    full = model.generate(prompt, max_new_tokens=10, kv_cache=False)
    # the two graphs reduce in different orders, so a near-tied logit could
    # flip one argmax in the last ulp — require near-total, not bit, parity
    assert np.mean(kv == full) >= 0.9, (kv, full)
    kv_s = model.generate(prompt, max_new_tokens=6, temperature=1.0, seed=7)
    full_s = model.generate(prompt, max_new_tokens=6, temperature=1.0,
                            seed=7, kv_cache=False)
    assert np.mean(kv_s == full_s) >= 0.8, (kv_s, full_s)


def test_generate_moe_and_untrained(mesh8):
    mesh = worker_mesh(2)
    moe = MoETransformerLM({**CFG, "mesh": mesh, "size": 2, "rank": 0,
                            "moe_experts": 4, "moe_every": 2})
    # untrained (no step_state yet): falls back to init params
    out = moe.generate(np.array([0, 1, 2], np.int32), max_new_tokens=4)
    assert out.shape == (1, 4)
    assert ((0 <= out) & (out < CFG["vocab"])).all()


def test_moe_kv_cache_matches_full_forward(mesh8):
    """MoE blocks decode through the KV cache too (per-token routing; aux
    discarded).  Inference routing is DROP-FREE, so the per-step and
    full-buffer samplers agree in every regime — including the default
    capacity factor and multi-row batches (where training-style capacity
    would drop different tokens per sampler)."""
    mesh = worker_mesh(4)
    moe = MoETransformerLM({**CFG, "mesh": mesh, "size": 4, "rank": 0,
                            "moe_experts": 4, "moe_every": 2})
    _train(moe, 40)
    prompt = np.array([[2, 3, 4], [8, 9, 10], [11, 12, 13],
                       [1, 2, 3]], np.int32)
    kv = moe.generate(prompt, max_new_tokens=8, kv_cache=True)
    full = moe.generate(prompt, max_new_tokens=8, kv_cache=False)
    assert np.mean(kv == full) >= 0.85, (kv, full)


def test_generate_rejects_overflow(mesh8):
    mesh = worker_mesh(2)
    model = TransformerLM({**CFG, "mesh": mesh, "size": 2, "rank": 0})
    with pytest.raises(AssertionError, match="seq_len"):
        model.generate(np.zeros((1, 30), np.int32), max_new_tokens=8)
    with pytest.raises(AssertionError, match="prompt token"):
        model.generate(np.zeros((1, 0), np.int32), max_new_tokens=2)


def test_generate_from_model_parallel_layouts(mesh8):
    """tp and pp models sample through a dense twin on the gathered global
    params — same tokens as the dense model trained identically."""
    dense = _train(TransformerLM({**CFG, "mesh": worker_mesh(2),
                                  "size": 2, "rank": 0}), 30)
    prompt = np.array([[3, 4, 5]], np.int32)
    want = dense.generate(prompt, max_new_tokens=6)
    for kw in ({"tp": 4}, {"pp": 2, "pp_microbatches": 4}):
        mesh = worker_mesh(2, tp=kw.get("tp", 1), pp=kw.get("pp", 1))
        cfg = {**CFG, "mesh": mesh, "size": 2, "rank": 0, **kw}
        mp = _train(TransformerLM(cfg), 30)
        got = mp.generate(prompt, max_new_tokens=6)
        # tp AND pp (2 stages × 1 of the dense model's 2 layers) are the
        # SAME model as the dense run — exact token parity
        np.testing.assert_array_equal(got, want)
    # the gather must not corrupt live params pre-compile (regression)
    fresh = TransformerLM({**CFG, "mesh": worker_mesh(2, pp=2), "size": 2,
                           "rank": 0, "pp": 2, "pp_microbatches": 4})
    fresh.generate(prompt, max_new_tokens=2)
    assert "blocks" in fresh.params
    from theanompi_tpu.parallel.exchanger import BSP_Exchanger
    fresh.compile_iter_fns(BSP_Exchanger(fresh.config))

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
