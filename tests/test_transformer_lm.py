"""Transformer LM (beyond-parity model family) through the full framework:
contract compliance, BSP training convergence, rule/exchanger compatibility.
"""

import jax
import numpy as np
import pytest

from theanompi_tpu.models.transformer_lm import LMData, TransformerLM
from theanompi_tpu.parallel.exchanger import BSP_Exchanger, get_exchanger
from theanompi_tpu.parallel.mesh import worker_mesh


def _model(n=4, **cfg):
    mesh = worker_mesh(n)
    config = {"mesh": mesh, "size": n, "rank": 0, "verbose": False,
              "batch_size": 8, "seq_len": 32, "vocab": 32, "d_model": 64,
              "n_layer": 2, "n_head": 4, "compute_dtype": "float32",
              "synthetic_train": 512, "synthetic_val": 128,
              "sync_each_iter": True, **cfg}
    import jax.numpy as jnp
    if config["compute_dtype"] == "float32":
        config["compute_dtype"] = jnp.float32
    m = TransformerLM(config)
    return m, config


def test_lm_data_next_token_alignment():
    d = LMData({"size": 1, "seq_len": 16, "vocab": 32,
                "synthetic_train": 64, "synthetic_val": 64}, batch_size=8)
    d.shuffle_data(0)
    b = d.next_train_batch(1)
    assert b["x"].dtype == np.int32 and b["y"].dtype == np.int32
    assert b["x"].shape == b["y"].shape == (8, 16)
    # y is x shifted by one within the underlying sequence: where no noise
    # flip hit, y[t] == (x[t]+1) % vocab — check it holds for most positions
    match = (b["y"] == (b["x"] + 1) % 32).mean()
    assert match > 0.8, match


def test_lm_trains_under_bsp():
    m, config = _model()
    m.compile_iter_fns(BSP_Exchanger(config))
    m.data.shuffle_data(0)
    costs = []
    for i in range(1, 13):
        m.train_iter(i, None)
        costs.append(float(m.current_info["cost"]))
    # the modular-increment rule is easy: loss must drop well below ln(V)
    assert costs[-1] < costs[0] * 0.6, costs
    m.begin_val()
    m.val_iter(1, None)
    m.end_val()


@pytest.mark.parametrize("rule", ["easgd", "gosgd"])
def test_lm_runs_under_async_rules(rule):
    m, config = _model(sync_freq=2, exch_prob=0.8)
    exch = get_exchanger(rule, config)
    m.compile_iter_fns(exch)
    m.data.shuffle_data(0)
    for i in range(1, 5):
        m.train_iter(i, None)
        exch.exchange(None, i)
    assert np.isfinite(float(m.current_info["cost"]))


def test_lm_session_api():
    """Through the 3-call rule API, like any zoo model."""
    import theanompi_tpu as tmpi
    rule = tmpi.BSP()
    rule.init(devices=4, modelfile="theanompi_tpu.models.transformer_lm",
              modelclass="TransformerLM", epochs=1, batch_size=8,
              seq_len=32, vocab=32, d_model=64, n_layer=1, n_head=4,
              compute_dtype="float32", synthetic_train=256,
              synthetic_val=128, verbose=False, scale_lr=False)
    rec = rule.wait()
    assert rec.epoch_records and np.isfinite(rec.epoch_records[-1]["val_cost"])


def test_remat_is_loss_equivalent(mesh4):
    """remat=True (per-block jax.checkpoint) changes memory, not math."""
    import jax.numpy as jnp
    from theanompi_tpu.models.transformer_lm import TransformerLM
    from theanompi_tpu.parallel.exchanger import BSP_Exchanger

    def run(remat):
        cfg = {"mesh": mesh4, "size": 4, "rank": 0, "verbose": False,
               "remat": remat, "batch_size": 8, "seq_len": 16, "vocab": 32,
               "d_model": 32, "n_head": 4, "n_layer": 2,
               "synthetic_train": 64, "compute_dtype": jnp.float32}
        m = TransformerLM(cfg)
        m.compile_iter_fns(BSP_Exchanger(cfg))
        m.data.shuffle_data(0)
        costs = []
        for i in range(4):
            m.train_iter(i, None)
            costs.append(float(m.current_info["cost"]))
        return costs

    np.testing.assert_allclose(run(True), run(False), rtol=1e-6, atol=1e-8)

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
