"""Mixture-of-Experts / expert parallelism (parallel/moe.py).

Oracles are analytic (uniform-router aux = 1, tie-break routing to expert 0
scaled by the 1/E gate) or our own dense/ep=1 runs — the reference
(Theano-MPI) has no sparse models.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from theanompi_tpu.models import layers as L
from theanompi_tpu.models.transformer_lm import MoETransformerLM
from theanompi_tpu.parallel.exchanger import BSP_Exchanger
from theanompi_tpu.parallel.mesh import MODEL_AXIS, worker_mesh
from theanompi_tpu.parallel.moe import MoE
from theanompi_tpu.jax_compat import shard_map

CFG = dict(verbose=False, batch_size=8, seq_len=16, vocab=32,
           synthetic_train=64, synthetic_val=32,
           d_model=32, n_head=4, n_layer=2, moe_experts=4, moe_every=2,
           compute_dtype=jnp.float32)


def _make(dp, tp, **kw):
    mesh = worker_mesh(dp, tp=tp)
    cfg = {**CFG, "mesh": mesh, "size": dp, "rank": 0, "tp": tp, **kw}
    return MoETransformerLM(cfg)


def _train_steps(model, n_steps):
    exch = BSP_Exchanger(model.config)
    model.compile_iter_fns(exch)
    model.data.shuffle_data(0)
    costs = []
    for i in range(n_steps):
        model.train_iter(i, None)
        costs.append(float(model.current_info["cost"]))
    return costs


def test_moe_uniform_router_matches_scaled_dense():
    """wg = 0 → uniform probs, argmax ties to expert 0, gate = 1/E: the MoE
    output must equal (1/E)·MLP_expert0(x) and aux must be exactly 1."""
    r = np.random.RandomState(0)
    d, E = 16, 4
    moe = MoE(d, E, mlp_ratio=2, ep=1, capacity_factor=float(E),
              compute_dtype=jnp.float32)
    params = moe.init(jax.random.key(0))
    params = dict(params, wg=jnp.zeros_like(params["wg"]))
    x = jnp.asarray(r.randn(12, d).astype(np.float32))
    y, aux = moe.apply(params, x)
    w1, b1 = params["w1"][0], params["b1"][0]
    w2, b2 = params["w2"][0], params["b2"][0]
    dense = jnp.dot(jax.nn.relu(jnp.dot(x, w1) + b1), w2) + b2
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense) / E,
                               rtol=1e-5, atol=1e-6)
    assert float(aux) == pytest.approx(1.0, abs=1e-6)


def test_moe_capacity_drops_overflow_tokens():
    """TRAINING: all tokens routed to expert 0 with capacity < N — rows
    past capacity come out ZERO (they ride the block's residual instead).
    INFERENCE is drop-free (capacity = N): every row gets its expert."""
    r = np.random.RandomState(1)
    d, E, n = 8, 2, 10
    moe = MoE(d, E, mlp_ratio=1, ep=1, capacity_factor=0.4,  # C = 2
              compute_dtype=jnp.float32)
    params = moe.init(jax.random.key(0))
    wg = np.zeros((d, E), np.float32)
    x = jnp.asarray(np.abs(r.randn(n, d)).astype(np.float32))  # positive
    wg[:, 0] = 1.0                                             # favor e0
    params = dict(params, wg=jnp.asarray(wg))
    y, _ = moe.apply(params, x, train=True)
    C = moe.capacity(n, train=True)
    assert C == 2
    np.testing.assert_array_equal(np.asarray(y[C:]), 0.0)
    assert np.abs(np.asarray(y[:C])).sum() > 0
    y_inf, _ = moe.apply(params, x, train=False)
    assert (np.abs(np.asarray(y_inf)).sum(axis=1) > 0).all()  # no zero rows


def test_moe_ep4_matches_ep1(mesh8):
    """Expert-parallel ep=4 training must trace the dense-layout ep=1 loss
    curve (same seed/data): routing is replicated, only the expert placement
    and psum order differ."""
    m1 = _make(dp=2, tp=1)
    m4 = _make(dp=2, tp=4)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), m1.params, m4.params)
    c1 = _train_steps(m1, 5)
    c4 = _train_steps(m4, 5)
    np.testing.assert_allclose(c4, c1, rtol=2e-4, atol=2e-5)


def test_moe_converges_and_validates(mesh8):
    model = _make(dp=4, tp=2)
    costs = _train_steps(model, 8)
    assert np.isfinite(costs).all()
    assert costs[-1] < costs[0]          # learnable synthetic stream
    model.begin_val()
    model.val_iter(0, None)
    model.end_val()


def test_moe_pp_matches_dense_layout(mesh8):
    """A homogeneous all-MoE stack (moe_every=1) pipelines over 'pipe': same
    init (stacked from the same keys) and — with drop-free capacity and the
    aux term off — the same loss curve as the pp=1 layout.  (With binding
    capacity the layouts legitimately differ: GPipe routes per MICROBATCH,
    so the capacity cutoff and the nonlinear aux statistic see B/M-row
    token sets — inherent pipeline-MoE semantics, not an implementation
    gap.)"""
    def make(pp):
        mesh = worker_mesh(2, pp=pp)
        cfg = {**CFG, "mesh": mesh, "size": 2, "rank": 0, "tp": 1, "pp": pp,
               "moe_every": 1, "n_layer": 4, "pp_microbatches": 4,
               "capacity_factor": 4.0, "moe_aux": 0.0}
        return MoETransformerLM(cfg)

    m1, m4 = make(1), make(4)
    stacked = m4.params["blocks"]
    for i, blk in enumerate(m1.blocks):
        jax.tree.map(lambda s, d: np.testing.assert_array_equal(
            np.asarray(s[i]), np.asarray(d)),
            stacked, m1.params[blk.name])
    c1 = _train_steps(m1, 5)
    c4 = _train_steps(m4, 5)
    np.testing.assert_allclose(c4, c1, rtol=2e-4, atol=2e-5)


def test_moe_pp_with_aux_converges(mesh8):
    """Default capacity/aux on the pipelined MoE stack: the aux rides the
    pipeline (bubble ticks masked) and training converges."""
    mesh = worker_mesh(2, pp=4)
    cfg = {**CFG, "mesh": mesh, "size": 2, "rank": 0, "tp": 1, "pp": 4,
           "moe_every": 1, "n_layer": 4, "pp_microbatches": 4}
    m = MoETransformerLM(cfg)
    costs = _train_steps(m, 8)
    assert np.isfinite(costs).all()
    assert np.mean(costs[-3:]) < np.mean(costs[:3])


def test_moe_mixed_stack_rejects_pp(mesh8):
    mesh = worker_mesh(2, pp=4)
    cfg = {**CFG, "mesh": mesh, "size": 2, "rank": 0, "tp": 1, "pp": 4,
           "moe_every": 2, "n_layer": 4}
    with pytest.raises(AssertionError, match="homogeneous"):
        MoETransformerLM(cfg)


def test_moe_checkpoint_roundtrip(tmp_path, mesh8):
    from theanompi_tpu.parallel import steps
    model = _make(dp=2, tp=4)
    _train_steps(model, 3)
    model.save(str(tmp_path), epoch=0, count=3)
    before = jax.device_get(steps.tree_to_host(model.step_state["params"]))
    model2 = _make(dp=2, tp=4)
    model2.compile_iter_fns(BSP_Exchanger(model2.config))
    assert model2.load(str(tmp_path)) == 0
    after = jax.device_get(steps.tree_to_host(model2.step_state["params"]))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), before, after)


# -- round 4: sequence-sharded MoE (all-to-all dispatch) ---------------------

def _make_sp(dp, sp, tp=1, **kw):
    mesh = worker_mesh(dp, tp=tp, sp=sp)
    cfg = {**CFG, "mesh": mesh, "size": dp, "rank": 0, "tp": tp, "sp": sp,
           **kw}
    return MoETransformerLM(cfg)


def test_moe_sp_a2a_layer_exact_vs_dense(mesh8):
    """The all-to-all dispatch itself is EXACT: identical inputs route
    identically, travel to their seq-sharded expert and back, and
    reproduce the dense layer's output and aux to float noise."""
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P
    S, B, T, D, E = 4, 16, 16, 32, 4
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, S),
                ("workers", "seq"))
    r = np.random.RandomState(0)
    x = jnp.asarray(r.randn(B, T, D).astype(np.float32))
    from theanompi_tpu.parallel.moe import MoE
    dense = MoE(D, E, ep=1, capacity_factor=100.0,
                compute_dtype=jnp.float32)
    params = dense.init(jax.random.key(1))
    y_d, _ = dense.apply(params, x, train=True)
    sp = MoE(D, E, ep=1, seq_shards=S, seq_axis="seq",
             capacity_factor=100.0, compute_dtype=jnp.float32)
    pspec = sp.specs()

    def body(p, xb):
        y, _aux = sp.apply(p, xb, train=True)
        return y

    f = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(pspec, P("workers", "seq", None)),
        out_specs=P("workers", "seq", None)))
    pp = {k: jax.device_put(params[k], NamedSharding(mesh, pspec[k]))
          for k in params}
    y_s = f(pp, jax.device_put(
        x, NamedSharding(mesh, P("workers", "seq", None))))
    np.testing.assert_allclose(np.asarray(y_d), np.asarray(y_s),
                               rtol=1e-6, atol=1e-7)


def test_moe_sp_model_close_to_dense_dropfree(mesh8):
    """Model-level: ring-vs-dense attention reorders fp32 sums by ~1e-6,
    and the ARGMAX router amplifies borderline flips into different expert
    assignments — so tight parity is ill-posed at the model level (the
    layer is exact above).  The loss curves must still agree loosely."""
    dense = _make(dp=2, tp=1, capacity_factor=100.0)
    sp = _make_sp(dp=2, sp=4, capacity_factor=100.0)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), dense.params, sp.params)
    c_dense = _train_steps(dense, 4)
    c_sp = _train_steps(sp, 4)
    np.testing.assert_allclose(c_sp, c_dense, rtol=2e-2)
    # expert tables really shard over 'seq'
    from theanompi_tpu.parallel.mesh import SEQ_AXIS, WORKER_AXIS
    w1 = sp.step_state["params"]["block1"]["moe"]["w1"]
    assert w1.sharding.spec == (WORKER_AXIS, SEQ_AXIS), w1.sharding.spec


def test_moe_sp_trains_with_default_capacity(mesh8):
    """Default capacity (tokens drop per source shard): trains finite and
    the loss decreases; Σ capacity budget matches the replicated path."""
    m = _make_sp(dp=2, sp=4)
    costs = _train_steps(m, 6)
    assert np.isfinite(costs).all()
    assert np.mean(costs[-3:]) < np.mean(costs[:3])
    m.begin_val()
    m.val_iter(0)
    m.end_val()


def test_moe_sp_tp_3d_smoke(mesh8):
    """sp×tp MoE: experts on 'model', tokens on 'seq' — one full train+val
    step on the 3-D mesh."""
    m = _make_sp(dp=2, sp=2, tp=2, moe_every=1)
    costs = _train_steps(m, 2)
    assert np.isfinite(costs).all()
    m.begin_val()
    m.val_iter(0)
    m.end_val()


def test_moe_sp_uses_global_positions(mesh8):
    """Regression (round-4 review): MoE's _forward must offset position ids
    by the seq rank, like the base model.  With an amplified position table
    the local-positions bug would blow the costs apart; with global
    positions the sp model tracks the dense one."""
    dense = _make(dp=2, tp=1, capacity_factor=100.0)
    sp = _make_sp(dp=2, sp=4, capacity_factor=100.0)
    # make position embeddings LOUD and position-distinctive
    amp = np.outer(np.arange(CFG["seq_len"], dtype=np.float32) - 8.0,
                   np.ones(CFG["d_model"], np.float32))
    for m in (dense, sp):
        m.params = dict(m.params, pos={"w": jnp.asarray(amp)})
    c_d = _train_steps(dense, 1)[0]
    c_s = _train_steps(sp, 1)[0]
    assert abs(c_s - c_d) < 0.1 * abs(c_d), (c_d, c_s)


# -- round 4: top-k (GShard-style) routing -----------------------------------

def test_moe_top2_identical_experts_equals_dense():
    """With every expert's weights identical and drop-free capacity, the
    normalized top-2 gates sum to 1, so y = MLP(x) EXACTLY — whatever the
    router does."""
    r = np.random.RandomState(1)
    d, E = 16, 4
    moe = MoE(d, E, mlp_ratio=2, ep=1, top_k=2, capacity_factor=100.0,
              compute_dtype=jnp.float32)
    params = moe.init(jax.random.key(0))
    # copy expert 0 into every expert; router weights stay random
    for k in ("w1", "b1", "w2", "b2"):
        params[k] = jnp.broadcast_to(params[k][:1], params[k].shape)
    x = jnp.asarray(r.randn(24, d).astype(np.float32))
    y, aux = moe.apply(params, x, train=True)
    w1, b1 = params["w1"][0], params["b1"][0]
    w2, b2 = params["w2"][0], params["b2"][0]
    dense = jnp.dot(jax.nn.relu(jnp.dot(x, w1) + b1), w2) + b2
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense),
                               rtol=1e-5, atol=1e-6)
    assert np.isfinite(float(aux))


def test_moe_top2_priority_capacity_drops_secondaries_first():
    """REAL rank contention (GShard priority ordering): group A routes
    (e0 primary, e1 secondary), group B the mirror.  With C=4 and 3+3
    tokens, every primary survives and each expert keeps exactly ONE
    secondary (the earliest), so precisely tokens 0 and 3 get their full
    top-2 output — with identical experts the per-token output SCALE
    reveals exactly which routes were kept.  Inverting rank priority or
    mis-accumulating the slot base changes the scales and fails."""
    d, E, n_g = 4, 2, 3
    moe = MoE(d, E, mlp_ratio=1, ep=1, top_k=2, capacity_factor=1.0,
              compute_dtype=jnp.float32)
    params = moe.init(jax.random.key(2))
    for k in ("w1", "b1", "w2", "b2"):     # identical experts: y = s·MLP(x)
        params[k] = jnp.broadcast_to(params[k][:1], params[k].shape)
    wg = np.zeros((d, E), np.float32)
    wg[0, 0] = 1.0
    wg[1, 1] = 1.0
    params = dict(params, wg=jnp.asarray(wg))
    a = np.array([2.0, 1.0, 0.0, 0.0], np.float32)   # prefers e0 then e1
    b = np.array([1.0, 2.0, 0.0, 0.0], np.float32)   # prefers e1 then e0
    x = jnp.asarray(np.stack([a, a, a, b, b, b]))    # rows 0-2 = A, 3-5 = B
    # capacity(6, train) = ceil(6*2/2 * 1.0) = 6 — too roomy; force C=4 via
    # eval-free capacity_factor choice: use cf = 4/6 exactly
    moe.capacity_factor = 4.0 / 6.0
    assert moe.capacity(6, True) == 4
    y, _ = moe.apply(params, x, train=True)
    w1, b1_, w2, b2_ = (params["w1"][0], params["b1"][0],
                        params["w2"][0], params["b2"][0])
    mlp = np.asarray(jnp.dot(jax.nn.relu(jnp.dot(x, w1) + b1_), w2) + b2_)
    scale = np.asarray(y)[:, 0] / mlp[:, 0]          # per-token kept gates
    # normalized top-2 gates of softmax([2,1]): g_hi ≈ 0.731, g_lo ≈ 0.269
    g_hi = float(np.exp(2) / (np.exp(2) + np.exp(1)))
    # rows 0 and 3: both routes kept (scale 1); the other four lose ONLY
    # their secondary (scale = primary gate) — primaries never drop
    np.testing.assert_allclose(scale[[0, 3]], 1.0, rtol=1e-5)
    np.testing.assert_allclose(scale[[1, 2, 4, 5]], g_hi, rtol=1e-5)


def test_moe_top2_lm_trains_and_composes_with_ep(mesh8):
    """moe_topk=2 through the model config: trains finite/decreasing dense
    AND with experts sharded over 'model' (ep=tp=2)."""
    for tp in (1, 2):
        m = _make(dp=2, tp=tp, moe_topk=2)
        costs = _train_steps(m, 5)
        assert np.isfinite(costs).all()
        assert np.mean(costs[-2:]) < np.mean(costs[:2])

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
