"""GAN family tests (reference parity: ``wgan.py`` / ``lsgan.py``,
SURVEY.md §2.7): shapes, combined G/D step correctness, the n_critic
gradient gate, WGAN weight clipping, and multi-worker BSP compilation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.parallel import steps
from theanompi_tpu.parallel.exchanger import BSP_Exchanger, GOSGD_Exchanger
from theanompi_tpu.parallel.mesh import worker_mesh


def _build(cls_name, n=1, **cfg):
    from theanompi_tpu.models import gan
    mesh = worker_mesh(n)
    config = {"mesh": mesh, "size": n, "rank": 0, "verbose": False,
              "batch_size": 4, "compute_dtype": jnp.float32,
              "synthetic_train": 8 * n, "synthetic_val": 8 * n,
              "base_width": 8, "z_dim": 16, **cfg}
    return getattr(gan, cls_name)(config)


def test_reference_alias_paths_import():
    from theanompi_tpu.models.wgan import WGAN
    from theanompi_tpu.models.lsgan import LSGAN
    from theanompi_tpu.models import gan
    assert WGAN is gan.WGAN and LSGAN is gan.LSGAN


def test_generator_output_shape_and_range():
    m = _build("WGAN")
    z = jax.random.normal(jax.random.key(0), (3, m.z_dim))
    imgs, _ = m.generate(m.params, z)
    assert imgs.shape == (3, 32, 32, 3)
    assert bool((jnp.abs(imgs.astype(jnp.float32)) <= 1.0).all())  # tanh


@pytest.mark.parametrize("cls_name", ["WGAN", "LSGAN"])
def test_gan_train_step_finite(cls_name):
    m = _build(cls_name)
    m.compile_iter_fns(BSP_Exchanger(m.config))
    m.data.shuffle_data(0)
    for i in range(2):
        m.train_iter(i + 1, None)
    assert np.isfinite(float(np.asarray(m.current_info["cost"])))
    assert np.isfinite(float(np.asarray(m.current_info["error"])))


def test_wgan_n_critic_gate_and_clip():
    """G params move ONLY on count % n_critic == 0 steps; D weights stay
    inside the clip box every step."""
    m = _build("WGAN", n_critic=3, clip=0.005)
    m.compile_iter_fns(BSP_Exchanger(m.config))
    m.data.shuffle_data(0)

    def g_leaves():
        return [np.asarray(x) for x in jax.tree_util.tree_leaves(
            jax.device_get(steps.unbox(m.step_state["params"]))["G"])]

    g0 = g_leaves()
    m.train_iter(1, None)          # 1 % 3 != 0 → G frozen
    g1 = g_leaves()
    for a, b in zip(g0, g1):
        np.testing.assert_array_equal(a, b)
    m.train_iter(2, None)          # still frozen
    m.train_iter(3, None)          # 3 % 3 == 0 → G updates
    g3 = g_leaves()
    assert any((a != b).any() for a, b in zip(g1, g3))

    d = jax.device_get(steps.unbox(m.step_state["params"]))["D"]
    for leaf in jax.tree_util.tree_leaves(d):
        assert np.abs(np.asarray(leaf)).max() <= 0.005 + 1e-7


def test_n_critic_gate_holds_under_stateful_adam():
    """Regression: zeroed grads are NOT enough — adam's momentum would still
    move G on gated steps.  The update gate must keep G's params AND
    optimizer state bit-frozen."""
    m = _build("LSGAN", n_critic=4)
    assert m.optimizer == "adam"
    m.compile_iter_fns(BSP_Exchanger(m.config))
    m.data.shuffle_data(0)

    def g_side(tree):
        flat = jax.tree_util.tree_flatten_with_path(jax.device_get(tree))[0]
        return [(str(p), np.asarray(v)) for p, v in flat if "'G'" in str(p)]

    m.train_iter(4, None)          # 4 % 4 == 0 → G updates, adam m/v warm
    p1 = g_side(steps.unbox(m.step_state["params"]))
    o1 = g_side(steps.unbox(m.step_state["opt_state"]))
    m.train_iter(5, None)          # gated → G params and G adam state frozen
    p2 = g_side(steps.unbox(m.step_state["params"]))
    o2 = g_side(steps.unbox(m.step_state["opt_state"]))
    for (_, a), (_, b) in zip(p1 + o1, p2 + o2):
        np.testing.assert_array_equal(a, b)
    m.train_iter(6, None)
    m.train_iter(7, None)
    m.train_iter(8, None)          # 8 % 4 == 0 → G moves again
    p3 = g_side(steps.unbox(m.step_state["params"]))
    assert any((a != b).any() for (_, a), (_, b) in zip(p2, p3))

    # Adam's bias-correction clock is per-leaf so the gate freezes it too:
    # after 5 steps (counts 4..8) G updated twice (4, 8) while D updated on
    # every step — "as if the G update function was never called" includes t.
    opt = jax.device_get(steps.unbox(m.step_state["opt_state"]))
    g_ts = {int(np.asarray(t)) for t in jax.tree.leaves(opt["t"]["G"])}
    d_ts = {int(np.asarray(t)) for t in jax.tree.leaves(opt["t"]["D"])}
    assert g_ts == {2}, g_ts
    assert d_ts == {5}, d_ts


def test_lsgan_loss_math():
    from theanompi_tpu.models.gan import LSGAN
    sr = jnp.asarray([1.0, 0.0])
    sf = jnp.asarray([0.5, 0.5])
    d = LSGAN.d_loss(None, sr, sf)
    g = LSGAN.g_loss(None, sf)
    np.testing.assert_allclose(float(d), 0.5 * (0.5 + 0.25), rtol=1e-6)
    np.testing.assert_allclose(float(g), 0.5 * 0.25, rtol=1e-6)


def test_gan_multiworker_bsp_and_gossip():
    """The combined G/D pytree rides the exchangers unchanged: 4-worker BSP
    keeps replicas identical; GoSGD conserves Σα."""
    m = _build("WGAN", n=4)
    m.compile_iter_fns(BSP_Exchanger(m.config))
    m.data.shuffle_data(0)
    m.train_iter(1, None)
    boxed = jax.device_get(m.step_state["params"])
    for leaf in jax.tree_util.tree_leaves(boxed):
        for r in range(1, 4):
            np.testing.assert_allclose(leaf[0], leaf[r], rtol=1e-5, atol=1e-6)

    m2 = _build("LSGAN", n=4, exch_prob=1.0)
    ex = GOSGD_Exchanger(m2.config)
    m2.compile_iter_fns(ex)
    m2.data.shuffle_data(0)
    m2.train_iter(1, None)
    ex.exchange(None, 1)
    alpha = np.asarray(jax.device_get(m2.step_state["extra"]["alpha"]))
    np.testing.assert_allclose(alpha.sum(), 4.0, rtol=1e-5)


def test_gan_rejects_zero_opt_but_composes_with_ema():
    """ZeRO flattens the optimizer state (no param paths), so the GANs'
    path-keyed n_critic gating cannot compose with it — rejected at build.
    EMA nests the state but keeps paths, so the gating (and the shadow)
    work through it."""
    with pytest.raises(AssertionError, match="param paths"):
        _build("WGAN", zero_opt=True)
    m = _build("WGAN", n=2, n_critic=2, ema_decay=0.9)
    m.compile_iter_fns(BSP_Exchanger(m.config))
    m.data.shuffle_data(0)
    p0 = steps.unbox(jax.device_get(m.step_state["params"]))
    m.train_iter(1, None)      # count=1: G is GATED on this step
    st = steps.unbox(jax.device_get(m.step_state["opt_state"]))
    assert "ema" in st
    # the gate reverts G's shadow to its INIT value — which must be G's
    # params (the init-time seed), NOT zeros: a zeroed shadow would make
    # validation/generate read a near-dead generator for ~1/(1-decay) steps
    def maxabs(t):
        return max(float(np.abs(np.asarray(l)).max())
                   for l in jax.tree.leaves(t))
    jax.tree.map(lambda e, p: np.testing.assert_allclose(
        np.asarray(e), np.asarray(p), rtol=1e-6, atol=1e-7),
        st["ema"]["G"], p0["G"])
    assert maxabs(st["ema"]["G"]) > 0.0
    m.train_iter(2, None)      # count=2: G updates; D's shadow keeps moving
    assert np.isfinite(float(np.asarray(m.current_info["cost"])))
    st2 = steps.unbox(jax.device_get(m.step_state["opt_state"]))
    moved = jax.tree.map(lambda e, p: float(np.abs(np.asarray(e)
                                                   - np.asarray(p)).max()),
                         st2["ema"]["D"], p0["D"])
    assert max(jax.tree.leaves(moved)) > 0.0
    # WGAN projects the shadow's critic into the clip box too — otherwise
    # validation would score a Lipschitz-violating critic for ~1/(1-decay)
    # steps (the EMA blend happens before the clip hook)
    clip = float(m.clip)
    for leaf in jax.tree.leaves(st2["ema"]["D"]):
        assert float(np.abs(np.asarray(leaf)).max()) <= clip + 1e-7


def test_wgan_rejects_ema_plus_zero_opt():
    """ADVICE r3: zero_opt nests the EMA shadow as flat chunks the clip
    projection can't reach — the combination must fail loudly, not score an
    unclipped critic shadow silently."""
    with pytest.raises(AssertionError, match="EMA shadow"):
        _build("WGAN", ema_decay=0.99, zero_opt=True)

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
