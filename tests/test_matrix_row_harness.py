"""The perf-matrix row harness (scripts/_bench_row.sh): the shell logic the
measurement record depends on — resumable skip of measured rows, null
recording on failure, and the wedge short-circuit — tested against a stub
bench.py."""

import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STUB_BENCH = textwrap.dedent("""\
    import json, os, sys
    mode = os.environ.get("STUB_MODE", "ok")
    name = os.environ.get("BENCH_MODEL", "m")
    if mode == "ok":
        print(json.dumps({"metric": f"x ({name})", "value": 1.0,
                          "unit": "u", "vs_baseline": 1.0}))
        sys.exit(0)
    if mode == "fail":
        print(json.dumps({"error": "measurement rc=1: boom"}))
        sys.exit(0)
    # wedge: the wrapper's structured wedge report
    print(json.dumps({"error": "probe hung \\u2014 tunnel wedged"}))
    sys.exit(0)
""")


def _run_matrix(tmp_path, script_body):
    (tmp_path / "bench.py").write_text(STUB_BENCH)
    scripts = tmp_path / "scripts"
    scripts.mkdir(exist_ok=True)
    with open(os.path.join(REPO, "scripts", "_bench_row.sh")) as f:
        (scripts / "_bench_row.sh").write_text(f.read())
    # merge_matrix is invoked by the real matrix scripts, not the helper —
    # the driver script here exercises the helper alone
    driver = tmp_path / "driver.sh"
    driver.write_text("#!/usr/bin/env bash\nset -u\nOUT=out.jsonl\n"
                      "cd \"$(dirname \"$0\")\"\n"
                      ". scripts/_bench_row.sh\n" + script_body)
    r = subprocess.run(["bash", str(driver)], capture_output=True,
                       text=True, cwd=tmp_path,
                       env={**os.environ, "PATH": os.environ["PATH"]})
    rows = []
    out = tmp_path / "out.jsonl"
    if out.exists():
        rows = [json.loads(l) for l in out.read_text().splitlines()]
    return r, rows


def test_rows_append_and_resume_skips_measured(tmp_path):
    r, rows = _run_matrix(tmp_path,
                          "run a BENCH_MODEL=a STUB_MODE=ok\n"
                          "run b BENCH_MODEL=b STUB_MODE=ok\n")
    assert [x["config"] for x in rows] == ["a", "b"]
    assert all(x["result"]["value"] == 1.0 for x in rows)
    # second pass: both measured -> both skipped, file unchanged
    r2, rows2 = _run_matrix(tmp_path,
                            "run a BENCH_MODEL=a STUB_MODE=ok\n"
                            "run b BENCH_MODEL=b STUB_MODE=ok\n")
    assert len(rows2) == 2
    assert r2.stderr.count("already measured") == 2


def test_failure_records_null_and_is_retried(tmp_path):
    _, rows = _run_matrix(tmp_path, "run a BENCH_MODEL=a STUB_MODE=fail\n")
    assert rows == [{"config": "a", "result": None}]
    # a null row is NOT treated as measured: the next pass retries it
    r2, rows2 = _run_matrix(tmp_path, "run a BENCH_MODEL=a STUB_MODE=ok\n")
    assert "already measured" not in r2.stderr
    assert rows2[-1]["result"]["value"] == 1.0


def test_wedge_short_circuits_the_pass(tmp_path):
    r, rows = _run_matrix(
        tmp_path,
        "run a BENCH_MODEL=a STUB_MODE=ok\n"
        "run b BENCH_MODEL=b STUB_MODE=wedge\n"
        "run c BENCH_MODEL=c STUB_MODE=ok\n")
    # a measured, b null (the wedge), c skipped without running
    assert [x["config"] for x in rows] == ["a", "b"]
    assert rows[1]["result"] is None
    assert "tunnel wedged earlier this pass" in r.stderr
    assert not any(x["config"] == "c" for x in rows)


def test_r5_matrix_script_row_inventory():
    """The round-5 matrix script's static contract: unique labels, the
    watcher's N_CONFIGS grep counts them all, the lc A/B rows flip the
    compile-venue env, and the big-compile rows stay at the back."""
    path = os.path.join(REPO, "scripts", "perf_matrix_r5.sh")
    lines = [ln.strip() for ln in open(path)
             if ln.strip().startswith("run ")]
    labels = [ln.split()[1] for ln in lines]
    assert len(labels) == len(set(labels)), "duplicate row labels"
    assert len(labels) >= 30
    # the degraded r4 row re-measures FIRST (verdict #8)
    assert labels[0] == "alexnet-b128"
    # wedge-correlated big compiles last: all spc rows after all spc-less
    # non-lc rows
    first_spc = next(i for i, l in enumerate(labels) if "spc" in l)
    assert all("spc" in l or l.endswith("-lc")
               for l in labels[first_spc:]), labels[first_spc:]
    # every lc row flips the compile venue for exactly that row
    for ln in lines:
        assert (" PALLAS_AXON_REMOTE_COMPILE=0" in ln) == \
            (ln.split()[1].endswith("-lc")), ln
    # the watcher counts rows with the same grep it gates completion on
    import subprocess as sp
    n = int(sp.run(["grep", "-c", "^run ", path],
                   capture_output=True, text=True).stdout.strip())
    assert n == len(labels)


def test_r5_watcher_fresh_bench_gating(tmp_path):
    """The watcher re-runs the flagship bench until one HEALTHY reading
    lands: the gating grep must treat a missing file, an error-only file,
    and a STALE last-good as 'retry', and a healthy value as 'done'."""
    import subprocess as sp

    # extract the LIVE compound condition from the watcher script, so an
    # edit there (e.g. dropping the STALE clause) fails THIS test rather
    # than leaving a stale inline copy green
    import re
    src = open(os.path.join(REPO, "scripts", "tpu_watch_r5.sh")).read()
    m = re.search(
        r"if (! grep -qs.*?BENCH_r05_fresh\.json.*?); then", src, re.S)
    assert m, "fresh-bench gating condition not found in tpu_watch_r5.sh"
    cond = m.group(1).replace("\\\n", " ")

    def needs_retry(content):
        f = tmp_path / "BENCH_r05_fresh.json"
        if content is None:
            f.unlink(missing_ok=True)
        else:
            f.write_text(content)
        r = sp.run(["bash", "-c",
                    f"if {cond}; then echo retry; else echo done; fi"],
                   capture_output=True, text=True, cwd=tmp_path)
        return r.stdout.strip() == "retry"

    assert needs_retry(None)
    assert needs_retry(json.dumps({"error": "backend probe hung"}))
    assert needs_retry(json.dumps(
        {"metric": "STALE last-good (alexnet-b128-spc4) ...",
         "value": 14162.35}))
    assert not needs_retry(json.dumps(
        {"metric": "images_per_sec_per_chip (alexnet ... spc=4)",
         "value": 15000.0, "unit": "images/sec/chip"}))
