"""The perf-matrix row harness (scripts/_bench_row.sh): the shell logic the
measurement record depends on — resumable skip of measured rows, null
recording on failure, and the wedge short-circuit — tested against a stub
bench.py."""

import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STUB_BENCH = textwrap.dedent("""\
    import json, os, re, sys

    def _matrix_round(path):
        # predict_scaling.py does `from bench import _matrix_round` —
        # keep the stub import-compatible with the real bench.py
        m = re.search(r"_r(\\d+)", os.path.basename(path))
        return int(m.group(1)) if m else -1

    if __name__ == "__main__":
        mode = os.environ.get("STUB_MODE", "ok")
        name = os.environ.get("BENCH_MODEL", "flagship")
        if mode == "ok":
            print(json.dumps({"metric": f"x ({name})", "value": 1.0,
                              "unit": "u", "vs_baseline": 1.0}))
            sys.exit(0)
        if mode == "fail":
            print(json.dumps({"error": "measurement rc=1: boom"}))
            sys.exit(0)
        # wedge: the wrapper's structured wedge report
        print(json.dumps({"error": "probe hung \\u2014 tunnel wedged"}))
        sys.exit(0)
""")


def _run_matrix(tmp_path, script_body):
    (tmp_path / "bench.py").write_text(STUB_BENCH)
    scripts = tmp_path / "scripts"
    scripts.mkdir(exist_ok=True)
    with open(os.path.join(REPO, "scripts", "_bench_row.sh")) as f:
        (scripts / "_bench_row.sh").write_text(f.read())
    # merge_matrix is invoked by the real matrix scripts, not the helper —
    # the driver script here exercises the helper alone
    driver = tmp_path / "driver.sh"
    driver.write_text("#!/usr/bin/env bash\nset -u\nOUT=out.jsonl\n"
                      "cd \"$(dirname \"$0\")\"\n"
                      ". scripts/_bench_row.sh\n" + script_body)
    r = subprocess.run(["bash", str(driver)], capture_output=True,
                       text=True, cwd=tmp_path,
                       env={**os.environ, "PATH": os.environ["PATH"]})
    rows = []
    out = tmp_path / "out.jsonl"
    if out.exists():
        rows = [json.loads(l) for l in out.read_text().splitlines()]
    return r, rows


def test_rows_append_and_resume_skips_measured(tmp_path):
    r, rows = _run_matrix(tmp_path,
                          "run a BENCH_MODEL=a STUB_MODE=ok\n"
                          "run b BENCH_MODEL=b STUB_MODE=ok\n")
    assert [x["config"] for x in rows] == ["a", "b"]
    assert all(x["result"]["value"] == 1.0 for x in rows)
    # second pass: both measured -> both skipped, file unchanged
    r2, rows2 = _run_matrix(tmp_path,
                            "run a BENCH_MODEL=a STUB_MODE=ok\n"
                            "run b BENCH_MODEL=b STUB_MODE=ok\n")
    assert len(rows2) == 2
    assert r2.stderr.count("already measured") == 2


def test_failure_records_null_and_is_retried(tmp_path):
    _, rows = _run_matrix(tmp_path, "run a BENCH_MODEL=a STUB_MODE=fail\n")
    assert rows == [{"config": "a", "result": None}]
    # a null row is NOT treated as measured: the next pass retries it
    r2, rows2 = _run_matrix(tmp_path, "run a BENCH_MODEL=a STUB_MODE=ok\n")
    assert "already measured" not in r2.stderr
    assert rows2[-1]["result"]["value"] == 1.0


def test_wedge_short_circuits_the_pass(tmp_path):
    r, rows = _run_matrix(
        tmp_path,
        "run a BENCH_MODEL=a STUB_MODE=ok\n"
        "run b BENCH_MODEL=b STUB_MODE=wedge\n"
        "run c BENCH_MODEL=c STUB_MODE=ok\n")
    # a measured, b null (the wedge), c skipped without running
    assert [x["config"] for x in rows] == ["a", "b"]
    assert rows[1]["result"] is None
    assert "tunnel wedged earlier this pass" in r.stderr
    assert not any(x["config"] == "c" for x in rows)


def test_r5_matrix_script_row_inventory():
    """The round-5 matrix script's static contract: unique labels, the
    watcher's N_CONFIGS grep counts them all, the lc A/B rows flip the
    compile-venue env, and the big-compile rows stay at the back."""
    path = os.path.join(REPO, "scripts", "perf_matrix_r5.sh")
    lines = [ln.strip() for ln in open(path)
             if ln.strip().startswith("run ")]
    labels = [ln.split()[1] for ln in lines]
    assert len(labels) == len(set(labels)), "duplicate row labels"
    assert len(labels) >= 30
    # the degraded r4 row re-measures FIRST (verdict #8)
    assert labels[0] == "alexnet-b128"
    # wedge-correlated big compiles last: all spc rows after all spc-less
    # non-lc rows
    first_spc = next(i for i, l in enumerate(labels) if "spc" in l)
    assert all("spc" in l or l.endswith("-lc")
               for l in labels[first_spc:]), labels[first_spc:]
    # every lc row flips the compile venue for exactly that row
    for ln in lines:
        assert (" PALLAS_AXON_REMOTE_COMPILE=0" in ln) == \
            (ln.split()[1].endswith("-lc")), ln
    # the watcher counts rows with the same grep it gates completion on
    import subprocess as sp
    n = int(sp.run(["grep", "-c", "^run ", path],
                   capture_output=True, text=True).stdout.strip())
    assert n == len(labels)


def test_r5_watcher_fresh_bench_gating(tmp_path):
    """The watcher re-runs the flagship bench until one HEALTHY reading
    lands: the gating grep must treat a missing file, an error-only file,
    and a STALE last-good as 'retry', and a healthy value as 'done'."""
    import subprocess as sp

    # extract the LIVE compound condition from the watcher script, so an
    # edit there (e.g. dropping the STALE clause) fails THIS test rather
    # than leaving a stale inline copy green
    import re
    src = open(os.path.join(REPO, "scripts", "tpu_watch_r5.sh")).read()
    m = re.search(
        r"if (! grep -qs.*?BENCH_r05_fresh\.json.*?); then", src, re.S)
    assert m, "fresh-bench gating condition not found in tpu_watch_r5.sh"
    cond = m.group(1).replace("\\\n", " ")

    def needs_retry(content):
        f = tmp_path / "BENCH_r05_fresh.json"
        if content is None:
            f.unlink(missing_ok=True)
        else:
            f.write_text(content)
        r = sp.run(["bash", "-c",
                    f"if {cond}; then echo retry; else echo done; fi"],
                   capture_output=True, text=True, cwd=tmp_path)
        return r.stdout.strip() == "retry"

    assert needs_retry(None)
    assert needs_retry(json.dumps({"error": "backend probe hung"}))
    assert needs_retry(json.dumps(
        {"metric": "STALE last-good (alexnet-b128-spc4) ...",
         "value": 14162.35}))
    assert not needs_retry(json.dumps(
        {"metric": "images_per_sec_per_chip (alexnet ... spc=4)",
         "value": 15000.0, "unit": "images/sec/chip"}))


def test_r5_watcher_full_chain_rehearsal(tmp_path):
    """Round-4 verdict weak #1 ('the measurement layer is untested in
    anger ... still a rehearsal'): rehearse the ENTIRE unattended
    recovery chain — tpu_watch_r5.sh -> perf_matrix_r5.sh -> per-row
    bench -> merge_matrix -> flagship BENCH_r05_fresh -> predict_scaling
    -> clean exit — against a stubbed healthy backend.  This drives the
    real scripts byte-for-byte except: the TPU probe is forced true, the
    probe/sleep cadence collapsed, the lockfile moved (the REAL watcher
    is live on this box), and bench.py replaced by a stub that emits a
    healthy row per invocation."""
    import re
    import subprocess as sp

    # the shared stub models bench.py's output contract in ONE place
    # (healthy JSON per invocation + the _matrix_round import surface)
    (tmp_path / "bench.py").write_text(STUB_BENCH)
    scripts = tmp_path / "scripts"
    scripts.mkdir()
    for f in ("_bench_row.sh", "perf_matrix_r5.sh", "merge_matrix.py",
              "predict_scaling.py"):
        scripts.joinpath(f).write_text(
            open(os.path.join(REPO, "scripts", f)).read())
    # pre-seed the param-count cache so predict_scaling needs no model
    # instantiation subprocess
    cache = os.path.join(REPO, "model_param_counts.json")
    (tmp_path / "model_param_counts.json").write_text(open(cache).read())

    watch = open(os.path.join(REPO, "scripts", "tpu_watch_r5.sh")).read()
    # force the probe healthy, collapse the cadence, relocate the lock
    watch2, n1 = re.subn(
        r"if timeout 90 python -c \\\n.*?>/dev/null 2>&1; then",
        "if true; then", watch, flags=re.S)
    watch2, n2 = re.subn(r"\bsleep 120\b", "sleep 0", watch2)
    watch2, n3 = re.subn(r"LOCK=/tmp/tpu_watch_r5\.pid",
                         f"LOCK={tmp_path}/watch.pid", watch2)
    # the backgrounded net_snapshot would hold the captured pipes open
    # for its full ~180s sleep ladder after the watcher exits — skip it
    watch2, n4 = re.subn(r"net_snapshot &", ": net_snapshot-skipped",
                         watch2)
    assert (n1, n2, n3, n4) == (1, 1, 1, 1), (n1, n2, n3, n4)
    scripts.joinpath("tpu_watch_r5.sh").write_text(watch2)
    for f in scripts.iterdir():
        f.chmod(0o755)

    r = sp.run(["bash", str(scripts / "tpu_watch_r5.sh")],
               capture_output=True, text=True, cwd=tmp_path, timeout=300)
    assert r.returncode == 0, (r.returncode, r.stderr[-2000:])
    assert "matrix complete" in r.stderr

    rows = [json.loads(l)
            for l in (tmp_path / "perf_matrix_r5.jsonl").read_text()
            .splitlines()]
    n_expected = sum(1 for ln in open(os.path.join(
        REPO, "scripts", "perf_matrix_r5.sh")) if ln.startswith("run "))
    assert len(rows) == n_expected
    assert all(r["result"]["value"] == 1.0 for r in rows)

    fresh = json.loads((tmp_path / "BENCH_r05_fresh.json").read_text())
    assert fresh["value"] == 1.0 and "STALE" not in fresh["metric"]
    pred = json.loads((tmp_path / "scaling_prediction_r5.json").read_text())
    # spc-less staged configs got anchored predictions from the stub rows
    anchored = [row for row in pred["rows"]
                if row.get("pred_32chip") is not None]
    assert anchored, pred
    assert (tmp_path / "forensics" / "probe_timeline.log").exists()
