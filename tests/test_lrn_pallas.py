"""Fused LRN Pallas kernels vs the jnp oracle (interpret mode on CPU).

The fwd kernel must match the reference formula; the bwd kernel must match
``jax.grad`` of the oracle — including the cross-channel coupling terms and
the window-truncated edge channels.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.ops import lrn as lrn_ops

SHAPES = [
    (2, 5, 5, 96),       # AlexNet lrn1 channel count
    (2, 3, 3, 256),      # lrn2 channel count
    (4, 1, 1, 128),      # exactly one lane tile
]


@pytest.mark.parametrize("shape", SHAPES)
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fwd_kernel_matches_oracle(shape, dtype):
    x = jax.random.normal(jax.random.key(0), shape, dtype)
    want = lrn_ops.lrn_jnp(x, 5, 2.0, 1e-4, 0.75)
    got = lrn_ops._lrn_fwd_pallas(x, 5, 2.0, 1e-4, 0.75, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-6, atol=2e-6)


@pytest.mark.parametrize("shape", SHAPES[:2])
def test_bwd_kernel_matches_oracle_grad(shape):
    x = jax.random.normal(jax.random.key(1), shape, jnp.float32)
    dy = jax.random.normal(jax.random.key(2), shape, jnp.float32)

    def loss(x):
        return jnp.vdot(lrn_ops.lrn_jnp(x, 5, 2.0, 1e-4, 0.75), dy)

    want = jax.grad(loss)(x)
    got = lrn_ops._lrn_bwd_pallas(x, dy, 5, 2.0, 1e-4, 0.75, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ragged_row_blocks():
    """Row count not a multiple of BLOCK_ROWS: padded blocks must not
    corrupt real rows."""
    x = jax.random.normal(jax.random.key(3), (3, 7, 11, 96), jnp.float32)
    want = lrn_ops.lrn_jnp(x, 5, 2.0, 1e-4, 0.75)
    got = lrn_ops._lrn_fwd_pallas(x, 5, 2.0, 1e-4, 0.75, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-6, atol=2e-6)


def test_general_beta_branch():
    x = jax.random.normal(jax.random.key(4), (2, 3, 3, 96), jnp.float32)
    want = lrn_ops.lrn_jnp(x, 5, 1.0, 2e-4, 0.5)
    got = lrn_ops._lrn_fwd_pallas(x, 5, 1.0, 2e-4, 0.5, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-6, atol=2e-6)


def test_public_lrn_dispatches_to_oracle_off_tpu(monkeypatch):
    from theanompi_tpu.ops import _pallas_util
    monkeypatch.setenv("THEANOMPI_TPU_NO_PALLAS", "1")   # force oracle path
    _pallas_util.reset_dispatch_cache()   # the gate is memoized per process
    try:
        x = jax.random.normal(jax.random.key(5), (2, 3, 3, 96), jnp.bfloat16)
        got = lrn_ops.lrn(x)
        want = lrn_ops.lrn_jnp(x, 5, 2.0, 1e-4, 0.75)
        np.testing.assert_array_equal(np.asarray(got, np.float32),
                                      np.asarray(want, np.float32))
    finally:
        _pallas_util.reset_dispatch_cache()

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
