"""Convergence gates (round-3 verdict missing #5): "actually works" as a
machine-checked accuracy number, not a loss-delta smell test.

Each rule trains the CIFAR-10 smoke model end to end through the 3-call
session API on the 8-device mesh and must reach a stated val accuracy.
The synthetic task (per-class prototypes + noise, ``data/cifar10.py``) is
deterministic and cleanly learnable.  Per-rule budgets are CALIBRATED
(runs recorded in the round-4 changelog): BSP's 128-image global batch
hits 100% by epoch 3; the weakly-coupled rules train on per-worker
batch-16 shards, so their consensus (the validated model — ≙ the
reference scoring its server's center) takes longer: GoSGD reached 94.5%
at epoch 8, EASGD 92% at epoch 11 / 100% at 12.  Each ≥90% gate sits 2+
epochs inside its measured margin while still failing loudly if a rule
stops learning.

Deselected by default (~12 min of CPU-sim training):
    python -m pytest tests/test_convergence.py -m convergence -q

Also marked ``slow``: the tier-1 gate's ``-m 'not slow'`` OVERRIDES the
ini's ``-m 'not convergence'`` (last -m wins in pytest), so without the
second marker these 12 minutes of training would silently re-enter the
870s-budgeted gate and starve it.
"""

import numpy as np
import pytest

import theanompi_tpu as tmpi

GATE_ACC = 0.90


@pytest.mark.slow
@pytest.mark.convergence
@pytest.mark.parametrize("rule_name,epochs,extra", [
    ("BSP", 5, {}),
    ("EASGD", 14, {"sync_freq": 2, "alpha": 0.1}),
    # ASGD's center absorbs the SUM of worker deltas (downpour), so the
    # stable lr scales down by the worker count — 0.02 diverges at 8
    # workers, 0.02/8 reached 100% by epoch 10 (rules_time_to_acc.json)
    ("ASGD", 14, {"sync_freq": 2, "learning_rate": 0.0025}),
    ("GOSGD", 10, {"exch_prob": 0.25}),
    # Round-5 compressed-wire gates: BSP training through each wire
    # format must still reach the gate, not just pass the algebraic
    # oracle tests.  Calibration (2026-07-31 probe): at the plain-BSP
    # lr 0.02 the sign/low-rank wire is UNSTABLE on this task (onebit
    # hit 90% in epoch 1 then diverged to chance; powersgd2 never left
    # ~13%); at lr 0.005 both train cleanly (onebit 100% by epoch 2,
    # powersgd2 by 3) — the standard EF-compression smaller-stable-lr
    # practice, pinned here and documented in docs/api.md §4.  topk is
    # gated at the PLAIN lr 0.02 on purpose: the docs say only
    # onebit/powersgd need the lr drop, so topk's stability at the
    # unmodified rate is machine-checked.
    ("BSP", 6, {"exch_strategy": "onebit", "learning_rate": 0.005}),
    ("BSP", 7, {"exch_strategy": "topk"}),
    ("BSP", 6, {"exch_strategy": "powersgd2", "learning_rate": 0.005}),
], ids=lambda v: v.get("exch_strategy", "") or None
   if isinstance(v, dict) else None)
def test_rule_trains_cifar10_to_accuracy(rule_name, epochs, extra):
    label = rule_name + (f"+{extra['exch_strategy']}"
                         if "exch_strategy" in extra else "")
    rule = getattr(tmpi, rule_name)()
    kw = dict(devices=8, modelfile="theanompi_tpu.models.cifar10",
              modelclass="Cifar10_model", epochs=epochs,
              synthetic_train=2048, synthetic_val=256, batch_size=16,
              printFreq=1000, compute_dtype="float32", learning_rate=0.02,
              scale_lr=False, verbose=False)
    kw.update(extra)                   # per-rule overrides win (ASGD's lr)
    rule.init(**kw)
    rec = rule.wait()
    accs = [1.0 - r["val_error"] for r in rec.epoch_records]
    assert len(accs) == epochs
    best = max(accs)
    assert best >= GATE_ACC, (
        f"{label} reached only {best:.1%} val accuracy in {epochs} "
        f"epochs (gate {GATE_ACC:.0%}); per-epoch: "
        f"{[round(a, 3) for a in accs]}")
    # and it should not be a fluke of one epoch: the training tail holds
    # the gate too
    assert np.mean(accs[-2:]) >= GATE_ACC
