"""Wedge-proof bench harness internals (bench.py at the repo root): the
config-matched last_good fallback and the canonical matrix merge — pure
host logic, no backend needed."""

import importlib
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
bench = importlib.import_module("bench")
merge_matrix = importlib.import_module("scripts.merge_matrix")


@pytest.fixture
def env(monkeypatch):
    """Clean BENCH_* env for each case."""
    for k in list(os.environ):
        if k.startswith("BENCH_"):
            monkeypatch.delenv(k, raising=False)
    # _cfg_matches also keys the lc (local-compile) rows off this env var;
    # an ambient =0 (e.g. after hand-running an lc matrix row) must not
    # leak into the suite
    monkeypatch.delenv("PALLAS_AXON_REMOTE_COMPILE", raising=False)
    return monkeypatch


@pytest.mark.parametrize("envs,cfg,want", [
    # default alexnet BSP at its class-default batch
    ({}, "alexnet-b128", True),
    ({}, "alexnet-b128-spc4", False),          # spc row ≠ spc-less run
    ({}, "alexnet-b128-realdata", False),
    ({"BENCH_SPC": "4"}, "alexnet-b128-spc4", True),
    ({"BENCH_SPC": "4"}, "alexnet-b128-spc8", False),
    # 'asgd' must NOT substring-match 'easgd' rows (round-4 review catch)
    ({"BENCH_MODEL": "vgg16", "BENCH_RULE": "easgd"},
     "vgg16-b32-easgd", True),
    ({"BENCH_MODEL": "vgg16", "BENCH_RULE": "asgd"},
     "vgg16-b32-easgd", False),
    ({"BENCH_MODEL": "vgg16"}, "vgg16-b32-easgd", False),
    # default-batch pinning: a b64 row must not serve a default-b32 run
    ({"BENCH_MODEL": "resnet50"}, "resnet50-b64", False),
    ({"BENCH_MODEL": "resnet50"}, "resnet50-b32", True),
    ({"BENCH_MODEL": "resnet50", "BENCH_BATCH": "64"},
     "resnet50-b64", True),
    # u8-wire rows are their own configuration
    ({"BENCH_MODEL": "alexnet", "BENCH_REAL_DATA": "1"},
     "alexnet-b128-realdata", True),
    ({"BENCH_MODEL": "alexnet", "BENCH_REAL_DATA": "1"},
     "alexnet-b128-realdata-u8w", False),
    ({"BENCH_MODEL": "alexnet", "BENCH_REAL_DATA": "1",
      "BENCH_WIRE_U8": "1"}, "alexnet-b128-realdata-u8w", True),
    # strategy rows
    ({"BENCH_MODEL": "vgg16", "BENCH_STRATEGY": "topk"},
     "vgg16-b32-topk", True),
    ({"BENCH_MODEL": "vgg16"}, "vgg16-b32-topk", False),
    # bf16-BN lever rows
    ({"BENCH_MODEL": "resnet50", "BENCH_BN_DTYPE": "bfloat16"},
     "resnet50-b32-bnbf16", True),
    ({"BENCH_MODEL": "resnet50"}, "resnet50-b32-bnbf16", False),
    # winload rows (producer-staged spc windows) ≠ plain spc rows
    ({"BENCH_SPC": "4", "BENCH_WINLOAD": "1"},
     "alexnet-b128-spc4-winload", True),
    ({"BENCH_SPC": "4"}, "alexnet-b128-spc4-winload", False),
    ({"BENCH_SPC": "4", "BENCH_WINLOAD": "1"}, "alexnet-b128-spc4", False),
    ({"BENCH_MODEL": "vgg16", "BENCH_RULE": "easgd", "BENCH_SPC": "8",
      "BENCH_WINLOAD": "1"}, "vgg16-b32-easgd-spc8-winload", True),
])
def test_cfg_matches(env, envs, cfg, want):
    for k, v in envs.items():
        env.setenv(k, v)
    assert bench._cfg_matches(cfg) is want


def test_last_good_prefers_newest_round_and_duplicate(env, tmp_path,
                                                      monkeypatch):
    """Numeric round ordering (r10 > r4 > r3) and newest-duplicate-wins
    within a file; the base config beats suffixed ones on ties."""
    repo = tmp_path
    def row(cfg, value):
        return json.dumps({"config": cfg, "result": {
            "metric": "m", "value": value, "unit": "u",
            "vs_baseline": 1.0}}) + "\n"
    (repo / "perf_matrix_r3.jsonl").write_text(row("alexnet-b128", 1.0))
    (repo / "perf_matrix_r4.jsonl").write_text(
        row("alexnet-b128", 2.0) + row("alexnet-b128", 3.0))
    (repo / "perf_matrix_r10.jsonl").write_text(row("alexnet-b128", 4.0))
    # point bench's repo root at the tmp dir (its _last_good derives the
    # matrix glob from __file__); patching the module attr is scoped
    monkeypatch.setattr(bench, "__file__", str(repo / "bench.py"))
    got = bench._last_good()
    assert got is not None
    cfg, res = got
    assert cfg == "alexnet-b128" and res["value"] == 4.0
    # without r10, the newest duplicate in r4 wins
    (repo / "perf_matrix_r10.jsonl").unlink()
    cfg, res = bench._last_good()
    assert res["value"] == 3.0


def test_merge_matrix_last_nonnull_wins(tmp_path):
    p = tmp_path / "m.jsonl"
    rows = [
        {"config": "a", "result": None},
        {"config": "b", "result": {"metric": "m", "value": 1}},
        {"config": "a", "result": {"metric": "m", "value": 2}},
        {"config": "b", "result": None},          # null cannot demote
    ]
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\ngarbage{{{\n")
    merge_matrix.merge([str(p)])
    out = [json.loads(l) for l in p.read_text().splitlines()]
    assert [r["config"] for r in out] == ["a", "b"]   # first-seen order
    assert out[0]["result"]["value"] == 2
    assert out[1]["result"]["value"] == 1


def _run_bench(env_extra, timeout=420):
    import subprocess
    import tempfile
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("BENCH_")}
    # each case gets a FRESH compile-cache dir: the timeout test's workload
    # must pay the real compile (a warm hit from a prior case could finish
    # inside BENCH_TIMEOUT and flip the expected failure into a success)
    env["BENCH_COMPILE_CACHE"] = tempfile.mkdtemp(prefix="bench_cache_")
    env.update(env_extra)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, os.path.join(repo, "bench.py")],
                       capture_output=True, text=True, timeout=timeout,
                       env=env, cwd=repo)
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    return r.returncode, (json.loads(lines[-1]) if lines else None)


def test_wrapper_cpu_success_end_to_end():
    """The driver's exact invocation shape, forced to CPU: one JSON line
    with the metric contract keys."""
    rc, out = _run_bench({"BENCH_FORCE_CPU": "1", "BENCH_MODEL": "cifar10",
                          "BENCH_BATCH": "16", "BENCH_ITERS": "2",
                          "BENCH_WARMUP": "1"})
    assert rc == 0, out
    assert set(out) >= {"metric", "value", "unit", "vs_baseline"}
    assert out["value"] > 0 and "cpu" in out["metric"]
    # executable-cache evidence rides every row (ISSUE 3): where the train
    # program came from and what its compile cost — a fresh tmp cache dir,
    # so this cold row must be an honest miss with a real compile time
    assert out["cache"] == "miss" and out["compile_secs"] > 0, out


@pytest.mark.slow
def test_bench_trace_row_carries_overlap_columns():
    """ISSUE 7 acceptance: BENCH_TRACE=1 captures a profiler window after
    the timed loop and folds the devprof attribution into the row — the
    BSP-grads step contains a psum, so the comm/compute breakdown is
    nonzero and overlap_ratio is a real number in [0, 1].

    Slow lane (round 19): this is a full bench subprocess — CPU-compiling
    train/val/trace programs costs ~4 min of the 870 s tier-1 budget for
    one row.  The trace-row SCHEMA stays tier-1-guarded by the
    schema-drift checker (profile_row_fields ≡ TRACE_ROW_COLUMNS, live
    synthetic-trace probe); the end-to-end capture runs with the other
    full-pipeline gates under ``-m slow``."""
    rc, out = _run_bench({"BENCH_FORCE_CPU": "1", "BENCH_MODEL": "cifar10",
                          "BENCH_BATCH": "16", "BENCH_ITERS": "2",
                          "BENCH_WARMUP": "1", "BENCH_TRACE": "1",
                          "BENCH_TRACE_ITERS": "2"})
    assert rc == 0, out
    assert out["value"] > 0
    from theanompi_tpu.utils import devprof
    assert set(devprof.TRACE_ROW_COLUMNS) <= set(out), sorted(out)
    assert out["device_comm_secs"] > 0 and out["device_compute_secs"] > 0
    assert 0.0 <= out["overlap_ratio"] <= 1.0
    assert 0.0 <= out["exposed_comm_secs"] <= out["device_comm_secs"] + 1e-9
    assert out["device_mfu"] is None          # CPU: no peak-flops table


def test_wrapper_timeout_kills_and_reports():
    """A hung measurement dies at BENCH_TIMEOUT as a process group and the
    wrapper still emits structured JSON (no last_good for this config →
    rc 3 with the error)."""
    rc, out = _run_bench({"BENCH_FORCE_CPU": "1", "BENCH_MODEL": "cifar10",
                          "BENCH_BATCH": "16", "BENCH_TIMEOUT": "3"})
    assert rc in (0, 3)
    assert "error" in out and "BENCH_TIMEOUT" in out["error"]


def test_last_good_skips_degraded_rows(env, tmp_path, monkeypatch):
    """Round-4 verdict weak #7: a reading tagged as from a degraded tunnel
    window must never be handed out as the honest fallback — _last_good
    skips it (by metric marker or row note) and falls through to the
    newest healthy round."""
    repo = tmp_path

    def row(cfg, value, metric="m", **extra):
        return json.dumps({"config": cfg, "result": {
            "metric": metric, "value": value, "unit": "u",
            "vs_baseline": 1.0}, **extra}) + "\n"
    (repo / "perf_matrix_r3.jsonl").write_text(row("alexnet-b128", 10584.0))
    (repo / "perf_matrix_r4.jsonl").write_text(
        row("alexnet-b128", 6334.0,
            metric="m (DEGRADED-window reading — re-measure)"))
    monkeypatch.setattr(bench, "__file__", str(repo / "bench.py"))
    cfg, res = bench._last_good()
    assert res["value"] == 10584.0
    # the voiding convention: null result + a 'degraded' note row also
    # falls through (this is the shape of the real r4 artifact)
    (repo / "perf_matrix_r4.jsonl").write_text(json.dumps(
        {"config": "alexnet-b128", "result": None,
         "note": "voided: degraded window"}) + "\n")
    cfg, res = bench._last_good()
    assert res["value"] == 10584.0


def test_merge_matrix_degraded_never_beats_healthy(tmp_path, capsys):
    """Round-4 verdict #8: healthy > degraded > null per config, and a
    degraded survivor (no healthy sibling) is flagged on stderr so it
    can't be quoted silently."""
    p = tmp_path / "m.jsonl"
    rows = [
        {"config": "a", "result": {"metric": "m (degraded window)",
                                   "value": 6334}},
        {"config": "a", "result": {"metric": "m", "value": 10584}},
        # degraded row arriving AFTER a healthy one must not supersede it
        {"config": "a", "result": {"metric": "m (degraded window)",
                                   "value": 6000}},
        {"config": "b", "result": {"metric": "m (degraded window)",
                                   "value": 1}},
    ]
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    merge_matrix.merge([str(p)])
    out = [json.loads(l) for l in p.read_text().splitlines()]
    by = {r["config"]: r for r in out}
    assert by["a"]["result"]["value"] == 10584
    assert by["b"]["result"]["value"] == 1      # survives, but flagged
    assert "DEGRADED" in capsys.readouterr().err


def test_flagship_default_is_spc4_and_matrix_rows_untouched(env):
    """The driver's bare round-end run measures the flagship best config
    (spc=4, the r3 record config); any explicit BENCH_MODEL (every matrix
    row) keeps its exact semantics."""
    bench._apply_flagship_defaults()
    assert os.environ.get("BENCH_SPC") == "4"
    del os.environ["BENCH_SPC"]
    env.setenv("BENCH_MODEL", "alexnet")
    bench._apply_flagship_defaults()
    assert "BENCH_SPC" not in os.environ
    env.delenv("BENCH_MODEL")
    env.setenv("BENCH_REAL_DATA", "1")          # realdata requires spc=1
    bench._apply_flagship_defaults()
    assert "BENCH_SPC" not in os.environ


def test_merge_matrix_tombstone_blocks_resurrection(tmp_path, capsys):
    """A voiding tombstone (null + degraded note + voided_value) must beat
    an UNTAGGED copy of the voided reading arriving from an old backup —
    and a genuine healthy re-measure must beat the tombstone."""
    main = tmp_path / "m.jsonl"
    backup = tmp_path / "old.jsonl"
    tomb = {"config": "a", "result": None,
            "note": "voided: degraded window", "voided_value": 6333.91}
    stale = {"config": "a", "result": {"metric": "m", "value": 6333.91}}
    healthy = {"config": "a", "result": {"metric": "m", "value": 10584.5}}

    main.write_text(json.dumps(tomb) + "\n")
    backup.write_text(json.dumps(stale) + "\n")
    merge_matrix.merge([str(main), str(backup)])
    out = [json.loads(l) for l in main.read_text().splitlines()]
    assert out[0]["result"] is None          # tombstone survived the backup

    main.write_text(json.dumps(tomb) + "\n" + json.dumps(healthy) + "\n")
    merge_matrix.merge([str(main)])
    out = [json.loads(l) for l in main.read_text().splitlines()]
    assert out[0]["result"]["value"] == 10584.5


def test_wrapper_sigterm_reaps_detached_inner():
    """Round-5 regression: the inner measurement runs in its OWN session
    (so BENCH_TIMEOUT can killpg it), which means a TERM'd wrapper (outer
    `timeout`, watcher restart) would orphan it — a leaked 100%-CPU inner
    on a 1-core box poisons later measurements.  The wrapper must reap the
    inner when it is itself terminated."""
    import signal
    import subprocess
    import time
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("BENCH_")}
    env.update(BENCH_FORCE_CPU="1", BENCH_MODEL="cifar10",
               BENCH_TIMEOUT="600",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen([sys.executable, os.path.join(repo, "bench.py")],
                            env=env, cwd=repo, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)

    def my_inner_pid():
        # ONLY this wrapper's child (ppid match): a machine-wide
        # BENCH_INNER scan could find — and later kill — a live
        # production measurement (the round-5 watcher runs on this box)
        for p in os.listdir("/proc"):
            if not p.isdigit():
                continue
            try:
                stat = open(f"/proc/{p}/stat").read()
                environ = open(f"/proc/{p}/environ", "rb").read()
            except OSError:
                continue
            ppid = int(stat.rsplit(")", 1)[1].split()[1])
            if ppid == proc.pid and b"BENCH_INNER=1" in environ:
                return int(p)
        return None

    def alive(pid):
        try:
            os.kill(pid, 0)
            return True
        except ProcessLookupError:
            return False

    inner = None
    try:
        # wait for the inner to exist (wrapper spawns it immediately — the
        # probe is skipped under BENCH_FORCE_CPU)
        deadline = time.time() + 60
        while inner is None and time.time() < deadline:
            inner = my_inner_pid()
            if inner is None:
                time.sleep(0.5)
        assert inner is not None, "inner measurement process never appeared"
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=30)
        deadline = time.time() + 10
        while alive(inner) and time.time() < deadline:
            time.sleep(0.5)
        leaked = alive(inner)
        assert not leaked, f"wrapper TERM leaked inner pid {inner}"
    finally:
        # never leave a CPU-burner behind, whatever failed above; the
        # inner is a session leader, so killpg takes its children too
        if proc.poll() is None:
            proc.kill()
        if inner is not None and alive(inner):
            try:
                os.killpg(inner, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass


def test_merge_matrix_value_match_demotion_logged_and_ts_gated(tmp_path,
                                                               capsys):
    """Round-5 ADVICE: a healthy re-measure that coincidentally reproduces
    a tombstoned reading must not be silently discarded — the demotion is
    logged, and a row whose ``ts`` postdates the tombstone's survives."""
    p = tmp_path / "m.jsonl"
    tomb = {"config": "a", "result": None, "ts": 100.0,
            "note": "voided: degraded window", "voided_value": 6333.91}
    same_no_ts = {"config": "a", "result": {"metric": "m", "value": 6333.91}}
    p.write_text(json.dumps(tomb) + "\n" + json.dumps(same_no_ts) + "\n")
    merge_matrix.merge([str(p)])
    out = [json.loads(l) for l in p.read_text().splitlines()]
    assert out[0]["result"] is None          # demoted: tombstone wins...
    err = capsys.readouterr().err
    assert "matches the tombstoned" in err   # ...but never silently
    # a value-matching row STAMPED newer than the tombstone is a genuine
    # healthy re-measure — it supersedes
    newer = {"config": "a", "ts": 200.0,
             "result": {"metric": "m", "value": 6333.91}}
    p.write_text(json.dumps(tomb) + "\n" + json.dumps(newer) + "\n")
    merge_matrix.merge([str(p)])
    out = [json.loads(l) for l in p.read_text().splitlines()]
    assert out[0]["result"]["value"] == 6333.91


def test_powersgd_wire_bytes_uses_real_factorization():
    """Round-5 ADVICE (medium): the wire model must follow PowerSGD's own
    [prod(shape[:-1]), shape[-1]] per-leaf factorization gated by
    _compressible, plus a dense psum term for the rejected leaves — for
    vgg16 the corrected rows+cols is ~80k, ~60x below the old
    shape[0]+size//shape[0] figure that overstated the wire."""
    from scripts.predict_scaling import wire_bytes
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    counts = json.load(open(os.path.join(repo, "model_param_counts.json")))
    vgg = counts["vgg16"]
    assert 60_000 < vgg["rows_plus_cols"] < 120_000, vgg
    assert vgg["powersgd_dense"] > 0
    wb = wire_bytes("powersgd4", vgg["params"], vgg["rows_plus_cols"], 8,
                    vgg["powersgd_dense"])
    ring = 2.0 * 7 / 8
    assert wb == ring * (4 * vgg["rows_plus_cols"]
                         + vgg["powersgd_dense"]) * 4
    # and it stays far below both the dense allreduce and the old estimate
    assert wb < 0.05 * wire_bytes("allreduce", vgg["params"], 0, 8)


def test_recovery_backoff_schedule(env):
    """bench.py's probe recovery (BENCH_r05 postmortem: the single fixed
    45 s re-probe lost the round to one wedged tunnel): BENCH_PROBE_RETRIES
    attempts with exponential backoff from BENCH_RECOVERY_WAIT, capped at
    120 s, jittered ±25% so fleet-mates don't re-probe in lockstep."""
    env.setenv("BENCH_PROBE_RETRIES", "5")
    env.setenv("BENCH_RECOVERY_WAIT", "10")
    waits = bench._recovery_waits()
    assert len(waits) == 5
    for i, w in enumerate(waits):
        nominal = min(10.0 * 2 ** i, 120.0)
        assert 0.75 * nominal <= w <= 1.25 * nominal, (i, w)
    assert min(10.0 * 2 ** 4, 120.0) == 120.0      # the cap engages
    env.setenv("BENCH_PROBE_RETRIES", "0")
    assert bench._recovery_waits() == []           # opt out entirely


def test_fail_tags_stale_last_good(env, capsys, monkeypatch):
    """The wedge fallback's re-emitted last-good row carries stale: true
    so downstream ranking can never mistake it for a fresh measurement."""
    monkeypatch.setattr(bench, "_last_good", lambda: (
        "alexnet-b128", {"metric": "m", "value": 5.0, "unit": "u",
                         "vs_baseline": 1.0}))
    rc = bench._fail("tunnel wedged")
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 0
    assert out["stale"] is True and out["value"] == 5.0
    assert "STALE last-good" in out["metric"]
    # no last_good → no stale tag, rc 3
    monkeypatch.setattr(bench, "_last_good", lambda: None)
    rc = bench._fail("tunnel wedged")
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 3 and "stale" not in out


def test_merge_matrix_stale_ranks_below_fresh(tmp_path, capsys):
    """A stale last-good row (bench's wedge fallback) must lose to any
    fresh measurement — whatever the file order — but still beat nulls
    and degraded rows; a stale-only survivor is flagged on stderr."""
    p = tmp_path / "m.jsonl"
    rows = [
        {"config": "a", "result": {"metric": "m", "value": 3.0,
                                   "stale": True}},
        {"config": "a", "result": {"metric": "m", "value": 2.0}},
        # stale arriving AFTER the fresh row must not supersede it —
        # also via the metric-string marker (pre-tag artifacts)
        {"config": "a", "result": {
            "metric": "STALE last-good (a) — run failed", "value": 4.0}},
        {"config": "b", "result": None},
        {"config": "b", "result": {"metric": "m", "value": 1.0,
                                   "stale": True}},
        {"config": "c", "result": {"metric": "m (degraded window)",
                                   "value": 9.0}},
        {"config": "c", "result": {"metric": "m", "value": 8.0,
                                   "stale": True}},
    ]
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    merge_matrix.merge([str(p)])
    by = {r["config"]: r for r in
          (json.loads(l) for l in p.read_text().splitlines())}
    assert by["a"]["result"]["value"] == 2.0       # fresh beats stale
    assert by["b"]["result"]["value"] == 1.0       # stale beats null
    assert by["c"]["result"]["value"] == 8.0       # stale beats degraded
    err = capsys.readouterr().err
    assert "STALE last-good" in err                # survivors are flagged


def test_merge_matrix_stale_cannot_launder_through_ts(tmp_path):
    """A stale fallback re-emitting a tombstoned value is ts-stamped at
    re-emission time — NEWER than the tombstone — so it passes the
    genuine-re-measure ts escape; it must still rank as stale and lose
    to a fresh measurement."""
    p = tmp_path / "m.jsonl"
    rows = [
        {"config": "a", "ts": 50, "result": None,
         "note": "degraded window — reading voided", "voided_value": 3.0},
        {"config": "a", "ts": 100,
         "result": {"metric": "m", "value": 3.0, "stale": True}},
        {"config": "a", "ts": 60, "result": {"metric": "m", "value": 2.0}},
    ]
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    merge_matrix.merge([str(p)])
    got = [json.loads(l) for l in p.read_text().splitlines()]
    assert got[0]["result"]["value"] == 2.0


def test_merge_matrix_newest_tombstone_governs(tmp_path, capsys):
    """An old backup's EARLIER tombstone for the same config must not
    re-open the ts window: the newest tombstone governs, so a reading
    voided by it (ts between the two tombstones) stays demoted."""
    main = tmp_path / "m.jsonl"
    backup = tmp_path / "old.jsonl"
    tomb_new = {"config": "a", "result": None, "ts": 200.0,
                "note": "voided: degraded window", "voided_value": 6333.91}
    tomb_old = {"config": "a", "result": None, "ts": 100.0,
                "note": "voided: degraded window", "voided_value": 6333.91}
    voided_reading = {"config": "a", "ts": 150.0,
                      "result": {"metric": "m", "value": 6333.91}}
    main.write_text(json.dumps(tomb_new) + "\n"
                    + json.dumps(voided_reading) + "\n")
    backup.write_text(json.dumps(tomb_old) + "\n")
    merge_matrix.merge([str(main), str(backup)])
    out = [json.loads(l) for l in main.read_text().splitlines()]
    assert out[0]["result"] is None      # ts=150 reading stays voided
