"""Subprocess body for the 2-process jax.distributed test (not a test file).

Each process owns 2 virtual CPU devices; together they form a 4-worker
global mesh.  Exercises the REAL multi-host path end to end:
``init_multihost`` (jax.distributed bring-up), per-host data loading
(``DataBase`` slices by ``jax.process_index()``), ``make_per_host_array``
stitching inside ``steps.put_batch``, one compiled BSP train step, and the
multi-host checkpoint gather (``steps.tree_to_host``).

Prints one JSON line with a params fingerprint; the parent test asserts both
processes agree AND match a single-process 4-worker oracle run.
"""

import json
import os
import sys


def main() -> int:
    proc_id = int(sys.argv[1])
    port = sys.argv[2]

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from theanompi_tpu.parallel.mesh import init_multihost

    init_multihost(f"localhost:{port}", 2, proc_id)
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4

    mode = sys.argv[3] if len(sys.argv) > 3 else "dense"
    if mode == "tp":
        # dp=2 across the processes × tp=2 within each process's 2 devices
        from tests.twoproc_model import fingerprint_after_steps_tp
        fp = fingerprint_after_steps_tp(dp=2, tp=2)
    elif mode == "pp":
        from tests.twoproc_model import fingerprint_after_steps_pp
        fp = fingerprint_after_steps_pp(dp=2, pp=2)
    elif mode == "sp":
        from tests.twoproc_model import fingerprint_after_steps_sp
        fp = fingerprint_after_steps_sp(dp=2, sp=2)
    elif mode == "onebit":
        from tests.twoproc_model import fingerprint_after_steps_onebit
        fp = fingerprint_after_steps_onebit(n_workers=4)
    elif mode == "sp_spc":
        from tests.twoproc_model import fingerprint_after_steps_sp_spc
        fp = fingerprint_after_steps_sp_spc(dp=2, sp=2)
    elif mode == "fsdp":
        # FSDP/ZeRO-3 across REAL process boundaries: the param chunks
        # partition over hosts, the in-step all_gather and its psum_scatter
        # transpose cross the process boundary
        from tests.twoproc_model import fingerprint_after_steps
        fp = fingerprint_after_steps(n_workers=4, fsdp=True)
    elif mode == "spc":
        # multi-step dispatch on the multi-host path: each host stacks its
        # k local batches, put_batch_stack stitches [k, global, ...]
        from tests.twoproc_model import fingerprint_after_steps
        fp = fingerprint_after_steps(n_workers=4, steps_per_call=2)
    else:
        from tests.twoproc_model import fingerprint_after_steps
        fp = fingerprint_after_steps(n_workers=4)
    print("FP " + json.dumps({"proc": proc_id, **fp}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
