"""Window-granular input staging (ISSUE 2 tentpole): with ``para_load``
on and ``steps_per_call > 1`` the PrefetchLoader producer assembles whole
spc windows — k sequential draws, one host stack, one
``steps.stage_window`` — and the bounded queue holds DEVICE-RESIDENT
windows, so ``train_iter`` dequeues a mesh-resident dispatch input.

Contracts pinned here:

* bit-equivalence — the window-staged batch stream AND the params after N
  windows equal the serial path (k× ``next_train_batch`` +
  ``put_batch_stack`` on the consumer) exactly;
* the acceptance accounting — window mode's ``stage`` recorder bucket is
  ~0 (the producer staged off-thread; the consumer bracket is a
  pass-through) and ``load`` reflects only dequeue wait;
* restart-mid-epoch cursor exactness at window granularity;
* a producer error (load/augment/stage) surfaces in the consumer.
"""

import numpy as np
import pytest

import jax

from tests.conftest import SyntheticData, TinyModel
from theanompi_tpu.models.data.prefetch import PrefetchLoader
from theanompi_tpu.parallel import steps
from theanompi_tpu.parallel.exchanger import BSP_Exchanger
from theanompi_tpu.parallel.mesh import worker_mesh
from theanompi_tpu.utils.recorder import Recorder


def _mk_model(spc, para_load, n=4, **cfg):
    mesh = worker_mesh(n)
    config = {"mesh": mesh, "size": n, "rank": 0, "verbose": False,
              "batch_size": 8, "steps_per_call": spc, "n_train": 512,
              "para_load": para_load, **cfg}
    model = TinyModel(config)
    model.compile_iter_fns(BSP_Exchanger(config))
    model.data.shuffle_data(0)
    return model


def _leaves(tree):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(
        jax.device_get(tree))]


def test_stage_window_stream_bit_equals_put_batch_stack():
    """The dequeued device window IS the serial path's staged stack,
    bit for bit — same draws, same stack, same sharding."""
    mesh = worker_mesh(4)
    k = 4
    ref = SyntheticData({"size": 4}, 8)
    ref.shuffle_data(5)
    loader = PrefetchLoader(SyntheticData({"size": 4}, 8))
    loader.set_window(k, lambda w: steps.stage_window(mesh, w, None))
    loader.shuffle_data(5)
    for w in range(2):
        batches = [ref.next_train_batch(w * k + j + 1) for j in range(k)]
        want = steps.put_batch_stack(mesh, batches, None)
        got = loader.next_train_window((w + 1) * k)
        assert steps.is_device_window(got)
        for a, b in zip(_leaves(want), _leaves(got)):
            np.testing.assert_array_equal(a, b)


def test_window_staged_params_bit_equal_serial_after_n_windows():
    """The acceptance criterion: N windows through the window-staged
    pipeline leave the model in the EXACT state of the serial
    k× next_train_batch + consumer put_batch_stack path."""
    k, windows = 4, 3
    serial = _mk_model(k, para_load=False)
    staged = _mk_model(k, para_load=True)
    assert getattr(staged.data, "window", 0) == k
    for w in range(1, windows + 1):
        serial.train_iter(w * k, None)
        staged.train_iter(w * k, None)
    for part in ("params", "opt_state"):
        for a, b in zip(_leaves(serial.step_state[part]),
                        _leaves(staged.step_state[part])):
            np.testing.assert_array_equal(a, b, err_msg=part)


def test_window_mode_stage_bucket_near_zero():
    """The recorder contract: in window mode the consumer's `stage`
    bracket is a pass-through (the producer already staged the window),
    so its bucket stays ~0 while `load` (dequeue wait) and `train` book
    the real time — the overlap win is visible in records."""
    staged = _mk_model(4, para_load=True)
    rec = Recorder({"verbose": False, "printFreq": 4, "size": 4})
    for w in range(1, 4):
        staged.train_iter(w * 4, rec)
        rec.print_train_info(w * 4, stride=4)
    assert rec.t_sec_total["stage"] < 0.05, rec.t_sec_total
    assert rec.t_sec_total["train"] > 0.0
    # row accounting matches the serial path: k × global rows per window
    assert rec.n_images_total == 3 * 4 * 32
    # and the JSONL record carries the new bucket
    assert "t_stage" in rec._all_records[-1]
    # serial contrast: the consumer pays the stack+put in `stage`
    serial = _mk_model(4, para_load=False)
    rec1 = Recorder({"verbose": False})
    for w in range(1, 4):
        serial.train_iter(w * 4, rec1)
    assert rec1.t_sec_total["stage"] > 0.0
    assert rec1.n_images_total == rec.n_images_total


def test_window_cursor_restart_mid_epoch_exact():
    """Mid-epoch restart at window granularity: resuming from
    get_cursor() replays the remaining windows bit-identically (the
    committed cursor is as of after the last CONSUMED window's k-th
    batch, never the producer's read-ahead)."""
    mesh = worker_mesh(2)
    k = 4

    def fresh():
        l = PrefetchLoader(SyntheticData({"size": 2}, 8))
        l.set_window(k, lambda w: steps.stage_window(mesh, w, None))
        return l

    a = fresh()
    a.shuffle_data(3)
    wins = [_leaves(a.next_train_window((i + 1) * k)) for i in range(3)]

    b = fresh()
    b.shuffle_data(3)
    b.next_train_window(k)
    cur = b.get_cursor()
    assert cur["train_ptr"] == k          # window granularity, exactly

    c = fresh()
    c.set_cursor(cur)
    for want in wins[1:]:
        got = _leaves(c.next_train_window(0))
        for x, y in zip(want, got):
            np.testing.assert_array_equal(x, y)


def test_producer_error_surfaces_in_consumer():
    """A load failure inside the producer (here: batch 6 of window 2)
    must re-raise at the consumer's next dequeue, not hang or vanish."""

    class BoomData(SyntheticData):
        def next_train_batch(self, count):
            if self._train_ptr >= 6:
                raise RuntimeError("boom at batch 6")
            return super().next_train_batch(count)

    l = PrefetchLoader(BoomData({"size": 2}, 8))
    l.set_window(4)                      # host windows: staging not at issue
    l.shuffle_data(0)
    l.next_train_window(4)               # batches 0-3: fine
    with pytest.raises(RuntimeError, match="boom at batch 6"):
        l.next_train_window(8)


def test_stage_error_surfaces_in_consumer():
    """An error in the staging hook itself (device_put on the producer
    thread) surfaces in the consumer too."""
    def bad_stage(window):
        raise ValueError("stage blew up")

    l = PrefetchLoader(SyntheticData({"size": 2}, 8))
    l.set_window(4, bad_stage)
    l.shuffle_data(0)
    with pytest.raises(ValueError, match="stage blew up"):
        l.next_train_window(4)


def test_pooled_window_producer_stream_identical():
    """n_workers > 1 + plan/materialize data: a window's k batches
    materialize in the pool, but plans stay sequential — the staged
    stream is bit-identical to the 1-worker window producer's."""

    class PlannedData(SyntheticData):
        """plan/materialize split over the synthetic set (the ImageNet
        contract shape, cheap enough for tier-1)."""

        def plan_train_batch(self, count):
            i = self._train_ptr % self.n_batch_train
            self._train_ptr += 1
            return {"idx": self._perm[self._local(i * self.global_batch)]}

        def materialize(self, plan):
            idx = plan["idx"]
            return self._make_batch(self.x_train[idx], self.y_train[idx],
                                    train=True)

    mesh = worker_mesh(2)

    def fresh(n_workers):
        l = PrefetchLoader(PlannedData({"size": 2}, 8), n_workers=n_workers)
        l.set_window(4, lambda w: steps.stage_window(mesh, w, None))
        l.shuffle_data(9)
        return l

    a, b = fresh(1), fresh(4)
    for _ in range(3):
        for x, y in zip(_leaves(a.next_train_window(0)),
                        _leaves(b.next_train_window(0))):
            np.testing.assert_array_equal(x, y)


def test_put_batch_stack_stages_host_window():
    """set_window(k, stage_fn=None) leaves host windows on the queue; the
    consumer's put_batch_stack stages them (the documented contract),
    bit-equal to producer-side staging."""
    mesh = worker_mesh(2)
    k = 4
    a = PrefetchLoader(SyntheticData({"size": 2}, 8))
    a.set_window(k)                      # host windows
    a.shuffle_data(7)
    host_w = a.next_train_window(k)
    assert not steps.is_device_window(host_w)
    staged = steps.put_batch_stack(mesh, host_w, None)
    assert steps.is_device_window(staged)
    b = PrefetchLoader(SyntheticData({"size": 2}, 8))
    b.set_window(k, lambda w: steps.stage_window(mesh, w, None))
    b.shuffle_data(7)
    for x, y in zip(_leaves(b.next_train_window(k)), _leaves(staged)):
        np.testing.assert_array_equal(x, y)


def test_set_window_midstream_rewire_drops_nothing():
    """Re-wiring window mode with a live producer (session recompile
    passes a NEW stage_fn closure) rewinds to the last CONSUMED position:
    the read-ahead the drained queue held is re-drawn, so the stream
    stays bit-identical to an uninterrupted run."""
    mesh = worker_mesh(2)
    k = 4

    def fresh():
        l = PrefetchLoader(SyntheticData({"size": 2}, 8))
        l.set_window(k, lambda w: steps.stage_window(mesh, w, None))
        l.shuffle_data(3)
        return l

    ref = fresh()
    wins = [_leaves(ref.next_train_window((i + 1) * k)) for i in range(3)]

    l = fresh()
    got = [_leaves(l.next_train_window(k))]
    # same k, new closure — the recompile case; the producer has read
    # ahead past window 0 by now (or will have: restart handles both)
    l.set_window(k, lambda w: steps.stage_window(mesh, w, None))
    got += [_leaves(l.next_train_window(0)) for _ in range(2)]
    for want, have in zip(wins, got):
        for x, y in zip(want, have):
            np.testing.assert_array_equal(x, y)


def test_mixed_granularity_consumption_refused():
    """next_train_batch against a live window-mode producer would desync
    the queue granularity — refused loudly."""
    l = PrefetchLoader(SyntheticData({"size": 2}, 8))
    l.set_window(4)
    l.shuffle_data(0)
    with pytest.raises(RuntimeError, match="window mode"):
        l.next_train_batch(1)


def test_recompile_to_spc1_reverts_to_per_batch():
    """compile_iter_fns re-wires window mode every compile: going back to
    steps_per_call=1 must revert the loader to per-batch production (a
    stale window setting would wedge the queue granularity)."""
    model = _mk_model(4, para_load=True)
    assert model.data.window == 4
    model.steps_per_call = 1
    model.compile_iter_fns(BSP_Exchanger(model.config))
    assert model.data.window == 0
    model.data.shuffle_data(1)
    model.train_iter(1, None)            # per-batch path works again
    assert np.isfinite(float(model.current_info["cost"]))


def test_para_load_window_opt_out():
    """para_load_window=false keeps the pre-window behavior (per-batch
    producer + consumer-side stack) — the A/B lever."""
    model = _mk_model(4, para_load=True, para_load_window=False)
    assert getattr(model.data, "window", 0) == 0
    model.train_iter(4, None)
    assert np.isfinite(float(model.current_info["cost"]))
