"""Fused compression-pipeline contracts (ISSUE 18, docs/design.md §24).

Three layers, all runnable on the CPU venue:

* the NEW jnp oracles must be bit-exact (or honestly allclose, where the
  fold reassociates a division) against the UNFUSED formulas they
  replaced in ``parallel/strategies.py`` — the oracles are the non-TPU
  dispatch targets, so these identities are what keeps every CPU/
  forced-oracle run on the pre-fusion numbers;
* the dispatch plumbing: the memoized ``THEANOMPI_TPU_NO_PALLAS`` gate,
  the ``no_pallas`` AOT-key stamp, the ``BENCH_FUSE`` row-label token,
  and ``bench_row_config``'s shared control-row side effect;
* the traffic model: :data:`devprof.COMPRESS_ROW_COLUMNS` schema (pinned
  disjoint from the other row vocabularies), the modeled ≥2× HBM
  shrinks the acceptance gates on, and the live-model report.

The kernel-vs-oracle bit-equality tests live in tests/test_strategies.py
(interpret mode, TPU venue) — the tpulint ``oracle-pair`` checker pins
that every ``PALLAS_ORACLES`` entry has one.
"""

import importlib
import os
import sys
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.ops import _pallas_util, compress, factor_pack
from theanompi_tpu.parallel import strategies
from theanompi_tpu.utils import compile_cache, devprof

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture
def clean_dispatch(monkeypatch):
    """Each case owns the env gate and the process-wide memo; both are
    restored afterwards so test order can't leak a forced-oracle state."""
    monkeypatch.delenv("THEANOMPI_TPU_NO_PALLAS", raising=False)
    _pallas_util.reset_dispatch_cache()
    yield monkeypatch
    _pallas_util.reset_dispatch_cache()


# ---------------------------------------------------------------------------
# oracle vs the unfused legacy formulas
# ---------------------------------------------------------------------------

def test_encode_oracle_matches_legacy_pack():
    r = np.random.RandomState(0)
    flat = jnp.asarray(r.randn(compress.PACK_ALIGN).astype(np.float32))
    state = jnp.asarray(r.randn(compress.PACK_ALIGN).astype(np.float32))
    packed, absc = compress.pack_signs_encode_jnp(flat, state)
    c = flat + state
    np.testing.assert_array_equal(np.asarray(packed),
                                  np.asarray(compress.pack_signs_jnp(c)))
    np.testing.assert_array_equal(np.asarray(absc), np.abs(np.asarray(c)))


def test_signed_residual_oracle_bit_exact_vs_legacy():
    """``where(bit, |c|−s, s−|c|)`` ≡ ``c − s·sign(where(c==0,1,c))`` in
    IEEE fp32, including c == 0 (packed bit 1) — the identity the fused
    onebit state update rests on."""
    r = np.random.RandomState(1)
    c = r.randn(compress.PACK_ALIGN).astype(np.float32)
    c[::53] = 0.0
    c = jnp.asarray(c)
    scale = jnp.float32(0.123)
    legacy = c - scale * jnp.sign(jnp.where(c == 0, 1.0, c))
    got = compress.signed_residual_jnp(jnp.abs(c),
                                       compress.pack_signs_jnp(c), scale)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(legacy))


def test_weighted_mean_oracle_matches_sum_then_divide():
    """The /size fold moves the division from the decoded vector onto the
    [w]-length scales — allclose, not bit-equal (last-ulp reassociation),
    which is why the PINNED contracts compare fused-vs-fused."""
    r = np.random.RandomState(2)
    w, size = 4, 4
    c = r.randn(w, compress.PACK_ALIGN).astype(np.float32)
    scales = jnp.asarray(np.abs(r.randn(w)).astype(np.float32) + 0.1)
    packed = jnp.stack(
        [compress.pack_signs_jnp(jnp.asarray(ci)) for ci in c])
    got = compress.unpack_signs_weighted_mean_jnp(packed, scales, size)
    legacy = compress.unpack_signs_weighted_sum_jnp(packed, scales) / size
    np.testing.assert_allclose(np.asarray(got), np.asarray(legacy),
                               rtol=1e-6, atol=1e-7)


def test_topk_encode_oracle_selection_and_residual():
    r = np.random.RandomState(3)
    rows, chunk, k = 4, 256, 8
    c2 = jnp.asarray(r.randn(rows, chunk).astype(np.float32))
    vals, idx, new_c2 = compress.topk_encode_jnp(c2, k)
    assert vals.dtype == jnp.bfloat16 and idx.dtype == jnp.int16
    _, want_idx = jax.lax.top_k(jnp.abs(c2), k)
    np.testing.assert_array_equal(np.asarray(idx),
                                  np.asarray(want_idx).astype(np.int16))
    c2n, idxn = np.asarray(c2), np.asarray(idx)
    new_n = np.asarray(new_c2)
    for rr in range(rows):
        sel = c2n[rr, idxn[rr].astype(np.int64)]
        np.testing.assert_array_equal(
            np.asarray(vals[rr], dtype=np.float32),
            sel.astype(jnp.bfloat16).astype(np.float32))
        # selected slots hold the bf16 rounding residual, others untouched
        np.testing.assert_array_equal(
            new_n[rr, idxn[rr].astype(np.int64)],
            sel - np.asarray(vals[rr], dtype=np.float32))
        mask = np.ones(chunk, bool)
        mask[idxn[rr].astype(np.int64)] = False
        np.testing.assert_array_equal(new_n[rr, mask], c2n[rr, mask])


def test_topk_encode_oracle_tie_break_lower_index():
    c2 = jnp.asarray([[1.0, -2.0, 2.0, 0.5]], jnp.float32)
    _, idx, _ = compress.topk_encode_jnp(c2, 2)
    # |−2| ties |2|: lax.top_k (and the kernel's min-index argmax) picks
    # the lower index first
    np.testing.assert_array_equal(np.asarray(idx), [[1, 2]])


def test_topk_decode_oracle_matches_numpy_scatter():
    r = np.random.RandomState(4)
    w, rows, chunk, k = 3, 2, 128, 16
    encs = [compress.topk_encode_jnp(
        jnp.asarray(r.randn(rows, chunk).astype(np.float32)), k)
        for _ in range(w)]
    all_vals = jnp.stack([e[0] for e in encs])
    all_idx = jnp.stack([e[1] for e in encs])
    got = compress.topk_decode_jnp(all_vals, all_idx, chunk, size=w)
    dense = np.zeros(rows * chunk, np.float32)
    vn = np.asarray(all_vals, dtype=np.float32)
    inn = np.asarray(all_idx)
    for wi in range(w):
        for rr in range(rows):
            for j in range(k):
                dense[rr * chunk + inn[wi, rr, j]] += vn[wi, rr, j]
    np.testing.assert_allclose(np.asarray(got), dense / w,
                               rtol=1e-6, atol=1e-7)


def test_topk_decode_size_fold_is_elementwise_divide():
    r = np.random.RandomState(5)
    vals, idx, _ = compress.topk_encode_jnp(
        jnp.asarray(r.randn(2, 128).astype(np.float32)), 8)
    all_vals, all_idx = vals[None], idx[None]
    folded = compress.topk_decode_jnp(all_vals, all_idx, 128, size=4)
    unfolded = compress.topk_decode_jnp(all_vals, all_idx, 128, size=1) / 4
    np.testing.assert_array_equal(np.asarray(folded), np.asarray(unfolded))


def test_matmul_pack_oracle_pads_with_exact_zeros():
    r = np.random.RandomState(6)
    m = jnp.asarray(r.randn(13, 32).astype(np.float32))
    q = jnp.asarray(r.randn(32, 2).astype(np.float32))
    out = factor_pack.matmul_pack_jnp(m, q, factor_pack.pad_rows(13))
    assert out.shape == (16, 2)
    np.testing.assert_array_equal(np.asarray(out)[13:], 0.0)
    np.testing.assert_allclose(np.asarray(out)[:13], np.asarray(m @ q),
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# dispatch plumbing: env gate, memo, AOT key stamp, bench labels
# ---------------------------------------------------------------------------

def test_public_dispatchers_match_with_no_pallas_toggled(clean_dispatch):
    """Every public fused entry point must return identical bits with the
    forced-oracle gate on and off.  On this CPU venue both sides resolve
    to the oracle, so the equality is trivially bit-exact — what the test
    pins is the dispatch seam itself: the env gate + memo reset reaches
    every entry point and flips nothing numerically."""
    r = np.random.RandomState(7)
    flat = jnp.asarray(r.randn(compress.PACK_ALIGN).astype(np.float32))
    state = jnp.asarray(r.randn(compress.PACK_ALIGN).astype(np.float32))
    c2 = jnp.asarray(r.randn(2, 256).astype(np.float32))
    m = jnp.asarray(r.randn(12, 32).astype(np.float32))
    q = jnp.asarray(r.randn(32, 2).astype(np.float32))

    def run_all():
        packed, absc = compress.pack_signs_encode(flat, state)
        scale = jnp.mean(absc)
        res = compress.signed_residual(absc, packed, scale)
        mean = compress.unpack_signs_weighted_mean(
            packed[None], scale[None], 2)
        vals, idx, new_c2 = compress.topk_encode(c2, 8)
        dense = compress.topk_decode(vals[None], idx[None], 256, size=2)
        tile = factor_pack.matmul_pack(m, q)
        return [packed, absc, res, mean, vals, idx, new_c2, dense, tile]

    base = run_all()
    clean_dispatch.setenv("THEANOMPI_TPU_NO_PALLAS", "1")
    _pallas_util.reset_dispatch_cache()
    assert _pallas_util.dispatch_pallas() is False
    forced = run_all()
    for a, b in zip(base, forced):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dispatch_gate_is_memoized_until_reset(clean_dispatch):
    assert _pallas_util.dispatch_pallas() is False      # CPU venue
    # flipping the env WITHOUT a reset must not re-read it: bench sets the
    # var once per process through bench_row_config, and a per-call
    # os.environ lookup was the satellite this memo removed
    clean_dispatch.setenv("THEANOMPI_TPU_NO_PALLAS", "1")
    assert _pallas_util.dispatch_pallas() is False
    assert _pallas_util._DISPATCH_MEMO is False
    _pallas_util.reset_dispatch_cache()
    assert _pallas_util._DISPATCH_MEMO is None
    assert _pallas_util.dispatch_pallas() is False


def test_aot_key_extra_stamps_no_pallas_only_when_forced(clean_dispatch):
    base = compile_cache.key_extra("train")
    assert "no_pallas" not in base       # pre-existing keys stay byte-stable
    clean_dispatch.setenv("THEANOMPI_TPU_NO_PALLAS", "1")
    forced = compile_cache.key_extra("train")
    assert forced.pop("no_pallas") == 1
    assert forced == base                # the stamp is the ONLY delta


def test_cfg_matches_fuse_token(monkeypatch):
    bench = importlib.import_module("bench")
    for k in list(os.environ):
        if k.startswith("BENCH_"):
            monkeypatch.delenv(k, raising=False)
    monkeypatch.delenv("PALLAS_AXON_REMOTE_COMPILE", raising=False)
    monkeypatch.setenv("BENCH_MODEL", "transformer_lm")
    monkeypatch.setenv("BENCH_BATCH", "8")
    monkeypatch.setenv("BENCH_STRATEGY", "onebit")
    monkeypatch.setenv("BENCH_CFG", '{"n_workers": 2}')
    assert bench._cfg_matches("transformer_lm-b8-onebit-n2")
    assert not bench._cfg_matches("transformer_lm-b8-onebit-n2-fuse")
    monkeypatch.setenv("BENCH_FUSE", "1")
    assert bench._cfg_matches("transformer_lm-b8-onebit-n2-fuse")
    assert not bench._cfg_matches("transformer_lm-b8-onebit-n2")
    # BENCH_FUSE=0 is the explicit CONTROL row, not the fuse row
    monkeypatch.setenv("BENCH_FUSE", "0")
    assert bench._cfg_matches("transformer_lm-b8-onebit-n2")
    assert not bench._cfg_matches("transformer_lm-b8-onebit-n2-fuse")


def test_bench_row_config_control_rows_force_oracle(clean_dispatch):
    """BENCH_FUSE=0 must flow through the ONE shared env→config assembly
    (bench_row_config) so prewarm and measurement agree on the forced-
    oracle key stamp — and must reset the dispatch memo in-process."""
    bench = importlib.import_module("bench")
    clean_dispatch.delenv("THEANOMPI_TPU_NO_PALLAS", raising=False)
    _pallas_util.dispatch_pallas()      # prime the memo pre-control
    bench.bench_row_config({"BENCH_MODEL": "transformer_lm",
                            "BENCH_FUSE": "0"})
    try:
        assert os.environ.get("THEANOMPI_TPU_NO_PALLAS") == "1"
        assert _pallas_util._DISPATCH_MEMO is None or \
            _pallas_util.dispatch_pallas() is False
    finally:
        os.environ.pop("THEANOMPI_TPU_NO_PALLAS", None)


def test_topk_chunk_over_int16_range_raises():
    """Satellite 1: the docstring previously claimed chunk ≤ 65536 but the
    int16 wire offsets wrap past 32768 — the assert is the contract."""
    strategies.TopK(chunk=32768)                      # boundary: fine
    with pytest.raises(AssertionError, match="32768"):
        strategies.TopK(chunk=40000)


def test_onebit_scale_uses_true_length_only():
    """Satellite 2: the scale is mean(|c|) over the TRUE vector, not the
    zero-padded pack grid — padding must not deflate it."""
    n = 100                      # pads to PACK_ALIGN = 32768
    tree = {"w": jnp.ones((n,), jnp.float32) * 2.0}
    strat = strategies.OneBit()
    state = strat.init_state(tree)
    assert state.shape[0] == compress.PACK_ALIGN
    # drive the scale computation exactly as __call__ does, minus the mesh
    from theanompi_tpu.utils import helper_funcs
    flat = helper_funcs.flatten_tree(
        tree, pad_to_multiple_of=compress.PACK_ALIGN)
    packed, absc = compress.pack_signs_encode(flat, state)
    n_true = helper_funcs.tree_size(tree)
    scale = jnp.mean(absc[:n_true]) + 1e-12
    np.testing.assert_allclose(float(scale), 2.0, rtol=1e-6)
    # the padded mean the old code computed would have been ~100/32768 of it
    assert float(jnp.mean(absc)) < 0.1


# ---------------------------------------------------------------------------
# traffic model + report schema
# ---------------------------------------------------------------------------

def test_compress_row_columns_disjoint_from_other_vocabularies():
    vocabularies = {
        "TRACE_ROW_COLUMNS": devprof.TRACE_ROW_COLUMNS,
        "BUCKET_ROW_COLUMNS": devprof.BUCKET_ROW_COLUMNS,
        "PIPELINE_ROW_COLUMNS": devprof.PIPELINE_ROW_COLUMNS,
        "USHARD_ROW_COLUMNS": devprof.USHARD_ROW_COLUMNS,
    }
    compress_cols = set(devprof.COMPRESS_ROW_COLUMNS)
    assert len(compress_cols) == len(devprof.COMPRESS_ROW_COLUMNS)
    for name, cols in vocabularies.items():
        clash = compress_cols & set(cols)
        assert not clash, f"COMPRESS_ROW_COLUMNS collide with {name}: {clash}"


def test_traffic_model_pinned_shrinks():
    """The acceptance numbers: ≥2× total HBM shrink for onebit and ≥2×
    decode shrink for topk, by construction of the stage lists.  Pinned to
    3 decimals so a stage silently dropped from the accounting fails."""
    onebit = devprof.compress_traffic_model("onebit", 1 << 22, 2)
    assert onebit["compress_hbm_shrink"] == pytest.approx(2.68, abs=0.02)
    assert onebit["compress_decode_shrink"] == pytest.approx(2.882, abs=0.02)
    assert onebit["compress_hbm_shrink"] >= 2.0
    topk = devprof.compress_traffic_model("topk", 1 << 22, 2)
    assert topk["compress_hbm_shrink"] >= 2.0
    assert topk["compress_decode_shrink"] >= 2.0
    psgd = devprof.compress_traffic_model(
        "powersgd2", 1 << 22, 2, leaf_shapes=[(512, 256), (256,)])
    assert psgd is not None and psgd["compress_hbm_shrink"] > 1.0
    # every returned dict carries exactly the declared columns + metadata
    for rep in (onebit, topk, psgd):
        assert set(devprof.COMPRESS_ROW_COLUMNS) <= set(rep)
        for _, stages in rep["stages"].items():
            assert all(b > 0 for _, b in stages)


def test_traffic_model_none_for_uncompressed_strategies():
    assert devprof.compress_traffic_model("bsp", 1 << 20, 2) is None
    assert devprof.compress_traffic_model("nccl16", 1 << 20, 2) is None
    # powersgd with nothing compressible (all leaves too small/1-D)
    assert devprof.compress_traffic_model(
        "powersgd2", 1 << 20, 2, leaf_shapes=[(8,), (4, 4)]) is None


def test_traffic_report_from_live_model_stub():
    """compress_traffic_report reads only (exchanger.strategy, params,
    mesh.shape[WORKER_AXIS]) — the stub pins that surface."""
    from theanompi_tpu.parallel.mesh import WORKER_AXIS
    strat = strategies.TopK(chunk=4096)
    model = types.SimpleNamespace(
        exchanger=types.SimpleNamespace(strategy=strat),
        params={"w": np.zeros((64, 32), np.float32),
                "b": np.zeros((32,), np.float32)},
        mesh=types.SimpleNamespace(shape={WORKER_AXIS: 2}))
    rep = devprof.compress_traffic_report(model)
    assert set(rep) == set(devprof.COMPRESS_ROW_COLUMNS)
    want = devprof.compress_traffic_model(
        "topk", 64 * 32 + 32, 2, chunk=4096, k_c=strat._k_c())
    assert rep["compress_hbm_shrink"] == want["compress_hbm_shrink"]
    # non-compression strategy → None, so bench rows stay clean
    model.exchanger.strategy = strategies.get_strategy("allreduce")
    assert devprof.compress_traffic_report(model) is None
