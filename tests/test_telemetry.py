"""Run-wide structured telemetry (ISSUE 4 tentpole): registry semantics,
the JSONL event stream, the flight recorder + launcher sweep, the
off-by-default cost contract, and the cross-worker run report."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import theanompi_tpu as tmpi
from theanompi_tpu.utils import telemetry
from theanompi_tpu.utils.telemetry import DISABLED, Histogram, Telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset_global_telemetry():
    """Every test leaves the process-wide registry disabled."""
    yield
    telemetry.init({})


# -- registry ---------------------------------------------------------------

def test_histogram_percentiles_and_bounded_reservoir():
    h = Histogram()
    for v in range(1, 1001):
        h.observe(float(v))
    s = h.summary()
    assert s["count"] == 1000 and s["min"] == 1.0 and s["max"] == 1000.0
    assert abs(s["p50"] - 500) <= 10
    assert abs(s["p95"] - 950) <= 15
    assert abs(s["p99"] - 990) <= 15
    # past the cap the reservoir thins but count/sum/extrema stay exact
    h2 = Histogram(cap=128)
    for v in range(20000):
        h2.observe(float(v))
    assert h2.count == 20000 and h2.max == 19999.0
    assert len(h2._samples) <= 128
    assert h2.percentile(99) > 15000          # tail stays representative


def test_registry_counters_gauges_events_and_ring():
    tm = Telemetry(rank=3, run_id="r", flight_events=16)
    tm.counter("a")
    tm.counter("a", 2)
    tm.gauge("g", 7.5)
    tm.observe("h", 0.25)
    for i in range(40):
        tm.event("e", i=i)
    assert tm.counters["a"] == 3 and tm.gauges["g"] == 7.5
    assert tm.hists["h"].count == 1
    tail = tm.tail(4)
    assert len(tail) == 4 and tail[-1]["i"] == 39
    assert all(ev["rank"] == 3 and ev["run"] == "r" for ev in tail)
    # ring is bounded: only the last 16 events survive
    assert len(tm.tail(100)) == 16


def test_stream_summary_and_flight_dump(tmp_path):
    d = str(tmp_path)
    tm = Telemetry(rank=1, run_id="rx", stream_dir=d)
    tm.phase("train", 0.01)
    tm.event("beat", ring_only=True, label="iter 1")   # ring, not stream
    tm.counter("c")
    path = tm.dump_flight(reason="test dump")
    tm.close()
    evs = [json.loads(line)
           for line in open(os.path.join(d, "telemetry_rank1.jsonl"))]
    assert [e["ev"] for e in evs] == ["run_start", "phase"]
    assert evs[1]["sec"] == "train"
    flight = [json.loads(line) for line in open(path)]
    assert flight[0]["ev"] == "flight_dump"
    assert flight[0]["reason"] == "test dump"
    assert any(e["ev"] == "beat" for e in flight)      # ring-only included
    summ = json.load(open(os.path.join(d, "telemetry_summary_rank1.json")))
    assert summ["counters"]["c"] == 1
    assert summ["hist"]["phase.train"]["count"] == 1
    # closed instance is inert: stale references become no-ops, not errors
    assert not tm.enabled
    tm.event("late")
    tm.counter("late")


def test_init_resolution_rules(tmp_path):
    assert telemetry.init({}) is DISABLED                  # off by default
    assert telemetry.init({"telemetry": False,
                           "record_dir": str(tmp_path)}) is DISABLED
    tm = telemetry.init({"telemetry": True})               # in-memory
    assert tm.enabled and tm.stream_dir is None
    tm2 = telemetry.init({"record_dir": str(tmp_path), "rank": 2,
                          "run_id": "rid"})
    assert not tm.enabled                  # re-init closed the previous one
    assert tm2.stream_dir == str(tmp_path) and tm2.rank == 2
    assert telemetry.active() is tm2
    telemetry.init({})
    assert telemetry.active() is DISABLED


def test_aggregate_memory_stats_multi_device():
    """ISSUE 7 satellite: HBM gauges aggregate across ALL local devices —
    summed in-use, max peak, min limit, worst-device headroom — so
    multi-chip pressure can't hide behind device 0."""
    stats = [
        {"bytes_in_use": 100, "peak_bytes_in_use": 900, "bytes_limit": 1000},
        {"bytes_in_use": 300, "peak_bytes_in_use": 400, "bytes_limit": 1000},
        None,                                    # a device with no stats
        {"bytes_in_use": 50},                    # partial stats
    ]
    vals = telemetry.aggregate_memory_stats(stats)
    assert vals["hbm_bytes_in_use"] == 450          # summed
    assert vals["hbm_peak_bytes"] == 900            # max (hottest chip)
    assert vals["hbm_bytes_limit"] == 1000          # min (binding budget)
    assert vals["hbm_min_headroom_bytes"] == 100    # worst device: 1000-900
    assert telemetry.aggregate_memory_stats([None, None]) == {}
    assert telemetry.aggregate_memory_stats([]) == {}


def test_system_snapshot_emits_device_count_and_queue_depth():
    tm = telemetry.init({"telemetry": True})
    tm.gauge("prefetch.queue_depth", 3)
    vals = tm.system_snapshot(iter=7)
    # the 8-device CPU mesh: count emitted even though CPU has no
    # memory_stats; the loader's queue-depth gauge is sampled into the
    # stream (the Perfetto counter track reads it from gauges events)
    assert vals["device_count"] == 8
    assert vals["prefetch.queue_depth"] == 3
    assert vals["iter"] == 7


# -- the cost contract ------------------------------------------------------

def test_disabled_registry_is_noop_and_cheap():
    """Disabled ≡ one attribute check: every method is a no-op and the
    guarded hot-path pattern adds no measurable per-iteration cost."""
    tm = DISABLED
    assert not tm.enabled
    tm.counter("x")
    tm.gauge("x", 1)
    tm.observe("x", 1.0)
    tm.phase("train", 0.1)
    tm.event("x", a=1)
    assert tm.tail() == [] and tm.summary() == {}
    assert tm.dump_flight(reason="r") is None
    assert tm.counters == {} and tm.hists == {}

    N = 200_000

    def bare():
        t0 = time.perf_counter()
        acc = 0
        for i in range(N):
            acc += i
        return time.perf_counter() - t0

    def guarded():
        t0 = time.perf_counter()
        acc = 0
        for i in range(N):
            if tm.enabled:                      # the whole hot-path cost
                tm.phase("train", 0.1)
            acc += i
        return time.perf_counter() - t0

    b = min(bare() for _ in range(3))
    g = min(guarded() for _ in range(3))
    per_iter = max(0.0, g - b) / N
    assert per_iter < 2e-6, (
        f"disabled telemetry costs {per_iter * 1e9:.0f} ns/iter "
        f"(bare {b:.3f}s vs guarded {g:.3f}s)")


def test_enabled_telemetry_does_not_perturb_training():
    """Telemetry only reads clocks: the same seeded run with the registry
    on (in-memory) and off must produce bit-identical parameters."""
    import jax

    def run(**extra):
        rule = tmpi.BSP()
        rule.init(devices=4, modelfile="tests.conftest",
                  modelclass="TinyModel", epochs=1, batch_size=8,
                  n_train=64, verbose=False, scale_lr=False, seed=5, **extra)
        rule.wait()
        return jax.device_get(rule.model.step_state["params"])

    a = run()
    b = run(telemetry=True)
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(la, lb)


# -- component threading ----------------------------------------------------

def test_prefetch_exports_queue_depth_and_producer_gauges():
    from tests.conftest import SyntheticData
    from theanompi_tpu.models.data.prefetch import PrefetchLoader

    tm = telemetry.init({"telemetry": True})
    data = PrefetchLoader(SyntheticData(batch_size=8, n_train=64))
    data.shuffle_data(0)
    for i in range(1, 9):
        data.next_train_batch(i)
    assert tm.counters["prefetch.dequeues"] == 8
    assert tm.hists["prefetch.queue_depth"].count == 8
    assert tm.hists["prefetch.produce_secs"].count >= 1
    assert "prefetch.queue_depth" in tm.gauges
    # a consumer outrunning the producer leaves starved dequeues behind
    assert tm.counters.get("prefetch.starved_dequeues", 0) <= 8


def test_exchanger_records_per_exchange_histograms():
    """Unfused EASGD: each exchange lands one sample in the dispatch
    histogram and one in phase.comm (via the recorder bridge) — full
    per-exchange distributions, not bare sums."""
    rule = tmpi.EASGD()
    rule.init(devices=4, modelfile="tests.conftest", modelclass="TinyModel",
              epochs=1, batch_size=8, n_train=64, verbose=False,
              scale_lr=False, sync_freq=1, telemetry=True)
    rule.wait()
    tm = rule.worker.telemetry
    assert tm.counters["exchange.count"] >= 1
    assert tm.counters["exchange.count.easgd"] == tm.counters["exchange.count"]
    assert tm.hists["exchange.dispatch_secs"].count == \
        tm.counters["exchange.count"]
    assert tm.hists["phase.comm"].count == tm.counters["exchange.count"]
    assert tm.hists["phase.train"].count >= 1


def test_compile_cache_counters_mirror_into_telemetry(tmp_path):
    from theanompi_tpu.utils.compile_cache import CompileCache

    tm = telemetry.init({"telemetry": True})
    cc = CompileCache(str(tmp_path))
    cc._tick("hits")
    cc._tick("misses")
    cc._tick("misses")
    assert tm.counters["compile_cache.hits"] == 1
    assert tm.counters["compile_cache.misses"] == 2
    assert cc.counters["misses"] == 2               # the local view too


def test_watchdog_stall_message_includes_flight_tail(capfd):
    from theanompi_tpu.utils.watchdog import StallWatchdog

    telemetry.init({"telemetry": True})
    wd = StallWatchdog(timeout_s=10)
    wd.beat("epoch 0 iter 7")
    wd.beat("epoch 0 iter 8")
    wd._default_handler(12.0, "epoch 0 iter 8")
    err = capfd.readouterr().err
    assert "last telemetry events" in err
    assert "epoch 0 iter 7" in err and "epoch 0 iter 8" in err


# -- the acceptance path: run → streams → report ----------------------------

def test_two_worker_run_streams_and_report(tmp_path):
    """A two-worker launcher run with telemetry on: per-rank JSONL streams
    appear, and telemetry_report.py merges them into a report with phase
    p50/p95, a straggler ranking, and queue-depth gauges."""
    from theanompi_tpu import launcher

    rec = str(tmp_path / "run")
    rc = launcher.main([
        "--rule", "bsp", "--modelfile", "tests.conftest",
        "--modelclass", "TinyModel", "--n-workers", "2",
        "--record-dir", rec,
        "platform=cpu", "epochs=2", "batch_size=8", "n_train=64",
        "verbose=false", "scale_lr=false", "para_load=true", "printFreq=2",
    ])
    assert rc == 0
    stream = os.path.join(rec, "telemetry_rank0.jsonl")
    assert os.path.exists(stream)
    evs = [json.loads(line) for line in open(stream)]
    kinds = {e["ev"] for e in evs}
    assert {"run_start", "train_begin", "phase", "train_record",
            "val_record", "gauges", "train_end"} <= kinds
    # one shared run id, launcher-stamped
    assert len({e["run"] for e in evs}) == 1
    # host gauges always present (HBM joins on TPU via memory_stats)
    gauges = [e for e in evs if e["ev"] == "gauges"]
    assert gauges and "host_rss_bytes" in gauges[-1]
    assert os.path.exists(
        os.path.join(rec, "telemetry_summary_rank0.json"))

    out_json = str(tmp_path / "report.json")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/telemetry_report.py"),
         rec, "--json", out_json],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "phase breakdown" in r.stdout
    assert "straggler ranking" in r.stdout
    rep = json.load(open(out_json))
    for sec in ("train", "load", "compile"):
        assert rep["phases"][sec]["p95"] is not None
        assert rep["phases"][sec]["p50"] is not None
    assert rep["straggler_ranking"] and \
        rep["straggler_ranking"][0]["p95_train_secs"] is not None
    # para_load=true → the prefetch queue-depth gauges reach the report
    pf = rep["flags"]["prefetch"]["0"] if "0" in rep["flags"].get(
        "prefetch", {}) else rep["flags"]["prefetch"][0]
    assert pf["min_queue_depth"] is not None
    assert rep["throughput_timeline"]


def test_worker_sigterm_dumps_flight(tmp_path):
    """ISSUE 7 satellite — the fatal-signal path of the PR 4 flight
    recorder, previously only exercised by the stall path: a CLI worker
    SIGTERM'd mid-run leaves a flight_rank0.jsonl that parses and ends
    with the fatal_signal event, and the process dies with the honest
    signal exit.

    ISSUE 19 rides the same run: with ``numerics=true`` and an absurd
    ratio floor every numerics report trips ``update_ratio_collapse``,
    so the dumped ring must carry the §25 numerics report events AND the
    numerics-detector anomaly — the end-to-end proof that the new
    detectors reach the flight/post-mortem plane."""
    import signal

    rec = str(tmp_path / "rec")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "theanompi_tpu.worker",
         "bsp", "tests.conftest", "TinyModel",
         "platform=cpu", "epochs=999", "batch_size=8", "n_train=64",
         "verbose=false", "scale_lr=false", "printFreq=2",
         "numerics=true", "sentry_ratio_floor=1000000",
         f"record_dir={rec}"],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    try:
        # wait until training is demonstrably mid-run: the per-rank stream
        # carries at least one phase bracket
        stream = os.path.join(rec, "telemetry_rank0.jsonl")
        deadline = time.time() + 120
        seen_phase = False
        while time.time() < deadline and not seen_phase:
            if os.path.exists(stream):
                with open(stream) as f:
                    seen_phase = any('"ev": "phase"' in ln for ln in f)
            if proc.poll() is not None:
                break
            if not seen_phase:
                time.sleep(0.25)
        assert seen_phase, (proc.poll(),
                            proc.stderr.read()[-2000:] if proc.poll()
                            is not None else "no phase event within 120s")
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.communicate()
    # the hook re-raises with the default handler: honest signal exit
    assert proc.returncode == -signal.SIGTERM
    flight_path = os.path.join(rec, "flight_rank0.jsonl")
    assert os.path.exists(flight_path), os.listdir(rec)
    flight = [json.loads(line) for line in open(flight_path)]  # parses
    assert flight[0]["ev"] == "flight_dump"
    assert "signal" in flight[0]["reason"]
    assert flight[-1]["ev"] == "fatal_signal"
    assert flight[-1]["signum"] == int(signal.SIGTERM)
    # the trail shows the run was mid-training when the signal landed
    assert any(e["ev"] in ("phase", "beat", "train_record")
               for e in flight[1:])
    # §25 end-to-end: the ring carries the numerics reports and the
    # numerics-detector anomaly the rigged ratio floor forced
    numerics_evs = [e for e in flight[1:] if e["ev"] == "numerics"]
    assert numerics_evs, "no numerics report reached the flight ring"
    assert all(e["grad_norm"] > 0 for e in numerics_evs)
    anoms = [e for e in flight[1:] if e["ev"] == "anomaly"]
    assert any(e["kind"] == "update_ratio_collapse" for e in anoms), anoms


def test_crash_dumps_flight_and_launcher_sweeps(tmp_path):
    """A forced mid-run crash leaves flight_rank*.jsonl (dumped by the
    dying worker) which the supervising launcher sweeps into a crash_
    directory before restarting; the resumed run completes."""
    from theanompi_tpu import launcher

    rec = str(tmp_path / "rec")
    ckpt = str(tmp_path / "ckpt")
    marker = str(tmp_path / "crashed")
    # 4 iters/epoch; crash_at=5 fires in epoch 1, after epoch 0's ckpt
    rc = launcher.main([
        "--supervise", "2", "--rule", "bsp",
        "--modelfile", "tests.conftest", "--modelclass", "CrashOnceModel",
        "--record-dir", rec,
        "platform=cpu", "epochs=2", "batch_size=8", "n_train=256",
        "n_workers=8", "verbose=false", "scale_lr=false",
        f"ckpt_dir={ckpt}", f"crash_marker={marker}", "crash_at=5",
    ])
    assert rc == 0
    assert os.path.exists(marker)               # the crash really happened
    swept = [d for d in os.listdir(rec) if d.startswith("crash_")]
    assert swept, f"no swept crash dir in {os.listdir(rec)}"
    flight_path = os.path.join(rec, swept[0], "flight_rank0.jsonl")
    assert os.path.exists(flight_path)
    flight = [json.loads(line) for line in open(flight_path)]
    assert flight[0]["ev"] == "flight_dump"
    assert "injected crash" in flight[0]["reason"]
    # the trail shows what the rank was doing: beats + phases + the crash
    kinds = {e["ev"] for e in flight}
    assert "beat" in kinds and "crash" in kinds
    # the dump itself was NOT left in record_dir root (swept aside)
    assert not os.path.exists(os.path.join(rec, "flight_rank0.jsonl"))
    # the resumed run's stream appended to the same per-rank file
    evs = [json.loads(line)
           for line in open(os.path.join(rec, "telemetry_rank0.jsonl"))]
    assert any(e["ev"] == "train_end" for e in evs)
    assert any(e["ev"] == "crash" for e in evs)
    # and the resumed run's recorder LOADED the pre-crash records before
    # its first save, so the final JSONL holds BOTH epochs' val records
    # (the Recorder.load round-trip running on the path it exists for)
    recs = [json.loads(line)
            for line in open(os.path.join(rec, "inforec_rank0.jsonl"))]
    assert len([x for x in recs if "val_cost" in x]) == 2
