"""Model-zoo contract tests: every zoo model builds, runs a forward pass,
and (for the cheap ones) a full compiled train step (SURVEY.md §2.7 parity:
AlexNet / GoogLeNet / VGG-16 (+11) / ResNet-50 / CIFAR-10)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.parallel.exchanger import BSP_Exchanger
from theanompi_tpu.parallel.mesh import worker_mesh

ZOO = [
    ("theanompi_tpu.models.cifar10", "Cifar10_model", 10),
    ("theanompi_tpu.models.alex_net", "AlexNet", 16),
    ("theanompi_tpu.models.googlenet", "GoogLeNet", 16),
    ("theanompi_tpu.models.vggnet_16", "VGGNet_16", 16),
    ("theanompi_tpu.models.vggnet_16", "VGGNet_11_shallow", 16),
    ("theanompi_tpu.models.resnet50", "ResNet50", 16),
]


def _build(modelfile, modelclass, n_class, **cfg):
    import importlib
    cls = getattr(importlib.import_module(modelfile), modelclass)
    mesh = worker_mesh(1)
    config = {"mesh": mesh, "size": 1, "rank": 0, "verbose": False,
              "batch_size": 2, "n_class": n_class,
              "compute_dtype": jnp.float32, "synthetic_batches": 1,
              "synthetic_train": 64, "synthetic_val": 32, **cfg}
    return cls(config)


@pytest.mark.parametrize("modelfile,modelclass,n_class", ZOO)
def test_forward_shapes_and_finite(modelfile, modelclass, n_class):
    model = _build(modelfile, modelclass, n_class)
    batch = model.data.next_train_batch(0)
    x = jnp.asarray(batch["x"][:2])
    logits, _ = model.apply_model(model.params, x, train=False, rng=None,
                                  state=model.bn_state)
    assert logits.shape == (2, n_class)
    assert bool(jnp.isfinite(logits.astype(jnp.float32)).all())


@pytest.mark.parametrize("modelfile,modelclass,n_class", [
    ("theanompi_tpu.models.cifar10", "Cifar10_model", 10),
    ("theanompi_tpu.models.resnet50", "ResNet50", 8),
])
def test_full_train_step(modelfile, modelclass, n_class):
    """One compiled SPMD train step end-to-end (ResNet covers the BN-state
    threading path; CIFAR covers the plain path)."""
    model = _build(modelfile, modelclass, n_class)
    model.compile_iter_fns(BSP_Exchanger(model.config))
    model.data.shuffle_data(0)
    model.train_iter(1, None)
    cost = float(np.asarray(model.current_info["cost"]))
    assert np.isfinite(cost)
    if modelclass == "ResNet50":
        # BN running stats must have moved off their init
        bn = jax.device_get(model.step_state["bn_state"])
        means = [np.asarray(v) for k, v in
                 jax.tree_util.tree_flatten_with_path(bn)[0]
                 if "mean" in str(k[-1])]
        assert any((m != 0).any() for m in means)


def test_train_decreases_loss_alexnet_tiny():
    """AlexNet trains on its synthetic data (labels are random, but the
    model can still fit them — loss must drop within a few steps)."""
    model = _build("theanompi_tpu.models.alex_net", "AlexNet", 8,
                   batch_size=4, learning_rate=0.02)
    model.compile_iter_fns(BSP_Exchanger(model.config))
    model.data.shuffle_data(0)
    costs = []
    for i in range(6):
        model.train_iter(i + 1, None)
        costs.append(float(np.asarray(model.current_info["cost"])))
    assert costs[-1] < costs[0], costs


@pytest.mark.parametrize("n_workers", [1, 4])
def test_resnet_bn_composes_with_steps_per_call(n_workers):
    """Round-5 regression (found pre-hardware by the AOT compile of the
    staged resnet50-*-spc8 rows): sync_bn's pmean returns worker-INVARIANT
    BN stats, which mismatched the worker-varying scan carry under
    steps_per_call > 1 — BN models never met spc>1 anywhere else
    (AlexNet/GoogLeNet/VGG use LRN).  Must trace, run, and keep updating
    BN stats on both a single-worker mesh (the real-TPU-row shape) and a
    multi-worker mesh."""
    mesh = worker_mesh(n_workers)
    model = _build("theanompi_tpu.models.resnet50", "ResNet50", 8,
                   mesh=mesh, size=n_workers, batch_size=2,
                   steps_per_call=2, synthetic_batches=2)
    model.compile_iter_fns(BSP_Exchanger(model.config))
    model.data.shuffle_data(0)
    model.train_iter(1, None)                   # steps 0 and 1, one call
    assert np.isfinite(float(np.asarray(model.current_info["cost"])))
    bn = jax.device_get(model.step_state["bn_state"])
    means = [np.asarray(v) for k, v in
             jax.tree_util.tree_flatten_with_path(bn)[0]
             if "mean" in str(k[-1])]
    assert any((m != 0).any() for m in means)


def test_resnet_bn_trains_under_async_rules():
    """Round-5 review regression: the async rules' sync_bn is the
    identity (replicas diverge on purpose), so their BN stats reach
    _revary_bn already worker-varying — the re-mark must be idempotent,
    not crash with pcast varying->varying.  (Rule tests elsewhere use the
    BN-free TinyModel, which is how this stayed latent.)"""
    from theanompi_tpu.parallel.exchanger import get_exchanger
    mesh = worker_mesh(4)
    model = _build("theanompi_tpu.models.resnet50", "ResNet50", 8,
                   mesh=mesh, size=4, batch_size=2)
    cfg = dict(model.config)
    model.compile_iter_fns(get_exchanger("gosgd", cfg))
    model.data.shuffle_data(0)
    model.train_iter(0, None)
    assert np.isfinite(float(np.asarray(model.current_info["cost"])))

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
