"""Layer library unit tests vs NumPy oracles (reference layers2.py parity:
Conv/Pool/LRN/FC/Dropout/Softmax/BatchNorm — SURVEY.md §2.7)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.models import layers as L

KEY = jax.random.key(0)
F32 = jnp.float32


def test_conv_shapes_and_groups():
    x = jnp.ones((2, 16, 16, 4))
    conv = L.Conv(4, 8, 3, padding="SAME", compute_dtype=F32, name="c")
    p = conv.init(KEY)
    assert p["w"].shape == (3, 3, 4, 8)
    y = conv.apply(p, x)
    assert y.shape == (2, 16, 16, 8)
    # AlexNet-style 2-group conv halves the per-group input channels
    gconv = L.Conv(4, 8, 3, groups=2, compute_dtype=F32, name="g")
    gp = gconv.init(KEY)
    assert gp["w"].shape == (3, 3, 2, 8)
    assert gconv.apply(gp, x).shape == (2, 16, 16, 8)


def test_conv_stride_valid():
    x = jnp.ones((1, 227, 227, 3))
    conv = L.Conv(3, 96, 11, stride=4, padding="VALID", compute_dtype=F32)
    y = conv.apply(conv.init(KEY), x)
    assert y.shape == (1, 55, 55, 96)   # AlexNet conv1 geometry


def test_conv_grad_works_in_bf16():
    """The default mixed-precision path must be differentiable (regression:
    conv transpose with preferred_element_type broke in jax 0.9)."""
    conv = L.Conv(3, 4, 3, compute_dtype=jnp.bfloat16, name="c")
    p = conv.init(KEY)
    x = jnp.ones((2, 8, 8, 3))

    def loss(p):
        return conv.apply(p, x).astype(jnp.float32).sum()

    g = jax.grad(loss)(p)
    assert g["w"].shape == p["w"].shape
    assert bool(jnp.isfinite(g["w"]).all())


def test_pool_max_oracle():
    x = jnp.asarray(np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1))
    pool = L.Pool(2, 2, mode="max")
    y = np.asarray(pool.apply(None, x))[0, :, :, 0]
    np.testing.assert_array_equal(y, [[5, 7], [13, 15]])


def test_pool_avg_oracle():
    x = jnp.ones((1, 4, 4, 1))
    pool = L.Pool(2, 2, mode="avg")
    np.testing.assert_allclose(np.asarray(pool.apply(None, x)), 1.0)


def test_overlapping_pool():
    # the reference zoo's 3x3/stride-2 overlapping pooling
    x = jnp.ones((1, 15, 15, 2))
    pool = L.Pool(3, 2, mode="max")
    assert pool.apply(None, x).shape == (1, 7, 7, 2)


def test_lrn_oracle():
    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 3, 7).astype(np.float32)
    lrn = L.LRN(n=5, k=2.0, alpha=1e-4, beta=0.75)
    got = np.asarray(lrn.apply(None, jnp.asarray(x)))
    # numpy oracle: cross-channel windowed sum of squares
    sq = x ** 2
    pad = np.pad(sq, [(0, 0)] * 3 + [(2, 2)])
    ssum = sum(pad[..., i:i + 7] for i in range(5))
    expect = x / (2.0 + (1e-4 / 5) * ssum) ** 0.75
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)


def test_dropout_train_vs_eval():
    d = L.Dropout(0.5)
    x = jnp.ones((4, 100))
    # eval: identity
    np.testing.assert_array_equal(np.asarray(d.apply(None, x)), 1.0)
    # train: ~half dropped, survivors scaled 2x
    y = np.asarray(d.apply(None, x, train=True, rng=jax.random.key(1)))
    assert set(np.unique(y)) <= {0.0, 2.0}
    assert 0.3 < (y == 0).mean() < 0.7


def test_batchnorm_train_and_running_stats():
    bn = L.BatchNorm(4, momentum=0.5)
    p, s = bn.init(KEY), bn.init_state()
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(32, 5, 5, 4).astype(np.float32) * 3 + 1)
    y, s2 = bn.apply(p, x, train=True, state=s)
    ym = np.asarray(y).mean(axis=(0, 1, 2))
    ys = np.asarray(y).std(axis=(0, 1, 2))
    np.testing.assert_allclose(ym, 0.0, atol=1e-4)
    np.testing.assert_allclose(ys, 1.0, atol=1e-2)
    # running stats pulled halfway (momentum 0.5) toward batch stats
    assert (np.asarray(s2["mean"]) != 0).all()
    # eval mode uses running stats and returns no update
    y_eval, s3 = bn.apply(p, x, train=False, state=s2)
    assert s3 is None


def test_sequential_threads_bn_state():
    seq = L.Sequential([
        L.FC(8, 8, compute_dtype=F32, name="fc"),
        L.BatchNorm(8, name="bn"),
    ])
    p = seq.init(KEY)
    s = seq.init_state()
    x = jnp.ones((4, 8))
    y, s2 = seq.apply(p, x, train=True, state=s)
    assert y.shape == (4, 8)
    assert "bn" in s2 and (np.asarray(s2["bn"]["mean"]) !=
                           np.asarray(s["bn"]["mean"])).any()


def test_sequential_unique_names():
    seq = L.Sequential([L.Pool(2, name="p"), L.Pool(2, name="p")])
    assert seq._keys == ["p", "p_1"]


def test_softmax_cross_entropy_oracle():
    logits = jnp.asarray([[2.0, 0.0, -2.0], [0.0, 0.0, 0.0]])
    labels = jnp.asarray([0, 2])
    got = float(L.softmax_cross_entropy(logits, labels))
    p0 = np.exp(2) / (np.exp(2) + 1 + np.exp(-2))
    expect = (-np.log(p0) - np.log(1 / 3)) / 2
    np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_errors_topk():
    logits = jnp.asarray([[5., 4., 3., 2., 1., 0.]] * 2)
    labels = jnp.asarray([0, 5])
    assert float(L.errors(logits, labels)) == 0.5
    assert float(L.errors_top_x(logits, labels, 5)) == 0.5
    assert float(L.errors_top_x(logits, labels, 6)) == 0.0


def test_init_schemes():
    # one fixed key on purpose: scheme shapes/scales are under test,
    # not stream independence (suppressions below)
    k = jax.random.key(2)
    w = L.init_weight(k, (1000,), ("normal", 0.01))
    assert 0.005 < float(jnp.std(w)) < 0.015
    c = L.init_weight(k, (10,), ("constant", 0.1))  # tpulint: disable=rng-discipline
    np.testing.assert_allclose(np.asarray(c), 0.1)
    he = L.init_weight(k, (100, 100), "he")  # tpulint: disable=rng-discipline
    assert 0.1 < float(jnp.std(he)) < 0.2    # sqrt(2/100) ≈ 0.141
    with pytest.raises(ValueError):
        L.init_weight(k, (3,), "bogus")  # tpulint: disable=rng-discipline


def test_batchnorm_bf16_norm_dtype_matches_fp32_path():
    """norm_dtype=bfloat16 (the perf lever) must keep stats fp32-exact and
    normalize within bf16 rounding of the fp32-exact path."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from theanompi_tpu.models import layers as L

    r = np.random.RandomState(3)
    x = jnp.asarray(r.randn(4, 5, 5, 8).astype(np.float32) * 2 + 1,
                    dtype=jnp.bfloat16)
    bn32 = L.BatchNorm(8)
    bnbf = L.BatchNorm(8, norm_dtype=jnp.bfloat16)
    params = bn32.init(jax.random.key(0))
    params["scale"] = jnp.asarray(r.rand(8).astype(np.float32) + 0.5)
    params["bias"] = jnp.asarray(r.randn(8).astype(np.float32))
    state = bn32.init_state()

    y32, st32 = bn32.apply(params, x, train=True, state=state)
    ybf, stbf = bnbf.apply(params, x, train=True, state=state)
    # running stats are computed identically in fp32
    for k in st32:
        np.testing.assert_array_equal(np.asarray(st32[k]),
                                      np.asarray(stbf[k]))
    np.testing.assert_allclose(np.asarray(ybf, np.float32),
                               np.asarray(y32, np.float32),
                               rtol=0.05, atol=0.05)
    assert ybf.dtype == jnp.bfloat16

    # eval path too
    ye32, _ = bn32.apply(params, x, train=False, state=st32)
    yebf, _ = bnbf.apply(params, x, train=False, state=stbf)
    np.testing.assert_allclose(np.asarray(yebf, np.float32),
                               np.asarray(ye32, np.float32),
                               rtol=0.05, atol=0.05)
