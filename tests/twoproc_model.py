"""Shared model body for the 2-process distributed test (not a test file).

Standalone on purpose: must be importable from the spawned subprocesses
WITHOUT pulling in ``tests.conftest`` (which pins 8 virtual devices and
single-process mode).
"""

from __future__ import annotations


def _train_and_fingerprint(m, exchanger, n_steps: int,
                           steps_per_call: int = 1) -> dict:
    """Shared tail: compile, train ``n_steps``, gather multi-host, and
    fingerprint the params (per-leaf sums + first elements).  With
    ``steps_per_call=k`` each call's count names its LAST step, so the
    counts stride by k over the same step indices."""
    import jax
    import numpy as np

    from theanompi_tpu.parallel import steps

    m.compile_iter_fns(exchanger)
    m.data.shuffle_data(0)
    for i in range(steps_per_call, n_steps + 1, steps_per_call):
        m.train_iter(i, None)
    if getattr(m, "_fsdp", None) is not None:
        # chunks partition the params across workers (and hosts) — the
        # comparable object is the assembled canonical tree
        leaves = jax.tree_util.tree_leaves(m.canonical_host_params())
    else:
        host = steps.tree_to_host(m.step_state["params"])
        leaves = jax.tree_util.tree_leaves(jax.device_get(host))
    return {"sums": [float(np.asarray(l).sum()) for l in leaves],
            "first": [float(np.asarray(l).reshape(-1)[0]) for l in leaves]}


def fingerprint_after_steps(n_workers: int, n_steps: int = 2,
                            steps_per_call: int = 1, **cfg_extra) -> dict:
    """Run ``n_steps`` BSP iterations on a tiny MLP over ``n_workers`` and
    return a params fingerprint (per-leaf sums + first elements) computed
    from the gathered global state.  ``cfg_extra`` passes straight into the
    model config (e.g. ``fsdp=True``)."""
    import jax.numpy as jnp
    import numpy as np

    from theanompi_tpu.models import layers as L
    from theanompi_tpu.models.data import DataBase
    from theanompi_tpu.models.model_base import ModelBase
    from theanompi_tpu.parallel.exchanger import BSP_Exchanger
    from theanompi_tpu.parallel.mesh import worker_mesh

    class Data(DataBase):
        def __init__(self, config=None, batch_size=8):
            super().__init__(config, batch_size)
            r = np.random.RandomState(7)
            w = r.randn(12)
            rr = np.random.RandomState(11)
            x = rr.randn(128, 12).astype(np.float32)
            self.x_train = x
            self.y_train = (x @ w > 0).astype(np.int32)
            self.x_val, self.y_val = self.x_train, self.y_train
            self._finalize()

    class M(ModelBase):
        batch_size = 8
        n_subb = 1
        learning_rate = 0.05
        momentum = 0.9
        weight_decay = 0.0
        seed = 3

        def build_model(self):
            self.seq = L.Sequential([
                L.FC(12, 16, w_init="he", compute_dtype=jnp.float32,
                     name="fc1"),
                L.FC(16, 2, w_init=("normal", 0.01), activation=None,
                     compute_dtype=jnp.float32, name="out"),
            ])
            self.data = Data(self.config, self.batch_size)

    mesh = worker_mesh(n_workers)
    config = {"mesh": mesh, "size": n_workers, "rank": 0, "verbose": False,
              "steps_per_call": steps_per_call, **cfg_extra}
    return _train_and_fingerprint(M(config), BSP_Exchanger(config), n_steps,
                                  steps_per_call)


def _lm_fingerprint(dp: int, n_steps: int, **parallel_kw) -> dict:
    """One shared tiny-LM config for the model-parallel two-process modes —
    only the mesh/parallelism kwargs differ between tp and pp."""
    import jax.numpy as jnp

    from theanompi_tpu.models.transformer_lm import TransformerLM
    from theanompi_tpu.parallel.exchanger import BSP_Exchanger
    from theanompi_tpu.parallel.mesh import worker_mesh

    mesh = worker_mesh(dp, tp=parallel_kw.get("tp", 1),
                       pp=parallel_kw.get("pp", 1),
                       sp=parallel_kw.get("sp", 1))
    cfg = {"mesh": mesh, "size": dp, "rank": 0, "verbose": False,
           "batch_size": 8, "seq_len": 16, "vocab": 16, "d_model": 16,
           "n_head": 2, "synthetic_train": 64, "synthetic_val": 32,
           "compute_dtype": jnp.float32, "seed": 5, "n_layer": 1,
           **parallel_kw}
    return _train_and_fingerprint(TransformerLM(cfg), BSP_Exchanger(cfg),
                                  n_steps,
                                  parallel_kw.get("steps_per_call", 1))


def fingerprint_after_steps_tp(dp: int = 2, tp: int = 2,
                               n_steps: int = 2) -> dict:
    """The real-scale layout: dp ACROSS hosts × tp WITHIN a host — the tp
    psums ride intra-host links, the dp gradient reduce crosses hosts."""
    return _lm_fingerprint(dp, n_steps, tp=tp)


def fingerprint_after_steps_pp(dp: int = 2, pp: int = 2,
                               n_steps: int = 2) -> dict:
    """dp across hosts × pipeline stages within a host: microbatch
    activations ppermute intra-host, the gradient reduce crosses hosts."""
    return _lm_fingerprint(dp, n_steps, pp=pp, pp_microbatches=4,
                           n_layer=2)


def fingerprint_after_steps_sp(dp: int = 2, sp: int = 2,
                               n_steps: int = 2) -> dict:
    """dp across hosts x sequence shards within a host (round-4): each host
    feeds its worker rows' FULL sequences; put_batch stitches them with the
    [workers, seq] sharding — the ring-attention ppermutes stay intra-host,
    the gradient reduce crosses hosts."""
    return _lm_fingerprint(dp, n_steps, sp=sp)


def fingerprint_after_steps_sp_spc(dp: int = 2, sp: int = 2,
                                   n_steps: int = 2) -> dict:
    """Multi-host x sp x steps_per_call — the full composition: per-host
    [k, local-rows, full-seq] stacks stitched P(None, workers, seq)."""
    return _lm_fingerprint(dp, n_steps, sp=sp, steps_per_call=2)


def fingerprint_after_steps_onebit(n_workers: int = 4,
                                   n_steps: int = 2) -> dict:
    """Multi-host x compressed EF wire (round-4 coverage): the onebit
    strategy's packed sign allgather and per-worker error-feedback state
    run across REAL process boundaries; must match a single-process
    oracle."""
    return _lm_fingerprint(n_workers, n_steps, exch_strategy="onebit")
