"""scripts/bench_regress.py: the self-judging throughput gate —
per-label best-fresh baseline, stale/degraded exclusion, exit codes."""

import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

spec = importlib.util.spec_from_file_location(
    "_bench_regress", os.path.join(REPO, "scripts", "bench_regress.py"))
br = importlib.util.module_from_spec(spec)
spec.loader.exec_module(br)


def _write_bench(path, value, metric="images_per_sec_per_chip "
                 "(alexnet batch 128 BSP, 1 chip(s), tpu)", **extra):
    doc = {"parsed": dict({"value": value, "metric": metric,
                           "unit": "images/sec/chip"}, **extra)}
    with open(path, "w") as f:
        json.dump(doc, f)


def _write_jsonl(path, rows):
    with open(path, "w") as f:
        for config, result in rows:
            f.write(json.dumps({"config": config, "result": result}) + "\n")


def _baseline_dir(tmp_path):
    """A committed trajectory: two fresh BENCH readings (the best wins),
    one stale wedge-fallback carrying a HIGHER number (must be excluded
    from the bar), and a perf-matrix file with a second label."""
    _write_bench(str(tmp_path / "BENCH_r01.json"), 13000.0)
    _write_bench(str(tmp_path / "BENCH_r02.json"), 13300.0)
    _write_bench(str(tmp_path / "BENCH_r05.json"), 14162.0,
                 metric="STALE last-good (alexnet-b128) — wedged",
                 error="tunnel wedged")
    _write_jsonl(str(tmp_path / "perf_matrix_r07.jsonl"),
                 [("vgg16-easgd", {"value": 900.0}),
                  ("vgg16-easgd", {"value": 950.0, "stale": True}),
                  ("null-row", None)])
    return [str(tmp_path / "BENCH_r*.json"),
            str(tmp_path / "perf_matrix_r*.jsonl")]


def test_baseline_excludes_stale_and_keeps_best(tmp_path):
    base, stale_only = br.build_baseline(
        sorted(p for g in _baseline_dir(tmp_path)
               for p in __import__("glob").glob(g)))
    assert base["alexnet-b128"][0] == 13300.0   # not the stale 14162
    assert base["vgg16-easgd"][0] == 900.0      # not the stale 950
    # both real labels carry fresh rows, so neither is stale-ONLY (the
    # label-less wedge row falls to "default", which IS stale-only here)
    assert "alexnet-b128" not in stale_only
    assert "vgg16-easgd" not in stale_only


def test_gate_pass_regression_and_new_labels(tmp_path):
    globs = _baseline_dir(tmp_path)
    fresh = str(tmp_path / "fresh.jsonl")
    # within 10% of the 13300 bar: PASS (and a new label is informational)
    _write_jsonl(fresh, [("alexnet-b128", {"value": 12500.0}),
                         ("brand-new", {"value": 1.0})])
    args = [fresh] + [a for g in globs for a in ("--baseline", g)]
    assert br.main(args + ["--threshold", "10"]) == 0
    # >10% below: exit 3, and the verdict names the regression
    _write_jsonl(fresh, [("alexnet-b128", {"value": 11000.0})])
    out = str(tmp_path / "verdicts.json")
    assert br.main(args + ["--threshold", "10", "--json", out]) == 3
    with open(out) as f:
        verdicts = json.load(f)["verdicts"]
    assert verdicts[0]["verdict"] == "regression" \
        and verdicts[0]["baseline"] == 13300.0
    # a stale FRESH row is skipped, never judged (the wedge fallback
    # re-emission can't fail its own gate) — that's a baseline-hygiene
    # warning, not a verdict: exit 0, not 2
    _write_jsonl(fresh, [("alexnet-b128", {"value": 11000.0,
                                           "stale": True})])
    assert br.main(args + ["--threshold", "10"]) == 0
    # no overlap with the trajectory at all: exit 2 (warning, no verdict)
    _write_jsonl(fresh, [("never-seen", {"value": 5.0})])
    assert br.main(args + ["--threshold", "10"]) == 2


def test_stale_only_baseline_warns_loudly_and_passes(tmp_path, capsys):
    """A label whose every COMMITTED row is stale/degraded has no
    trustworthy bar: the gate must warn loudly and exit 0 — it must not
    judge fresh work against a wedge re-emission, in either direction."""
    _write_bench(str(tmp_path / "BENCH_r05.json"), 14162.0,
                 metric="STALE last-good (alexnet-b128) — wedged",
                 error="tunnel wedged",
                 last_good={"config": "alexnet-b128"})
    fresh = str(tmp_path / "fresh.jsonl")
    # 11000 would be a -22% regression against the stale 14162 — but
    # that bar is a wedge echo, so: warning, exit 0
    _write_jsonl(fresh, [("alexnet-b128", {"value": 11000.0})])
    args = [fresh, "--baseline", str(tmp_path / "BENCH_r*.json")]
    assert br.main(args + ["--threshold", "10"]) == 0
    err = capsys.readouterr().err
    assert "STALE-BASELINE WARNING" in err
    assert "alexnet-b128" in err


def test_r9_script_wires_the_gate():
    with open(os.path.join(REPO, "scripts", "perf_matrix_r9.sh")) as f:
        src = f.read()
    assert "bench_regress.py" in src and "exit 7" in src
