"""The defining BSP invariant (SURVEY.md §4 item b):

N-worker BSP training must equal 1-worker training on the concatenated
batch — gradients averaged across workers == gradient of the global batch.
The reference could only argue this; the simulated mesh proves it.
"""

import jax
import numpy as np
import pytest

from tests.conftest import TinyModel
from theanompi_tpu.parallel import steps
from theanompi_tpu.parallel.exchanger import BSP_Exchanger
from theanompi_tpu.parallel.mesh import worker_mesh


def _train(n_workers, per_worker_bs, n_iters=4, **cfg):
    mesh = worker_mesh(n_workers)
    config = {"mesh": mesh, "size": n_workers, "rank": 0, "verbose": False,
              "batch_size": per_worker_bs, **cfg}
    model = TinyModel(config)
    exch = BSP_Exchanger(config)
    model.compile_iter_fns(exch)
    model.data.shuffle_data(0)
    for i in range(n_iters):
        model.train_iter(i + 1, None)
        exch.exchange(None, i + 1)   # no-op in grads mode; averaging in params mode
    return jax.device_get(steps.unbox(model.step_state["params"]))


@pytest.mark.parametrize("strategy", ["allreduce", "ring"])
def test_8_workers_equal_1_worker(strategy):
    # global batch 64 either way; identical data order (common seed)
    p8 = _train(8, 8, exch_strategy=strategy)
    p1 = _train(1, 64, exch_strategy=strategy)
    flat8 = jax.tree_util.tree_leaves(p8)
    flat1 = jax.tree_util.tree_leaves(p1)
    for a, b in zip(flat8, flat1):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_bsp_replicas_stay_identical():
    mesh = worker_mesh(8)
    config = {"mesh": mesh, "size": 8, "rank": 0, "verbose": False,
              "batch_size": 8}
    model = TinyModel(config)
    model.compile_iter_fns(BSP_Exchanger(config))
    model.data.shuffle_data(0)
    for i in range(3):
        model.train_iter(i + 1, None)
    boxed = jax.device_get(model.step_state["params"])
    for leaf in jax.tree_util.tree_leaves(boxed):
        for w in range(1, 8):
            np.testing.assert_array_equal(leaf[w], leaf[0])


def test_bsp_params_mode_exact_oracle():
    """Pin params-mode semantics exactly: each worker takes a LOCAL momentum
    step on its own shard's gradient, then parameters (not velocities) are
    averaged across workers.  The oracle recomputes both steps independently
    — per-worker grads via plain ``jax.grad`` (no mesh, no exchanger), the
    momentum algebra and the average in NumPy."""
    import jax.numpy as jnp
    from tests.conftest import SyntheticData
    from theanompi_tpu.models import layers as L

    n, bs = 2, 8
    mesh = worker_mesh(n)
    config = {"mesh": mesh, "size": n, "rank": 0, "verbose": False,
              "batch_size": bs, "exch_mode": "params"}
    model = TinyModel(config)
    exch = BSP_Exchanger(config)
    model.compile_iter_fns(exch)
    model.data.shuffle_data(0)

    params0 = jax.device_get(model.params)
    oracle = [jax.tree.map(np.array, params0) for _ in range(n)]
    vel = [jax.tree.map(np.zeros_like, params0) for _ in range(n)]
    data = SyntheticData({"size": n}, batch_size=bs)
    data.shuffle_data(0)
    lr, mu = model.current_lr, model.momentum
    assert model.weight_decay == 0.0  # keeps the oracle algebra minimal

    def loss_fn(p, x, y):
        logits, _ = model.seq.apply(p, x, train=True, state={})
        return L.softmax_cross_entropy(logits, y)

    for step in range(1, 3):
        batch = data.next_train_batch(step)
        model.train_iter(step, None)
        exch.exchange(None, step)
        for w in range(n):
            xw = jnp.asarray(batch["x"][w * bs:(w + 1) * bs])
            yw = jnp.asarray(batch["y"][w * bs:(w + 1) * bs])
            g = jax.device_get(jax.grad(loss_fn)(
                jax.tree.map(jnp.asarray, oracle[w]), xw, yw))
            vel[w] = jax.tree.map(lambda v, gg: mu * v - lr * gg, vel[w], g)
            oracle[w] = jax.tree.map(lambda p, v: p + v, oracle[w], vel[w])
        avg = jax.tree.map(lambda *xs: np.mean(np.stack(xs), axis=0), *oracle)
        oracle = [jax.tree.map(np.array, avg) for _ in range(n)]

    got = jax.device_get(steps.unbox(model.step_state["params"]))
    for a, b in zip(jax.tree_util.tree_leaves(avg),
                    jax.tree_util.tree_leaves(got)):
        np.testing.assert_allclose(a, b, rtol=2e-6, atol=1e-7)


def test_bsp_params_mode_replicas_identical_after_exchange():
    """After the params-mode averaging collective, all replicas must agree —
    the invariant the reference's per-iteration allreduce maintained."""
    mesh = worker_mesh(4)
    config = {"mesh": mesh, "size": 4, "rank": 0, "verbose": False,
              "batch_size": 8, "exch_mode": "params"}
    model = TinyModel(config)
    exch = BSP_Exchanger(config)
    model.compile_iter_fns(exch)
    model.data.shuffle_data(0)
    for i in range(2):
        model.train_iter(i + 1, None)
        exch.exchange(None, i + 1)
    boxed = jax.device_get(model.step_state["params"])
    for leaf in jax.tree_util.tree_leaves(boxed):
        for w in range(1, 4):
            np.testing.assert_array_equal(leaf[w], leaf[0])


def test_steps_per_call_matches_single_step_dispatch():
    """steps_per_call=k (k full steps scanned inside one dispatch, the
    host-overhead amortizer) must produce the same params as k single-step
    dispatches — same data order, same per-step RNG folding."""
    p1 = _train(4, 8, n_iters=4)

    mesh = worker_mesh(4)
    config = {"mesh": mesh, "size": 4, "rank": 0, "verbose": False,
              "batch_size": 8, "steps_per_call": 2}
    model = TinyModel(config)
    model.compile_iter_fns(BSP_Exchanger(config))
    model.data.shuffle_data(0)
    for count in (2, 4):              # each call covers steps {c-1, c}
        model.train_iter(count, None)
    p2 = jax.device_get(steps.unbox(model.step_state["params"]))
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_steps_per_call_with_para_load_across_epochs():
    """Drop-last striding (n_batch_train // spc dispatches per epoch) with
    the prefetch loader: the per-epoch shuffle must cleanly restart the
    producer past the leftover batch — two full epochs stream with no
    deadlock and training state keeps advancing."""
    mesh = worker_mesh(4)
    config = {"mesh": mesh, "size": 4, "rank": 0, "verbose": False,
              "batch_size": 8, "n_train": 4 * 8 * 5,   # 5 batches/epoch
              "para_load": True, "steps_per_call": 2}  # 2 dispatches + 1 left
    model = TinyModel(config)
    model.compile_iter_fns(BSP_Exchanger(config))
    count = 0
    for epoch in range(2):
        model.data.shuffle_data(epoch)
        for _ in range(model.data.n_batch_train // 2):
            count += 2
            model.train_iter(count, None)
    assert count == 8
    assert np.isfinite(float(np.asarray(model.current_info["cost"])))


def test_steps_per_call_accepts_every_rule():
    """Multi-step dispatch is no longer BSP-grads-only: rules with a
    post-step collective get their cadence fused INTO the scanned step
    (ISSUE 1 tentpole) — compile_iter_fns accepts them and flags the
    exchanger so the Python hook knows to stand down."""
    from theanompi_tpu.parallel.exchanger import (ASGD_Exchanger,
                                                  BSP_Exchanger,
                                                  EASGD_Exchanger,
                                                  GOSGD_Exchanger)
    mesh = worker_mesh(4)
    for cls, cfg in ((EASGD_Exchanger, {}), (ASGD_Exchanger, {}),
                     (GOSGD_Exchanger, {}),
                     (BSP_Exchanger, {"exch_mode": "params"})):
        config = {"mesh": mesh, "size": 4, "rank": 0, "verbose": False,
                  "batch_size": 8, "steps_per_call": 2, **cfg}
        model = TinyModel(config)
        exch = cls(config)
        model.compile_iter_fns(exch)          # must not raise
        assert exch.fused, cls.__name__
    # BSP grads mode has no post-step hook — nothing to fuse, flag stays off
    config = {"mesh": mesh, "size": 4, "rank": 0, "verbose": False,
              "batch_size": 8, "steps_per_call": 2}
    model = TinyModel(config)
    exch = BSP_Exchanger(config)
    model.compile_iter_fns(exch)
    assert not exch.fused


def test_training_reduces_loss():
    mesh = worker_mesh(8)
    config = {"mesh": mesh, "size": 8, "rank": 0, "verbose": False,
              "batch_size": 8, "sync_each_iter": True}
    model = TinyModel(config)
    model.compile_iter_fns(BSP_Exchanger(config))
    model.data.shuffle_data(0)
    costs = []
    for i in range(8):
        model.train_iter(i + 1, None)
        costs.append(float(model.current_info["cost"]))
    assert costs[-1] < costs[0], costs


def test_n_subb_grad_accumulation_equivalent():
    """n_subb microbatching (the reference's sub-batch machinery, §3.4) must
    not change the update for a mean-loss model."""
    p1 = _train(4, 8, n_subb=1)
    p2 = _train(4, 8, n_subb=2)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)
