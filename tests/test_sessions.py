"""Session-API convergence smokes (SURVEY.md §4 item c + §2.6).

The reference was exercised through user session scripts calling the 3-call
rule API (``BSP().init(devices); rule.wait()``) on ``Cifar10_model`` for a
few epochs.  These tests drive exactly that surface — launcher → worker loop
→ model contract → exchanger — end-to-end on the simulated 8-device mesh,
and assert the training cost actually falls (the reference only eyeballed
curves)."""

import numpy as np
import pytest

import theanompi_tpu as tmpi

COMMON = dict(
    modelfile="theanompi_tpu.models.cifar10",
    modelclass="Cifar10_model",
    epochs=2,
    synthetic_train=192,
    synthetic_val=64,
    batch_size=8,
    printFreq=1,
    compute_dtype="float32",
    learning_rate=0.005,
    scale_lr=False,
    verbose=False,
)


@pytest.mark.parametrize("rule_cls, extra", [
    (tmpi.BSP, {}),
    (tmpi.EASGD, {"sync_freq": 2}),
    # downpour sums worker deltas into the center (an effective size× step),
    # so the smoke needs plain SGD at a cooler lr to descend
    (tmpi.ASGD, {"sync_freq": 2, "learning_rate": 0.005,
                 "optimizer": "sgd"}),
    (tmpi.GOSGD, {"exch_prob": 0.8}),
])
def test_cifar10_session_cost_falls(rule_cls, extra):
    rule = rule_cls()
    rule.init(devices=4, **{**COMMON, **extra})
    rec = rule.wait()
    costs = [r["cost"] for r in rec._all_records]
    assert len(costs) >= 8          # 12 iters at printFreq=1
    # tiny noisy batches: compare window means, not endpoints
    assert np.mean(costs[-4:]) < np.mean(costs[:4]), costs
    assert np.isfinite(rec.epoch_records[-1]["val_cost"])


def test_session_devices_overcommit_raises():
    rule = tmpi.BSP()
    rule.init(devices=4096, **COMMON)
    with pytest.raises(ValueError, match="devices"):
        rule.wait()


def test_reference_import_alias_runs_a_session():
    """A reference-style session script — ``from theanompi import BSP`` with
    a ``theanompi.models.*`` modelfile string — must run unmodified."""
    from theanompi import BSP as RefBSP

    rule = RefBSP()
    rule.init(devices=2, modelfile="theanompi.models.cifar10",
              modelclass="Cifar10_model", epochs=1, synthetic_train=64,
              synthetic_val=32, batch_size=8, compute_dtype="float32",
              verbose=False, scale_lr=False)
    rec = rule.wait()
    assert np.isfinite(rec.epoch_records[-1]["val_cost"])


def test_warmup_ramps_scaled_lr():
    """warmup_epochs linearly ramps the scale_lr factor; default (0) keeps
    the reference's instant linear scaling."""
    from tests.conftest import TinyModel

    m = TinyModel({"verbose": False, "n_workers": 1, "warmup_epochs": 4,
                   "learning_rate": 0.01})
    m.scale_lr(8)
    ramp = []
    for e in range(5):
        m.adjust_hyperp(e)
        ramp.append(round(m.current_lr, 4))
    assert ramp == [0.0275, 0.045, 0.0625, 0.08, 0.08], ramp

    m2 = TinyModel({"verbose": False, "n_workers": 1, "learning_rate": 0.01})
    m2.scale_lr(8)
    m2.adjust_hyperp(0)
    assert abs(m2.current_lr - 0.08) < 1e-9


def test_prng_impl_config_applies():
    import jax
    from theanompi_tpu.base import MeshProcess

    old = jax.config.jax_default_prng_impl
    try:
        p = MeshProcess({"prng_impl": "rbg", "verbose": False})
        p.get_internode_comm()
        assert jax.config.jax_default_prng_impl == "rbg"
    finally:
        jax.config.update("jax_default_prng_impl", old)

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
