#!/usr/bin/env python
"""Standalone child for the executable-cache round-trip test.

A FRESH process per invocation — the cache's whole claim is surviving
process death, so the test must cross a process boundary (same pattern as
``twoproc_helper.py``).  Builds the conftest TinyModel shape (defined
inline: conftest is pytest-session state), compiles through
``compile_iter_fns`` with the given cache dir, runs a few deterministic
train dispatches + one val pass, and dumps outputs + compile metadata for
the parent to compare bit-for-bit across cold (fresh XLA compile) and warm
(deserialize) runs.

    python tests/_compile_cache_child.py <cache_dir|off> <out.npz> <rule> <spc>
"""

import json
import os
import sys
import time

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax                                              # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp                                 # noqa: E402
import numpy as np                                      # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from theanompi_tpu.models import layers as L            # noqa: E402
from theanompi_tpu.models.data import DataBase          # noqa: E402
from theanompi_tpu.models.model_base import ModelBase   # noqa: E402
from theanompi_tpu.parallel.exchanger import get_exchanger  # noqa: E402
from theanompi_tpu.utils import helper_funcs            # noqa: E402


class ChildData(DataBase):
    DIM = 16

    def __init__(self, config=None, batch_size=8):
        super().__init__(config, batch_size)
        rng = np.random.RandomState(7)
        w = rng.randn(self.DIM)

        def make(n, seed):
            r = np.random.RandomState(seed)
            x = r.randn(n, self.DIM).astype(np.float32)
            return x, (x @ w > 0).astype(np.int32)

        self.x_train, self.y_train = make(256, 11)
        self.x_val, self.y_val = make(64, 22)
        self._finalize()


class ChildModel(ModelBase):
    batch_size = 8
    epochs = 1
    learning_rate = 0.05
    momentum = 0.9
    weight_decay = 0.0
    seed = 3

    def build_model(self):
        dim = ChildData.DIM
        self.seq = L.Sequential([
            L.FC(dim, 32, w_init="he", name="fc1"),
            L.FC(32, 2, w_init=("normal", 0.01), activation=None,
                 name="out"),
        ])
        self.data = ChildData(self.config, self.batch_size)


def main() -> int:
    cache_dir, out_path, rule, spc = sys.argv[1:5]
    spc = int(spc)
    config = {"verbose": False, "steps_per_call": spc,
              "compile_cache": "" if cache_dir == "off" else cache_dir}
    model = ChildModel(config)
    exchanger = get_exchanger(rule, model.config)
    t0 = time.time()
    model.compile_iter_fns(exchanger)
    compile_wall = time.time() - t0

    model.data.shuffle_data(0)
    costs = []
    count = 0
    for _ in range(3):
        count += spc
        model.train_iter(count)
        if not getattr(exchanger, "fused", False):
            exchanger.exchange(None, count)
        costs.append(float(model.current_info["cost"]))
    model.begin_val()
    model.val_iter(count)
    model.end_val()
    params = model.canonical_host_params()
    flat = np.concatenate([np.asarray(x, np.float32).ravel()
                           for x in jax.tree_util.tree_leaves(params)])
    np.savez(out_path, params=flat, costs=np.asarray(costs, np.float64),
             compile_wall=compile_wall,
             info=json.dumps(model.compile_info, default=str))
    print(json.dumps({"train_cache": model.compile_info["train"]["cache"],
                      "compile_wall": round(compile_wall, 3),
                      "compile_secs":
                      model.compile_info["total_compile_secs"]}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
