"""tpulint suite: paired good/bad fixtures per checker + repo smoke.

Contract (ISSUE 5 / docs/design.md §12): every checker has a failing
fixture producing EXACTLY its expected finding and a passing fixture
producing zero; the whole-repo run matches the committed baseline
exactly (no stale entries, no new findings); the CLI enforces the gate
semantics tier1.sh relies on — and does it all without importing jax.
"""

import json
import os
import subprocess
import sys

import pytest

from theanompi_tpu.analysis import core
from theanompi_tpu.analysis.checkers import schema_drift as sd

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(REPO, "scripts", "lint.py")


def lint_snippet(tmp_path, name, code, only):
    (tmp_path / name).write_text(code)
    return core.run_lint(str(tmp_path), paths=[name], only=[only])


# ---------------------------------------------------------------------------
# trace-purity
# ---------------------------------------------------------------------------

TRACE_BAD = """
import time
import numpy as np
import jax
from jax import lax

def build(model):
    def body(carry, x):
        t = time.time()
        carry = carry + np.random.rand()
        print("mid-trace")
        if carry:
            carry = carry + x.item()
        return carry, jax.device_get(x)
    return lax.scan(body, 0.0, model)
"""

TRACE_GOOD = """
import time
import numpy as np
import jax
from jax import lax

def host_loop(model):
    # host side: clocks / numpy RNG / print are all fine here
    t = time.time()
    noise = np.random.rand()
    print("host", t)

    def body(carry, x):
        return carry + x, x
    out, _ = lax.scan(body, noise, model)
    return out, time.time() - t
"""


def test_trace_purity_bad_fixture(tmp_path):
    found = lint_snippet(tmp_path, "bad.py", TRACE_BAD, "trace-purity")
    msgs = [f.message for f in found]
    assert len(found) == 6, msgs
    assert any("time.time" in m for m in msgs)
    assert any("numpy.random" in m for m in msgs)
    assert any("print" in m for m in msgs)
    assert any("tracer-typed name `carry`" in m for m in msgs)
    assert any(".item()" in m for m in msgs)
    assert any("jax.device_get" in m for m in msgs)
    assert all(f.check == "trace-purity" for f in found)


def test_trace_purity_good_fixture(tmp_path):
    assert lint_snippet(tmp_path, "good.py", TRACE_GOOD,
                        "trace-purity") == []


def test_trace_purity_keyword_passed_body(tmp_path):
    """A scan body passed by keyword (`lax.scan(f=body, ...)`) is traced
    all the same."""
    code = (
        "import time\n"
        "from jax import lax\n"
        "def build(xs):\n"
        "    def body(carry, x):\n"
        "        t = time.time()\n"
        "        return carry, x\n"
        "    return lax.scan(f=body, init=0.0, xs=xs)\n")
    found = lint_snippet(tmp_path, "x.py", code, "trace-purity")
    assert len(found) == 1 and "time.time" in found[0].message


def test_trace_purity_decorator_jit(tmp_path):
    """@jax.jit / @functools.partial(jax.jit, ...) trace the decorated
    function — the repo's pallas kernels use exactly this shape."""
    code = (
        "import functools\n"
        "import time\n"
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    t = time.time()\n"
        "    return x\n"
        "@functools.partial(jax.jit, static_argnums=(1,))\n"
        "def g(x, n):\n"
        "    print(n)\n"
        "    return x\n")
    found = lint_snippet(tmp_path, "x.py", code, "trace-purity")
    msgs = [f.message for f in found]
    assert len(found) == 2, msgs
    assert any("time.time" in m for m in msgs)
    assert any("print" in m for m in msgs)


def test_trace_purity_catches_injection_into_real_steps(tmp_path):
    """The acceptance scenario: a time.time() injected into the repo's
    actual microbatch scan body must fail the gate."""
    src = open(os.path.join(REPO, "theanompi_tpu", "parallel",
                            "steps.py")).read()
    bad = src.replace(
        "    def body(carry, mb):\n"
        "        acc_g, acc_c, acc_e, bn, key = carry",
        "    def body(carry, mb):\n"
        "        t0 = time.time()\n"
        "        acc_g, acc_c, acc_e, bn, key = carry").replace(
        "import functools", "import functools\nimport time")
    assert bad != src, "steps.py scan body changed shape; update fixture"
    # keep the repo-relative package shape so the resolver sees the
    # same relative imports steps.py really uses
    pkg = tmp_path / "theanompi_tpu" / "parallel"
    pkg.mkdir(parents=True)
    (pkg / "steps.py").write_text(bad)
    found = core.run_lint(str(tmp_path),
                          paths=["theanompi_tpu/parallel/steps.py"],
                          only=["trace-purity"])
    assert len(found) == 1 and "time.time" in found[0].message


# ---------------------------------------------------------------------------
# rng-discipline
# ---------------------------------------------------------------------------

RNG_BAD = """
import jax

def draw(key):
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))
    return a + b
"""

RNG_GOOD = """
import jax

def draw(key, count):
    key, sub = jax.random.split(key)
    a = jax.random.normal(sub, (4,))
    # fold_in with distinct data is the sanctioned multi-stream pattern
    b = jax.random.uniform(jax.random.fold_in(key, 1), (4,))
    c = jax.random.normal(jax.random.fold_in(key, 2), (4,))
    for i in range(3):
        step = jax.random.fold_in(key, count + i)
        a = a + jax.random.normal(step, (4,))
    return a + b + c
"""

RNG_BAD_LOOP = """
import jax

def draw(key, n):
    out = 0.0
    for i in range(n):
        out = out + jax.random.normal(key, ())
    return out
"""


def test_rng_discipline_bad_fixture(tmp_path):
    found = lint_snippet(tmp_path, "bad.py", RNG_BAD, "rng-discipline")
    assert len(found) == 1
    assert "key `key` consumed again" in found[0].message
    assert found[0].check == "rng-discipline"


def test_rng_discipline_loop_fixture(tmp_path):
    found = lint_snippet(tmp_path, "badloop.py", RNG_BAD_LOOP,
                         "rng-discipline")
    assert len(found) == 1
    assert "inside a loop" in found[0].message


def test_rng_discipline_good_fixture(tmp_path):
    assert lint_snippet(tmp_path, "good.py", RNG_GOOD,
                        "rng-discipline") == []


def test_rng_discipline_exclusive_arms_are_not_reuse(tmp_path):
    """Only one arm of a conditional expression (or a short-circuit
    chain) ever runs — a draw in each is not key reuse."""
    code = (
        "import jax\n"
        "def draw(key, c, d):\n"
        "    a = jax.random.normal(key) if c else jax.random.uniform(key)\n"
        "    b = d or jax.random.normal(key)\n"
        "    return a, b\n")
    # NOTE: `key` genuinely IS consumed on both lines 3 and 4 here —
    # but each consumption is inside an exclusive/conditional position,
    # so neither pairing is provably reached twice
    assert lint_snippet(tmp_path, "x.py", code, "rng-discipline") == []


def test_rng_discipline_nested_def_in_loop_is_own_scope(tmp_path):
    """A helper defined inside a loop gets fresh key parameters per
    call — its draws are not 'consumed inside a loop'."""
    code = (
        "import jax\n"
        "def outer(n):\n"
        "    fns = []\n"
        "    for i in range(n):\n"
        "        if i:\n"
        "            def inner(k2):\n"
        "                return jax.random.normal(k2)\n"
        "            fns.append(inner)\n"
        "    return fns\n")
    assert lint_snippet(tmp_path, "x.py", code, "rng-discipline") == []


def test_rng_discipline_both_arms_then_reuse_is_flagged(tmp_path):
    """A key consumed in BOTH arms of a conditional IS definitely
    consumed — a later unconditional draw is reuse."""
    code = (
        "import jax\n"
        "def draw(key, c):\n"
        "    a = jax.random.normal(key) if c else jax.random.uniform(key)\n"
        "    b = jax.random.normal(key)\n"
        "    return a, b\n")
    found = lint_snippet(tmp_path, "x.py", code, "rng-discipline")
    assert len(found) == 1 and found[0].line == 4


# ---------------------------------------------------------------------------
# donation-safety
# ---------------------------------------------------------------------------

DONATION_BAD = """
import jax

def run(state, batch):
    step = jax.jit(lambda s, b: s, donate_argnums=(0,))
    new_state = step(state, batch)
    return new_state, state["params"]
"""

DONATION_GOOD = """
import jax

def run(state, batch):
    step = jax.jit(lambda s, b: s, donate_argnums=(0,))
    # the sanctioned shape: consume and rebind in one statement
    state = step(state, batch)
    return state, state["params"]
"""


def test_donation_safety_bad_fixture(tmp_path):
    found = lint_snippet(tmp_path, "bad.py", DONATION_BAD,
                         "donation-safety")
    assert len(found) == 1
    assert "`state` read after being donated" in found[0].message


def test_donation_safety_good_fixture(tmp_path):
    assert lint_snippet(tmp_path, "good.py", DONATION_GOOD,
                        "donation-safety") == []


def test_donation_safety_argnames_maps_through_lambda(tmp_path):
    """donate_argnames against an inline lambda maps names to slots —
    the donated arg is flagged, the non-donated one is not."""
    code = (
        "import jax\n"
        "def run(state, batch):\n"
        "    step = jax.jit(lambda b, s: s, donate_argnames='s')\n"
        "    out = step(batch, state)\n"
        "    return out, batch.shape, state['params']\n")
    found = lint_snippet(tmp_path, "x.py", code, "donation-safety")
    assert len(found) == 1
    assert "`state` read after being donated" in found[0].message


def test_donation_safety_module_level_jit_seen_in_functions(tmp_path):
    """`f = jax.jit(g, donate_argnums=0)` at module level, called inside
    a function — the common layout — must still flag read-after-donate."""
    code = (
        "import jax\n"
        "def g(s):\n"
        "    return s\n"
        "f = jax.jit(g, donate_argnums=0)\n"
        "def h(state):\n"
        "    out = f(state)\n"
        "    return out, state['params']\n")
    found = lint_snippet(tmp_path, "x.py", code, "donation-safety")
    assert len(found) == 1
    assert "`state` read after being donated" in found[0].message


def test_donation_safety_unresolvable_spec_is_skipped(tmp_path):
    """A donation spec the checker cannot resolve statically (argnames
    against an opaque callee, non-literal argnums) must not guess an
    index — guessing flags the WRONG argument."""
    code = (
        "import jax\n"
        "def run(f, state, batch, idx):\n"
        "    step = jax.jit(f, donate_argnames='s')\n"
        "    step2 = jax.jit(f, donate_argnums=idx)\n"
        "    out = step(batch, state)\n"
        "    out2 = step2(batch, state)\n"
        "    return out, out2, batch.shape\n")
    assert lint_snippet(tmp_path, "x.py", code, "donation-safety") == []


# ---------------------------------------------------------------------------
# shard-rebuild-dominance
# ---------------------------------------------------------------------------

USHARD_BAD = """
from theanompi_tpu.parallel.update_sharding import slice_chunk

def step(flat, rank, chunk, lr, grads):
    my_p = slice_chunk(flat, rank, chunk)
    new_p = my_p - lr * grads
    return new_p
"""

USHARD_GOOD = """
from theanompi_tpu.parallel.update_sharding import (all_gather_chunks,
                                                    slice_chunk)

def step(flat, rank, chunk, lr, grads):
    my_p = slice_chunk(flat, rank, chunk)
    new_p = my_p - lr * grads
    full = all_gather_chunks(new_p, "workers")
    return full
"""

USHARD_BRANCH_BAD = """
from theanompi_tpu.parallel.update_sharding import (all_gather_chunks,
                                                    slice_chunk)

def step(flat, rank, chunk, gather):
    my_p = slice_chunk(flat, rank, chunk)
    if gather:
        my_p = all_gather_chunks(my_p, "workers")
    return my_p
"""

USHARD_EXEMPT_GOOD = """
from theanompi_tpu.parallel.update_sharding import shard_tree

def reshard_extra(extra, plan, rank):
    # a named producer helper: returning chunks is its JOB
    return shard_tree(extra, plan, rank)
"""


def test_shard_rebuild_bad_fixture(tmp_path):
    """A chunk laundered through arithmetic and returned without its
    rebuild: under donate_argnums the caller's full buffer silently
    becomes a 1/N local shard."""
    found = lint_snippet(tmp_path, "bad.py", USHARD_BAD,
                         "shard-rebuild-dominance")
    assert len(found) == 1
    assert "`new_p` holds a worker-local shard" in found[0].message
    assert "allgather rebuild" in found[0].message


def test_shard_rebuild_good_fixture(tmp_path):
    assert lint_snippet(tmp_path, "good.py", USHARD_GOOD,
                        "shard-rebuild-dominance") == []


def test_shard_rebuild_branch_does_not_dominate(tmp_path):
    """A rebuild INSIDE one arm of an `if` does not dominate the return
    — the no-gather path still escapes the shard."""
    found = lint_snippet(tmp_path, "x.py", USHARD_BRANCH_BAD,
                         "shard-rebuild-dominance")
    assert len(found) == 1
    assert "`my_p`" in found[0].message


def test_shard_rebuild_exempts_named_producers(tmp_path):
    """The schema's own producer helpers (shard_*/reshard_*/slice_*/
    chunk_*) return chunks by design — never flagged."""
    assert lint_snippet(tmp_path, "x.py", USHARD_EXEMPT_GOOD,
                        "shard-rebuild-dominance") == []


# ---------------------------------------------------------------------------
# compat-boundary
# ---------------------------------------------------------------------------

COMPAT_BAD = """
import jax
from jax import lax
from jax.experimental.shard_map import shard_map

def build(f, mesh):
    g = jax.shard_map
    h = lax.pvary
    return shard_map, g, h
"""

COMPAT_GOOD = """
import jax
from theanompi_tpu.jax_compat import shard_map

def build(f, mesh, specs):
    return jax.jit(shard_map(f, mesh=mesh, in_specs=specs,
                             out_specs=specs))
"""


def test_compat_boundary_bad_fixture(tmp_path):
    found = lint_snippet(tmp_path, "bad.py", COMPAT_BAD, "compat-boundary")
    msgs = [f.message for f in found]
    assert len(found) == 3, msgs
    assert any("jax.experimental.shard_map" in m for m in msgs)
    assert any("jax.shard_map" in m for m in msgs)
    assert any("jax.lax.pvary" in m for m in msgs)


def test_compat_boundary_good_fixture(tmp_path):
    assert lint_snippet(tmp_path, "good.py", COMPAT_GOOD,
                        "compat-boundary") == []


def test_compat_boundary_exempts_the_shim(tmp_path):
    found = lint_snippet(tmp_path, "jax_compat.py", COMPAT_BAD,
                         "compat-boundary")
    assert found == []


def test_compat_boundary_catches_from_import_of_banned_name(tmp_path):
    """`from jax import shard_map` binds the banned name with no
    Attribute node — the import itself must be the finding."""
    code = ("from jax import shard_map\n"
            "from jax.lax import pvary\n"
            "from jax import lax\n")        # `lax` itself is fine
    found = lint_snippet(tmp_path, "x.py", code, "compat-boundary")
    msgs = [f.message for f in found]
    assert len(found) == 2, msgs
    assert any("jax.shard_map" in m for m in msgs)
    assert any("jax.lax.pvary" in m for m in msgs)


# ---------------------------------------------------------------------------
# telemetry-hot-path
# ---------------------------------------------------------------------------

TELEMETRY_BAD = """
from theanompi_tpu.utils import telemetry

def hot_loop(n):
    tm = telemetry.active()
    for i in range(n):
        tm.counter("iters")
"""

TELEMETRY_GOOD = """
from theanompi_tpu.utils import telemetry

def hot_loop(n, rec=None):
    tm = telemetry.active()
    for i in range(n):
        if tm.enabled:
            tm.counter("iters")
        if rec and tm.enabled:
            tm.observe("loop.i", i)
"""


def test_telemetry_hot_path_bad_fixture(tmp_path):
    # checker keys on hot-path basenames — name the fixture worker.py
    found = lint_snippet(tmp_path, "worker.py", TELEMETRY_BAD,
                         "telemetry-hot-path")
    assert len(found) == 1
    assert "unguarded telemetry call `tm.counter" in found[0].message


def test_telemetry_hot_path_good_fixture(tmp_path):
    assert lint_snippet(tmp_path, "worker.py", TELEMETRY_GOOD,
                        "telemetry-hot-path") == []


TRACING_BAD = """
from theanompi_tpu.utils import tracing

def island_loop(n, center):
    tr = tracing.active()
    for i in range(n):
        rnd = tr.begin("round", count=i)
        rnd.end()
"""

TRACING_GOOD = """
from theanompi_tpu.utils import tracing

def island_loop(n, center):
    tr = tracing.active()
    for i in range(n):
        rnd = tr.begin("round", count=i) if tr.enabled else None
        if rnd is not None:
            rnd.end()
"""

EMIT_BAD = """
from theanompi_tpu.utils import telemetry, tracing

def request(trace, op):
    tm = telemetry.active()
    tracing.emit_wire_span(tm, trace, op, dt=0.1)
"""

EMIT_GOOD = """
from theanompi_tpu.utils import telemetry, tracing

def request(trace, op):
    tm = telemetry.active()
    if trace is not None and tm.enabled:
        tracing.emit_wire_span(tm, trace, op, dt=0.1)
"""


def test_span_emission_unguarded_begin_is_a_finding(tmp_path):
    """Round 16: the span API is part of the hot-path contract — an
    unguarded `Tracer.begin` in a hot file (async_easgd joined the set)
    is a finding; the `... if tr.enabled else None` idiom is the guard."""
    found = lint_snippet(tmp_path, "async_easgd.py", TRACING_BAD,
                         "telemetry-hot-path")
    assert len(found) == 1
    assert "tr.begin" in found[0].message


def test_span_emission_guarded_begin_is_clean(tmp_path):
    assert lint_snippet(tmp_path, "async_easgd.py", TRACING_GOOD,
                        "telemetry-hot-path") == []


def test_module_level_emit_helpers_are_recording_calls(tmp_path):
    """`tracing.emit_wire_span(...)` resolves to the tracing module —
    unguarded in wire.py (now a hot file) it is a finding; the
    `trace is not None and tm.enabled` conjunction guards."""
    found = lint_snippet(tmp_path, "wire.py", EMIT_BAD,
                         "telemetry-hot-path")
    assert len(found) == 1
    assert "emit_wire_span" in found[0].message
    assert lint_snippet(tmp_path, "wire.py", EMIT_GOOD,
                        "telemetry-hot-path") == []


FLEETMON_BAD = """
from theanompi_tpu.utils import fleetmon, telemetry

def eval_loop(alerts):
    tm = telemetry.active()
    for a in alerts:
        fleetmon.emit_alert(tm, a)
"""

FLEETMON_GOOD = """
from theanompi_tpu.utils import fleetmon, telemetry

def eval_loop(alerts):
    tm = telemetry.active()
    for a in alerts:
        if tm.enabled:
            fleetmon.emit_alert(tm, a)
"""


def test_fleetmon_emission_api_is_a_recording_call(tmp_path):
    """Round 18: the checker knows the fleet-health emission API —
    `fleetmon.emit_alert(...)` unguarded in a hot file (fleetmon.py
    itself joined the set) is a finding; the enabled guard clears it."""
    found = lint_snippet(tmp_path, "fleetmon.py", FLEETMON_BAD,
                         "telemetry-hot-path")
    assert len(found) == 1
    assert "emit_alert" in found[0].message
    assert lint_snippet(tmp_path, "fleetmon.py", FLEETMON_GOOD,
                        "telemetry-hot-path") == []


def test_telemetry_hot_path_only_applies_to_hot_files(tmp_path):
    # the same unguarded call in a non-hot-path file is NOT a finding
    assert lint_snippet(tmp_path, "report_tool.py", TELEMETRY_BAD,
                        "telemetry-hot-path") == []


def test_telemetry_hot_path_early_return_guard(tmp_path):
    """`if not tm.enabled: return` dominates the rest of the block —
    the most common Python guard shape must not be flagged."""
    code = (
        "from theanompi_tpu.utils import telemetry\n"
        "def hot_loop(n):\n"
        "    tm = telemetry.active()\n"
        "    if not tm.enabled:\n"
        "        return\n"
        "    tm.counter('iters')\n"
        "    tm.observe('n', n)\n")
    assert lint_snippet(tmp_path, "worker.py", code,
                        "telemetry-hot-path") == []


def test_telemetry_hot_path_elif_guard(tmp_path):
    """An `elif tm.enabled:` arm guards its own body."""
    code = (
        "from theanompi_tpu.utils import telemetry\n"
        "def hot_loop(rec):\n"
        "    tm = telemetry.active()\n"
        "    if rec:\n"
        "        pass\n"
        "    elif tm.enabled:\n"
        "        tm.counter('iters')\n")
    assert lint_snippet(tmp_path, "worker.py", code,
                        "telemetry-hot-path") == []


def test_telemetry_hot_path_or_guard_is_not_dominance(tmp_path):
    """`if other or tm.enabled:` reaches its body with telemetry off —
    mentioning `.enabled` somewhere is not domination."""
    code = (
        "from theanompi_tpu.utils import telemetry\n"
        "def hot_loop(other):\n"
        "    tm = telemetry.active()\n"
        "    if other or tm.enabled:\n"
        "        tm.counter('iters')\n"
        "    if tm.enabled or other.enabled:\n"
        "        tm.gauge('x', 1)\n")    # every alternative guards: ok
    found = lint_snippet(tmp_path, "worker.py", code,
                         "telemetry-hot-path")
    assert len(found) == 1 and "tm.counter" in found[0].message


def test_telemetry_hot_path_early_return_without_exit_still_flags(tmp_path):
    """A negated-enabled If whose body does NOT end control flow must
    not guard what follows."""
    code = (
        "from theanompi_tpu.utils import telemetry\n"
        "def hot_loop(n):\n"
        "    tm = telemetry.active()\n"
        "    if not tm.enabled:\n"
        "        n = 0\n"
        "    tm.counter('iters')\n")
    found = lint_snippet(tmp_path, "worker.py", code,
                         "telemetry-hot-path")
    assert len(found) == 1


# ---------------------------------------------------------------------------
# schema-drift
# ---------------------------------------------------------------------------

def test_schema_drift_good_live_modules():
    """The real modules must be in sync (this IS the absorbed guard)."""
    from theanompi_tpu.utils import recorder, telemetry
    assert sd.live_drift_errors(recorder, telemetry) == []


def test_schema_drift_bad_fixture(monkeypatch):
    """A drifted SECTIONS list must produce a finding."""
    from theanompi_tpu.utils import recorder, telemetry

    class FakeRecorder:
        SECTIONS = tuple(telemetry.PHASES) + ("rogue",)
        RECORD_KEYS = recorder.RECORD_KEYS
        Recorder = recorder.Recorder

    errors = sd.live_drift_errors(FakeRecorder, telemetry)
    assert any("SECTIONS" in msg for _, msg in errors)


def test_schema_drift_tracing_probe_good_and_bad():
    """Round 16: the live tracing probe — real modules in sync; a report
    stand-in that cannot assemble spans (or tracks the wrong vocabulary)
    fails the gate; a span emitter drifting from SPAN_FIELDS fails."""
    import importlib.util

    from theanompi_tpu.utils import telemetry, tracing
    report_path = os.path.join(REPO, "scripts", "telemetry_report.py")
    spec = importlib.util.spec_from_file_location("_sd_test_report",
                                                  report_path)
    report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(report)

    assert sd.tracing_schema_errors(tracing, telemetry, report) == []

    class BlindReport:
        # tracks neither span nor statusz, assembles nothing
        TRACKED_EVENTS = ("phase",)
        TRACE_COMPONENTS = ()

        @staticmethod
        def assemble_traces(events):
            return []

    errors = sd.tracing_schema_errors(tracing, telemetry, BlindReport)
    msgs = [m for _, m in errors]
    assert any("TRACKED_EVENTS" in m for m in msgs)
    assert any("round(s)" in m for m in msgs), msgs
    assert any("TRACE_COMPONENTS" in m for m in msgs)


# ---------------------------------------------------------------------------
# framework behaviors: suppression, baseline, runner
# ---------------------------------------------------------------------------

def test_inline_suppression(tmp_path):
    code = RNG_BAD.replace(
        "    b = jax.random.uniform(key, (4,))",
        "    b = jax.random.uniform(key, (4,))"
        "  # tpulint: disable=rng-discipline")
    assert lint_snippet(tmp_path, "bad.py", code, "rng-discipline") == []


def test_previous_line_suppression(tmp_path):
    code = RNG_BAD.replace(
        "    b = jax.random.uniform(key, (4,))",
        "    # tpulint: disable=rng-discipline\n"
        "    b = jax.random.uniform(key, (4,))")
    assert lint_snippet(tmp_path, "bad.py", code, "rng-discipline") == []


def test_suppression_is_check_specific(tmp_path):
    code = RNG_BAD.replace(
        "    b = jax.random.uniform(key, (4,))",
        "    b = jax.random.uniform(key, (4,))"
        "  # tpulint: disable=trace-purity")
    assert len(lint_snippet(tmp_path, "bad.py", code,
                            "rng-discipline")) == 1


def test_baseline_roundtrip_deterministic(tmp_path):
    (tmp_path / "bad.py").write_text(RNG_BAD)
    findings = core.run_lint(str(tmp_path), paths=["bad.py"],
                             only=["rng-discipline"])
    bl = tmp_path / "baseline.json"
    core.save_baseline(str(bl), findings)
    first = bl.read_text()
    entries = core.load_baseline(str(bl))
    assert entries[0]["justification"] == "TODO: justify"
    # justification edits survive a regeneration; output is byte-stable
    entries[0]["justification"] = "grandfathered: fixture"
    core.save_baseline(str(bl), findings, entries)
    again = core.load_baseline(str(bl))
    assert again[0]["justification"] == "grandfathered: fixture"
    core.save_baseline(str(bl), findings, again)
    assert json.loads(bl.read_text())["entries"] == again
    assert bl.read_text() != first  # only the justification changed
    new, matched, stale = core.compare_baseline(findings, again)
    assert new == [] and stale == [] and len(matched) == 1


def test_baseline_matches_on_message_not_line(tmp_path):
    (tmp_path / "bad.py").write_text(RNG_BAD)
    findings = core.run_lint(str(tmp_path), paths=["bad.py"],
                             only=["rng-discipline"])
    moved = [dict(check=f.check, path=f.path, line=f.line + 40,
                  message=f.message, justification="ok")
             for f in findings]
    new, matched, stale = core.compare_baseline(findings, moved)
    assert new == [] and stale == []


def test_parse_error_is_a_finding(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    found = core.run_lint(str(tmp_path), paths=["broken.py"],
                          only=["rng-discipline"])
    assert len(found) == 1 and found[0].check == "parse-error"


# ---------------------------------------------------------------------------
# whole-repo smoke + CLI gate semantics
# ---------------------------------------------------------------------------

def test_repo_matches_committed_baseline_exactly():
    """The committed baseline is exact: no new findings, no stale
    entries, and every entry carries a real justification."""
    findings = core.run_lint(REPO)
    entries = core.load_baseline(
        os.path.join(REPO, core.BASELINE_NAME))
    new, matched, stale = core.compare_baseline(findings, entries)
    assert new == [], [f.render() for f in new]
    assert stale == [], stale
    assert all(not e["justification"].startswith("TODO")
               for e in entries), "baseline entries need justifications"


def test_cli_runs_clean_without_jax():
    """scripts/lint.py on the repo: exit 0, and jax must never load
    (the synthetic-parent bootstrap contract)."""
    env = dict(os.environ, TPULINT_ASSERT_NO_JAX="1")
    proc = subprocess.run(
        [sys.executable, LINT, "--check-baseline"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_fails_on_new_finding(tmp_path):
    (tmp_path / "steps.py").write_text(TRACE_BAD)
    proc = subprocess.run(
        [sys.executable, LINT, "--root", str(tmp_path), "steps.py"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "trace-purity" in proc.stdout


def test_cli_check_baseline_fails_on_stale_entry(tmp_path):
    (tmp_path / "clean.py").write_text("x = 1\n")
    bl = tmp_path / core.BASELINE_NAME
    bl.write_text(json.dumps({"version": 1, "entries": [{
        "check": "rng-discipline", "path": "gone.py", "line": 1,
        "message": "key `k` consumed again", "justification": "stale"}]}))
    base = [sys.executable, LINT, "--root", str(tmp_path)]
    # full-repo default mode: stale entry is a warning, not a failure
    proc = subprocess.run(base, capture_output=True, text=True,
                          timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "stale" in proc.stderr
    # tier-1 mode: the committed baseline must be exact
    proc = subprocess.run(base + ["--check-baseline"],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1


def test_cli_rejects_nonexistent_explicit_path(tmp_path):
    """A typo'd path must error (exit 2), not report 'linted clean'."""
    proc = subprocess.run(
        [sys.executable, LINT, "--root", str(tmp_path), "no_such.py"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2
    assert "no such path" in proc.stderr


def test_cli_nags_on_todo_justification(tmp_path):
    """A TODO-justified baseline entry nags on every run, not only on
    the --update-baseline that wrote it."""
    (tmp_path / "bad.py").write_text(RNG_BAD)
    findings = core.run_lint(str(tmp_path), paths=["bad.py"],
                             only=["rng-discipline"])
    core.save_baseline(str(tmp_path / core.BASELINE_NAME), findings)
    proc = subprocess.run(
        [sys.executable, LINT, "--root", str(tmp_path), "bad.py",
         "--only", "rng-discipline"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "needs a justification" in proc.stderr


def test_cli_json_output(tmp_path):
    (tmp_path / "bad.py").write_text(RNG_BAD)
    proc = subprocess.run(
        [sys.executable, LINT, "--root", str(tmp_path), "bad.py",
         "--json"], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    out = json.loads(proc.stdout)
    assert out["new"] and out["new"][0]["check"] == "rng-discipline"


def test_cli_list_checks():
    proc = subprocess.run(
        [sys.executable, LINT, "--list-checks"], capture_output=True,
        text=True, timeout=120)
    assert proc.returncode == 0
    for name in ("trace-purity", "rng-discipline", "donation-safety",
                 "compat-boundary", "telemetry-hot-path", "schema-drift"):
        assert name in proc.stdout


def test_cli_update_baseline_refuses_partial_run(tmp_path):
    """A partial run sees a slice of the findings; writing the baseline
    from it would silently drop every entry outside the slice."""
    (tmp_path / "bad.py").write_text(RNG_BAD)
    proc = subprocess.run(
        [sys.executable, LINT, "--root", str(tmp_path), "bad.py",
         "--update-baseline"], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2
    assert "full run" in proc.stderr
    assert not (tmp_path / core.BASELINE_NAME).exists()


def test_cli_unknown_checker_is_usage_error():
    proc = subprocess.run(
        [sys.executable, LINT, "--only", "no-such-check"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2


def test_project_only_run_skips_repo_parse(tmp_path):
    """`--only schema-drift` reads no files: an unrelated syntax error
    must not turn the shim's in-sync exit 0 into a bogus failure."""
    (tmp_path / "broken.py").write_text("x = (\n")
    found = core.run_lint(str(tmp_path), paths=["broken.py"],
                          only=["schema-drift"])
    assert [f for f in found if f.check == "parse-error"] == []


def test_project_level_findings_honor_suppression(tmp_path):
    """The suppression contract covers check_project findings too."""

    class _ProjProbe(core.Checker):
        name = "proj-probe"
        description = "test-only"
        reads_files = True

        def check_project(self, files):
            return [core.Finding(self.name, "probe.py", 2, 0, "hit")]

    core.CHECKERS[_ProjProbe.name] = _ProjProbe()
    try:
        (tmp_path / "probe.py").write_text(
            "x = 1\ny = 2  # tpulint: disable=proj-probe\n")
        assert core.run_lint(str(tmp_path), paths=["probe.py"],
                             only=["proj-probe"]) == []
        (tmp_path / "probe.py").write_text("x = 1\ny = 2\n")
        found = core.run_lint(str(tmp_path), paths=["probe.py"],
                              only=["proj-probe"])
        assert len(found) == 1 and found[0].check == "proj-probe"
    finally:
        del core.CHECKERS[_ProjProbe.name]


def test_shim_still_guards_schema(tmp_path):
    """The deprecated check_schema_drift.py shim execs the lint CLI and
    keeps the old exit-code contract."""
    shim = os.path.join(REPO, "scripts", "check_schema_drift.py")
    proc = subprocess.run([sys.executable, shim], capture_output=True,
                          text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "deprecated" in proc.stderr


# ---------------------------------------------------------------------------
# whole-program engine: cross-file closure + call-graph resolution
# ---------------------------------------------------------------------------

def _write_pkg(tmp_path, files):
    """Lay out a package tree and return the repo-relative paths."""
    rels = []
    for rel, code in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(code)
        rels.append(rel)
    return rels


def test_trace_purity_cross_file_host_clock(tmp_path):
    """The ISSUE-6 motivating case: a host clock TWO modules away from
    the scan body must be visible to the closure."""
    rels = _write_pkg(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/clock.py": (
            "import time\n"
            "def stamp():\n"
            "    return time.time()\n"),
        "pkg/mid.py": (
            "from .clock import stamp\n"
            "def helper(x):\n"
            "    return x + stamp()\n"),
        "pkg/body.py": (
            "from jax import lax\n"
            "from .mid import helper\n"
            "def build(xs):\n"
            "    def body(c, x):\n"
            "        return helper(c), x\n"
            "    return lax.scan(body, 0.0, xs)\n"),
    })
    found = core.run_lint(str(tmp_path), paths=rels,
                          only=["trace-purity"])
    assert len(found) == 1, [f.render() for f in found]
    assert found[0].path == "pkg/clock.py"
    assert "time.time" in found[0].message


def test_trace_purity_cross_file_good(tmp_path):
    """The same helper chain WITHOUT the host clock stays silent, and a
    host-side caller of the clock helper is not flagged."""
    rels = _write_pkg(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/clock.py": (
            "import time\n"
            "def stamp():\n"
            "    return time.time()\n"),
        "pkg/body.py": (
            "from jax import lax\n"
            "from .clock import stamp\n"
            "def host_loop(xs):\n"
            "    t0 = stamp()\n"         # host side: fine
            "    def body(c, x):\n"
            "        return c + x, x\n"
            "    return lax.scan(body, 0.0, xs), stamp() - t0\n"),
    })
    assert core.run_lint(str(tmp_path), paths=rels,
                         only=["trace-purity"]) == []


def test_trace_purity_method_override_reached_cross_file(tmp_path):
    """`self.exchange_body` passed to shard_map must close over a
    SUBCLASS override defined in another file."""
    rels = _write_pkg(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/base.py": (
            "from theanompi_tpu.jax_compat import shard_map\n"
            "class Base:\n"
            "    def build(self, mesh, spec):\n"
            "        return shard_map(self.exchange_body, mesh=mesh,\n"
            "                         in_specs=(spec,), out_specs=spec)\n"
            "    def exchange_body(self, state):\n"
            "        return state\n"),
        "pkg/sub.py": (
            "import time\n"
            "from .base import Base\n"
            "class Sub(Base):\n"
            "    def exchange_body(self, state):\n"
            "        t = time.time()\n"
            "        return state\n"),
    })
    found = core.run_lint(str(tmp_path), paths=rels,
                          only=["trace-purity"])
    assert len(found) == 1 and found[0].path == "pkg/sub.py", \
        [f.render() for f in found]


def test_rng_discipline_cross_file_reuse(tmp_path):
    """A helper that spends its key parameter makes two same-key calls
    of it reuse — even when the helper lives in another module."""
    rels = _write_pkg(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/draws.py": (
            "import jax\n"
            "def draw(key, shape):\n"
            "    return jax.random.normal(key, shape)\n"),
        "pkg/use.py": (
            "from .draws import draw\n"
            "def run(key):\n"
            "    a = draw(key, (4,))\n"
            "    b = draw(key, (4,))\n"
            "    return a + b\n"),
    })
    found = core.run_lint(str(tmp_path), paths=rels,
                          only=["rng-discipline"])
    assert len(found) == 1 and found[0].path == "pkg/use.py", \
        [f.render() for f in found]
    assert "key `key` consumed again" in found[0].message


def test_rng_discipline_cross_file_fold_in_helper_ok(tmp_path):
    """A helper that only DERIVES (fold_in) does not consume — two
    calls with one key are the sanctioned multi-stream pattern."""
    rels = _write_pkg(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/draws.py": (
            "import jax\n"
            "def derive(key, n):\n"
            "    return jax.random.fold_in(key, n)\n"),
        "pkg/use.py": (
            "from .draws import derive\n"
            "def run(key):\n"
            "    return derive(key, 1), derive(key, 2)\n"),
    })
    assert core.run_lint(str(tmp_path), paths=rels,
                         only=["rng-discipline"]) == []


def test_donation_safety_cross_file_donating_import(tmp_path):
    """`from train import step` where train.py jits with donation:
    read-after-donate at the importing call site."""
    rels = _write_pkg(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/train.py": (
            "import jax\n"
            "def g(s):\n"
            "    return s\n"
            "step = jax.jit(g, donate_argnums=0)\n"),
        "pkg/use.py": (
            "from .train import step\n"
            "def run(state):\n"
            "    out = step(state)\n"
            "    return out, state['params']\n"),
    })
    found = core.run_lint(str(tmp_path), paths=rels,
                          only=["donation-safety"])
    assert len(found) == 1 and found[0].path == "pkg/use.py", \
        [f.render() for f in found]
    assert "`state` read after being donated" in found[0].message


def test_engine_resolves_every_exchange_body_override():
    """Repo-wide: the call graph must see the whole exchange_body
    override family (the checkers build on exactly this)."""
    from theanompi_tpu.analysis.engine import ProgramIndex
    files = core.collect_files(REPO, ["theanompi_tpu"])
    index = ProgramIndex(files)
    recs = index.method_records(
        ("theanompi_tpu.parallel.exchanger", "Exchanger"),
        "exchange_body")
    owners = {r.class_name for r in recs}
    assert {"Exchanger", "BSP_Exchanger", "EASGD_Exchanger",
            "ASGD_Exchanger", "GOSGD_Exchanger"} <= owners, owners
    # and the symmetry checker enumerates the same family
    from theanompi_tpu.analysis.checkers.exchange_symmetry import \
        ExchangeSymmetryChecker
    bodies = ExchangeSymmetryChecker()._exchange_bodies(index)
    assert {r.class_name for r in bodies} >= {
        "BSP_Exchanger", "EASGD_Exchanger", "ASGD_Exchanger",
        "GOSGD_Exchanger"}


# ---------------------------------------------------------------------------
# collective-discipline
# ---------------------------------------------------------------------------

def test_collective_discipline_axis_typo(tmp_path):
    code = (
        "from jax import lax\n"
        "def exchange(x):\n"
        "    return lax.pmean(x, 'workerz')\n")
    found = lint_snippet(tmp_path, "x.py", code, "collective-discipline")
    assert len(found) == 1
    assert "undeclared mesh axis 'workerz'" in found[0].message


def test_collective_discipline_axis_constant_prop(tmp_path):
    """The `axis, alpha = WORKER_AXIS, self.alpha` tuple-assign shape
    (exchanger.py) resolves through constant propagation."""
    code = (
        "from jax import lax\n"
        "from theanompi_tpu.parallel.mesh import WORKER_AXIS\n"
        "def good(x, alpha):\n"
        "    axis, a = WORKER_AXIS, alpha\n"
        "    return lax.psum(x, axis)\n"
        "def bad(x, alpha):\n"
        "    axis, a = 'workerz', alpha\n"
        "    return lax.psum(x, axis)\n")
    found = lint_snippet(tmp_path, "x.py", code, "collective-discipline")
    assert len(found) == 1 and found[0].line == 8, \
        [f.render() for f in found]


def test_collective_discipline_same_file_mesh_declares_axis(tmp_path):
    """An axis declared by a literal Mesh(...) in the same file is
    valid vocabulary (tests declare ('workers', 'seq') meshes)."""
    code = (
        "import numpy as np\n"
        "import jax\n"
        "from jax import lax\n"
        "from jax.sharding import Mesh\n"
        "mesh = Mesh(np.array(jax.devices()), ('rows', 'cols'))\n"
        "def f(x):\n"
        "    return lax.psum(x, 'rows')\n")
    assert lint_snippet(tmp_path, "x.py", code,
                        "collective-discipline") == []


def test_collective_discipline_rank_branch(tmp_path):
    code = (
        "from jax import lax\n"
        "def exchange(x):\n"
        "    rank = lax.axis_index('workers')\n"
        "    if rank == 0:\n"
        "        x = lax.psum(x, 'workers')\n"
        "    return x\n")
    found = lint_snippet(tmp_path, "x.py", code, "collective-discipline")
    assert len(found) == 1
    assert "divergence hazard" in found[0].message


def test_collective_discipline_rank_branch_via_helper(tmp_path):
    """The hazard is interprocedural: a branch calling a helper whose
    SUMMARY issues collectives is flagged too."""
    code = (
        "import jax\n"
        "from jax import lax\n"
        "def reduce_all(x):\n"
        "    return lax.psum(x, 'workers')\n"
        "def exchange(x):\n"
        "    if jax.process_index() == 0:\n"
        "        x = reduce_all(x)\n"
        "    return x\n")
    found = lint_snippet(tmp_path, "x.py", code, "collective-discipline")
    assert len(found) == 1
    assert "reduce_all" in found[0].message


def test_collective_discipline_uniform_branch_ok(tmp_path):
    """Static-config branches (mesh size, flags) are NOT rank taint."""
    code = (
        "from jax import lax\n"
        "def exchange(x, n, use_ring):\n"
        "    rank = lax.axis_index('workers')\n"
        "    y = x + rank\n"                       # data use: fine
        "    if n > 1 and use_ring:\n"
        "        y = lax.psum(y, 'workers')\n"
        "    return y\n")
    assert lint_snippet(tmp_path, "x.py", code,
                        "collective-discipline") == []


def test_collective_discipline_start_done_pairing(tmp_path):
    code = (
        "from jax import lax\n"
        "def overlap(x):\n"
        "    t = lax.psum_start(x, 'workers')\n"
        "    return x\n"
        "def balanced(x):\n"
        "    t = lax.psum_start(x, 'workers')\n"
        "    return lax.psum_done(t)\n")
    found = lint_snippet(tmp_path, "x.py", code, "collective-discipline")
    assert len(found) == 1 and found[0].line == 3
    assert "unbalanced async collective pair" in found[0].message


def test_collective_discipline_discarded_ticket(tmp_path):
    """The bucket-balance probe: a start whose ticket hits the floor is
    flagged even when another pair balances the scope's counts."""
    code = (
        "from jax import lax\n"
        "def exchange(xs):\n"
        "    lax.psum_start(xs[0], 'workers')\n"       # discarded!
        "    t = lax.psum_start(xs[1], 'workers')\n"
        "    a = lax.psum_done(t)\n"
        "    b = lax.psum_done(t)\n"                   # counts balance...
        "    return a + b\n")
    found = lint_snippet(tmp_path, "x.py", code, "collective-discipline")
    assert len(found) == 1 and found[0].line == 3
    assert "leaked in-flight collective" in found[0].message


def test_collective_discipline_bucket_loop_balanced_ok(tmp_path):
    """The bucketed-wire shape (parallel/buckets.py): starts collected
    into a ticket list, dones drained from it — balanced, clean."""
    code = (
        "from theanompi_tpu.jax_compat import psum_start, psum_done\n"
        "def exchange(vecs):\n"
        "    tickets = [psum_start(v, 'workers') for v in vecs]\n"
        "    return [psum_done(t) for t in tickets]\n")
    assert lint_snippet(tmp_path, "x.py", code,
                        "collective-discipline") == []


def test_collective_discipline_shim_module_exempt():
    """The shim-definition module is the pairing boundary: each half
    wraps its one-sided lax call by construction — no findings on the
    real file."""
    found = core.run_lint(REPO, paths=["theanompi_tpu/jax_compat.py"],
                          only=["collective-discipline"])
    assert found == [], [f.render() for f in found]


def test_injection_dropped_done_in_buckets(tmp_path):
    """Live injection (the ISSUE 13 bucket-balance gate): drop the ONE
    psum_done from the real bucketed-psum engine and the checker must
    catch the leaked in-flight buckets; the unmodified file is clean."""
    clean = core.run_lint(REPO, paths=["theanompi_tpu/parallel/buckets.py"],
                          only=["collective-discipline"])
    assert clean == [], [f.render() for f in clean]
    rel = _inject(tmp_path, "theanompi_tpu/parallel/buckets.py",
                  "lambda t: psum_done(t))",
                  "lambda t: t.value)")
    found = core.run_lint(str(tmp_path), paths=[rel],
                          only=["collective-discipline"])
    assert any("unbalanced async collective pair" in f.message
               and "psum_start" in f.message for f in found), \
        [f.render() for f in found]


def test_injection_dropped_done_in_onebit_strategy(tmp_path):
    """Same gate on the compressed wire: strip the all_gather_done from
    OneBit's bucketed decode loop → unbalanced pair."""
    rel = _inject(tmp_path, "theanompi_tpu/parallel/strategies.py",
                  "compress_ops.unpack_signs_weighted_mean(\n"
                  "                all_gather_done(t), all_scales, size)",
                  "compress_ops.unpack_signs_weighted_mean(\n"
                  "                t.value, all_scales, size)")
    found = core.run_lint(str(tmp_path), paths=[rel],
                          only=["collective-discipline"])
    assert any("unbalanced async collective pair" in f.message
               and "all_gather_start" in f.message for f in found), \
        [f.render() for f in found]


def test_collective_discipline_dead_ticket(tmp_path):
    """The round-10 dead-ticket probe: a ticket assigned from a start
    and never read is flagged even when the scope's counts balance
    (a typo'd done consuming the wrong ticket twice)."""
    code = (
        "from jax import lax\n"
        "def hop(a, b):\n"
        "    t1 = lax.ppermute_start(a, 'pipe', [(0, 1)])\n"
        "    t2 = lax.ppermute_start(b, 'pipe', [(0, 1)])\n"   # dead!
        "    x = lax.ppermute_done(t1)\n"
        "    y = lax.ppermute_done(t1)\n"                # counts balance
        "    return x + y\n")
    found = lint_snippet(tmp_path, "x.py", code, "collective-discipline")
    assert len(found) == 1 and found[0].line == 4
    assert "dropped hop ticket" in found[0].message
    assert "`t2`" in found[0].message


def test_collective_discipline_consumed_ticket_ok(tmp_path):
    """The healthy per-slot hop shape (pipeline.py scan body): ticket
    started and awaited — clean."""
    code = (
        "from theanompi_tpu.jax_compat import ppermute_start, "
        "ppermute_done\n"
        "def hop(x, perm):\n"
        "    ticket = ppermute_start(x, 'pipe', perm)\n"
        "    return ppermute_done(ticket)\n")
    assert lint_snippet(tmp_path, "x.py", code,
                        "collective-discipline") == []


def test_injection_stripped_hop_done_in_pipeline(tmp_path):
    """Live injection (the ISSUE 16 schedule-slot gate): strip the ONE
    ppermute_done from the real interleaved scan body — the per-slot
    hop ticket is started and never awaited — and the checker must
    fire; the unmodified file is clean."""
    clean = core.run_lint(REPO,
                          paths=["theanompi_tpu/parallel/pipeline.py"],
                          only=["collective-discipline"])
    assert clean == [], [f.render() for f in clean]
    rel = _inject(tmp_path, "theanompi_tpu/parallel/pipeline.py",
                  "            state = jc.ppermute_done(ticket)",
                  "            state = out")
    found = core.run_lint(str(tmp_path), paths=[rel],
                          only=["collective-discipline"])
    assert any("unbalanced async collective pair" in f.message
               and "ppermute_start" in f.message for f in found), \
        [f.render() for f in found]


def test_injection_wrong_ticket_in_pipeline(tmp_path):
    """The harder schedule-slot failure: the done consumes the WRONG
    value so start/done counts still balance — only the dead-ticket
    probe sees the leaked per-slot hop."""
    rel = _inject(tmp_path, "theanompi_tpu/parallel/pipeline.py",
                  "            state = jc.ppermute_done(ticket)",
                  "            state = jc.ppermute_done(out)")
    found = core.run_lint(str(tmp_path), paths=[rel],
                          only=["collective-discipline"])
    assert any("dropped hop ticket" in f.message
               and "`ticket`" in f.message for f in found), \
        [f.render() for f in found]


# ---------------------------------------------------------------------------
# sharding-schema
# ---------------------------------------------------------------------------

def test_sharding_schema_bad_axis_in_spec(tmp_path):
    code = (
        "from jax.sharding import PartitionSpec as P\n"
        "SPEC = P('workerz', None)\n")
    found = lint_snippet(tmp_path, "x.py", code, "sharding-schema")
    assert len(found) == 1
    assert "undeclared mesh axis 'workerz'" in found[0].message


def test_sharding_schema_good_specs(tmp_path):
    """Declared axes, tuple entries, None, and star-constructions
    (the steps.stage_window P(None, *base) shape) all pass."""
    code = (
        "from jax.sharding import PartitionSpec as P\n"
        "A = P('workers', None)\n"
        "B = P(('workers', 'model'), 'seq')\n"
        "def stage(base):\n"
        "    return P(None, *base)\n")
    assert lint_snippet(tmp_path, "x.py", code, "sharding-schema") == []


def test_sharding_schema_in_specs_arity(tmp_path):
    code = (
        "from jax.sharding import PartitionSpec as P\n"
        "from theanompi_tpu.jax_compat import shard_map\n"
        "def build(mesh):\n"
        "    def per_worker(state, batch, lr):\n"
        "        return state\n"
        "    return shard_map(per_worker, mesh=mesh,\n"
        "                     in_specs=(P(), P()), out_specs=P())\n")
    found = lint_snippet(tmp_path, "x.py", code, "sharding-schema")
    assert len(found) == 1
    assert "2 spec(s)" in found[0].message
    assert "3 positional parameter(s)" in found[0].message


def test_sharding_schema_out_specs_arity(tmp_path):
    code = (
        "from jax.sharding import PartitionSpec as P\n"
        "from theanompi_tpu.jax_compat import shard_map\n"
        "def build(mesh):\n"
        "    def per_worker(state):\n"
        "        return state, 1.0, 2.0\n"
        "    return shard_map(per_worker, mesh=mesh,\n"
        "                     in_specs=(P(),), out_specs=(P(), P()))\n")
    found = lint_snippet(tmp_path, "x.py", code, "sharding-schema")
    assert len(found) == 1
    assert "returns 3 value(s)" in found[0].message


def test_sharding_schema_matching_arity_ok(tmp_path):
    code = (
        "from jax.sharding import PartitionSpec as P\n"
        "from theanompi_tpu.jax_compat import shard_map\n"
        "def build(mesh):\n"
        "    def per_worker(state, batch):\n"
        "        return state, batch\n"
        "    return shard_map(per_worker, mesh=mesh,\n"
        "                     in_specs=(P('workers'), P('workers')),\n"
        "                     out_specs=(P('workers'), P('workers')))\n")
    assert lint_snippet(tmp_path, "x.py", code, "sharding-schema") == []


# ---------------------------------------------------------------------------
# exchange-symmetry
# ---------------------------------------------------------------------------

SYMMETRY_BAD = """
from jax import lax
from theanompi_tpu.parallel.exchanger import Exchanger

class Skippy(Exchanger):
    def exchange_body(self, state, key, count):
        if state.get("skip"):
            return state
        return {k: lax.pmean(v, "workers") for k, v in state.items()}
"""

SYMMETRY_BAD_ONE_ARM = """
from jax import lax
from theanompi_tpu.parallel.exchanger import Exchanger

class OneArm(Exchanger):
    def exchange_body(self, state, key, count):
        if count % 2:
            state = {k: lax.psum(v, "workers") for k, v in state.items()}
        return state
"""

SYMMETRY_GOOD = """
from jax import lax
from theanompi_tpu.parallel.exchanger import Exchanger

class Clean(Exchanger):
    def exchange_body(self, state, key, count):
        reduced = {k: lax.pmean(v, "workers") for k, v in state.items()}
        if count % 2:
            reduced = {k: v * 2 for k, v in reduced.items()}
        return reduced
"""


def test_exchange_symmetry_early_return(tmp_path):
    found = lint_snippet(tmp_path, "x.py", SYMMETRY_BAD,
                         "exchange-symmetry")
    assert len(found) == 1
    assert "early exit" in found[0].message
    assert "pmean" in found[0].message


def test_exchange_symmetry_one_armed_branch(tmp_path):
    found = lint_snippet(tmp_path, "x.py", SYMMETRY_BAD_ONE_ARM,
                         "exchange-symmetry")
    assert len(found) == 1
    assert "diverges across `if` arms" in found[0].message


def test_exchange_symmetry_good_subclass(tmp_path):
    assert lint_snippet(tmp_path, "x.py", SYMMETRY_GOOD,
                        "exchange-symmetry") == []


def test_exchange_symmetry_repo_rules_clean():
    """The four live rules already satisfy the invariant."""
    found = core.run_lint(REPO, paths=["theanompi_tpu/parallel"],
                          only=["exchange-symmetry"])
    assert found == [], [f.render() for f in found]


# ---------------------------------------------------------------------------
# acceptance injections against the REAL files (tmp copies)
# ---------------------------------------------------------------------------

def _inject(tmp_path, rel, old, new):
    src = open(os.path.join(REPO, rel)).read()
    assert old in src, f"{rel} changed shape; update the injection"
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(src.replace(old, new))
    return rel


def test_injection_axis_typo_in_exchanger(tmp_path):
    rel = _inject(tmp_path, "theanompi_tpu/parallel/exchanger.py",
                  "axis, alpha = WORKER_AXIS, self.alpha",
                  "axis, alpha = 'workerz', self.alpha")
    found = core.run_lint(str(tmp_path), paths=[rel],
                          only=["collective-discipline"])
    assert any("undeclared mesh axis 'workerz'" in f.message
               for f in found), [f.render() for f in found]


def test_injection_rank_conditional_psum_in_strategies(tmp_path):
    rel = _inject(
        tmp_path, "theanompi_tpu/parallel/strategies.py",
        "        if wd is None:\n"
        "            out = jax.tree.map(lambda g: lax.psum(g, axis) * inv"
        ", tree)",
        "        rank = lax.axis_index(axis)\n"
        "        if wd is None:\n"
        "            if rank == 0:\n"
        "                tree = jax.tree.map(lambda g: lax.psum(g, axis),"
        " tree)\n"
        "            out = jax.tree.map(lambda g: lax.psum(g, axis) * inv"
        ", tree)")
    found = core.run_lint(str(tmp_path), paths=[rel],
                          only=["collective-discipline"])
    assert any("divergence hazard" in f.message for f in found), \
        [f.render() for f in found]


def test_injection_wrong_length_in_specs_in_steps(tmp_path):
    rel = _inject(tmp_path, "theanompi_tpu/parallel/steps.py",
                  "in_specs=(state_spec, batch_spec, P(), P(), P()),",
                  "in_specs=(state_spec, batch_spec, P(), P()),")
    found = core.run_lint(str(tmp_path), paths=[rel],
                          only=["sharding-schema"])
    assert any("4 spec(s)" in f.message and "5 positional" in f.message
               for f in found), [f.render() for f in found]


# ---------------------------------------------------------------------------
# result cache (.tpulint_cache/)
# ---------------------------------------------------------------------------

def _lint_cli(root, *extra, env_extra=None):
    env = dict(os.environ, **(env_extra or {}))
    return subprocess.run(
        [sys.executable, LINT, "--root", str(root), *extra],
        capture_output=True, text=True, timeout=300, env=env)


def test_cache_warm_run_identical_and_fast(tmp_path):
    """Cold vs warm: identical findings, warm under a second, and the
    status line says which happened."""
    (tmp_path / "bad.py").write_text(RNG_BAD)
    cold = _lint_cli(tmp_path, "bad.py", "--format", "json")
    assert json.loads(cold.stdout)["cache"] == "miss"
    import time as _time
    t0 = _time.monotonic()
    warm = _lint_cli(tmp_path, "bad.py", "--format", "json")
    elapsed = _time.monotonic() - t0
    w = json.loads(warm.stdout)
    assert w["cache"] == "hit"
    assert w["findings"] == json.loads(cold.stdout)["findings"]
    assert cold.returncode == warm.returncode == 1
    # interpreter startup dominates; the run itself must be trivial
    assert elapsed < 5.0, elapsed
    assert (tmp_path / ".tpulint_cache").is_dir()


def test_cache_invalidates_on_content_change(tmp_path):
    (tmp_path / "f.py").write_text("x = 1\n")
    assert json.loads(_lint_cli(tmp_path, "f.py", "--format",
                                "json").stdout)["cache"] == "miss"
    (tmp_path / "f.py").write_text(RNG_BAD)
    out = json.loads(_lint_cli(tmp_path, "f.py", "--format",
                               "json").stdout)
    assert out["cache"] == "miss"
    assert out["findings"], "edited file must re-lint, not hit"


def test_cache_no_cache_flag(tmp_path):
    (tmp_path / "f.py").write_text("x = 1\n")
    _lint_cli(tmp_path, "f.py")
    out = json.loads(_lint_cli(tmp_path, "f.py", "--no-cache",
                               "--format", "json").stdout)
    assert out["cache"] == "off"


def test_cache_key_depends_on_analysis_sources():
    """Editing any analysis/ source changes the fingerprint — the
    auto-invalidation the cache's soundness rests on."""
    from theanompi_tpu.analysis import cache as cm
    fp = cm.analysis_fingerprint()
    h1 = cm.tree_key(fp, ["a"], [], [("f.py", "sha")])
    h2 = cm.tree_key(fp + "x", ["a"], [], [("f.py", "sha")])
    h3 = cm.tree_key(fp, ["a", "b"], [], [("f.py", "sha")])
    h4 = cm.tree_key(fp, ["a"], [], [("f.py", "sha2")])
    assert len({h1, h2, h3, h4}) == 4


def test_cache_repo_gate_warm_subsecond():
    """The acceptance criterion: a cached re-run of the unchanged repo
    completes in < 1s (process time minus interpreter startup) and is
    finding-identical to the cold run."""
    import time as _time
    cold = subprocess.run(
        [sys.executable, LINT, "--format", "json"], cwd=REPO,
        capture_output=True, text=True, timeout=300)
    t0 = _time.monotonic()
    warm = subprocess.run(
        [sys.executable, LINT, "--format", "json"], cwd=REPO,
        capture_output=True, text=True, timeout=300)
    elapsed = _time.monotonic() - t0
    w, c = json.loads(warm.stdout), json.loads(cold.stdout)
    assert w["cache"] == "hit"
    assert w["findings"] == c["findings"]
    assert elapsed < 2.5, f"warm repo lint took {elapsed:.2f}s"


# ---------------------------------------------------------------------------
# --format json fingerprints + TODO-nag collapse
# ---------------------------------------------------------------------------

def test_json_format_stable_fingerprints(tmp_path):
    (tmp_path / "bad.py").write_text(TRACE_BAD)
    out = json.loads(_lint_cli(tmp_path, "bad.py", "--format",
                               "json").stdout)
    f = out["findings"][0]
    assert set(f) >= {"check", "path", "line", "col", "message",
                      "fingerprint"}
    # stable = line-insensitive (for messages that don't quote a line):
    # shifting the file moves the finding but keeps the fingerprint
    (tmp_path / "bad.py").write_text("\n\n\n" + TRACE_BAD)
    out2 = json.loads(_lint_cli(tmp_path, "bad.py", "--format",
                                "json").stdout)
    assert out2["findings"][0]["fingerprint"] == f["fingerprint"]
    assert out2["findings"][0]["line"] != f["line"]


def test_todo_nag_collapses_to_summary(tmp_path):
    """Two TODO entries: default output is ONE summary line carrying
    the count; --verbose restores the per-entry list.  Never silent."""
    (tmp_path / "bad.py").write_text(RNG_BAD + RNG_BAD_LOOP)
    findings = core.run_lint(str(tmp_path), paths=["bad.py"],
                             only=["rng-discipline"])
    assert len(findings) == 2
    core.save_baseline(str(tmp_path / core.BASELINE_NAME), findings)
    proc = _lint_cli(tmp_path, "bad.py", "--only", "rng-discipline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    nag_lines = [l for l in proc.stderr.splitlines()
                 if "justification" in l]
    assert len(nag_lines) == 1, proc.stderr
    assert "2 baseline entries" in nag_lines[0]
    verbose = _lint_cli(tmp_path, "bad.py", "--only", "rng-discipline",
                        "--verbose")
    v_lines = [l for l in verbose.stderr.splitlines()
               if "needs a justification" in l]
    assert len(v_lines) == 2, verbose.stderr


# ---------------------------------------------------------------------------
# precommit entry point
# ---------------------------------------------------------------------------

def test_precommit_lint_script_clean_and_failing(tmp_path):
    """scripts/precommit_lint.sh lints exactly the staged in-scope
    files of a scratch clone: clean stage exits 0, a staged finding
    exits 1, out-of-scope stages are ignored."""
    import shutil
    repo = tmp_path / "r"
    (repo / "scripts").mkdir(parents=True)
    (repo / "theanompi_tpu").mkdir()
    shutil.copy(os.path.join(REPO, "scripts", "precommit_lint.sh"),
                repo / "scripts" / "precommit_lint.sh")
    shutil.copy(LINT, repo / "scripts" / "lint.py")
    # the launcher needs the analysis package under the scratch root
    shutil.copytree(os.path.join(REPO, "theanompi_tpu", "analysis"),
                    repo / "theanompi_tpu" / "analysis")
    shutil.copy(os.path.join(REPO, "theanompi_tpu", "jax_compat.py"),
                repo / "theanompi_tpu" / "jax_compat.py")
    # the schema-drift live probe imports these for real (devprof/sentry
    # feed the round-12 device-schema probes; the checker skips them
    # gracefully when a partial tree omits them)
    (repo / "theanompi_tpu" / "utils").mkdir()
    for m in ("__init__.py", "recorder.py", "telemetry.py", "devprof.py",
              "sentry.py"):
        shutil.copy(os.path.join(REPO, "theanompi_tpu", "utils", m),
                    repo / "theanompi_tpu" / "utils" / m)

    def git(*a):
        return subprocess.run(["git", *a], cwd=repo, capture_output=True,
                              text=True, timeout=60)

    git("init", "-q")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    sh = ["bash", "scripts/precommit_lint.sh"]

    # nothing staged in scope
    (repo / "NOTES.md").write_text("x\n")
    git("add", "NOTES.md")
    p = subprocess.run(sh, cwd=repo, capture_output=True, text=True,
                       timeout=300)
    assert p.returncode == 0 and "no staged python files" in p.stdout

    # a staged clean file
    (repo / "theanompi_tpu" / "ok.py").write_text("x = 1\n")
    git("add", "theanompi_tpu/ok.py")
    p = subprocess.run(sh, cwd=repo, capture_output=True, text=True,
                       timeout=300)
    assert p.returncode == 0, p.stdout + p.stderr

    # a staged finding fails the hook
    (repo / "theanompi_tpu" / "bad.py").write_text(RNG_BAD)
    git("add", "theanompi_tpu/bad.py")
    p = subprocess.run(sh, cwd=repo, capture_output=True, text=True,
                       timeout=300)
    assert p.returncode == 1, p.stdout + p.stderr
    assert "rng-discipline" in p.stdout


def test_collective_discipline_axis_name_kwarg_typo(tmp_path):
    """A typo'd axis passed as `axis_name=` on a COLLECTIVE must not
    self-whitelist (review finding: kwarg harvesting is for binders)."""
    code = (
        "from jax import lax\n"
        "def exchange(x):\n"
        "    return lax.pmean(x, axis_name='workerz')\n")
    found = lint_snippet(tmp_path, "x.py", code, "collective-discipline")
    assert len(found) == 1
    assert "undeclared mesh axis 'workerz'" in found[0].message


def test_exchange_symmetry_exiting_arm_issues_collective(tmp_path):
    """The mirror of SYMMETRY_BAD: the EXITING arm reduces and the
    fall-through does not — same divergence, must be flagged."""
    code = (
        "from jax import lax\n"
        "from theanompi_tpu.parallel.exchanger import Exchanger\n"
        "class Mirror(Exchanger):\n"
        "    def exchange_body(self, state, key, count):\n"
        "        if state.get('skip'):\n"
        "            return {k: lax.pmean(v, 'workers')\n"
        "                    for k, v in state.items()}\n"
        "        return state\n")
    found = lint_snippet(tmp_path, "x.py", code, "exchange-symmetry")
    assert len(found) == 1, [f.render() for f in found]
    assert "pmean" in found[0].message


def test_exchange_symmetry_config_assert_not_flagged(tmp_path):
    """A raising guard before the collectives is a loud uniform abort,
    not a silent divergence — no finding."""
    code = (
        "from jax import lax\n"
        "from theanompi_tpu.parallel.exchanger import Exchanger\n"
        "class Guarded(Exchanger):\n"
        "    def exchange_body(self, state, key, count):\n"
        "        if not state:\n"
        "            raise ValueError('empty state')\n"
        "        return {k: lax.pmean(v, 'workers')\n"
        "                for k, v in state.items()}\n")
    assert lint_snippet(tmp_path, "x.py", code, "exchange-symmetry") == []


def test_precommit_lints_staged_blob_not_worktree(tmp_path):
    """Stage a violation, fix the worktree WITHOUT re-staging: the hook
    must still fail — the commit would contain the staged violation."""
    import shutil
    repo = tmp_path / "r"
    (repo / "scripts").mkdir(parents=True)
    (repo / "theanompi_tpu").mkdir()
    shutil.copy(os.path.join(REPO, "scripts", "precommit_lint.sh"),
                repo / "scripts" / "precommit_lint.sh")
    shutil.copy(LINT, repo / "scripts" / "lint.py")
    shutil.copytree(os.path.join(REPO, "theanompi_tpu", "analysis"),
                    repo / "theanompi_tpu" / "analysis")
    shutil.copy(os.path.join(REPO, "theanompi_tpu", "jax_compat.py"),
                repo / "theanompi_tpu" / "jax_compat.py")
    (repo / "theanompi_tpu" / "utils").mkdir()
    for m in ("__init__.py", "recorder.py", "telemetry.py"):
        shutil.copy(os.path.join(REPO, "theanompi_tpu", "utils", m),
                    repo / "theanompi_tpu" / "utils" / m)

    def git(*a):
        return subprocess.run(["git", *a], cwd=repo, capture_output=True,
                              text=True, timeout=60)

    git("init", "-q")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    (repo / "theanompi_tpu" / "f.py").write_text(RNG_BAD)
    git("add", "theanompi_tpu/f.py")
    (repo / "theanompi_tpu" / "f.py").write_text("x = 1\n")  # fixed, unstaged
    p = subprocess.run(["bash", "scripts/precommit_lint.sh"], cwd=repo,
                       capture_output=True, text=True, timeout=300)
    assert p.returncode == 1, p.stdout + p.stderr
    assert "rng-discipline" in p.stdout
    # re-stage the fix: clean
    git("add", "theanompi_tpu/f.py")
    p = subprocess.run(["bash", "scripts/precommit_lint.sh"], cwd=repo,
                       capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, p.stdout + p.stderr


# ---------------------------------------------------------------------------
# host-concurrency pass (round 15): thread-role inference + four checkers
# ---------------------------------------------------------------------------

RACE_BAD = """
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.items = []
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        self.count = self.count + 1
        self.items.append(self.count)

    def bump(self):
        self.count = 0

    def snapshot(self):
        return list(self.items)

    def stop(self):
        self._thread.join(timeout=1)
"""

RACE_GOOD = """
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.items = []
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        with self._lock:
            self.count = self.count + 1
            self.items.append(self.count)

    def bump(self):
        with self._lock:
            self.count = 0

    def snapshot(self):
        with self._lock:
            return list(self.items)

    def stop(self):
        self._thread.join(timeout=1)
"""


def test_shared_state_race_bad_fixture(tmp_path):
    found = lint_snippet(tmp_path, "bad.py", RACE_BAD, "shared-state-race")
    msgs = [f.message for f in found]
    assert len(found) == 2, msgs
    assert any("`count`" in m and "no common lock" in m for m in msgs)
    assert any("`items`" in m and "iteration/copy" in m for m in msgs)


def test_shared_state_race_good_fixture(tmp_path):
    assert lint_snippet(tmp_path, "good.py", RACE_GOOD,
                        "shared-state-race") == []


def test_shared_state_race_init_writes_are_happens_before(tmp_path):
    """__init__ writes never conflict — construction precedes start()."""
    code = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self.flag = False\n"
        "        threading.Thread(target=self._go, daemon=True).start()\n"
        "    def _go(self):\n"
        "        self.flag = True\n")
    assert lint_snippet(tmp_path, "x.py", code, "shared-state-race") == []


def test_shared_state_race_needs_instance_sharing(tmp_path):
    """A thread that constructs its OWN instance of a class does not
    conflict with main-thread users of other instances (the per-island
    private ModelBase shape)."""
    code = (
        "import threading\n"
        "class Model:\n"
        "    def compile(self):\n"
        "        self.train_fn = 1\n"
        "class Island:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._run, daemon=True)\n"
        "        self._t.start()\n"
        "    def _run(self):\n"
        "        m = Model()\n"
        "        m.compile()\n"
        "    def stop(self):\n"
        "        self._t.join(timeout=1)\n"
        "def main_path():\n"
        "    m = Model()\n"
        "    m.compile()\n")
    assert lint_snippet(tmp_path, "x.py", code, "shared-state-race") == []


LOCK_ORDER_BAD = """
import threading

class Pair:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()

    def ab(self):
        with self._a_lock:
            with self._b_lock:
                pass

    def ba(self):
        with self._b_lock:
            with self._a_lock:
                pass
"""

LOCK_ORDER_GOOD = LOCK_ORDER_BAD.replace(
    "        with self._b_lock:\n            with self._a_lock:",
    "        with self._a_lock:\n            with self._b_lock:")


def test_lock_ordering_cycle_fixture(tmp_path):
    found = lint_snippet(tmp_path, "bad.py", LOCK_ORDER_BAD,
                         "lock-ordering")
    assert len(found) == 1, [f.message for f in found]
    assert "lock-order cycle" in found[0].message
    assert "_a_lock" in found[0].message and "_b_lock" in found[0].message


def test_lock_ordering_consistent_order_clean(tmp_path):
    assert lint_snippet(tmp_path, "good.py", LOCK_ORDER_GOOD,
                        "lock-ordering") == []


def test_lock_ordering_nonreentrant_self_deadlock(tmp_path):
    code = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def outer(self):\n"
        "        with self._lock:\n"
        "            self.inner()\n"
        "    def inner(self):\n"
        "        with self._lock:\n"
        "            pass\n")
    found = lint_snippet(tmp_path, "x.py", code, "lock-ordering")
    assert found and all("self-deadlock" in f.message for f in found)
    # the reentrant version is the sanctioned idiom (telemetry registry)
    rcode = code.replace("threading.Lock()", "threading.RLock()")
    assert lint_snippet(tmp_path, "y.py", rcode, "lock-ordering") == []


SIGNAL_BAD = """
import signal
import threading
import time

_state_lock = threading.Lock()

def _handler(signum, frame):
    time.sleep(0.1)
    with _state_lock:
        pass
    t = threading.Thread(target=_work, daemon=True)
    t.start()

def _work():
    pass

signal.signal(signal.SIGTERM, _handler)
"""

SIGNAL_GOOD = """
import signal
import threading

_halt = threading.Event()

def _handler(signum, frame):
    _halt.set()

signal.signal(signal.SIGTERM, _handler)
"""


def test_signal_safety_bad_fixture(tmp_path):
    found = lint_snippet(tmp_path, "bad.py", SIGNAL_BAD, "signal-safety")
    msgs = [f.message for f in found]
    assert any("time.sleep" in m for m in msgs), msgs
    assert any("NON-reentrant lock" in m for m in msgs), msgs
    assert any("spawns a thread" in m for m in msgs), msgs


def test_signal_safety_good_fixture(tmp_path):
    assert lint_snippet(tmp_path, "good.py", SIGNAL_GOOD,
                        "signal-safety") == []


def test_signal_safety_telemetry_recording_flagged(tmp_path):
    code = (
        "import signal\n"
        "from theanompi_tpu.utils import telemetry\n"
        "tm = telemetry.active()\n"
        "def _handler(signum, frame):\n"
        "    tm.event('sig')\n"
        "signal.signal(signal.SIGTERM, _handler)\n")
    found = lint_snippet(tmp_path, "x.py", code, "signal-safety")
    assert len(found) == 1 and "reentrant call" in found[0].message


def test_signal_safety_sanctioned_hook_is_exempt():
    """The live telemetry.py fatal-signal hook records by design (it is
    terminal) — the repo-wide run must not flag it."""
    found = core.run_lint(REPO, paths=["theanompi_tpu/utils/telemetry.py"],
                          only=["signal-safety"])
    assert found == [], [f.render() for f in found]


DAEMON_BAD = """
import threading

class Owner:
    def start(self):
        self._pump = threading.Thread(target=self._run_pump)
        self._pump.start()

    def _run_pump(self):
        pass

class BadThread(threading.Thread):
    def __init__(self):
        super().__init__()
        self._stop = threading.Event()

    def run(self):
        pass
"""

DAEMON_GOOD = """
import threading

class Owner:
    def start(self):
        self._pump = threading.Thread(target=self._run_pump, daemon=True)
        self._pump.start()

    def _run_pump(self):
        pass

    def stop(self):
        self._pump.join(timeout=1)

class GoodThread(threading.Thread):
    def __init__(self):
        super().__init__(daemon=True)
        self._halt = threading.Event()

    def run(self):
        pass

    def stop(self):
        self._halt.set()
        self.join(timeout=1)
"""


def test_daemon_discipline_bad_fixture(tmp_path):
    found = lint_snippet(tmp_path, "bad.py", DAEMON_BAD,
                         "daemon-discipline")
    msgs = [f.message for f in found]
    assert any("non-daemon Thread" in m for m in msgs), msgs
    assert any("`self._stop`" in m and "shadowing" in m
               for m in msgs), msgs
    assert any("non-daemon and never joins itself" in m
               for m in msgs), msgs


def test_daemon_discipline_good_fixture(tmp_path):
    assert lint_snippet(tmp_path, "good.py", DAEMON_GOOD,
                        "daemon-discipline") == []


def test_daemon_discipline_escaping_started_thread_needs_join(tmp_path):
    code = (
        "import threading\n"
        "class P:\n"
        "    def __init__(self):\n"
        "        self._threads = []\n"
        "    def start(self):\n"
        "        t = threading.Thread(target=self._go, daemon=True)\n"
        "        t.start()\n"
        "        self._threads.append(t)\n"
        "    def _go(self):\n"
        "        pass\n")
    found = lint_snippet(tmp_path, "x.py", code, "daemon-discipline")
    assert len(found) == 1 and "never joined" in found[0].message
    fixed = code + (
        "    def stop(self):\n"
        "        for t in self._threads:\n"
        "            t.join(timeout=1)\n")
    assert lint_snippet(tmp_path, "y.py", fixed,
                        "daemon-discipline") == []


# -- engine thread-role inference -------------------------------------------

ENGINE_ROLES = """
import atexit
import signal
import threading

class Prod:
    def start(self):
        self._t = threading.Thread(target=self._producer, daemon=True)
        self._t.start()

    def _producer(self):
        self._helper()

    def _helper(self):
        pass

    def consume(self):
        pass

    def stop(self):
        self._t.join(timeout=1)

class Mon(threading.Thread):
    def run(self):
        pass

def _on_exit():
    pass

def _on_sig(s, f):
    pass

atexit.register(_on_exit)
signal.signal(signal.SIGTERM, _on_sig)
"""


def test_engine_thread_roles_and_main_exclusion(tmp_path):
    from theanompi_tpu.analysis.engine import MAIN_ROLE, ProgramIndex
    (tmp_path / "roles.py").write_text(ENGINE_ROLES)
    sf = core.SourceFile(str(tmp_path), "roles.py")
    index = ProgramIndex([sf])
    kinds = {r.kind for r in index.thread_roles()}
    assert kinds == {"thread", "thread-subclass", "atexit", "signal"}
    by_qual = {r.qualname: r for r in index.records.values()
               if not r.qualname.startswith("roles.<lambda>")}
    rm = index.role_map()
    prod_roles = rm[id(by_qual["roles.Prod._producer"].node)]
    help_roles = rm[id(by_qual["roles.Prod._helper"].node)]
    # the producer and its exclusive helper run ONLY on the spawned
    # thread — a spawn reference is not a main-role call edge
    assert MAIN_ROLE not in prod_roles and MAIN_ROLE not in help_roles
    assert any(r.startswith("thread:") for r in prod_roles)
    assert prod_roles <= help_roles
    # the public surface stays main
    assert MAIN_ROLE in rm[id(by_qual["roles.Prod.consume"].node)]
    assert MAIN_ROLE in rm[id(by_qual["roles.Prod.start"].node)]


def test_engine_spawn_sites_resolve_tuple_loop_targets(tmp_path):
    """The ChaosProxy pump-pair shape: Thread targets bound by a for
    loop over a literal tuple of methods must resolve."""
    from theanompi_tpu.analysis.engine import ProgramIndex
    code = (
        "import threading\n"
        "class P:\n"
        "    def start(self):\n"
        "        for fn in (self._a, self._b):\n"
        "            threading.Thread(target=fn, daemon=True).start()\n"
        "    def _a(self):\n"
        "        pass\n"
        "    def _b(self):\n"
        "        pass\n")
    (tmp_path / "pumps.py").write_text(code)
    sf = core.SourceFile(str(tmp_path), "pumps.py")
    index = ProgramIndex([sf])
    sites = [s for s in index.spawn_sites() if s.kind == "thread"]
    assert len(sites) == 1
    assert sorted(e.name for e in sites[0].entries) == ["_a", "_b"]


def test_schema_drift_thread_role_probe_live_and_bad(tmp_path):
    """The live repo's membership/chaos spawn sites all resolve; a
    planted unresolvable spawn fails the probe."""
    assert sd.thread_role_coverage_errors() == []
    bad = tmp_path / "theanompi_tpu" / "utils"
    bad.mkdir(parents=True)
    (bad / "chaos.py").write_text(
        "import threading\n"
        "def go(fns):\n"
        "    threading.Thread(target=fns[0], daemon=True).start()\n")
    errors = sd.thread_role_coverage_errors(root=str(tmp_path))
    assert errors and "does not resolve" in errors[0][1]


# -- live injections against the REAL files (CLI --check-baseline gate) -----

def test_injection_unguarded_producer_write_in_prefetch(tmp_path):
    """An unguarded cross-thread write planted in the prefetch producer
    fails the tier-1 gate with rc 1."""
    rel = _inject(
        tmp_path, "theanompi_tpu/models/data/prefetch.py",
        "                cursor = self._data.get_cursor() \\\n"
        "                    if hasattr(self._data, \"get_cursor\") else {}\n"
        "                if tm.enabled:\n",
        "                cursor = self._data.get_cursor() \\\n"
        "                    if hasattr(self._data, \"get_cursor\") else {}\n"
        "                self._consumed_cursor = cursor\n"
        "                if tm.enabled:\n")
    proc = _lint_cli(tmp_path, rel, "--check-baseline")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "shared-state-race" in proc.stdout
    assert "_consumed_cursor" in proc.stdout


def test_injection_lock_order_inversion_in_center_server(tmp_path):
    """A planted A→B / B→A inversion across snapshot() and stop() fails
    the gate with a lock-ordering cycle."""
    rel = _inject(
        tmp_path, "theanompi_tpu/parallel/center_server.py",
        "        with self.center._lock:\n"
        "            if self.center._leaves is None:\n"
        "                return None\n",
        "        with self.center._lock:\n"
        "            with self._conns_lock:\n"
        "                pass\n"
        "            if self.center._leaves is None:\n"
        "                return None\n")
    p = tmp_path / rel
    src = p.read_text()
    old = ("            with self._conns_lock:\n"
           "                conns = list(self._conns)\n"
           "                self._conns.clear()\n")
    assert old in src, "center_server.stop changed shape; update injection"
    p.write_text(src.replace(old,
                 "            with self._conns_lock:\n"
                 "                with self.center._lock:\n"
                 "                    pass\n"
                 "                conns = list(self._conns)\n"
                 "                self._conns.clear()\n"))
    proc = _lint_cli(tmp_path, rel, "--check-baseline")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "lock-order cycle" in proc.stdout


def test_injection_telemetry_event_in_signal_hook(tmp_path):
    """A telemetry.event() call planted into the center CLI's SIGTERM
    hook fails the gate (reentrant-BufferedWriter hazard)."""
    rel = _inject(
        tmp_path, "theanompi_tpu/parallel/center_server.py",
        "    signal.signal(signal.SIGTERM, lambda *_: halt.set())",
        "    signal.signal(signal.SIGTERM,\n"
        "                  lambda *_: (tm.event(\"sigterm\"), halt.set()))")
    proc = _lint_cli(tmp_path, rel, "--check-baseline")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "signal-safety" in proc.stdout
    assert "reentrant call" in proc.stdout


# -- the --only concurrency group + cache behavior ---------------------------

def test_only_concurrency_group_runs_just_the_pass(tmp_path):
    (tmp_path / "bad.py").write_text(RACE_BAD + LOCK_ORDER_BAD)
    out = json.loads(_lint_cli(tmp_path, "bad.py", "--only", "concurrency",
                               "--format", "json").stdout)
    from theanompi_tpu.analysis.checkers import CHECK_GROUPS
    group = set(CHECK_GROUPS["concurrency"])
    checks = {f["check"] for f in out["findings"]}
    assert checks and checks <= group, checks
    # the v2 schema carries the new checker names + stable fingerprints
    for f in out["findings"]:
        assert f["check"] in group
        assert len(f["fingerprint"]) == 12
    # and a non-concurrency finding source stays silent under the group
    (tmp_path / "rng.py").write_text(RNG_BAD)
    out2 = json.loads(_lint_cli(tmp_path, "rng.py", "--only",
                                "concurrency", "--format", "json").stdout)
    assert out2["findings"] == []


def test_only_concurrency_repo_warm_cache_subsecond():
    """Satellite gate: a warm-cache whole-repo run of just the
    concurrency pass stays sub-second (modulo interpreter startup),
    mirroring the existing full-suite cache gate."""
    import time as _time
    cold = subprocess.run(
        [sys.executable, LINT, "--only", "concurrency", "--format",
         "json"], cwd=REPO, capture_output=True, text=True, timeout=300)
    t0 = _time.monotonic()
    warm = subprocess.run(
        [sys.executable, LINT, "--only", "concurrency", "--format",
         "json"], cwd=REPO, capture_output=True, text=True, timeout=300)
    elapsed = _time.monotonic() - t0
    w, c = json.loads(warm.stdout), json.loads(cold.stdout)
    assert w["cache"] == "hit"
    assert w["findings"] == c["findings"]
    assert elapsed < 2.5, f"warm concurrency lint took {elapsed:.2f}s"


def test_precommit_carries_concurrency_checkers(tmp_path):
    """precommit_lint.sh runs the concurrency pass on staged blobs with
    the same names/fingerprints (satellite: the hook and the json v2
    schema carry the new checkers unchanged)."""
    import shutil
    repo = tmp_path / "r"
    (repo / "scripts").mkdir(parents=True)
    (repo / "theanompi_tpu").mkdir()
    shutil.copy(os.path.join(REPO, "scripts", "precommit_lint.sh"),
                repo / "scripts" / "precommit_lint.sh")
    shutil.copy(LINT, repo / "scripts" / "lint.py")
    shutil.copytree(os.path.join(REPO, "theanompi_tpu", "analysis"),
                    repo / "theanompi_tpu" / "analysis")
    shutil.copy(os.path.join(REPO, "theanompi_tpu", "jax_compat.py"),
                repo / "theanompi_tpu" / "jax_compat.py")
    (repo / "theanompi_tpu" / "utils").mkdir()
    for m in ("__init__.py", "recorder.py", "telemetry.py"):
        shutil.copy(os.path.join(REPO, "theanompi_tpu", "utils", m),
                    repo / "theanompi_tpu" / "utils" / m)

    def git(*a):
        return subprocess.run(["git", *a], cwd=repo, capture_output=True,
                              text=True, timeout=60)

    git("init", "-q")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    (repo / "theanompi_tpu" / "racy.py").write_text(RACE_BAD)
    git("add", "theanompi_tpu/racy.py")
    p = subprocess.run(["bash", "scripts/precommit_lint.sh"], cwd=repo,
                       capture_output=True, text=True, timeout=300)
    assert p.returncode == 1, p.stdout + p.stderr
    assert "shared-state-race" in p.stdout


def test_engine_resolve_callable_survives_cyclic_rebind(tmp_path):
    """`fn = fn` (or a = b / b = a) around a spawn target must degrade
    to unresolved, not recurse to death and abort the engine."""
    from theanompi_tpu.analysis.engine import ProgramIndex
    code = (
        "import threading\n"
        "def go(fn=None):\n"
        "    fn = fn\n"
        "    a = b = None\n"
        "    a = b\n"
        "    b = a\n"
        "    threading.Thread(target=fn, daemon=True).start()\n"
        "    threading.Thread(target=a, daemon=True).start()\n")
    (tmp_path / "cyc.py").write_text(code)
    sf = core.SourceFile(str(tmp_path), "cyc.py")
    index = ProgramIndex([sf])
    sites = [s for s in index.spawn_sites() if s.kind == "thread"]
    assert len(sites) == 2
    assert all(s.entries == [] for s in sites)


def test_daemon_discipline_stored_attr_daemonized_after(tmp_path):
    """`self._t = Thread(...); self._t.daemon = True` is daemonic — the
    post-construction daemon assign must be seen for stored attrs too."""
    code = (
        "import threading\n"
        "class C:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._go)\n"
        "        self._t.daemon = True\n"
        "        self._t.start()\n"
        "    def _go(self):\n"
        "        pass\n"
        "    def stop(self):\n"
        "        self._t.join(timeout=1)\n")
    assert lint_snippet(tmp_path, "x.py", code, "daemon-discipline") == []


# ---------------------------------------------------------------------------
# protocol conformance (round 19, docs/design.md §21)
# ---------------------------------------------------------------------------

from theanompi_tpu.analysis import protocol as proto  # noqa: E402
from theanompi_tpu.analysis.engine import ProgramIndex as _PI  # noqa: E402

CENTER_REL = proto.CENTER_PATH
MEMBERSHIP_REL = proto.MEMBERSHIP_PATH


def _write_at(tmp_path, rel, code):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(code)
    return rel


def _protocol_lint(tmp_path, only, rels):
    return core.run_lint(str(tmp_path), paths=list(rels), only=[only])


WIRECONTRACT_GOOD = '''
class CenterServer:
    def start(self):
        center = self.center
        dedup = self.dedup

        class Handler:
            def _dispatch(self, header, body):
                op = header.get("op")
                tok = header.get("tok")
                if op == "push":
                    wire.send_msg(self.request, {"ok": True})
                elif op == "pull":
                    wire.send_msg(self.request, {"ok": True}, body)
                else:
                    wire.send_msg(self.request,
                                  {"ok": False, "error": "?"})


class RemoteCenter:
    def _roundtrip(self, header, body=b""):
        return self._wire.request(header, body)

    def push(self, body):
        self._roundtrip({"op": "push"}, body)

    def pull(self):
        resp, body = self._roundtrip({"op": "pull"})
        return body
'''

WIRECONTRACT_BAD = WIRECONTRACT_GOOD + '''

class Extra(RemoteCenter):
    def poke(self):
        self._roundtrip({"op": "poke"})
'''


def test_wire_contract_good_fixture(tmp_path):
    rel = _write_at(tmp_path, CENTER_REL, WIRECONTRACT_GOOD)
    assert _protocol_lint(tmp_path, "wire-contract", [rel]) == []


def test_wire_contract_client_op_without_handler(tmp_path):
    rel = _write_at(tmp_path, CENTER_REL, WIRECONTRACT_BAD)
    found = _protocol_lint(tmp_path, "wire-contract", [rel])
    # Extra subclasses RemoteCenter, so its sends are NOT in the
    # declared RemoteCenter scope — move the send in to see it
    assert found == [], [f.render() for f in found]
    bad = WIRECONTRACT_GOOD.replace(
        '    def pull(self):',
        '    def poke(self):\n'
        '        self._roundtrip({"op": "poke"})\n\n'
        '    def pull(self):')
    rel = _write_at(tmp_path, CENTER_REL, bad)
    found = _protocol_lint(tmp_path, "wire-contract", [rel])
    assert len(found) == 1 and "no handler arm" in found[0].message \
        and "'poke'" in found[0].message, [f.render() for f in found]


def test_wire_contract_dead_handler_arm(tmp_path):
    bad = WIRECONTRACT_GOOD.replace(
        'elif op == "pull":',
        'elif op == "purge":\n'
        '                    wire.send_msg(self.request, {"ok": True})\n'
        '                elif op == "pull":')
    rel = _write_at(tmp_path, CENTER_REL, bad)
    found = _protocol_lint(tmp_path, "wire-contract", [rel])
    assert len(found) == 1 and "no in-repo client ever sends" in \
        found[0].message and "'purge'" in found[0].message, \
        [f.render() for f in found]


def test_wire_contract_retry_on_success_is_incoherent(tmp_path):
    bad = WIRECONTRACT_GOOD.replace(
        'wire.send_msg(self.request, {"ok": True}, body)',
        'wire.send_msg(self.request, '
        '{"ok": True, "retry": True}, body)')
    rel = _write_at(tmp_path, CENTER_REL, bad)
    found = _protocol_lint(tmp_path, "wire-contract", [rel])
    assert len(found) == 1 and "retry=true without ok=false" in \
        found[0].message, [f.render() for f in found]


def test_wire_contract_client_reads_unset_reply_field(tmp_path):
    bad = WIRECONTRACT_GOOD.replace(
        "        return body", '        return resp.get("shard")')
    rel = _write_at(tmp_path, CENTER_REL, bad)
    found = _protocol_lint(tmp_path, "wire-contract", [rel])
    assert len(found) == 1 and "reads reply field 'shard'" in \
        found[0].message, [f.render() for f in found]


def test_wire_contract_dynamic_reply_suppresses_read_diff(tmp_path):
    """A ``**``-splat reply can set anything — the read diff must not
    guess against it."""
    bad = WIRECONTRACT_GOOD.replace(
        "        return body", '        return resp.get("shard")'
    ).replace(
        'wire.send_msg(self.request, {"ok": True}, body)',
        'wire.send_msg(self.request, '
        '{"ok": True, **center.stats()}, body)')
    rel = _write_at(tmp_path, CENTER_REL, bad)
    found = _protocol_lint(tmp_path, "wire-contract", [rel])
    assert found == [], [f.render() for f in found]


STATUSZ_FAMILY = {
    proto.TRACING_PATH: '''
class Handler:
    def handle(self):
        header, _ = w.recv_msg(self.request)
        op = header.get("op")
        if op == "health":
            w.send_msg(self.request, {"ok": True})
        elif op == "events":
            w.send_msg(self.request, {"ok": True, "events": []})
        elif op == "flight":
            w.send_msg(self.request, {"ok": True, "path": None})


def statusz_query(addr, op="health", n=16):
    return {}
''',
    proto.FLEETMON_PATH: '''
class Handler:
    def _dispatch(self, header, body):
        op = header.get("op")
        if op == "metrics":
            wire.send_msg(self.request, {"ok": True})
        elif op == "alerts":
            wire.send_msg(self.request, {"ok": True, "alerts": []})


class MetricStreamer:
    def push(self):
        header = {"op": "metrics"}
        self.client.request(header, b"")
''',
    proto.FLEETZ_PATH: '''
from theanompi_tpu.utils import tracing


def probe(addr):
    tracing.statusz_query(addr, "health")
    tracing.statusz_query(addr, "events")
    tracing.statusz_query(addr, "flight")
    tracing.statusz_query(addr, "alerts")
''',
}


def test_wire_contract_statusz_family_pooled(tmp_path):
    rels = [_write_at(tmp_path, rel, code)
            for rel, code in STATUSZ_FAMILY.items()]
    found = core.run_lint(str(tmp_path), paths=rels,
                          only=["wire-contract"])
    assert found == [], [f.render() for f in found]
    # an op the dialer sends that NO statusz-compatible endpoint handles
    bad = STATUSZ_FAMILY[proto.FLEETZ_PATH] + \
        '\n\ndef bad(addr):\n    tracing.statusz_query(addr, "bogus")\n'
    _write_at(tmp_path, proto.FLEETZ_PATH, bad)
    found = core.run_lint(str(tmp_path), paths=rels,
                          only=["wire-contract"])
    assert len(found) == 1 and "statusz_query sends op 'bogus'" in \
        found[0].message, [f.render() for f in found]


RETRY_GOOD = '''
class CenterServer:
    def start(self):
        center = self.center
        dedup = self.dedup

        class Handler:
            def _dispatch(self, header, body):
                op = header.get("op")
                tok = header.get("tok")
                if op == "push":
                    dup, cached = dedup.check(tok, op)
                    if dup:
                        wire.send_msg(self.request,
                                      {"ok": True, "dedup": True})
                        return
                    try:
                        center.n_updates += 1
                        dedup.record(tok, op, {"ok": True})
                    except Exception:
                        dedup.release(tok, op)
                        raise
                    wire.send_msg(self.request, {"ok": True})
                elif op == "pull":
                    wire.send_msg(self.request, {"ok": True}, body)
'''

RETRY_BAD = RETRY_GOOD.replace(
    "                    dup, cached = dedup.check(tok, op)\n"
    "                    if dup:\n"
    "                        wire.send_msg(self.request,\n"
    "                                      {\"ok\": True, \"dedup\": True})\n"
    "                        return\n", "")


def test_retry_safety_claimed_mutation_is_clean(tmp_path):
    rel = _write_at(tmp_path, CENTER_REL, RETRY_GOOD)
    assert _protocol_lint(tmp_path, "retry-safety", [rel]) == []


def test_retry_safety_unclaimed_mutation_is_flagged(tmp_path):
    rel = _write_at(tmp_path, CENTER_REL, RETRY_BAD)
    found = _protocol_lint(tmp_path, "retry-safety", [rel])
    assert len(found) == 1, [f.render() for f in found]
    assert "writes `center.n_updates`" in found[0].message
    assert "at-most-once" in found[0].message


def test_retry_safety_nonterminating_dup_arm_is_not_a_claim(tmp_path):
    """A dup arm that falls through to the mutation reapplies it — the
    claim only dominates when the duplicate path exits."""
    bad = RETRY_GOOD.replace(
        "                        wire.send_msg(self.request,\n"
        "                                      {\"ok\": True, \"dedup\": True})\n"
        "                        return\n",
        "                        pass\n")
    rel = _write_at(tmp_path, CENTER_REL, bad)
    found = _protocol_lint(tmp_path, "retry-safety", [rel])
    assert len(found) == 1 and "writes `center.n_updates`" in \
        found[0].message, [f.render() for f in found]


def test_retry_safety_mutating_method_via_lattice(tmp_path):
    """A handler calling a state-class method that mutates (directly or
    through a same-class call) is a mutation site — the §21 lattice."""
    state = '''
class ElasticCenter:
    def __init__(self):
        self.n_updates = 0

    def _bump(self):
        self.n_updates += 1

    def apply(self, body):
        self._bump()

    def read(self):
        return self.n_updates
'''
    srv = RETRY_GOOD.replace("center.n_updates += 1",
                             "center.apply(body)")
    srv_bad = RETRY_BAD.replace("center.n_updates += 1",
                                "center.apply(body)")
    rel_state = _write_at(tmp_path, proto.ASYNC_EASGD_PATH, state)
    rel = _write_at(tmp_path, CENTER_REL, srv)
    assert core.run_lint(str(tmp_path), paths=[rel, rel_state],
                         only=["retry-safety"]) == []
    rel = _write_at(tmp_path, CENTER_REL, srv_bad)
    found = core.run_lint(str(tmp_path), paths=[rel, rel_state],
                          only=["retry-safety"])
    assert len(found) == 1 and "calls mutating `center.apply`" in \
        found[0].message, [f.render() for f in found]
    # read-only calls never flag, claimed or not
    srv_read = RETRY_BAD.replace("center.n_updates += 1",
                                 "x = center.read()")
    rel = _write_at(tmp_path, CENTER_REL, srv_read)
    assert core.run_lint(str(tmp_path), paths=[rel, rel_state],
                         only=["retry-safety"]) == []


def test_retry_safety_idempotent_op_exempt(tmp_path):
    """An op declared idempotent-by-algebra (init/demote/readmit) may
    mutate unclaimed."""
    srv = RETRY_BAD.replace('if op == "push":', 'if op == "demote":')
    rel = _write_at(tmp_path, CENTER_REL, srv)
    assert _protocol_lint(tmp_path, "retry-safety", [rel]) == [], \
        [f.render() for f in _protocol_lint(tmp_path, "retry-safety",
                                            [rel])]


SM_GOOD = '''
MEMBERSHIP_EVENTS = ("worker_join", "worker_leave", "worker_demote")
CENTER_EVENTS = ("center_down", "center_restored")


class Reactor:
    def on_join(self, worker, info):
        pass

    def on_leave(self, worker, info):
        pass

    def on_demote(self, worker, info):
        pass

    def on_readmit(self, worker, info):
        pass


class LogReactor(Reactor):
    def on_join(self, worker, info):
        pass

    def on_leave(self, worker, info):
        pass

    def on_demote(self, worker, info):
        pass

    def on_readmit(self, worker, info):
        pass


class MembershipController:
    def _emit(self, event, worker, hook, **info):
        self.transitions.append((event, worker, info))

    def join(self, worker):
        st = self.workers[worker]
        st["status"] = "live"
        self._emit("worker_join", worker, "on_join")

    def leave(self, worker, reason="exit"):
        st = self.workers[worker]
        st["status"] = "left" if reason == "finished" else "dead"
        self._emit("worker_leave", worker, "on_leave")

    def demote(self, worker):
        st = self.workers[worker]
        st["status"] = "demoted"
        self._emit("worker_demote", worker, "on_demote")
'''


def test_state_machine_good_fixture(tmp_path):
    rel = _write_at(tmp_path, MEMBERSHIP_REL, SM_GOOD)
    assert _protocol_lint(tmp_path, "state-machine", [rel]) == []


def test_state_machine_transition_without_event(tmp_path):
    bad = SM_GOOD.replace(
        '        st["status"] = "demoted"\n'
        '        self._emit("worker_demote", worker, "on_demote")\n',
        '        st["status"] = "demoted"\n')
    rel = _write_at(tmp_path, MEMBERSHIP_REL, bad)
    found = _protocol_lint(tmp_path, "state-machine", [rel])
    msgs = [f.message for f in found]
    assert any("without emitting its declared 'worker_demote'" in m
               for m in msgs), msgs
    assert any("'worker_demote' is never emitted" in m for m in msgs), \
        msgs


def test_state_machine_reactor_missing_hook(tmp_path):
    bad = SM_GOOD.replace(
        "class LogReactor(Reactor):\n"
        "    def on_join(self, worker, info):\n"
        "        pass\n\n"
        "    def on_leave(self, worker, info):\n"
        "        pass\n\n"
        "    def on_demote(self, worker, info):\n"
        "        pass\n",
        "class LogReactor(Reactor):\n"
        "    def on_join(self, worker, info):\n"
        "        pass\n\n"
        "    def on_leave(self, worker, info):\n"
        "        pass\n")
    rel = _write_at(tmp_path, MEMBERSHIP_REL, bad)
    found = _protocol_lint(tmp_path, "state-machine", [rel])
    assert len(found) == 1 and "neither handles nor explicitly " \
        "ignores `on_demote`" in found[0].message, \
        [f.render() for f in found]


def test_state_machine_event_outside_vocabulary(tmp_path):
    bad = SM_GOOD.replace('self._emit("worker_demote", worker',
                          'self._emit("worker_demotedz", worker')
    rel = _write_at(tmp_path, MEMBERSHIP_REL, bad)
    found = _protocol_lint(tmp_path, "state-machine", [rel])
    msgs = [f.message for f in found]
    assert any("outside the declared MEMBERSHIP_EVENTS" in m
               for m in msgs), msgs


def test_state_machine_header_version_guard(tmp_path):
    good = '''
class Handler:
    def _dispatch(self, header, body):
        op = header.get("op")
        trc = header.get("trace")
        island = header["island"]
'''
    rel = _write_at(tmp_path, CENTER_REL, good)
    assert _protocol_lint(tmp_path, "state-machine", [rel]) == []
    bad = good.replace('header.get("trace")', 'header["trace"]') \
              .replace('header["island"]', 'header.get("shard")')
    rel = _write_at(tmp_path, CENTER_REL, bad)
    found = _protocol_lint(tmp_path, "state-machine", [rel])
    msgs = sorted(f.message for f in found)
    assert len(found) == 2, msgs
    assert any("undeclared wire-header field 'shard'" in m
               for m in msgs), msgs
    assert any("subscript-reads v2-optional header field 'trace'" in m
               for m in msgs), msgs


# -- op-table extraction units on a synthetic pair ---------------------------

SYN_SERVER = '''
OP_C = "c"


class Srv:
    def handle(self, header, body):
        op = header.get("op")
        if op == "a":
            pass
        elif op in ("b", "a"):
            pass
        elif op == OP_C:
            pass
'''

SYN_CLIENT = '''
class Cli:
    def send_a(self):
        self.wire.request({"op": "a"})

    def send_b(self):
        header = {"op": "b"}
        self.wire.request(header)

    def send_dynamic(self, op):
        self.wire.request({"op": op})      # not statically evaluable
'''


def _syn_index(tmp_path):
    (tmp_path / "srv.py").write_text(SYN_SERVER)
    (tmp_path / "cli.py").write_text(SYN_CLIENT)
    files = [core.SourceFile(str(tmp_path), "srv.py"),
             core.SourceFile(str(tmp_path), "cli.py")]
    return _PI(files)


def test_protocol_op_table_extraction(tmp_path):
    index = _syn_index(tmp_path)
    spec = proto.EndpointSpec(
        name="syn", server_path="srv.py", dispatch="Srv.handle",
        clients=(proto.ClientSurface("cli.py", "Cli", ("request",)),))
    table = proto.server_op_table(index, spec)
    assert set(table) == {"a", "b", "c"}        # eq, membership, const
    ctab = proto.client_op_table(index, spec)
    assert set(ctab) == {"a", "b"}              # inline + local header
    assert all(s.path == "cli.py" for sites in ctab.values()
               for s in sites)


def test_protocol_dispatch_missing_is_reported(tmp_path):
    """Renaming the dispatch function must fail loudly, not blind the
    checker."""
    rel = _write_at(tmp_path, CENTER_REL,
                    WIRECONTRACT_GOOD.replace("_dispatch", "_route"))
    found = _protocol_lint(tmp_path, "wire-contract", [rel])
    assert len(found) == 1 and "protocol model" in found[0].message \
        and "out of date" in found[0].message, \
        [f.render() for f in found]


def test_protocol_mutation_lattice(tmp_path):
    (tmp_path / "state.py").write_text('''
class State:
    def __init__(self):
        self.n = 0
        self.items = {}

    def read(self):
        return self.n

    def peek(self, k):
        return self.items.get(k)

    def bump(self):
        self.n += 1

    def bump_twice(self):
        self.bump()

    def stash(self, k, v):
        self.items[k] = v

    def retire(self, k):
        self.items.pop(k)
''')
    index = _PI([core.SourceFile(str(tmp_path), "state.py")])
    mut = proto.mutating_methods(index, ("state.State",))
    assert mut == {"__init__", "bump", "bump_twice", "stash", "retire"}


def test_protocol_fold_op_test(tmp_path):
    import ast as _ast
    (tmp_path / "m.py").write_text("X = 'c'\n")
    sf = core.SourceFile(str(tmp_path), "m.py")
    index = _PI([sf])

    def fold(src, value):
        test = _ast.parse(src, mode="eval").body
        return proto.fold_op_test(test, {"op"}, value, sf, index)

    assert fold('op == "a"', "a") is True
    assert fold('op == "a"', "b") is False
    assert fold('op in ("a", "b")', "b") is True
    assert fold('op not in ("a", "b")', "b") is False
    assert fold('op == "a" and leaves is None', "b") is False
    assert fold('op == "a" and leaves is None', "a") is None
    assert fold('op == X', "c") is True
    assert fold('other == "a"', "a") is None


# -- the three live injections (ISSUE 15 acceptance) -------------------------

def _check_baseline_cli(root, *paths):
    return subprocess.run(
        [sys.executable, LINT, "--root", str(root), "--check-baseline",
         *paths], capture_output=True, text=True, timeout=300)


def test_injection_removed_center_handler_arm(tmp_path):
    rel = _inject(tmp_path, CENTER_REL,
                  'elif op == "readmit":', 'elif op == "readmitz":')
    r = _check_baseline_cli(tmp_path, rel)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "no handler arm" in r.stdout and "'readmit'" in r.stdout
    assert "no in-repo client ever sends" in r.stdout     # the dead twin


def test_injection_unclaimed_mutating_handler_path(tmp_path):
    import shutil
    # the mutation lattice needs the state class in scope, exactly as
    # the repo-wide gate has it
    dst = tmp_path / proto.ASYNC_EASGD_PATH
    dst.parent.mkdir(parents=True, exist_ok=True)
    shutil.copy(os.path.join(REPO, proto.ASYNC_EASGD_PATH), dst)
    rel = _inject(tmp_path, CENTER_REL,
                  "dup, cached = dedup.check(tok, op)",
                  "dup, cached = False, None")
    r = _check_baseline_cli(tmp_path, "theanompi_tpu")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "retry-safety" in r.stdout
    assert "without a dominating DedupWindow claim check" in r.stdout
    assert "push_delta_leaves" in r.stdout
    assert "push_pull_leaves" in r.stdout


def test_injection_transition_without_event(tmp_path):
    rel = _inject(
        tmp_path, MEMBERSHIP_REL,
        '        self._emit("worker_demote", worker, "on_demote",\n'
        '                   reason=reason, **info)\n',
        '')
    r = _check_baseline_cli(tmp_path, rel)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "state-machine" in r.stdout
    assert "without emitting its declared 'worker_demote'" in r.stdout


# -- cache-key sensitivity + json fingerprints for the new checkers ----------

def test_protocol_findings_cache_and_fingerprints(tmp_path):
    """Protocol findings are engine-scoped: cached at tree level,
    reproduced bit-identically on a warm hit, invalidated by a
    server-file edit, and fingerprinted in --format json."""
    bad = WIRECONTRACT_GOOD.replace(
        '    def pull(self):',
        '    def poke(self):\n'
        '        self._roundtrip({"op": "poke"})\n\n'
        '    def pull(self):')
    rel = _write_at(tmp_path, CENTER_REL, bad)
    cold = _lint_cli(tmp_path, rel, "--only", "wire-contract",
                     "--format", "json")
    c = json.loads(cold.stdout)
    assert c["cache"] == "miss" and cold.returncode == 1
    assert len(c["findings"]) == 1
    fp = c["findings"][0]["fingerprint"]
    assert len(fp) == 12 and int(fp, 16) >= 0
    warm = _lint_cli(tmp_path, rel, "--only", "wire-contract",
                     "--format", "json")
    w = json.loads(warm.stdout)
    assert w["cache"] == "hit" and w["findings"] == c["findings"]
    # fixing the server invalidates the tree entry
    _write_at(tmp_path, CENTER_REL, WIRECONTRACT_GOOD)
    fixed = _lint_cli(tmp_path, rel, "--only", "wire-contract",
                      "--format", "json")
    f = json.loads(fixed.stdout)
    assert f["cache"] == "miss" and f["findings"] == []
    # checker selection keys the cache: a different --only over the
    # same tree is its own entry, not a stale hit of the first
    other = _lint_cli(tmp_path, rel, "--only", "retry-safety",
                      "--format", "json")
    assert json.loads(other.stdout)["cache"] == "miss"


def test_protocol_group_alias():
    r = subprocess.run(
        [sys.executable, LINT, "--only", "protocol", "--check-baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr


# -- --diff mode -------------------------------------------------------------

def _git(cwd, *args):
    return subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=str(cwd), capture_output=True, text=True, timeout=60)


def test_diff_mode(tmp_path):
    assert _git(tmp_path, "init", "-q").returncode == 0
    d = tmp_path / "theanompi_tpu"
    d.mkdir()
    (d / "x.py").write_text("x = 1\n")
    (tmp_path / "outside.py").write_text("import time\n")
    _git(tmp_path, "add", "-A")
    assert _git(tmp_path, "commit", "-qm", "init").returncode == 0

    # nothing changed: exits 0 without linting anything
    r = _lint_cli(tmp_path, "--diff", "HEAD")
    assert r.returncode == 0 and "no changed python files" in r.stdout

    # a worktree edit introducing a finding is seen
    (d / "x.py").write_text(RNG_BAD)
    (tmp_path / "outside.py").write_text("import os\n")   # out of scope
    r = _lint_cli(tmp_path, "--diff", "HEAD", "--format", "json")
    out = json.loads(r.stdout)
    assert r.returncode == 1
    assert {f["path"] for f in out["findings"]} == \
        {"theanompi_tpu/x.py"}

    # CACHED = the staged index vs HEAD
    r = _lint_cli(tmp_path, "--diff", "CACHED")
    assert r.returncode == 0 and "no changed python files" in r.stdout
    _git(tmp_path, "add", "-A")
    r = _lint_cli(tmp_path, "--diff", "CACHED")
    assert r.returncode == 1

    # guard rails
    r = _lint_cli(tmp_path, "--diff", "HEAD", "theanompi_tpu/x.py")
    assert r.returncode == 2 and "mutually exclusive" in r.stderr
    r = _lint_cli(tmp_path, "--diff", "NOSUCHREF")
    assert r.returncode == 2
    r = _lint_cli(tmp_path, "--diff", "HEAD", "--update-baseline")
    assert r.returncode == 2 and "--diff" in r.stderr
    # ...and the refusal must hold on an EMPTY changeset too — the
    # early exit 0 must not read as "baseline updated" to automation
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "sync")
    r = _lint_cli(tmp_path, "--diff", "HEAD", "--update-baseline")
    assert r.returncode == 2 and "--diff" in r.stderr


def test_retry_safety_direct_self_attr_mutation(tmp_path):
    """A mutation spelled through the server attr itself
    (``self.center.x`` / ``outer.center.x``) is the same mutation as
    through a closure alias."""
    src = '''
class CenterServer:
    def start(self):
        dedup = self.dedup
        outer = self

        class Handler:
            def _dispatch(self, header, body):
                op = header.get("op")
                tok = header.get("tok")
                if op == "push":
                    outer.center.n_updates += 1
                    dedup.record(tok, op, {"ok": True})
'''
    rel = _write_at(tmp_path, CENTER_REL, src)
    found = _protocol_lint(tmp_path, "retry-safety", [rel])
    assert len(found) == 1 and \
        "writes `outer.center.n_updates`" in found[0].message, \
        [f.render() for f in found]


def test_retry_safety_renamed_self_capture_still_seen(tmp_path):
    """The self-capture alias is DERIVED, not hardcoded: renaming
    ``outer = self`` must not blind the direct-write detection
    (review finding, round 19)."""
    src = '''
class CenterServer:
    def start(self):
        dedup = self.dedup
        srv = self

        class Handler:
            def _dispatch(self, header, body):
                op = header.get("op")
                tok = header.get("tok")
                if op == "push":
                    srv.center.n_updates += 1
                    dedup.record(tok, op, {"ok": True})
'''
    rel = _write_at(tmp_path, CENTER_REL, src)
    found = _protocol_lint(tmp_path, "retry-safety", [rel])
    assert len(found) == 1 and \
        "writes `srv.center.n_updates`" in found[0].message, \
        [f.render() for f in found]


def test_wire_contract_unrelated_dict_does_not_mask_read_diff(tmp_path):
    """A constant-key store into a dict that never reaches a reply must
    not launder its key into the emitted set (review finding: the
    unset-reply-field diff would be silently masked)."""
    bad = WIRECONTRACT_GOOD.replace(
        "        return body", '        return resp.get("shard")'
    ).replace(
        'wire.send_msg(self.request, {"ok": True}, body)',
        'info = {}\n'
        '                    info["shard"] = 1\n'
        '                    wire.send_msg(self.request, '
        '{"ok": True}, body)')
    rel = _write_at(tmp_path, CENTER_REL, bad)
    found = _protocol_lint(tmp_path, "wire-contract", [rel])
    assert len(found) == 1 and "reads reply field 'shard'" in \
        found[0].message, [f.render() for f in found]


def test_schema_drift_probes_stay_jax_free():
    """The live probes — including the §21 probe that drives a real
    RemoteCenter against a stubbed wire — must never drag jax into the
    lint process.  Pinned with the cache OFF: on a warm tree hit the
    probes never run, so the cached variant of this contract
    (test_cli_runs_clean_without_jax) can mask a probe regression —
    exactly how the round-19 `import jax`-before-roundtrip bug in
    RemoteCenter.pull slipped through a green gate."""
    env = dict(os.environ, TPULINT_ASSERT_NO_JAX="1")
    proc = subprocess.run(
        [sys.executable, LINT, "--only", "schema-drift", "--no-cache"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# oracle-pair (ops/ Pallas kernels must keep registered, tested jnp oracles)
# ---------------------------------------------------------------------------

ORACLE_MOD_GOOD = '''
from jax.experimental import pallas as pl


def thing_jnp(x):
    return x + 1


def _thing_pallas(x):
    return pl.pallas_call(lambda i, o: None)(x)


PALLAS_ORACLES = {"_thing_pallas": "thing_jnp"}
'''

ORACLE_TEST_GOOD = '''
def test_thing_pallas_matches_oracle():
    assert _thing_pallas is not thing_jnp
'''


def _oracle_lint(tmp_path, mod_code, test_code=ORACLE_TEST_GOOD):
    from theanompi_tpu.analysis.checkers import oracle_pair
    ops = tmp_path / "theanompi_tpu" / "ops"
    ops.mkdir(parents=True)
    (ops / "mymod.py").write_text(mod_code)
    tdir = tmp_path / "tests"
    tdir.mkdir()
    (tdir / "test_mymod.py").write_text(test_code)
    return oracle_pair.oracle_pair_findings(str(tmp_path))


def test_oracle_pair_good_fixture(tmp_path):
    assert _oracle_lint(tmp_path, ORACLE_MOD_GOOD) == []


def test_oracle_pair_missing_registry(tmp_path):
    bad = ORACLE_MOD_GOOD.replace(
        'PALLAS_ORACLES = {"_thing_pallas": "thing_jnp"}', "")
    found = _oracle_lint(tmp_path, bad)
    assert len(found) == 1 and "declares no pure-literal" in \
        found[0].message, [f.render() for f in found]


def test_oracle_pair_unregistered_wrapper_and_stale_entry(tmp_path):
    # registry names a ghost wrapper while the real one goes unregistered:
    # both directions of drift must surface
    bad = ORACLE_MOD_GOOD.replace('{"_thing_pallas": "thing_jnp"}',
                                  '{"_gone_pallas": "thing_jnp"}')
    found = _oracle_lint(tmp_path, bad)
    msgs = " | ".join(f.message for f in found)
    assert len(found) == 2, [f.render() for f in found]
    assert "`_thing_pallas` has no PALLAS_ORACLES entry" in msgs
    assert "stale registry entry" in msgs


def test_oracle_pair_oracle_not_defined(tmp_path):
    bad = ORACLE_MOD_GOOD.replace('"thing_jnp"}', '"missing_jnp"}')
    found = _oracle_lint(tmp_path, bad)
    assert len(found) == 1 and "not defined in this module" in \
        found[0].message, [f.render() for f in found]


def test_oracle_pair_untested_pair(tmp_path):
    # the test file references only the wrapper, never the oracle — the
    # equality contract is unpinned even though both names exist
    found = _oracle_lint(tmp_path, ORACLE_MOD_GOOD,
                         "def test_x():\n    return _thing_pallas\n")
    assert len(found) == 1 and "no tests/ file references both" in \
        found[0].message, [f.render() for f in found]


def test_oracle_pair_repo_is_clean_and_jax_free():
    """The real ops/ tree must pass (every kernel paired + tested), and
    the probe itself must never import jax — it runs inside the lint
    CLI's backend-free process."""
    env = dict(os.environ, TPULINT_ASSERT_NO_JAX="1")
    proc = subprocess.run(
        [sys.executable, LINT, "--only", "oracle-pair", "--no-cache"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# compile-surface discipline (PR 20): cache-key / retrace-hazard /
# dtype-flow
# ---------------------------------------------------------------------------

CACHEKEY_BAD = """
import jax.numpy as jnp

def build_train_step(model):
    warm = int(model.config.get("scan_warm_steps", 0) or 0)
    tbl = jnp.arange(warm + 1)
    return tbl
"""

CACHEKEY_GOOD = """
import jax.numpy as jnp

def build_train_step(model):
    n = int(model.config.get("n_subb", 1))
    tbl = jnp.arange(n + 1)
    probe = int(model.config.get("host_probe_rows", 0))  # tpulint: disable=cache-key
    tbl2 = jnp.arange(probe + 1)
    return tbl, tbl2
"""


def test_cache_key_bad_fixture(tmp_path):
    """An uncovered knob flowing into a shape slot inside an AOT surface
    is exactly one finding, anchored at the read."""
    found = lint_snippet(tmp_path, "bad.py", CACHEKEY_BAD, "cache-key")
    assert len(found) == 1, [f.render() for f in found]
    m = found[0].message
    assert "'scan_warm_steps'" in m and "build_train_step" in m
    assert "key_extra stamp" in m and "only-when-on" in m
    assert found[0].check == "cache-key"


def test_cache_key_good_fixture(tmp_path):
    """A STAMP_KNOBS-covered knob and a disable-comment exemption both
    stay silent."""
    assert lint_snippet(tmp_path, "good.py", CACHEKEY_GOOD,
                        "cache-key") == []


KEYEXTRA_UNGUARDED = """
def key_extra(fn, model=None, spc=None):
    extra = {"fn": str(fn)}
    extra["spc"] = spc
    return extra
"""

KEYEXTRA_GUARDED = """
def key_extra(fn, model=None, spc=None):
    extra = {"fn": str(fn)}
    if spc is not None:
        extra["spc"] = int(spc)
    return extra
"""


def test_cache_key_unguarded_stamp(tmp_path):
    """Every stamp except `fn` must sit under a guard (only-when-on):
    an unconditional stamp churns every pre-existing cache key."""
    found = lint_snippet(tmp_path, "ke.py", KEYEXTRA_UNGUARDED,
                         "cache-key")
    assert len(found) == 1, [f.render() for f in found]
    assert "stamp 'spc' is unconditional" in found[0].message


def test_cache_key_guarded_stamp(tmp_path):
    assert lint_snippet(tmp_path, "ke.py", KEYEXTRA_GUARDED,
                        "cache-key") == []


def test_cache_key_non_literal_stamp_key(tmp_path):
    code = KEYEXTRA_GUARDED.replace('extra["spc"]', 'extra[name]')
    found = lint_snippet(tmp_path, "ke.py", code, "cache-key")
    assert len(found) == 1 and "non-literal key_extra stamp key" in \
        found[0].message, [f.render() for f in found]


RETRACE_BAD = """
import time
import jax
import jax.numpy as jnp

def step(x):
    return x * 2

def install(cache, key):
    probe = jax.jit(lambda s: s)
    fns = []
    for i in range(4):
        fns.append(jax.jit(step))
    compiled = cache.get_or_compile(key, step)
    lowered = compiled.lower()
    return probe, fns, lowered

def shaped(x, n):
    return x + jnp.arange(n)

run = jax.jit(shaped)

def build_train_step(model):
    return jnp.arange(int(time.time()) % 128)
"""

RETRACE_GOOD = """
import jax
import jax.numpy as jnp

def step(x):
    return x * 2

_jitted_step = jax.jit(step)

def shaped(x, n):
    return x + jnp.arange(n)

run = jax.jit(shaped, static_argnums=(1,))

def install(cache, key):
    compiled = cache.get_or_compile(key, step)
    return compiled
"""


def test_retrace_hazard_bad_fixture(tmp_path):
    """All five hazard classes fire on one file: fresh lambda identity,
    jit-in-loop, .lower() on an installed Compiled, a non-static shape
    param, and a host clock feeding shape arithmetic."""
    found = lint_snippet(tmp_path, "bad.py", RETRACE_BAD,
                         "retrace-hazard")
    msgs = [f.message for f in found]
    assert len(found) == 5, msgs
    assert any("fresh lambda at a jax.jit boundary" in m for m in msgs)
    assert any("jax.jit called inside a loop" in m for m in msgs)
    assert any("`.lower()` on `compiled`" in m and "PR 3" in m
               for m in msgs)
    assert any("spends parameter `n` in a shape-static slot" in m
               for m in msgs)
    assert any("host value `time.time()` feeds shape arithmetic" in m
               for m in msgs)
    assert all(f.check == "retrace-hazard" for f in found)


def test_retrace_hazard_good_fixture(tmp_path):
    """Hoisted defs, static_argnums coverage, loop-free jit, and a
    get_or_compile result left alone are all silent."""
    assert lint_snippet(tmp_path, "good.py", RETRACE_GOOD,
                        "retrace-hazard") == []


def test_retrace_hazard_partial_decorator(tmp_path):
    """@functools.partial(jax.jit, static_argnums=...) boundaries get
    the same static-name credit as direct @jax.jit."""
    code = (
        "import functools\n"
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@functools.partial(jax.jit, static_argnums=(1,))\n"
        "def good(x, n):\n"
        "    return x + jnp.arange(n)\n"
        "@jax.jit\n"
        "def bad(x, n):\n"
        "    return x + jnp.arange(n)\n")
    found = lint_snippet(tmp_path, "x.py", code, "retrace-hazard")
    assert len(found) == 1, [f.render() for f in found]
    assert "`bad` spends parameter `n`" in found[0].message


DTYPE_BAD = """
import jax.numpy as jnp
from jax import lax

def all_reduce(g, axis, bias):
    total = lax.psum(g.astype(jnp.bfloat16), axis) + bias
    r = lax.psum(g.astype(jnp.bfloat16), axis)
    out = r + bias
    return total, out

def bucketed(packed, axis):
    flat = packed.astype(jnp.bfloat16)
    outs = [lax.psum(b, axis) for b in flat]
    return outs

def roundtrip(g, wd):
    return g.astype(wd).astype(jnp.float32)
"""

DTYPE_GOOD = """
import jax.numpy as jnp
from jax import lax

NONBITEXACT = {
    "wire_round": "owned chunk rounds to the wire dtype so every rank "
                  "holds the identical bit pattern",
}

def all_reduce(g, axis, bias):
    total = lax.psum(g.astype(jnp.bfloat16), axis).astype(g.dtype) + bias
    r = lax.psum(g.astype(jnp.bfloat16), axis)
    r = r.astype(jnp.float32)
    return total, r + bias

def bucketed(buckets, axis):
    outs = [lax.psum(b.astype(jnp.bfloat16), axis).astype(jnp.float32)
            for b in buckets]
    return outs

def wire_round(g, wd):
    return g.astype(wd).astype(jnp.float32)
"""


def test_dtype_flow_bad_fixture(tmp_path):
    """Direct low-precision accumulate, accumulate through a local,
    pre-bucket wire cast, and an unregistered round-trip all fire."""
    found = lint_snippet(tmp_path, "bad.py", DTYPE_BAD, "dtype-flow")
    msgs = [f.message for f in found]
    assert len(found) == 4, msgs
    assert any("bfloat16 collective result accumulated via `+`" in m
               for m in msgs)
    assert any("`r` accumulated via `+`" in m for m in msgs)
    assert any("wire-cast BEFORE bucketing" in m for m in msgs)
    assert any("round-trip in `roundtrip`" in m and "NONBITEXACT" in m
               for m in msgs)
    assert all(f.check == "dtype-flow" for f in found)


def test_dtype_flow_good_fixture(tmp_path):
    """Immediate re-upcast, per-bucket casts, and a registered
    round-trip are the blessed shapes — zero findings."""
    assert lint_snippet(tmp_path, "good.py", DTYPE_GOOD,
                        "dtype-flow") == []


def test_dtype_flow_stale_registry_entry(tmp_path):
    """Renaming the registry key breaks both directions at once: the
    real chain goes unregistered AND the ghost entry goes stale."""
    code = DTYPE_GOOD.replace('"wire_round":', '"ghost_site":')
    found = lint_snippet(tmp_path, "m.py", code, "dtype-flow")
    msgs = " | ".join(f.message for f in found)
    assert len(found) == 2, [f.render() for f in found]
    assert "round-trip in `wire_round`" in msgs
    assert "stale NONBITEXACT entry 'ghost_site'" in msgs


def test_dtype_flow_registry_must_be_literal(tmp_path):
    code = 'NONBITEXACT = dict(x="y")\n'
    found = lint_snippet(tmp_path, "m.py", code, "dtype-flow")
    assert len(found) == 1 and "pure literal" in found[0].message, \
        [f.render() for f in found]


# -- the three real-file injections, through the CLI gate -------------------

def _gate(tmp_path):
    return _lint_cli(tmp_path, "--check-baseline", "--no-cache")


def test_injection_unstamped_knob_in_steps_cli(tmp_path):
    """A config knob feeding jnp.arange inside build_train_step fails
    the baseline gate (rc 1) and the revert restores rc 0."""
    rel = _inject(
        tmp_path, "theanompi_tpu/parallel/steps.py",
        '    n_subb = getattr(model, "n_subb", 1)\n',
        '    n_subb = getattr(model, "n_subb", 1)\n'
        '    warm = int(model.config.get("scan_warm_steps", 0) or 0)\n'
        '    _warm_tbl = jnp.arange(warm + 1)\n')
    bad = _gate(tmp_path)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "scan_warm_steps" in bad.stdout
    assert "cache-key" in bad.stdout
    (tmp_path / rel).write_text(
        open(os.path.join(REPO, rel)).read())
    good = _gate(tmp_path)
    assert good.returncode == 0, good.stdout + good.stderr


def test_injection_fresh_lambda_in_model_base_cli(tmp_path):
    rel = _inject(
        tmp_path, "theanompi_tpu/models/model_base.py",
        "        from ..parallel.exchanger import BSP_Exchanger\n",
        "        from ..parallel.exchanger import BSP_Exchanger\n"
        "        probe = jax.jit(lambda s: s)\n")
    bad = _gate(tmp_path)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "fresh lambda at a jax.jit boundary" in bad.stdout
    assert "retrace-hazard" in bad.stdout
    (tmp_path / rel).write_text(
        open(os.path.join(REPO, rel)).read())
    good = _gate(tmp_path)
    assert good.returncode == 0, good.stdout + good.stderr


def test_injection_low_precision_accumulate_in_strategies_cli(tmp_path):
    rel = "theanompi_tpu/parallel/strategies.py"
    src = open(os.path.join(REPO, rel)).read()
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(src + "\n\ndef _injected_total(g, axis):\n"
                 "    return lax.psum(g.astype(jnp.bfloat16), axis)"
                 " + 1.0\n")
    bad = _gate(tmp_path)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "accumulated via `+`" in bad.stdout
    assert "dtype-flow" in bad.stdout
    p.write_text(src)
    good = _gate(tmp_path)
    assert good.returncode == 0, good.stdout + good.stderr


# -- disk_scoped + result-cache sensitivity ---------------------------------

def test_disk_scoped_is_a_checker_attribute():
    """The partial-run disk probes are declared per checker (one
    attribute the runner folds in), not a CLI carve-out list."""
    from theanompi_tpu.analysis.checkers.compile_surface import \
        COMPILE_CACHE_PATH
    from theanompi_tpu.analysis.core import CHECKERS, Checker
    assert Checker.disk_scoped == ()
    assert CHECKERS["cache-key"].disk_scoped == (COMPILE_CACHE_PATH,)
    assert COMPILE_CACHE_PATH in CHECKERS["schema-drift"].disk_scoped
    assert any("*" in pat
               for pat in CHECKERS["oracle-pair"].disk_scoped)


def test_cache_key_result_cache_tracks_compile_cache(tmp_path):
    """disk_scoped keys the result cache: a cached --only cache-key run
    over steps.py alone is invalidated by an edit to compile_cache.py,
    which the checker reads from disk for the stamp vocabulary."""
    import shutil
    for rel in ("theanompi_tpu/parallel/steps.py",
                "theanompi_tpu/utils/compile_cache.py"):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(REPO, rel), p)
    rel = "theanompi_tpu/parallel/steps.py"
    cold = _lint_cli(tmp_path, rel, "--only", "cache-key",
                     "--format", "json")
    assert json.loads(cold.stdout)["cache"] == "miss"
    warm = _lint_cli(tmp_path, rel, "--only", "cache-key",
                     "--format", "json")
    w = json.loads(warm.stdout)
    assert w["cache"] == "hit"
    assert w["findings"] == json.loads(cold.stdout)["findings"]
    cc = tmp_path / "theanompi_tpu" / "utils" / "compile_cache.py"
    cc.write_text(cc.read_text() + "\n# vocabulary touched\n")
    edited = _lint_cli(tmp_path, rel, "--only", "cache-key",
                       "--format", "json")
    assert json.loads(edited.stdout)["cache"] == "miss"


# -- group alias, warm cache, jax-free --------------------------------------

def test_compile_surface_group_alias():
    r = subprocess.run(
        [sys.executable, LINT, "--only", "compile-surface",
         "--check-baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr


def test_only_compile_surface_repo_warm_cache_subsecond():
    """Acceptance gate: a warm-cache whole-repo run of just the
    compile-surface group stays sub-second (modulo interpreter
    startup) and finding-identical to the cold run."""
    import time as _time
    cold = subprocess.run(
        [sys.executable, LINT, "--only", "compile-surface", "--format",
         "json"], cwd=REPO, capture_output=True, text=True, timeout=300)
    t0 = _time.monotonic()
    warm = subprocess.run(
        [sys.executable, LINT, "--only", "compile-surface", "--format",
         "json"], cwd=REPO, capture_output=True, text=True, timeout=300)
    elapsed = _time.monotonic() - t0
    w, c = json.loads(warm.stdout), json.loads(cold.stdout)
    assert w["cache"] == "hit"
    assert w["findings"] == c["findings"]
    assert elapsed < 2.5, f"warm compile-surface lint took {elapsed:.2f}s"


def test_compile_surface_stays_jax_free():
    env = dict(os.environ, TPULINT_ASSERT_NO_JAX="1")
    proc = subprocess.run(
        [sys.executable, LINT, "--only", "compile-surface", "--no-cache"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- key_extra byte-stability + schema-drift live probe ---------------------

def test_key_extra_byte_stability():
    """§26 floor pinned directly: a knob-less config's extras are frozen
    by this PR — every pre-existing cache key stays byte-stable."""
    from theanompi_tpu.utils import compile_cache as cc
    saved = os.environ.pop("THEANOMPI_TPU_NO_PALLAS", None)
    try:
        assert cc.key_extra("val") == {"fn": "val"}

        class _Bare:
            config = {}

        assert cc.key_extra("train", model=_Bare()) == {
            "fn": "train", "model": "_Bare", "n_subb": 1}
    finally:
        if saved is not None:
            os.environ["THEANOMPI_TPU_NO_PALLAS"] = saved


def test_key_extra_schema_probe_clean_on_repo():
    assert sd.key_extra_schema_errors() == []


def test_key_extra_schema_probe_ignores_ambient_no_pallas():
    """The probe pins THEANOMPI_TPU_NO_PALLAS itself — a host process
    that happens to export it (bench control rows do) must not flip the
    verdict, which the result cache would then store."""
    saved = os.environ.get("THEANOMPI_TPU_NO_PALLAS")
    os.environ["THEANOMPI_TPU_NO_PALLAS"] = "1"
    try:
        assert sd.key_extra_schema_errors() == []
        assert os.environ.get("THEANOMPI_TPU_NO_PALLAS") == "1", \
            "the probe must restore the ambient value"
    finally:
        if saved is None:
            os.environ.pop("THEANOMPI_TPU_NO_PALLAS", None)
        else:
            os.environ["THEANOMPI_TPU_NO_PALLAS"] = saved


def test_key_extra_schema_probe_catches_drift():
    """A stamping path that drifts from the static vocabulary (or the
    byte-stability floor) trips all three probe checks."""

    class _Drifted:
        @staticmethod
        def key_extra(fn, model=None, exchanger=None, spc=None):
            return {"fn": str(fn), "surprise": 1}

    errs = sd.key_extra_schema_errors(compile_cache_mod=_Drifted)
    msgs = " | ".join(m for _p, m in errs)
    assert len(errs) == 3, errs
    assert "extraction rules drifted" in msgs
    assert "STAMP_KNOBS" in msgs
    assert "byte-stable" in msgs


def test_key_extra_schema_probe_catches_backend_dependence():
    class _Raising:
        @staticmethod
        def key_extra(fn, model=None, exchanger=None, spc=None):
            raise RuntimeError("needs a backend")

    errs = sd.key_extra_schema_errors(compile_cache_mod=_Raising)
    assert len(errs) == 1 and "callable" in errs[0][1], errs


# -- SARIF emitter ----------------------------------------------------------

def test_sarif_format_findings(tmp_path):
    (tmp_path / "bad.py").write_text(DTYPE_BAD)
    r = _lint_cli(tmp_path, "bad.py", "--only", "dtype-flow",
                  "--format", "sarif")
    assert r.returncode == 1, r.stdout + r.stderr
    log = json.loads(r.stdout)
    assert log["version"] == "2.1.0"
    run = log["runs"][0]
    assert run["tool"]["driver"]["name"] == "tpulint"
    assert [ru["id"] for ru in run["tool"]["driver"]["rules"]] == \
        ["dtype-flow"]
    results = run["results"]
    assert len(results) == 4, results
    for res in results:
        assert res["ruleId"] == "dtype-flow"
        assert res["level"] == "error"
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "bad.py"
        assert loc["region"]["startLine"] >= 1
        assert loc["region"]["startColumn"] >= 1
        fp = res["partialFingerprints"]["tpulintFingerprint/v1"]
        assert len(fp) == 12 and int(fp, 16) >= 0


def test_sarif_format_clean_tree(tmp_path):
    (tmp_path / "ok.py").write_text("x = 1\n")
    r = _lint_cli(tmp_path, "ok.py", "--format", "sarif")
    assert r.returncode == 0, r.stdout + r.stderr
    assert json.loads(r.stdout)["runs"][0]["results"] == []


# -- explain_program --diff key_extra ---------------------------------------

EXPLAIN = os.path.join(REPO, "scripts", "explain_program.py")


def _explain_diff(tmp_path, a, b):
    return subprocess.run(
        [sys.executable, EXPLAIN, str(tmp_path), "--diff", a, b],
        capture_output=True, text=True, timeout=60)


def test_explain_program_diff_names_the_knob(tmp_path):
    """The structured key_extra diff names WHICH stamp split the key,
    with the checker's one-line meaning — and degrades honestly for
    pre-extras entries and identical stamp dicts."""
    entry = {"label": "train:Net:spc1", "platform": "tpu", "created": 1,
             "compile_secs": 1.0, "bytes": 10, "cost": {"flops": 1.0},
             "extra": {"fn": "train", "model": "Net", "n_subb": 1,
                       "spc": 1}}
    import copy
    b = copy.deepcopy(entry)
    b["label"], b["created"], b["extra"]["spc"] = "train:Net:spc4", 2, 4
    old = {"label": "old", "platform": "tpu", "created": 0,
           "compile_secs": 1.0, "bytes": 10, "cost": {}}
    (tmp_path / "manifest.json").write_text(json.dumps(
        {"aaaa1111": entry, "bbbb2222": b, "cccc3333": old}))

    r = _explain_diff(tmp_path, "aaaa", "bbbb")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "key_extra:" in r.stdout
    assert "spc" in r.stdout and "1 -> 4" in r.stdout
    assert "fused steps per compiled call" in r.stdout

    r2 = _explain_diff(tmp_path, "aaaa", "cccc")
    assert r2.returncode == 0
    assert "predate the extras manifest" in r2.stdout

    r3 = _explain_diff(tmp_path, "aaaa1111", "aaaa1111")
    assert r3.returncode == 0
    assert "identical — the key split came from the traced program" in \
        r3.stdout
