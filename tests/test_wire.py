"""The resilient RPC wire layer (parallel/wire.py, docs/design.md §15):
framing taxonomy, CRC/version integrity, client retry/reconnect/give-up,
the server dedup window's exactly-once contract, and center snapshot
crash recovery."""

import json
import socket
import struct
import threading
import time

import numpy as np
import pytest

from theanompi_tpu.parallel import wire
from theanompi_tpu.parallel.center_server import (CenterServer,
                                                  RemoteCenter,
                                                  snapshot_path)
from theanompi_tpu.parallel.membership import Backoff
from theanompi_tpu.utils import telemetry


def _tm():
    return telemetry.Telemetry(rank=0, run_id="wire-test")


def _fast_client(addr, **kw):
    kw.setdefault("op_timeout_s", 2.0)
    kw.setdefault("connect_timeout_s", 1.0)
    kw.setdefault("max_retries", 6)
    kw.setdefault("deadline_s", 20.0)
    kw.setdefault("backoff", Backoff(base=0.05, cap=0.3))
    return wire.WireClient(addr, **kw)


# -- framing -----------------------------------------------------------------

def test_framing_roundtrip_and_crc_detection():
    a, b = socket.socketpair()
    try:
        body = b"x" * 1000
        wire.send_msg(a, {"op": "probe", "n": 3}, body)
        header, got = wire.recv_msg(b)
        assert header["op"] == "probe" and header["n"] == 3
        assert got == body and header["v"] == wire.WIRE_VERSION
        # corrupt ONE body byte in flight: the body CRC must catch it
        # (header CRC intact → stream provably aligned → retryable)
        wire.send_msg(a, {"op": "probe"}, body)
        hl = wire.recv_exact(b, 4, at_boundary=True)
        hcrc = wire.recv_exact(b, 4)
        hb = wire.recv_exact(b, struct.unpack("!I", hl)[0])
        bl = wire.recv_exact(b, 4)
        raw = bytearray(wire.recv_exact(b, struct.unpack("!I", bl)[0]))
        raw[500] ^= 0xFF
        c, d = socket.socketpair()
        try:
            c.sendall(hl + hcrc + hb + bl + bytes(raw))
            with pytest.raises(wire.CorruptPayload, match="CRC"):
                wire.recv_msg(d)
        finally:
            c.close()
            d.close()
        # corrupt a HEADER byte: FramingError (drop, don't reuse) — a
        # header flip is indistinguishable from a length flip
        wire.send_msg(a, {"op": "probe"}, b"")
        hl = wire.recv_exact(b, 4, at_boundary=True)
        hcrc = wire.recv_exact(b, 4)
        hb = bytearray(wire.recv_exact(b, struct.unpack("!I", hl)[0]))
        bl = wire.recv_exact(b, 4)
        hb[2] ^= 0xFF
        c, d = socket.socketpair()
        try:
            c.sendall(hl + hcrc + bytes(hb) + bl)
            with pytest.raises(wire.FramingError, match="header CRC"):
                wire.recv_msg(d)
        finally:
            c.close()
            d.close()
    finally:
        a.close()
        b.close()


def test_clean_close_vs_mid_message_truncation():
    """The old code raised one ConnectionError for both; the client must
    be able to tell 'peer left between requests' (retry freely) from
    'payload lost mid-flight'."""
    a, b = socket.socketpair()
    a.close()                               # clean close at a boundary
    with pytest.raises(wire.ConnectionClosed):
        wire.recv_msg(b)
    b.close()

    a, b = socket.socketpair()
    hb = json.dumps({"op": "x", "v": wire.WIRE_VERSION}).encode()
    a.sendall(struct.pack("!I", len(hb)) + hb[: len(hb) // 2])
    a.close()                               # died mid-header
    with pytest.raises(wire.TruncatedMessage, match="mid-message"):
        wire.recv_msg(b)
    b.close()
    # and the subclass relationship keeps legacy handlers working
    assert issubclass(wire.ConnectionClosed, ConnectionError)
    assert issubclass(wire.TruncatedMessage, ConnectionError)


def test_version_mismatch_fails_loudly_with_both_versions():
    a, b = socket.socketpair()
    try:
        a.sendall(wire.encode_frame({"op": "x", "v": 999999}))
        with pytest.raises(wire.VersionMismatch) as ei:
            wire.recv_msg(b)
        msg = str(ei.value)
        assert "999999" in msg and str(wire.WIRE_VERSION) in msg
    finally:
        a.close()
        b.close()


def test_server_replies_version_mismatch_with_both_versions():
    """A mismatched CLIENT gets an error reply naming both versions — not
    a silent close, not a misparse."""
    srv = CenterServer(alpha=0.5)
    host, port = srv.start()
    try:
        s = socket.create_connection((host, port), timeout=5)
        s.sendall(wire.encode_frame({"op": "stats", "v": 0}))
        header, _ = wire.recv_msg(s)
        assert header["ok"] is False
        assert "v0" in header["error"] and \
            f"v{wire.WIRE_VERSION}" in header["error"]
        s.close()
    finally:
        srv.stop()


# -- client resilience -------------------------------------------------------

class _FlakyServer(threading.Thread):
    """Accepts connections; drops the first ``drop_conns`` connections
    after reading one frame (no reply), then serves ``stats`` forever."""

    def __init__(self, drop_conns=1, stall_first=False):
        super().__init__(daemon=True)
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.addr = "127.0.0.1:%d" % self.sock.getsockname()[1]
        self.drop_conns = drop_conns
        self.stall_first = stall_first
        self.requests = 0
        self._halt = threading.Event()

    def run(self):
        conns = 0
        while not self._halt.is_set():
            try:
                c, _ = self.sock.accept()
            except OSError:
                return
            conns += 1
            try:
                while True:
                    header, _ = wire.recv_msg(c)
                    self.requests += 1
                    if conns <= self.drop_conns:
                        if self.stall_first:
                            time.sleep(5.0)     # force a client op timeout
                        c.close()               # reply lost / conn dropped
                        break
                    wire.send_msg(c, {"ok": True, "echo": header.get("op")})
            except (ConnectionError, OSError):
                pass

    def stop(self):
        self._halt.set()
        try:
            self.sock.close()
        except OSError:
            pass


def test_client_reconnects_and_retries_through_dropped_connection():
    srv = _FlakyServer(drop_conns=1)
    srv.start()
    tm = _tm()
    try:
        client = _fast_client(srv.addr, client_id="w9", telemetry_=tm)
        resp, _ = client.request({"op": "stats"})
        assert resp["ok"] and resp["echo"] == "stats"
        assert tm.counters.get("wire.retry", 0) >= 1
        assert tm.counters.get("wire.reconnect", 0) >= 1
        # the heal recorded an outage gauge + rtt sample
        assert "wire.outage_s" in tm.gauges
        assert tm.hists["wire.rtt"].count >= 1
        client.close()
    finally:
        srv.stop()


def test_client_times_out_and_gives_up_with_clear_error():
    srv = _FlakyServer(drop_conns=99, stall_first=True)
    srv.start()
    tm = _tm()
    try:
        client = _fast_client(srv.addr, client_id="w9", telemetry_=tm,
                              op_timeout_s=0.3, max_retries=1,
                              deadline_s=2.0)
        with pytest.raises(wire.WireGiveUp) as ei:
            client.request({"op": "pull"})
        msg = str(ei.value)
        assert "gave up" in msg and "'pull'" in msg and "attempts" in msg
        assert tm.counters.get("wire.timeout", 0) >= 1
        assert tm.counters.get("wire.giveup", 0) == 1
        evs = [e for e in tm.tail(8) if e["ev"] == wire.WIRE_EVENT]
        assert evs and evs[-1]["kind"] == "giveup"
        client.close()
    finally:
        srv.stop()


def test_client_gives_up_fast_on_dead_address():
    """The satellite contract: a dead center at spawn time must produce a
    bounded, DIAGNOSABLE give-up — not a hang."""
    tm = _tm()
    client = wire.WireClient("127.0.0.1:9", client_id="w1",
                             connect_timeout_s=0.2, op_timeout_s=0.2,
                             max_retries=2, deadline_s=1.5,
                             backoff=Backoff(base=0.02, cap=0.05),
                             telemetry_=tm)
    t0 = time.time()
    with pytest.raises(wire.WireGiveUp, match="unreachable"):
        client.request({"op": "pull"})
    assert time.time() - t0 < 10.0
    assert tm.counters.get("wire.giveup", 0) == 1


# -- dedup window ------------------------------------------------------------

def test_dedup_window_claim_record_release_and_hwm():
    win = wire.DedupWindow(depth=4, telemetry_=telemetry.DISABLED)
    tok = {"w": "w1", "seq": 0}
    fresh, _ = win.check(tok, "push")
    assert fresh is False                     # fresh = not duplicate
    dup, cached = win.check(tok, "push")      # in-flight twin
    assert dup and cached is wire.INFLIGHT    # busy, NOT an ack
    win.record(tok, "push", {"ok": True}, b"r")
    dup, cached = win.check(tok, "push")
    assert dup and cached == ({"ok": True}, b"r")
    # release withdraws an UNrecorded claim only
    tok2 = {"w": "w1", "seq": 1}
    win.check(tok2, "push")
    win.release(tok2, "push")
    fresh2, _ = win.check(tok2, "push")
    assert fresh2 is False                    # claimable again
    win.record(tok2, "push", {"ok": True})
    # below-HWM tokens evicted from the window still dedup (synthesized)
    for seq in range(2, 10):
        t = {"w": "w1", "seq": seq}
        win.check(t, "push")
        win.record(t, "push", {"ok": True})
    dup_old, cached_old = win.check({"w": "w1", "seq": 0}, "push")
    assert dup_old and cached_old is None
    # snapshots persist APPLIED tokens, never in-flight claims
    win.check({"w": "w1", "seq": 99}, "push")      # claim, not recorded
    snap = win.snapshot()
    assert ["push", 99] not in snap["tokens"]["w1"]
    win2 = wire.DedupWindow(telemetry_=telemetry.DISABLED)
    win2.restore(snap)
    dup_r, cached_r = win2.check({"w": "w1", "seq": 9}, "push")
    assert dup_r and cached_r is not None and cached_r[1] is None
    fresh_r, _ = win2.check({"w": "w1", "seq": 99}, "push")
    assert fresh_r is False                   # the claim did not persist


def test_dedup_window_width_stress_eviction_and_hwm_roundtrip():
    """The 1,000-client width contract (round 17): per-client windows
    stay depth-bounded under seq churn (memory is O(clients × depth),
    not O(ops)), HWMs stay exact after eviction, and a snapshot/restore
    round-trip preserves BOTH the recognized-replay semantics and the
    fresh-push semantics for every client."""
    depth = 8
    win = wire.DedupWindow(depth=depth, telemetry_=telemetry.DISABLED)
    n_clients, seqs_per = 1000, 40
    for seq in range(seqs_per):                  # interleaved churn
        for c in range(n_clients):
            tok = {"w": f"w{c}", "seq": seq}
            dup, _ = win.check(tok, "push")
            assert not dup, (c, seq)
            win.record(tok, "push", {"ok": True})
    # bounded memory: every client's window holds exactly `depth` tokens
    assert len(win._seen) == n_clients
    assert all(len(w) == depth for w in win._seen.values())
    # HWMs exact for every client despite eviction of 32/40 tokens
    assert win.hwm_snapshot() == {f"w{c}": seqs_per - 1
                                  for c in range(n_clients)}
    # evicted-but-below-HWM replays still dedup (synthesized reply)...
    dup, cached = win.check({"w": "w500", "seq": 0}, "push")
    assert dup and cached is None
    # ...and cached-window replays return their recorded reply
    dup, cached = win.check({"w": "w500", "seq": seqs_per - 1}, "push")
    assert dup and cached == ({"ok": True}, b"")
    hits_before = win.hits
    # snapshot/restore round-trip at width
    win2 = wire.DedupWindow(depth=depth, telemetry_=telemetry.DISABLED)
    win2.restore(win.snapshot())
    assert win2.hwm_snapshot() == win.hwm_snapshot()
    assert win2.hits == hits_before
    for c in (0, 499, 999):
        dup, _ = win2.check({"w": f"w{c}", "seq": 0}, "push")      # old
        assert dup
        dup, _ = win2.check({"w": f"w{c}", "seq": seqs_per - 1},
                            "push")                                # cached
        assert dup
        fresh, _ = win2.check({"w": f"w{c}", "seq": seqs_per + 7},
                              "push")                              # fresh
        assert fresh is False
        win2.record({"w": f"w{c}", "seq": seqs_per + 7}, "push",
                    {"ok": True})
        assert win2.hwm_snapshot()[f"w{c}"] == seqs_per + 7


def _raw_push(sock, island, seq, leaves, w="w1", op="push"):
    wire.send_msg(sock, {"op": op, "island": island,
                         "tok": {"w": w, "seq": seq}},
                  wire.pack_leaves(leaves))


def test_duplicated_push_applied_exactly_once_by_server():
    """THE dedup-window contract (satellite): the same framed push sent
    twice (a retry whose original actually landed, or a chaos-proxy
    duplicate) moves the center ONCE; the duplicate gets a valid reply."""
    srv = CenterServer(alpha=0.5)
    host, port = srv.start()
    try:
        boot = RemoteCenter(f"{host}:{port}", alpha=0.5, client_id="boot")
        boot.ensure_init({"w": np.ones(3, np.float32)})
        s = socket.create_connection((host, port), timeout=5)
        delta = [np.full(3, 2.0, np.float32)]
        _raw_push(s, island=1, seq=0, leaves=delta)
        h1, _ = wire.recv_msg(s)
        _raw_push(s, island=1, seq=0, leaves=delta)     # the duplicate
        h2, _ = wire.recv_msg(s)
        assert h1["ok"] and h2["ok"]
        after = boot.pull_leaves()[0]
        np.testing.assert_allclose(after, 2.0)          # 1 + 0.5·2, ONCE
        st = boot.stats()
        assert st["n_updates"] == 1
        assert st["dedup_hits"] == 1
        # push_pull: duplicate reply still carries a full center body
        _raw_push(s, island=1, seq=1, leaves=delta, op="push_pull")
        wire.recv_msg(s)
        _raw_push(s, island=1, seq=1, leaves=delta, op="push_pull")
        hd, body = wire.recv_msg(s)
        assert hd["ok"]
        np.testing.assert_allclose(wire.unpack_leaves(body)[0], 4.0)
        assert boot.stats()["n_updates"] == 2
        s.close()
    finally:
        srv.stop()


def test_framing_error_on_corrupted_length_prefix():
    """A blown length prefix means the STREAM is desynced — FramingError,
    not CorruptPayload: the connection must be dropped, not reused."""
    a, b = socket.socketpair()
    try:
        a.sendall(struct.pack("!I", 0xFFFFFFFF))      # 4 GiB header?!
        with pytest.raises(wire.FramingError, match="desynced"):
            wire.recv_msg(b)
    finally:
        a.close()
        b.close()
    a, b = socket.socketpair()
    try:
        import zlib
        hb = json.dumps({"op": "x", "v": wire.WIRE_VERSION}).encode()
        a.sendall(struct.pack("!I", len(hb))
                  + struct.pack("!I", zlib.crc32(hb) & 0xFFFFFFFF) + hb
                  + struct.pack("!I", 0xFFFFFFF0))    # huge body length
        with pytest.raises(wire.FramingError, match="desynced"):
            wire.recv_msg(b)
        # the body bound is LIVE for u32 values (a 4<<30 bound never was)
        assert 0xFFFFFFF0 > wire._MAX_BODY
    finally:
        a.close()
        b.close()


def test_uninitialized_center_is_structured_and_recoverable():
    """A respawned center with no usable snapshot must answer pull/push
    with a STRUCTURED uninit verdict the clients can recover from by
    re-seeding — not an opaque assertion repr that crashes every
    worker into the world restart the design forbids."""
    srv = CenterServer(alpha=0.5)
    host, port = srv.start()
    try:
        c = RemoteCenter(f"{host}:{port}", alpha=0.5, client_id="w1")
        with pytest.raises(wire.CenterUninitialized, match="re-seed"):
            c.pull_leaves()
        with pytest.raises(wire.CenterUninitialized):
            c.push_delta({"w": np.ones(3, np.float32)}, island=1)
        c.ensure_init({"w": np.ones(3, np.float32)})   # the recovery
        c.push_delta({"w": np.full(3, 2.0, np.float32)}, island=1)
        assert c.stats()["n_updates"] == 1
        c.close()
    finally:
        srv.stop()


def test_island_reseeds_after_snapshotless_center_restart():
    """The cascade fix end to end: the center dies BEFORE any snapshot
    landed and comes back empty; the island re-seeds the consensus from
    its own params and keeps training — no worker death, no restart."""
    from tests.conftest import TinyModel
    from theanompi_tpu.parallel.async_easgd import AsyncEASGDTrainer

    def factory(cfg):
        cfg = dict(cfg)
        cfg["verbose"] = False
        cfg.setdefault("batch_size", 8)
        return TinyModel(cfg)

    srv = CenterServer(alpha=0.5)              # NO snapshot dir
    host, port = srv.start()
    tr = AsyncEASGDTrainer(factory, {
        "async_islands": 1, "sync_freq": 1, "seed": 3, "batch_size": 8,
        "center_addr": f"{host}:{port}", "wire_timeout": 0.5,
        "wire_retries": 2, "wire_deadline": 1.0})
    srv2 = None
    try:
        tr.start()
        isl = tr.islands[0]
        deadline = time.time() + 180
        while isl.exchanges_done < 1 and time.time() < deadline:
            assert isl.error is None, isl.error
            time.sleep(0.05)
        assert isl.exchanges_done >= 1
        srv.stop()                             # killed, nothing persisted
        srv2 = CenterServer(alpha=0.5)
        srv2.start(host, port)                 # fresh, SAME port, EMPTY
        e0 = isl.exchanges_done
        while isl.exchanges_done < e0 + 2 and time.time() < deadline:
            assert isl.error is None, isl.error
            time.sleep(0.05)
        tr.stop_and_join(timeout=120)
        assert isl.error is None               # no crash, no cascade
        assert isl.exchanges_done >= e0 + 2    # training continued
        assert isl.exchanges_skipped >= 1      # the uninit hit is counted
        assert srv2.center.n_updates >= 2      # re-seeded center absorbed
    finally:
        if srv2 is not None:
            srv2.stop()
        srv.stop()


# -- server hygiene ----------------------------------------------------------

def test_server_idle_timeout_frees_wedged_handler():
    """A client that connects and goes silent (SIGSTOP, wedge) must not
    pin a handler thread forever — the server closes it at the idle
    timeout while healthy clients keep being served."""
    srv = CenterServer(alpha=0.5, idle_timeout_s=0.4)
    host, port = srv.start()
    try:
        wedged = socket.create_connection((host, port), timeout=5)
        wedged.settimeout(3.0)
        assert wedged.recv(1) == b""          # server hung up on idle
        wedged.close()
        healthy = RemoteCenter(f"{host}:{port}", alpha=0.5, client_id="h")
        healthy.ensure_init({"w": np.zeros(2, np.float32)})
        assert healthy.stats()["n_updates"] == 0
        healthy.close()
    finally:
        srv.stop()


def test_server_corrupt_request_gets_retryable_error_reply():
    srv = CenterServer(alpha=0.5)
    host, port = srv.start()
    try:
        s = socket.create_connection((host, port), timeout=5)
        body = wire.pack_leaves([np.ones(3, np.float32)])
        s.sendall(wire.encode_frame(
            {"op": "init", "v": wire.WIRE_VERSION,
             "crc": 12345},                      # wrong on purpose
            body))
        header, _ = wire.recv_msg(s)
        assert header["ok"] is False and header.get("retry") is True
        # the connection stayed aligned: a good request still works
        wire.send_msg(s, {"op": "stats"})
        header, _ = wire.recv_msg(s)
        assert header["ok"] is True
        s.close()
    finally:
        srv.stop()


# -- center snapshot / crash recovery ----------------------------------------

def test_center_snapshot_restore_roundtrip_with_dedup(tmp_path):
    """Kill-and-restore: params, counters, membership, AND the dedup
    high-water marks survive — a client retrying a push that landed
    before the crash is answered from the window, not reapplied."""
    d = str(tmp_path)
    srv = CenterServer(alpha=0.5, snapshot_dir=d)
    host, port = srv.start()
    client = RemoteCenter(f"{host}:{port}", alpha=0.5, client_id="boot")
    client.ensure_init({"w": np.ones(3, np.float32)})
    # the push whose token must survive the crash goes RAW with a known
    # seq (WireClient seqs are clock-seeded per incarnation)
    s = socket.create_connection((host, port), timeout=5)
    push_seq = 1000
    _raw_push(s, island=1, seq=push_seq,
              leaves=[np.full(3, 2.0, np.float32)])
    h, _ = wire.recv_msg(s)
    assert h["ok"]
    s.close()
    client.demote_island(7)
    srv.stop(final_snapshot=True)             # ≙ SIGTERM'd center
    assert snapshot_path(d)

    srv2 = CenterServer(alpha=0.5, snapshot_dir=d)
    assert srv2.restore() is True
    host2, port2 = srv2.start()
    try:
        c2 = RemoteCenter(f"{host2}:{port2}", alpha=0.5, client_id="w2")
        st = c2.stats()
        assert st["n_updates"] == 1
        assert st["demoted"] == [7]
        np.testing.assert_allclose(c2.pull_leaves()[0], 2.0)
        # replay the pre-crash push token: must be deduped, not reapplied
        s = socket.create_connection((host2, port2), timeout=5)
        _raw_push(s, island=1, seq=push_seq,
                  leaves=[np.full(3, 2.0, np.float32)])
        h, _ = wire.recv_msg(s)
        assert h["ok"]
        assert c2.stats()["n_updates"] == 1          # NOT reapplied
        assert c2.stats()["dedup_hits"] >= 1
        # a NEW incarnation of the same client id (clock-seeded seq) is
        # NOT deduped — the regression a 0-seeded seq would reintroduce
        c1b = RemoteCenter(f"{host2}:{port2}", alpha=0.5, client_id="w1")
        c1b.push_delta({"w": np.full(3, 2.0, np.float32)}, island=1)
        assert c2.stats()["n_updates"] == 2
        c1b.close()
        s.close()
        c2.close()
    finally:
        srv2.stop()


def test_remote_center_rides_out_center_restart(tmp_path):
    """The crash-recovery story end to end in-process: the center dies
    mid-run, a new one restores from its snapshot on the SAME port, and
    the client's next op succeeds through retries — no caller-visible
    error, exactly-once bookkeeping intact."""
    d = str(tmp_path)
    srv = CenterServer(alpha=0.5, snapshot_dir=d)
    host, port = srv.start()
    tm = _tm()
    client = RemoteCenter(f"{host}:{port}", alpha=0.5, client_id="w1",
                          op_timeout_s=1.0, max_retries=10,
                          deadline_s=30.0, telemetry_=tm)
    client.ensure_init({"w": np.ones(3, np.float32)})
    client.push_delta({"w": np.full(3, 2.0, np.float32)}, island=1)
    srv.stop(final_snapshot=True)

    def _revive():
        time.sleep(1.0)
        srv2 = CenterServer(alpha=0.5, snapshot_dir=d)
        assert srv2.restore()
        srv2.start(host, port)                # the SAME fixed port
        _revive.srv = srv2

    t = threading.Thread(target=_revive, daemon=True)
    t.start()
    client.push_delta({"w": np.full(3, 2.0, np.float32)}, island=1)
    t.join()
    try:
        st = client.stats()
        assert st["n_updates"] == 2                   # both pushes, once
        np.testing.assert_allclose(client.pull_leaves()[0], 3.0)
        assert tm.counters.get("wire.retry", 0) >= 1
        assert tm.gauges.get("wire.outage_s", 0) > 0
        client.close()
    finally:
        _revive.srv.stop()


# -- round 15: locked HWM reads + serve-thread join ---------------------------

def test_dedup_hwm_snapshot_is_a_locked_copy():
    """hwm_snapshot is the one sanctioned cross-thread read of seq_hwm
    (tpulint shared-state-race fix): it returns a copy — and survives a
    writer hammering the window concurrently, where an unlocked dict()
    over the live mapping can raise mid-iteration."""
    win = wire.DedupWindow(depth=8)
    tok = {"w": "c0", "seq": 1}
    win.check(tok, "push")
    win.record(tok, "push", {"ok": True})
    snap = win.hwm_snapshot()
    assert snap == {"c0": 1}
    snap["c0"] = 999                      # mutating the copy is inert
    assert win.hwm_snapshot() == {"c0": 1}

    halt = threading.Event()

    def hammer():
        seq = 2
        while not halt.is_set():
            t = {"w": f"c{seq % 17}", "seq": seq}
            win.check(t, "push")
            win.record(t, "push", {"ok": True})
            seq += 1

    t = threading.Thread(target=hammer, daemon=True)
    t.start()
    try:
        t0 = time.time()
        while time.time() - t0 < 0.5:
            s = win.hwm_snapshot()        # must never raise
            assert all(isinstance(v, int) for v in s.values())
    finally:
        halt.set()
        t.join(timeout=5)


def test_center_server_stop_joins_serve_thread():
    """stop() bounded-joins the serve thread (tpulint daemon-discipline
    fix): a stop immediately followed by a same-port restart must not
    race a still-unwinding serve loop."""
    srv = CenterServer(alpha=0.5)
    srv.start("127.0.0.1", 0)
    t = srv._thread
    assert t is not None and t.is_alive()
    srv.stop()
    assert not t.is_alive()
    assert srv._thread is None
