"""Global-norm gradient clipping (config grad_clip) — exchanger-level, so
every rule gets it; pinned against a hand-computed clipped step."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tests.conftest import TinyModel
from theanompi_tpu.parallel import steps
from theanompi_tpu.parallel.exchanger import BSP_Exchanger, get_exchanger


def _one_step(zero_clip, clip, mesh):
    cfg = {"mesh": mesh, "size": 4, "rank": 0, "verbose": False,
           "optimizer": "sgd", "learning_rate": 1.0, "weight_decay": 0.0}
    if not zero_clip:
        cfg["grad_clip"] = clip
    m = TinyModel(cfg)
    m.compile_iter_fns(BSP_Exchanger(m.config))
    m.data.shuffle_data(0)
    p0 = steps.unbox(jax.device_get(m.step_state["params"]))
    m.train_iter(0, None)
    p1 = steps.unbox(jax.device_get(m.step_state["params"]))
    # with sgd lr=1 wd=0: update = -grad (possibly clipped)
    g = jax.tree.map(lambda a, b: np.asarray(a) - np.asarray(b), p0, p1)
    return g


def test_grad_clip_matches_manual_scaling(mesh4):
    g_raw = _one_step(True, None, mesh4)
    norm = float(np.sqrt(sum(np.sum(np.square(l))
                             for l in jax.tree.leaves(g_raw))))
    clip = norm / 2.0                      # force clipping at half the norm
    g_clip = _one_step(False, clip, mesh4)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(b), np.asarray(a) * 0.5, rtol=1e-5, atol=1e-7),
        g_raw, g_clip)
    # a generous threshold leaves gradients untouched
    g_loose = _one_step(False, norm * 10, mesh4)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-8),
        g_raw, g_loose)


def test_grad_clip_on_async_rule(mesh4):
    cfg = {"mesh": mesh4, "size": 4, "rank": 0, "verbose": False,
           "grad_clip": 0.5, "sync_freq": 2}
    m = TinyModel(cfg)
    exch = get_exchanger("easgd", cfg)
    m.compile_iter_fns(exch)
    m.data.shuffle_data(0)
    for i in range(4):
        m.train_iter(i, None)
        exch.exchange(None, i)
    assert np.isfinite(float(m.current_info["cost"]))


@pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="CPU venue gap: the legacy (0.4.x, check_rep=False) shard_map "
           "transposes psum as psum, inflating tp-sharded grads ~tp x "
           "(rank-partial for replicated leaves) — Adam absorbs the "
           "scale so plain tp equivalence passes, but the norm-dependent "
           "clip exposes it; needs the vma type system")
def test_grad_clip_under_tensor_parallelism(mesh8):
    """The clip norm must be the GLOBAL norm under tp (sharded leaves
    psum'd, replicated leaves counted once): tp=4 with an aggressive clip
    must trace the dense run's loss curve."""
    import jax.numpy as jnp
    from theanompi_tpu.models.transformer_lm import TransformerLM
    from theanompi_tpu.parallel.mesh import worker_mesh

    def run(tp):
        mesh = worker_mesh(2, tp=tp)
        cfg = {"mesh": mesh, "size": 2, "rank": 0, "tp": tp,
               "verbose": False, "grad_clip": 0.05,   # bites every step
               "batch_size": 8, "seq_len": 16, "vocab": 32, "d_model": 32,
               "n_head": 4, "n_layer": 2, "synthetic_train": 64,
               "compute_dtype": jnp.float32}
        m = TransformerLM(cfg)
        m.compile_iter_fns(BSP_Exchanger(cfg))
        m.data.shuffle_data(0)
        costs = []
        for i in range(5):
            m.train_iter(i, None)
            costs.append(float(m.current_info["cost"]))
        return costs

    np.testing.assert_allclose(run(4), run(1), rtol=2e-4, atol=2e-5)
