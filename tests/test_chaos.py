"""Chaos harness + the chaos acceptance gates (utils/chaos.py,
parallel/membership.py, docs/design.md §14).

Tier-1 ("not slow"): schedule/monkey unit tests against dummy processes,
the fast elastic kill-and-rejoin run, the supervised SIGKILL-mid-epoch
resume (the BSP reaction), and the crash-loop breaker.  The full
convergence-under-chaos gate is marked slow."""

import glob
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from theanompi_tpu.parallel import membership as mb
from theanompi_tpu.utils import chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- schedules ---------------------------------------------------------------

def test_parse_schedule():
    faults = chaos.parse_schedule("kill@8:1,stop@12:2:3.5,delay@3:0:0.5")
    assert [(f.kind, f.at, f.target, f.duration) for f in faults] == [
        ("delay", 3.0, 0, 0.5), ("kill", 8.0, 1, 0.0),
        ("stop", 12.0, 2, 3.5)]
    with pytest.raises(ValueError, match="bad fault entry"):
        chaos.parse_schedule("kill@oops")
    with pytest.raises(AssertionError, match="unknown fault kind"):
        chaos.parse_schedule("maim@3:0")


def test_seeded_schedule_reproducible_and_in_bounds():
    a = chaos.seeded_schedule(7, [1, 2, 3], n_faults=4, t_min=5, t_max=30,
                              kinds=("kill", "stop"))
    b = chaos.seeded_schedule(7, [1, 2, 3], n_faults=4, t_min=5, t_max=30,
                              kinds=("kill", "stop"))
    assert [repr(f) for f in a] == [repr(f) for f in b]
    assert all(5 <= f.at <= 30 and f.target in (1, 2, 3) for f in a)
    c = chaos.seeded_schedule(8, [1, 2, 3], n_faults=4)
    assert [repr(f) for f in a] != [repr(f) for f in c]


# -- the monkey --------------------------------------------------------------

def test_monkey_kill_fault_against_live_process():
    p = subprocess.Popen(["sleep", "30"])
    try:
        monkey = chaos.ChaosMonkey(chaos.parse_schedule("kill@0.1:0"),
                                   pid_of=lambda t: p.pid)
        monkey.start()
        rc = p.wait(timeout=10)
        monkey.stop()
        assert rc == -signal.SIGKILL
        assert monkey.applied and monkey.applied[0].error is None
    finally:
        if p.poll() is None:
            p.kill()


def test_monkey_stop_fault_wedges_then_releases():
    t0 = time.time()
    p = subprocess.Popen(["sleep", "0.2"])
    try:
        monkey = chaos.ChaosMonkey(chaos.parse_schedule("stop@0.05:0:0.6"),
                                   pid_of=lambda t: p.pid)
        monkey.start()
        rc = p.wait(timeout=10)
        monkey.stop()
        # SIGSTOPped for 0.6s: a 0.2s sleep cannot finish before ~0.6s
        assert rc == 0 and time.time() - t0 > 0.5
    finally:
        if p.poll() is None:
            p.kill()


def test_monkey_delay_hook_and_no_pid_drop():
    hits = []
    monkey = chaos.ChaosMonkey(
        chaos.parse_schedule("delay@0.05:3:0.7,kill@0.05:1"),
        pid_of=lambda t: None, delay_hook=lambda t, d: hits.append((t, d)),
        grace_s=0.3)
    monkey.start()
    time.sleep(1.0)
    monkey.stop()
    assert hits == [(3, 0.7)]
    killf = [f for f in monkey.schedule if f.kind == "kill"][0]
    assert killf.applied and killf.error == "no-pid"


def test_monkey_and_proxy_write_realized_schedule(tmp_path):
    """Round-17 satellite: every fault that actually LANDS (and every
    asked-but-missed drop) is appended to the realized-schedule log,
    and the log parses back into a replayable schedule."""
    path = str(tmp_path / chaos.REALIZED_SCHEDULE)
    p = subprocess.Popen(["sleep", "30"])
    try:
        monkey = chaos.ChaosMonkey(
            chaos.parse_schedule("kill@0.05:2,kill@0.1:9"),
            pid_of=lambda t: p.pid if t == 2 else None,
            grace_s=0.3, realized_path=path)
        monkey.start()
        p.wait(timeout=10)
        time.sleep(0.6)                      # let the no-pid drop resolve
        monkey.stop()
    finally:
        if p.poll() is None:
            p.kill()
    with open(path) as f:
        docs = [json.loads(ln) for ln in f if ln.strip()]
    by_target = {d["target"]: d for d in docs}
    assert by_target[2]["kind"] == "kill" and \
        by_target[2]["error"] is None and \
        by_target[2]["source"] == "monkey"
    assert by_target[9]["error"] == "no-pid"     # the miss is on record
    # a proxy window-open appends to the SAME log dialect
    proxy = chaos.ChaosProxy("127.0.0.1:1",
                             chaos.parse_schedule("net_dup@0:-1:5"),
                             realized_path=path)
    proxy._emit(proxy.schedule[0])
    sched = chaos.schedule_from_realized(path)
    # errored faults are excluded; landed ones replay at their REAL
    # relative landing time
    assert sorted((f.kind, f.target) for f in sched) == \
        [("kill", 2), ("net_dup", -1)]
    assert all(0.0 <= f.at < 5.0 for f in sched)
    dup = [f for f in sched if f.kind == "net_dup"][0]
    assert chaos.fault_window_active(sched, "net_dup", 3, dup.at + 1.0)


def test_fault_window_active_is_the_proxy_rule():
    sched = chaos.parse_schedule("net_drop@10:-1:5,net_dup@20:3:2")
    # -1 windows cover every client, incl. identity-unknown (None)
    assert chaos.fault_window_active(sched, "net_drop", None, 12.0)
    assert chaos.fault_window_active(sched, "net_drop", 7, 15.0)
    assert not chaos.fault_window_active(sched, "net_drop", 7, 15.1)
    # targeted windows cover only their worker, never None
    assert chaos.fault_window_active(sched, "net_dup", 3, 21.0)
    assert not chaos.fault_window_active(sched, "net_dup", 4, 21.0)
    assert not chaos.fault_window_active(sched, "net_dup", None, 21.0)
    # the live proxy delegates to the same rule
    proxy = chaos.ChaosProxy("127.0.0.1:1", sched, t0=0.0)
    proxy.t0 = proxy.clock.now() - 12.0          # "now" is t=12 rel
    assert proxy._active("net_drop", 7)
    assert not proxy._active("net_dup", 3)


def test_find_child_pid(tmp_path):
    p = subprocess.Popen([sys.executable, "-c",
                          "import time; time.sleep(20)"])
    try:
        found = chaos.find_child_pid(os.getpid(), "time.sleep(20)",
                                     timeout_s=10)
        assert found == p.pid
        assert chaos.find_child_pid(os.getpid(), "no-such-needle",
                                    timeout_s=0.2) is None
    finally:
        p.kill()


# -- fast elastic chaos (tier-1): kill → leave → backoff rejoin --------------

def _merged_events(record_dir):
    events = []
    for p in sorted(glob.glob(os.path.join(record_dir,
                                           "telemetry_rank*.jsonl"))):
        with open(p) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        events.append(json.loads(line))
                    except ValueError:
                        pass
    return events


def test_elastic_easgd_survives_sigkill_and_rejoins(tmp_path):
    """The fast chaos gate: SIGKILL a non-zero elastic worker mid-run; the
    EASGD run completes WITHOUT a world restart, the telemetry stream
    records the matching worker_leave/worker_join pair, and the rejoining
    worker restored from the center and contributed again."""
    record_dir = str(tmp_path)
    schedule = chaos.parse_schedule("kill@6:1")
    # two margins make the kill land mid-run whatever the box's load:
    # the monkey clock is progress-gated (run_elastic releases it only
    # once a lease reports step ≥ 1, so jit-compile time never eats the
    # window), and iter_sleep stretches the post-gate run to
    # ≥ steps·sleep ≈ 10 s — the t=6 kill sits well inside it with room
    # on both sides (≥ 1 step done before, ≥ 3 s of run left after)
    rc = mb.run_elastic(
        "easgd", "tests.conftest", "SleepyModel",
        {"sync_freq": 2, "batch_size": 8, "iter_sleep": 0.25}, 2,
        record_dir=record_dir, steps=40, host_devices=1,
        chaos_schedule=schedule, timeout_s=420,
        supervisor_kw={"poll_s": 0.2, "backoff": mb.Backoff(base=0.3),
                       "lease_timeout": 60.0})
    assert rc == 0
    assert schedule[0].error is None, "kill fault never landed"
    events = _merged_events(record_dir)
    kinds = [(e["ev"], e.get("worker"), e.get("reason")) for e in events
             if e["ev"] in mb.MEMBERSHIP_EVENTS + (chaos.FAULT_EVENT,)]
    # the injected fault is audited, the death observed, the rejoin made
    assert ("fault_injected", 1, None) in kinds
    crash_leaves = [k for k in kinds
                    if k[0] == "worker_leave" and k[1] == 1
                    and k[2] in ("crashed", "wedged", "lease_expired")]
    rejoins = [e for e in events if e["ev"] == "worker_join"
               and e.get("worker") == 1 and e.get("rejoin")]
    assert crash_leaves, kinds
    assert rejoins, kinds
    # both workers finished cleanly (no world restart: worker 2 has ONE
    # join — it was never restarted)
    finished = [k for k in kinds if k[0] == "worker_leave"
                and k[2] == "finished"]
    assert {k[1] for k in finished} == {1, 2}
    w2_joins = [e for e in events if e["ev"] == "worker_join"
                and e.get("worker") == 2]
    assert len(w2_joins) == 1
    # the center heard pushes and the final snapshot landed for offline eval
    assert os.path.exists(os.path.join(record_dir, "center_final.npz"))


def test_elastic_corrupt_chaos_raises_replica_divergence(tmp_path):
    """ISSUE 19 acceptance: a chaos ``corrupt`` fault perturbs one
    island's LIVE params — the bad value never crosses the wire as a
    frame, so the §15 CRC can't catch it; the §25 numerics plane must.
    The perturbed island gauges its post-rejoin ``‖w_i − c‖`` spike, the
    fleetmon ``replica_divergence`` rule alerts on that worker within
    one beacon period, the §20 coverage audit closes over the realized
    fault, and a simfleet rehearsal of the same fault kind raises the
    identical alert set."""
    from theanompi_tpu.utils import fleetmon

    record_dir = str(tmp_path)
    # the third field is the perturbation SCALE; the rule threshold sits
    # between the healthy ‖w−c‖ drift ceiling (≲1 for this model) and
    # the corruption's jump (50·√numel ≫ 10) — the §25 calibration
    # contract the docs spell out
    schedule = chaos.parse_schedule("corrupt@2:1:50")
    rc = mb.run_elastic(
        "easgd", "tests.conftest", "SleepyModel",
        {"sync_freq": 2, "batch_size": 8, "iter_sleep": 0.25,
         "fleetmon": True, "fleetmon_divergence": 10.0,
         "fleetmon_eval_s": 0.5}, 2,
        record_dir=record_dir, steps=40, host_devices=1,
        chaos_schedule=schedule, timeout_s=420,
        supervisor_kw={"poll_s": 0.2, "backoff": mb.Backoff(base=0.3),
                       "lease_timeout": 60.0})
    assert rc == 0
    assert schedule[0].error is None, "corrupt fault never landed"
    # the trigger file was consumed by the island (perturbation applied)
    assert not os.path.exists(
        os.path.join(record_dir, "chaos", "corrupt_w1.json"))
    events = _merged_events(record_dir)
    assert any(e["ev"] == chaos.FAULT_EVENT and e.get("kind") == "corrupt"
               for e in events)
    alerts = [e for e in events if e["ev"] == fleetmon.ALERT_EVENT]
    div_alerts = [a for a in alerts if a["rule"] == "replica_divergence"]
    assert div_alerts, [a["rule"] for a in alerts]
    # Fleet-wide alarm is the CORRECT detection for EASGD corruption:
    # the corrupted replica's elastic push moves the CENTER, so every
    # live replica's distance to the consensus spikes — not just the
    # poisoned one.  Both workers must raise replica_divergence.
    assert {a["worker"] for a in div_alerts} == {1, 2}
    # the §20 coverage audit closes: corrupt → replica_divergence within
    # the deadline.  interval_s covers the full symptom pipeline — the
    # island polls the trigger at its next sync round (≤ 2·iter_sleep),
    # then one streamer beat (1 s) carries the gauge to the collector
    with open(os.path.join(record_dir, "chaos_realized.jsonl")) as f:
        realized = [json.loads(ln) for ln in f if ln.strip()]
    assert any(doc["kind"] == "corrupt" and not doc.get("error")
               for doc in realized)
    rules = fleetmon.default_rules(heartbeat_s=10.0, divergence=10.0)
    ok, lines = fleetmon.audit_alerts(alerts, realized, rules,
                                      eval_window_s=0.5, interval_s=4.0)
    assert ok, "\n".join(lines)
    assert any("corrupt" in ln and "replica_divergence" in ln
               for ln in lines)

    # the simfleet rehearsal of the same fault kind: deterministic, and
    # the SAME alert set — the corrupted push poisons the center, so the
    # rehearsal (like the live run) alerts on EVERY replica, no flapping.
    # 400 steps at sync_freq=8 is ~10 virtual seconds; inject at t=4 so
    # the fault lands mid-run, not on the finish line
    from theanompi_tpu.simfleet.fleet import FleetSim

    def rehearse():
        f = FleetSim(n_workers=2, steps=400, sync_freq=8, seed=9,
                     n_stragglers=0,
                     schedule=list(chaos.parse_schedule("corrupt@4:1:50")),
                     fleetmon=True)
        f.run()
        return f

    f1, f2 = rehearse(), rehearse()
    assert f1.log.sha256() == f2.log.sha256()
    sim_alerts = f1.log.select("alert")
    sim_set = {(a["rule"], a["worker"]) for a in sim_alerts}
    live_set = {(a["rule"], a["worker"]) for a in div_alerts}
    assert sim_set == live_set == {("replica_divergence", 1),
                                   ("replica_divergence", 2)}
    ok, lines = fleetmon.audit_alerts(
        f1.health.collector.alerts, f1.realized,
        f1.health.collector.rules,
        eval_window_s=f1.health.eval_window_s,
        interval_s=FleetSim.BEAT_EVERY_S)
    assert ok, "\n".join(lines)


# -- supervised SIGKILL resume (the BSP reaction) ----------------------------

def test_supervised_sigkill_mid_epoch_resumes_at_window_cursor(tmp_path):
    """SIGKILL (not a Python crash: no atexit, no flight dump, no unwind)
    a supervised worker mid-epoch; the launcher restarts it with backoff
    and the run resumes at the last committed window cursor with the
    recorder history intact — extends the PR 4 supervised-resume and PR 7
    SIGTERM tests to the preemption signal you cannot handle."""
    ckpt = str(tmp_path / "ckpt")
    rec_dir = str(tmp_path / "rec")
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    # the worker subprocess imports jax before tests.conftest can set the
    # flag — the 8-chip CPU sim must come in through the environment
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    sup = subprocess.Popen(
        [sys.executable, "-m", "theanompi_tpu.launcher",
         "--supervise", "3", "--rule", "bsp", "--backoff", "0.05",
         "--modelfile", "tests.conftest", "--modelclass", "SleepyModel",
         "platform=cpu", "epochs=2", "batch_size=8", "n_train=2048",
         "n_workers=8", "scale_lr=false", "printFreq=8",
         "iter_sleep=0.05", f"ckpt_dir={ckpt}", f"record_dir={rec_dir}"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        # wait for the first committed checkpoint (epoch 0), then kill the
        # WORKER subprocess mid-epoch-1 — epoch 1 runs ~1.6s of slowed
        # iterations, a wide window
        assert chaos.wait_for_file(os.path.join(ckpt, "LATEST"),
                                   timeout_s=180,
                                   predicate=lambda s: s.strip() == "0")
        wpid = chaos.find_child_pid(sup.pid, "theanompi_tpu.worker",
                                    timeout_s=30)
        assert wpid is not None
        os.kill(wpid, signal.SIGKILL)
        out, _ = sup.communicate(timeout=300)
    finally:
        if sup.poll() is None:
            sup.kill()
            sup.communicate()
    assert sup.returncode == 0, out[-3000:]
    assert "restarting in" in out                  # the backoff restart
    assert "resumed from epoch 0" in out           # committed-cursor resume
    with open(os.path.join(ckpt, "LATEST")) as f:
        assert int(f.read()) == 1                  # run completed epoch 1
    # recorder history intact across the kill: the final records file
    # still holds pre-kill train records (epoch 0 iters) AND both epochs'
    # val records (Recorder.load round-trip on the resume path)
    with open(os.path.join(rec_dir, "inforec_rank0.jsonl")) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    train_iters = [r["iter"] for r in recs if "val_cost" not in r]
    val_iters = [r["iter"] for r in recs if "val_cost" in r]
    assert any(i <= 32 for i in train_iters), train_iters   # pre-kill
    assert set(val_iters) == {32, 64}, val_iters            # both epochs


def test_supervise_crash_loop_breaker_stops_with_flight_tail(tmp_path,
                                                             capsys):
    """A systemically-crashing worker must trip the breaker (N failures
    within the window) instead of burning every restart — nonzero exit
    with the flight-recorder tail printed."""
    from theanompi_tpu import launcher

    rec_dir = str(tmp_path / "rec")
    rc = launcher.main([
        "--supervise", "6", "--rule", "bsp",
        "--backoff", "0.05", "--crash-limit", "2", "--crash-window", "300",
        "--modelfile", "tests.conftest", "--modelclass", "AlwaysCrashModel",
        "platform=cpu", "epochs=1", "batch_size=8", "n_train=64",
        "n_workers=1", "verbose=false", "scale_lr=false", "crash_at=1",
        f"record_dir={rec_dir}",
    ])
    assert rc != 0
    err = capsys.readouterr().err
    assert "crash loop: 2 failures" in err
    assert err.count("restarting in") == 1         # breaker beat restart #2
    assert "flight tail" in err                    # the evidence printed


# -- wire-level chaos (round 14): the faulting proxy -------------------------

def test_parse_schedule_net_kinds():
    faults = chaos.parse_schedule("net_dup@5:-1:6,net_partition@12:2:3")
    assert [(f.kind, f.at, f.target, f.duration) for f in faults] == [
        ("net_dup", 5.0, -1, 6.0), ("net_partition", 12.0, 2, 3.0)]
    # a pid-targeted monkey must ignore net faults (the proxy's job)
    monkey = chaos.ChaosMonkey(faults + chaos.parse_schedule("kill@1:0"))
    assert [f.kind for f in monkey.schedule] == ["kill"]


def test_proxy_duplicates_frames_and_server_dedups():
    import numpy as np

    from theanompi_tpu.parallel.center_server import CenterServer, \
        RemoteCenter
    srv = CenterServer(alpha=0.5)
    host, port = srv.start()
    proxy = chaos.ChaosProxy(f"{host}:{port}",
                             chaos.parse_schedule("net_dup@0:-1:30"))
    paddr = proxy.start()
    try:
        rc = RemoteCenter(paddr, alpha=0.5, client_id="w1")
        rc.ensure_init({"w": np.ones(3, np.float32)})
        rc.push_delta({"w": np.full(3, 2.0, np.float32)}, island=1)
        st = rc.stats()
        # every frame arrived TWICE; each MUTATING op applied ONCE (init/
        # pull/stats are naturally idempotent — no token, no dedup count)
        assert st["n_updates"] == 1
        assert st["dedup_hits"] == 1        # the duplicated push
        np.testing.assert_allclose(rc.pull()["w"], 2.0)
        assert proxy.frames_faulted.get("net_dup", 0) >= 3
        assert proxy.applied and proxy.applied[0].kind == "net_dup"
        rc.close()
    finally:
        proxy.stop()
        srv.stop()


def test_proxy_drop_and_corrupt_are_survived_by_retry():
    import numpy as np

    from theanompi_tpu.parallel.center_server import CenterServer, \
        RemoteCenter
    from theanompi_tpu.parallel.membership import Backoff
    from theanompi_tpu.utils import telemetry
    tm = telemetry.Telemetry(rank=0, run_id="proxy-test")
    srv = CenterServer(alpha=0.5)
    host, port = srv.start()
    t0 = time.time()
    proxy = chaos.ChaosProxy(
        f"{host}:{port}",
        chaos.parse_schedule("net_corrupt@0:-1:1.2,net_drop@1.3:-1:1.2"),
        t0=t0, telemetry_=tm)
    paddr = proxy.start()
    try:
        rc = RemoteCenter(paddr, alpha=0.5, client_id="w1",
                          op_timeout_s=0.5, max_retries=20, deadline_s=30,
                          telemetry_=tm)
        rc._wire.backoff = Backoff(base=0.05, cap=0.3)
        rc.ensure_init({"w": np.ones(3, np.float32)})       # corrupt window
        time.sleep(max(0.0, t0 + 1.4 - time.time()))
        rc.push_delta({"w": np.full(3, 2.0, np.float32)}, island=1)  # drops
        st = rc.stats()
        assert st["n_updates"] == 1                         # exactly once
        np.testing.assert_allclose(rc.pull()["w"], 2.0)
        assert proxy.frames_faulted.get("net_corrupt", 0) >= 1
        assert proxy.frames_faulted.get("net_drop", 0) >= 1
        # corrupt → server-detected CRC failure → client retried the token;
        # drop → op timeout → reconnect+retry
        assert tm.counters.get("wire.corrupt", 0) >= 1
        assert tm.counters.get("wire.timeout", 0) >= 1
        rc.close()
    finally:
        proxy.stop()
        srv.stop()


def test_partitioned_easgd_island_reconnects_and_resyncs():
    """Satellite gate: an EASGD worker behind a partition keeps training
    locally (exchanges SKIPPED, not fatal), reconnects when the partition
    heals, and its pushes land on the live center again."""
    from tests.conftest import TinyModel
    from theanompi_tpu.parallel.async_easgd import AsyncEASGDTrainer
    from theanompi_tpu.parallel.center_server import CenterServer

    def factory(cfg):
        cfg = dict(cfg)
        cfg["verbose"] = False
        cfg.setdefault("batch_size", 8)
        return TinyModel(cfg)

    srv = CenterServer(alpha=0.5)
    host, port = srv.start()
    # window armed only once the island is live (model build + first
    # exchange can outlast any fixed schedule on a loaded CI box)
    sched = chaos.parse_schedule("net_partition@0.2:-1:2.0")
    proxy = chaos.ChaosProxy(f"{host}:{port}", sched,
                             t0=time.time() + 3600)
    paddr = proxy.start()
    tr = AsyncEASGDTrainer(factory, {
        "async_islands": 1, "sync_freq": 1, "seed": 3, "batch_size": 8,
        "center_addr": paddr, "wire_timeout": 0.5, "wire_retries": 2,
        "wire_deadline": 1.0})
    try:
        tr.start()
        isl = tr.islands[0]
        deadline = time.time() + 180
        while isl.exchanges_done < 1 and time.time() < deadline:
            assert isl.error is None, isl.error
            time.sleep(0.05)
        assert isl.exchanges_done >= 1, "island never reached the center"
        proxy.t0 = time.time()                  # partition in 0.2s, 2s long
        time.sleep(2.6)                          # ride through the window
        skipped = isl.exchanges_skipped
        e_heal = isl.exchanges_done
        while isl.exchanges_done < e_heal + 2 and time.time() < deadline:
            assert isl.error is None, isl.error
            time.sleep(0.05)
        tr.stop_and_join(timeout=120)
        assert isl.error is None
        assert skipped >= 1, "the partition never bit an exchange"
        # reconnected: post-heal exchanges landed on the LIVE center
        assert isl.exchanges_done >= e_heal + 2
        assert srv.center.n_updates >= e_heal + 2
        # the run's stats surface the outage
        assert tr.stats()["islands"][0]["exchanges_skipped"] == \
            isl.exchanges_skipped
    finally:
        proxy.stop()
        srv.stop()


def test_elastic_center_sigkill_recovers_without_world_restart(tmp_path):
    """The round-14 fast chaos gate: SIGKILL the CENTER mid-run while a
    net_dup window duplicates every frame; the elastic EASGD run completes
    with no world restart (each worker joins exactly once), the telemetry
    stream carries the center_down → center_restored pair, and every
    landed duplicate push was applied exactly once (dedup counter > 0,
    bookkeeping balanced).

    Round 16 rides the SAME run for the causal-tracing acceptance
    (ISSUE 11, docs/design.md §17): with ``tracing=true`` the merged
    stream must assemble distributed traces where ≥95% of exchange-round
    client spans join an applied server span, every round's critical
    path sums to the observed round time within 5%, the straggler
    root-cause table names each worker's dominant component, and the
    Perfetto export carries cross-process flow arrows — one elastic run,
    both gates (a second full elastic run would blow the tier-1
    budget)."""
    record_dir = str(tmp_path)
    schedule = chaos.parse_schedule("kill@18:0")      # worker 0 = center
    net_schedule = chaos.parse_schedule("net_dup@0:-1:600")
    # iter_sleep stretches each worker's run to ≥ steps·sleep ≈ 24 s of
    # training AFTER the center first answers (ensure_init gates the
    # loop), so the t=18 kill always lands MID-run whatever the box's
    # load — and a worker blocked in an exchange retry rides out the
    # whole respawn instead of finishing before `center_restored`
    rc = mb.run_elastic(
        "easgd", "tests.conftest", "SleepyModel",
        {"sync_freq": 2, "batch_size": 8, "iter_sleep": 0.2,
         "tracing": True, "wire_timeout": 5, "wire_deadline": 90,
         "center_snapshot_every_s": 0.5}, 2,
        record_dir=record_dir, steps=120, host_devices=1,
        chaos_schedule=schedule, net_chaos_schedule=net_schedule,
        center_proc=True, timeout_s=420,
        supervisor_kw={"poll_s": 0.2, "backoff": mb.Backoff(base=0.3),
                       "lease_timeout": 120.0})
    assert rc == 0
    assert schedule[0].error is None, "center kill never landed"
    events = _merged_events(record_dir)
    downs = [e for e in events if e["ev"] == "center_down"]
    restores = [e for e in events if e["ev"] == "center_restored"]
    assert downs, "no center_down for the SIGKILLed center"
    assert restores, "center never audited as restored"
    assert restores[-1]["ts"] > downs[-1]["ts"], "run ended center-down"
    # no world restart: every worker joined exactly once and finished
    for w in (1, 2):
        joins = [e for e in events if e["ev"] == "worker_join"
                 and e.get("worker") == w]
        finishes = [e for e in events if e["ev"] == "worker_leave"
                    and e.get("worker") == w
                    and e.get("reason") == "finished"]
        assert len(joins) == 1, (w, joins)
        assert finishes, (w, "did not finish cleanly")
    # the duplicate pushes were deduplicated, applied exactly once
    with open(os.path.join(record_dir, "center_stats.json")) as f:
        stats = json.load(f)
    assert stats["dedup_hits"] > 0, stats
    assert stats["n_updates"] == sum(stats["by_island"].values())
    assert stats["center_downs"] >= 1
    assert os.path.exists(os.path.join(record_dir, "center_final.npz"))
    # chaos_run's own audit logic agrees (the CI gate path)
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import chaos_run
    ok, _ = chaos_run.audit_center(record_dir, n_center_kills=1,
                                   require_dedup=True)
    assert ok

    # -- round 16: the causal-tracing acceptance on this same run ------------
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_chaos_test_report", os.path.join(REPO, "scripts",
                                           "telemetry_report.py"))
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)
    trace_events = rep.load_events(record_dir)
    summary = rep.trace_summary(trace_events, window_s=5.0)
    assert summary, "tracing=true produced no spans"
    assert summary["rounds"] >= 20, summary
    # ≥95% of client wire spans join an applied server span — through
    # the center kill (snapshot restore + retries) AND the dup storm
    assert summary["join_rate"] is not None
    assert summary["join_rate"] >= 0.95, summary
    # every frame was duplicated: twins observed, tagged, never joined
    assert summary["dedup_twins"] > 0, summary
    # per-round critical path sums to the observed round time within 5%
    for t in [t for t in rep.assemble_traces(trace_events)
              if t["name"] == "round"]:
        total = sum(t["components"].values())
        assert abs(total - t["dt"]) <= 0.05 * t["dt"] + 0.005, t
    # the root-cause table names each worker's dominant component
    root = summary["root_cause"]
    assert set(root) >= {1, 2}, root
    for rcause in root.values():
        assert rcause["dominant"] in rep.TRACE_COMPONENTS
        assert rcause["rounds"] > 0
    # SleepyModel's 0.2s/iter local steps dominate these rounds
    assert all(rcause["dominant"] == "compute" for rcause in root.values())
    # statusz endpoints were live on every long-lived process
    assert {e.get("role") for e in trace_events
            if e.get("ev") == "statusz"} >= {"worker", "supervisor",
                                             "center"}
    # Perfetto export: span slices + flow arrows binding client wire
    # spans to the server spans they caused, landing on the center track
    trace = rep.build_trace(trace_events)
    tevs = trace["traceEvents"]
    assert any(e.get("cat") == "span" and e.get("ph") == "X"
               for e in tevs)
    starts = [e for e in tevs if e.get("ph") == "s"]
    finishes = [e for e in tevs if e.get("ph") == "f"]
    assert starts and finishes
    assert {e["id"] for e in starts} == {e["id"] for e in finishes}
    assert {e["pid"] for e in finishes} == {-1}
    assert {e["pid"] for e in starts} >= {1, 2}


# -- slow: the full convergence-under-chaos gate -----------------------------

@pytest.mark.slow
def test_chaos_gate_easgd_convergence_under_kills(tmp_path):
    """The acceptance gate: random SIGKILLs into non-zero workers mid-run;
    the EASGD run completes without a world restart AND the final center
    reaches the fault-free run's loss neighborhood — convergence under
    churn, not mere survival.  Audited through scripts/chaos_run.py's own
    matching logic."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import chaos_run

    cfg = {"sync_freq": 2, "batch_size": 8}
    # fault-free reference
    clean_dir = str(tmp_path / "clean")
    rc = mb.run_elastic("easgd", "tests.conftest", "TinyModel", dict(cfg),
                        2, record_dir=clean_dir, steps=80, host_devices=1,
                        timeout_s=420)
    assert rc == 0
    clean_loss = chaos_run.eval_center_loss(
        "tests.conftest", "TinyModel", dict(cfg),
        os.path.join(clean_dir, "center_final.npz"))
    # chaotic run: two kills on the non-zero workers
    chaos_dir = str(tmp_path / "chaos")
    schedule = chaos.seeded_schedule(7, [1, 2], n_faults=2,
                                     t_min=6.0, t_max=14.0)
    rc = mb.run_elastic("easgd", "tests.conftest", "TinyModel", dict(cfg),
                        2, record_dir=chaos_dir, steps=80, host_devices=1,
                        chaos_schedule=schedule, timeout_s=420,
                        supervisor_kw={"poll_s": 0.2,
                                       "backoff": mb.Backoff(base=0.3),
                                       "lease_timeout": 60.0})
    assert rc == 0
    # only faults that actually LANDED on a live pid are auditable (a
    # worker can finish before its fault time; the monkey then drops it)
    kills = [f.target for f in schedule
             if f.kind == "kill" and f.applied and f.error is None]
    assert kills, "no kill landed — schedule mistimed"
    ok, _ = chaos_run.audit_membership(chaos_dir, kills)
    assert ok
    chaos_loss = chaos_run.eval_center_loss(
        "tests.conftest", "TinyModel", dict(cfg),
        os.path.join(chaos_dir, "center_final.npz"))
    # convergence-to-accuracy: better than a random 2-class model and
    # within the fault-free run's neighborhood
    assert chaos_loss < 0.69, (chaos_loss, clean_loss)
    assert chaos_loss < clean_loss + 0.15, (chaos_loss, clean_loss)


@pytest.mark.slow
def test_chaos_gate_center_kill_and_net_faults_convergence(tmp_path):
    """The full round-14 acceptance gate, driven through chaos_run's own
    CLI: center SIGKILLed once, a seeded drop/delay/dup/corrupt/partition
    schedule active, and the run must complete without a world restart
    with the leave/join + center_down/center_restored audits passing,
    duplicates deduplicated, and final center val cost under the
    fault-free reference threshold."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import chaos_run

    cfg = {"sync_freq": 2, "batch_size": 8}
    clean_dir = str(tmp_path / "clean")
    rc = mb.run_elastic("easgd", "tests.conftest", "TinyModel", dict(cfg),
                        2, record_dir=clean_dir, steps=80, host_devices=1,
                        timeout_s=420)
    assert rc == 0
    clean_loss = chaos_run.eval_center_loss(
        "tests.conftest", "TinyModel", dict(cfg),
        os.path.join(clean_dir, "center_final.npz"))

    chaos_dir = str(tmp_path / "chaos")
    rc = chaos_run.main([
        "--rule", "easgd", "--workers", "2", "--steps", "80",
        "--faults", "kill@16:0,kill@20:1",      # the center AND a worker
        "--net-seed", "11", "--net-n-faults", "4",
        "--net-duration", "2.5", "--t-min", "8", "--t-max", "30",
        "--record-dir", chaos_dir, "--host-devices", "1",
        "--lease-timeout", "60",
        "--loss-threshold", str(clean_loss + 0.15),
        "sync_freq=2", "batch_size=8", "wire_timeout=5",
        "wire_deadline=60", "center_snapshot_every_s=0.5"])
    assert rc == 0, f"chaos_run gate failed rc={rc}"
    with open(os.path.join(chaos_dir, "chaos_gate.json")) as f:
        gate = json.load(f)
    assert gate["val_cost"] < clean_loss + 0.15


def test_proxy_stop_joins_its_threads():
    """ChaosProxy.stop() bounded-joins the accept/monitor threads
    (tpulint daemon-discipline): nothing of the proxy may outlive
    stop() into the caller's teardown/audit."""
    import socket as _socket

    up = _socket.socket()
    up.bind(("127.0.0.1", 0))
    up.listen(1)
    host, port = up.getsockname()
    # a far-future window keeps the monitor loop alive until stop()
    proxy = chaos.ChaosProxy(f"{host}:{port}",
                             chaos.parse_schedule("net_drop@600:1:1"))
    proxy.start()
    threads = list(proxy._threads)
    assert threads and all(t.is_alive() for t in threads)
    proxy.stop()
    assert all(not t.is_alive() for t in threads)
    assert proxy._threads == []
    up.close()
