"""simfleet: the deterministic virtual-time fleet simulator
(theanompi_tpu/simfleet/, docs/design.md §18) — determinism gate,
at-width invariant suite, clock-seam equivalence, transport fault
semantics, and the realized-schedule export/replay loop."""

import time

import pytest

from theanompi_tpu.parallel import membership as mb
from theanompi_tpu.simfleet import (EventLog, EventQueue, FleetSim,
                                    VirtualClock, check_invariants)
from theanompi_tpu.simfleet.fidelity import (export_realized,
                                             normalize_sequence,
                                             sim_membership_sequence)
from theanompi_tpu.simfleet.transport import SimCenter, SimTransport
from theanompi_tpu.utils import chaos
from theanompi_tpu.utils.clock import WALL, WallClock

# one explicit schedule covering the WHOLE fault taxonomy: center kill,
# worker kills, a lease-expiring wedge, a short wedge, a delay
# straggler, and all five wire window kinds
FULL_SCHEDULE = (
    "kill@10:0,kill@12:3,kill@14:7,stop@20:5:20,stop@30:9:2,"
    "delay@40:11:15,net_dup@8:-1:6,net_dup@35:-1:5,net_drop@25:-1:3,"
    "net_partition@45:-1:3,net_delay@55:-1:4,net_corrupt@60:-1:3")


def _run(n_workers=64, steps=2000, seed=11, schedule=FULL_SCHEDULE,
         **kw):
    kw.setdefault("sync_freq", 8)
    kw.setdefault("n_stragglers", 3)
    fleet = FleetSim(n_workers=n_workers, steps=steps, seed=seed,
                     schedule=chaos.parse_schedule(schedule)
                     if schedule else None, **kw)
    fleet.run()
    return fleet


# -- event core ---------------------------------------------------------------

def test_event_queue_total_order_and_clock_advance():
    clock = VirtualClock()
    q = EventQueue(clock)
    seen = []
    q.push(2.0, lambda: seen.append(("b", clock.now())))
    q.push(1.0, lambda: seen.append(("a", clock.now())))
    q.push(2.0, lambda: seen.append(("c", clock.now())))  # same t: FIFO
    q.run()
    assert seen == [("a", 1.0), ("b", 2.0), ("c", 2.0)]
    with pytest.raises(RuntimeError, match="schedule an event"):
        clock.sleep(1.0)


def test_event_log_canonical_and_hashable():
    a, b = EventLog(), EventLog()
    for log in (a, b):
        log.append(1.23456789, "x", worker=3, reason="spawn")
        log.append(2.0, "y")
    assert a.sha256() == b.sha256()
    assert a.to_jsonl().count("\n") == 2
    b.append(3.0, "z")
    assert a.sha256() != b.sha256()


# -- determinism gate ---------------------------------------------------------

def test_same_seed_byte_identical_log_different_seed_differs():
    f1 = _run(n_workers=48, steps=800, seed=5)
    f2 = _run(n_workers=48, steps=800, seed=5)
    assert f1.log.to_jsonl() == f2.log.to_jsonl()       # byte-identical
    assert f1.log.sha256() == f2.log.sha256()
    f3 = _run(n_workers=48, steps=800, seed=6)
    assert f3.log.sha256() != f1.log.sha256()


# -- the at-width invariant suite (tier-1 budgeted) ---------------------------

def test_invariant_suite_at_width_under_budget():
    """The §18 claim in-suite at 256 workers (scripts/tier1.sh's
    simfleet gate owns the full 512-worker run — no need to pay it
    twice per tier-1): full fault taxonomy, every invariant checker
    green, in CPU-seconds."""
    t0 = time.process_time()
    fleet = _run(n_workers=256, steps=2000, seed=11, sync_freq=16,
                 n_stragglers=10)
    cpu = time.process_time() - t0
    results = check_invariants(fleet)
    failures = [(n, d) for n, ok, d in results if not ok]
    assert not failures, failures
    assert cpu < 60.0, f"256-worker suite took {cpu:.1f}s CPU"
    s = fleet.summary
    assert s["finished"] == 256
    assert s["deaths"] >= 3                 # kills + the long wedge
    assert s["center"]["restarts"] == 1     # kill@10:0 restarted it
    assert sum(s["center"]["dedup_hits_per_shard"]) > 0
    assert s["frames_faulted"].get("net_dup", 0) > 0


def test_killed_wedged_delayed_worker_sequences():
    fleet = _run(n_workers=32, steps=2000, seed=11)
    seqs = sim_membership_sequence(fleet)
    assert seqs[3] == ["join", "death", "rejoin", "finish"]   # SIGKILL
    assert seqs[5] == ["join", "death", "rejoin", "finish"]   # long wedge
    assert seqs[9] == ["join", "finish"]                      # short wedge
    # the delay target straggles; it may be demoted (then readmitted or
    # respawned) but must finish
    assert seqs[11][0] == "join" and seqs[11][-1] == "finish"
    # the center outage pair landed in order
    evs = [r["ev"] for r in fleet.log.select("center_down",
                                             "center_restored")]
    assert evs == ["center_down", "center_restored"]


def test_straggler_demotion_and_alpha_freeze_at_width():
    fleet = _run(n_workers=64, steps=3000, seed=9, schedule=None,
                 n_stragglers=4)
    results = dict((n, (ok, d)) for n, ok, d in check_invariants(fleet))
    assert results["straggler_demotion_converges"][0], results
    assert results["alpha_conservation_under_churn"][0], results
    demoted = {r["worker"] for r in fleet.log.select("worker_demote")}
    assert set(fleet.stragglers) <= demoted


# -- clock seam: wall vs virtual equivalence (satellite) ----------------------

def _scripted_controller(clock, base, table):
    ctl = mb.MembershipController(lease_timeout=10.0,
                                  telemetry_=None, clock=clock,
                                  lease_source=lambda: table)
    # identical scripted event sequence, timestamps relative to ``base``
    table[1] = {"worker": 1, "ts": base + 0.0, "step": 0, "status": "live"}
    table[2] = {"worker": 2, "ts": base + 0.0, "step": 0, "status": "live"}
    ctl.poll(now=base + 1.0)                 # both join
    ctl.demote(1, reason="straggler")
    table[2]["ts"] = base + 8.0              # 2 beats, 1 goes silent...
    ctl.poll(now=base + 9.0)                 # ...but not expired yet
    ctl.poll(now=base + 12.0)                # 1 expires (demoted+silent)
    ctl.leave(2, reason="crashed", now=base + 13.0)
    table[2]["ts"] = base + 12.5             # stale beat from before death
    ctl.poll(now=base + 14.0)                # must NOT resurrect 2
    table[2] = {"worker": 2, "ts": base + 15.0, "step": 0,
                "status": "live"}            # a real respawn beat
    ctl.poll(now=base + 16.0)                # rejoin via lease
    table[2]["ts"] = base + 17.0
    table[2]["status"] = "left"
    ctl.poll(now=base + 18.0)                # clean finish
    return [(ev, w, info.get("reason"), bool(info.get("rejoin")))
            for ev, w, info in ctl.transitions]


def test_controller_transitions_identical_wall_vs_virtual_clock():
    """The clock-seam refactor is behavior-preserving: the same scripted
    event sequence produces IDENTICAL transitions whether the controller
    runs on wall time or virtual time."""
    import time as _time
    wall_base = _time.time() - 3600.0        # arbitrary real epoch
    wall = _scripted_controller(WallClock(), wall_base, {})
    virt = _scripted_controller(VirtualClock(), 0.0, {})
    assert wall == virt
    assert [t[:3] for t in virt] == [
        ("worker_join", 1, "lease"), ("worker_join", 2, "lease"),
        ("worker_demote", 1, "straggler"),
        ("worker_leave", 1, "lease_expired"),
        ("worker_leave", 2, "crashed"),
        ("worker_join", 2, "lease"),
        ("worker_leave", 2, "finished")]
    # the rejoin flag carried through identically too
    assert virt[5][3] is True


def test_wall_clock_is_real_time():
    t = time.time()
    assert abs(WALL.now() - t) < 5.0
    assert isinstance(WALL, WallClock)


# -- transport fault semantics -----------------------------------------------

def _transport(schedule, center, seed=0, **kw):
    clock = VirtualClock()
    import random
    return clock, SimTransport(clock, random.Random(seed),
                               chaos.parse_schedule(schedule),
                               center=center, **kw)


def test_transport_drop_dup_corrupt_partition_semantics():
    center = SimCenter(n_shards=1)
    clock, tp = _transport(
        "net_drop@10:-1:5,net_dup@20:1:5,net_corrupt@30:-1:5", center)
    # clean push applies
    st, verdict, _ = tp.request_push(1, 0, 100)
    assert (st, verdict) == ("ok", "applied")
    # drop window: lost, client times out
    clock.advance_to(11.0)
    st, verdict, t_done = tp.request_push(1, 0, 101)
    assert st == "lost" and t_done == pytest.approx(11.0 + tp.op_timeout_s)
    # retry of the lost frame AFTER the window: same seq applies once
    clock.advance_to(16.0)
    st, verdict, _ = tp.request_push(1, 0, 101)
    assert (st, verdict) == ("ok", "applied")
    # dup window targeted at worker 1: twin applies get deduped
    clock.advance_to(21.0)
    st, verdict, _ = tp.request_push(1, 0, 102)
    assert (st, verdict) == ("ok", "applied")
    assert center.shards[0].window.hits == 1       # the swallowed twin
    assert tp.frames_faulted["net_dup"] == 1
    # ...and worker 2 is untouched by worker 1's window
    st, _, _ = tp.request_push(2, 0, 1)
    assert st == "ok" and tp.frames_faulted["net_dup"] == 1
    # corrupt window: retryable verdict, dedup window NOT consulted
    clock.advance_to(31.0)
    hits = center.shards[0].window.hits
    st, verdict, _ = tp.request_push(1, 0, 103)
    assert (st, verdict) == ("retry", "corrupt")
    assert center.shards[0].window.hits == hits
    # exactly-once ledger stayed clean through all of it
    assert not center.shards[0].violations


def test_transport_partition_ack_loss_then_dedup():
    """The case the tokens exist for: the op APPLIES, the ack is lost in
    a partition, the retry is answered from the dedup window."""
    center = SimCenter(n_shards=1)
    # window opens just after delivery (~58.004) and covers the reply
    clock, tp = _transport("net_partition@58.005:-1:2", center,
                           latency_jitter=0.0)
    clock.advance_to(58.0)
    st, verdict, _ = tp.request_push(4, 0, 7)
    assert st == "lost"
    assert center.shards[0].applied_by_worker.get(4) == 1   # it landed
    clock.advance_to(61.0)
    st, verdict, _ = tp.request_push(4, 0, 7)               # the retry
    assert (st, verdict) == ("ok", "dedup")
    assert center.shards[0].applied_by_worker.get(4) == 1   # ONCE
    assert not center.shards[0].violations


def test_center_crash_restore_dedups_replays_at_width():
    center = SimCenter(n_shards=2)
    for w in range(1, 201):
        for shard in (0, 1):
            center.apply_push(shard, w, 1000 + w)
    center.crash_and_restore(now=50.0, outage_s=2.0)
    assert center.is_down(51.0) and not center.is_down(52.5)
    # replays of every pre-crash token are recognized post-restore
    for w in range(1, 201):
        for shard in (0, 1):
            assert center.apply_push(shard, w, 1000 + w) == "dedup"
    assert not center.shards[0].violations
    assert not center.shards[1].violations
    # fresh seqs above the restored HWM still apply
    assert center.apply_push(0, 7, 5000) == "applied"


# -- realized schedule export / replay grammar --------------------------------

def test_realized_export_parses_back_into_the_chaos_grammar(tmp_path):
    fleet = _run(n_workers=16, steps=1200, seed=11)
    path = str(tmp_path / "sim_realized.jsonl")
    export_realized(fleet.realized, path, min_at=6.0)
    sched = chaos.schedule_from_realized(path)
    kinds = {(f.kind, f.target) for f in sched}
    assert ("kill", 3) in kinds and ("kill", 7) in kinds
    assert ("kill", 0) in kinds                   # the center kill
    assert any(f.kind == "net_dup" for f in sched)
    assert all(f.at >= 6.0 for f in sched)        # re-timed for live boot
    # faults that never landed are excluded from the replay
    err = [d for d in fleet.realized if d.get("error")]
    assert len(sched) == len(fleet.realized) - len(err)


def test_normalize_sequence_collapses_double_observations():
    evs = [
        {"ev": "worker_join", "worker": 1, "reason": "spawn"},
        {"ev": "worker_leave", "worker": 1, "reason": "lease_expired"},
        {"ev": "worker_leave", "worker": 1, "reason": "crashed"},
        {"ev": "worker_join", "worker": 1, "reason": "respawn",
         "rejoin": True},
        {"ev": "worker_leave", "worker": 1, "reason": "finished"},
    ]
    assert normalize_sequence(evs) == {
        1: ["join", "death", "rejoin", "finish"]}


# -- fidelity: the live cross-check (subprocesses + jax) ----------------------

def test_fidelity_crosscheck_sim_matches_live(tmp_path):
    """The acceptance cross-check: one simulated kill schedule, exported
    and replayed through the LIVE ChaosMonkey + elastic runtime at 4
    workers — same membership-event sequence, modulo timing."""
    from theanompi_tpu.simfleet.fidelity import crosscheck
    out = crosscheck(str(tmp_path), n_workers=4, schedule="kill@6:1",
                     steps=40, seed=0)
    assert out["live_rc"] == 0
    assert out["sim"] == out["live"], (out["sim"], out["live"])
    assert out["ok"] is True
    assert out["sim"][1] == ["join", "death", "rejoin", "finish"]
