"""ZeRO-1 sharded optimizer state (parallel/zero.py): bit-equal to the
replicated optimizer, with per-chip optimizer memory 1/N."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tests.conftest import TinyModel
from theanompi_tpu.models.transformer_lm import TransformerLM
from theanompi_tpu.parallel import steps
from theanompi_tpu.parallel.exchanger import BSP_Exchanger, get_exchanger
from theanompi_tpu.parallel.mesh import WORKER_AXIS, worker_mesh


def _train(model, exch, n_steps):
    model.compile_iter_fns(exch)
    model.data.shuffle_data(0)
    costs = []
    for i in range(n_steps):
        model.train_iter(i, None)
        costs.append(float(model.current_info["cost"]))
    return costs


def _make_tiny(zero, mesh, **kw):
    cfg = {"mesh": mesh, "size": 4, "rank": 0, "verbose": False,
           "zero_opt": zero, **kw}
    return TinyModel(cfg), cfg


def test_zero1_ragged_chunking_is_explicit():
    """P=10, N=4 (the ragged case): chunk ceil(10/4)=3, padded length 12.
    Callers pad to ``padded_size`` EXPLICITLY before slicing — a ragged
    flat must never rely on a downstream implicit zero-fill (dynamic_slice
    would silently clamp an 11th-element read)."""
    from theanompi_tpu.parallel import zero as zero_lib
    assert zero_lib.chunk_size(10, 4) == 3
    assert zero_lib.padded_size(10, 4) == 12
    # and the boxed re-partition round-trips the ragged layout exactly
    flat = np.arange(10, dtype=np.float32)
    boxed4 = np.pad(flat, (0, 2)).reshape(4, 3)
    boxed2 = zero_lib.rechunk_boxed(boxed4, 2, 1, 10)
    assert boxed2.shape == (2, 5)
    np.testing.assert_array_equal(boxed2.reshape(-1)[:10], flat)
    back = zero_lib.rechunk_boxed(boxed2, 4, 1, 10)
    np.testing.assert_array_equal(back, boxed4)


def test_zero1_bit_equal_to_replicated(mesh4):
    """Same data, same seed: the ZeRO-sharded optimizer must trace the
    replicated optimizer's params EXACTLY (elementwise math on disjoint
    chunks; no reduction-order change)."""
    for optimizer in ("momentum", "adam"):
        base, _ = _make_tiny(False, mesh4, optimizer=optimizer)
        zero, _ = _make_tiny(True, mesh4, optimizer=optimizer)
        c0 = _train(base, BSP_Exchanger(base.config), 6)
        c1 = _train(zero, BSP_Exchanger(zero.config), 6)
        np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))
        p0 = steps.unbox(jax.device_get(base.step_state["params"]))
        p1 = steps.unbox(jax.device_get(zero.step_state["params"]))
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), p0, p1)


def test_zero1_state_is_sharded(mesh4):
    """Optimizer memory: each worker holds ONE ceil(P/N) chunk (adam: m, v,
    t per chunk) instead of a full replica."""
    model, _ = _make_tiny(True, mesh4, optimizer="adam")
    model.compile_iter_fns(BSP_Exchanger(model.config))
    n_params = model.n_params
    chunk = -(-n_params // 4)
    m = model.step_state["opt_state"]["opt"]["m"]
    assert m.shape == (4, chunk)                      # boxed = the partition
    assert m.sharding.spec == (WORKER_AXIS,)
    # the four chunks diverge once training starts (they cover different
    # parameter ranges)
    _train(model, model.exchanger, 3)
    mm = np.asarray(jax.device_get(model.step_state["opt_state"]["opt"]["m"]))
    assert not np.allclose(mm[0], mm[1])


def test_zero1_checkpoint_roundtrip(tmp_path, mesh4):
    model, _ = _make_tiny(True, mesh4, optimizer="momentum")
    _train(model, BSP_Exchanger(model.config), 3)
    model.save(str(tmp_path), epoch=0, count=3)
    # per-part dedup: params (bit-identical replicas) stored ONCE, only the
    # genuinely per-worker ZeRO chunks stored boxed
    import json, os
    with open(os.path.join(str(tmp_path), "ckpt_epoch0.json")) as f:
        meta = json.load(f)
    assert meta["boxed_parts"] == ["opt_state"], meta
    import numpy as np_
    data = np_.load(os.path.join(str(tmp_path), "ckpt_epoch0.npz"))
    p_leaf = data["params__0"]
    unboxed = steps.unbox(jax.device_get(model.step_state["params"]))
    assert p_leaf.shape == jax.tree.leaves(unboxed)[0].shape
    before = jax.device_get(steps.tree_to_host(model.step_state["opt_state"]))
    m2, _ = _make_tiny(True, mesh4, optimizer="momentum")
    m2.compile_iter_fns(BSP_Exchanger(m2.config))
    assert m2.load(str(tmp_path)) == 0
    after = jax.device_get(steps.tree_to_host(m2.step_state["opt_state"]))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), before, after)
    m2.data.shuffle_data(0)
    m2.train_iter(3, None)


def test_zero1_rejects_async_rules(mesh4):
    """(tp composition is no longer rejected — round-4; see the tp tests
    below.)"""
    model, cfg = _make_tiny(True, mesh4, optimizer="momentum",
                            sync_freq=2)
    with pytest.raises(AssertionError, match="BSP grads"):
        model.compile_iter_fns(get_exchanger("easgd", cfg))
    # params mode / 'none' strategy never reduce grads — ZeRO would slice
    # UN-reduced per-worker grads and train silently wrong
    for bad in ({"exch_mode": "params"}, {"exch_strategy": "none"}):
        m, c = _make_tiny(True, mesh4, optimizer="momentum", **bad)
        with pytest.raises(AssertionError, match="grads"):
            m.compile_iter_fns(BSP_Exchanger(c))


def test_zero1_transformer_with_compressed_wire(mesh8):
    """ZeRO composes with the EF-compressed wire (grads identical across
    workers after decode) on the LM family."""
    mesh = worker_mesh(8)
    cfg = {"mesh": mesh, "size": 8, "rank": 0, "verbose": False,
           "zero_opt": True, "exch_strategy": "onebit",
           "batch_size": 8, "seq_len": 16, "vocab": 32, "d_model": 32,
           "n_head": 4, "n_layer": 2, "synthetic_train": 128,
           "compute_dtype": jnp.float32}
    model = TransformerLM(cfg)
    costs = _train(model, BSP_Exchanger(cfg), 6)
    assert np.isfinite(costs).all()
    assert np.mean(costs[-3:]) < np.mean(costs[:3])


def test_zero1_checkpoint_is_worker_count_portable(tmp_path, mesh4, mesh8):
    """Elastic resume for ZeRO chunks (round-4, matching fsdp): the boxed
    optimizer chunks re-partition onto a different worker count on load —
    the reassembled optimizer flat is identical, and training continues."""
    d = str(tmp_path / "ckpt")
    m4, _ = _make_tiny(True, mesh4, optimizer="adam")
    _train(m4, BSP_Exchanger(m4.config), 3)
    m4.save(d, epoch=0, count=3)
    ref_p = steps.unbox(jax.device_get(m4.step_state["params"]))
    ref_m = np.asarray(jax.device_get(
        m4.step_state["opt_state"]["opt"]["m"])).reshape(-1)[:m4.n_params]

    cfg8 = {"mesh": mesh8, "size": 8, "rank": 0, "verbose": False,
            "zero_opt": True, "optimizer": "adam"}
    m8 = TinyModel(cfg8)
    m8.compile_iter_fns(BSP_Exchanger(cfg8))
    assert m8.load(d) == 0
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)),
        ref_p, steps.unbox(jax.device_get(
            jax.tree.map(lambda x: x[:1], m8.step_state["params"]))))
    got_m = np.asarray(jax.device_get(
        m8.step_state["opt_state"]["opt"]["m"])).reshape(-1)[:m8.n_params]
    np.testing.assert_array_equal(ref_m, got_m)
    t8 = np.asarray(jax.device_get(m8.step_state["opt_state"]["opt"]["t"]))
    assert t8.shape == (8,) and (t8 == t8[0]).all() and t8[0] == 3
    m8.data.shuffle_data(0)
    m8.train_iter(3, None)               # and it keeps training


def test_zero1_ckpt_portable_under_tp(tmp_path, mesh8):
    """ZeRO chunk re-partition under tensor parallelism: dp=2×tp=2 saved,
    resumed on dp=4×tp=2 — each model rank's local flat reassembles
    identically across the two worker-chunkings."""
    base = _make_tp_lm(True, dp=2, tp=2, optimizer="adam")
    _train(base, BSP_Exchanger(base.config), 3)
    d = str(tmp_path / "ckpt")
    base.save(d, epoch=0, count=3)
    from theanompi_tpu.parallel import zero as zero_lib
    lay = base._zero_layout
    m_saved = np.asarray(jax.device_get(
        base.step_state["opt_state"]["opt"]["m"]))

    m2 = _make_tp_lm(True, dp=4, tp=2, optimizer="adam")
    m2.compile_iter_fns(BSP_Exchanger(m2.config))
    assert m2.load(d) == 0
    m_new = np.asarray(jax.device_get(
        m2.step_state["opt_state"]["opt"]["m"]))
    # reassembling per model rank must agree between the two layouts
    def per_rank(arr, n):
        c = arr.shape[1] // lay["shards"]
        return np.transpose(arr.reshape(n, lay["shards"], c),
                            (1, 0, 2)).reshape(lay["shards"],
                                               -1)[:, :lay["local_total"]]
    np.testing.assert_array_equal(per_rank(m_saved, 2), per_rank(m_new, 4))
    assert m_new.shape == (4, lay["shards"] * zero_lib.chunk_size(
        lay["local_total"], 4))
    m2.data.shuffle_data(0)
    m2.train_iter(3, None)


# -- round 4: composition with tensor parallelism ---------------------------

TP_LM = dict(verbose=False, batch_size=8, seq_len=16, vocab=32,
             synthetic_train=64, synthetic_val=32, d_model=32, n_head=4,
             n_layer=2, compute_dtype=jnp.float32)


def _make_tp_lm(zero, dp=2, tp=2, **kw):
    mesh = worker_mesh(dp, tp=tp)
    cfg = {**TP_LM, "mesh": mesh, "size": dp, "rank": 0, "tp": tp,
           "zero_opt": zero, **kw}
    return TransformerLM(cfg)


@pytest.mark.parametrize("optimizer", ["momentum", "adam"])
def test_zero1_bit_equal_under_tp(mesh8, optimizer):
    """dp=2 × tp=2: the ZeRO partition now chunks each device's LOCAL param
    shard — still bit-equal to the replicated optimizer on the same layout
    (round-3 verdict #6)."""
    base = _make_tp_lm(False, optimizer=optimizer)
    zero = _make_tp_lm(True, optimizer=optimizer)
    c0 = _train(base, BSP_Exchanger(base.config), 5)
    c1 = _train(zero, BSP_Exchanger(zero.config), 5)
    np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))
    p0 = jax.device_get(steps.tree_to_host(base.step_state["params"]))
    p1 = jax.device_get(steps.tree_to_host(zero.step_state["params"]))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), p0, p1)


def test_zero1_state_sharded_over_workers_and_model(mesh8):
    """The chunk state varies over BOTH axes: boxed [dp, tp·chunk] sharded
    P(workers, model) — per-device optimizer memory is local_P/dp."""
    from theanompi_tpu.parallel.mesh import MODEL_AXIS
    model = _make_tp_lm(True, optimizer="adam")
    model.compile_iter_fns(BSP_Exchanger(model.config))
    m = model.step_state["opt_state"]["opt"]["m"]
    local = steps.local_param_template(model.params, model.param_specs(),
                                       model.mesh)
    from theanompi_tpu.utils import helper_funcs
    chunk = -(-helper_funcs.tree_size(local) // 2)
    assert m.shape == (2, 2 * chunk), m.shape
    assert m.sharding.spec == (WORKER_AXIS, (MODEL_AXIS,)) or \
        m.sharding.spec == (WORKER_AXIS, MODEL_AXIS), m.sharding.spec
    # each device's addressable block is exactly one chunk
    assert m.addressable_shards[0].data.shape == (1, chunk)


def test_zero1_bit_equal_under_pp(mesh8):
    """Pipeline composition: zero chunks each stage's local stack shard;
    bit-equal to the replicated optimizer on the same pp layout."""
    def make(zero):
        mesh = worker_mesh(2, pp=2)
        cfg = {**TP_LM, "mesh": mesh, "size": 2, "rank": 0, "tp": 1,
               "pp": 2, "zero_opt": zero, "optimizer": "adam"}
        return TransformerLM(cfg)
    base, zero = make(False), make(True)
    c0 = _train(base, BSP_Exchanger(base.config), 4)
    c1 = _train(zero, BSP_Exchanger(zero.config), 4)
    np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))
    p0 = jax.device_get(steps.tree_to_host(base.step_state["params"]))
    p1 = jax.device_get(steps.tree_to_host(zero.step_state["params"]))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), p0, p1)


def test_zero1_bit_equal_under_3d_mesh(mesh8):
    """dp=2 × pp=2 × tp=2: leaves sharded over ONE model axis but replicated
    over the other must anchor per-axis (the all-or-nothing anchor failed
    compile here)."""
    def make(zero):
        mesh = worker_mesh(2, tp=2, pp=2)
        cfg = {**TP_LM, "mesh": mesh, "size": 2, "rank": 0, "tp": 2,
               "pp": 2, "pp_microbatches": 2, "zero_opt": zero,
               "optimizer": "adam"}
        return TransformerLM(cfg)
    base, zero = make(False), make(True)
    c0 = _train(base, BSP_Exchanger(base.config), 3)
    c1 = _train(zero, BSP_Exchanger(zero.config), 3)
    np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
