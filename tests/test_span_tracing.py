"""Cross-process causal tracing (utils/tracing.py, docs/design.md §17):
span context over the wire, server-side time split, trace assembly with
critical paths, chaos-path dedup semantics, statusz/fleetz — and the
elastic chaos acceptance gate (center kill + net dup → joined traces)."""

import glob
import importlib.util
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from theanompi_tpu.parallel import membership as mb
from theanompi_tpu.parallel import wire
from theanompi_tpu.parallel.center_server import CenterServer, RemoteCenter
from theanompi_tpu.utils import chaos, telemetry, tracing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _report_mod():
    path = os.path.join(REPO, "scripts", "telemetry_report.py")
    spec = importlib.util.spec_from_file_location("_span_test_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def traced_stream(tmp_path):
    """Process-global telemetry stream + enabled tracer (the server
    handler reads telemetry.active(), so the global must be live);
    restored to disabled afterwards so other tests stay unaffected."""
    d = str(tmp_path / "stream")
    tm = telemetry.init({"record_dir": d, "rank": 0,
                         "telemetry_flush_every": 1})
    tr = tracing.init({"tracing": True})
    yield d, tm, tr
    telemetry.init({"telemetry": False})
    tracing.init({})


def _events(record_dir):
    out = []
    for p in sorted(glob.glob(os.path.join(record_dir,
                                           "telemetry_rank*.jsonl"))):
        with open(p) as f:
            for line in f:
                if line.strip():
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        pass
    return out


def _spans(events):
    return [e for e in events if e.get("ev") == "span"]


# -- tracer unit surface ------------------------------------------------------

def test_tracer_disabled_is_inert_and_default():
    tracing.init({})
    tr = tracing.active()
    assert tr.enabled is False
    assert tr.begin("round") is None          # call sites guard on enabled
    # tracing=true without telemetry stays disabled: spans ride the stream
    telemetry.init({"telemetry": False})
    assert tracing.init({"tracing": True}).enabled is False


def test_span_ids_hierarchy_and_event_schema():
    tm = telemetry.Telemetry(rank=3, run_id="t")
    tr = tracing.Tracer(telemetry_=tm)
    rnd = tr.begin("round", island=2)
    assert tr.current["span"] == rnd.span     # statusz current-span view
    child = rnd.child("wire.push")
    assert child.trace == rnd.trace and child.parent == rnd.span
    assert child.span != rnd.span
    rnd.note(train_s=0.5)
    ev = rnd.end(outcome="exchanged")
    assert tr.spans == 1 and tr.current is None
    for k in tracing.SPAN_FIELDS:
        if k != "parent":                     # root: parent=None omitted
            assert k in ev, (k, ev)
    streamed = [e for e in tm.tail(4) if e["ev"] == tracing.SPAN_EVENT]
    assert streamed and streamed[-1]["name"] == "round"
    assert streamed[-1]["train_s"] == 0.5
    assert streamed[-1]["outcome"] == "exchanged"
    # ids are unique across mints
    ids = {tracing.new_span_id() for _ in range(64)}
    assert len(ids) == 64


# -- propagation over the wire ------------------------------------------------

def test_wire_span_propagation_and_server_split(traced_stream):
    """One traced round against a live center: the client's wire spans
    and the server's handler spans share the trace id, parent-chain
    correctly, and carry the queue/apply split; an UNtraced op still
    feeds the wire.server_queue/apply histograms (satellite: RTT stays
    decomposable with tracing disabled)."""
    d, tm, tr = traced_stream
    srv = CenterServer(alpha=0.5)
    host, port = srv.start()
    try:
        c = RemoteCenter(f"{host}:{port}", alpha=0.5, client_id="w1")
        c.ensure_init({"w": np.ones(3, np.float32)})
        rnd = tr.begin("round", island=1)
        _ = c.pull(trace=rnd.ctx())
        c.push_delta({"w": np.full(3, 2.0, np.float32)}, island=1,
                     trace=rnd.ctx())
        rnd.end(outcome="exchanged")
        c.push_delta({"w": np.full(3, 1.0, np.float32)}, island=1)  # untraced
        c.close()
    finally:
        srv.stop()
    tm.close()
    spans = _spans(_events(d))
    rounds = [s for s in spans if s["name"] == "round"]
    wires = [s for s in spans if s["name"].startswith("wire.")]
    servers = [s for s in spans if s["side"] == "server"]
    assert len(rounds) == 1 and len(wires) == 2 and len(servers) == 2
    tid = rounds[0]["trace"]
    assert all(s["trace"] == tid for s in wires + servers)
    assert all(w["parent"] == rounds[0]["span"] for w in wires)
    wire_ids = {w["span"] for w in wires}
    assert all(s["parent"] in wire_ids for s in servers)
    # the push's server span carries the q/a split; the wire span echoes it
    push_srv = [s for s in servers if s["name"] == "center.push"][0]
    assert push_srv.get("q") is not None and push_srv.get("a") is not None
    push_wire = [w for w in wires if w["name"] == "wire.push"][0]
    assert push_wire.get("a") == push_srv["a"]
    # histograms fed by EVERY reply (3 traced+untraced center ops + init)
    summ = json.load(open(os.path.join(d, "telemetry_summary_rank0.json")))
    for k in ("wire.server_queue", "wire.server_apply"):
        assert summ["hist"][k]["count"] >= 4, (k, summ["hist"].get(k))


def test_chaos_dup_yields_one_applied_span_and_tagged_twin(traced_stream):
    """THE chaos-path pin: a ChaosProxy-duplicated push produces exactly
    ONE applied server span joined to the client span; the deduped twin
    is tagged `dedup` and the assembled critical path counts it never."""
    d, tm, tr = traced_stream
    srv = CenterServer(alpha=0.5)
    host, port = srv.start()
    proxy = chaos.ChaosProxy(f"{host}:{port}",
                             chaos.parse_schedule("net_dup@0:-1:60"))
    paddr = proxy.start()
    try:
        c = RemoteCenter(paddr, alpha=0.5, client_id="w1")
        c.ensure_init({"w": np.ones(3, np.float32)})
        rnd = tr.begin("round", island=1)
        time.sleep(0.01)
        c.push_delta({"w": np.full(3, 2.0, np.float32)}, island=1,
                     trace=rnd.ctx())
        rnd.end(outcome="exchanged")
        assert srv.center.n_updates == 1          # applied exactly once
        # the proxy forwards the duplicated frame concurrently with the
        # original's reply — the client can return before the twin has
        # been SERVED; wait (bounded) for it to land before judging the
        # dedup bookkeeping and the twin's span below
        deadline = time.time() + 10.0
        while srv.dedup.hits < 1 and time.time() < deadline:
            time.sleep(0.02)
        assert srv.dedup.hits >= 1
        c.close()
    finally:
        proxy.stop()
        srv.stop()
    tm.close()
    events = _events(d)
    spans = _spans(events)
    push_wire = [s for s in spans if s["name"] == "wire.push"]
    assert len(push_wire) == 1                    # retries/dups share ONE span
    servers = [s for s in spans if s["side"] == "server"
               and s["name"] == "center.push"]
    applied = [s for s in servers if not s.get("dedup")]
    twins = [s for s in servers if s.get("dedup")]
    assert len(applied) == 1 and len(twins) >= 1
    assert all(s["parent"] == push_wire[0]["span"] for s in servers)
    rep = _report_mod()
    traces = rep.assemble_traces(events)
    rounds = [t for t in traces if t["name"] == "round"]
    assert len(rounds) == 1
    t = rounds[0]
    assert t["joined"] == 1 and t["unjoined"] == 0
    assert t["dedup_twins"] >= 1
    # the twin never double-counts: apply charged once, from the applied span
    assert t["components"]["apply"] <= float(applied[0]["a"]) + 1e-6 + \
        float(applied[0].get("q", 0))


def test_corrupt_retry_shares_trace_and_joins_once(traced_stream):
    """A corrupted request is retried under the SAME token and trace ids:
    one client wire span (retries counted), exactly one applied server
    span, trace joined."""
    d, tm, tr = traced_stream
    srv = CenterServer(alpha=0.5)
    host, port = srv.start()
    t0 = time.time()
    proxy = chaos.ChaosProxy(f"{host}:{port}",
                             chaos.parse_schedule("net_corrupt@0:-1:0.6"),
                             t0=t0)
    paddr = proxy.start()
    try:
        c = RemoteCenter(paddr, alpha=0.5, client_id="w1",
                         op_timeout_s=1.0, max_retries=20, deadline_s=30)
        c._wire.backoff = mb.Backoff(base=0.05, cap=0.2)
        c.ensure_init({"w": np.ones(3, np.float32)})   # corrupt window bites
        rnd = tr.begin("round", island=1)
        c.push_delta({"w": np.full(3, 2.0, np.float32)}, island=1,
                     trace=rnd.ctx())
        rnd.end(outcome="exchanged")
        c.close()
    finally:
        proxy.stop()
        srv.stop()
    tm.close()
    events = _events(d)
    spans = _spans(events)
    push_wire = [s for s in spans if s["name"] == "wire.push"]
    assert len(push_wire) == 1
    applied = [s for s in spans if s["side"] == "server"
               and s["name"] == "center.push" and not s.get("dedup")]
    assert len(applied) == 1
    assert applied[0]["trace"] == push_wire[0]["trace"]
    assert srv.center.n_updates == 1


def test_partition_round_fails_then_next_round_joins(traced_stream):
    """A ChaosProxy partition mid-round: the wire span ends ok=false (the
    round is `skipped`, joined to nothing), and the FIRST round after the
    window heals joins an applied server span again — outage and recovery
    both visible in the assembled trace."""
    d, tm, tr = traced_stream
    srv = CenterServer(alpha=0.5)
    host, port = srv.start()
    sched = chaos.parse_schedule("net_partition@0:-1:1.0")
    proxy = chaos.ChaosProxy(f"{host}:{port}", sched,
                             t0=time.time() + 3600)    # armed manually
    paddr = proxy.start()
    try:
        c = RemoteCenter(paddr, alpha=0.5, client_id="w1",
                         op_timeout_s=0.4, max_retries=2, deadline_s=1.0)
        c._wire.backoff = mb.Backoff(base=0.05, cap=0.1)
        c.ensure_init({"w": np.ones(3, np.float32)})   # pre-partition
        proxy.t0 = time.time()                         # window opens NOW
        time.sleep(0.05)
        rnd1 = tr.begin("round", island=1)
        with pytest.raises(wire.WireGiveUp):
            c.push_delta({"w": np.full(3, 2.0, np.float32)}, island=1,
                         trace=rnd1.ctx())
        rnd1.end(outcome="skipped")
        time.sleep(max(0.0, proxy.t0 + 1.2 - time.time()))   # heal
        rnd2 = tr.begin("round", island=1)
        c.push_delta({"w": np.full(3, 2.0, np.float32)}, island=1,
                     trace=rnd2.ctx())
        rnd2.end(outcome="exchanged")
        c.close()
    finally:
        proxy.stop()
        srv.stop()
    tm.close()
    events = _events(d)
    rep = _report_mod()
    traces = {t["outcome"]: t for t in rep.assemble_traces(events)
              if t["name"] == "round"}
    assert traces["skipped"]["joined"] == 0
    assert traces["skipped"]["unjoined"] == 1
    assert traces["exchanged"]["joined"] == 1
    assert srv.center.n_updates == 1                   # only the healed push


def test_giveup_ends_span_with_failure(traced_stream):
    """A partitioned/dead center still ENDS the wire span (ok=false, the
    error carried) so a round through an outage assembles instead of
    leaking an unfinished trace."""
    d, tm, tr = traced_stream
    rnd = tr.begin("round", island=1)
    client = wire.WireClient("127.0.0.1:9", client_id="w1",
                             connect_timeout_s=0.2, op_timeout_s=0.2,
                             max_retries=1, deadline_s=1.0,
                             backoff=mb.Backoff(base=0.02, cap=0.05))
    with pytest.raises(wire.WireGiveUp):
        client.request({"op": "pull"}, trace=rnd.ctx())
    rnd.end(outcome="skipped")
    tm.close()
    spans = _spans(_events(d))
    pulls = [s for s in spans if s["name"] == "wire.pull"]
    assert len(pulls) == 1 and pulls[0]["ok"] is False
    assert "err" in pulls[0]
    rounds = [s for s in spans if s["name"] == "round"]
    assert rounds and rounds[0]["outcome"] == "skipped"


# -- assembly / critical path / root cause on synthetic streams ---------------

def _synthetic_round(rank, t0, compute_s, wire_s, q, a, trace=None):
    """One round + wire + applied server span triple as raw events."""
    trace = trace or tracing.new_trace_id()
    rid, wid, sid = (tracing.new_span_id() for _ in range(3))
    dt = compute_s + wire_s + q + a
    return [
        {"ev": "span", "ts": t0 + dt, "rank": rank, "name": "round",
         "side": "client", "trace": trace, "span": rid, "parent": None,
         "t0": t0, "dt": dt, "outcome": "exchanged"},
        {"ev": "span", "ts": t0 + dt, "rank": rank, "name": "wire.push",
         "side": "client", "trace": trace, "span": wid, "parent": rid,
         "t0": t0 + compute_s, "dt": wire_s + q + a, "q": q, "a": a,
         "ok": True},
        {"ev": "span", "ts": t0 + dt, "rank": -1, "name": "center.push",
         "side": "server", "trace": trace, "span": sid, "parent": wid,
         "t0": t0 + compute_s, "dt": q + a, "q": q, "a": a, "ok": True},
    ]


def test_assemble_critical_path_and_root_cause():
    rep = _report_mod()
    events = []
    t = 1000.0
    # rank 1: compute-bound; rank 2: queue-bound
    for i in range(4):
        events += _synthetic_round(1, t + i, compute_s=0.8, wire_s=0.05,
                                   q=0.01, a=0.02)
        events += _synthetic_round(2, t + i, compute_s=0.1, wire_s=0.05,
                                   q=0.6, a=0.02)
    traces = rep.assemble_traces(events)
    assert len(traces) == 8
    for tr_ in traces:
        assert abs(sum(tr_["components"].values()) - tr_["dt"]) <= \
            0.05 * tr_["dt"] + 1e-9
        assert tr_["joined"] == 1
    rc = rep.straggler_root_cause(events, window_s=2.0)
    assert rc[1]["dominant"] == "compute"
    assert rc[2]["dominant"] == "queue"
    assert rc[2]["rounds"] == 4 and rc[2]["windows"] >= 1
    summary = rep.trace_summary(events, window_s=2.0)
    assert summary["rounds"] == 8 and summary["join_rate"] == 1.0
    assert set(summary["components_total_s"]) == set(rep.TRACE_COMPONENTS)


def test_check_stragglers_cites_root_cause_component():
    """The demote event names the dominant component from the root-cause
    table — 'demoted: straggler' comes with a cause."""
    tm = telemetry.Telemetry(rank=0, run_id="rc")
    ctl = mb.MembershipController(telemetry_=tm, straggle_windows=2)
    ctl.join(1)
    ctl.join(2)
    ctl._root_cause = {2: {"dominant": "queue", "dominant_share": 0.7}}
    ranking = [{"rank": 2, "windows_straggled": 5,
                "mean_train_secs": 0.9},
               {"rank": 1, "windows_straggled": 0,
                "mean_train_secs": 0.1}]
    assert ctl.check_stragglers(ranking) == [2]
    demotes = [e for e in tm.tail(8) if e["ev"] == "worker_demote"]
    assert demotes and demotes[-1]["component"] == "queue"


def test_report_since_and_last_window_filtering(tmp_path):
    d = str(tmp_path)
    tm = telemetry.Telemetry(rank=0, run_id="w", stream_dir=d,
                             flush_every=1)
    rep = _report_mod()
    # hand-stamp phases at controlled times via direct event writes
    for i in range(10):
        tm.event("phase", sec="train", dt=0.01)
    tm.close()
    # rewrite ts fields to a spread so the window bites deterministically
    path = os.path.join(d, "telemetry_rank0.jsonl")
    lines = [json.loads(ln) for ln in open(path) if ln.strip()]
    for i, ev in enumerate(lines):
        ev["ts"] = 1000.0 + i
    with open(path, "w") as f:
        for ev in lines:
            f.write(json.dumps(ev) + "\n")
    assert len(rep.load_events(d)) == len(lines)
    windowed = rep.load_events(d, since=1005.0)
    assert windowed and all(e["ts"] >= 1005.0 for e in windowed)
    assert len(windowed) == len(lines) - 5
    lo, hi = rep.stream_extent(d)
    assert lo == 1000.0 and hi == 1000.0 + len(lines) - 1
    # the CLI path: --last uses the extent, prints a windowed report
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "telemetry_report.py"),
         d, "--last", "3"], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "phase breakdown" in out.stdout


# -- statusz / fleetz ---------------------------------------------------------

def test_statusz_health_events_and_fleetz(tmp_path):
    d = str(tmp_path)
    tm = telemetry.Telemetry(rank=1, run_id="sz", stream_dir=d)
    tr = tracing.Tracer(telemetry_=tm)
    tr.begin("round", island=1)              # live current-span
    sz = tracing.StatuszServer("worker", ident=1, run_dir=d,
                               telemetry_=tm, tracer_=tr,
                               extra=lambda: {"steps": 42})
    host, port = sz.start()
    try:
        rep = tracing.statusz_query(f"{host}:{port}", "health")
        assert rep["ok"] and rep["role"] == "worker" and rep["id"] == 1
        assert rep["steps"] == 42
        assert rep["current_span"]["name"] == "round"
        for k in tracing.STATUSZ_FIELDS:
            assert k in rep, k
        evs = tracing.statusz_query(f"{host}:{port}", "events", n=4)
        assert evs["ok"] and isinstance(evs["events"], list)
        bad = tracing.statusz_query(f"{host}:{port}", "nope")
        assert bad["ok"] is False and "unknown" in bad["error"]
        # fleetz aggregates the roster (this live one + a ghost)
        ghost = os.path.join(tracing.statusz_dir(d), "center_-1.json")
        with open(ghost, "w") as f:
            json.dump({"role": "center", "id": -1, "pid": 99999,
                       "host": "127.0.0.1", "port": 9}, f)
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "fleetz.py"),
             d, "--json"], capture_output=True, text=True)
        assert out.returncode == 2, out.stderr        # a DOWN row present
        fleet = json.loads(out.stdout)["fleet"]
        by_role = {r["role"]: r for r in fleet}
        assert by_role["worker"]["ok"] and by_role["worker"]["spans"] == 0
        assert by_role["center"].get("down") is True
    finally:
        sz.stop()
        tm.close()
    assert not os.path.exists(os.path.join(tracing.statusz_dir(d),
                                           "worker_1.json"))
    # a CRASH exit path (stop(deregister=False)) keeps the discovery doc
    # so fleetz lists the process DOWN instead of losing it from the
    # roster (SIGKILL — no stop() at all — gets the same verdict)
    tm2 = telemetry.Telemetry(rank=2, run_id="sz2", stream_dir=d)
    sz2 = tracing.StatuszServer("worker", ident=2, run_dir=d,
                                telemetry_=tm2)
    sz2.start()
    sz2.stop(deregister=False)
    tm2.close()
    ghost2 = os.path.join(tracing.statusz_dir(d), "worker_2.json")
    assert os.path.exists(ghost2)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "fleetz.py"),
         d, "--json"], capture_output=True, text=True)
    assert out.returncode == 2
    fleet = json.loads(out.stdout)["fleet"]
    down = [r for r in fleet if str(r.get("id")) == "2"]
    assert down and down[0].get("down") is True


def test_island_round_spans_measure_stage(tmp_path):
    """The §17 stage component is MEASURED, not decorative: an in-process
    island running under tracing attaches recorder-derived stage_s
    (load + host staging) to every round span, and with telemetry on the
    island's brackets stream phase events for the straggler ranking."""
    from tests.conftest import TinyModel
    from theanompi_tpu.parallel.async_easgd import AsyncEASGDTrainer

    d = str(tmp_path / "stream")
    telemetry.init({"record_dir": d, "rank": 0,
                    "telemetry_flush_every": 1})
    tracing.init({"tracing": True})
    try:
        def factory(cfg):
            cfg = dict(cfg)
            cfg["verbose"] = False
            cfg.setdefault("batch_size", 8)
            return TinyModel(cfg)

        trainer = AsyncEASGDTrainer(factory, {
            "async_islands": 1, "sync_freq": 2, "seed": 3,
            "batch_size": 8})
        trainer.start()
        isl = trainer.islands[0]
        deadline = time.time() + 180
        while isl.exchanges_done < 3 and time.time() < deadline:
            assert isl.error is None, isl.error
            time.sleep(0.05)
        trainer.stop_and_join(timeout=120)
    finally:
        tm = telemetry.active()
        tm.close()
        telemetry.init({"telemetry": False})
        tracing.init({})
    events = _events(d)
    rounds = [s for s in _spans(events) if s["name"] == "round"]
    assert len(rounds) >= 3
    assert all("stage_s" in r for r in rounds), rounds[0]
    assert all(r["stage_s"] >= 0 for r in rounds)
    # stage is bounded by the round itself
    assert all(r["stage_s"] <= r["dt"] + 1e-6 for r in rounds)
    # the island recorder's brackets stream phase events (straggler
    # ranking raw material) — train at minimum
    phases = {e.get("sec") for e in events if e.get("ev") == "phase"}
    assert "train" in phases and "load" in phases


# -- the acceptance gate: elastic chaos run with joined traces ----------------
# The ISSUE 11 acceptance (center SIGKILL + net_dup elastic run → ≥95%
# span join rate, per-round critical paths within 5%, root-cause table,
# Perfetto flow arrows, live statusz audit) rides the EXISTING round-14
# chaos gate — test_chaos.test_elastic_center_sigkill_recovers_without_
# world_restart now runs with tracing=true and asserts the full trace
# contract on the same run, so tier-1 pays for ONE elastic chaos world,
# not two.
