"""Asynchronous EASGD: worker islands around a host-side center.

The defining EASGD property the synchronous-cadence exchanger cannot show
(SURVEY.md §3.2, VERDICT round-1 Missing #3): a straggler must not block the
others.  Islands run their own compiled programs from their own threads, so
a deliberately throttled island lags while the rest keep exchanging with the
center.
"""

import numpy as np
import pytest

from tests.conftest import TinyModel
from theanompi_tpu.parallel.async_easgd import AsyncEASGDTrainer, ElasticCenter


def _factory(cfg):
    cfg = dict(cfg)
    cfg["verbose"] = False
    cfg.setdefault("batch_size", 8)
    return TinyModel(cfg)


def test_slow_island_does_not_block_fast_one():
    import time
    tr = AsyncEASGDTrainer(_factory, {
        "async_islands": 2, "alpha": 0.5, "sync_freq": 2, "seed": 3})
    # island 1 wedges (sleeps 15s after each step) — under a synchronous
    # cadence NOTHING could exchange while it sleeps.  The fast island must
    # keep stepping AND exchanging with the center regardless.  (Rate-ratio
    # comparisons are fragile under CI CPU contention — sleeps still elapse
    # while compute threads starve — so assert unblocked progress instead.)
    tr.start(throttle={1: 15.0})
    fast, slow = tr.islands
    deadline = time.time() + 120
    while fast.exchanges_done < 3 and time.time() < deadline:
        time.sleep(0.05)
    f_steps, f_exch = fast.steps_done, fast.exchanges_done
    s_steps = slow.steps_done
    tr.stop_and_join(timeout=60)
    assert fast.error is None and slow.error is None
    assert f_exch >= 3, (
        f"fast island exchanged only {f_exch}× in 120s while the slow "
        f"island slept — it is being blocked")
    assert f_steps >= 6
    assert s_steps <= 2                  # the wedged island truly lagged
    assert tr.center.updates_by_island.get(0, 0) >= 3
    # center bookkeeping stays consistent (the wedged island may or may not
    # have reached its first exchange before the stop)
    assert tr.center.n_updates == sum(tr.center.updates_by_island.values())


def test_easgd_rule_async_mode():
    """The reference 3-call session API selects the async path by config."""
    import theanompi_tpu as tmpi
    rule = tmpi.EASGD()
    rule.init(devices=4, modelfile="tests.conftest", modelclass="TinyModel",
              easgd_mode="async", async_islands=2, sync_freq=2,
              run_seconds=4.0, batch_size=8, verbose=False)
    tr = rule.wait()
    assert tr.center.n_updates > 0
    assert len(tr.islands) == 2
    assert all(i.error is None for i in tr.islands)


def test_async_easgd_drives_the_transformer():
    """The islands machinery is model-agnostic: the LM family trains under
    genuinely asynchronous EASGD through the same session config."""
    import jax.numpy as jnp
    import theanompi_tpu as tmpi
    rule = tmpi.EASGD()
    rule.init(devices=4,
              modelfile="theanompi_tpu.models.transformer_lm",
              modelclass="TransformerLM",
              easgd_mode="async", async_islands=2, sync_freq=2,
              run_seconds=30.0, batch_size=8, seq_len=16, vocab=32,
              d_model=32, n_head=4, n_layer=1, synthetic_train=64,
              compute_dtype="float32", verbose=False)
    tr = rule.wait()
    assert tr.center.n_updates > 0
    assert all(i.error is None for i in tr.islands)
    assert all(i.steps_done > 0 for i in tr.islands)


def test_center_update_algebra():
    """center += α·mean_i delta_i, serialized under the lock."""
    params = {"w": np.zeros((2,), np.float32)}
    c = ElasticCenter(params, alpha=0.5)
    c.push_delta({"w": np.array([1.0, 2.0], np.float32)}, island=0)
    np.testing.assert_allclose(c.pull()["w"], [0.5, 1.0])
    c.push_delta({"w": np.array([1.0, 0.0], np.float32)}, island=1)
    np.testing.assert_allclose(c.pull()["w"], [1.0, 1.0])
    assert c.n_updates == 2


def test_async_easgd_trains():
    """End to end: the consensus (center) must actually learn — its loss on
    the islands' task decreases versus the initial parameters."""
    import jax
    import jax.numpy as jnp
    from tests.conftest import SyntheticData
    from theanompi_tpu.models import layers as L

    tr = AsyncEASGDTrainer(_factory, {
        "async_islands": 2, "alpha": 0.5, "sync_freq": 2, "seed": 3})
    # the center lazy-inits from the first island; its t=0 value equals any
    # same-seeded model's init params
    p0 = jax.device_get(_factory({"n_workers": 1}).params)
    tr.run_for(3.0)

    data = SyntheticData({"size": 1}, batch_size=64)
    b = data.next_train_batch(0)
    model = _factory({"n_workers": 1})

    def loss(p):
        logits, _ = model.seq.apply(
            jax.tree.map(jnp.asarray, p), jnp.asarray(b["x"]),
            train=False, state={})
        return float(L.softmax_cross_entropy(logits, jnp.asarray(b["y"])))

    assert loss(tr.center_params) < loss(p0)

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
