"""PowerSGD rank-r gradient compression (parallel/strategies.py PowerSGD):
exactness when the rank covers the mean, error-feedback accounting,
cross-worker bit-consistency, and end-to-end training."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tests.conftest import TinyModel
from tests.test_strategies import N, _mk_tree, _oracle_mean, _run_strategy
from theanompi_tpu.parallel.exchanger import BSP_Exchanger
from theanompi_tpu.parallel.strategies import PowerSGD, get_strategy


def _mk_matrix_tree(seed=0, rank=None, rows=24, cols=16):
    """Boxed per-worker tree with one compressible matrix leaf (optionally
    of known low rank) and one exact-path vector leaf."""
    r = np.random.RandomState(seed)
    if rank is None:
        w = r.randn(N, rows, cols)
    else:
        # per-worker low-rank matrices SHARING a column space, so the mean
        # stays within it and rank-r decode can be exact
        u = r.randn(rows, rank)
        w = np.einsum("ik,wkj->wij", u, r.randn(N, rank, cols))
    return {"w": w.astype(np.float32),
            "b": r.randn(N, 11).astype(np.float32)}


def test_registry_names():
    assert get_strategy("powersgd").rank == 2
    assert get_strategy("powersgd4").rank == 4
    assert get_strategy("powersgd1").name == "powersgd1"


def test_exact_when_rank_covers_the_mean(mesh8):
    """If the workers' matrices share an r-dimensional column space, the
    orthonormal basis spans the mean exactly: decode == psum oracle."""
    strat = PowerSGD(rank=3)
    tree = _mk_matrix_tree(1, rank=3)
    out, _ = _run_strategy(mesh8, strat, tree)
    expect = _oracle_mean(tree)
    got = np.asarray(out["w"])
    for w in range(N):
        np.testing.assert_allclose(got[w], expect["w"], rtol=1e-4,
                                   atol=1e-5)
    # the vector leaf takes the exact psum path regardless
    np.testing.assert_allclose(np.asarray(out["b"])[0], expect["b"],
                               rtol=1e-5, atol=1e-6)


def test_decode_identical_across_workers_and_ef_accounting(mesh8):
    """Full-rank inputs: the decode is lossy but (a) every worker decodes
    the SAME matrix (BSP replicas stay identical) and (b) the residual is
    accounted exactly: e' = (M + e) − M̂ per worker."""
    strat = PowerSGD(rank=2)
    tree = _mk_matrix_tree(2)                    # full-rank
    out, new_state = _run_strategy(mesh8, strat, tree)
    got = np.asarray(out["w"])
    for w in range(1, N):
        np.testing.assert_array_equal(got[w], got[0])
    # error feedback: M' − M̂ (initial e is zero, so M' = M).  State
    # entries align with tree_flatten leaf order: "b" < "w", so the
    # matrix leaf's state is entry 1.
    e = np.asarray(jax.device_get(new_state)[1]["e"])
    for w in range(N):
        np.testing.assert_allclose(e[w], tree["w"][w] - got[w],
                                   rtol=1e-5, atol=1e-6)
    # decode + residual reconstructs the input exactly (nothing is lost
    # from the fp32 master stream)
    np.testing.assert_allclose(e[0] + got[0], tree["w"][0],
                               rtol=1e-5, atol=1e-6)


def test_ef_conservation_identity(mesh8):
    """The defining error-feedback identity, exact by induction on
    ē_t = ē_{t-1} + mean − M̂_t:   Σ_{s≤t} M̂_s = t·mean − mean_w(e_w,t).
    Nothing ever leaks from the fp32 master stream, however lossy each
    individual decode is (the Σα-conservation analogue for PowerSGD)."""
    strat = PowerSGD(rank=1)
    tree = _mk_matrix_tree(3)                    # isotropic = worst case
    expect = _oracle_mean(tree)["w"]
    state = None
    decoded_sum = 0.0
    for it in range(5):
        out, state = _run_strategy(mesh8, strat, tree, state_boxed=state)
        decoded_sum = decoded_sum + np.asarray(out["w"])[0]
        e_mean = np.asarray(jax.device_get(state)[1]["e"]).mean(axis=0)
        np.testing.assert_allclose(decoded_sum,
                                   (it + 1) * expect - e_mean,
                                   rtol=1e-4, atol=1e-4)


def test_ef_recovers_a_low_rank_signal_under_noise(mesh8):
    """Realistic-spectrum progress: per-worker gradients = shared rank-3
    signal + per-worker noise.  A rank-3 compressor's cumulative decode
    must converge to the signal mean far faster than the noise floor."""
    r = np.random.RandomState(5)
    u = r.randn(24, 3)
    signal = np.einsum("ik,wkj->wij", u, r.randn(N, 3, 16))
    tree = {"w": (signal + 0.05 * r.randn(N, 24, 16)).astype(np.float32),
            "b": r.randn(N, 11).astype(np.float32)}
    expect = _oracle_mean(tree)["w"]
    noise_mean = expect - signal.mean(axis=0)    # the uncapturable floor
    floor = np.linalg.norm(noise_mean)
    strat = PowerSGD(rank=3)
    state = None
    decoded_sum = 0.0
    errs = []
    for it in range(6):
        out, state = _run_strategy(mesh8, strat, tree, state_boxed=state)
        decoded_sum = decoded_sum + np.asarray(out["w"])[0]
        errs.append(np.linalg.norm(decoded_sum / (it + 1) - expect))
    # the signal mean is captured immediately; what remains is (at most)
    # the rank-3-invisible part of the noise mean, and it never diverges
    assert errs[1] < 0.55 * errs[0], errs
    assert errs[-1] < 1.1 * floor, (errs, floor)
    assert errs[-1] < errs[1] * 1.05, errs


def test_trains_end_to_end_and_stays_identical(mesh4):
    """TinyModel under powersgd: loss decreases and the BSP replicas stay
    bit-identical (every worker decodes the same update)."""
    cfg = {"mesh": mesh4, "size": 4, "rank": 0, "verbose": False,
           "exch_strategy": "powersgd2", "n_train": 512}
    m = TinyModel(cfg)
    m.compile_iter_fns(BSP_Exchanger(cfg))
    m.data.shuffle_data(0)
    costs = []
    for i in range(12):
        m.train_iter(i, None)
        costs.append(float(m.current_info["cost"]))
    assert np.mean(costs[-4:]) < np.mean(costs[:4])
    p = jax.device_get(m.step_state["params"])
    for leaf in jax.tree.leaves(p):
        arr = np.asarray(leaf)
        for w in range(1, 4):
            np.testing.assert_array_equal(arr[w], arr[0])


def test_composes_with_zero_and_spc(mesh4):
    """Every worker decodes the SAME update, so ZeRO's slice-my-chunk
    assumption holds under powersgd; steps_per_call's fused-exchange
    requirement holds too (grads mode, no post-step collective).  The
    spc=2 run must match two single-step dispatches bit-for-bit."""
    def make(**kw):
        cfg = {"mesh": mesh4, "size": 4, "rank": 0, "verbose": False,
               "exch_strategy": "powersgd2", "n_train": 512, **kw}
        m = TinyModel(cfg)
        m.compile_iter_fns(BSP_Exchanger(cfg))
        m.data.shuffle_data(0)
        return m

    one = make(zero_opt=True)
    for i in range(4):
        one.train_iter(i, None)
    spc = make(zero_opt=True, steps_per_call=2)
    for last in (1, 3):
        spc.train_iter(last, None)
    import numpy as _np
    jax.tree.map(lambda a, b: _np.testing.assert_array_equal(
        _np.asarray(jax.device_get(a)), _np.asarray(jax.device_get(b))),
        one.step_state["params"], spc.step_state["params"])


def test_composes_with_tensor_parallelism(mesh8):
    """Round-4 verdict #6 (the one strategy×parallelism hole): powersgd
    under tp.  Each tp rank compresses ITS local grad shard independently
    (the flat strategies' shard-wise composition), with the per-leaf Q/e
    state carried in a leading [prod(group)] axis sharded over 'model'.
    Loss trains down; EF state is genuinely per-rank; Q stays identical
    across the two WORKERS of each rank (the shared-Q invariant)."""
    from theanompi_tpu.models.transformer_lm import TransformerLM
    from theanompi_tpu.parallel.mesh import MODEL_AXIS, worker_mesh
    mesh = worker_mesh(2, tp=4)
    cfg = {"mesh": mesh, "size": 2, "rank": 0, "tp": 4, "verbose": False,
           "exch_strategy": "powersgd2", "batch_size": 8, "seq_len": 16,
           "vocab": 32, "d_model": 64, "n_head": 4, "n_layer": 2,
           "synthetic_train": 64, "synthetic_val": 32,
           "compute_dtype": jnp.float32}
    lm = TransformerLM(cfg)
    lm.compile_iter_fns(BSP_Exchanger(cfg))
    lm.data.shuffle_data(0)
    costs = []
    for i in range(8):
        lm.train_iter(i, None)
        costs.append(float(lm.current_info["cost"]))
    assert np.isfinite(costs).all(), costs
    assert np.mean(costs[-3:]) < np.mean(costs[:3]), costs

    state = lm.step_state["extra"]["strat"]
    # state arrays: [n_workers, tp, ...] sharded (workers, model)
    seen_ranked = rank_diff = False
    for st in state:
        q = np.asarray(jax.device_get(st["q"]))
        e = np.asarray(jax.device_get(st["e"]))
        if e.shape[-1] and e.shape[-2]:
            for key in ("q", "e"):   # empty sentinels lose their spec —
                leaf = st[key]       # only real state must shard (w, tp)
                spec = tuple(leaf.sharding.spec)
                assert spec[:2] == ("workers", MODEL_AXIS), \
                    (leaf.shape, spec)
            seen_ranked = True
            # the shared-Q invariant holds per tp rank: Q is a psum over
            # the worker axis, identical on both workers
            np.testing.assert_allclose(q[0], q[1], rtol=1e-5, atol=1e-6)
            # EF residuals are genuinely per-worker (different data)
            assert not np.allclose(e[0], e[1])
            # ...and per tp rank on SHARDED leaves (tp-replicated leaves
            # legitimately carry rank-identical residuals)
            rank_diff = rank_diff or not np.allclose(e[0, 0], e[0, 1])
    assert seen_ranked, "no compressible leaf exercised the tp state path"
    assert rank_diff, "no leaf showed per-tp-rank EF state"


def test_composes_with_sequence_parallelism(mesh8):
    """Regression (round-5 review): under sp the params are replicated
    (param_specs() is None) and the per-leaf state must stay in its plain
    layout — the leading-group-axis unwrap applies only to sharded-param
    models."""
    from theanompi_tpu.models.transformer_lm import TransformerLM
    from theanompi_tpu.parallel.mesh import worker_mesh
    mesh = worker_mesh(2, sp=4)
    cfg = {"mesh": mesh, "size": 2, "rank": 0, "sp": 4, "verbose": False,
           "exch_strategy": "powersgd2", "batch_size": 8, "seq_len": 32,
           "vocab": 32, "d_model": 64, "n_head": 4, "n_layer": 2,
           "synthetic_train": 64, "synthetic_val": 32,
           "compute_dtype": jnp.float32}
    lm = TransformerLM(cfg)
    lm.compile_iter_fns(BSP_Exchanger(cfg))
    lm.data.shuffle_data(0)
    costs = []
    for i in range(6):
        lm.train_iter(i, None)
        costs.append(float(lm.current_info["cost"]))
    assert np.isfinite(costs).all(), costs
    assert np.mean(costs[-2:]) < np.mean(costs[:2]), costs

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
