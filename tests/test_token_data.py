"""Token-file dataset (models/data/tokens.py): nanoGPT-style train.bin /
val.bin streams through the standard DataBase contract."""

import numpy as np
import pytest

import jax.numpy as jnp

from theanompi_tpu.models.data.tokens import TokenFileData
from theanompi_tpu.models.transformer_lm import TransformerLM
from theanompi_tpu.parallel.exchanger import BSP_Exchanger
from theanompi_tpu.parallel.mesh import worker_mesh


def _write_corpus(tmp_path, n_train=4096, n_val=1024, vocab=16):
    d = tmp_path / "toks"
    d.mkdir()
    # deterministic modular-increment stream: learnable next-token rule
    (np.arange(n_train, dtype=np.uint16) % vocab).tofile(d / "train.bin")
    (np.arange(n_val, dtype=np.uint16) % vocab).tofile(d / "val.bin")
    return str(d)


def test_windows_and_shifted_targets(tmp_path):
    root = _write_corpus(tmp_path)
    data = TokenFileData({"size": 2, "data_dir": root, "seq_len": 8},
                         batch_size=4)
    b = data.next_train_batch(0)          # unshuffled: identity permutation
    assert b["x"].shape == (8, 8) and b["y"].shape == (8, 8)
    # window i covers tokens [8i, 8i+8]; y is x shifted by one
    np.testing.assert_array_equal(b["x"][0], np.arange(8) % 16)
    np.testing.assert_array_equal(b["y"][0], np.arange(1, 9) % 16)
    np.testing.assert_array_equal(b["y"][:, :-1], b["x"][:, 1:])


def test_host_slices_partition(tmp_path):
    root = _write_corpus(tmp_path)
    cfg = {"size": 4, "data_dir": root, "seq_len": 8}
    whole = TokenFileData({**cfg, "process_count": 1}, batch_size=4)
    parts = [TokenFileData({**cfg, "process_count": 2, "process_index": h},
                           batch_size=4) for h in (0, 1)]
    for d in (whole, *parts):
        d.shuffle_data(42)
    g = whole.next_train_batch(0)
    a, b = (p.next_train_batch(0) for p in parts)
    np.testing.assert_array_equal(np.concatenate([a["x"], b["x"]]), g["x"])
    np.testing.assert_array_equal(np.concatenate([a["y"], b["y"]]), g["y"])


def test_cursor_roundtrip(tmp_path):
    root = _write_corpus(tmp_path)
    data = TokenFileData({"size": 2, "data_dir": root, "seq_len": 8},
                         batch_size=4)
    data.shuffle_data(7)
    data.next_train_batch(0)
    cur = data.get_cursor()
    want = data.next_train_batch(1)
    d2 = TokenFileData({"size": 2, "data_dir": root, "seq_len": 8},
                       batch_size=4)
    d2.set_cursor(cur)
    got = d2.next_train_batch(1)
    np.testing.assert_array_equal(got["x"], want["x"])


def test_lm_trains_and_generates_from_token_files(tmp_path, mesh8):
    # vocab COPRIME with seq_len so window starts cycle through all
    # residues — the +1 rule must be learned from content, not position
    root = _write_corpus(tmp_path, n_train=8192, vocab=13)
    mesh = worker_mesh(4)
    model = TransformerLM({
        "mesh": mesh, "size": 4, "rank": 0, "verbose": False,
        "data_dir": root, "batch_size": 8, "seq_len": 16, "vocab": 13,
        "d_model": 64, "n_head": 4, "n_layer": 2, "learning_rate": 3e-3,
        "compute_dtype": jnp.float32})
    model.compile_iter_fns(BSP_Exchanger(model.config))
    model.data.shuffle_data(0)
    costs = []
    for i in range(40):
        model.train_iter(i, None)
        costs.append(float(model.current_info["cost"]))
    assert costs[-1] < 0.5 * costs[0]
    out = model.generate(np.array([[3, 4, 5, 6]], np.int32),
                         max_new_tokens=6)
    np.testing.assert_array_equal(out[0], np.arange(7, 13) % 13)
    model.begin_val()
    model.val_iter(0, None)
    model.end_val()


def test_make_token_dataset_script(tmp_path):
    """Text → byte-token files → loadable by TokenFileData."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    txt = tmp_path / "corpus.txt"
    txt.write_text("hello token world! " * 400)
    out = tmp_path / "toks"
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts/make_token_dataset.py"),
         str(txt), "--out", str(out), "--val-frac", "0.1"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    data = TokenFileData({"size": 2, "data_dir": str(out), "seq_len": 16,
                          "vocab": 256}, batch_size=4)
    b = data.next_train_batch(0)
    assert b["x"].shape == (8, 16)
    # byte-level: tokens are the utf-8 bytes of the corpus
    assert bytes(b["x"][0].astype(np.uint8)).decode() in \
        "hello token world! " * 3


def test_missing_files_error(tmp_path):
    (tmp_path / "empty").mkdir()
    with pytest.raises(FileNotFoundError, match="token file"):
        TokenFileData({"size": 1, "data_dir": str(tmp_path / "empty"),
                       "seq_len": 8}, batch_size=4)


def test_vocab_guard_fires_with_model_default_vocab(tmp_path):
    """ADVICE r3: the out-of-range check must fire even when the user relies
    on the model's class-default vocab (no 'vocab' in config) — the model
    passes its RESOLVED vocab into TokenFileData."""
    root = _write_corpus(tmp_path, vocab=64)
    data = TokenFileData({"size": 1, "data_dir": root, "seq_len": 8},
                         batch_size=4, vocab=32)   # corpus ids reach 63
    with pytest.raises(AssertionError, match="vocab=32"):
        data.next_train_batch(0)
    # config['vocab'] still wins over the passed default when both exist
    data2 = TokenFileData({"size": 1, "data_dir": root, "seq_len": 8,
                           "vocab": 64}, batch_size=4, vocab=32)
    data2.next_train_batch(0)   # no raise
