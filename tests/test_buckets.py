"""Bucketed overlap-scheduled collectives (ISSUE 13 tentpole):
``parallel/buckets.py`` planner determinism and boundary cases, the
per-rule bucketed ≡ monolithic bit-identity contract, the
collectives-per-window count on a CPU devprof capture, and AOT cache key
sensitivity to ``bucket_bytes``.

The correctness contract this file pins (the way test_fused_exchange.py
pinned the PR 1 fusion): at fixed membership, the bucketed wire is a
SCHEDULE change only — every rule's exchange produces bit-identical
state whether the payload crosses as one monolith or as ~bucket_bytes
async start/done slices.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import TinyModel
from theanompi_tpu import jax_compat
from theanompi_tpu.parallel import buckets
from theanompi_tpu.parallel.exchanger import (ASGD_Exchanger, BSP_Exchanger,
                                              EASGD_Exchanger,
                                              GOSGD_Exchanger)
from theanompi_tpu.parallel.mesh import worker_mesh
from theanompi_tpu.utils import compile_cache, devprof


# -- the planner ------------------------------------------------------------

def _tree(**leaves):
    return dict(leaves)


def test_plan_deterministic_and_pure():
    """Same tree-def + shapes/dtypes → the same plan, values ignored."""
    t1 = _tree(a=jnp.zeros(100), b=jnp.ones(200), c=jnp.zeros(50))
    t2 = _tree(a=jnp.full(100, 7.0), b=jnp.zeros(200), c=jnp.ones(50))
    p1 = buckets.plan_buckets(t1, 512)
    p2 = buckets.plan_buckets(t2, 512)
    assert p1 == p2
    assert buckets.plan_signature(p1) == buckets.plan_signature(p2)
    # abstract avals plan identically (the AOT prewarm venue traces
    # shapes, never values)
    p3 = buckets.plan_buckets(jax.eval_shape(lambda: t1), 512)
    assert p3 == p1
    # every non-empty leaf lands in exactly one bucket, in tree order
    covered = [i for b in p1.buckets for i in b.leaf_ids]
    assert sorted(covered) == covered
    assert set(covered) | set(p1.empty_leaf_ids) == set(range(p1.n_leaves))


def test_plan_oversized_leaf_is_single_leaf_bucket():
    """A leaf ≥ bucket_bytes becomes its OWN bucket — never split
    mid-leaf, never merged with neighbors."""
    t = _tree(small=jnp.zeros(8), big=jnp.zeros(4096), tail=jnp.zeros(8))
    p = buckets.plan_buckets(t, 1024)          # big leaf = 16 KiB > 1 KiB
    big_buckets = [b for b in p.buckets if 4096 in b.sizes]
    assert len(big_buckets) == 1
    assert big_buckets[0].sizes == (4096,)     # alone in its bucket
    assert len(big_buckets[0].leaf_ids) == 1


def test_plan_mixed_dtypes_never_share_a_bucket():
    t = _tree(a=jnp.zeros(10, jnp.float32), b=jnp.zeros(10, jnp.bfloat16),
              c=jnp.zeros(10, jnp.float32), d=jnp.zeros(10, jnp.float32))
    p = buckets.plan_buckets(t, 1 << 20)
    for b in p.buckets:
        leaf_dts = {np.dtype(jnp.zeros(1, jnp.bfloat16).dtype).name
                    if i == 1 else "float32" for i in b.leaf_ids}
        assert len(leaf_dts) == 1 and b.dtype in leaf_dts
    # d cannot rejoin c's float32 bucket across the bfloat16 boundary
    # (tree order is preserved), so at least 3 buckets exist
    assert p.n_buckets >= 3


def test_plan_empty_and_scalar_leaves():
    t = _tree(a=jnp.zeros(()), b=jnp.zeros((0,)), c=jnp.zeros((4, 0)),
              d=jnp.zeros(3))
    p = buckets.plan_buckets(t, 1 << 20)
    assert p.empty_leaf_ids == (1, 2)          # zero-size: nothing to wire
    assert sum(b.size for b in p.buckets) == 4  # scalar counts as 1
    # pack/unpack round-trips the empty leaves verbatim
    vecs = buckets.pack(t, p)
    out = buckets.unpack(vecs, t, p)
    for a, b in zip(jax.tree.leaves(t), jax.tree.leaves(out)):
        assert a.shape == b.shape and a.dtype == b.dtype


def test_pack_unpack_bit_exact_round_trip():
    rng = np.random.RandomState(0)
    t = _tree(a=jnp.asarray(rng.randn(7, 3), jnp.float32),
              b=jnp.asarray(rng.randn(11), jnp.float32),
              c=jnp.asarray(rng.randn(2, 2, 2), jnp.float32))
    p = buckets.plan_buckets(t, 64)
    out = buckets.unpack(buckets.pack(t, p), t, p)
    for a, b in zip(jax.tree.leaves(t), jax.tree.leaves(out)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_plan_stable_under_membership_masking():
    """set_active_ranks scales VALUES, not shapes — the plan (and so the
    compiled collective schedule) is identical before and after a
    demotion, which is what keeps the masked-membership algebra exact
    per bucket."""
    mesh = worker_mesh(4)
    cfg = {"mesh": mesh, "size": 4, "rank": 0, "verbose": False,
           "batch_size": 8, "bucket_bytes": 256, "sync_freq": 1}
    model = TinyModel(cfg)
    exch = EASGD_Exchanger(cfg)
    model.compile_iter_fns(exch)
    sig_full = buckets.plan_signature(
        buckets.plan_buckets(model.params, exch.bucket_bytes))
    n_full = exch.n_buckets()
    exch.set_active_ranks((0, 2))
    sig_masked = buckets.plan_signature(
        buckets.plan_buckets(model.params, exch.bucket_bytes))
    assert sig_full == sig_masked and exch.n_buckets() == n_full


# -- the jax_compat shim ----------------------------------------------------

def test_shim_sync_fallback_round_trip():
    """Without a real async surface the start eagerly reduces and the
    done unwraps — the pair is still the one calling convention the
    bucketed wire (and tpulint's pairing probe) sees."""
    mesh = worker_mesh(4)
    from jax.sharding import PartitionSpec as P

    def f(x):
        t = jax_compat.psum_start(x, "workers")
        return jax_compat.psum_done(t)

    g = jax.jit(jax_compat.shard_map(f, mesh=mesh, in_specs=P("workers"),
                                     out_specs=P()))
    x = jnp.arange(8.0)
    np.testing.assert_allclose(np.asarray(g(x))[0], x.reshape(4, 2).sum(0)[0])


# -- per-rule bit-identity --------------------------------------------------

def _run(exch_cls, n_steps=4, spc=1, active=None, **kw):
    mesh = worker_mesh(4)
    cfg = {"mesh": mesh, "size": 4, "rank": 0, "verbose": False,
           "batch_size": 8, "steps_per_call": spc, **kw}
    model = TinyModel(cfg)
    exch = exch_cls(cfg)
    if active is not None:
        # demote BEFORE compile so both dispatch shapes trace the mask
        exch.mesh, exch.model = mesh, model
        exch.size = 4
        exch.set_active_ranks(active)
    model.compile_iter_fns(exch)
    model.data.shuffle_data(0)
    for count in range(spc, n_steps + 1, spc):
        model.train_iter(count, None)
        exch.exchange(None, count)
    return jax.device_get(model.step_state)


def _assert_bit_identical(a, b):
    for part in ("params", "opt_state", "extra"):
        for x, y in zip(jax.tree_util.tree_leaves(a[part]),
                        jax.tree_util.tree_leaves(b[part])):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                          err_msg=part)


@pytest.mark.parametrize("exch_cls,cfg", [
    (BSP_Exchanger, {}),                                   # fused psum wire
    (BSP_Exchanger, {"exch_strategy": "nccl16"}),          # bf16 wire cast
    (BSP_Exchanger, {"exch_mode": "params"}),              # post-step wire
    (BSP_Exchanger, {"exch_strategy": "onebit"}),          # packed signs
    (BSP_Exchanger, {"exch_strategy": "topk"}),            # sparse rows
    (BSP_Exchanger, {"exch_strategy": "powersgd"}),        # dense remainder
    (EASGD_Exchanger, {"sync_freq": 2}),
    (ASGD_Exchanger, {"sync_freq": 1}),
    (GOSGD_Exchanger, {"exch_prob": 0.9}),
    (GOSGD_Exchanger, {"exch_prob": 0.9, "gosgd_peers": "iid"}),
    (GOSGD_Exchanger, {"exch_prob": 0.9, "gosgd_peers": "shift"}),
], ids=["bsp-allreduce", "bsp-nccl16", "bsp-params", "bsp-onebit",
        "bsp-topk", "bsp-powersgd", "easgd", "asgd", "gosgd-perm",
        "gosgd-iid", "gosgd-shift"])
def test_bucketed_equals_monolithic(exch_cls, cfg):
    """THE acceptance contract: tiny buckets (many slices on this model)
    vs the monolithic wire, bit-for-bit across params, optimizer and
    rule state after several exchanges."""
    mono = _run(exch_cls, **cfg)
    buck = _run(exch_cls, bucket_bytes=256, **cfg)
    _assert_bit_identical(mono, buck)


def test_bucketed_equals_monolithic_fused_cadence():
    """The in-scan fused cadence (steps_per_call > 1) traces the same
    exchange_body — bucketing must survive the lax.cond/scan wrapping."""
    mono = _run(EASGD_Exchanger, spc=4, sync_freq=2)
    buck = _run(EASGD_Exchanger, spc=4, sync_freq=2, bucket_bytes=256)
    _assert_bit_identical(mono, buck)


def test_bucketed_masked_membership_bit_identity():
    """Demoted-rank algebra per bucket: with ranks (0, 2) active, the
    bucketed and monolithic EASGD exchanges still agree bit-for-bit —
    the mask scales values upstream of the pack."""
    mono = _run(EASGD_Exchanger, sync_freq=1, active=(0, 2))
    buck = _run(EASGD_Exchanger, sync_freq=1, active=(0, 2),
                bucket_bytes=256)
    _assert_bit_identical(mono, buck)


# -- collectives-per-window (devprof CPU capture) ---------------------------

def _window_allreduce_count(bucket_bytes, k=3, n=4):
    """all-reduce executions over a k-dispatch BSP window, driving
    train_fn directly (train_iter's cost-mean helper dispatches its own
    tiny all-reduce that would pollute the count)."""
    mesh = worker_mesh(n)
    cfg = {"mesh": mesh, "size": n, "rank": 0, "verbose": False,
           "batch_size": 8, "bucket_bytes": bucket_bytes}
    model = TinyModel(cfg)
    exch = BSP_Exchanger(cfg)
    model.compile_iter_fns(exch)
    model.data.shuffle_data(0)
    from theanompi_tpu.parallel import steps
    batch = steps.put_batch(mesh, model.data.next_train_batch(0),
                            model.batch_spec())
    lr = jnp.float32(0.05)
    rng = jax.random.key(0)
    st, _, _ = model.train_fn(model.step_state, batch, lr, rng,
                              jnp.int32(1))
    jax.block_until_ready(st["params"])         # compile outside window
    with devprof.capture() as cap:
        for count in range(2, 2 + k):
            st, _, _ = model.train_fn(st, batch, lr, rng, jnp.int32(count))
        jax.block_until_ready(st["params"])
    assert cap.profile is not None
    ops = {o["op"]: o["count"] for o in cap.profile["top_ops"]}
    # balanced start/done pairs: every async start class has an
    # equal-count done twin (vacuous on a sync-lowering backend)
    for op, c in ops.items():
        if op.endswith("-start"):
            assert ops.get(op[:-len("-start")] + "-done") == c, ops
    return (sum(c for op, c in ops.items()
                if op.startswith("all-reduce")), exch.n_buckets())


def test_bucketed_bsp_window_collective_count():
    """Structure verified without hardware: a devprof capture of a
    bucketed BSP window shows exactly n_buckets all-reduce executions
    per dispatch per device — the planner's count, not the leaf count
    the monolithic wire issues."""
    k, n = 3, 4
    n_ar, n_buckets = _window_allreduce_count(1024, k=k, n=n)
    assert n_buckets and n_buckets > 1, "buckets must slice TinyModel"
    assert n_ar == n_buckets * k * n, (n_ar, n_buckets)
    # the monolithic control: leaf-wise psums (one per param leaf — more
    # collectives than the planner's packed buckets on this model)
    n_ar_mono, nb_mono = _window_allreduce_count(0, k=k, n=n)
    assert nb_mono is None
    n_leaves = len(jax.tree.leaves(TinyModel(
        {"mesh": worker_mesh(n), "size": n, "rank": 0, "verbose": False,
         "batch_size": 8}).params))
    assert n_ar_mono == n_leaves * k * n
    assert n_ar != n_ar_mono                    # the schedule moved


# -- AOT cache key sensitivity ----------------------------------------------

def test_aot_key_extras_sensitive_to_bucket_bytes():
    """Two builds of the same rule at different bucket_bytes must never
    share an executable-cache entry (belt-and-braces over the HLO hash:
    key_extra carries the knob)."""
    mesh = worker_mesh(4)
    base = {"mesh": mesh, "size": 4, "rank": 0, "verbose": False,
            "batch_size": 8}
    model = TinyModel(base)
    e0 = BSP_Exchanger(base)
    e4 = BSP_Exchanger({**base, "bucket_bytes": 4 << 20})
    e1 = BSP_Exchanger({**base, "bucket_bytes": 1 << 20})
    x0 = compile_cache.key_extra("train", model, e0, spc=1)
    x4 = compile_cache.key_extra("train", model, e4, spc=1)
    x1 = compile_cache.key_extra("train", model, e1, spc=1)
    assert "bucket_bytes" not in x0              # monolithic: legacy key,
    #                                              prewarmed entries survive
    assert x4["bucket_bytes"] == 4 << 20 and x1["bucket_bytes"] == 1 << 20
    assert len({str(sorted(x.items())) for x in (x0, x4, x1)}) == 3


def test_bench_row_config_carries_bucket_bytes():
    """The one BENCH_* → config assembly hands the knob through, so the
    prewarm venue and the measurement request byte-identical programs."""
    import bench
    _, _, config, _ = bench.bench_row_config(
        {"BENCH_MODEL": "alexnet", "BENCH_BUCKET_BYTES": "4194304"})
    assert config["bucket_bytes"] == 4194304
    assert bench._bucket_label(4194304) == "4m"
    assert bench._bucket_label(65536) == "64k"
    assert bench._bucket_label(1000) == "1000"
