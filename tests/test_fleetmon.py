"""Fleet health plane (utils/fleetmon, docs/design.md §20): rule-engine
episode semantics, the wire-framed collector service, alert-driven
supervision, the simfleet rehearsal, and the chaos alert-audit."""

import json
import os
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from theanompi_tpu.parallel import wire  # noqa: E402
from theanompi_tpu.parallel.membership import ElasticSupervisor  # noqa: E402
from theanompi_tpu.simfleet import FleetSim, VirtualClock  # noqa: E402
from theanompi_tpu.utils import chaos, fleetmon, telemetry, tracing  # noqa


def _rule(**kw):
    base = {"name": "r", "series": "step_p99", "predicate": "threshold",
            "op": ">", "value": 1.0, "scope": "rank"}
    base.update(kw)
    return base


# -- rule grammar -------------------------------------------------------------

def test_rule_grammar_validation():
    fleetmon.validate_rules(fleetmon.DEFAULT_RULES)
    fleetmon.validate_rules(fleetmon.default_rules(
        step_p99_s=0.5, hbm_headroom_bytes=1e9))
    for bad, msg in [
            (_rule(predicate="nope"), "predicate"),
            (_rule(series="nope"), "series"),
            (_rule(op="!="), "op"),
            (_rule(bogus_key=1), "unknown key"),
            (_rule(predicate="sustained"), "window_s"),
            (_rule(predicate="fleet_quantile", quantile=7.0), "quantile"),
            (_rule(action="explode"), "action"),
            ({"series": "step_p99"}, "name")]:
        with pytest.raises(ValueError, match=msg):
            fleetmon.validate_rules([bad])
    with pytest.raises(ValueError, match="duplicate"):
        fleetmon.validate_rules([_rule(), _rule()])


# -- episode semantics (the no-flapping contract) -----------------------------

def test_threshold_episode_fires_once_until_clear():
    clk = VirtualClock()
    col = fleetmon.FleetCollector(rules=[_rule()], clock=clk,
                                  telemetry_=telemetry.DISABLED)
    col.ingest({"step_p99": 3.0}, rank=1)
    assert len(col.evaluate()) == 1
    # persisting breach: NO re-fire, however many evaluations pass
    for _ in range(5):
        clk.advance_to(clk.now() + 1.0)
        col.ingest({"step_p99": 3.0}, rank=1)
        assert col.evaluate() == []
    # clears, then a NEW breach opens a new episode
    clk.advance_to(clk.now() + 1.0)
    col.ingest({"step_p99": 0.1}, rank=1)
    assert col.evaluate() == []
    clk.advance_to(clk.now() + 1.0)
    col.ingest({"step_p99": 9.0}, rank=1)
    fired = col.evaluate()
    assert len(fired) == 1 and fired[0]["value"] == 9.0
    assert len(col.alerts) == 2


def test_sustained_needs_full_window_and_blip_resets():
    clk = VirtualClock()
    col = fleetmon.FleetCollector(
        rules=[_rule(predicate="sustained", window_s=5.0)], clock=clk,
        telemetry_=telemetry.DISABLED)
    for _ in range(4):                      # 4s of breach: under window
        col.ingest({"step_p99": 3.0}, rank=1)
        assert col.evaluate() == []
        clk.advance_to(clk.now() + 1.0)
    col.ingest({"step_p99": 0.5}, rank=1)   # blip clears: window resets
    assert col.evaluate() == []
    for i in range(7):
        clk.advance_to(clk.now() + 1.0)
        col.ingest({"step_p99": 3.0}, rank=1)
        fired = col.evaluate()
        assert bool(fired) == (i == 5), f"iteration {i}: {fired}"
    assert len(col.alerts) == 1


def test_rate_of_change_on_cumulative_counter():
    clk = VirtualClock()
    col = fleetmon.FleetCollector(
        rules=[{"name": "wire_degraded", "series": "wire_retries",
                "predicate": "rate_of_change", "op": ">", "value": 0.5,
                "window_s": 4.0, "scope": "rank"}],
        clock=clk, telemetry_=telemetry.DISABLED)
    for v in (0, 0, 0, 0, 0):               # flat baseline: no alert
        col.ingest({"wire_retries": float(v)}, rank=2)
        assert col.evaluate() == []
        clk.advance_to(clk.now() + 1.0)
    for v in (3, 6, 9):                     # burst: slope ~3/s
        col.ingest({"wire_retries": float(v)}, rank=2)
        clk.advance_to(clk.now() + 1.0)
    assert len(col.evaluate()) == 1
    for _ in range(6):                      # counter flat again: clears
        clk.advance_to(clk.now() + 1.0)
        col.ingest({"wire_retries": 9.0}, rank=2)
        col.evaluate()
    assert len(col.alerts) == 1
    for v in (12, 15, 18):                  # second fault, second episode
        clk.advance_to(clk.now() + 1.0)
        col.ingest({"wire_retries": float(v)}, rank=2)
        col.evaluate()
    assert len(col.alerts) == 2


def test_fleet_quantile_needs_two_ranks_and_scopes_fleet():
    clk = VirtualClock()
    col = fleetmon.FleetCollector(
        rules=[{"name": "queue_starved", "series": "queue_depth",
                "predicate": "fleet_quantile", "quantile": 0.5,
                "op": "<", "value": 1.0, "scope": "fleet",
                "action": "flight_dump"}],
        clock=clk, telemetry_=telemetry.DISABLED)
    col.ingest({"queue_depth": 0.0}, rank=1)
    assert col.evaluate() == []             # one rank is not a fleet
    col.ingest({"queue_depth": 0.0}, rank=2)
    col.ingest({"queue_depth": 4.0}, rank=3)
    fired = col.evaluate()
    assert len(fired) == 1 and fired[0]["scope"] == "fleet" \
        and fired[0]["rank"] is None
    assert col.pop_actions() == fired and col.pop_actions() == []


def test_heartbeat_age_derived_and_clean_exit_retires():
    clk = VirtualClock()
    col = fleetmon.FleetCollector(
        rules=[{"name": "heartbeat_lost", "series": "heartbeat_age_s",
                "predicate": "threshold", "op": ">", "value": 5.0,
                "scope": "rank"}],
        clock=clk, telemetry_=telemetry.DISABLED)
    col.ingest({"steps": 1.0}, rank=1)
    col.ingest({"steps": 1.0}, rank=2, status="left")   # clean departure
    clk.advance_to(clk.now() + 10.0)
    fired = col.evaluate()
    assert [a["rank"] for a in fired] == [1]    # the retired rank stays
    assert col.retired == {2}                   # silent without alerting
    # the rank streams again (a respawn): episode clears, age resets
    col.ingest({"steps": 2.0}, rank=1)
    assert col.evaluate() == []


# -- emission side ------------------------------------------------------------

def test_snapshot_from_telemetry_fields_and_disabled():
    assert fleetmon.snapshot_from_telemetry(telemetry.DISABLED) == {}
    tm = telemetry.Telemetry(rank=0, run_id="snap")
    for v in (0.1, 0.2, 0.3):
        tm.observe("phase.train", v)
        tm.observe("wire.rtt", v / 10)
    tm.gauge("images_per_sec", 123.0)
    tm.gauge("hbm_min_headroom_bytes", 1e9)
    tm.gauge("prefetch.queue_depth", 2.0)
    tm.gauge("heartbeat.iter", 17.0)
    tm.counter("wire.retry", 3)
    snap = fleetmon.snapshot_from_telemetry(tm)
    assert snap["img_s"] == 123.0 and snap["steps"] == 17.0
    assert snap["queue_depth"] == 2.0
    assert snap["hbm_headroom_bytes"] == 1e9
    assert 0.1 <= snap["step_p50"] <= snap["step_p99"] <= 0.3
    assert snap["wire_retries"] == 3.0
    assert set(snap) <= set(fleetmon.METRIC_FIELDS)
    # alert events carry the schema fields and go through ONE emitter
    col = fleetmon.FleetCollector(rules=[_rule()], telemetry_=tm)
    col.ingest({"step_p99": 5.0}, rank=4)
    col.evaluate()
    evs = [e for e in tm.tail(8) if e["ev"] == fleetmon.ALERT_EVENT]
    assert len(evs) == 1 and evs[0]["rule"] == "r" \
        and evs[0]["worker"] == 4 and evs[0]["threshold"] == 1.0


def test_exposition_covers_every_series_and_restore_keeps_episodes():
    clk = VirtualClock()
    col = fleetmon.FleetCollector(rules=[_rule()], clock=clk,
                                  telemetry_=telemetry.DISABLED)
    col.ingest({k: 1.0 for k in fleetmon.METRIC_FIELDS}, rank=0)
    col.ingest({"step_p99": 7.0}, rank=1)
    assert len(col.evaluate()) == 1
    text = col.expose_text()
    for name in fleetmon.FLEET_SERIES:
        assert f"theanompi_{name}" in text, name
    assert "theanompi_fleet_alerts_total 1" in text
    # snapshot/restore: alerts AND the firing state survive — a restored
    # collector must not re-fire the episode it already alerted on
    snap = json.loads(json.dumps(col.snapshot()))    # disk round-trip
    col2 = fleetmon.FleetCollector(rules=[_rule()], clock=clk,
                                   telemetry_=telemetry.DISABLED)
    col2.restore(snap)
    assert len(col2.alerts) == 1
    col2.ingest({"step_p99": 7.0}, rank=1)
    assert col2.evaluate() == []


# -- the wire service ---------------------------------------------------------

def test_server_ingest_dedup_ops_and_restart(tmp_path):
    d = str(tmp_path)
    srv = fleetmon.FleetMonServer(
        rules=[_rule()], run_dir=d, snapshot_dir=os.path.join(d, "snap"),
        eval_window_s=0.1, telemetry_=telemetry.DISABLED)
    host, port = srv.start()
    addr = f"{host}:{port}"
    try:
        tm = telemetry.Telemetry(rank=3, run_id="live")
        tm.observe("phase.train", 2.0)
        st = fleetmon.MetricStreamer(addr, rank=3, telemetry_=tm)
        assert st.push()
        # a RETRIED snapshot (same idempotency token) ingests once
        s = socket.create_connection((host, port))
        h = {"op": fleetmon.METRICS_OP, "rank": 9, "role": "worker",
             "status": "live", "tok": {"w": "w9", "seq": 5}}
        body = json.dumps({"steps": 1.0}).encode()
        wire.send_msg(s, h, body)
        assert wire.recv_msg(s)[0]["ok"]
        wire.send_msg(s, h, body)
        resp = wire.recv_msg(s)[0]
        assert resp["ok"] and resp.get("dedup") is True
        s.close()
        assert srv.collector.samples_ingested == 2    # 3 sends, 2 lands
        # ops: series / rollup / alerts / exposition / statusz health
        c = wire.WireClient(addr, client_id="probe")
        resp, _ = c.request({"op": "series", "rank": 3,
                             "series": "step_p99"})
        assert resp["ok"] and len(resp["samples"]) == 1
        resp, body = c.request({"op": "exposition"})
        assert resp["ok"] and b"theanompi_step_p99" in body
        deadline = time.time() + 5.0                 # eval thread fires
        while time.time() < deadline and not srv.collector.alerts:
            time.sleep(0.05)
        resp, _ = c.request({"op": "alerts"})
        assert resp["ok"] and resp["alerts"] \
            and resp["alerts"][0]["rule"] == "r"
        rep = tracing.statusz_query(addr, "health")
        assert rep["ok"] and rep["role"] == "fleetmon" \
            and rep["samples"] == 2
        c.close()
        # restart on the same port restores series + alerts + episodes,
        # and the streamer rides the outage (a failed send is dropped,
        # the next one lands — §15 retry + §14 snapshot machinery)
        srv.stop(deregister=False)
        assert not st.push() and st.failed == 1
        srv2 = fleetmon.FleetMonServer(
            rules=[_rule()], run_dir=d,
            snapshot_dir=os.path.join(d, "snap"), eval_window_s=0.1,
            telemetry_=telemetry.DISABLED)
        srv2.start(port=port)
        try:
            assert len(srv2.collector.alerts) >= 1
            assert srv2.collector.samples_ingested == 2
            assert st.push()
            assert srv2.collector.samples_ingested == 3
        finally:
            srv2.stop()
        st.stop(final=False)
        tm.close()
    finally:
        srv.stop()


# -- alert-driven supervision -------------------------------------------------

def test_supervisor_tick_applies_demote_and_flight_dump(tmp_path):
    d = str(tmp_path)
    tm = telemetry.Telemetry(rank=0, run_id="sup", stream_dir=d)
    srv = fleetmon.FleetMonServer(rules=fleetmon.default_rules(),
                                  telemetry_=telemetry.DISABLED)
    sup = ElasticSupervisor(lambda w, a: ["true"], [1, 2], str(tmp_path),
                            record_dir=d, telemetry_=tm, fleetmon=srv)
    sup.controller.join(1, pid=11)
    sup.controller.join(2, pid=22)
    # a statusz endpoint in the run dir: the fleet-wide flight dump
    # must reach it (the §17 `flight` op)
    sz = tracing.StatuszServer("worker", ident=1, run_dir=d,
                               telemetry_=tm)
    sz.start()
    try:
        srv.collector.alerts.append({})   # not actionable — ignored
        srv.collector.actions.append(
            {"rule": "heartbeat_lost", "series": "heartbeat_age_s",
             "rank": 1, "value": 30.0, "threshold": 10.0,
             "action": "demote"})
        srv.collector.actions.append(
            {"rule": "queue_starved", "series": "queue_depth",
             "rank": None, "value": 0.0, "threshold": 1.0,
             "action": "flight_dump"})
        sup._tick_fleetmon()
        assert sup.alert_demotions == [("heartbeat_lost", 1)]
        assert sup.controller.workers[1]["status"] == "demoted"
        demotes = [e for e in tm.tail(16) if e["ev"] == "worker_demote"]
        assert demotes and demotes[-1]["rule"] == "heartbeat_lost" \
            and demotes[-1]["reason"] == "alert"
        assert sup.flight_dumps_requested == 1
        assert os.path.exists(os.path.join(d, "flight_rank0.jsonl"))
        # the supervisor's own liveness sample joined the fleet view
        assert -2 in srv.collector.roles
    finally:
        sz.stop()
        tm.close()


# -- fleetz: roster/exit-code contracts + --watch -----------------------------

def test_fleetz_watch_single_iteration_and_down_exit(tmp_path):
    d = str(tmp_path)
    tm = telemetry.Telemetry(rank=1, run_id="fz", stream_dir=d)
    sz = tracing.StatuszServer("worker", ident=1, run_dir=d,
                               telemetry_=tm)
    sz.start()
    srv = fleetmon.FleetMonServer(rules=[_rule()], run_dir=d,
                                  eval_window_s=0.1,
                                  telemetry_=telemetry.DISABLED)
    srv.start()
    srv.collector.ingest({"step_p99": 5.0}, rank=1)
    deadline = time.time() + 5.0
    while time.time() < deadline and not srv.collector.alerts:
        time.sleep(0.05)
    try:
        # healthy roster: --watch --iterations 1 runs ONE frame, exits 0,
        # and surfaces the collector's alert line in the live view
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "fleetz.py"),
             d, "--watch", "--iterations", "1"],
            capture_output=True, text=True)
        assert out.returncode == 0, out.stderr + out.stdout
        assert "fleetz watch frame 1" in out.stdout
        assert "fleetmon" in out.stdout and "ALERT r" in out.stdout
        # a ghost doc (crashed process kept its roster entry): DOWN → 2,
        # same contract in watch mode
        ghost = os.path.join(tracing.statusz_dir(d), "worker_9.json")
        with open(ghost, "w") as f:
            json.dump({"role": "worker", "id": 9, "pid": 99999,
                       "host": "127.0.0.1", "port": 9}, f)
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "fleetz.py"),
             d, "--watch", "--iterations", "1", "--timeout", "0.5"],
            capture_output=True, text=True)
        assert out.returncode == 2, out.stderr + out.stdout
        assert "DOWN" in out.stdout
    finally:
        srv.stop()
        sz.stop()
        tm.close()


# -- the simfleet rehearsal (§20 acceptance) ----------------------------------

def _rehearsal(seed=5):
    sched = chaos.parse_schedule("kill@10:3,stop@12:4:25,delay@8:5:40")
    net = chaos.parse_schedule("net_partition@20:-1:6")
    f = FleetSim(n_workers=12, steps=800, sync_freq=8, seed=seed,
                 n_stragglers=0, schedule=list(sched),
                 net_schedule=list(net), fleetmon=True)
    f.run()
    return f


def test_simfleet_rehearsal_exact_alerts_deterministic_no_flapping():
    f1, f2 = _rehearsal(), _rehearsal()
    # same seed ⇒ byte-identical event log INCLUDING the alert lines
    assert f1.log.sha256() == f2.log.sha256()
    alerts = f1.log.select("alert")
    got = sorted((a["rule"], a["worker"]) for a in alerts)
    # the expected alert set for this schedule, exactly: the delayed
    # straggler (w5) trips the sustained step-time rule, the wedge (w4)
    # outlives the lease timeout, the partition's retry bursts trip the
    # wire rate rule on the workers caught mid-push; the KILL (w3) is
    # healed by supervised respawn faster than any heartbeat threshold —
    # it must NOT alert (that is the supervision plane's job)
    assert ("step_time_degraded", 5) in got
    assert ("heartbeat_lost", 4) in got
    assert any(r == "wire_degraded" for r, _ in got)
    assert not any(w == 3 and r == "heartbeat_lost" for r, w in got)
    # no flapping: one alert per (rule, rank) episode in this schedule
    assert len(got) == len(set(got))
    # the audit closes: every covered landed fault matched to its alert
    # within one evaluation window (virtual time base on both sides)
    ok, lines = fleetmon.audit_alerts(
        f1.health.collector.alerts, f1.realized,
        f1.health.collector.rules,
        eval_window_s=f1.health.eval_window_s,
        interval_s=FleetSim.BEAT_EVERY_S)
    assert ok, "\n".join(lines)
    assert sum("alert-audit:" in ln for ln in lines) >= 3
    assert f1.summary["fleetmon"]["alerts"] == len(alerts)
    assert f1.summary["finished"] == 12


def test_simfleet_default_has_no_health_plane():
    # fleetmon off (the default): no collector, no summary key, so the
    # §18 determinism hashes of existing gates are untouched
    f = FleetSim(n_workers=4, steps=64, sync_freq=8, seed=1)
    f.run()
    assert f.health is None and "fleetmon" not in f.summary
    assert f.log.select("alert") == []


# -- the live chaos alert-audit ----------------------------------------------

def test_live_alert_audit_stop_and_delay(tmp_path):
    """Live machinery end to end, no subprocesses: three streamers over
    real sockets feed the real collector; a SIGSTOP-shaped fault (one
    streamer silenced) and a delay-shaped fault (one rank's step
    histogram inflated) land per a real chaos schedule, and the §20
    audit matches each landed fault to its alert within one evaluation
    window."""
    rules = [
        {"name": "heartbeat_lost", "series": "heartbeat_age_s",
         "predicate": "threshold", "op": ">", "value": 1.2,
         "scope": "rank", "action": "demote", "roles": ("worker",)},
        {"name": "step_time_degraded", "series": "step_p99",
         "predicate": "sustained", "op": ">", "value": 0.5,
         "window_s": 0.6, "scope": "rank", "roles": ("worker",)},
    ]
    tm0 = telemetry.Telemetry(rank=0, run_id="audit",
                              stream_dir=str(tmp_path))
    srv = fleetmon.FleetMonServer(rules=rules, eval_window_s=0.2,
                                  telemetry_=tm0)
    host, port = srv.start()
    addr = f"{host}:{port}"
    schedule = chaos.parse_schedule("stop@0.6:2:2.0,delay@0.6:3:1.5")
    tms, streamers = {}, {}
    try:
        for rank in (1, 2, 3):
            tms[rank] = telemetry.Telemetry(rank=rank, run_id="audit")
            tms[rank].observe("phase.train", 0.1)
            streamers[rank] = fleetmon.MetricStreamer(
                addr, rank=rank, interval_s=0.2, telemetry_=tms[rank])
            streamers[rank].start()
        t0 = time.time()
        realized = []
        for f in schedule:                    # land the faults
            time.sleep(max(0.0, t0 + f.at - time.time()))
            realized.append({"ts": time.time(), "kind": f.kind,
                             "target": f.target,
                             "duration": f.duration, "error": None})
            if f.kind == "stop":              # SIGSTOP: silence, resume
                streamers[f.target]._halt.set()
                streamers[f.target].join(timeout=2)
            else:                             # delay: inflated steps
                for _ in range(8):
                    tms[f.target].observe("phase.train", 2.0)
        time.sleep(2.6)                       # wedge runs its duration
        streamers[2] = fleetmon.MetricStreamer(   # SIGCONT: beats resume
            addr, rank=2, interval_s=0.2, telemetry_=tms[2])
        streamers[2].start()
        time.sleep(0.6)
        alerts = list(srv.collector.alerts)
        ok, lines = fleetmon.audit_alerts(alerts, realized, rules,
                                          eval_window_s=0.2,
                                          interval_s=0.2)
        assert ok, "\n".join(lines) + f"\nalerts: {alerts}"
        # ... and the alert EVENTS landed in the telemetry stream with
        # the demote action queued for the supervisor
        evs = [e for e in tm0.tail(16) if e["ev"] == fleetmon.ALERT_EVENT]
        assert any(e["rule"] == "heartbeat_lost" and e["worker"] == 2
                   for e in evs)
        assert any(a["action"] == "demote"
                   for a in srv.collector.pop_actions())
    finally:
        for st in streamers.values():
            st.stop(final=False)
        for t in tms.values():
            t.close()
        srv.stop()
        tm0.close()


# -- report + drift-probe integration ----------------------------------------

def test_report_renders_alert_markers_and_cites_wire_alerts(tmp_path):
    d = str(tmp_path)
    tm = telemetry.Telemetry(rank=0, run_id="rep", stream_dir=d)
    col = fleetmon.FleetCollector(
        rules=[{"name": "wire_degraded", "series": "wire_retries",
                "predicate": "rate_of_change", "op": ">", "value": 0.5,
                "window_s": 0.2, "scope": "rank"}],
        telemetry_=tm)
    col.ingest({"wire_retries": 0.0}, rank=1)
    time.sleep(0.25)
    col.ingest({"wire_retries": 9.0}, rank=1)
    col.evaluate()
    assert len(col.alerts) == 1
    tm.counter("wire.retry", 9)     # the wire-health row the citation
    tm.close()                      # attaches to
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_fleetmon_report", os.path.join(REPO, "scripts",
                                         "telemetry_report.py"))
    tr_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tr_mod)
    events = tr_mod.load_events(d)
    rep = tr_mod.build_report(d, events=events)
    assert rep["alerts"] and rep["alerts"][0]["rule"] == "wire_degraded"
    trace = tr_mod.build_trace(events)
    markers = [e for e in trace["traceEvents"]
               if e.get("cat") == "alert"
               and str(e.get("name", "")).startswith("alert:")]
    assert markers and "wire_degraded" in markers[0]["name"] \
        and "=" in markers[0]["name"]         # rule name + firing value
    import contextlib
    import io
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        tr_mod.print_report(rep)
    out = buf.getvalue()
    assert "alerts fired: wire_degraded[w1]" in out
    assert "fleet-health alerts" in out


def test_schema_drift_fleetmon_probes_clean():
    from theanompi_tpu.analysis.checkers import schema_drift as sd
    membership = sd._load_by_path(
        os.path.join("theanompi_tpu", "parallel", "membership.py"),
        "_t_fleetmon_membership")
    report = sd._load_telemetry_report()
    errors = sd.fleetmon_schema_errors(fleetmon, membership, telemetry,
                                       report)
    assert errors == [], errors
    # and the probe FIRES on a broken vocabulary: a coverage entry
    # naming a rule that no stock set defines
    orig = fleetmon.FAULT_ALERT_COVERAGE
    fleetmon.FAULT_ALERT_COVERAGE = dict(orig, delay=("renamed_rule",))
    try:
        errors = sd.fleetmon_schema_errors(fleetmon, membership,
                                           telemetry, report)
        assert any("renamed_rule" in str(e) for e in errors)
    finally:
        fleetmon.FAULT_ALERT_COVERAGE = orig
