"""Leaf-wise update-plane sharding (parallel/update_sharding.py,
docs/design.md §23): the per-leaf schema stamps correctly, the
shard/rebuild round trip is the identity bit for bit, and training with
the sharded update plane is assert_array_equal-identical to the
replicated path — for BSP moments, the EASGD/ASGD centers, and a
compressed rule with error feedback — including under steps_per_call
fused dispatch.  Fast suite: tier-1 runs this file (unlike
tests/test_zero.py, which stays slow-marked)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tests.conftest import TinyModel
from theanompi_tpu.parallel import steps
from theanompi_tpu.parallel import update_sharding as us
from theanompi_tpu.parallel.exchanger import BSP_Exchanger, get_exchanger
from theanompi_tpu.parallel.mesh import WORKER_AXIS, worker_mesh
from theanompi_tpu.utils import compile_cache, devprof


def _train(model, exch, n_steps):
    model.compile_iter_fns(exch)
    model.data.shuffle_data(0)
    costs = []
    for i in range(n_steps):
        model.train_iter(i, None)
        costs.append(float(model.current_info["cost"]))
    return costs


def _make_tiny(ushard, mesh, **kw):
    cfg = {"mesh": mesh, "size": 4, "rank": 0, "verbose": False,
           "update_sharding": ushard, "ushard_min_bytes": 0, **kw}
    return TinyModel(cfg), cfg


def _assert_trees_equal(a, b):
    jax.tree.map(lambda x, y: np.testing.assert_array_equal(
        np.asarray(x), np.asarray(y)), a, b)


# ---------------------------------------------------------------------------
# the schema itself
# ---------------------------------------------------------------------------

def test_plan_stamps_per_leaf_schema():
    """Ragged P=10, N=4: chunk ceil(10/4)=3, pad 2, spec P(workers); a
    3-element leaf (< N) and a scalar stay replicated with P()."""
    tree = {"w": np.zeros(10, np.float32), "b": np.zeros(3, np.float32),
            "s": np.float32(0.0)}
    plan = us.plan_tree(tree, 4, min_bytes=0)
    by_path = {lp.path: lp for lp in plan.leaves}
    w = by_path["['w']"]
    assert (w.sharded, w.chunk, w.pad, w.spec) == (True, 3, 2,
                                                   P(WORKER_AXIS))
    assert not by_path["['b']"].sharded and by_path["['b']"].spec == P()
    assert not by_path["['s']"].sharded
    assert plan.any_sharded
    specs = plan.specs(tree)
    assert specs["w"] == P(WORKER_AXIS) and specs["b"] == P()
    # the byte threshold moves leaves back to replicated wholesale
    assert not us.plan_tree(tree, 4, min_bytes=1 << 20).any_sharded
    # one worker: nothing to partition
    assert not us.plan_tree(tree, 1, min_bytes=0).any_sharded


def test_host_boxed_roundtrip_identity():
    """shard_host_boxed → unshard_boxed is the identity (ragged leaf:
    the [N, chunk] rows carry the pad, the rebuild trims it)."""
    rng = np.random.RandomState(0)
    tree = {"w": rng.randn(10).astype(np.float32),
            "b": rng.randn(3).astype(np.float32)}
    plan = us.plan_tree(tree, 4, min_bytes=0)
    boxed = us.shard_host_boxed(tree, plan)
    assert boxed["w"].shape == (4, 3)        # rows ARE the partition
    assert boxed["b"].shape == (4, 3)        # replicated rows
    _assert_trees_equal(us.unshard_boxed(boxed, plan), tree)


def test_traced_roundtrip_identity():
    """shard_tree → unshard_tree under shard_map rebuilds every leaf bit
    for bit (the fused per-dtype allgather is value-exact)."""
    from theanompi_tpu.jax_compat import shard_map
    mesh = worker_mesh(4)
    rng = np.random.RandomState(1)
    tree = {"w": rng.randn(4, 5).astype(np.float32),
            "m": rng.randn(8).astype(np.float32),
            "c": rng.randn(6).astype(np.int32)}
    plan = us.plan_tree(tree, 4, min_bytes=0)

    def body(t):
        rank = jax.lax.axis_index(WORKER_AXIS)
        full = us.unshard_tree(us.shard_tree(t, plan, rank), plan,
                               WORKER_AXIS)
        return jax.tree.map(lambda x: x[None], full)   # boxed per worker

    out = shard_map(body, mesh=mesh, in_specs=(P(),),
                    out_specs=P(WORKER_AXIS))(tree)
    for row in range(4):                     # every worker rebuilt it all
        _assert_trees_equal(jax.tree.map(lambda x: x[row], out), tree)
    assert out["c"].dtype == np.int32        # dtypes preserved per lane


def test_ushard_row_columns_schema():
    """The report vocabulary is pinned in the jax-free schema home and
    stays disjoint from the other column families (the schema-drift
    checker diffs bench.py against these names)."""
    cols = set(devprof.USHARD_ROW_COLUMNS)
    assert cols == {"update_state_bytes_per_chip",
                    "update_state_bytes_replicated", "update_state_shrink"}
    assert not cols & set(devprof.BUCKET_ROW_COLUMNS)
    assert not cols & set(devprof.PIPELINE_ROW_COLUMNS)


# ---------------------------------------------------------------------------
# bit-identity vs the replicated update plane, per rule
# ---------------------------------------------------------------------------

def test_bsp_bit_equal_under_fused_dispatch(mesh4):
    """BSP momentum with the sharded optimizer, under steps_per_call=2
    fused dispatch: cost trace and final params EXACTLY equal the
    replicated run (elementwise math on disjoint chunks + value-exact
    gather; no reduction-order change)."""
    base, _ = _make_tiny(False, mesh4, optimizer="momentum",
                         steps_per_call=2)
    shard, _ = _make_tiny(True, mesh4, optimizer="momentum",
                          steps_per_call=2)
    assert shard._ushard_plan is not None
    c0 = _train(base, BSP_Exchanger(base.config), 6)
    c1 = _train(shard, BSP_Exchanger(shard.config), 6)
    np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))
    _assert_trees_equal(
        steps.unbox(jax.device_get(base.step_state["params"])),
        steps.unbox(jax.device_get(shard.step_state["params"])))


@pytest.mark.parametrize("rule", ["easgd", "asgd"])
def test_center_rules_bit_equal(mesh4, rule):
    """EASGD/ASGD with the center sharded into per-worker chunks: cost
    trace, final params, and the canonical CENTER itself all exactly
    equal the replicated run."""
    kw = {"rule": rule, "sync_freq": 2}
    base, bcfg = _make_tiny(False, mesh4, **kw)
    shard, scfg = _make_tiny(True, mesh4, **kw)
    c0 = _train(base, get_exchanger(rule, bcfg), 6)
    c1 = _train(shard, get_exchanger(rule, scfg), 6)
    assert shard.exchanger.update_plan() is not None
    np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))
    _assert_trees_equal(
        steps.unbox(jax.device_get(base.step_state["params"])),
        steps.unbox(jax.device_get(shard.step_state["params"])))
    _assert_trees_equal(
        jax.device_get(base.exchanger.canonical_params(base.step_state)),
        jax.device_get(shard.exchanger.canonical_params(shard.step_state)))


def test_powersgd_ef_bit_equal(mesh4):
    """BSP + powersgd compressed wire: the moments shard, the per-worker
    error-feedback buffers stay LOCAL (never planned — they diverge per
    worker by construction), and training is bit-equal."""
    kw = {"optimizer": "momentum", "exch_strategy": "powersgd"}
    base, _ = _make_tiny(False, mesh4, **kw)
    shard, _ = _make_tiny(True, mesh4, **kw)
    assert shard._ushard_plan is not None
    c0 = _train(base, BSP_Exchanger(base.config), 6)
    c1 = _train(shard, BSP_Exchanger(shard.config), 6)
    # the EF buffers are not in any plan: BSP declares nothing shardable
    assert shard.exchanger.update_plan() is None
    assert shard.exchanger.shardable_extra() == ()
    np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))
    _assert_trees_equal(
        steps.unbox(jax.device_get(base.step_state["params"])),
        steps.unbox(jax.device_get(shard.step_state["params"])))


# ---------------------------------------------------------------------------
# memory: the headline ~N× shrink, measured
# ---------------------------------------------------------------------------

def test_update_state_memory_shrinks(mesh4):
    model, _ = _make_tiny(True, mesh4, optimizer="momentum")
    model.compile_iter_fns(BSP_Exchanger(model.config))
    # the boxed [N, chunk] layout IS the partition, sharded on the data
    # axis — per-chip bytes are boxed/N (momentum state: a velocity tree)
    vel = model.step_state["opt_state"]["opt"]
    chunks = [l for l in jax.tree.leaves(vel)
              if l.sharding.spec == (WORKER_AXIS,)]
    assert chunks and all(l.ndim == 2 for l in chunks)
    report = devprof.update_state_report(model)
    assert set(report) == set(devprof.USHARD_ROW_COLUMNS)
    # TinyModel at N=4: every leaf but the 2-element bias shards → ~3.9×
    assert report["update_state_shrink"] >= 3.0, report
    # control: the replicated run reports ~1×
    base, _ = _make_tiny(False, mesh4, optimizer="momentum")
    base.compile_iter_fns(BSP_Exchanger(base.config))
    flat = devprof.update_state_report(base)
    assert flat["update_state_shrink"] <= 1.01, flat


# ---------------------------------------------------------------------------
# cache keys and config guards
# ---------------------------------------------------------------------------

def test_cache_key_stamped_only_when_on(mesh4):
    """`ushard` enters the compile-cache identity ONLY when the knob is
    on — every pre-existing key (zero_opt sessions included) stays
    byte-stable."""
    on, _ = _make_tiny(True, mesh4, optimizer="momentum")
    off, _ = _make_tiny(False, mesh4, optimizer="momentum")
    zero_cfg = {"mesh": mesh4, "size": 4, "rank": 0, "verbose": False,
                "zero_opt": True}
    zero = TinyModel(zero_cfg)
    assert compile_cache.key_extra("train", model=on).get("ushard") == 0
    assert "ushard" not in compile_cache.key_extra("train", model=off)
    assert "ushard" not in compile_cache.key_extra("train", model=zero)


def test_rejects_zero_opt_composition(mesh4):
    """zero_opt and update_sharding are two layouts of the SAME memory —
    enabling both is a config error, loudly."""
    with pytest.raises(AssertionError, match="zero_opt"):
        _make_tiny(True, mesh4, zero_opt=True)


def test_min_bytes_threshold_disables(mesh4):
    """A threshold above every leaf leaves the plan inactive: identical
    programs, no `ushard` reshaping, nothing sharded."""
    model, _ = _make_tiny(True, mesh4, optimizer="momentum",
                          ushard_min_bytes=1 << 30)
    assert model._ushard_plan is None
