"""Fused exchange cadence (ISSUE 1 tentpole): ``steps_per_call > 1`` for
EVERY rule — the exchange algebra runs IN-SCAN via ``lax.cond`` on the
step count, so one XLA dispatch covers k full steps including their
cadenced exchanges.

Contracts pinned here:

* bit-equivalence — k steps fused must equal k single-step dispatches
  driven through the Python exchange hook, for EASGD / ASGD / BSP params
  mode exactly, and for GoSGD given the same traced gossip draws (the
  fused path derives them from ``steps.fused_exchange_key``; the
  standalone run is handed the same base key);
* one dispatch per window — ``train_fn`` fires once per k-step window and
  the standalone ``_exchange_fn`` never fires;
* recorder sanity — with the exchange in-scan, its cost rides the
  ``train`` bucket and ``t_comm`` stays zero (nothing double-counts).
"""

import jax
import numpy as np
import pytest

from tests.conftest import TinyModel
from theanompi_tpu.parallel import steps
from theanompi_tpu.parallel.exchanger import (ASGD_Exchanger, BSP_Exchanger,
                                              EASGD_Exchanger,
                                              GOSGD_Exchanger)
from theanompi_tpu.parallel.mesh import worker_mesh
from theanompi_tpu.utils.recorder import Recorder


def _build(exch_cls, spc, n=4, **cfg):
    mesh = worker_mesh(n)
    config = {"mesh": mesh, "size": n, "rank": 0, "verbose": False,
              "batch_size": 8, "steps_per_call": spc, **cfg}
    model = TinyModel(config)
    exch = exch_cls(config)
    model.compile_iter_fns(exch)
    model.data.shuffle_data(0)
    return model, exch


def _drive(model, exch, k, n_steps=4):
    """Worker-loop shape: count strides by steps_per_call; the Python hook
    is still CALLED (as the worker would for spc=1) — for fused exchangers
    it must stand down by itself."""
    for count in range(k, n_steps + 1, k):
        model.train_iter(count, None)
        exch.exchange(None, count)
    return jax.device_get(model.step_state)


def _assert_state_equal(a, b, parts=("params", "opt_state", "extra")):
    for part in parts:
        for x, y in zip(jax.tree_util.tree_leaves(a[part]),
                        jax.tree_util.tree_leaves(b[part])):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                          err_msg=part)


@pytest.mark.parametrize("exch_cls,cfg", [
    (EASGD_Exchanger, {"sync_freq": 2, "alpha": 0.5}),
    (EASGD_Exchanger, {"sync_freq": 3}),     # freq not dividing k: the
    (ASGD_Exchanger, {"sync_freq": 1}),      # lax.cond gate must skip steps
    (ASGD_Exchanger, {"sync_freq": 2}),
    (BSP_Exchanger, {"exch_mode": "params"}),
], ids=["easgd-f2", "easgd-f3", "asgd-f1", "asgd-f2", "bsp-params"])
def test_fused_bit_equals_unfused(exch_cls, cfg):
    s1 = _drive(*_build(exch_cls, 1, **cfg), k=1)
    s4 = _drive(*_build(exch_cls, 4, **cfg), k=4)
    _assert_state_equal(s1, s4)


@pytest.mark.parametrize("peers", ["perm", "shift", "iid"])
def test_fused_gosgd_bit_equal_given_same_draws(peers):
    """The GoSGD RNG contract: every gossip draw is a traced function of
    (base key, count).  Fused mode derives the base key as
    ``steps.fused_exchange_key(step_rng)``; hand the unfused run the same
    base key (instead of its host-split stream) and the two paths must
    agree bit-for-bit — send gates, routing picks, merges and all."""
    cfg = {"exch_prob": 0.7, "gosgd_peers": peers}
    model1, exch1 = _build(GOSGD_Exchanger, 1, **cfg)
    base = steps.fused_exchange_key(model1._step_rng)
    model1.next_exchange_key = lambda: base
    s1 = _drive(model1, exch1, k=1)
    s4 = _drive(*_build(GOSGD_Exchanger, 4, **cfg), k=4)
    _assert_state_equal(s1, s4)
    # α stays a conserved redistribution in fused mode too
    alpha = np.asarray(s4["extra"]["alpha"]).reshape(-1)
    np.testing.assert_allclose(alpha.sum(), 4.0, rtol=1e-5)


def test_one_dispatch_per_window_async_rules():
    """The acceptance criterion, counted: with steps_per_call=k an async
    rule costs ONE train_fn dispatch per k-step window and ZERO standalone
    _exchange_fn dispatches (the cadence lives inside the scan)."""
    model, exch = _build(EASGD_Exchanger, 4, sync_freq=2)
    calls = {"train": 0, "exch": 0}
    train_fn, exch_fn = model.train_fn, exch._exchange_fn

    def count_train(*a, **kw):
        calls["train"] += 1
        return train_fn(*a, **kw)

    def count_exch(*a, **kw):
        calls["exch"] += 1
        return exch_fn(*a, **kw)

    model.train_fn = count_train
    exch._exchange_fn = count_exch
    _drive(model, exch, k=4, n_steps=8)      # 2 windows of 4 steps
    assert calls == {"train": 2, "exch": 0}
    # same rule unfused: k dispatches + the due exchanges, for contrast
    model1, exch1 = _build(EASGD_Exchanger, 1, sync_freq=2)
    calls1 = {"train": 0, "exch": 0}
    train_fn1, exch_fn1 = model1.train_fn, exch1._exchange_fn
    model1.train_fn = lambda *a, **kw: (
        calls1.__setitem__("train", calls1["train"] + 1) or train_fn1(*a, **kw))
    exch1._exchange_fn = lambda *a, **kw: (
        calls1.__setitem__("exch", calls1["exch"] + 1) or exch_fn1(*a, **kw))
    _drive(model1, exch1, k=1, n_steps=8)
    assert calls1 == {"train": 8, "exch": 4}


def test_fused_worker_loop_skips_python_hook():
    """The worker loop's skip path: exchange() is a no-op while fused —
    state is untouched and no recorder comm section opens."""
    model, exch = _build(GOSGD_Exchanger, 2, exch_prob=1.0)
    assert exch.fused
    model.train_iter(2, None)
    before = jax.device_get(model.step_state["params"])
    rec = Recorder({"verbose": False})
    exch.exchange(rec, 2)
    after = jax.device_get(model.step_state["params"])
    for x, y in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(after)):
        np.testing.assert_array_equal(x, y)
    assert rec.t_sec_total["comm"] == 0.0


def test_recorder_t_comm_sane_when_fused():
    """With the exchange in-scan, its time lands in the train bucket:
    t_comm accumulates exactly zero over a fused run, t_train is positive,
    and the print path digests the stride without error."""
    model, exch = _build(EASGD_Exchanger, 2, sync_freq=2,
                         sync_each_iter=True)
    rec = Recorder({"verbose": False, "printFreq": 2, "size": 4})
    for count in (2, 4):
        model.train_iter(count, rec)
        exch.exchange(rec, count)
        rec.print_train_info(count, stride=2)
    assert rec.t_sec_total["comm"] == 0.0
    assert rec.t_sec_total["train"] > 0.0
    assert rec.n_images_total == 8 * 4 * 4   # rows/worker × workers × steps
    # contrast: the unfused cadence DOES book comm time when due
    model1, exch1 = _build(EASGD_Exchanger, 1, sync_freq=1,
                           sync_each_iter=True)
    rec1 = Recorder({"verbose": False, "printFreq": 2, "size": 4})
    for count in (1, 2):
        model1.train_iter(count, rec1)
        exch1.exchange(rec1, count)
    assert rec1.t_sec_total["comm"] > 0.0


def test_fused_easgd_center_still_canonical():
    """Validation semantics survive fusing: the center moves and
    begin_val snapshots it exactly as in the unfused cadence."""
    model, exch = _build(EASGD_Exchanger, 2, sync_freq=1, alpha=0.5)
    c0 = jax.device_get(exch.canonical_params(model.step_state))
    _drive(model, exch, k=2, n_steps=4)
    c1 = jax.device_get(exch.canonical_params(model.step_state))
    moved = any(not np.allclose(a, b)
                for a, b in zip(jax.tree_util.tree_leaves(c0),
                                jax.tree_util.tree_leaves(c1)))
    assert moved
    model.begin_val()
    model.val_iter(1, None)
    model.end_val()


def test_recompile_to_single_step_clears_fused_flag():
    """Recompiling the SAME exchanger back to steps_per_call=1 must clear
    the fused flag — a stale True would no-op exchange() forever and
    silently degrade the rule to local-only SGD."""
    mesh = worker_mesh(4)
    config = {"mesh": mesh, "size": 4, "rank": 0, "verbose": False,
              "batch_size": 8, "steps_per_call": 2, "sync_freq": 1}
    exch = EASGD_Exchanger(config)
    model = TinyModel(config)
    model.compile_iter_fns(exch)
    assert exch.fused
    model2 = TinyModel({**config, "steps_per_call": 1})
    model2.compile_iter_fns(exch)
    assert not exch.fused
    model2.data.shuffle_data(0)
    model2.train_iter(1, None)
    before = jax.device_get(steps.unbox(model2.step_state["extra"])["center"])
    exch.exchange(None, 1)               # must actually run again
    after = jax.device_get(steps.unbox(model2.step_state["extra"])["center"])
    moved = any(not np.array_equal(a, b)
                for a, b in zip(jax.tree_util.tree_leaves(before),
                                jax.tree_util.tree_leaves(after)))
    assert moved


def test_legacy_exchanger_pattern_fails_loudly_under_spc():
    """An out-of-tree exchanger on the pre-round-6 pattern (jits
    _exchange_fn in prepare() without declaring has_exchange) must be
    REFUSED under steps_per_call > 1 — its cadence would neither fuse nor
    fire per-step from the spc-strided worker loop."""
    from theanompi_tpu.parallel.exchanger import Exchanger

    class LegacyExchanger(Exchanger):
        def prepare(self, mesh, model):
            super().prepare(mesh, model)
            self._exchange_fn = lambda state, key, count: state

    mesh = worker_mesh(4)
    cfg = {"mesh": mesh, "size": 4, "rank": 0, "verbose": False,
           "batch_size": 8, "steps_per_call": 2}
    model = TinyModel(cfg)
    with pytest.raises(AssertionError, match="has_exchange"):
        model.compile_iter_fns(LegacyExchanger(cfg))
