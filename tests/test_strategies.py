"""Exchanger-strategy numerical equivalence vs a NumPy oracle.

SURVEY.md §4 test matrix item (a): run each strategy over known per-worker
buffers on a real 8-way (simulated) mesh and check the reduced values — what
the reference could only do manually under ``mpirun -np 2..8``.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from theanompi_tpu.ops import compress
from theanompi_tpu.parallel.mesh import WORKER_AXIS, worker_local_sharding
from theanompi_tpu.parallel import strategies
from theanompi_tpu.parallel.strategies import get_strategy
from theanompi_tpu.jax_compat import shard_map

N = 8


def _run_strategy(mesh, strat, per_worker_trees, state_boxed=None):
    """Drive a strategy inside shard_map exactly as the train step does."""
    from theanompi_tpu.parallel import steps

    def body(tree, state):
        tree = steps.unbox(tree)
        state = steps.unbox(state)
        out, new_state = strat(tree, state, axis=WORKER_AXIS, size=N)
        return steps.box(out), steps.box(new_state)

    sm = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(WORKER_AXIS), P(WORKER_AXIS)),
        out_specs=(P(WORKER_AXIS), P(WORKER_AXIS))))
    sh = worker_local_sharding(mesh)
    boxed = jax.tree.map(lambda x: jax.device_put(x, sh), per_worker_trees)
    if state_boxed is None:
        state_boxed = jax.tree.map(
            lambda x: jax.device_put(x, sh),
            jax.tree.map(lambda s: np.broadcast_to(
                np.asarray(s)[None], (N,) + np.asarray(s).shape).copy(),
                strat.init_state(steps.unbox(boxed))))
    return sm(boxed, state_boxed)


def _mk_tree(seed=0):
    """Per-worker pytree boxed as leaves [N, ...]."""
    r = np.random.RandomState(seed)
    return {
        "w": r.randn(N, 6, 10).astype(np.float32),
        "b": r.randn(N, 11).astype(np.float32),
    }


def _oracle_mean(tree):
    return jax.tree.map(lambda x: x.mean(axis=0), tree)


@pytest.mark.parametrize("name", ["allreduce", "ar", "nccl32", "asa32",
                                  "ring", "copper"])
def test_exact_strategies_match_oracle(mesh8, name):
    tree = _mk_tree(1)
    out, _ = _run_strategy(mesh8, get_strategy(name), tree)
    expect = _oracle_mean(tree)
    for k in tree:
        got = np.asarray(out[k])
        for w in range(N):
            np.testing.assert_allclose(got[w], expect[k], rtol=1e-5,
                                       atol=1e-6)


@pytest.mark.parametrize("name", ["nccl16", "asa16", "ring16", "copper16",
                                  "bf16"])
def test_bf16_wire_strategies_approximate_oracle(mesh8, name):
    tree = _mk_tree(2)
    out, _ = _run_strategy(mesh8, get_strategy(name), tree)
    expect = _oracle_mean(tree)
    for k in tree:
        got = np.asarray(out[k])
        # bf16 has ~3 decimal digits; ring accumulates over N-1 hops
        np.testing.assert_allclose(got[0], expect[k], rtol=0.05, atol=0.05)
        # all workers agree exactly
        for w in range(1, N):
            np.testing.assert_array_equal(got[w], got[0])


def test_ring_is_bit_consistent_across_workers(mesh8):
    tree = _mk_tree(3)
    out, _ = _run_strategy(mesh8, get_strategy("ring"), tree)
    for k in tree:
        got = np.asarray(out[k])
        for w in range(1, N):
            np.testing.assert_array_equal(got[w], got[0])


def test_onebit_identical_inputs_decode_exactly(mesh8):
    """With identical per-worker inputs, 1-bit EF decodes to scale·sign."""
    r = np.random.RandomState(4)
    base = r.randn(compress.PACK_ALIGN).astype(np.float32)
    tree = {"g": np.broadcast_to(base[None], (N,) + base.shape).copy()}
    strat = get_strategy("onebit")
    out, state = _run_strategy(mesh8, strat, tree)
    scale = np.abs(base).mean()
    expect = scale * np.where(base >= 0, 1.0, -1.0)
    np.testing.assert_allclose(np.asarray(out["g"])[0], expect, rtol=1e-4,
                               atol=1e-5)
    # error feedback holds the quantization residual
    ef = np.asarray(state)[0]
    np.testing.assert_allclose(ef, base - expect, rtol=1e-4, atol=1e-5)


def test_onebit_error_feedback_converges_on_average(mesh8):
    """EF property: the running sum of decoded outputs tracks the running
    sum of true means (residuals stay bounded)."""
    r = np.random.RandomState(5)
    tree = {"g": r.randn(N, compress.PACK_ALIGN).astype(np.float32)}
    strat = get_strategy("onebit")
    true_mean = np.asarray(_oracle_mean(tree)["g"])
    state = None
    total = np.zeros_like(true_mean)
    steps_n = 30
    for i in range(steps_n):
        out, state = _run_strategy(mesh8, strat, tree, state)
        total += np.asarray(out["g"])[0]
    avg = total / steps_n
    err = np.abs(avg - true_mean).mean() / (np.abs(true_mean).mean() + 1e-9)
    assert err < 0.25, f"EF average error too high: {err}"


def test_topk_full_k_is_bf16_wire_exact(mesh8):
    """With k = chunk (everything selected) chunked top-k degenerates to a
    bf16-wire allreduce: mean within bf16 rounding; the error buffer holds
    exactly the bf16 quantization residuals (≤ 2⁻⁸ relative)."""
    tree = _mk_tree(6)
    strat = get_strategy("topk", k=strategies.TopK.CHUNK)
    out, state = _run_strategy(mesh8, strat, tree)
    expect = _oracle_mean(tree)
    for k in tree:
        # same tolerance as the bf16-wire strategies: per-worker bf16
        # rounding before the sum, so abs error scales with |v_w|, not the
        # (possibly cancelled) mean
        np.testing.assert_allclose(np.asarray(out[k])[0], expect[k],
                                   rtol=0.05, atol=0.05)
    ef = np.asarray(state)[0]
    flat_w = np.concatenate(
        [np.asarray(tree[k])[0].reshape(-1) for k in tree])
    assert np.abs(ef).max() <= np.abs(flat_w).max() * 2**-8 + 1e-7


def test_topk_error_feedback_converges_on_average(mesh8):
    """EF property for chunked top-k: running sum of decoded outputs tracks
    the running sum of true means."""
    r = np.random.RandomState(6)
    tree = {"g": r.randn(N, 1024).astype(np.float32)}
    strat = get_strategy("topk", ratio=0.05)
    true_mean = np.asarray(_oracle_mean(tree)["g"])
    state = None
    total = np.zeros_like(true_mean)
    steps_n = 40
    for i in range(steps_n):
        out, state = _run_strategy(mesh8, strat, tree, state)
        total += np.asarray(out["g"])[0]
    avg = total / steps_n
    err = np.abs(avg - true_mean).mean() / (np.abs(true_mean).mean() + 1e-9)
    assert err < 0.3, f"EF average error too high: {err}"


def test_topk_selects_largest_per_chunk(mesh8):
    """One dominant entry per worker must survive a 1-per-chunk selection,
    arriving bf16-rounded at every worker."""
    x = np.zeros((N, 512), np.float32)
    for w in range(N):
        x[w, 7 * w] = 10.0 + w          # distinct spike per worker
    tree = {"g": x}
    strat = get_strategy("topk", k=1)
    out, _ = _run_strategy(mesh8, strat, tree)
    got = np.asarray(out["g"])[0]
    for w in range(N):
        np.testing.assert_allclose(got[7 * w], (10.0 + w) / N, rtol=1e-2)


def test_pack_unpack_roundtrip():
    r = np.random.RandomState(8)
    c = r.randn(4 * compress.PACK_ALIGN).astype(np.float32)
    packed = compress.pack_signs(jnp.asarray(c))
    assert packed.dtype == jnp.uint32
    # 32 sign bits per uint32 word, rows of 128 lanes
    assert packed.shape == (c.shape[0] // (32 * 128), 128)
    signs = np.asarray(compress.unpack_signs(packed))
    np.testing.assert_array_equal(signs, np.where(c >= 0, 1.0, -1.0))


# Known venue gap, NOT a regression: interpret-mode Pallas on this
# container's jax (0.4.x) dies on the removed `jax.typeof` before the
# kernel runs, so the kernel-vs-oracle comparison is only executable
# compiled on the TPU venue (or on a jax new enough to carry typeof).
# An explicit skip keeps tier-1 output distinguishing "oracle requires
# TPU" from a real kernel break; DOTS_PASSED is unaffected (skips print
# `s`, not `.`).
pallas_interpret_venue = pytest.mark.skipif(
    not hasattr(jax, "typeof"),
    reason="CPU venue gap: interpret-mode Pallas needs jax.typeof "
           "(absent on this 0.4.x container) — oracle comparison runs "
           "compiled on the TPU venue")


@pallas_interpret_venue
def test_pack_pallas_matches_jnp_oracle():
    """The Pallas kernel pair (interpret mode here — compiled on TPU) and the
    jnp oracle must produce bit-identical wire buffers."""
    r = np.random.RandomState(13)
    c = jnp.asarray(r.randn(2 * compress.PACK_ALIGN).astype(np.float32))
    packed_pl = compress._pack_pallas(
        c.reshape(-1, compress.LANES), interpret=True)
    packed_jnp = compress.pack_signs_jnp(c)
    np.testing.assert_array_equal(np.asarray(packed_pl),
                                  np.asarray(packed_jnp))


@pallas_interpret_venue
def test_unpack_weighted_sum_pallas_matches_jnp_oracle():
    r = np.random.RandomState(14)
    c = r.randn(4, compress.PACK_ALIGN).astype(np.float32)
    scales = jnp.asarray(np.abs(r.randn(4)).astype(np.float32) + 0.1)
    packed = jnp.stack([compress.pack_signs_jnp(jnp.asarray(ci)) for ci in c])
    got = compress._unpack_wsum_pallas(packed, scales, interpret=True)
    expect = compress.unpack_signs_weighted_sum_jnp(packed, scales)
    np.testing.assert_allclose(np.asarray(got).reshape(-1),
                               np.asarray(expect), rtol=1e-6, atol=1e-6)


@pallas_interpret_venue
def test_encode_pallas_matches_jnp_oracle():
    """Fused onebit encode: one error-fed read → (packed signs, |c|),
    bit-identical to the oracle on both outputs."""
    r = np.random.RandomState(15)
    flat = jnp.asarray(r.randn(2 * compress.PACK_ALIGN).astype(np.float32))
    state = jnp.asarray(r.randn(2 * compress.PACK_ALIGN).astype(np.float32))
    packed_pl, abs_pl = compress._encode_pallas(
        flat.reshape(-1, compress.LANES),
        state.reshape(-1, compress.LANES), interpret=True)
    packed_jnp, abs_jnp = compress.pack_signs_encode_jnp(flat, state)
    np.testing.assert_array_equal(np.asarray(packed_pl),
                                  np.asarray(packed_jnp))
    np.testing.assert_array_equal(np.asarray(abs_pl).reshape(-1),
                                  np.asarray(abs_jnp))


@pallas_interpret_venue
def test_residual_pallas_matches_jnp_oracle():
    """Fused onebit residual: ``where(bit, |c|−scale, scale−|c|)`` from the
    packed bits, bit-identical to the oracle (which is itself bit-exact vs
    the unfused ``c − scale·sign`` — pinned in test_compress_fusion.py)."""
    r = np.random.RandomState(16)
    c = r.randn(2 * compress.PACK_ALIGN).astype(np.float32)
    c[::97] = 0.0                    # exercise the c == 0 bit-1 convention
    c = jnp.asarray(c)
    packed = compress.pack_signs_jnp(c)
    absc = jnp.abs(c)
    scale = jnp.float32(0.37)
    got = compress._residual_pallas(
        absc.reshape(-1, compress.LANES), packed, scale, interpret=True)
    expect = compress.signed_residual_jnp(absc, packed, scale)
    np.testing.assert_array_equal(np.asarray(got).reshape(-1),
                                  np.asarray(expect))


@pallas_interpret_venue
def test_topk_encode_pallas_matches_jnp_oracle():
    """Fused topk encode: iterative-argmax selection must match lax.top_k
    bit-for-bit on values, indices (incl. the lower-index tie-break), and
    the in-place bf16 residual — with an all-zero row, where only explicit
    selected-lane masking keeps the orders identical."""
    r = np.random.RandomState(17)
    rows, chunk, k = 3, 512, 8
    c2 = r.randn(rows, chunk).astype(np.float32)
    c2[1, :] = 0.0
    c2 = jnp.asarray(c2)
    vals_pl, idx_pl, state_pl = compress._topk_encode_pallas(
        c2, k, interpret=True)
    vals_jnp, idx_jnp, state_jnp = compress.topk_encode_jnp(c2, k)
    np.testing.assert_array_equal(
        np.asarray(vals_pl, dtype=np.float32),
        np.asarray(vals_jnp, dtype=np.float32))
    np.testing.assert_array_equal(np.asarray(idx_pl), np.asarray(idx_jnp))
    np.testing.assert_array_equal(np.asarray(state_pl),
                                  np.asarray(state_jnp))


@pallas_interpret_venue
def test_topk_decode_pallas_matches_jnp_oracle():
    """Fused topk decode: VMEM block-local expand + folded /size mean vs
    the oracle's scatter-add (same (worker asc, slot asc) accumulation
    order per element)."""
    r = np.random.RandomState(18)
    w, rows, chunk, k = 3, 2, 256, 16
    encs = [compress.topk_encode_jnp(
        jnp.asarray(r.randn(rows, chunk).astype(np.float32)), k)
        for _ in range(w)]
    all_vals = jnp.stack([e[0] for e in encs])
    all_idx = jnp.stack([e[1] for e in encs])
    got = compress._topk_decode_pallas(all_vals, all_idx, chunk, w,
                                       interpret=True)
    expect = compress.topk_decode_jnp(all_vals, all_idx, chunk, size=w)
    np.testing.assert_allclose(np.asarray(got).reshape(-1),
                               np.asarray(expect), rtol=1e-6, atol=1e-6)


@pallas_interpret_venue
def test_matmul_pack_pallas_matches_jnp_oracle():
    """Fused PowerSGD factor matmul + staging pack: the MXU tile must equal
    ``m @ q`` with the pad rows exactly zero (the stacked-psum identity in
    parallel/strategies.py PowerSGD rests on those zeros)."""
    from theanompi_tpu.ops import factor_pack
    r = np.random.RandomState(19)
    m = jnp.asarray(r.randn(10, 64).astype(np.float32))
    q = jnp.asarray(r.randn(64, 2).astype(np.float32))
    rows_pad = factor_pack.pad_rows(10)
    got = factor_pack._matmul_pack_pallas(m, q, rows_pad, interpret=True)
    expect = factor_pack.matmul_pack_jnp(m, q, rows_pad)
    assert got.shape == (rows_pad, 2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(got)[10:], 0.0)


def test_unpack_weighted_sum_oracle():
    r = np.random.RandomState(9)
    c = r.randn(3, compress.PACK_ALIGN).astype(np.float32)
    scales = np.abs(r.randn(3)).astype(np.float32)
    packed = jnp.stack([compress.pack_signs(jnp.asarray(ci)) for ci in c])
    got = np.asarray(compress.unpack_signs_weighted_sum(packed,
                                                        jnp.asarray(scales)))
    expect = (np.where(c >= 0, 1.0, -1.0) * scales[:, None]).sum(axis=0)
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)


def test_unknown_strategy_raises():
    with pytest.raises(ValueError, match="unknown exchange strategy"):
        get_strategy("definitely-not-a-strategy")
