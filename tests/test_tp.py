"""Tensor parallelism (parallel/tp.py): the TP transformer must be the SAME
model as the dense one — identical init, equal losses/metrics/updates up to
fp32 summation-order noise — just laid out over a 2-D dp×model mesh.

The reference (Theano-MPI) has no model parallelism; this is a beyond-parity
capability, so the oracle is our own dense TransformerLM.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from theanompi_tpu.models.transformer_lm import TransformerLM
from theanompi_tpu.parallel.exchanger import (BSP_Exchanger, EASGD_Exchanger,
                                              get_exchanger)
from theanompi_tpu.parallel.mesh import MODEL_AXIS, WORKER_AXIS, worker_mesh
from theanompi_tpu.jax_compat import shard_map

LM_CFG = dict(verbose=False, batch_size=8, seq_len=16, vocab=32,
              synthetic_train=64, synthetic_val=32,
              d_model=32, n_head=4, n_layer=2, compute_dtype=jnp.float32)


def _make(dp, tp, **kw):
    mesh = worker_mesh(dp, tp=tp)
    cfg = {**LM_CFG, "mesh": mesh, "size": dp, "rank": 0, "tp": tp, **kw}
    return TransformerLM(cfg), cfg


def _train_steps(model, exch, n_steps):
    model.compile_iter_fns(exch)
    model.data.shuffle_data(0)
    costs = []
    for i in range(n_steps):
        model.train_iter(i, None)
        costs.append(float(model.current_info["cost"]))
    return costs


def test_tp_mesh_shape_and_param_shardings(mesh8):
    model, _ = _make(dp=2, tp=4)
    assert dict(model.mesh.shape) == {WORKER_AXIS: 2, MODEL_AXIS: 4}
    model.compile_iter_fns(BSP_Exchanger(model.config))
    # column-parallel fc1 weight: boxed [2, d, 4d] split over model on dim 2
    w = model.step_state["params"]["block0"]["fc1"]["w"]
    spec = w.sharding.spec
    assert spec == (WORKER_AXIS, None, MODEL_AXIS), spec
    # one device holds a [1, d, 4d/4] local block
    local = w.addressable_shards[0].data.shape
    assert local == (1, 32, 32), local
    # replicated-over-model leaf: ln_f scale
    s = model.step_state["params"]["ln_f"]["scale"]
    assert s.sharding.spec == (WORKER_AXIS,), s.sharding.spec
    # optimizer state (adam m) mirrors the param layout
    m = model.step_state["opt_state"]["m"]["block0"]["fc1"]["w"]
    assert m.sharding.spec == (WORKER_AXIS, None, MODEL_AXIS)


def test_tp_init_identical_to_dense(mesh8):
    dense, _ = _make(dp=2, tp=1)
    tp, _ = _make(dp=2, tp=4)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), dense.params, tp.params)


def test_tp_bsp_training_matches_dense(mesh8):
    """tp=4 × dp=2 must trace the same loss curve as dense dp=2 (same seed,
    same data): the model is mathematically identical — only the layout and
    the psum summation order differ."""
    dense, _ = _make(dp=2, tp=1)
    tp, _ = _make(dp=2, tp=4)
    c_dense = _train_steps(dense, BSP_Exchanger(dense.config), 6)
    c_tp = _train_steps(tp, BSP_Exchanger(tp.config), 6)
    np.testing.assert_allclose(c_tp, c_dense, rtol=2e-4, atol=2e-5)
    # params agree leaf-by-leaf after 6 updates
    from theanompi_tpu.parallel import steps
    pd = jax.device_get(steps.unbox(steps.tree_to_host(
        dense.step_state["params"])))
    pt = jax.device_get(steps.unbox(steps.tree_to_host(
        tp.step_state["params"])))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=5e-3, atol=1e-5), pd, pt)


def test_tp_val_matches_dense(mesh8):
    dense, _ = _make(dp=2, tp=1)
    tp, _ = _make(dp=2, tp=4)
    dense.compile_iter_fns(BSP_Exchanger(dense.config))
    tp.compile_iter_fns(BSP_Exchanger(tp.config))
    for m in (dense, tp):
        m.data.shuffle_data(0)
        m.begin_val()
    rec = []
    for m in (dense, tp):
        batch = m.data.next_val_batch(0)
        from theanompi_tpu.parallel import steps
        dev = steps.put_batch(m.mesh, batch)
        cost, err, err5 = m.val_fn(m._val_params_boxed, m._val_bn_boxed, dev)
        rec.append((float(np.mean(np.asarray(cost))),
                    float(np.mean(np.asarray(err))),
                    float(np.mean(np.asarray(err5)))))
    (cd, ed, e5d), (ct, et, e5t) = rec
    assert abs(cd - ct) < 1e-4
    assert ed == pytest.approx(et, abs=1e-6)      # discrete: must agree
    assert e5d == pytest.approx(e5t, abs=1e-6)


def test_tp_easgd_and_gosgd_smoke(mesh8):
    """Async rules compose with tp: the extra state (EASGD center / GoSGD α)
    inherits the params' sharded layout and the exchange collective runs."""
    for rule, kw in (("easgd", {"sync_freq": 2}),
                     ("gosgd", {"exch_prob": 1.0})):
        model, cfg = _make(dp=2, tp=4, **kw)
        exch = get_exchanger(rule, model.config)
        costs = _train_steps(model, exch, 4)
        exch.exchange(None, exch.exchange_freq)
        assert np.isfinite(costs).all()
        # canonical params + val path on the tp layout
        model.begin_val()
        model.val_iter(0, None)
        model.end_val()


def test_tp_checkpoint_roundtrip(tmp_path, mesh8):
    """Mid-training save/load on the tp layout restores bit-identically."""
    from theanompi_tpu.parallel import steps
    model, cfg = _make(dp=2, tp=4)
    exch = BSP_Exchanger(model.config)
    _train_steps(model, exch, 3)
    model.save(str(tmp_path), epoch=0, count=3)
    before = jax.device_get(steps.tree_to_host(model.step_state["params"]))

    model2, _ = _make(dp=2, tp=4)
    exch2 = BSP_Exchanger(model2.config)
    model2.compile_iter_fns(exch2)
    assert model2.load(str(tmp_path)) == 0
    after = jax.device_get(steps.tree_to_host(model2.step_state["params"]))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), before, after)
    # and training continues from the restored state
    model2.data.shuffle_data(0)
    model2.train_iter(3, None)
    assert np.isfinite(float(model2.current_info["cost"]))


def test_tp_with_grad_accumulation_and_multi_step_dispatch(mesh8):
    """n_subb (microbatch scan) and steps_per_call (multi-step dispatch)
    compose with tp: the tp=4 run must trace dense dp=2 exactly as in the
    plain case."""
    dense, _ = _make(dp=2, tp=1, n_subb=2)
    tp, _ = _make(dp=2, tp=4, n_subb=2)
    c_dense = _train_steps(dense, BSP_Exchanger(dense.config), 4)
    c_tp = _train_steps(tp, BSP_Exchanger(tp.config), 4)
    np.testing.assert_allclose(c_tp, c_dense, rtol=2e-4, atol=2e-5)

    spc, _ = _make(dp=2, tp=4, steps_per_call=2)
    base, _ = _make(dp=2, tp=4)
    spc.compile_iter_fns(BSP_Exchanger(spc.config))
    base.compile_iter_fns(BSP_Exchanger(base.config))
    for m in (spc, base):
        m.data.shuffle_data(0)
    base.train_iter(0, None)
    base.train_iter(1, None)
    spc.train_iter(1, None)          # one dispatch covering steps 0..1
    from theanompi_tpu.parallel import steps as steps_lib
    pb = steps_lib.unbox(jax.device_get(steps_lib.tree_to_host(
        base.step_state["params"])))
    ps = steps_lib.unbox(jax.device_get(steps_lib.tree_to_host(
        spc.step_state["params"])))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5), pb, ps)


def test_tp_compressed_strategies_train(mesh8):
    """onebit/topk error-feedback compression composes with tp: each tp rank
    compresses its LOCAL grad shard (EF state [tp·local_flat] sharded over
    'model').  Loss must stay finite and trend down; EF state must be
    per-model-shard (non-identical across tp ranks after training)."""
    for strat in ("onebit", "topk"):
        model, cfg = _make(dp=2, tp=4, exch_strategy=strat)
        costs = _train_steps(model, BSP_Exchanger(model.config), 8)
        assert np.isfinite(costs).all(), (strat, costs)
        assert np.mean(costs[-3:]) < np.mean(costs[:3]), (strat, costs)
        ef = model.step_state["extra"]["strat"]
        from theanompi_tpu.parallel.mesh import MODEL_AXIS
        assert ef.sharding.spec == ("workers", MODEL_AXIS)
        # per-shard residuals: the four tp shards' EF blocks differ
        blocks = np.asarray(jax.device_get(ef))[0].reshape(4, -1)
        assert not np.allclose(blocks[0], blocks[1])


def test_tp_loss_head_matches_dense_oracle(mesh8):
    """The vocab-parallel CE / error heads alone, against the dense heads, on
    random logits sharded over a 1-D model mesh."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from theanompi_tpu.models import layers as L
    from theanompi_tpu.parallel import tp as tplib

    devs = np.asarray(jax.devices()[:4])
    mesh = Mesh(devs, (MODEL_AXIS,))
    r = np.random.RandomState(0)
    logits = jnp.asarray(r.randn(16, 32).astype(np.float32) * 3)
    labels = jnp.asarray(r.randint(0, 32, 16).astype(np.int32))

    def f(lg, lb):
        return (tplib.tp_softmax_cross_entropy(lg, lb),
                tplib.tp_errors(lg, lb),
                tplib.tp_errors_top_x(lg, lb, 5))

    sm = jax.jit(shard_map(
        f, mesh=mesh, in_specs=(P(None, MODEL_AXIS), P()),
        out_specs=(P(), P(), P())))
    cost, err, err5 = sm(
        jax.device_put(logits, NamedSharding(mesh, P(None, MODEL_AXIS))),
        jax.device_put(labels, NamedSharding(mesh, P())))
    assert float(cost) == pytest.approx(
        float(L.softmax_cross_entropy(logits, labels)), rel=1e-5)
    assert float(err) == pytest.approx(float(L.errors(logits, labels)))
    assert float(err5) == pytest.approx(
        float(L.errors_top_x(logits, labels, 5)))
    # gradient of the sharded CE matches the dense CE gradient
    g_tp = jax.jit(shard_map(
        jax.grad(lambda lg, lb: tplib.tp_softmax_cross_entropy(lg, lb)),
        mesh=mesh, in_specs=(P(None, MODEL_AXIS), P()),
        out_specs=P(None, MODEL_AXIS)))(
            jax.device_put(logits, NamedSharding(mesh, P(None, MODEL_AXIS))),
            jax.device_put(labels, NamedSharding(mesh, P())))
    g_dense = jax.grad(L.softmax_cross_entropy)(logits, labels)
    np.testing.assert_allclose(np.asarray(g_tp), np.asarray(g_dense),
                               rtol=1e-5, atol=1e-7)

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
