"""Per-rule behavior tests (SURVEY.md §4 item c, plus rule invariants the
reference never machine-checked)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import TinyModel
from theanompi_tpu.parallel import steps
from theanompi_tpu.parallel.exchanger import (ASGD_Exchanger, BSP_Exchanger,
                                              EASGD_Exchanger,
                                              GOSGD_Exchanger, get_exchanger)
from theanompi_tpu.parallel.mesh import worker_mesh


def _setup(exchanger_cls, n=8, **cfg):
    mesh = worker_mesh(n)
    config = {"mesh": mesh, "size": n, "rank": 0, "verbose": False,
              "batch_size": 8, "sync_each_iter": True, **cfg}
    model = TinyModel(config)
    exch = exchanger_cls(config)
    model.compile_iter_fns(exch)
    model.data.shuffle_data(0)
    return model, exch


@pytest.mark.parametrize("rule", [
    "bsp", "easgd",
    pytest.param("asgd", marks=pytest.mark.skip(
        reason="downpour absorbs the SUM of all 8 workers' 2-step deltas "
               "per exchange (reference-faithful algebra, SURVEY.md "
               "§2.2) — an ~8x effective-lr overshoot at this smoke's "
               "scale/lr, so few-iteration descent is not a property of "
               "the rule; center/delta algebra is pinned by "
               "test_asgd_pull_resets_workers_to_center")),
    "gosgd"])
def test_rule_convergence_smoke(rule):
    """Few-iteration convergence smoke per rule — the reference's session
    scripts, made assertable."""
    model, exch = _setup(get_exchanger(rule).__class__,
                         sync_freq=2, exch_prob=0.8)
    costs = []
    for i in range(10):
        model.train_iter(i + 1, None)
        exch.exchange(None, i + 1)
        costs.append(float(model.current_info["cost"]))
    assert np.mean(costs[-3:]) < np.mean(costs[:3]), costs


def test_easgd_center_moves_toward_workers():
    model, exch = _setup(EASGD_Exchanger, sync_freq=1, alpha=0.5)
    center0 = jax.device_get(exch.canonical_params(model.step_state))
    for i in range(3):
        model.train_iter(i + 1, None)
        exch.exchange(None, i + 1)
    center1 = jax.device_get(exch.canonical_params(model.step_state))
    moved = any(
        not np.allclose(a, b)
        for a, b in zip(jax.tree_util.tree_leaves(center0),
                        jax.tree_util.tree_leaves(center1)))
    assert moved


def test_easgd_workers_pulled_toward_center():
    """After an elastic exchange, worker-replica spread must shrink."""
    model, exch = _setup(EASGD_Exchanger, sync_freq=10**9, alpha=0.5)
    for i in range(4):   # local steps only — replicas diverge
        model.train_iter(i + 1, None)

    def spread(state):
        leaves = jax.tree_util.tree_leaves(
            jax.device_get(state["params"]))
        return sum(np.ptp(l, axis=0).mean() for l in leaves)

    before = spread(model.step_state)
    assert before > 0
    exch.exchange_freq = 1
    exch.exchange(None, 1)
    after = spread(model.step_state)
    assert after < before * 0.75


def test_asgd_pull_resets_workers_to_center():
    model, exch = _setup(ASGD_Exchanger, sync_freq=1)
    for i in range(2):
        model.train_iter(i + 1, None)
        exch.exchange(None, i + 1)
    state = model.step_state
    params = jax.device_get(state["params"])
    center = jax.device_get(steps.unbox(state["extra"])["center"])
    for pl, cl in zip(jax.tree_util.tree_leaves(params),
                      jax.tree_util.tree_leaves(center)):
        for w in range(8):
            np.testing.assert_allclose(pl[w], cl, rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("peers", ["perm", "shift", "iid"])
def test_gosgd_alpha_sum_conserved(peers):
    """GoSGD's Σα invariant (mixing weights are redistributed, never created
    or destroyed) — in both peer-assignment modes."""
    model, exch = _setup(GOSGD_Exchanger, exch_prob=0.9, gosgd_peers=peers)
    for i in range(6):
        model.train_iter(i + 1, None)
        exch.exchange(None, i + 1)
        alpha = np.asarray(
            jax.device_get(model.step_state["extra"]["alpha"]))
        np.testing.assert_allclose(alpha.sum(), 8.0, rtol=1e-5)
        assert (alpha > 0).all()


def test_gosgd_perm_mode_routes_bijectively():
    """Every exchange must deliver each sent message to exactly one receiver
    — conservation of the α-weighted params sum under pure gossip (no
    training steps between exchanges)."""
    model, exch = _setup(GOSGD_Exchanger, exch_prob=1.0, gosgd_peers="perm")
    def weighted_sum(state):
        a = np.asarray(jax.device_get(state["extra"]["alpha"]))
        leaves = jax.tree_util.tree_leaves(jax.device_get(state["params"]))
        return sum((l * a.reshape((-1,) + (1,) * (l.ndim - 1))).sum(0).sum()
                   for l in leaves)
    before = weighted_sum(model.step_state)
    for i in range(4):
        exch.exchange(None, i + 1)
    after = weighted_sum(model.step_state)
    np.testing.assert_allclose(after, before, rtol=1e-4)


def test_gosgd_gossip_mixes_replicas():
    """With p=1 gossip every step, replicas must contract toward consensus
    versus never-exchanging replicas."""
    model, exch = _setup(GOSGD_Exchanger, exch_prob=1.0)
    model_ref, _ = _setup(GOSGD_Exchanger, exch_prob=1.0)

    def spread(m):
        leaves = jax.tree_util.tree_leaves(jax.device_get(
            m.step_state["params"]))
        return sum(np.ptp(l, axis=0).mean() for l in leaves)

    for i in range(6):
        model.train_iter(i + 1, None)
        exch.exchange(None, i + 1)
        model_ref.train_iter(i + 1, None)   # no exchange
    assert spread(model) < spread(model_ref)


def test_unknown_rule_raises():
    with pytest.raises(ValueError, match="unknown exchanger"):
        get_exchanger("gossip")


def test_gosgd_iid_maps_and_collision_rounds():
    """'iid' routing structure: maps avoid self-sends, draws are uniform
    over the other workers, collisions occur, and the round decomposition
    is a set of partial permutations covering each sender exactly once."""
    n = 8
    maps = GOSGD_Exchanger._iid_maps(n, 16)
    assert maps.shape == (16, n)
    assert (maps != np.arange(n)).all(), "self-send in an iid map"
    # with 16 maps of 8 iid draws, a collision (two senders -> one dest) is
    # a statistical certainty; the whole point of the mode
    assert any(len(np.unique(m)) < n for m in maps), "no collisions drawn"
    for m in maps:
        rounds = GOSGD_Exchanger._collision_rounds(m)
        senders = [s for r in rounds for (s, _) in r]
        assert sorted(senders) == list(range(n))       # each sender once
        for r in rounds:
            srcs = [s for (s, _) in r]
            dsts = [d for (_, d) in r]
            assert len(set(srcs)) == len(srcs)         # partial permutation
            assert len(set(dsts)) == len(dsts)
        # reconstruct the map from the rounds
        rebuilt = dict(pair for r in rounds for pair in r)
        assert all(rebuilt[i] == m[i] for i in range(n))


def test_gosgd_iid_mode_conserves_weighted_params_and_mixes():
    """Collision-mode routing end-to-end: the α-weighted params sum is
    conserved under pure gossip (every sent message lands exactly once even
    when two senders hit one receiver), and replicas contract."""
    model, exch = _setup(GOSGD_Exchanger, exch_prob=1.0, gosgd_peers="iid")

    def weighted_sum(state):
        a = np.asarray(jax.device_get(state["extra"]["alpha"]))
        leaves = jax.tree_util.tree_leaves(jax.device_get(state["params"]))
        return sum((l * a.reshape((-1,) + (1,) * (l.ndim - 1))).sum(0).sum()
                   for l in leaves)

    def spread(m):
        leaves = jax.tree_util.tree_leaves(jax.device_get(
            m.step_state["params"]))
        return sum(np.ptp(l, axis=0).mean() for l in leaves)

    for i in range(3):          # diversify replicas (no exchange yet)
        model.train_iter(i + 1, None)
    before, spread0 = weighted_sum(model.step_state), spread(model)
    assert spread0 > 0
    for i in range(6):
        exch.exchange(None, i + 1)
    np.testing.assert_allclose(weighted_sum(model.step_state), before,
                               rtol=1e-4)
    assert spread(model) < 0.7 * spread0
