"""Interleaved pipeline schedule TABLE (round 10, ISSUE 16): pure-python /
numpy pins on ``parallel.pipeline.build_schedule`` and everything that
consumes it — the devprof busy-count mirror, the predict_scaling bubble
model, the r10 row manifest, and the compile-cache key extra.

Unlike tests/test_pipeline.py (slow: real meshes, real training), this file
never traces or compiles anything, so it rides the tier-1 gate and keeps
the schedule contract pinned on every run.
"""

import json
import os

import numpy as np
import pytest

from theanompi_tpu.parallel.pipeline import (_validate, build_schedule,
                                             stage_permutation)
from theanompi_tpu.utils import compile_cache, devprof

# (pp, v, m) grid: v=1 legacy shapes plus every interleave branch corner —
# pp|m, v up to pp, non-power-of-two pp
GRID = [(2, 1, 3), (4, 1, 8), (2, 2, 4), (4, 2, 8), (4, 4, 8), (3, 2, 6),
        (3, 3, 6), (2, 4, 2)]


# -- build_schedule: v=1 closed forms ---------------------------------------

@pytest.mark.parametrize("pp,m", [(2, 3), (4, 8), (3, 5)])
def test_v1_closed_forms(pp, m):
    s = build_schedule(pp, 1, m)
    assert s.ticks == m + pp - 1
    t = np.arange(s.ticks)[:, None]
    r = np.arange(pp)[None, :]
    u = t - r
    np.testing.assert_array_equal(np.asarray(s.real), (u >= 0) & (u < m))
    np.testing.assert_array_equal(np.asarray(s.micro), np.clip(u, 0, m - 1))
    np.testing.assert_array_equal(np.asarray(s.chunk), np.zeros_like(u))
    # v=1 keeps the legacy always-inject/clipped-index form bit-for-bit
    assert bool(np.all(np.asarray(s.inject)))
    np.testing.assert_array_equal(
        np.asarray(s.inject_idx), np.clip(np.arange(s.ticks), 0, m - 1))
    np.testing.assert_array_equal(
        np.asarray(s.collect), np.arange(s.ticks) >= pp - 1)
    np.testing.assert_array_equal(
        np.asarray(s.collect_idx),
        np.clip(np.arange(s.ticks) - (pp - 1), 0, m - 1))
    # partial shift, not a ring: last stage's activations stay put
    assert s.perm == tuple((i, i + 1) for i in range(pp - 1))


# -- build_schedule: interleaved invariants ---------------------------------

@pytest.mark.parametrize("pp,v,m", [g for g in GRID if g[1] > 1])
def test_interleaved_schedule_invariants(pp, v, m):
    s = build_schedule(pp, v, m)
    assert s.ticks == v * m + pp - 1
    assert s.perm == tuple((i, (i + 1) % pp) for i in range(pp))
    real = np.asarray(s.real)
    chunk = np.asarray(s.chunk)
    micro = np.asarray(s.micro)
    # every (global stage, microbatch) pair runs exactly once, and
    # consecutive stages of one microbatch run on consecutive ticks
    when = {}
    for t in range(s.ticks):
        for r in range(pp):
            if real[t, r]:
                stage = int(chunk[t, r]) * pp + r
                key = (stage, int(micro[t, r]))
                assert key not in when, f"{key} scheduled twice"
                when[key] = t
    S = pp * v
    assert len(when) == S * m
    for stage in range(S - 1):
        for j in range(m):
            assert when[(stage + 1, j)] == when[(stage, j)] + 1, \
                f"stage {stage}->{stage + 1} of micro {j} not adjacent"
    # each device is busy exactly v*m ticks (its v chunks x m microbatches)
    np.testing.assert_array_equal(real.sum(axis=0), np.full(pp, v * m))
    # injection: stage 0 (device 0, chunk 0) consumes each microbatch once
    inject = np.asarray(s.inject)
    inj_idx = np.asarray(s.inject_idx)
    assert sorted(inj_idx[inject].tolist()) == list(range(m))
    # collection: the last stage emits each microbatch once
    collect = np.asarray(s.collect)
    col_idx = np.asarray(s.collect_idx)
    assert sorted(col_idx[collect].tolist()) == list(range(m))


def test_build_schedule_interleaved_needs_pp_divisible_micros():
    with pytest.raises(ValueError, match="pp_microbatches"):
        build_schedule(4, 2, 6)


# -- stage_permutation ------------------------------------------------------

def test_stage_permutation_identity_at_v1():
    np.testing.assert_array_equal(stage_permutation(8, 4, 1), np.arange(8))


def test_stage_permutation_interleaves_chunks():
    # 8 layers, pp=4, v=2: device r holds global stages {r, r+pp} — layer
    # rows regroup so each device's rows are its two non-contiguous stages
    np.testing.assert_array_equal(stage_permutation(8, 4, 2),
                                  np.asarray([0, 4, 1, 5, 2, 6, 3, 7]))
    # always a permutation
    for (L, pp, v) in [(12, 2, 3), (16, 4, 2), (24, 3, 4)]:
        p = stage_permutation(L, pp, v)
        assert sorted(p.tolist()) == list(range(L))


def test_stage_permutation_divisibility_error():
    with pytest.raises(ValueError, match="pp_interleave"):
        stage_permutation(8, 4, 3)


# -- _validate: loud config-knob errors -------------------------------------

def test_validate_names_the_config_knobs():
    with pytest.raises(ValueError, match="pp_microbatches"):
        _validate(4, 1, 2, 4)            # m < pp
    with pytest.raises(ValueError, match="pp_microbatches"):
        _validate(4, 2, 6, 2)            # v>1 and m % pp != 0
    with pytest.raises(ValueError, match="pp_interleave"):
        _validate(4, 2, 8, 3)            # local layers not divisible by v
    _validate(4, 2, 8, 2)                # healthy config passes


# -- devprof mirror: stdlib busy counts == jax-side table -------------------

@pytest.mark.parametrize("pp,v,m", GRID)
def test_devprof_busy_counts_match_schedule(pp, v, m):
    """devprof._schedule_busy_counts is a stdlib replica of the schedule's
    per-tick busy-device count (devprof must stay importable without jax);
    this is the pin its docstring promises."""
    s = build_schedule(pp, v, m)
    mirror = devprof._schedule_busy_counts(pp, v, m)
    np.testing.assert_array_equal(
        np.asarray(s.real).sum(axis=1), np.asarray(mirror))
    # and the idle sequence is a palindrome — what makes
    # pipeline_schedule_report pass-structure-agnostic
    assert mirror == mirror[::-1]


# -- predict_scaling bubble model -------------------------------------------

def test_pipeline_bubble_model():
    from scripts.predict_scaling import PIPELINE_CONFIGS, pipeline_bubble
    # hop-free v=1 reduces to the classic GPipe bubble (pp-1)/(m+pp-1)
    b = pipeline_bubble(4, 1, 8)
    assert b["ticks"] == 11 and b["warmup_ticks"] == 3
    assert b["bubble_fraction"] == pytest.approx(3 / 11, abs=1e-4)
    # interleave monotonically shrinks the bubble at fixed (pp, m)
    fracs = [pipeline_bubble(4, v, 8)["bubble_fraction"] for v in (1, 2, 4)]
    assert fracs == sorted(fracs, reverse=True)
    assert fracs[0] > fracs[-1]
    # hop overhead can only make the measured bubble worse
    assert (pipeline_bubble(4, 2, 8, t_chunk=1.0, t_hop=0.25)
            ["bubble_fraction"]
            > pipeline_bubble(4, 2, 8)["bubble_fraction"])
    # the prediction table covers exactly the r10 matrix rows
    from scripts.rows import rows
    assert [c[0] for c in PIPELINE_CONFIGS] == [r.label for r in rows("r10")]
    for (_, pp, v, m) in PIPELINE_CONFIGS:
        assert pipeline_bubble(pp, v, m)["bubble_fraction"] == \
            pytest.approx((pp - 1) / (v * m + pp - 1), abs=1e-4)


# -- r10 row manifest / bench label matching --------------------------------

def test_r10_rows_and_cfg_matching(monkeypatch):
    from bench import _cfg_matches
    from scripts.rows import rows
    r10 = rows("r10")
    labels = [r.label for r in r10]
    assert labels == ["transformer_lm-b16-pp4-trace",
                      "transformer_lm-b16-pp4-v2-trace",
                      "transformer_lm-b16-pp4-v4-trace"]
    for r in r10:
        cfg = json.loads(r.env["BENCH_CFG"])
        assert cfg["pp"] == 4 and cfg["pp_microbatches"] == 8
        assert r.env["BENCH_TRACE"] == "1"
    assert json.loads(r10[1].env["BENCH_CFG"])["pp_interleave"] == 2
    assert json.loads(r10[2].env["BENCH_CFG"])["pp_interleave"] == 4
    # each row's env matches its own label and NEITHER sibling's — the
    # resume-skip / last_good machinery must never confuse v levels
    for k in list(os.environ):
        if k.startswith("BENCH_"):
            monkeypatch.delenv(k)
    for row in r10:
        for k, val in row.env.items():
            monkeypatch.setenv(k, val)
        for other in r10:
            assert _cfg_matches(other.label) == (other.label == row.label), \
                f"env of {row.label} vs label {other.label}"
        for k in row.env:
            monkeypatch.delenv(k)


def test_pipeline_row_columns_distinct():
    # the row vocabularies must not collide — merge_matrix folds them all
    # into one flat row dict
    cols = set(devprof.PIPELINE_ROW_COLUMNS)
    assert not cols & set(devprof.TRACE_ROW_COLUMNS)
    assert not cols & set(devprof.BUCKET_ROW_COLUMNS)


# -- pipeline_schedule_report on synthetic traces ---------------------------

def _hop_events(pp, v, m, n_passes, tick_us=100.0):
    """Synthetic trace: every tick each of the pp devices hops once."""
    T = v * m + pp - 1
    evs = []
    for g in range(n_passes * T):
        for r in range(pp):
            evs.append({"ph": "X", "name": "collective-permute.7",
                        "pid": 1, "tid": r,
                        "args": {"hlo_op": f"collective-permute.{r}"},
                        "ts": g * tick_us + r, "dur": 3.0})
    return evs


def test_schedule_report_verified_and_exact():
    pp, v, m = 2, 2, 2                      # T = 5, bubble = 1/5
    rep = devprof.pipeline_schedule_report(
        _hop_events(pp, v, m, n_passes=2), pp=pp, v=v, m=m, passes=2)
    assert rep["ticks_per_pass"] == 5
    assert rep["n_hop_events"] == 20
    assert rep["measured_ticks"] == 10
    assert rep["schedule_verified"] is True
    assert rep["passes_detected"] == pytest.approx(2.0)
    assert rep["steps_detected"] == pytest.approx(1.0)
    assert rep["bubble_fraction_ticks"] == pytest.approx(0.2)
    # uniform tick spacing: duration weighting reproduces the tick model
    assert rep["bubble_fraction"] == pytest.approx(0.2, abs=1e-3)


def test_schedule_report_detects_wrong_tick_count():
    # a v=1 trace graded against the v=2 table: 28 hop events don't divide
    # into whole T=9 passes — the report must refuse to claim verification
    evs = _hop_events(2, 1, 6, n_passes=2)     # T = 7 -> 28 hop events
    rep = devprof.pipeline_schedule_report(evs, pp=2, v=2, m=4, passes=2)
    assert rep["ticks_per_pass"] == 9
    assert rep["schedule_verified"] is False


def test_schedule_report_ignores_done_halves_and_noise():
    pp, v, m = 2, 2, 2
    evs = _hop_events(pp, v, m, n_passes=2)
    extra = []
    for ev in evs:
        # async lowering emits a -done twin per hop; count one per hop
        extra.append({**ev, "name": "collective-permute-done.7",
                      "ts": ev["ts"] + 1.0})
        extra.append({**ev, "name": "fusion.12"})              # compute
        extra.append({**ev, "args": None})                     # malformed
    rep = devprof.pipeline_schedule_report(evs + extra,
                                           pp=pp, v=v, m=m)
    assert rep["n_hop_events"] == 20
    assert rep["schedule_verified"] is True


def test_schedule_report_empty_trace():
    rep = devprof.pipeline_schedule_report([], pp=4, v=2, m=8)
    assert rep["schedule_verified"] is False
    assert rep["bubble_fraction"] is None
    assert rep["bubble_fraction_ticks"] is None


# -- schedule_occupancy on synthetic lanes ----------------------------------

def test_schedule_occupancy_classifies_lanes():
    def ev(name, ts, dur, tid=0):
        return {"ph": "X", "name": name, "pid": 7, "tid": tid, "_src": "t0",
                "args": {"hlo_op": name}, "ts": ts, "dur": dur}

    events = [
        # lane 0: compute 0-10, exposed hop 10-15, compute 15-30 -> no idle
        ev("fusion.1", 0.0, 10.0), ev("collective-permute.2", 10.0, 5.0),
        ev("fusion.3", 15.0, 15.0),
        # lane 1: compute 0-10 and 20-30 with a 10us schedule gap
        ev("fusion.4", 0.0, 10.0, tid=1), ev("fusion.5", 20.0, 10.0, tid=1),
    ]
    occ = devprof.schedule_occupancy(events, min_gap_us=1.0, strip_width=12)
    assert occ["n_lanes"] == 2
    by_lane = {l["lane"]: l for l in occ["lanes"]}
    l0 = by_lane["t0:7/0"]
    assert l0["compute_secs"] == pytest.approx(25e-6)
    assert l0["hop_secs"] == pytest.approx(5e-6)
    assert l0["bubble_fraction"] == pytest.approx(0.0)
    l1 = by_lane["t0:7/1"]
    assert l1["n_slots"] == 2
    assert l1["idle_secs"] == pytest.approx(10e-6)
    assert l1["bubble_fraction"] == pytest.approx(1 / 3, abs=1e-3)
    assert "·" in l1["strip"] and "C" in l1["strip"]
    assert "H" in l0["strip"]
    # formatted view renders every lane plus the aggregate
    txt = devprof.format_schedule(occ)
    assert "t0:7/0" in txt and "bubble_fraction" in txt


# -- compile-cache key extra ------------------------------------------------

def test_key_extra_sensitive_to_pp_interleave():
    class _M:
        n_subbatches = 1

    def fn():
        pass

    base = compile_cache.key_extra(fn, model=_M())
    assert "pp_interleave" not in base            # fill/drain keys stay
    m1 = _M(); m1.pp_interleave = 1
    assert compile_cache.key_extra(fn, model=m1) == base   # byte-stable
    m2 = _M(); m2.pp_interleave = 2
    e2 = compile_cache.key_extra(fn, model=m2)
    assert e2.get("pp_interleave") == 2
    m4 = _M(); m4.pp_interleave = 4
    e4 = compile_cache.key_extra(fn, model=m4)
    assert e4.get("pp_interleave") == 4
    assert len({str(sorted(x.items())) for x in (base, e2, e4)}) == 3
