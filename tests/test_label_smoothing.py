"""Label smoothing (config label_smoothing): exact mixture with the uniform
term, pinned against a NumPy oracle — dense AND vocab-parallel heads."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from theanompi_tpu.models import layers as L
from theanompi_tpu.jax_compat import shard_map


def _oracle(logits, labels, eps):
    logits = np.asarray(logits, np.float64)
    logz = np.log(np.exp(logits).sum(-1))
    logp = logits - logz[:, None]
    n, v = logits.shape
    target = np.full((n, v), eps / v)
    target[np.arange(n), labels] += 1.0 - eps
    return float(np.mean(-(target * logp).sum(-1)))


def test_smoothing_matches_oracle():
    r = np.random.RandomState(0)
    logits = jnp.asarray(r.randn(16, 10).astype(np.float32) * 2)
    labels = jnp.asarray(r.randint(0, 10, 16).astype(np.int32))
    for eps in (0.0, 0.1, 0.3):
        got = float(L.softmax_cross_entropy(logits, labels, eps))
        assert got == pytest.approx(_oracle(logits, labels, eps), rel=1e-5)
    # eps=0 reduces to plain NLL
    assert float(L.softmax_cross_entropy(logits, labels, 0.0)) == \
        pytest.approx(float(L.softmax_cross_entropy(logits, labels)))


def test_tp_smoothing_matches_dense(mesh8):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from theanompi_tpu.parallel import tp as tplib
    from theanompi_tpu.parallel.mesh import MODEL_AXIS

    mesh = Mesh(np.asarray(jax.devices()[:4]), (MODEL_AXIS,))
    r = np.random.RandomState(1)
    logits = jnp.asarray(r.randn(16, 32).astype(np.float32) * 2)
    labels = jnp.asarray(r.randint(0, 32, 16).astype(np.int32))
    sm = jax.jit(shard_map(
        lambda lg, lb: tplib.tp_softmax_cross_entropy(
            lg, lb, label_smoothing=0.2),
        mesh=mesh, in_specs=(P(None, MODEL_AXIS), P()), out_specs=P()))
    got = float(sm(
        jax.device_put(logits, NamedSharding(mesh, P(None, MODEL_AXIS))),
        jax.device_put(labels, NamedSharding(mesh, P()))))
    assert got == pytest.approx(
        float(L.softmax_cross_entropy(logits, labels, 0.2)), rel=1e-5)


def test_smoothing_applies_to_train_only(mesh4):
    from tests.conftest import TinyModel

    cfg = {"mesh": mesh4, "size": 4, "rank": 0, "verbose": False,
           "label_smoothing": 0.2}
    m = TinyModel(cfg)
    plain = TinyModel({**cfg, "label_smoothing": 0.0})
    batch = {"x": jnp.asarray(np.random.RandomState(0)
                              .randn(8, 16).astype(np.float32)),
             "y": jnp.asarray(np.arange(8, dtype=np.int32) % 2)}
    # training loss differs (smoothed) on identical params/batch ...
    c_s, _ = m.loss_and_metrics(m.params, {}, batch, None, train=True)
    c_p, _ = plain.loss_and_metrics(plain.params, {}, batch, None,
                                    train=True)
    assert float(c_s) != pytest.approx(float(c_p), abs=1e-6)
    # ... the eval path never smooths
    v_s, _ = m.loss_and_metrics(m.params, {}, batch, None, train=False)
    v_p, _ = plain.loss_and_metrics(plain.params, {}, batch, None,
                                    train=False)
    assert float(v_s) == pytest.approx(float(v_p))
    assert float(v_s) == pytest.approx(float(c_p))   # = plain NLL
