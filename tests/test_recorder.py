"""Recorder edge cases (ISSUE 4 satellites): stride gating when printFreq
is not a multiple of the dispatch stride, zero-elapsed throughput windows,
and the lossless save/load round-trip (epoch records included)."""

import json
import os

import numpy as np

from theanompi_tpu.utils.recorder import RECORD_KEYS, SECTIONS, Recorder
from theanompi_tpu.utils.telemetry import PHASES


def _drive(r, counts, stride):
    fired = []
    for c in counts:
        r.start()
        r.end("train")
        r.train_error(c, 1.0, 0.5, 8 * stride)
        if r.print_train_info(c, stride=stride):
            fired.append(c)
    return fired


def test_stride_gate_when_printfreq_not_divisible():
    """printFreq=5, stride=3: the old residue gate (count % printFreq <
    stride) fired twice inside one window (counts 12 AND 15) and skipped
    another entirely; the dispatch-ordinal gate fires exactly once every
    ceil(5/3)=2 dispatches."""
    r = Recorder({"verbose": False, "printFreq": 5})
    counts = [3 * i for i in range(1, 11)]          # 3, 6, ..., 30
    fired = _drive(r, counts, stride=3)
    assert fired == [6, 12, 18, 24, 30]             # every 2nd dispatch
    # never less than printFreq steps between consecutive prints
    assert all(b - a >= 5 for a, b in zip(fired, fired[1:]))
    assert len(r._all_records) == len(fired)


def test_stride_gate_divisible_unchanged():
    """The common case (stride | printFreq) keeps the historical cadence:
    one print per printFreq steps, on the window boundary."""
    r = Recorder({"verbose": False, "printFreq": 4})
    fired = _drive(r, [2 * i for i in range(1, 11)], stride=2)
    assert fired == [4, 8, 12, 16, 20]
    # and the per-step cadence (stride=1) fires on exact multiples
    r1 = Recorder({"verbose": False, "printFreq": 2})
    fired1 = _drive(r1, list(range(1, 7)), stride=1)
    assert fired1 == [2, 4, 6]


def test_images_per_sec_zero_elapsed_window():
    """A zero (or negative, clock-step) elapsed window must not divide by
    zero: throughput reports 0 and the reference's headline unit inf."""
    r = Recorder({"verbose": False})
    r.n_images = 640
    r._last_print_wall = 9e18            # "now" is before the last print
    assert r.images_per_sec() == 0.0
    assert r.time_per_5120() == float("inf")
    # and the print path survives it (record carries the degenerate values)
    r.start()
    r.end("train")
    r.train_error(1, 1.0, 0.5, 8)
    assert r.print_train_info(40)
    assert r._all_records[-1]["images_per_sec"] == 0.0


def test_save_load_round_trip_is_lossless(tmp_path):
    """save → load → save must preserve BOTH record lists bit-for-bit: the
    old load() dropped epoch_records, so a resumed run's next save()
    rewrote the JSONL without the pre-resume epoch lines."""
    d = str(tmp_path)
    r = Recorder({"verbose": False, "printFreq": 1, "record_dir": d})
    for i in range(1, 4):
        r.start()
        r.end("train")
        r.train_error(i, 1.0 / i, 0.5, 8)
        assert r.print_train_info(i)
    r.val_error(3, 0.9, 0.4, 0.1)
    r.print_val_info(3)
    r.save()

    r2 = Recorder({"verbose": False, "record_dir": d})
    r2.load()
    assert r2._all_records == r._all_records
    assert r2.epoch_records == r.epoch_records      # the old resume hole

    # the resumed recorder's next save keeps the pre-resume epoch lines
    r2.save()
    with open(os.path.join(d, "inforec_rank0.jsonl")) as f:
        recs = [json.loads(line) for line in f if line.strip()]
    assert [x for x in recs if "val_cost" in x] == r.epoch_records
    assert [x for x in recs if "val_cost" not in x] == r._all_records


def test_load_survives_truncated_jsonl(tmp_path):
    """A worker killed mid-save leaves a truncated last line; the resume
    path must skip it and keep the intact records, not crash-loop the
    supervisor with a JSONDecodeError on every retry."""
    d = str(tmp_path)
    r = Recorder({"verbose": False, "printFreq": 1, "record_dir": d})
    for i in (1, 2):
        r.start()
        r.end("train")
        r.train_error(i, 1.0, 0.5, 8)
        r.print_train_info(i)
    r.val_error(2, 0.9, 0.4, 0.1)
    r.print_val_info(2)
    r.save()
    path = os.path.join(d, "inforec_rank0.jsonl")
    with open(path) as f:
        whole = f.read()
    with open(path, "w") as f:
        f.write(whole[:-25])               # kill mid final (epoch) line
    r2 = Recorder({"verbose": False, "record_dir": d})
    r2.load()                              # must not raise
    assert r2._all_records == r._all_records
    assert r2.epoch_records == []          # the mangled line was dropped


def test_load_falls_back_to_npy(tmp_path):
    """Without the JSONL (legacy dirs) the .npy still restores the train
    records — epoch records are simply not in that format."""
    d = str(tmp_path)
    r = Recorder({"verbose": False, "printFreq": 1, "record_dir": d})
    r.start()
    r.end("train")
    r.train_error(1, 2.0, 0.5, 8)
    r.print_train_info(1)
    r.save()
    os.remove(os.path.join(d, "inforec_rank0.jsonl"))
    r2 = Recorder({"verbose": False, "record_dir": d})
    r2.load()
    assert len(r2._all_records) == 1
    assert r2._all_records[0]["cost"] == 2.0
    assert r2.epoch_records == []


def test_sections_and_record_keys_single_source_of_truth():
    """The drift-guard contract (the tpulint schema-drift checker runs
    the full live-object version in tier1.sh via scripts/lint.py):
    SECTIONS aliases telemetry.PHASES and the record keys derive from
    it."""
    assert tuple(SECTIONS) == tuple(PHASES)
    assert RECORD_KEYS == tuple("t_" + s for s in PHASES if s != "val")
    r = Recorder({"verbose": False, "printFreq": 1})
    r.start()
    r.end("compile")
    r.train_error(1, 1.0, 0.5, 8)
    r.print_train_info(1)
    rec = r._all_records[-1]
    assert {k for k in rec if k.startswith("t_")} == set(RECORD_KEYS)
