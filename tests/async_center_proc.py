"""Subprocess body for the cross-process async-center test (not a test
file).  Each process is an INDEPENDENT JAX runtime (no jax.distributed —
that is the point: the only coupling is the center socket, exactly like the
reference's worker nodes talking to the server rank over MPI).

argv: proc_id center_addr rule throttle_s run_seconds
Prints one JSON line with the island stats.
"""

import json
import os
import sys


def main() -> int:
    proc_id = int(sys.argv[1])
    addr = sys.argv[2]
    rule = sys.argv[3]
    throttle = float(sys.argv[4])
    seconds = float(sys.argv[5])

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import numpy as np

    import jax.numpy as jnp
    from theanompi_tpu.models import layers as L
    from theanompi_tpu.models.data import DataBase
    from theanompi_tpu.models.model_base import ModelBase
    from theanompi_tpu.parallel.async_easgd import AsyncEASGDTrainer

    class Data(DataBase):
        def __init__(self, config=None, batch_size=8):
            super().__init__(config, batch_size)
            r = np.random.RandomState(7)
            w = r.randn(12)
            rr = np.random.RandomState(11)
            x = rr.randn(128, 12).astype(np.float32)
            self.x_train, self.y_train = x, (x @ w > 0).astype(np.int32)
            self.x_val, self.y_val = x, self.y_train
            self._finalize()

    class M(ModelBase):
        batch_size = 8
        n_subb = 1
        learning_rate = 0.05
        momentum = 0.9
        weight_decay = 0.0
        seed = 3                       # SHARED across processes: same init

        def build_model(self):
            self.seq = L.Sequential([
                L.FC(12, 16, w_init="he", compute_dtype=jnp.float32,
                     name="fc1"),
                L.FC(16, 2, w_init=("normal", 0.01), activation=None,
                     compute_dtype=jnp.float32, name="out"),
            ])
            self.data = Data(self.config, self.batch_size)

    tr = AsyncEASGDTrainer(M, {
        "async_islands": 1, "alpha": 0.5, "sync_freq": 2,
        "center_addr": addr, "island_base": proc_id, "verbose": False,
    }, rule=rule)
    # throttle keys are LOCAL island indices (this process runs 1 island)
    if seconds < 0:
        # GOAL-based run (contention-robust: fixed wall budgets flake when
        # a loaded 1-core CI box stretches the first compile): train until
        # 2 exchanges land, capped at 360 s
        import time
        tr.start(throttle={0: throttle} if throttle else None)
        deadline = time.time() + 360
        while (tr.islands[0].exchanges_done < 2
               and tr.islands[0].error is None      # fail fast on a crash
               and time.time() < deadline):
            time.sleep(0.2)
        tr.stop_and_join(timeout=120)
    else:
        tr.run_for(seconds, throttle={0: throttle} if throttle else None)
    st = tr.stats()
    print("ST " + json.dumps({"proc": proc_id, **st}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
